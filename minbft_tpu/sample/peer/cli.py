"""peer — run a replica or submit requests from the command line.

Reference sample/peer: ``peer run <id>`` loads the keystore + consensus
config, assembles the stack (authenticator, ledger, gRPC connector), and
serves (run.go:91-159); ``peer request <args…>`` is the client-side
equivalent, reading operations from argv or stdin (request.go:87-134);
flags layer over ``PEER_*`` environment variables (root.go:73-82).

    # shared flags (--keys/--config/--auth/--log-level) go BEFORE the
    # subcommand; per-subcommand flags (--listen/--batch/...) after it:
    python -m minbft_tpu.sample.peer --keys keys.yaml --config consensus.yaml run 0
    python -m minbft_tpu.sample.peer --keys keys.yaml --config consensus.yaml request "op"
    python -m minbft_tpu.sample.peer selftest   # in-process n=4 smoke test
    python -m minbft_tpu.sample.peer metrics 127.0.0.1:9464   # scrape
    python -m minbft_tpu.sample.peer top 127.0.0.1:9464 ...   # live console
    # `run --metrics-port N` serves Prometheus text (stdlib HTTP, no
    # aiohttp); MINBFT_TRACE_DUMP=path turns the flight recorder on and
    # dumps per-request stage spans at shutdown (README §Observability).

The replica's COMMIT-phase verification runs through the TPU batching
engine (``--batch``); ``--no-batch`` falls back to serial host crypto.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
import sys


from ..envflags import env_default


def _env(name: str, fallback, choices=None):
    return env_default("PEER", name, fallback, choices)


# Per-node options file (reference sample/peer/peer.yaml layered by viper
# under flags/env, root.go:54-82).  Same precedence here:
# flags > PEER_* env vars > options file > built-in defaults.
_PEER_OPTION_SCHEMA = {
    None: {"keys", "config", "log_level", "log_file", "auth", "transport"},
    "run": {"listen", "batch", "metrics_interval", "metrics_port",
            "metrics_host", "groups", "chips", "state_dir"},
    "request": {"client_id", "timeout", "group"},
}


def load_peer_options(path: str, explicit: bool) -> dict:
    """Load and validate a per-node ``peer.yaml``.  A missing DEFAULT path
    is fine (no file, no layering); a missing explicitly-requested one is
    an error.  Unknown keys fail loudly — a typo silently reverting an
    option to its default is how misconfigured replicas limp into
    clusters."""
    if not os.path.exists(path):
        if explicit:
            raise SystemExit(f"peer: options file {path!r} not found")
        return {}
    import yaml

    with open(path) as fh:
        data = yaml.safe_load(fh) or {}
    if not isinstance(data, dict):
        raise SystemExit(f"peer: options file {path!r} must be a mapping")
    def check_scalar(name: str, v) -> None:
        # str() would happily stringify a YAML list/mapping into a bogus
        # "path" — reject non-scalars here, where the message can say so.
        if isinstance(v, (dict, list)):
            raise SystemExit(
                f"peer: option {name} in {path!r} must be a scalar, "
                f"got {type(v).__name__}"
            )

    for opt, val in data.items():
        if opt in _PEER_OPTION_SCHEMA[None]:
            check_scalar(opt, val)
            continue
        sub = _PEER_OPTION_SCHEMA.get(opt)
        if sub is None:
            raise SystemExit(f"peer: unknown option {opt!r} in {path!r}")
        if not isinstance(val, dict):
            raise SystemExit(
                f"peer: section {opt!r} in {path!r} must be a mapping"
            )
        for sub_opt, v in val.items():
            if sub_opt not in sub:
                raise SystemExit(
                    f"peer: unknown option {opt}.{sub_opt!r} in {path!r}"
                )
            check_scalar(f"{opt}.{sub_opt}", v)
    return data


def peek_options_path(argv=None):
    """Resolve the options-file path BEFORE full parsing (its values feed
    the parser's defaults): --options flag > PEER_OPTIONS env > peer.yaml."""
    argv = list(sys.argv[1:] if argv is None else argv)
    path = os.environ.get("PEER_OPTIONS", "peer.yaml")
    explicit = "PEER_OPTIONS" in os.environ
    for i, a in enumerate(argv):
        if a == "--options" and i + 1 < len(argv):
            path, explicit = argv[i + 1], True
        elif a.startswith("--options="):
            path, explicit = a.split("=", 1)[1], True
    return path, explicit


def build_parser(options: dict | None = None) -> argparse.ArgumentParser:
    options = options or {}

    def _opt(name: str, fallback, section=None, choices=None):
        src = options.get(section) if section else options
        v = (src or {}).get(name, fallback)
        if v is not fallback and v is not None:
            try:
                v = type(fallback)(v)
            except (TypeError, ValueError):
                raise SystemExit(
                    f"peer: invalid options-file value {name}={v!r} "
                    f"(expected {type(fallback).__name__})"
                )
            if choices is not None and v not in choices:
                raise SystemExit(
                    f"peer: invalid options-file value {name}={v!r} "
                    f"(choose from {', '.join(map(str, choices))})"
                )
        elif v is None:
            v = fallback
        return _env(name, v, choices)

    p = argparse.ArgumentParser(prog="peer", description="minbft-tpu peer")
    p.add_argument(
        "--options",
        default=peek_options_path()[0],
        help="per-node options file layered under env vars and flags "
        "(default: peer.yaml if present)",
    )
    p.add_argument(
        "--keys", default=_opt("keys", "keys.yaml"), help="keystore path"
    )
    p.add_argument(
        "--config",
        default=_opt("config", "consensus.yaml"),
        help="consensus config path",
    )
    _levels = ("debug", "info", "warning", "error")
    p.add_argument(
        "--log-level",
        default=_opt("log_level", "info", choices=_levels),
        choices=_levels,
    )
    p.add_argument("--log-file", default=_opt("log_file", "") or None)
    _auths = ("signatures", "mac")
    p.add_argument(
        "--auth",
        choices=_auths,
        default=_opt("auth", "signatures", choices=_auths),
        help="message authentication: public-key signatures (default) or "
        "pairwise MACs (keys.yaml needs a macs section: keytool --macs)",
    )
    _transports = ("grpc", "tcp")
    p.add_argument(
        "--transport",
        choices=_transports,
        default=_opt("transport", "grpc", choices=_transports),
        help="wire transport: gRPC bidi streams (default) or the native "
        "length-prefixed TCP framing (lower per-frame cost; same "
        "authenticated protocol above it)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    r = sub.add_parser("run", help="run a replica")
    r.add_argument("id", type=int, help="replica id")
    r.add_argument(
        "--listen",
        default=_opt("listen", "", section="run"),
        help="listen address (default: this id's addr from the config)",
    )
    r.add_argument(
        "--batch",
        type=int,
        default=_opt("batch", 512, section="run"),
        help="max verification batch per kernel launch",
    )
    r.add_argument(
        "--no-batch",
        action="store_true",
        help="serial host-crypto verification (no TPU engine)",
    )
    r.add_argument(
        "--metrics-interval",
        type=float,
        default=_opt("metrics_interval", 0.0, section="run"),
        help="log the protocol counters every N seconds (0 = off)",
    )
    r.add_argument(
        "--metrics-port",
        type=int,
        default=_opt("metrics_port", -1, section="run"),
        help="serve Prometheus text metrics on this port (stdlib HTTP, "
        "daemon thread; 0 = pick a free port, printed to stderr; "
        "default: off).  Scrape with `peer metrics host:port`.",
    )
    r.add_argument(
        "--metrics-host",
        default=_opt("metrics_host", "127.0.0.1", section="run"),
        help="bind address for --metrics-port (default loopback — the "
        "endpoint is unauthenticated; widen deliberately)",
    )
    r.add_argument(
        "--groups",
        type=int,
        default=_opt("groups", 0, section="run"),
        help="host this many independent consensus groups in one replica "
        "process over shared transport + one engine (minbft_tpu/groups; "
        "README §Sharding).  0 (default) = the config's protocol.groups "
        "value; 1 = the plain ungrouped runtime.  Must be identical "
        "cluster-wide.",
    )
    r.add_argument(
        "--chips",
        type=int,
        default=_opt("chips", 1, section="run"),
        help="home chips for the multi-device engine pool (grouped "
        "runtime only): each consensus group's verify/sign traffic is "
        "placed on one chip's engine (perf/SHARDING.md §multi-chip).  "
        "0 = all visible devices; clamps to the device count; 1 "
        "(default) = the single shared engine.  Ignored with --no-batch "
        "or on the CPU backend (same rule as --batch).",
    )
    r.add_argument(
        "--peer-idle-timeout",
        type=float,
        default=_opt("peer_idle_timeout", 0.0, section="run"),
        help="TCP transport only: tear down a peer stream that delivers "
        "no frame for N seconds (a half-open link — machine wedged, NIC "
        "dead, but the socket still 'open'), so the redial loop can "
        "recover it; 0 = off (default).  Size it well above the "
        "checkpoint/view-change cadence — a healthy broadcast-log "
        "stream is never legitimately idle for long.",
    )
    r.add_argument(
        "--state-dir",
        default=_opt("state_dir", "", section="run"),
        help="durable crash-recovery store directory (minbft_tpu/"
        "recovery): every stable checkpoint is persisted atomically "
        "(write-to-temp + fsync + rename) and reloaded at startup, so a "
        "SIGKILLed replica resumes from its last stable count instead "
        "of a cold state fetch.  MINBFT_STATE_DIR is the env "
        "equivalent; empty (default) = no durability.  A corrupted "
        "committed store file is FATAL at startup (rc!=0) — silent "
        "acceptance of tampered state is worse than refusing to serve.",
    )

    m = sub.add_parser(
        "metrics",
        help="one-shot Prometheus scrape of replica --metrics-port "
        "endpoints (one target: prints the exposition text; several: "
        "per-target sections plus ONE merged cluster aggregate — the "
        "log2 histograms merge exactly, counters sum)",
    )
    m.add_argument(
        "addr",
        nargs="+",
        help="host:port (or full URL) of each replica's metrics endpoint",
    )
    m.add_argument("--timeout", type=float, default=5.0)
    m.add_argument(
        "--merged-only",
        action="store_true",
        help="with several targets: print only the merged cluster "
        "aggregate, not the per-target sections",
    )

    tp = sub.add_parser(
        "top",
        help="live cluster console: watch replica --metrics-port "
        "endpoints and render per-replica/per-group req/s, batch fill, "
        "device utilization, queue depth, loop lag, view, and health "
        "flags (commit stall / stale group).  Watch mode diffs "
        "consecutive scrapes; --once renders a single frame from the "
        "minbft_window_* gauges (CI-friendly).",
    )
    tp.add_argument(
        "addr",
        nargs="+",
        help="host:port (or full URL) of each replica's metrics endpoint",
    )
    tp.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in watch mode (seconds)",
    )
    tp.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (rc=1 if any target is down)",
    )
    tp.add_argument("--timeout", type=float, default=5.0)
    tp.add_argument(
        "--no-clear", action="store_true",
        help="append frames instead of clearing the screen",
    )
    tp.add_argument(
        "--stall-flag", action="store_true",
        help="exit 3 when any replica reports a commit stall, a stale "
        "group, or a fast-window SLO burn at/over its threshold "
        "(alerting hook for scripts)",
    )

    sl = sub.add_parser(
        "slo",
        help="one-shot latency-SLO report from replica --metrics-port "
        "endpoints: per-group good/breached counts, remaining error "
        "budget, fast/slow burn rates, and breach-dump spool counters "
        "(perf/SLO.md); --dumps additionally reads a trace-dump file "
        "set and prints the per-segment breach attribution",
    )
    sl.add_argument(
        "addr", nargs="+",
        help="host:port (or full URL) of each replica's metrics endpoint",
    )
    sl.add_argument("--timeout", type=float, default=5.0)
    sl.add_argument(
        "--json", action="store_true",
        help="machine-readable JSON instead of the table",
    )
    sl.add_argument(
        "--dumps", default="",
        help="MINBFT_TRACE_DUMP base path: load {base}.*.json and "
        "append the breach attribution (policy from MINBFT_SLO_* env)",
    )
    sl.add_argument(
        "--breach-flag", action="store_true",
        help="exit 3 when any group's fast-window burn is at/over its "
        "threshold (alerting hook for scripts)",
    )

    q = sub.add_parser("request", help="submit request(s) as a client")
    q.add_argument("ops", nargs="*", help="operations (default: stdin lines)")
    q.add_argument(
        "--client-id", type=int, default=_opt("client_id", 0, section="request")
    )
    q.add_argument(
        "--timeout", type=float, default=_opt("timeout", 30.0, section="request")
    )
    q.add_argument(
        "--group",
        type=int,
        default=_opt("group", -1, section="request"),
        help="pin requests to this consensus group instead of routing by "
        "the shard hash of the operation bytes (multi-group clusters; "
        "-1 = route by key).  The group count comes from the config's "
        "protocol.groups.",
    )
    q.add_argument(
        "--read-only",
        action="store_true",
        help="read from committed state: fast path on all-n agreement, "
        "ordered-read fallback otherwise (mutates nothing either way)",
    )
    q.add_argument(
        "--no-read-fallback",
        action="store_true",
        help="with --read-only: fail instead of falling back to an "
        "ordered read when the all-n fast quorum cannot form",
    )

    b = sub.add_parser(
        "bench",
        help="drive pipelined no-op requests from many clients and print "
        "one JSON line of throughput/latency stats (the multi-process "
        "bench's client process)",
    )
    b.add_argument("--clients", type=int, default=16, help="clients in this process")
    b.add_argument("--client-base", type=int, default=0, help="first client id")
    b.add_argument("--requests", type=int, default=1000, help="total across clients")
    b.add_argument("--depth", type=int, default=8, help="pipelined requests per client")
    b.add_argument("--timeout", type=float, default=240.0, help="per-request deadline")
    b.add_argument(
        "--read-only",
        action="store_true",
        help="drive read-only fast reads instead of ordered writes "
        "(one seed write, then reads — measures the no-consensus path)",
    )
    b.add_argument(
        "--tag", default="", help="payload tag (keeps concurrent procs' ops distinct)"
    )

    ld = sub.add_parser(
        "load",
        help="open-loop load run against a self-contained local cluster "
        "(minbft_tpu/loadgen): seeded arrival schedule at a FIXED offered "
        "rate over real loopback TCP, latency measured from scheduled "
        "arrival time, one JSON report line (README §Load testing)",
    )
    ld.add_argument(
        "--rate", type=float, default=200.0,
        help="offered arrivals/sec (time-averaged for --process onoff)",
    )
    ld.add_argument("--duration", type=float, default=5.0, help="seconds")
    ld.add_argument(
        "--seed", type=lambda s: int(s, 0), default=1,
        help="schedule seed (same seed = byte-identical schedule)",
    )
    ld.add_argument(
        "--process", choices=("poisson", "onoff"), default="poisson",
        help="arrival process: memoryless (default) or bursty on/off",
    )
    ld.add_argument(
        "--clients", type=int, default=1000,
        help="distinct client identities (own keys + seq spaces)",
    )
    ld.add_argument(
        "--conns", type=int, default=4,
        help="connection-pool slots; total sockets = slots x replicas",
    )
    ld.add_argument(
        "--replicas", type=int, default=4, help="cluster size (f=(n-1)//3)"
    )
    ld.add_argument(
        "--groups", type=int, default=1,
        help="consensus groups (arrivals shard-routed by client key)",
    )
    ld.add_argument(
        "--read-fraction", type=float, default=0.0,
        help="fraction of arrivals on the read-only fast path",
    )
    ld.add_argument(
        "--large-fraction", type=float, default=0.0,
        help="fraction of arrivals carrying the large payload class",
    )
    ld.add_argument(
        "--scheme", choices=("mac", "ecdsa-p256"), default="mac",
        help="request auth: pairwise MACs (default — measures the "
        "ingest/admission path, not host public-key crypto) or ECDSA",
    )
    ld.add_argument(
        "--expect-goodput", type=float, default=0.0,
        help="rc=1 unless goodput_per_sec reaches this (CI gate); with "
        "0 (default) rc gates only on schedule faithfulness (census)",
    )
    ld.add_argument(
        "--drain", type=float, default=10.0,
        help="seconds past the last arrival to wait for stragglers",
    )
    ld.add_argument(
        "--slo-target-ms", type=float, default=0.0,
        help="finality-SLO bar (perf/SLO.md): rc=1 unless the fraction "
        "of fired requests committing inside this budget reaches the "
        "objective (MINBFT_SLO_OBJECTIVE, default 0.99); 0 (default) = "
        "no SLO leg in the rc contract",
    )

    st = sub.add_parser("selftest", help="in-process n=4 cluster smoke test")
    st.add_argument(
        "--chaos-seed",
        type=lambda s: int(s, 0),
        default=None,
        metavar="SEED",
        help="run the smoke workload through a seeded fault-injection "
        "network (testing/faultnet.py); MINBFT_CHAOS_SEED overrides, "
        "omitted = fresh random seed (printed for replay)",
    )
    st.add_argument(
        "--chaos-profile",
        choices=("lossy", "flaky", "slow"),
        default=None,
        help="fault plan applied to every link (default with --chaos-seed: "
        "lossy); implies chaos mode",
    )

    t = sub.add_parser(
        "testnet", help="scaffold keys.yaml + consensus.yaml for a local cluster"
    )
    t.add_argument("-n", "--replicas", type=int, default=3)
    t.add_argument("-f", "--faults", type=int, default=None, help="default (n-1)//2")
    t.add_argument("--clients", type=int, default=1)
    t.add_argument("--base-port", type=int, default=42600)
    t.add_argument("--host", default="127.0.0.1")
    t.add_argument("-d", "--dir", default=".", help="output directory")
    t.add_argument(
        "--usig",
        choices=("auto", "NATIVE_ECDSA", "SOFT_ECDSA", "HMAC_SHA256"),
        default="auto",
    )
    t.add_argument(
        "--macs", action="store_true",
        default=bool(_env("macs", 0)),
        help="include pairwise-MAC material (enables run/request --auth mac)",
    )
    t.add_argument(
        "--groups", type=int, default=1,
        help="declare this many consensus groups in consensus.yaml "
        "(protocol.groups; `peer run` hosts them all per replica)",
    )
    return p


def _log_opts(args):
    from ...core.options import with_log_file, with_log_level

    opts = [with_log_level(getattr(logging, args.log_level.upper()))]
    if args.log_file:
        opts.append(with_log_file(args.log_file))
    return opts


async def _run_replica(args) -> int:
    from ...core import new_replica
    from ...sample.authentication import KeyStore
    from ...sample.config import load_config
    from ...utils import jaxcache

    # Tree-keyed persistent compile cache: a restarted replica loads its
    # kernels instead of recompiling them (set before any jax use).
    jaxcache.enable_compilation_cache()
    if args.transport == "tcp":
        from ...sample.conn.tcp import (
            TcpReplicaConnector as GrpcReplicaConnector,
        )
        from ...sample.conn.tcp import TcpReplicaServer as ReplicaServer
    else:
        from ...sample.conn.grpc import GrpcReplicaConnector, ReplicaServer
    from ...sample.requestconsumer import SimpleLedger

    store = KeyStore.load(args.keys)
    cfg = load_config(args.config)
    addrs = {p.id: p.addr for p in cfg.peers}
    if args.id not in addrs:
        raise SystemExit(f"peer: replica {args.id} not in {args.config} peers[]")

    # Eager tasks (3.12+): most protocol tasks complete without suspending
    # (memo hits, buffered sends) — running them synchronously at spawn
    # cuts event-loop scheduling overhead (same setting as the in-process
    # bench cluster).
    if hasattr(asyncio, "eager_task_factory"):
        asyncio.get_running_loop().set_task_factory(asyncio.eager_task_factory)

    engine = None
    batch_signatures = False
    if not args.no_batch:
        import jax

        # The batch engine only pays off where the limb kernels beat host
        # OpenSSL — i.e. on a real accelerator.  On the CPU backend a
        # single COMMIT would pad to a full unrolled-P256 batch (plus the
        # kernel's large XLA CPU compile), so fall back to serial host
        # crypto there exactly as --no-batch does.
        if jax.default_backend() != "cpu":
            from ...parallel import BatchVerifier

            engine = BatchVerifier(max_batch=args.batch, buckets=(args.batch,))
            batch_signatures = True

    def make_auth():
        # One call = one authenticator instance = one fresh USIG epoch
        # (the keystore restores the sealed key per call), so construct
        # exactly as many as the runtime needs: one ungrouped, or one
        # per group below — never a spare.
        if args.auth == "mac":
            # device_macs follows the signature-placement rule: the HMAC
            # batch kernel only beats host HMAC where the chip isn't
            # remote-attached.
            return store.mac_replica_authenticator(
                args.id, engine=engine, device_macs=batch_signatures
            )
        return store.replica_authenticator(
            args.id, engine=engine, batch_signatures=batch_signatures
        )

    if args.transport == "tcp":
        # Half-open peer detection (read-idle teardown) is a property of
        # the native framing only; gRPC manages its own channel health.
        conn = GrpcReplicaConnector(
            "peer", idle_timeout=args.peer_idle_timeout
        )
    else:
        conn = GrpcReplicaConnector("peer")
    for rid, addr in addrs.items():
        if rid != args.id:
            conn.connect_replica(rid, addr)

    # Env-gated chaos wrap (MINBFT_CHAOS_SEED): this replica's OUTBOUND
    # peer traffic flows through the seeded fault-injection network —
    # the real-process face of `selftest --chaos-seed`.  Sender-side
    # injection covers every directed link when all replicas run with
    # the seed (each owns its outgoing edges); the census rides the
    # /metrics exposition so a soak can assert the replayed schedule.
    # MINBFT_CHAOS_PLAN names a profile ("lossy") or inline
    # probabilities ("drop=0.02,reset=0.01").
    chaos_net = None
    if os.environ.get("MINBFT_CHAOS_SEED"):
        from ...testing import FaultNet, chaos_seed, plan_from_spec

        run_chaos_seed = chaos_seed()
        plan_spec = os.environ.get("MINBFT_CHAOS_PLAN", "lossy")
        chaos_net = FaultNet(
            seed=run_chaos_seed, default_plan=plan_from_spec(plan_spec)
        )
        conn = chaos_net.wrap(conn, f"r{args.id}")
        print(
            f"replica {args.id} chaos: seed={run_chaos_seed:#x} "
            f"plan={plan_spec} (outbound links)",
            file=sys.stderr,
        )

    # Durable crash-recovery store (minbft_tpu/recovery): flag wins,
    # then MINBFT_STATE_DIR; empty = no durability (today's behaviour).
    from ...recovery import CorruptStoreError, state_dir_from_env

    state_dir = getattr(args, "state_dir", "") or state_dir_from_env()

    n_groups = args.groups if args.groups > 0 else getattr(cfg, "groups", 1)
    grouped = n_groups > 1
    engine_pool = None
    if grouped and engine is not None and getattr(args, "chips", 1) != 1:
        # Multi-device engine pool (ISSUE 17): one engine per home chip,
        # groups placed round-robin; replaces the single shared engine.
        # The pool clamps to the visible device count, so --chips 8 on a
        # 1-device host degrades honestly to the C=1 (single-engine)
        # behaviour.  Authenticators are constructed engine-less here and
        # late-bound to their group's home-chip facade by the runtime.
        import jax

        from ...parallel import EnginePool

        chips = args.chips if args.chips > 0 else len(jax.devices())
        engine_pool = EnginePool(
            chips=chips, max_batch=args.batch, buckets=(args.batch,)
        )
        engine = None
    if grouped:
        # Multi-group runtime (README §Sharding): G independent group
        # cores over this one listener + peer connection set, every
        # core's verify/sign traffic coalescing in the ONE engine above.
        # Each group needs its own authenticator INSTANCE (own USIG
        # counter space — the keystore restores the same sealed key with
        # a fresh epoch per call); GroupAuthenticator domain separation
        # rides inside the runtime.
        from ...core.options import resolve as resolve_options
        from ...groups import new_group_runtime

        # Same log options as the ungrouped path (level AND --log-file):
        # resolve() materializes the minbft.replica{id} logger with its
        # one owned handler, and every group core's child logger
        # (minbft.replica{id}.g{g}) delivers into it by propagation.
        ropts = resolve_options(args.id, _log_opts(args))
        replica = new_group_runtime(
            args.id,
            cfg,
            [make_auth() for _ in range(n_groups)],
            conn,
            [SimpleLedger() for _ in range(n_groups)],
            logger=ropts.logger,
            engine_pool=engine_pool,
            state_dir=state_dir or None,
        )
    else:
        ledger = SimpleLedger()
        replica = new_replica(
            args.id, cfg, make_auth(), conn, ledger, opts=_log_opts(args),
            state_dir=state_dir or None,
        )
    server = ReplicaServer(replica)
    listen = args.listen or addrs[args.id]
    bound = await server.start(listen)
    print(f"replica {args.id} serving on {bound}", file=sys.stderr)
    try:
        await replica.start()
    except CorruptStoreError as e:
        # A committed store file that fails its own integrity or
        # certificate check is a hard startup refusal, not a warning: a
        # replica serving silently-wrong state is the one failure a BFT
        # deployment cannot tolerate.  The operator clears or restores
        # the state dir deliberately.
        print(
            f"peer: FATAL: replica {args.id} durable state store is "
            f"corrupt — refusing to serve: {e}\n"
            f"peer: clear or restore the --state-dir contents to recover",
            file=sys.stderr,
        )
        await server.stop()
        await conn.close()
        return 4

    from ...obs import trace as obs_trace

    # Engine dispatcher spans are exported by the MINBFT_TRACE_DUMP
    # shutdown dump, so recording is gated on exactly that knob —
    # independent of --metrics-port (a dump-only run must not lose
    # them), and never enabled without an export path (events must not
    # be recorded only to be discarded).
    if engine is not None and os.environ.get(obs_trace.TRACE_DUMP_ENV):
        engine.enable_obs_ring()

    # Latency-SLO engine (obs/slo.py): the Handlers built their own
    # BudgetLedger when the policy is enabled (MINBFT_SLO_* env or the
    # config's protocol.slo block) — gather them once for the sampler,
    # the Prometheus families, and the breach-forensics watch below.
    from ...obs import slo as obs_slo

    _handler_list = (
        [c.handlers for c in replica.cores] if grouped
        else [replica.handlers]
    )
    slo_ledgers = [
        h.slo for h in _handler_list if getattr(h, "slo", None) is not None
    ]
    slo_spool = obs_slo.BreachSpool.from_env() if slo_ledgers else None

    # Telemetry rings (obs/timeseries.py): sampled whenever anyone can
    # read them — the Prometheus endpoint (minbft_window_* gauges feed
    # `peer top --once`) or the trace-dump surface ({base}.rN.ts.json).
    # Without either consumer the sampler stays off: no tick task, zero
    # steady-state cost (the disabled-path A/B test pins this).
    tseries = sampler = None
    if args.metrics_port >= 0 or os.environ.get(obs_trace.TRACE_DUMP_ENV):
        from ...obs import timeseries as obs_ts

        tseries = obs_ts.TimeSeries()
        sampler = obs_ts.CounterSampler(tseries)
        if grouped:
            for core in replica.cores:
                obs_ts.register_replica_series(
                    sampler, core.metrics, group=core.group
                )
        else:
            obs_ts.register_replica_series(sampler, replica.metrics)
        if engine is not None:
            # once per engine — the grouped cores share it
            obs_ts.register_engine_series(sampler, engine)
        elif engine_pool is not None:
            # the pool exposes the same merged stats/depth surfaces
            obs_ts.register_engine_series(sampler, engine_pool)
        for lg in slo_ledgers:
            # good/breached counter deltas into the same ring: the
            # minbft_slo_burn_rate gauges and `peer top`'s BURN column
            # read their windows, and cross-process merges stay exact
            obs_slo.register_slo_series(sampler, lg)

    metrics_server = None
    if args.metrics_port >= 0:
        from ...obs import prom as obs_prom

        if grouped:
            # One family block per metric, samples labeled per group;
            # the shared engine's families ride once (see
            # obs.prom.collect_group_runtime).
            def render() -> str:
                # The pool stands in for the shared engine: its merged
                # stats carry c{chip}:-prefixed queue names, and the
                # runtime's engine_pool adds the minbft_engine_pool_*
                # per-chip families.
                fams = obs_prom.collect_group_runtime(
                    replica,
                    engine=engine if engine is not None else engine_pool,
                    replica_id=args.id,
                    timeseries=tseries,
                    slo_spool=slo_spool,
                )
                if chaos_net is not None:
                    fams.extend(obs_prom.collect_faultnet(
                        chaos_net.census, base={"replica": str(args.id)}
                    ))
                return obs_prom.render_families(fams)

        else:
            def render() -> str:
                fams = obs_prom.collect_replica(
                    metrics=replica.metrics,
                    recorder=replica.handlers.trace,
                    engine=engine,
                    replica_id=args.id,
                    timeseries=tseries,
                    slo=slo_ledgers[0] if slo_ledgers else None,
                    slo_spool=slo_spool,
                    recovery=getattr(replica, "recovery", None),
                )
                if chaos_net is not None:
                    fams.extend(obs_prom.collect_faultnet(
                        chaos_net.census, base={"replica": str(args.id)}
                    ))
                return obs_prom.render_families(fams)

        metrics_server = obs_prom.MetricsServer(
            render, host=args.metrics_host, port=args.metrics_port
        )
        mport = metrics_server.start()
        print(
            f"replica {args.id} metrics on "
            f"http://{args.metrics_host}:{mport}/metrics",
            file=sys.stderr,
        )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    # SIGINT and SIGTERM both route through the clean-stop path, so the
    # flight-recorder dump fires on ctrl-C exactly as on a managed stop.
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-Unix
            pass

    def dump_engine_obs() -> None:
        # Engine dispatcher spans + queue-wait histograms ride the
        # shutdown dump alongside the replica's stage dump (no-op unless
        # MINBFT_TRACE_DUMP is set — recorded events must land
        # somewhere, not silently vanish).  The queue histograms feed
        # the cluster critical-path merge (obs/critpath.py).
        base = os.environ.get(obs_trace.TRACE_DUMP_ENV)
        if engine is None or not base:
            return
        import json as _json

        from ...obs import critpath as obs_critpath

        doc = obs_critpath.engine_queue_doc(engine, ident=args.id)
        events = engine.drain_obs_events()
        if events:
            doc["events"] = [list(e) for e in events]
        # noqa: AH102 - one-shot crash/shutdown dump; forensics cannot rely on executors
        with open(f"{base}.engine{args.id}.json", "w") as fh:
            _json.dump(doc, fh)

    def dump_ts() -> None:
        # The saturation timeline rides the same dump surface as the
        # trace files ({base}.r{id}.ts.json; kind="timeseries" keeps the
        # trace loaders' shared glob safe).  The "id" stamp is what the
        # merge's incarnation refusal keys on.
        base = os.environ.get(obs_trace.TRACE_DUMP_ENV)
        if tseries is None or not base:
            return
        from ...obs import timeseries as obs_ts

        obs_ts.dump_timeseries(
            tseries, f"{base}.r{args.id}", extra={"id": args.id}
        )

    async def log_metrics() -> None:
        import json as _json

        while not stop.is_set():
            await asyncio.sleep(args.metrics_interval)
            if grouped:
                snap = replica.metrics_aggregate()
                # Same schema as the ungrouped line: the one rate field
                # is the cluster-process aggregate across group cores.
                snap["executed_per_sec"] = round(
                    sum(
                        core.metrics.executed_per_sec()
                        for core in replica.cores
                    ),
                    2,
                )
            else:
                snap = replica.metrics.snapshot()
                snap["executed_per_sec"] = round(
                    replica.metrics.executed_per_sec(), 2
                )
            print(f"metrics: {_json.dumps(snap)}", file=sys.stderr)

    metrics_task = (
        loop.create_task(log_metrics()) if args.metrics_interval > 0 else None
    )
    sampler_task = (
        loop.create_task(sampler.run()) if sampler is not None else None
    )

    # Breach-forensics watch (obs/slo.py): one task per policy group
    # reads the ring's fast-window burn every second; crossing the
    # threshold hands the spool a lazy bundle (built only if the token
    # bucket and the spool bound both allow).  Needs the sampler — burn
    # is a ring reading, and without ticks the window is always empty.
    slo_watch_tasks = []
    if slo_spool is not None and sampler is not None:
        _slo_recorders = [
            h.trace for h in _handler_list
            if getattr(h, "trace", None) is not None
        ]

        def _slo_bundle(burn: dict) -> dict:
            return obs_slo.build_bundle(
                slo_ledgers[0].policy,
                burn,
                slo_ledgers,
                recorders=_slo_recorders,
                timeseries=tseries,
            )

        for lg in slo_ledgers:
            slo_watch_tasks.append(loop.create_task(obs_slo.watch(
                tseries, lg.policy, slo_spool, _slo_bundle, group=lg.group
            )))

    async def stop_sampler() -> None:
        # Cancel-and-await: the sampler's CancelledError handler flushes
        # the final partial interval before the ring is dumped/rendered.
        for t in slo_watch_tasks:
            t.cancel()
        for t in slo_watch_tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        if sampler_task is not None:
            sampler_task.cancel()
            try:
                await sampler_task
            except asyncio.CancelledError:
                pass

    try:
        await stop.wait()
    except BaseException:
        # Fatal error (or a cancellation unwinding the process): the
        # trace must not die with it — a crashed run loses exactly the
        # forensics that explain the crash.  Best-effort stop (which
        # dumps) and engine-span dump, then let the error propagate.
        print(f"replica {args.id} crashing: dumping trace", file=sys.stderr)
        try:
            await stop_sampler()
            await replica.stop()
            dump_engine_obs()
            dump_ts()
        except Exception:  # noqa: BLE001 - forensics must not mask the
            pass  # original fatal error
        raise
    if metrics_task is not None:
        # Cancel-and-await: a log_metrics() failure surfaces here
        # instead of rotting as an unretrieved task exception.
        metrics_task.cancel()
        try:
            await metrics_task
        except asyncio.CancelledError:
            pass
    await stop_sampler()
    print(f"replica {args.id} shutting down", file=sys.stderr)
    if metrics_server is not None:
        metrics_server.stop()
    await replica.stop()  # writes the replica's MINBFT_TRACE_DUMP file
    dump_engine_obs()
    dump_ts()
    await server.stop()
    await conn.close()
    return 0


async def _run_request(args) -> int:
    from ...client import new_client
    from ...sample.authentication import KeyStore
    from ...sample.config import load_config
    if args.transport == "tcp":
        from ...sample.conn.tcp import (
            connect_many_replicas_tcp as connect_many_replicas,
        )
    else:
        from ...sample.conn.grpc import connect_many_replicas

    store = KeyStore.load(args.keys)
    cfg = load_config(args.config)
    addrs = {p.id: p.addr for p in cfg.peers}
    if len(addrs) < cfg.n:
        raise SystemExit("peer: config peers[] does not cover all replicas")

    ops = [op.encode() for op in args.ops]
    if not ops:
        ops = [line.rstrip("\n").encode() for line in sys.stdin if line.strip()]

    conn = connect_many_replicas(addrs, kind="client")
    if args.auth == "mac":
        client_auth = store.mac_client_authenticator(args.client_id)
    else:
        client_auth = store.client_authenticator(args.client_id)
    n_groups = getattr(cfg, "groups", 1)
    pin = getattr(args, "group", -1)
    if n_groups > 1:
        # Multi-group cluster: route each operation to its key-space
        # shard (stable hash of the op bytes), or pin with --group.
        from ...groups import MultiGroupClient

        if pin >= n_groups:
            # validate the pin up front: a clean CLI error, not a
            # ValueError traceback out of the router mid-request
            raise SystemExit(
                f"peer: --group {pin} out of range (config declares "
                f"{n_groups} groups: 0..{n_groups - 1})"
            )
        client = MultiGroupClient(
            args.client_id, cfg.n, cfg.f, n_groups, client_auth, conn
        )
    elif pin > 0:
        # --group 0 against an ungrouped config stays accepted: group 0
        # IS the ungrouped wire format by definition (bare frames).
        raise SystemExit(
            f"peer: --group {pin} but the config declares no groups"
        )
    else:
        client = new_client(args.client_id, cfg.n, cfg.f, client_auth, conn)
    await client.start()
    rc = 0
    try:
        for op in ops:
            kw = {}
            if n_groups > 1 and pin >= 0:
                kw["group"] = pin
            result = await asyncio.wait_for(
                client.request(
                    op,
                    read_only=getattr(args, "read_only", False),
                    read_fallback=not getattr(args, "no_read_fallback", False),
                    read_timeout=min(args.timeout, 30.0),
                    **kw,
                ),
                args.timeout,
            )
            print(result.hex())
    except asyncio.TimeoutError:
        print("peer: request timed out", file=sys.stderr)
        rc = 1
    finally:
        await client.stop()
        await conn.close()
    return rc


async def _run_bench_clients(args) -> int:
    """Client process of the multi-process bench: ``--clients`` pipelined
    clients drive ``--requests`` no-ops over gRPC and print ONE JSON line
    — committed count, wall seconds, and every request's latency (ms) so
    the harness can aggregate exact percentiles across processes.

    The reference only ever runs replicas as separate OS processes
    (reference sample/peer/main.go); this subcommand is what lets the
    flagship bench measure THAT deployment shape instead of an in-process
    event-loop cluster."""
    import faulthandler
    import json as _json
    import time as _time

    from ...client import new_client
    from ...sample.authentication import KeyStore
    from ...sample.config import load_config

    if args.transport == "tcp":
        from ...sample.conn.tcp import (
            connect_many_replicas_tcp as connect_many_replicas,
        )
    else:
        from ...sample.conn.grpc import connect_many_replicas

    # Wedge forensics: SIGUSR1 dumps every thread's stack to stderr.
    try:
        faulthandler.register(signal.SIGUSR1, all_threads=True)
    except (AttributeError, ValueError):
        pass

    store = KeyStore.load(args.keys)
    cfg = load_config(args.config)
    addrs = {p.id: p.addr for p in cfg.peers}

    if hasattr(asyncio, "eager_task_factory"):
        asyncio.get_running_loop().set_task_factory(asyncio.eager_task_factory)

    conn = connect_many_replicas(addrs, kind="client")
    clients = []
    for k in range(args.clients):
        cid = args.client_base + k
        if args.auth == "mac":
            auth = store.mac_client_authenticator(cid)
        else:
            auth = store.client_authenticator(cid)
        c = new_client(
            cid, cfg.n, cfg.f, auth, conn, retransmit_interval=30.0
        )
        await c.start()
        clients.append(c)

    per_client = max(args.requests // args.clients, 1)
    total = per_client * args.clients
    tag = (args.tag or "mp").encode()

    # settle the streams (and any cold server-side state) off the clock
    await asyncio.wait_for(clients[0].request(tag + b"-warmup"), args.timeout)

    latencies_ms: list = []

    read_only = getattr(args, "read_only", False)

    async def timed(client, k: int) -> None:
        t = _time.time()
        if read_only:
            # identical op bytes on purpose: reads have no dedup hazard,
            # and identical results are exactly what the all-n fast
            # quorum needs.  read_fallback=False: this mode MEASURES the
            # no-consensus path — a degraded cluster (all-n quorum
            # unreachable) must fail loudly, not silently report ordered
            # consensus latencies as fast reads.
            await asyncio.wait_for(
                client.request(
                    b"head",
                    read_only=True,
                    read_timeout=min(args.timeout, 30.0),
                    read_fallback=False,
                ),
                args.timeout,
            )
        else:
            await asyncio.wait_for(
                client.request(tag + b"-%d-%d" % (client.client_id, k)),
                args.timeout,
            )
        latencies_ms.append(round((_time.time() - t) * 1e3, 2))

    async def drive(client) -> None:
        # Gather-windows, deliberately NOT a rolling semaphore window: the
        # window's burst of `depth` requests coalesces into few transport
        # frames and fills PREPARE batches; a steady rolling trickle
        # measured ~15% slower (362 vs 422 req/s at depth 32, n=7).
        for k0 in range(0, per_client, args.depth):
            await asyncio.gather(
                *[
                    timed(client, k)
                    for k in range(k0, min(k0 + args.depth, per_client))
                ]
            )

    t0 = _time.time()
    await asyncio.gather(*[drive(c) for c in clients])
    dt = _time.time() - t0

    async def teardown() -> None:
        for c in clients:
            await c.stop()
        await conn.close()

    # Best-effort teardown with a bound, then a HARD exit: grpc.aio's
    # channel/stream teardown can wedge asyncio.run's cancel-all in a
    # thread join (observed: the process prints nothing and never exits,
    # hanging the whole multi-process bench).  This process exists only to
    # emit one stats line — once that's out, nothing it leaks matters.
    try:
        await asyncio.wait_for(teardown(), 10)
    except Exception:  # noqa: BLE001 - teardown is best-effort
        pass
    print(
        _json.dumps(
            {
                "committed": total,
                "seconds": round(dt, 3),
                "req_per_sec": round(total / dt, 1),
                "latencies_ms": latencies_ms,
            }
        ),
        flush=True,
    )
    os._exit(0)


async def _run_load(args) -> int:
    """Open-loop load run (ISSUE 15): self-contained — scaffolds its own
    keys and in-process cluster (client traffic over real loopback TCP),
    drives the seeded schedule, prints ONE JSON report line on stdout.

    rc contract (the CI load-smoke step's interface): 0 = schedule fired
    faithfully (live census == seed replay) and any --expect-goodput bar
    was met and any --slo-target-ms bar was met; 1 otherwise.  Progress
    notes go to stderr."""
    import json as _json

    from ...loadgen import LoadSpec
    from ...loadgen.runner import run_local_load

    n = args.replicas
    spec = LoadSpec(
        seed=args.seed,
        rate=args.rate,
        duration_s=args.duration,
        n_clients=args.clients,
        process=args.process,
        read_fraction=args.read_fraction,
        large_fraction=args.large_fraction,
        n_groups=args.groups,
    )
    spec.validate()
    print(
        # noqa: SH301 - a load-schedule seed is a PUBLIC replay token
        # (printed so a run can be reproduced, same as chaos seeds), not
        # key material.
        f"load: seed={spec.seed:#x} {spec.process} {spec.rate}/s x "  # noqa: SH301
        f"{spec.duration_s}s, {spec.n_clients} clients over "
        f"{args.conns * n} sockets, n={n}",
        file=sys.stderr,
    )
    report = await run_local_load(
        spec,
        n=n,
        f=(n - 1) // 3,
        pool_slots=args.conns,
        drain_s=args.drain,
        expect_goodput=args.expect_goodput,
        scheme=args.scheme,
        slo_target_ms=args.slo_target_ms if args.slo_target_ms > 0 else None,
    )
    print(_json.dumps(report), flush=True)
    ok = (
        report["census_ok"]
        and report.get("goodput_ok", True)
        and report.get("slo_ok", True)
    )
    if not report["census_ok"]:
        print("load: FAILED — generator diverged from the seeded "
              "schedule (census mismatch)", file=sys.stderr)
    if not report.get("goodput_ok", True):
        print(
            f"load: FAILED — goodput {report['goodput_per_sec']}/s below "
            f"the --expect-goodput {args.expect_goodput}/s bar",
            file=sys.stderr,
        )
    if not report.get("slo_ok", True):
        print(
            f"load: FAILED — slo_good_fraction "
            f"{report['slo_good_fraction']} below the "
            f"{report['slo_objective']} objective for the "
            f"{args.slo_target_ms}ms finality budget",
            file=sys.stderr,
        )
    # The report is out; a leaked replica task wedging interpreter
    # shutdown must not turn a green run red (the `peer bench` idiom).
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0 if ok else 1)


async def _run_selftest(args) -> int:
    """In-process n=4/f=1 commit through generated keys + the dummy
    connector — a deployment smoke test needing no files or sockets."""
    from ... import api
    from ...client import new_client
    from ...core import new_replica
    from ...sample.authentication import generate_testnet_keys
    from ...sample.config import SimpleConfiger
    from ...sample.conn.inprocess import (
        InProcessClientConnector,
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from ...sample.requestconsumer import SimpleLedger

    n, f = 4, 1
    store = generate_testnet_keys(n, n_clients=1)
    cfg = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
    stubs = make_testnet_stubs(n)

    # Chaos mode: the same smoke workload, but every link flows through a
    # seeded fault-injection network — the CLI face of tests/test_chaos.py
    # (deterministic replay via the printed seed / MINBFT_CHAOS_SEED).
    net = None
    if args.chaos_seed is not None or args.chaos_profile is not None:
        from ...testing import PROFILES, FaultNet, chaos_seed

        # The chaos seed is a PUBLIC replay token (printed so a failed
        # run can be reproduced) — identifiers carry the "chaos" word
        # so the secret-hygiene pass knows it is not key material.
        run_chaos_seed = chaos_seed(args.chaos_seed)
        profile = args.chaos_profile or "lossy"
        net = FaultNet(seed=run_chaos_seed, default_plan=PROFILES[profile])
        cfg = SimpleConfiger(
            n=n, f=f, timeout_request=2.0, timeout_prepare=1.0,
            timeout_viewchange=4.0,
        )
        print(
            f"chaos selftest: profile={profile} seed={run_chaos_seed:#x} "
            f"(replay: MINBFT_CHAOS_SEED={run_chaos_seed:#x})",
            file=sys.stderr,
        )

    def _wrap(conn, endpoint):
        return net.wrap(conn, endpoint) if net is not None else conn

    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        r = new_replica(
            i,
            cfg,
            store.replica_authenticator(i),
            _wrap(InProcessPeerConnector(stubs), f"r{i}"),
            ledgers[i],
            opts=_log_opts(args),
        )
        stubs[i].assign_replica(r)
        replicas.append(r)
    for r in replicas:
        await r.start()
    client = new_client(
        0,
        n,
        f,
        store.client_authenticator(0),
        _wrap(InProcessClientConnector(stubs), "c0"),
        retransmit_interval=1.0 if net is not None else None,
    )
    await client.start()

    if net is not None:
        # The smoke request plus a short seeded soak: more ordered
        # traffic, then the cross-replica safety invariants.  The strict
        # fast-read check below is skipped — under a lossy plan the
        # no-fallback fast quorum is legitimately unavailable.  A
        # TimeoutError here is the chaos run's MOST LIKELY failure mode
        # (a wedged cluster) — it must fall through to the designed
        # report (census + replay seed + clean teardown), not escape as
        # a raw traceback that skips all three.
        from ...testing import InvariantChecker

        accepted = []
        ok = True
        try:
            result = await asyncio.wait_for(client.request(b"selftest"), 60)
            accepted.append((b"selftest", result))
            ops = [b"chaos-%d" % i for i in range(5)]
            results = await asyncio.wait_for(
                asyncio.gather(
                    *[client.request(op, timeout=90) for op in ops]
                ),
                120,
            )
            accepted.extend(zip(ops, results))
        except asyncio.TimeoutError:
            print("selftest: chaos workload wedged past its deadline",
                  file=sys.stderr)
            ok = False
        want = len(accepted)
        if ok:
            for _ in range(600):
                if all(lg.length >= want for lg in ledgers):
                    break
                await asyncio.sleep(0.05)
            ok = all(lg.length >= want for lg in ledgers)
        if ok:
            try:
                InvariantChecker(replicas, ledgers).check(accepted)
            except AssertionError as e:
                print(f"selftest FAILED: invariant violation: {e}",
                      file=sys.stderr)
                ok = False
        await client.stop()
        for r in replicas:
            await r.stop()
        census = net.census.snapshot()
        print(f"chaos census: {census['counters']} "
              f"({census['frames_total']} frames)", file=sys.stderr)
        if not ok:
            print("selftest FAILED: chaos workload did not commit on all "
                  f"replicas (replay: MINBFT_CHAOS_SEED={net.chaos_seed:#x})",
                  file=sys.stderr)
            return 1
        print(f"chaos selftest ok: {want} requests committed on all {n} "
              f"replicas under seed {net.chaos_seed:#x}, invariants green",
              file=sys.stderr)
        return 0

    result = await asyncio.wait_for(client.request(b"selftest"), 60)
    for _ in range(200):
        if all(lg.length == 1 for lg in ledgers):
            break
        await asyncio.sleep(0.02)
    ok = all(lg.length == 1 for lg in ledgers)
    read_ok = False
    if ok:
        # and the read-only fast path: strict (no ordered fallback) so a
        # fast-quorum regression fails the selftest loudly — as the
        # diagnostic line below, not an unhandled traceback
        try:
            head = await asyncio.wait_for(
                client.request(
                    b"head",
                    read_only=True,
                    read_fallback=False,
                    read_timeout=30.0,
                ),
                60,
            )
        except (asyncio.TimeoutError, api.ReadOnlyQueryError):
            head = b""
        read_ok = bool(head) and head.endswith(ledgers[0].state_digest())
        read_ok = read_ok and all(lg.length == 1 for lg in ledgers)
    await client.stop()
    for r in replicas:
        await r.stop()
    if not ok:
        print("selftest FAILED: not all ledgers committed", file=sys.stderr)
        return 1
    if not read_ok:
        print("selftest FAILED: read-only fast path", file=sys.stderr)
        return 1
    print(f"selftest ok: request committed on all {n} replicas, "
          f"fast read served "
          f"(usig={store.usig_spec}, result={result.hex()[:16]}…)", file=sys.stderr)
    return 0


def _run_testnet_scaffold(args) -> int:
    """Write keys.yaml + consensus.yaml for an n-replica local cluster
    (the docker-entrypoint key-generation step of the reference,
    sample/docker/docker-entrypoint.sh, as an explicit command)."""
    from ...sample.authentication import generate_testnet_keys

    f = args.faults if args.faults is not None else (args.replicas - 1) // 2
    if args.replicas < 2 * f + 1:
        raise SystemExit(f"peer: n={args.replicas} < 2f+1 with f={f}")
    os.makedirs(args.dir, exist_ok=True)
    store = generate_testnet_keys(
        args.replicas, n_clients=args.clients, usig_spec=args.usig,
        with_macs=args.macs,
    )
    keys_path = os.path.join(args.dir, "keys.yaml")
    store.save(keys_path)
    # Per-replica least-privilege copies: replica i gets only its own
    # private material (and only its rows of the MAC matrix) — handing the
    # full store to every node would let one compromised replica forge
    # other principals' keys/MAC slots.  The full keys.yaml stays for the
    # operator/client side.  All files are written 0600 (KeyStore.save).
    for i in range(args.replicas):
        store.strip_private(keep_replica=i).save(
            os.path.join(args.dir, f"keys.replica{i}.yaml")
        )
    peers = [
        {"id": i, "addr": f"{args.host}:{args.base_port + i}"}
        for i in range(args.replicas)
    ]
    cfg = {
        "protocol": {
            "n": args.replicas,
            "f": f,
            # Checkpointing on by default: every 128 executions the
            # replicas certify state, GC their logs behind the stable
            # certificate, and serve state transfer (override with
            # CONSENSUS_CHECKPOINT_PERIOD; 0 disables).
            "checkpointPeriod": 128,
            "logsize": 0,
            "batchsizePrepare": 64,
            "groups": max(1, args.groups),
            "timeout": {"request": "8s", "prepare": "4s", "viewchange": "8s"},
        },
        "peers": peers,
    }
    import yaml

    cfg_path = os.path.join(args.dir, "consensus.yaml")
    with open(cfg_path, "w") as fh:
        yaml.safe_dump(cfg, fh, sort_keys=False)
    # Sample per-node options file (reference ships sample/peer/peer.yaml):
    # picked up automatically by `peer` run from this directory; every
    # value still overridable by PEER_* env vars and flags.
    peer_path = os.path.join(args.dir, "peer.yaml")
    if not os.path.exists(peer_path):
        with open(peer_path, "w") as fh:
            fh.write(
                "# Per-node peer options (layered under PEER_* env vars"
                " and flags)\n"
                "keys: keys.yaml\n"
                "config: consensus.yaml\n"
                "log_level: info\n"
                "#run:\n"
                "#  batch: 512\n"
                "#  metrics_interval: 0\n"
                "#request:\n"
                "#  client_id: 0\n"
                "#  timeout: 30.0\n"
            )
    print(
        f"wrote {keys_path} (usig={store.usig_spec}), {cfg_path} "
        f"(n={args.replicas}, f={f}), and {peer_path}",
        file=sys.stderr,
    )
    return 0


def _run_metrics_scrape(args) -> int:
    """``peer metrics host:port [host:port ...]`` — fetch and print
    Prometheus expositions from running replicas (synchronous GETs, no
    event loop).

    One target prints its exposition verbatim (the original contract).
    Several targets print per-target sections and then ONE merged
    cluster aggregate: the log2 histograms are exactly mergeable by
    design (identical fixed bucket edges — obs/hist.py), counters sum,
    and the per-process ``replica`` label is stripped so the same
    logical series folds together.  A dead target costs its section
    (and rc=1), never the others'."""
    from ...obs.prom import merge_expositions, scrape

    scraped: list = []
    rc = 0
    for addr in args.addr:
        try:
            scraped.append((addr, scrape(addr, timeout=args.timeout)))
        except OSError as e:
            print(
                f"peer: metrics scrape of {addr} failed: {e}", file=sys.stderr
            )
            rc = 1
    if not scraped:
        return 1
    if len(args.addr) == 1:
        sys.stdout.write(scraped[0][1])
        return rc
    if not args.merged_only:
        for addr, text in scraped:
            print(f"# ==== target {addr} ====")
            sys.stdout.write(text)
    print(f"# ==== merged cluster aggregate ({len(scraped)} targets) ====")
    sys.stdout.write(merge_expositions(text for _, text in scraped))
    return rc


def _scrape_top_state(addr: str, timeout: float) -> dict:
    """One target's parsed state for the ``peer top`` console: per-
    (replica, group) identity rows plus process-level engine readings,
    all extracted from the standard exposition families."""
    import time as _time

    from ...obs.prom import parse_exposition, scrape

    fams = parse_exposition(scrape(addr, timeout=timeout))

    def samples(name: str) -> dict:
        fam = fams.get(name)
        return fam["samples"] if fam else {}

    def total(name: str) -> float:
        return float(sum(samples(name).values()))

    def by_identity(name: str) -> dict:
        out = {}
        for key, v in samples(name).items():
            lb = dict(key)
            out[(lb.get("replica", "?"), lb.get("group", "-"))] = v
        return out

    state = {
        "addr": addr,
        "mono": _time.monotonic(),
        "executed": by_identity("minbft_requests_executed_total"),
        "view": by_identity("minbft_health_view"),
        "stall": by_identity("minbft_health_commit_stall"),
        "stale": by_identity("minbft_health_stale_group"),
        "vchanges": by_identity("minbft_view_changes_completed_total"),
        # Crash-recovery phase (minbft_tpu/recovery): absent on targets
        # running without a durable store — the console renders "-".
        "recov": by_identity("minbft_recovery_phase"),
        "build": {},
        "depth": total("minbft_verify_queue_depth")
        + total("minbft_sign_queue_depth"),
        "peak": total("minbft_verify_queue_depth_peak")
        + total("minbft_sign_queue_depth_peak"),
        "device_s": total("minbft_verify_queue_device_seconds_total")
        + total("minbft_sign_queue_device_seconds_total"),
        "items": total("minbft_verify_queue_items_total"),
        "batches": total("minbft_verify_queue_batches_total"),
        # Admission sheds (ISSUE 15): requests refused at the admission
        # boundary — a nonzero rate means offered load exceeds capacity.
        "shed": total("minbft_admission_shed_total"),
        "uptime": max(
            samples("minbft_uptime_seconds").values(), default=0.0
        ),
        "window": {},
    }
    for key, _v in samples("minbft_build_info").items():
        lb = dict(key)
        state["build"][(lb.get("replica", "?"), lb.get("group", "-"))] = lb
    # Engine-pool per-chip readings (ISSUE 17): keyed (replica, chip).
    # Absent families leave the dicts empty — a pool-less target renders
    # exactly as before.
    chips: dict = {}
    for fam_name, field in (
        ("minbft_engine_pool_chip_busy", "busy"),
        ("minbft_engine_pool_chip_fill", "fill"),
        ("minbft_engine_pool_chip_depth", "depth"),
        ("minbft_engine_pool_chip_up", "up"),
    ):
        for key, v in samples(fam_name).items():
            lb = dict(key)
            ident = (lb.get("replica", "?"), lb.get("chip", "?"))
            chips.setdefault(ident, {})[field] = v
    state["chips"] = chips
    state["home_chip"] = by_identity("minbft_engine_pool_home_chip")
    # SLO families (obs/slo.py): absent when the target runs without a
    # policy — the console renders "-" columns, never crashes.
    state["slo_budget"] = by_identity("minbft_slo_budget_remaining")
    state["slo_threshold"] = by_identity("minbft_slo_burn_threshold")
    burn: dict = {}
    for key, v in samples("minbft_slo_burn_rate").items():
        lb = dict(key)
        burn[(
            lb.get("replica", "?"), lb.get("group", "-"),
            lb.get("window", "fast"),
        )] = v
    state["slo_burn"] = burn
    for name, fam in fams.items():
        if name.startswith("minbft_window_"):
            state["window"][name[len("minbft_window_"):]] = next(
                iter(fam["samples"].values()), 0.0
            )
    return state


def _top_frame(states: dict, errors: dict, prev: dict) -> "tuple[list, bool]":
    """Render one console frame: header + one row per (replica, group)
    identity per target, DOWN rows for unreachable targets.  Returns
    ``(lines, unhealthy)`` — unhealthy when any row flags a commit
    stall or stale group (the --stall-flag exit hook)."""
    from ...recovery import PHASE_NAMES

    lines = [
        f"{'TARGET':<24}{'R':>3}{'G':>3}{'REQ/S':>9}{'SHED/S':>8}"
        f"{'FILL':>7}{'UTIL%':>7}{'DEPTH':>7}{'PEAK':>6}{'LAG_MS':>8}"
        f"{'BURN':>6}{'BUDG':>6}{'VIEW':>5}{'RECOV':>8}  HEALTH"
    ]
    unhealthy = False
    for addr in sorted(set(states) | set(errors)):
        if addr in errors:
            lines.append(f"{addr:<24}{'—':>3}{'—':>3}  DOWN: {errors[addr]}")
            continue
        st = states[addr]
        pv = prev.get(addr)
        dt = (st["mono"] - pv["mono"]) if pv else 0.0

        def rate(cur: float, last: float, window_key: str) -> float:
            # watch mode: counter delta over the scrape gap; first
            # frame / --once: the server-side window gauge, falling
            # back to the lifetime mean when rings are off.
            if pv is not None and dt > 0 and cur >= last:
                return (cur - last) / dt
            if window_key in st["window"]:
                return st["window"][window_key]
            return cur / st["uptime"] if st["uptime"] > 0 else 0.0

        # Process-level engine readings (shared across the target's rows).
        if pv is not None and dt > 0 and st["device_s"] >= pv["device_s"]:
            util = 100.0 * (st["device_s"] - pv["device_s"]) / dt
        else:
            util = (
                100.0 * st["device_s"] / st["uptime"]
                if st["uptime"] > 0
                else 0.0
            )
        if (
            pv is not None
            and st["batches"] > pv["batches"]
            and st["items"] >= pv["items"]
        ):
            fill = (st["items"] - pv["items"]) / (
                st["batches"] - pv["batches"]
            )
        elif "verify_fill" in st["window"]:
            fill = st["window"]["verify_fill"]
        else:
            fill = st["items"] / st["batches"] if st["batches"] else 0.0
        # Shed rate is target-level (admission counters sum across the
        # target's groups); shown on every row of the target.
        shed_rate = rate(
            st["shed"], pv["shed"] if pv else 0.0, "admission_shed"
        )
        identities = sorted(
            set(st["executed"]) | set(st["build"]) | set(st["view"])
        )
        if not identities:
            identities = [("?", "-")]
        for rid, grp in identities:
            ident = (rid, grp)
            executed = st["executed"].get(ident, 0.0)
            win_key = (
                f"committed_g{grp}" if grp != "-" else "committed"
            )
            rps = rate(
                executed,
                pv["executed"].get(ident, 0.0) if pv else 0.0,
                win_key,
            )
            lag_key = (
                f"loop_lag_p50_ms_g{grp}" if grp != "-"
                else "loop_lag_p50_ms"
            )
            lag = st["window"].get(lag_key, 0.0)
            flags = []
            if st["stall"].get(ident):
                flags.append("STALL")
                unhealthy = True
            if st["stale"].get(ident):
                flags.append("STALE")
                unhealthy = True
            # SLO columns (perf/SLO.md): fast-window burn multiple and
            # remaining error budget; crossing the policy's threshold
            # raises BREACH (and trips --stall-flag like a stall).
            fast_burn = st.get("slo_burn", {}).get((rid, grp, "fast"))
            budget = st.get("slo_budget", {}).get(ident)
            thr = st.get("slo_threshold", {}).get(ident)
            if (fast_burn is not None and thr is not None and thr > 0
                    and fast_burn >= thr):
                flags.append("BREACH")
                unhealthy = True
            burn_s = f"{fast_burn:.1f}" if fast_burn is not None else "-"
            budg_s = f"{budget:.2f}" if budget is not None else "-"
            vc = st["vchanges"].get(ident, 0)
            if vc:
                flags.append(f"vc={int(vc)}")
            view = int(st["view"].get(ident, 0))
            # RECOV: the durable-store recovery phase by short name; a
            # replica stuck in "fetch"/"install" long after restart is
            # the console's first visible symptom of a wedged transfer.
            ph = st.get("recov", {}).get(ident)
            if ph is None:
                recov_s = "-"
            else:
                pi = int(ph)
                recov_s = (
                    PHASE_NAMES[pi] if 0 <= pi < len(PHASE_NAMES) else str(pi)
                )
            lines.append(
                f"{addr:<24}{rid:>3}{grp:>3}{rps:>9.1f}{shed_rate:>8.1f}"
                f"{fill:>7.1f}{min(util, 999.0):>7.1f}{st['depth']:>7.0f}"
                f"{st['peak']:>6.0f}{lag:>8.2f}{burn_s:>6}{budg_s:>6}"
                f"{view:>5}{recov_s:>8}  {' '.join(flags) or 'ok'}"
            )
            # Engine-pool expansion (ISSUE 17): the group's home chip as
            # a sub-row.  A chip the scrape knows nothing about (or one
            # whose every queue wrote its device off) renders DOWN with
            # zeroed readings — missing fields must never crash a frame.
            home = st.get("home_chip", {}).get(ident)
            if home is not None:
                chip = str(int(home))
                row = st.get("chips", {}).get((rid, chip), {})
                down = not row or not row.get("up", 0)
                lines.append(
                    f"{'':<24} └ chip {chip:<3}"
                    f" busy={row.get('busy', 0.0):<7.3f}"
                    f" fill={row.get('fill', 0.0):<7.3f}"
                    f" depth={row.get('depth', 0.0):<6.0f}"
                    f" {'DOWN' if down else 'up'}"
                )
        build = next(iter(st["build"].values()), None)
        if build is not None:
            lines.append(
                f"{'':<24} └ pid={build.get('pid', '?')} "
                f"backend={build.get('backend', '?')} "
                f"rev={build.get('git_rev', '?')} "
                f"run={str(build.get('run_id', '?'))[:18]}"
            )
    return lines, unhealthy


def _run_top(args) -> int:
    """``peer top`` — the live cluster console (ISSUE 14).  Watch mode
    clears and redraws every ``--interval`` seconds, computing rates
    from consecutive-scrape counter deltas; ``--once`` prints a single
    frame whose rates come from the replicas' own ``minbft_window_*``
    gauges (one scrape, no diffing — the CI/scripting mode)."""
    import time as _time

    prev: dict = {}
    while True:
        states: dict = {}
        errors: dict = {}
        for addr in args.addr:
            try:
                states[addr] = _scrape_top_state(addr, args.timeout)
            except OSError as e:
                errors[addr] = str(e)
        lines, unhealthy = _top_frame(states, errors, prev)
        if not args.once and not args.no_clear and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        print("\n".join(lines), flush=True)
        if args.once:
            if errors:
                return 1
            if args.stall_flag and unhealthy:
                return 3
            return 0
        prev = states
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _run_slo(args) -> int:
    """``peer slo`` — one-shot latency-SLO report (perf/SLO.md).

    Scrapes each target's ``minbft_slo_*`` families and prints one row
    per (target, group): lifetime good/breached counts, the policy's
    target/objective, remaining error budget, fast/slow burn multiples,
    and the breach-dump spool counters.  ``--dumps BASE`` additionally
    loads a trace-dump file set ({base}.*.json) and appends the
    per-segment breach attribution.  rc: 0 ok, 1 scrape failure, 3 with
    ``--breach-flag`` when any fast burn is at/over its threshold."""
    import json as _json

    from ...obs.prom import parse_exposition, scrape

    rc = 0
    breach = False
    report: dict = {"targets": []}
    for addr in args.addr:
        try:
            fams = parse_exposition(scrape(addr, timeout=args.timeout))
        except OSError as e:
            print(f"peer: slo scrape of {addr} failed: {e}",
                  file=sys.stderr)
            rc = 1
            continue

        def samples(name: str) -> dict:
            fam = fams.get(name)
            return fam["samples"] if fam else {}

        groups: dict = {}

        def fold(name: str, field: str) -> None:
            for key, v in samples(name).items():
                lb = dict(key)
                g = lb.get("group", "-")
                f = (
                    f"{field}_{lb['window']}" if "window" in lb else field
                )
                groups.setdefault(g, {})[f] = v

        fold("minbft_slo_good_total", "good")
        fold("minbft_slo_breached_total", "breached")
        fold("minbft_slo_target_ms", "target_ms")
        fold("minbft_slo_objective", "objective")
        fold("minbft_slo_budget_remaining", "budget_remaining")
        fold("minbft_slo_burn_threshold", "burn_threshold")
        fold("minbft_slo_burn_rate", "burn")
        spool = {
            "written": sum(
                samples("minbft_slo_breach_dumps_total").values()
            ),
            "suppressed": sum(
                samples(
                    "minbft_slo_breach_dumps_suppressed_total"
                ).values()
            ),
        }
        for g in groups.values():
            total = g.get("good", 0) + g.get("breached", 0)
            g["good_fraction"] = (
                round(g.get("good", 0) / total, 4) if total else 1.0
            )
            thr = g.get("burn_threshold", 0)
            if thr > 0 and g.get("burn_fast", 0.0) >= thr:
                g["breach"] = True
                breach = True
        report["targets"].append(
            {"addr": addr, "groups": groups, "spool": spool}
        )
    if args.dumps:
        from ...obs import slo as obs_slo
        from ...obs.trace import load_dumps

        docs = load_dumps(args.dumps)
        report["breach_report"] = obs_slo.breach_report(
            docs, obs_slo.SLOPolicy.from_env()
        )
    if args.json:
        print(_json.dumps(report, sort_keys=True), flush=True)
    else:
        print(
            f"{'TARGET':<24}{'G':>3}{'GOOD':>9}{'BREACHED':>9}"
            f"{'GOODFRAC':>9}{'TARGET_MS':>10}{'BUDGET':>8}"
            f"{'FAST':>7}{'SLOW':>7}  FLAG"
        )
        for tgt in report["targets"]:
            if not tgt["groups"]:
                print(f"{tgt['addr']:<24}  (no SLO policy — set "
                      "MINBFT_SLO_TARGET_MS or protocol.slo)")
                continue
            for g in sorted(tgt["groups"]):
                row = tgt["groups"][g]
                print(
                    f"{tgt['addr']:<24}{g:>3}"
                    f"{int(row.get('good', 0)):>9}"
                    f"{int(row.get('breached', 0)):>9}"
                    f"{row.get('good_fraction', 1.0):>9.4f}"
                    f"{row.get('target_ms', 0.0):>10.0f}"
                    f"{row.get('budget_remaining', 1.0):>8.2f}"
                    f"{row.get('burn_fast', 0.0):>7.1f}"
                    f"{row.get('burn_slow', 0.0):>7.1f}"
                    f"  {'BREACH' if row.get('breach') else 'ok'}"
                )
            if tgt["spool"]["written"] or tgt["spool"]["suppressed"]:
                print(
                    f"{'':<24} └ breach dumps: "
                    f"{int(tgt['spool']['written'])} written, "
                    f"{int(tgt['spool']['suppressed'])} suppressed"
                )
        br = report.get("breach_report")
        if br:
            print(
                f"breach attribution ({br['origin']}-origin, "
                f"{br['breached']}/{br['requests']} breached, "
                f"{br['breached_spend_ms']}ms spent):"
            )
            for seg, ms in sorted(
                br["attribution_ms"].items(), key=lambda kv: -kv[1]
            ):
                print(f"  {seg:<16}{ms:>12.3f} ms")
    if rc:
        return rc
    if args.breach_flag and breach:
        return 3
    return 0


def main(argv=None) -> int:
    path, explicit = peek_options_path(argv)
    args = build_parser(load_peer_options(path, explicit)).parse_args(argv)
    if args.command == "run":
        # Optional uvloop (MINBFT_UVLOOP, auto-detected): must be
        # installed as the policy BEFORE asyncio.run creates the loop.
        from ...utils.loop import maybe_enable_uvloop

        if maybe_enable_uvloop():
            logging.getLogger("minbft.peer").info("event loop: uvloop")
        return asyncio.run(_run_replica(args))
    if args.command == "metrics":
        return _run_metrics_scrape(args)
    if args.command == "top":
        return _run_top(args)
    if args.command == "slo":
        return _run_slo(args)
    if args.command == "request":
        return asyncio.run(_run_request(args))
    if args.command == "bench":
        return asyncio.run(_run_bench_clients(args))
    if args.command == "selftest":
        return asyncio.run(_run_selftest(args))
    if args.command == "load":
        return asyncio.run(_run_load(args))
    if args.command == "testnet":
        return _run_testnet_scaffold(args)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
