"""peer CLI package (reference sample/peer/)."""

from .cli import main

__all__ = ["main"]
