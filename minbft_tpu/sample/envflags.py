"""Environment-variable flag binding shared by the CLIs.

The reference layers flags over env vars via viper's ``SetEnvPrefix``
(sample/peer/cmd/root.go:73-82).  argparse neither type-checks nor
``choices``-checks *defaults*, so env-sourced values must be validated
here, before they reach the parser.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence


def env_default(prefix: str, name: str, fallback, choices: Optional[Sequence] = None):
    """``$<PREFIX>_<NAME>`` coerced to ``type(fallback)``, else ``fallback``.

    Exits with a usage-style message on a value that fails coercion or is
    outside ``choices``."""
    var = f"{prefix}_{name.upper()}"
    v = os.environ.get(var)
    if v is None:
        return fallback
    try:
        value = type(fallback)(v)
    except ValueError:
        raise SystemExit(
            f"{prefix.lower()}: invalid {var}={v!r} "
            f"(expected {type(fallback).__name__})"
        )
    if choices is not None and value not in choices:
        raise SystemExit(
            f"{prefix.lower()}: invalid {var}={v!r} (choose from {', '.join(map(str, choices))})"
        )
    return value
