"""Configuration provider (reference sample/config/viperconfiger.go).

``SimpleConfiger`` is the programmatic form; ``load_config`` reads the YAML
schema of the reference's consensus.yaml (protocol.{n,f,checkpointPeriod,
logsize,timeout.{request,prepare,viewchange}}, peers[] with id/addr) via
PyYAML (baked into the runtime image).

Layering (the viper equivalent, reference viperconfiger.go + root.go env
binding): ``CONSENSUS_*`` environment variables override file values —
``CONSENSUS_TIMEOUT_REQUEST=5s``, ``CONSENSUS_CHECKPOINT_PERIOD=16``, etc.
The quorum shape (n, f) is deliberately NOT env-overridable: it must be
identical cluster-wide and belongs to the shared file.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from .. import api


@dataclasses.dataclass
class PeerAddr:
    id: int
    addr: str


class SimpleConfiger(api.Configer):
    def __init__(
        self,
        n: int,
        f: int,
        checkpoint_period: int = 0,
        logsize: int = 0,
        timeout_request: float = 2.0,
        timeout_prepare: float = 1.0,
        timeout_viewchange: float = 8.0,
        peers: Optional[List[PeerAddr]] = None,
        batchsize_prepare: int = 64,
        groups: int = 1,
        slo_target_ms: Optional[float] = None,
        slo_objective: Optional[float] = None,
    ):
        self._n = n
        self._f = f
        self._checkpoint_period = checkpoint_period
        self._logsize = logsize
        self._timeout_request = timeout_request
        self._timeout_prepare = timeout_prepare
        self._timeout_viewchange = timeout_viewchange
        self.peers = peers or []
        # Max requests coalesced into one PREPARE (this build's request
        # batching; the reference has none — roadmap README.md:505).
        self.batchsize_prepare = batchsize_prepare
        # Consensus groups per replica process (minbft_tpu/groups): G
        # independent MinBFT instances over shared transport + one
        # engine.  1 = the ungrouped runtime.  Like n/f this must be
        # identical cluster-wide, so it lives in the shared file —
        # CONSENSUS_GROUPS exists for test/bench layering only.
        self.groups = groups
        # Latency-SLO policy (obs/slo.py): finality budget + objective
        # fraction.  None = SLO accounting stays off unless the
        # MINBFT_SLO_* env knobs turn it on; a set target here enables
        # it (consensus.yaml ``protocol.slo.{target,objective}``).  The
        # MINBFT_SLO_* env always layers on top, including per-group
        # comma lists.
        self.slo_target_ms = slo_target_ms
        self.slo_objective = slo_objective

    @property
    def n(self) -> int:
        return self._n

    @property
    def f(self) -> int:
        return self._f

    @property
    def checkpoint_period(self) -> int:
        return self._checkpoint_period

    @property
    def logsize(self) -> int:
        return self._logsize

    @property
    def timeout_request(self) -> float:
        return self._timeout_request

    @property
    def timeout_prepare(self) -> float:
        return self._timeout_prepare

    @property
    def timeout_viewchange(self) -> float:
        return self._timeout_viewchange


def load_config(path: str, env: Optional[Dict[str, str]] = None) -> SimpleConfiger:
    """Load a consensus.yaml (reference sample/config/consensus.yaml schema),
    with ``CONSENSUS_*`` env overrides layered on top (see module doc)."""
    if env is None:
        env = os.environ
    # noqa: AH102 - one small config file read once at replica startup
    with open(path) as fh:
        text = fh.read()
    data = _parse_yaml(text)
    proto = data.get("protocol", {})
    timeout = proto.get("timeout", {})
    slo = proto.get("slo", {})
    peers = [
        PeerAddr(id=int(p["id"]), addr=str(p["addr"]))
        for p in data.get("peers", [])
    ]

    def layered(env_key: str, file_val, cast):
        v = env.get(f"CONSENSUS_{env_key}")
        return cast(v) if v is not None else cast(file_val)

    return SimpleConfiger(
        n=int(proto["n"]),
        f=int(proto["f"]),
        checkpoint_period=layered(
            "CHECKPOINT_PERIOD", proto.get("checkpointPeriod", 0), int
        ),
        logsize=layered("LOGSIZE", proto.get("logsize", 0), int),
        timeout_request=layered(
            "TIMEOUT_REQUEST", timeout.get("request", "2s"), _seconds
        ),
        timeout_prepare=layered(
            "TIMEOUT_PREPARE", timeout.get("prepare", "1s"), _seconds
        ),
        timeout_viewchange=layered(
            "TIMEOUT_VIEWCHANGE", timeout.get("viewchange", "8s"), _seconds
        ),
        peers=peers,
        batchsize_prepare=layered(
            "BATCHSIZE_PREPARE", proto.get("batchsizePrepare", 64), int
        ),
        groups=layered("GROUPS", proto.get("groups", 1), int),
        # `protocol.slo.target: 1s` / `.objective: 0.99`; absent keys
        # stay None so the SLO engine's env-gated default is untouched.
        slo_target_ms=(
            layered("SLO_TARGET", slo.get("target", "1s"), _seconds) * 1e3
            if "target" in slo or env.get("CONSENSUS_SLO_TARGET")
            else None
        ),
        slo_objective=(
            layered("SLO_OBJECTIVE", slo.get("objective", 0.99), float)
            if "objective" in slo or env.get("CONSENSUS_SLO_OBJECTIVE")
            else None
        ),
    )


def _seconds(v) -> float:
    """'1500ms' / '2s' / numeric → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


def _parse_yaml(text: str) -> Dict:
    import yaml  # baked into the environment

    return yaml.safe_load(text) or {}
