"""Configuration provider (reference sample/config/viperconfiger.go).

``SimpleConfiger`` is the programmatic form; ``load_config`` reads the YAML
schema of the reference's consensus.yaml (protocol.{n,f,checkpointPeriod,
logsize,timeout.{request,prepare,viewchange}}, peers[] with id/addr) via
PyYAML (baked into the runtime image).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from .. import api


@dataclasses.dataclass
class PeerAddr:
    id: int
    addr: str


class SimpleConfiger(api.Configer):
    def __init__(
        self,
        n: int,
        f: int,
        checkpoint_period: int = 0,
        logsize: int = 0,
        timeout_request: float = 2.0,
        timeout_prepare: float = 1.0,
        peers: Optional[List[PeerAddr]] = None,
    ):
        self._n = n
        self._f = f
        self._checkpoint_period = checkpoint_period
        self._logsize = logsize
        self._timeout_request = timeout_request
        self._timeout_prepare = timeout_prepare
        self.peers = peers or []

    @property
    def n(self) -> int:
        return self._n

    @property
    def f(self) -> int:
        return self._f

    @property
    def checkpoint_period(self) -> int:
        return self._checkpoint_period

    @property
    def logsize(self) -> int:
        return self._logsize

    @property
    def timeout_request(self) -> float:
        return self._timeout_request

    @property
    def timeout_prepare(self) -> float:
        return self._timeout_prepare


def load_config(path: str) -> SimpleConfiger:
    """Load a consensus.yaml (reference sample/config/consensus.yaml schema)."""
    with open(path) as fh:
        text = fh.read()
    data = _parse_yaml(text)
    proto = data.get("protocol", {})
    timeout = proto.get("timeout", {})
    peers = [
        PeerAddr(id=int(p["id"]), addr=str(p["addr"]))
        for p in data.get("peers", [])
    ]
    return SimpleConfiger(
        n=int(proto["n"]),
        f=int(proto["f"]),
        checkpoint_period=int(proto.get("checkpointPeriod", 0)),
        logsize=int(proto.get("logsize", 0)),
        timeout_request=_seconds(timeout.get("request", "2s")),
        timeout_prepare=_seconds(timeout.get("prepare", "1s")),
        peers=peers,
    )


def _seconds(v) -> float:
    """'1500ms' / '2s' / numeric → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


def _parse_yaml(text: str) -> Dict:
    import yaml  # baked into the environment

    return yaml.safe_load(text) or {}
