"""Sample stack: pluggable external modules for the core protocol
(reference sample/): authentication schemes + keystore, connectors
(in-process and TCP), configuration, the SimpleLedger request consumer, and
the peer CLI."""
