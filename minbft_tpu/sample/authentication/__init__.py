"""Authentication: schemes, authenticator, keystore
(reference sample/authentication/).

The reference's ``Authenticator`` maps roles to authentication schemes
backed by a YAML keystore (reference authenticator.go:88-116).  The TPU
build adds the north-star piece: :class:`SampleAuthenticator` dispatches
*verification* through the :class:`minbft_tpu.parallel.BatchVerifier`, so
every concurrent protocol validation joins a batched XLA kernel launch
("TPUAuthenticator" in BASELINE.json)."""

from .authenticator import SampleAuthenticator, new_test_authenticators
from .keystore import KeyStore, KeyStoreError, generate_testnet_keys

__all__ = [
    "SampleAuthenticator",
    "new_test_authenticators",
    "KeyStore",
    "KeyStoreError",
    "generate_testnet_keys",
]
