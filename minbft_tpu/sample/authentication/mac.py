"""Pairwise-MAC message authentication — the reference's roadmap item
("Defining authentication mechanism based on MACs", reference
README.md:500-505), PBFT-style MAC vectors re-designed for the batch
engine.

Scheme (symmetric, pairwise 32-byte secrets):

- REQUEST (client c → all): a vector of n MACs; slot r is
  ``HMAC(K(c,r), SHA256(authen_bytes))``.  Replica r verifies its slot.
- REPLY (replica r → client c): a single MAC under K(c,r) — the tag is
  recipient-specific, which is what the ``audience`` parameter of
  :meth:`minbft_tpu.api.Authenticator.generate_message_authen_tag` exists
  for.
- REQ-VIEW-CHANGE (replica i → all): a vector of n MACs under the
  replica-pair keys K(i,j); the own slot is zeros (own messages are
  trusted, never self-verified).
- PREPARE/COMMIT UI certificates are unchanged: they come from the USIG
  (the protocol's equivocation guard must not be forgeable by MAC-key
  holders), delegated to a wrapped authenticator.

MAC verification fits the existing HMAC-SHA256 batch kernel, so the
engine's device or host queues (with the cluster-wide dedup memo) apply
unchanged.

Trust caveat (inherent to MAC authenticators, known from PBFT): a faulty
*client* can craft a vector whose slots verify at the primary but fail at
a correct backup.  The consequence is worse than losing that one request:
the backup rejects the whole PREPARE embedding it, so the primary's UI
counter is never captured there, and **every subsequent message from that
primary parks on the counter gap** (peerstate in-order capture) until the
per-stream concurrency bound fills — a liveness stall for the whole
stream, not one request.  Never safety: no forged request can commit.
Mitigation wired in core: a backup that sees a UI-valid proposal with a
bad embedded-request MAC raises
:class:`minbft_tpu.api.EmbeddedRequestAuthError`, and message handling
immediately demands a view change to depose the wedged primary (instead
of waiting for the request timeout); repeated faulty clients can still
thrash views — public-key signatures remain the default scheme, and MAC
deployments assume clients are trusted-or-expendable.  MACs trade that
robustness for ~100x cheaper authentication.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import secrets
from typing import Dict, Optional, Tuple

from ... import api
from .authenticator import SampleAuthenticator

_MAC_LEN = 32


class MacKeys:
    """Pairwise secrets: ``client_replica[(c, r)]`` and
    ``replica_pair[(min(i,j), max(i,j))]``, each 32 bytes."""

    def __init__(
        self,
        client_replica: Dict[Tuple[int, int], bytes],
        replica_pair: Dict[Tuple[int, int], bytes],
    ):
        self.client_replica = client_replica
        self.replica_pair = replica_pair

    def k_client(self, client_id: int, replica_id: int) -> bytes:
        key = self.client_replica.get((client_id, replica_id))
        if key is None:
            # AuthenticationError, not KeyError: an unknown principal id is
            # an authentication failure (a rejected message), never an
            # internal error (the Authenticator error contract).
            raise api.AuthenticationError(
                f"no MAC key for client {client_id} / replica {replica_id}"
            )
        return key

    def k_replicas(self, i: int, j: int) -> bytes:
        key = self.replica_pair.get((min(i, j), max(i, j)))
        if key is None:
            raise api.AuthenticationError(f"no MAC key for replicas {i},{j}")
        return key

    def view_for_replica(self, r: int) -> "MacKeys":
        """This replica's share only (what its keystore would hold)."""
        return MacKeys(
            {k: v for k, v in self.client_replica.items() if k[1] == r},
            {k: v for k, v in self.replica_pair.items() if r in k},
        )

    def view_for_client(self, c: int) -> "MacKeys":
        return MacKeys(
            {k: v for k, v in self.client_replica.items() if k[0] == c}, {}
        )


def generate_testnet_mac_keys(n: int, n_clients: int) -> MacKeys:
    """Fresh random pairwise secrets for an in-process testnet."""
    return MacKeys(
        {
            (c, r): secrets.token_bytes(32)
            for c in range(n_clients)
            for r in range(n)
        },
        {
            (i, j): secrets.token_bytes(32)
            for i in range(n)
            for j in range(i + 1, n)
        },
    )


def _mac(key: bytes, digest: bytes) -> bytes:
    return hmac_mod.new(key, digest, hashlib.sha256).digest()


class MacAuthenticator(api.Authenticator):
    """MAC-vector authenticator; USIG certificates delegate to ``inner``
    (a :class:`SampleAuthenticator` carrying the USIG + engine)."""

    def __init__(
        self,
        own_id: int,
        is_client: bool,
        n: int,
        keys: MacKeys,
        inner: Optional[SampleAuthenticator] = None,
        engine=None,
        device_macs: bool = False,
    ):
        self.own_id = own_id
        self.is_client = is_client
        self.n = n
        self._keys = keys
        self._inner = inner
        self._engine = engine
        self._device_macs = device_macs

    def bind_engine(self, engine) -> None:
        """Late-bind a batching engine (engine-pool home-chip facade):
        MAC checks then ride its host HMAC lane (``device_macs`` still
        decides device placement), and the inner USIG authenticator gets
        the same binding.  No-op when an engine was already injected —
        same contract as :meth:`SampleAuthenticator.bind_engine`."""
        if self._engine is None and engine is not None:
            self._engine = engine
        if self._inner is not None and hasattr(self._inner, "bind_engine"):
            self._inner.bind_engine(engine)

    # -- generation ---------------------------------------------------------

    def generate_message_authen_tag(
        self, role: api.AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        digest = hashlib.sha256(msg).digest()
        if role == api.AuthenticationRole.CLIENT:
            if not self.is_client:
                raise api.AuthenticationError("not a client")
            return b"".join(
                _mac(self._keys.k_client(self.own_id, r), digest)
                for r in range(self.n)
            )
        if role == api.AuthenticationRole.REPLICA:
            if self.is_client:
                raise api.AuthenticationError("not a replica")
            if audience >= 0:  # REPLY to one client
                return _mac(self._keys.k_client(audience, self.own_id), digest)
            # REQ-VIEW-CHANGE: vector over replicas, own slot zeroed
            return b"".join(
                (
                    b"\x00" * _MAC_LEN
                    if r == self.own_id
                    else _mac(self._keys.k_replicas(self.own_id, r), digest)
                )
                for r in range(self.n)
            )
        if role == api.AuthenticationRole.USIG:
            if self._inner is None:
                raise api.AuthenticationError("no USIG authenticator")
            return self._inner.generate_message_authen_tag(role, msg, audience)
        raise api.AuthenticationError(f"unknown role {role}")

    # -- verification -------------------------------------------------------

    async def _verify_mac(self, key: bytes, digest: bytes, mac: bytes) -> None:
        if len(mac) != _MAC_LEN:
            raise api.AuthenticationError("malformed MAC")
        if self._engine is not None:
            if self._device_macs:
                ok = await self._engine.verify_hmac_sha256(key, digest, mac)
            else:
                ok = await self._engine.verify_hmac_sha256_host(key, digest, mac)
            if not ok:
                raise api.AuthenticationError("bad MAC")
            return
        if not hmac_mod.compare_digest(_mac(key, digest), mac):
            raise api.AuthenticationError("bad MAC")

    async def verify_message_authen_tag(
        self, role: api.AuthenticationRole, peer_id: int, msg: bytes, tag: bytes
    ) -> None:
        digest = hashlib.sha256(msg).digest()
        if role == api.AuthenticationRole.CLIENT:
            # replica self verifying client peer_id's REQUEST vector
            if self.is_client:
                raise api.AuthenticationError("clients don't verify requests")
            if len(tag) != self.n * _MAC_LEN:
                raise api.AuthenticationError("malformed MAC vector")
            slot = tag[self.own_id * _MAC_LEN : (self.own_id + 1) * _MAC_LEN]
            await self._verify_mac(
                self._keys.k_client(peer_id, self.own_id), digest, slot
            )
            return
        if role == api.AuthenticationRole.REPLICA:
            if self.is_client:  # client verifying a REPLY from peer_id
                await self._verify_mac(
                    self._keys.k_client(self.own_id, peer_id), digest, tag
                )
                return
            # replica verifying a replica's vector (REQ-VIEW-CHANGE)
            if len(tag) != self.n * _MAC_LEN:
                raise api.AuthenticationError("malformed MAC vector")
            slot = tag[self.own_id * _MAC_LEN : (self.own_id + 1) * _MAC_LEN]
            await self._verify_mac(
                self._keys.k_replicas(peer_id, self.own_id), digest, slot
            )
            return
        if role == api.AuthenticationRole.USIG:
            if self._inner is None:
                raise api.AuthenticationError("no USIG authenticator")
            await self._inner.verify_message_authen_tag(role, peer_id, msg, tag)
            return
        raise api.AuthenticationError(f"unknown role {role}")

    def reset_usig_epoch(self, peer_id: int) -> None:
        """Operator re-bootstrap hook (see SampleAuthenticator): forwarded
        to the inner USIG authenticator so --auth mac deployments can
        re-accept a restarted replica's fresh epoch."""
        if self._inner is not None:
            self._inner.reset_usig_epoch(peer_id)

    def allow_epoch_capture_from(self, peer_id: int, counter: int) -> None:
        """State-transfer TOFU floor (see SampleAuthenticator): forwarded
        to the inner USIG authenticator."""
        if self._inner is not None:
            self._inner.allow_epoch_capture_from(peer_id, counter)


def new_test_mac_authenticators(
    n: int,
    n_clients: int = 1,
    usig_kind: str = "hmac",
    engines=None,
    engine=None,
    device_macs: bool = False,
    client_engine=None,
):
    """Testnet MAC authenticators (mirrors new_test_authenticators):
    returns (replica_auths, client_auths)."""
    from .authenticator import make_testnet_usigs

    # Inner authenticators carry only the USIG role (MACs replace the
    # signature roles, so no signature keypairs are generated).
    usigs, usig_ids = make_testnet_usigs(n, usig_kind)
    inner_replicas = [
        SampleAuthenticator(
            usig=usigs[i],
            usig_ids=usig_ids,
            engine=(engines[i] if engines else engine),
            batch_signatures=False,
            own_replica_id=i,
        )
        for i in range(n)
    ]
    keys = generate_testnet_mac_keys(n, n_clients)
    replica_auths = [
        MacAuthenticator(
            i,
            False,
            n,
            keys.view_for_replica(i),
            inner=inner_replicas[i],
            engine=(engines[i] if engines else engine),
            device_macs=device_macs,
        )
        for i in range(n)
    ]
    client_auths = [
        MacAuthenticator(
            c, True, n, keys.view_for_client(c), engine=client_engine,
            device_macs=device_macs,
        )
        for c in range(n_clients)
    ]
    return replica_auths, client_auths
