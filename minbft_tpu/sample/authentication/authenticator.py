"""The sample Authenticator: role → scheme dispatch with TPU batch verify.

Reference sample/authentication/authenticator.go:43-116 maps each role to an
``AuthenticationScheme`` from the keystore keyspec (ECDSA → public-key
scheme, SGX_ECDSA → USIG scheme).  This build's authenticator additionally
takes a :class:`minbft_tpu.parallel.BatchVerifier`: every ``verify`` call
becomes an awaitable batch lane — **this is the TPUAuthenticator of
BASELINE.json** ("accumulates PREPARE/COMMIT/REQUEST signature checks into
fixed-size batches and dispatches them to a jax.vmap'd verifier").

Scheme wire formats (canonical, defined by this build):

- ECDSA-P256 signature tag: r(32) || s(32), big-endian.
- Ed25519 signature tag: RFC 8032 (R(32) || S(32)).
- USIG tag: marshalled UI = counter_be8 || cert, where cert =
  epoch(8) || scheme-specific certificate (see minbft_tpu/usig/software.py).
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Dict, Optional, Tuple

from ... import api
from ...messages import UI
from ...parallel import BatchVerifier
from ...usig.software import EcdsaUSIG, HmacUSIG, _signed_payload, parse_usig_id
from ...utils import hostcrypto as hc

_EPOCH_LEN = 8


class SigScheme:
    """Public-key signature scheme plug-in (reference SignatureCipher +
    PublicAuthenScheme, sample/authentication/crypto.go:36-126).

    ``verify`` placement: ``engine=None`` verifies inline on the host;
    with an engine, ``device=True`` joins the TPU batch queue and
    ``device=False`` the engine's host queue — which still provides the
    cluster-wide dedup memo (the n replicas check the same client
    signature once) without the device round trip."""

    name = "?"
    # Whether a TPU batch-verify kernel exists for this scheme; the
    # authenticator routes device-incapable schemes to the host path.
    device_capable = True
    # Whether a device batch-SIGN kernel exists (the fixed-base comb
    # k*G / r*B paths); schemes without one fall back to sync sign.
    sign_capable = False

    def sign(self, priv, msg: bytes) -> bytes:
        raise NotImplementedError

    async def sign_async(self, priv, msg: bytes, engine) -> bytes:
        """Awaitable signing through the engine's sign queue.  Only
        defined for sign_capable schemes — the queue itself falls back to
        serial host signing when no healthy device exists, so callers
        never need a scheme-level device probe."""
        raise NotImplementedError

    async def verify(self, pub, msg: bytes, tag: bytes, engine, device=True) -> bool:
        raise NotImplementedError

    async def verify_many(self, items, engine, device=True) -> list:
        """Whole-bundle verification: ``items = [(pub, msg, tag), ...]``
        -> [bool, ...].  Default is the serial loop; schemes with an
        engine batch entry override it so a decoded ingest bundle reaches
        the verify queue in ONE call (engine.submit_many) instead of one
        racing submit per message."""
        return [
            await self.verify(pub, msg, tag, engine, device)
            for pub, msg, tag in items
        ]


class EcdsaScheme(SigScheme):
    name = "ecdsa-p256"
    sign_capable = True

    def sign(self, priv: int, msg: bytes) -> bytes:
        digest = hashlib.sha256(msg).digest()
        r, s = hc.ecdsa_sign(priv, digest)
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    async def sign_async(self, priv: int, msg: bytes, engine) -> bytes:
        digest = hashlib.sha256(msg).digest()
        r, s = await engine.sign_ecdsa_p256(priv, digest)
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    async def verify(
        self, pub: Tuple[int, int], msg: bytes, tag: bytes, engine, device=True
    ) -> bool:
        if len(tag) != 64:
            return False
        digest = hashlib.sha256(msg).digest()
        sig = (int.from_bytes(tag[:32], "big"), int.from_bytes(tag[32:], "big"))
        if engine is not None:
            if device:
                return await engine.verify_ecdsa_p256(pub, digest, sig)
            return await engine.verify_ecdsa_p256_host(pub, digest, sig)
        return hc.ecdsa_verify(pub, digest, sig)

    async def verify_many(self, items, engine, device=True) -> list:
        if engine is None:
            return await super().verify_many(items, engine, device)
        lanes = []
        bad = []  # malformed tags short-circuit to False, item-wise
        for i, (pub, msg, tag) in enumerate(items):
            if len(tag) != 64:
                bad.append(i)
                continue
            digest = hashlib.sha256(msg).digest()
            sig = (
                int.from_bytes(tag[:32], "big"),
                int.from_bytes(tag[32:], "big"),
            )
            lanes.append((pub, digest, sig))
        verify = (
            engine.verify_ecdsa_p256_many
            if device
            else engine.verify_ecdsa_p256_host_many
        )
        verdicts = iter(await verify(lanes) if lanes else ())
        bad_set = set(bad)
        return [
            False if i in bad_set else next(verdicts)
            for i in range(len(items))
        ]


class Ed25519Scheme(SigScheme):
    name = "ed25519"
    sign_capable = True

    def sign(self, priv: bytes, msg: bytes) -> bytes:
        return hc.ed25519_sign(priv, hashlib.sha256(msg).digest())

    async def sign_async(self, priv: bytes, msg: bytes, engine) -> bytes:
        return await engine.sign_ed25519(priv, hashlib.sha256(msg).digest())

    async def verify(
        self, pub: bytes, msg: bytes, tag: bytes, engine, device=True
    ) -> bool:
        digest = hashlib.sha256(msg).digest()
        if engine is not None:
            if device:
                return await engine.verify_ed25519(pub, digest, tag)
            return await engine.verify_ed25519_host(pub, digest, tag)
        return hc.ed25519_verify(pub, digest, tag)

    async def verify_many(self, items, engine, device=True) -> list:
        if engine is None:
            return await super().verify_many(items, engine, device)
        lanes = [
            (pub, hashlib.sha256(msg).digest(), tag) for pub, msg, tag in items
        ]
        verify = (
            engine.verify_ed25519_many if device else engine.verify_ed25519_host_many
        )
        return await verify(lanes) if lanes else []


class NistEcdsaScheme(SigScheme):
    """Wider NIST curves, HOST path only (reference keymanager.go:169-241
    accepts P-224..P-521 keys; this build serves P-384/P-521).  There is
    deliberately no TPU kernel for these curves — the device queue rejects
    with a clear error rather than silently degrading, and the normal
    routing never sends them there."""

    device_capable = False

    def __init__(self, curve: str):
        self.name = f"ecdsa-{curve}"
        self._curve = curve

    def sign(self, priv: bytes, msg: bytes) -> bytes:
        return hc.nist_sign(self._curve, priv, msg)

    async def verify(
        self, pub: bytes, msg: bytes, tag: bytes, engine, device=True
    ) -> bool:
        if engine is not None:
            if device:
                raise api.AuthenticationError(
                    f"{self.name} has no TPU verify kernel: host path only "
                    "(only ecdsa-p256 / ed25519 batch on device)"
                )
            # Engine host queue: cluster-wide dedup memo + worker-thread
            # OpenSSL, same placement as the sibling schemes' host path.
            return await engine.verify_nist_host(self._curve, pub, msg, tag)
        return hc.nist_verify(self._curve, pub, msg, tag)


SCHEMES = {
    s.name: s
    for s in (
        EcdsaScheme(),
        Ed25519Scheme(),
        NistEcdsaScheme("p384"),
        NistEcdsaScheme("p521"),
    )
}


class SampleAuthenticator(api.Authenticator):
    """Role-dispatching authenticator with TPU-batched verification.

    ``sig_keys``: {role: (own_private_key, {peer_id: public_key})} for the
    CLIENT/REPLICA roles (only the roles this node plays need a private
    key; pass None).  ``usig``: own USIG instance (replicas only).
    ``usig_ids``: {replica_id: anchor bytes} — trust anchors for peers'
    USIGs, in either of two forms:

    - **key-material anchor** (64B ECDSA x||y / 32B HMAC fingerprint, the
      keystore's ``usigKey``): the peer's epoch is captured
      trust-on-first-use from its first valid counter-1 UI and pinned
      thereafter — the reference's SGXUSIGAuthenticationScheme behavior
      (crypto.go:204-239, assumption comment at 204-218).  A peer restart
      draws a fresh epoch (reference usig.c:168-186); verifiers that
      already captured the old epoch reject the new one until an operator
      re-bootstraps them (:meth:`reset_usig_epoch`), exactly the
      reference's documented assumption.
    - **full pinned ID** (epoch || key material, 72B/40B): no capture —
      for single-run in-process tests where instances live exactly once.
    """

    def __init__(
        self,
        scheme: str = "ecdsa-p256",
        client_priv=None,
        client_pubs: Optional[Dict[int, object]] = None,
        replica_priv=None,
        replica_pubs: Optional[Dict[int, object]] = None,
        usig=None,
        usig_ids: Optional[Dict[int, bytes]] = None,
        engine: Optional[BatchVerifier] = None,
        batch_signatures: bool = True,
        batch_sign: bool = True,
        own_replica_id: Optional[int] = None,
    ):
        self._scheme = SCHEMES[scheme]
        self._client_priv = client_priv
        self._client_pubs = client_pubs or {}
        self._replica_priv = replica_priv
        self._replica_pubs = replica_pubs or {}
        self._usig = usig
        self._usig_ids = usig_ids or {}
        # TOFU-captured epochs per peer (reference crypto.go:149-152
        # "USIG key fingerprint -> captured epoch value"), plus one
        # in-flight first-contact capture future per peer so concurrent
        # higher-counter UIs wait instead of spuriously failing.
        self._usig_epochs: Dict[int, bytes] = {}
        self._usig_epoch_pending: Dict[int, "asyncio.Future"] = {}
        # Per-peer minimum counters from which first-contact epoch capture
        # is allowed WITHOUT counter 1 (state-transfer joins; see
        # allow_epoch_capture_from).
        self._epoch_capture_floor: Dict[int, int] = {}
        # Self-anchor: our own epoch needs no first-contact capture — we
        # ARE the trusted source.  Without this, a replica that becomes
        # primary after a view change cannot verify its own UIs embedded
        # in peers' COMMITs: its own counter-1 message never passes
        # through its validation path (own messages are trusted), so TOFU
        # would wait for a first contact that cannot happen.  Keyed by the
        # explicit own id — anchors alone cannot identify "self" (the
        # HMAC scheme's key fingerprint is shared by every replica).
        if usig is not None and own_replica_id is not None:
            anchor = self._usig_ids.get(own_replica_id)
            own_id = usig.id()
            if anchor is not None and own_id[_EPOCH_LEN:] == anchor:
                self._usig_epochs[own_replica_id] = own_id[:_EPOCH_LEN]
        # How long a non-counter-1 UI waits for a first-contact capture
        # before rejecting (only relevant before a peer's epoch is known).
        self.tofu_capture_timeout = 10.0
        self._engine = engine
        # Batch the public-key signature checks too (on by default; tests
        # may disable it to exercise only the USIG batch path without
        # paying the big-kernel compile on the CPU SIM backend).
        self._batch_signatures = batch_signatures
        # Route own CLIENT/REPLICA signing through the engine's sign
        # queue (the awaitable batch sign surface).  Unlike
        # batch_signatures this needs no placement judgement call: the
        # queue itself resolves device-vs-host (sign_on_device auto-gates
        # on the backend, write-off demotes a sick tunnel), so leaving it
        # on is safe everywhere an engine exists.  USIG signing is
        # unaffected by design — see generate_message_authen_tag_async.
        self._batch_sign = batch_sign

    def bind_engine(self, engine) -> None:
        """Late-bind a batching engine (or an engine-pool facade) onto an
        engine-less authenticator.  The multi-group runtime uses this to
        hand each group's base authenticator its HOME-CHIP engine after
        placement: the authenticator was constructed before the pool
        (key material first, placement later).  A no-op when an engine
        was already injected at construction — an explicit per-replica
        engine wins over pool placement."""
        if self._engine is None and engine is not None:
            self._engine = engine

    # -- generation ---------------------------------------------------------

    def generate_message_authen_tag(
        self, role: api.AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        if role == api.AuthenticationRole.CLIENT:
            if self._client_priv is None:
                raise api.AuthenticationError("no client key")
            return self._scheme.sign(self._client_priv, msg)
        if role == api.AuthenticationRole.REPLICA:
            if self._replica_priv is None:
                raise api.AuthenticationError("no replica key")
            return self._scheme.sign(self._replica_priv, msg)
        if role == api.AuthenticationRole.USIG:
            if self._usig is None:
                raise api.AuthenticationError("no USIG")
            return self._usig.create_ui(msg).to_bytes()
        raise api.AuthenticationError(f"unknown role {role}")

    async def generate_message_authen_tag_async(
        self, role: api.AuthenticationRole, msg: bytes, audience: int = -1
    ) -> bytes:
        """Batch-aware signing: CLIENT/REPLICA tags of sign-capable
        schemes join the engine's sign queue (an awaitable batch lane
        over the comb kernels — host fallback inside the queue when no
        device is healthy); everything else takes the synchronous path.

        The USIG role ALWAYS signs serially: create_ui holds the counter
        lock across certify-then-increment (reference usig.c:66-69) and
        must keep doing so — batching UI creation would either reorder
        counters against send order or serialize on the lock anyway.
        Tests pin this boundary by asserting no sign-queue traffic from
        USIG tag generation."""
        if (
            self._engine is not None
            and self._batch_sign
            and self._scheme.sign_capable
            and role
            in (api.AuthenticationRole.CLIENT, api.AuthenticationRole.REPLICA)
        ):
            priv = (
                self._client_priv
                if role == api.AuthenticationRole.CLIENT
                else self._replica_priv
            )
            if priv is not None:
                return await self._scheme.sign_async(priv, msg, self._engine)
        return self.generate_message_authen_tag(role, msg, audience)

    # -- verification -------------------------------------------------------

    async def verify_message_authen_tag(
        self, role: api.AuthenticationRole, peer_id: int, msg: bytes, tag: bytes
    ) -> None:
        # Signature placement: TPU batches when batch_signatures is on;
        # otherwise the engine's host queue (dedup without device round
        # trips) when an engine exists; plain inline verification when not.
        sig_engine = self._engine
        sig_device = self._batch_signatures and self._scheme.device_capable
        if role == api.AuthenticationRole.CLIENT:
            pub = self._client_pubs.get(peer_id)
            if pub is None:
                raise api.AuthenticationError(f"unknown client {peer_id}")
            if not await self._scheme.verify(pub, msg, tag, sig_engine, sig_device):
                raise api.AuthenticationError("bad client signature")
            return
        if role == api.AuthenticationRole.REPLICA:
            pub = self._replica_pubs.get(peer_id)
            if pub is None:
                raise api.AuthenticationError(f"unknown replica {peer_id}")
            if not await self._scheme.verify(pub, msg, tag, sig_engine, sig_device):
                raise api.AuthenticationError("bad replica signature")
            return
        if role == api.AuthenticationRole.USIG:
            await self._verify_usig(peer_id, msg, tag)
            return
        raise api.AuthenticationError(f"unknown role {role}")

    @property
    def supports_batch_verify(self) -> bool:
        # Engine-backed AND a scheme that actually overrides verify_many:
        # the verify queues' dedup/in-flight coalescing is what makes the
        # ingest seed free.  Without an engine — or for schemes stuck on
        # the base class's serial loop (the wider NIST curves) — the
        # batch surface IS the serial loop and must not be seeded.
        return (
            self._engine is not None
            and type(self._scheme).verify_many is not SigScheme.verify_many
        )

    async def verify_message_authen_tags(
        self, role: api.AuthenticationRole, items
    ) -> list:
        """Batch surface for the bundle-ingest runtime (api.Authenticator
        contract): CLIENT/REPLICA signature checks of a whole decoded
        bundle land on the engine verify queue in ONE call
        (scheme.verify_many -> engine.submit_many), so the device sees
        the bundle as one batch instead of len(bundle) racing submits.
        USIG tags keep the serial path — the TOFU epoch-capture state
        machine is inherently per-message (the base-class loop is used)."""
        if role not in (
            api.AuthenticationRole.CLIENT,
            api.AuthenticationRole.REPLICA,
        ):
            return await super().verify_message_authen_tags(role, items)
        pubs = (
            self._client_pubs
            if role == api.AuthenticationRole.CLIENT
            else self._replica_pubs
        )
        who = "client" if role == api.AuthenticationRole.CLIENT else "replica"
        out: list = [None] * len(items)
        lanes = []
        lane_rows = []
        for i, (peer_id, msg, tag) in enumerate(items):
            pub = pubs.get(peer_id)
            if pub is None:
                out[i] = api.AuthenticationError(f"unknown {who} {peer_id}")
                continue
            lanes.append((pub, msg, tag))
            lane_rows.append(i)
        if lanes:
            verdicts = await self._scheme.verify_many(
                lanes,
                self._engine,
                self._batch_signatures and self._scheme.device_capable,
            )
            for row, ok in zip(lane_rows, verdicts):
                if not ok:
                    out[row] = api.AuthenticationError(f"bad {who} signature")
        return out

    def reset_usig_epoch(self, peer_id: int) -> None:
        """Forget the captured epoch for a peer so its next counter-1 UI
        re-captures — the operator re-bootstrap hook for accepting a
        restarted peer's fresh epoch (the reference leaves this to "some
        bootstrapping procedure", crypto.go:219-225).

        Any state-transfer capture floor is dropped too: a restarted peer
        signs from counter 1 again, and a surviving floor would let a
        delayed PRE-restart message (counter >= floor) re-pin the stale
        epoch and undo this reset — the exact race the counter-1 rule
        exists to narrow."""
        self._usig_epochs.pop(peer_id, None)
        self._epoch_capture_floor.pop(peer_id, None)

    def allow_epoch_capture_from(self, peer_id: int, counter: int) -> None:
        """Permit first-contact epoch capture from a UI at counter >=
        ``counter`` for ``peer_id``.

        A replica that joins late via state transfer NEVER sees any
        peer's counter-1 UI — that history is provably covered by an
        f+1-certified checkpoint and was truncated — so the reference's
        counter-1-only TOFU rule would leave it unable to establish any
        epoch and deaf to all live traffic.  The core calls this when it
        validates a peer's LOG-BASE announcement (the f+1 certificate
        proves counters <= base hold no live evidence): capturing from
        the first valid UI above the certified base trusts exactly what
        counter-1 capture trusts — the first contact signed by the
        anchored key (reference crypto.go:204-218's stated assumption),
        no more."""
        cur = self._epoch_capture_floor.get(peer_id)
        if cur is None or counter < cur:
            self._epoch_capture_floor[peer_id] = counter

    def _resolve_usig_id(self, peer_id: int, ui: UI) -> Tuple[bytes, bool]:
        """Resolve the effective usig_id (epoch || key material) for a
        peer from its trust anchor; returns (usig_id, capture_needed).
        ``capture_needed`` is True only when the epoch was taken from the
        UI certificate itself (first contact) — an epoch read from the
        captured map must NOT be re-pinned after the verify await, or an
        in-flight old-epoch UI would silently undo reset_usig_epoch."""
        anchor = self._usig_ids.get(peer_id)
        if anchor is None:
            raise api.AuthenticationError(f"unknown USIG for replica {peer_id}")
        if len(anchor) in (_EPOCH_LEN + 64, _EPOCH_LEN + 32):
            return anchor, False  # full pinned ID
        if len(anchor) not in (64, 32):
            raise api.AuthenticationError("malformed USIG trust anchor")
        epoch = self._usig_epochs.get(peer_id)
        if epoch is not None:
            return epoch + anchor, False
        # Capture the epoch from the first valid UI — which must carry
        # counter 1 (reference crypto.go:220-226: epoch is taken from
        # the cert only when none is captured AND ui.Counter == 1), OR
        # sit at/above a checkpoint-certified log base this replica
        # adopted (state-transfer join: counter-1 history is truncated —
        # see allow_epoch_capture_from).
        floor = self._epoch_capture_floor.get(peer_id)
        if ui.counter != 1 and (floor is None or ui.counter < floor):
            raise api.AuthenticationError(
                f"no captured epoch for replica {peer_id} and UI counter "
                f"{ui.counter} != 1"
                + (f" (state-transfer capture floor: {floor})" if floor else "")
            )
        if len(ui.cert) < _EPOCH_LEN:
            raise api.AuthenticationError("malformed UI certificate")
        return ui.cert[:_EPOCH_LEN] + anchor, True

    def _capture_usig_epoch(self, peer_id: int, epoch: bytes) -> None:
        """Pin the epoch after a successful verification.  First capture
        wins; a concurrently-captured different epoch fails this UI (the
        reference holds a lock across verify, crypto.go:198-200 — here
        verification awaits the batch engine, so re-check instead)."""
        cur = self._usig_epochs.get(peer_id)
        if cur is None:
            self._usig_epochs[peer_id] = epoch
        elif cur != epoch:
            raise api.AuthenticationError(
                f"USIG epoch for replica {peer_id} changed during verification"
            )

    async def _verify_usig(self, peer_id: int, msg: bytes, tag: bytes) -> None:
        try:
            ui = UI.from_bytes(tag)
        except ValueError as e:
            raise api.AuthenticationError(f"malformed UI: {e}") from e
        if ui.counter == 0:
            raise api.AuthenticationError("zero UI counter")
        try:
            usig_id, tofu = self._resolve_usig_id(peer_id, ui)
        except api.AuthenticationError:
            # Startup race: this peer's counter-1 UI may be concurrently
            # in flight (concurrent stream tasks co-batch their UI checks)
            # but not yet captured — it may not even have reached
            # _verify_usig yet.  Wait (bounded) on a shared per-peer
            # future that the first-contact verification completes, then
            # retry the resolve once; if nothing was captured meanwhile,
            # the second resolve raises the right error.  (The reference
            # holds a lock across verify, crypto.go:198-200 — this is the
            # async analogue.)
            if self._usig_ids.get(peer_id) is None:
                raise  # unknown peer: waiting can't help
            fut = self._usig_epoch_pending.get(peer_id)
            if fut is None:
                fut = asyncio.get_event_loop().create_future()
                self._usig_epoch_pending[peer_id] = fut
            try:
                await asyncio.wait_for(
                    asyncio.shield(fut), self.tofu_capture_timeout
                )
            except asyncio.TimeoutError:
                if self._usig_epoch_pending.get(peer_id) is fut:
                    self._usig_epoch_pending.pop(peer_id, None)
                if self._usig_epochs.get(peer_id) is None:
                    raise api.AuthenticationError(
                        f"no counter-1 UI from replica {peer_id} to "
                        "establish its USIG epoch"
                    ) from None
            usig_id, tofu = self._resolve_usig_id(peer_id, ui)
        if tofu:
            # First contact: make sure a pending future exists for
            # concurrent non-counter-1 UIs to wait on, and complete it
            # when this verification settles (success or failure — the
            # waiters re-resolve and get the accurate outcome).
            fut = self._usig_epoch_pending.get(peer_id)
            if fut is None:
                fut = asyncio.get_event_loop().create_future()
                self._usig_epoch_pending[peer_id] = fut
            try:
                await self._verify_usig_resolved(peer_id, msg, ui, usig_id, tofu)
            finally:
                if self._usig_epoch_pending.get(peer_id) is fut:
                    self._usig_epoch_pending.pop(peer_id, None)
                if not fut.done():
                    fut.set_result(None)
            return
        await self._verify_usig_resolved(peer_id, msg, ui, usig_id, tofu)

    async def _verify_usig_resolved(
        self, peer_id: int, msg: bytes, ui: UI, usig_id: bytes, tofu: bool
    ) -> None:
        usig_scheme = getattr(self._usig, "SCHEME", None)
        if self._engine is not None and usig_scheme == "ecdsa-p256":
            # Batched TPU verification of the UI certificate (the TPU-USIG
            # of BASELINE.json).
            from ...usig.software import UsigError, usig_verify_items

            try:
                q, payload, sig = usig_verify_items(msg, ui, usig_id)
            except UsigError as e:
                raise api.AuthenticationError(str(e)) from e
            if not await self._engine.verify_ecdsa_p256(q, payload, sig):
                raise api.AuthenticationError("invalid UI certificate")
            if tofu:
                self._capture_usig_epoch(peer_id, usig_id[:_EPOCH_LEN])
            return
        if self._engine is not None and usig_scheme == "hmac-sha256":
            from ...usig.software import UsigError

            try:
                epoch, fp = parse_usig_id(usig_id)
            except UsigError as e:
                raise api.AuthenticationError(str(e)) from e
            # Mirror the serial HmacUSIG._verify checks exactly so batch and
            # serial verification can never disagree: key-fingerprint match
            # and an exact-length cert (no trailing bytes after the MAC).
            if fp != hashlib.sha256(self._usig._key).digest():
                raise api.AuthenticationError("USIG key fingerprint mismatch")
            if len(ui.cert) != _EPOCH_LEN + 32 or ui.cert[:_EPOCH_LEN] != epoch:
                raise api.AuthenticationError("epoch mismatch")
            digest = hashlib.sha256(msg).digest()
            payload = _signed_payload(digest, epoch, ui.counter)
            mac = ui.cert[_EPOCH_LEN : _EPOCH_LEN + 32]
            if not await self._engine.verify_hmac_sha256(
                self._usig._key, payload, mac
            ):
                raise api.AuthenticationError("invalid UI certificate")
            if tofu:
                self._capture_usig_epoch(peer_id, epoch)
            return
        # Serial host fallback (SIM mode without an engine).
        if self._usig is None:
            raise api.AuthenticationError("no USIG to verify with")
        from ...usig import UsigError

        try:
            self._usig.verify_ui(msg, ui, usig_id)
        except UsigError as e:
            raise api.AuthenticationError(str(e)) from e
        if tofu:
            self._capture_usig_epoch(peer_id, usig_id[:_EPOCH_LEN])


def make_testnet_usigs(n: int, usig_kind: str):
    """Testnet USIG instances + trust anchors, shared by the signature and
    MAC authenticator factories (one source of truth for the shared HMAC
    testnet key)."""
    if usig_kind == "ecdsa":
        usigs = [EcdsaUSIG() for _ in range(n)]
    elif usig_kind == "hmac":
        shared = hashlib.sha256(b"testnet-usig-key").digest()
        usigs = [HmacUSIG(shared) for _ in range(n)]
    else:
        raise ValueError(usig_kind)
    return usigs, {i: u.id() for i, u in enumerate(usigs)}


def new_test_authenticators(
    n: int,
    n_clients: int = 1,
    scheme: str = "ecdsa-p256",
    usig_kind: str = "ecdsa",
    engine: Optional[BatchVerifier] = None,
    engines: Optional[list] = None,
    batch_signatures: bool = True,
    batch_sign: bool = True,
    client_engine: Optional[BatchVerifier] = None,
    tofu_anchors: bool = False,
):
    """Generate a coherent set of authenticators for an in-process testnet
    (the reference's GenerateTestnetKeys equivalent,
    sample/authentication/keymanager.go:404-450).

    ``tofu_anchors=True`` hands out key-material anchors instead of full
    pinned IDs, so the epoch trust-on-first-use machinery (incl. the
    constructor self-anchor) is exercised like a deployed keystore.

    Returns (replica_auths, client_auths)."""
    if scheme == "ecdsa-p256":
        replica_keys = [hc.keygen() for _ in range(n)]
        client_keys = [hc.keygen() for _ in range(n_clients)]
        replica_pubs = {i: q for i, (_, q) in enumerate(replica_keys)}
        client_pubs = {i: q for i, (_, q) in enumerate(client_keys)}
    elif scheme == "ed25519":
        replica_keys = [hc.ed25519_keygen() for _ in range(n)]
        client_keys = [hc.ed25519_keygen() for _ in range(n_clients)]
        replica_pubs = {i: pub for i, (_, pub) in enumerate(replica_keys)}
        client_pubs = {i: pub for i, (_, pub) in enumerate(client_keys)}
    else:
        raise ValueError(scheme)

    usigs, usig_ids = make_testnet_usigs(n, usig_kind)
    if tofu_anchors:
        usig_ids = {i: uid[_EPOCH_LEN:] for i, uid in usig_ids.items()}

    replica_auths = [
        SampleAuthenticator(
            scheme=scheme,
            replica_priv=replica_keys[i][0],
            replica_pubs=replica_pubs,
            client_pubs=client_pubs,
            usig=usigs[i],
            usig_ids=usig_ids,
            engine=(engines[i] if engines else engine),
            batch_signatures=batch_signatures,
            batch_sign=batch_sign,
            own_replica_id=i,
        )
        for i in range(n)
    ]
    client_auths = [
        SampleAuthenticator(
            scheme=scheme,
            client_priv=client_keys[i][0],
            replica_pubs=replica_pubs,
            client_pubs=client_pubs,
            # Default None: clients verify replies serially (f+1 is small).
            # Pass client_engine to co-batch REPLY verification on TPU
            # (it also carries the client's REQUEST signing through the
            # sign queue when batch_sign is on).
            engine=client_engine,
            batch_sign=batch_sign,
        )
        for i in range(n_clients)
    ]
    return replica_auths, client_auths
