"""keytool — generate testnet keystores from the command line.

Reference: the cobra/viper ``keytool generate`` command
(sample/authentication/keytool/cmd/generate.go:44-142) writes a keys.yaml
with replica/usig/client sections.  Usage:

    python -m minbft_tpu.sample.authentication.keytool generate \
        -o keys.yaml -n 3 --clients 1 --scheme ecdsa-p256 --usig auto

Flags fall back to ``KEYTOOL_*`` environment variables (the viper env
binding equivalent, reference keytool/cmd/root.go).
"""

from __future__ import annotations

import argparse
import sys


from ..envflags import env_default

_SCHEMES = ("ecdsa-p256", "ed25519", "ecdsa-p384", "ecdsa-p521")
_USIG_SPECS = ("auto", "NATIVE_ECDSA", "SOFT_ECDSA", "HMAC_SHA256")


def _env_default(name: str, fallback, choices=None):
    return env_default("KEYTOOL", name, fallback, choices)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="keytool", description="minbft-tpu keystore generation"
    )
    sub = p.add_subparsers(dest="command", required=True)
    g = sub.add_parser("generate", help="generate a testnet keys.yaml")
    g.add_argument(
        "-o",
        "--output",
        default=_env_default("output", "keys.yaml"),
        help="output path (default keys.yaml)",
    )
    g.add_argument(
        "-n",
        "--replicas",
        type=int,
        default=_env_default("replicas", 3),
        help="number of replicas",
    )
    g.add_argument(
        "--clients",
        type=int,
        default=_env_default("clients", 1),
        help="number of clients",
    )
    g.add_argument(
        "--scheme",
        choices=_SCHEMES,
        default=_env_default("scheme", "ecdsa-p256", choices=_SCHEMES),
        help="signature scheme for replica/client keys",
    )
    g.add_argument(
        "--usig",
        choices=_USIG_SPECS,
        default=_env_default("usig", "auto", choices=_USIG_SPECS),
        help="USIG keyspec (auto = native module if buildable, else soft)",
    )
    g.add_argument(
        "--macs",
        action="store_true",
        default=bool(_env_default("macs", 0)),
        help="also generate pairwise-MAC material (MAC authentication scheme)",
    )
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "generate":
        from .keystore import generate_testnet_keys

        store = generate_testnet_keys(
            n=args.replicas,
            n_clients=args.clients,
            scheme=args.scheme,
            usig_spec=args.usig,
            with_macs=args.macs,
        )
        store.save(args.output)
        print(
            f"wrote {args.output}: {args.replicas} replicas, "
            f"{args.clients} clients, scheme={store.scheme}, "
            f"usig={store.usig_spec}"
            + (", pairwise MACs" if store.mac_keys is not None else ""),
            file=sys.stderr,
        )
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
