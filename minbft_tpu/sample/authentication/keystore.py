"""Persistent keystore: the keys.yaml of this build.

The reference stores all testnet key material in one YAML file with three
sections — replica / usig / client, each ``{keyspec, keys: [{id, ...}]}``
(reference sample/authentication/keymanager.go:129-162) — and pluggable
keyspecs (``ECDSA``, ``SGX_ECDSA``; keymanager.go:169-328).  This build
keeps that shape with its own specs:

- ``ECDSA_P256`` / ``ED25519`` — signature keypairs for the replica and
  client sections (privateKey/publicKey, base64).
- ``NATIVE_ECDSA`` — USIG sealed by the native C++ module
  (minbft_tpu/native); the sealed blob is opaque to Python, exactly as the
  enclave-sealed key is opaque to the reference's Go side
  (keymanager.go:299-328 stores it base64).
- ``SOFT_ECDSA`` — software-sealed USIG (SIM mode): a self-describing blob
  holding the private scalar with an integrity checksum.  Like SGX SIM
  sealing, this provides durability, not confidentiality.
- ``HMAC_SHA256`` — the shared-key testnet USIG; the blob holds the
  cluster-shared MAC key.

Every usig entry also records the **public** ``usigKey`` — the key
material (ECDSA x||y, or the HMAC key fingerprint) that anchors trust in
that replica's USIG (the reference stores the USIG *public key* the same
way, reference keymanager.go:169-239).  The epoch is deliberately NOT part
of the anchor: every USIG init draws a fresh random epoch (reference
usig/sgx/enclave/usig.c:168-186), and verifiers capture each peer's
current epoch trust-on-first-use from its first counter-1 UI
(SampleAuthenticator, reference crypto.go:204-218).

Durable-state story (SURVEY.md §5 "checkpoint/resume"): the sealed USIG
key is the system's only durable state.  ``KeyStore.make_usig`` restores a
replica's USIG from its sealed blob: same key — peers' key anchors remain
valid — but a fresh epoch and a counter restarting at 1 (volatile), so a
restart can never re-certify already-issued (epoch, cv) values.
"""

from __future__ import annotations

import base64
import hashlib
import secrets
from typing import Dict, Optional, Tuple

from ...usig.software import EcdsaUSIG, HmacUSIG
from ...utils import hostcrypto as hc
from .authenticator import SampleAuthenticator

_EPOCH_LEN = 8
_SOFT_MAGIC = b"SSL2"    # v2: magic || scalar32 || check8 (no epoch)
_SOFT_MAGIC_V1 = b"SSL1"  # v1 carried a sealed epoch; ignored on restore


# --------------------------------------------------------------------------
# signature keyspecs


def _ecdsa_generate() -> Tuple[bytes, bytes]:
    d, (x, y) = hc.keygen()
    return d.to_bytes(32, "big"), x.to_bytes(32, "big") + y.to_bytes(32, "big")


def _ecdsa_decode(priv: Optional[bytes], pub: bytes):
    q = (int.from_bytes(pub[:32], "big"), int.from_bytes(pub[32:], "big"))
    return (int.from_bytes(priv, "big") if priv else None), q


def _ed25519_generate() -> Tuple[bytes, bytes]:
    seed, pub = hc.ed25519_keygen()
    return seed, pub


def _ed25519_decode(priv: Optional[bytes], pub: bytes):
    return priv, pub


def _nist_generate(curve: str):
    def gen() -> Tuple[bytes, bytes]:
        return hc.nist_keygen(curve)

    return gen


def _nist_decode(priv: Optional[bytes], pub: bytes):
    return priv, pub


_SIG_SPECS = {
    "ECDSA_P256": ("ecdsa-p256", _ecdsa_generate, _ecdsa_decode),
    "ED25519": ("ed25519", _ed25519_generate, _ed25519_decode),
    # Wider-curve keyspecs (reference keymanager.go:169-241 accepts
    # P-224..P-521): host-path verification only — the TPU kernels are
    # P-256/Ed25519; see authenticator.NistEcdsaScheme.
    "ECDSA_P384": ("ecdsa-p384", _nist_generate("p384"), _nist_decode),
    "ECDSA_P521": ("ecdsa-p521", _nist_generate("p521"), _nist_decode),
}
_SPEC_FOR_SCHEME = {v[0]: k for k, v in _SIG_SPECS.items()}


# --------------------------------------------------------------------------
# USIG keyspecs (sealed blobs)


def _soft_seal(d: int) -> bytes:
    body = _SOFT_MAGIC + d.to_bytes(32, "big")
    return body + hashlib.sha256(body).digest()[:8]


def _soft_unseal(blob: bytes) -> int:
    """Recover the private scalar; the epoch is never restored (a fresh
    one is drawn per instance, reference usig.c:168-186).  v1 blobs
    (which sealed an epoch) are accepted with the epoch discarded."""
    if len(blob) == 4 + 32 + 8 and blob[:4] == _SOFT_MAGIC:
        scalar = blob[4:-8]
    elif len(blob) == 4 + _EPOCH_LEN + 32 + 8 and blob[:4] == _SOFT_MAGIC_V1:
        scalar = blob[4 + _EPOCH_LEN : -8]
    else:
        raise ValueError("malformed soft-sealed USIG blob")
    body, check = blob[:-8], blob[-8:]
    if hashlib.sha256(body).digest()[:8] != check:
        raise ValueError("soft-sealed USIG blob failed integrity check")
    return int.from_bytes(scalar, "big")


def _new_usig(spec: str, shared_hmac_key: Optional[bytes] = None):
    """Create a fresh USIG for ``spec``; returns (usig, sealed_blob)."""
    if spec == "NATIVE_ECDSA":
        from ...usig.native import NativeEcdsaUSIG

        u = NativeEcdsaUSIG()
        return u, u.seal()
    if spec == "SOFT_ECDSA":
        u = EcdsaUSIG()
        return u, _soft_seal(u._d)
    if spec == "HMAC_SHA256":
        key = shared_hmac_key or secrets.token_bytes(32)
        return HmacUSIG(key), key
    raise ValueError(f"unknown USIG keyspec {spec!r}")


def _restore_usig(spec: str, sealed: bytes):
    """Restore a USIG from its sealed blob: same key, fresh random epoch,
    counter restarting at 1 (reference usig.c:168-186)."""
    if spec == "NATIVE_ECDSA":
        from ...usig.native import NativeEcdsaUSIG

        return NativeEcdsaUSIG.from_sealed(sealed)
    if spec == "SOFT_ECDSA":
        return EcdsaUSIG(private_key=_soft_unseal(sealed))
    if spec == "HMAC_SHA256":
        if len(sealed) == 32:
            return HmacUSIG(sealed)
        if len(sealed) == _EPOCH_LEN + 32:  # v1 blob: epoch || key
            return HmacUSIG(sealed[_EPOCH_LEN:])
        raise ValueError("malformed HMAC USIG blob")
    raise ValueError(f"unknown USIG keyspec {spec!r}")


def usig_key_anchor(usig) -> bytes:
    """The epoch-free trust anchor for a USIG: its ID minus the volatile
    epoch prefix (= key material: x||y for ECDSA, key fingerprint for
    HMAC)."""
    return usig.id()[_EPOCH_LEN:]


# --------------------------------------------------------------------------


class KeyStoreError(Exception):
    pass


class KeyStore:
    """In-memory form of a keys.yaml (reference BftKeyStorer,
    keymanager.go:39-47): per-section keyspec + id-indexed key material."""

    def __init__(
        self,
        scheme: str = "ecdsa-p256",
        usig_spec: str = "SOFT_ECDSA",
    ):
        if scheme not in _SPEC_FOR_SCHEME:
            raise KeyStoreError(f"unknown signature scheme {scheme!r}")
        if usig_spec not in ("NATIVE_ECDSA", "SOFT_ECDSA", "HMAC_SHA256"):
            raise KeyStoreError(f"unknown USIG keyspec {usig_spec!r}")
        self.scheme = scheme
        self.usig_spec = usig_spec
        # {id: (privateKey bytes|None, publicKey bytes)}
        self.replica_keys: Dict[int, Tuple[Optional[bytes], bytes]] = {}
        self.client_keys: Dict[int, Tuple[Optional[bytes], bytes]] = {}
        # {id: (sealed bytes|None, key-material anchor bytes)} — the
        # anchor is epoch-free (see module docstring).
        self.usig_keys: Dict[int, Tuple[Optional[bytes], bytes]] = {}
        # optional pairwise-MAC material (sample/authentication/mac.py)
        self.mac_keys = None  # Optional[MacKeys]

    # -- serialization -------------------------------------------------------

    def to_dict(self, secret: Optional[bytes] = None) -> dict:
        """Serializable form.  With ``secret``, every PRIVATE field —
        signature private keys, sealed USIG blobs, the pairwise MAC
        matrix — is AES-256-GCM encrypted under a per-file master key
        (one PBKDF2 derivation, random salt recorded in the ``seal``
        section): a stolen keys.yaml then discloses no key material,
        matching the reference's sgx_seal_data property
        (reference usig/sgx/enclave/usig.c:107-116).  Public fields stay
        plaintext (peers need them)."""
        from ...utils import sealbox

        has_private = (
            any(priv is not None for priv, _ in self.replica_keys.values())
            or any(priv is not None for priv, _ in self.client_keys.values())
            or any(sealed is not None for sealed, _ in self.usig_keys.values())
            or self.mac_keys is not None
        )
        seal_hdr = {}
        if secret is not None and not has_private:
            # A strip_private() copy holds only public material: emitting
            # a seal header would make a fully-public file unreadable to
            # consumers without the operator secret for no benefit.
            secret = None
        if secret is not None:
            salt = secrets.token_bytes(sealbox.SALT_LEN)
            mk = sealbox.derive_key(secret, salt)
            seal_hdr["seal"] = {
                "kdf": sealbox.KDF,
                "salt": base64.b64encode(salt).decode(),
                "iterations": sealbox.ITERATIONS,
            }

            def enc(v: bytes) -> str:
                return base64.b64encode(sealbox.box(v, mk)).decode()

        else:

            def enc(v: bytes) -> str:
                return base64.b64encode(v).decode()

        def sig_section(keys):
            return {
                "keyspec": _SPEC_FOR_SCHEME[self.scheme],
                "keys": [
                    {
                        "id": kid,
                        **(
                            {"privateKey": enc(priv)}
                            if priv is not None
                            else {}
                        ),
                        "publicKey": base64.b64encode(pub).decode(),
                    }
                    for kid, (priv, pub) in sorted(keys.items())
                ],
            }

        mac_section = {}
        if self.mac_keys is not None:
            mac_section["macs"] = {
                "keyspec": "HMAC_PAIRWISE",
                "clientReplica": [
                    {"client": c, "replica": r, "key": enc(k)}
                    for (c, r), k in sorted(self.mac_keys.client_replica.items())
                ],
                "replicaPair": [
                    {"i": i, "j": j, "key": enc(k)}
                    for (i, j), k in sorted(self.mac_keys.replica_pair.items())
                ],
            }
        return {
            **seal_hdr,
            "replica": sig_section(self.replica_keys),
            "client": sig_section(self.client_keys),
            **mac_section,
            "usig": {
                "keyspec": self.usig_spec,
                "keys": [
                    {
                        "id": kid,
                        **(
                            {"sealedKey": enc(sealed)}
                            if sealed is not None
                            else {}
                        ),
                        "usigKey": base64.b64encode(anchor).decode(),
                    }
                    for kid, (sealed, anchor) in sorted(self.usig_keys.items())
                ],
            },
        }

    @classmethod
    def from_dict(cls, data: dict, secret: Optional[bytes] = None) -> "KeyStore":
        from ...utils import sealbox

        seal = data.get("seal")
        if seal is not None:
            if secret is None:
                raise KeyStoreError(
                    "keystore is sealed: set MINBFT_SEAL_SECRET or "
                    "MINBFT_SEAL_SECRET_FILE to open it"
                )
            if seal.get("kdf") != sealbox.KDF:
                raise KeyStoreError(f"unknown seal kdf {seal.get('kdf')!r}")
            iters = int(seal.get("iterations", sealbox.ITERATIONS))
            if not 0 < iters <= 10_000_000:
                # Mirror the native v3 parser's bound: a tampered file
                # must not be able to spin PBKDF2 for hours.
                raise KeyStoreError(f"seal iteration count {iters} out of range")
            mk = sealbox.derive_key(
                secret, base64.b64decode(seal["salt"]), iters
            )

            def dec(s: str) -> bytes:
                try:
                    return sealbox.unbox(base64.b64decode(s), mk)
                except sealbox.SealError as e:
                    raise KeyStoreError(str(e)) from e

        else:

            def dec(s: str) -> bytes:
                return base64.b64decode(s)

        rep = data.get("replica", {})
        spec = rep.get("keyspec", "ECDSA_P256")
        if spec not in _SIG_SPECS:
            raise KeyStoreError(f"unknown signature keyspec {spec!r}")
        client_spec = data.get("client", {}).get("keyspec", spec)
        if client_spec != spec:
            # One signature scheme per store (the decode path is shared);
            # refuse rather than silently misdecode client keys.
            raise KeyStoreError(
                f"client keyspec {client_spec!r} != replica keyspec {spec!r}"
            )
        usig = data.get("usig", {})
        store = cls(scheme=_SIG_SPECS[spec][0], usig_spec=usig.get("keyspec", "SOFT_ECDSA"))

        def read_sig(section) -> Dict[int, Tuple[Optional[bytes], bytes]]:
            out = {}
            for entry in section.get("keys", []):
                priv = entry.get("privateKey")
                out[int(entry["id"])] = (
                    dec(priv) if priv else None,
                    base64.b64decode(entry["publicKey"]),
                )
            return out

        store.replica_keys = read_sig(rep)
        store.client_keys = read_sig(data.get("client", {}))
        macs = data.get("macs")
        if macs:
            mac_spec = macs.get("keyspec", "HMAC_PAIRWISE")
            if mac_spec != "HMAC_PAIRWISE":
                raise KeyStoreError(f"unknown MAC keyspec {mac_spec!r}")
            from .mac import MacKeys

            store.mac_keys = MacKeys(
                {
                    (int(e["client"]), int(e["replica"])): dec(e["key"])
                    for e in macs.get("clientReplica", [])
                },
                {
                    (int(e["i"]), int(e["j"])): dec(e["key"])
                    for e in macs.get("replicaPair", [])
                },
            )
        for entry in usig.get("keys", []):
            sealed = entry.get("sealedKey")
            if "usigKey" in entry:
                anchor = base64.b64decode(entry["usigKey"])
            else:
                # legacy usigId = epoch(8) || key material: the epoch part
                # is volatile and must not be pinned — strip it.
                anchor = base64.b64decode(entry["usigId"])[_EPOCH_LEN:]
            store.usig_keys[int(entry["id"])] = (
                dec(sealed) if sealed else None,
                anchor,
            )
        return store

    _SECRET_FROM_ENV = object()  # sentinel: source the seal secret lazily

    def save(self, path: str, secret=_SECRET_FROM_ENV) -> None:
        """Write keys.yaml with owner-only permissions.  When a sealing
        secret is configured (MINBFT_SEAL_SECRET / _FILE, or passed
        explicitly) every private field is encrypted at rest — see
        :meth:`to_dict`; otherwise 0600 permissions are the only
        protection (the round-3 behavior).  Deployment flows should
        distribute per-replica ``strip_private(keep_replica=i)`` copies,
        not this full store."""
        import os as _os

        import yaml

        from ...utils import sealbox

        if secret is KeyStore._SECRET_FROM_ENV:
            secret = sealbox.seal_secret()
        fd = _os.open(path, _os.O_CREAT | _os.O_WRONLY | _os.O_TRUNC, 0o600)
        # O_CREAT's mode only applies to newly-created files; tighten a
        # pre-existing laxer file too before writing secrets into it.
        _os.fchmod(fd, 0o600)
        with _os.fdopen(fd, "w") as fh:
            yaml.safe_dump(self.to_dict(secret=secret), fh, sort_keys=False)

    @classmethod
    def load(cls, path: str, secret=_SECRET_FROM_ENV) -> "KeyStore":
        import yaml

        from ...utils import sealbox

        if secret is KeyStore._SECRET_FROM_ENV:
            secret = sealbox.seal_secret()
        with open(path) as fh:
            data = yaml.safe_load(fh) or {}
        return cls.from_dict(data, secret=secret)

    def strip_private(self, keep_replica: Optional[int] = None) -> "KeyStore":
        """A copy safe to hand to other nodes: private material removed
        except (optionally) one replica's own keys (for MACs: its pairwise
        rows only — MAC secrets are inherently shared per pair)."""
        out = KeyStore(scheme=self.scheme, usig_spec=self.usig_spec)
        out.replica_keys = {
            kid: (priv if kid == keep_replica else None, pub)
            for kid, (priv, pub) in self.replica_keys.items()
        }
        out.client_keys = {kid: (None, pub) for kid, (_, pub) in self.client_keys.items()}
        out.usig_keys = {
            kid: (sealed if kid == keep_replica else None, uid)
            for kid, (sealed, uid) in self.usig_keys.items()
        }
        if self.mac_keys is not None and keep_replica is not None:
            out.mac_keys = self.mac_keys.view_for_replica(keep_replica)
        return out

    # -- restoration ---------------------------------------------------------

    def make_usig(self, replica_id: int):
        """Restore replica_id's USIG from its sealed blob (durable state).

        The restored instance has a fresh epoch, so only the key-material
        anchor — never the full (epoch-bearing) ID — is checked."""
        sealed, anchor = self.usig_keys[replica_id]
        if sealed is None:
            raise KeyStoreError(f"no sealed USIG key for replica {replica_id}")
        u = _restore_usig(self.usig_spec, sealed)
        if usig_key_anchor(u) != anchor:
            raise KeyStoreError(
                f"restored USIG key mismatch for replica {replica_id}"
            )
        return u

    def usig_anchors(self) -> Dict[int, bytes]:
        """Epoch-free key-material trust anchors, one per replica (what
        SampleAuthenticator consumes for TOFU epoch capture)."""
        return {kid: anchor for kid, (_, anchor) in self.usig_keys.items()}


    def _decode_sig(self, keys, kid: int):
        if kid not in keys:
            raise KeyStoreError(f"no key with id {kid}")
        priv, pub = keys[kid]
        return _SIG_SPECS[_SPEC_FOR_SCHEME[self.scheme]][2](priv, pub)

    def replica_pubs(self) -> Dict[int, object]:
        return {kid: self._decode_sig(self.replica_keys, kid)[1] for kid in self.replica_keys}

    def client_pubs(self) -> Dict[int, object]:
        return {kid: self._decode_sig(self.client_keys, kid)[1] for kid in self.client_keys}

    def replica_authenticator(
        self,
        replica_id: int,
        engine=None,
        batch_signatures: bool = True,
        batch_sign: bool = True,
    ) -> SampleAuthenticator:
        priv, _ = self._decode_sig(self.replica_keys, replica_id)
        if priv is None:
            raise KeyStoreError(f"no private key for replica {replica_id}")
        return SampleAuthenticator(
            scheme=self.scheme,
            replica_priv=priv,
            replica_pubs=self.replica_pubs(),
            client_pubs=self.client_pubs(),
            usig=self.make_usig(replica_id),
            usig_ids=self.usig_anchors(),
            engine=engine,
            batch_signatures=batch_signatures,
            batch_sign=batch_sign,
            own_replica_id=replica_id,
        )

    def mac_replica_authenticator(
        self, replica_id: int, engine=None, device_macs: bool = False
    ):
        """MAC-scheme authenticator for a replica (requires a ``macs``
        section; USIG delegates to this store's sealed USIG)."""
        if self.mac_keys is None:
            raise KeyStoreError("keystore has no MAC section")
        from .mac import MacAuthenticator

        n = len(self.usig_keys)
        inner = SampleAuthenticator(
            usig=self.make_usig(replica_id),
            usig_ids=self.usig_anchors(),
            engine=engine,
            batch_signatures=False,
            own_replica_id=replica_id,
        )
        # The principal's view only — handing out the full matrix would let
        # one compromised replica forge other principals' MAC slots.
        return MacAuthenticator(
            replica_id, False, n, self.mac_keys.view_for_replica(replica_id),
            inner=inner, engine=engine, device_macs=device_macs,
        )

    def mac_client_authenticator(self, client_id: int, engine=None):
        if self.mac_keys is None:
            raise KeyStoreError("keystore has no MAC section")
        from .mac import MacAuthenticator

        return MacAuthenticator(
            client_id, True, len(self.usig_keys),
            self.mac_keys.view_for_client(client_id), engine=engine,
        )

    def client_authenticator(self, client_id: int, engine=None) -> SampleAuthenticator:
        priv, _ = self._decode_sig(self.client_keys, client_id)
        if priv is None:
            raise KeyStoreError(f"no private key for client {client_id}")
        return SampleAuthenticator(
            scheme=self.scheme,
            client_priv=priv,
            replica_pubs=self.replica_pubs(),
            client_pubs=self.client_pubs(),
            engine=engine,
        )


def generate_testnet_keys(
    n: int,
    n_clients: int = 1,
    scheme: str = "ecdsa-p256",
    usig_spec: str = "auto",
    with_macs: bool = False,
) -> KeyStore:
    """Generate a full testnet keystore (reference GenerateTestnetKeys,
    keymanager.go:404-450): n replica keypairs + USIGs, n_clients client
    keypairs.  ``usig_spec="auto"`` prefers the native module and falls
    back to the software seal."""
    if usig_spec == "auto":
        from ...usig import native as native_mod

        usig_spec = "NATIVE_ECDSA" if native_mod.available(auto_build=True) else "SOFT_ECDSA"
    store = KeyStore(scheme=scheme, usig_spec=usig_spec)
    spec = _SPEC_FOR_SCHEME[scheme]
    gen = _SIG_SPECS[spec][1]
    for i in range(n):
        store.replica_keys[i] = gen()
    for c in range(n_clients):
        store.client_keys[c] = gen()
    shared = secrets.token_bytes(32) if usig_spec == "HMAC_SHA256" else None
    for i in range(n):
        u, sealed = _new_usig(usig_spec, shared_hmac_key=shared)
        store.usig_keys[i] = (sealed, usig_key_anchor(u))
    if with_macs:
        from .mac import generate_testnet_mac_keys

        store.mac_keys = generate_testnet_mac_keys(n, n_clients)
    return store
