"""SimpleLedger: a hash-chained block ledger as the sample state machine.

Reference sample/requestconsumer/simpleledger.go: one block per delivered
request, each block carrying the previous block's hash; ``state_digest`` is
the hash of the last block.  The reference runs a serial executor goroutine
over a queue (113-134); here delivery happens on the event loop, which is
already serial — the protocol's commitment collector releases executions in
order (minbft_tpu/core/commit.py).
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Optional

from ... import api


class Block:
    def __init__(self, height: int, prev_hash: bytes, payload: bytes):
        self.height = height
        self.prev_hash = prev_hash
        self.payload = payload

    def digest(self) -> bytes:
        return hashlib.sha256(
            struct.pack(">Q", self.height) + self.prev_hash + self.payload
        ).digest()


class SimpleLedger(api.RequestConsumer):
    def __init__(self):
        genesis = Block(0, b"\x00" * 32, b"genesis")
        self._blocks: List[Block] = [genesis]

    async def deliver(self, operation: bytes) -> bytes:
        """Append one block per operation (reference simpleledger.go:168-187);
        the result returned to the client is the new block's digest."""
        prev = self._blocks[-1]
        block = Block(prev.height + 1, prev.digest(), operation)
        self._blocks.append(block)
        return block.digest()

    def state_digest(self) -> bytes:
        return self._blocks[-1].digest()

    @property
    def length(self) -> int:
        """Number of blocks excluding genesis (reference ledger length
        assertions in core/integration_test.go:199-210)."""
        return len(self._blocks) - 1

    def block(self, height: int) -> Optional[Block]:
        return self._blocks[height] if 0 <= height < len(self._blocks) else None
