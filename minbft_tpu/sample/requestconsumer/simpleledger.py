"""SimpleLedger: a hash-chained block ledger as the sample state machine.

Reference sample/requestconsumer/simpleledger.go: one block per delivered
request, each block carrying the previous block's hash; ``state_digest`` is
the hash of the last block.  The reference runs a serial executor goroutine
over a queue (113-134); here delivery happens on the event loop, which is
already serial — the protocol's commitment collector releases executions in
order (minbft_tpu/core/commit.py).
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Optional

from ... import api


class Block:
    def __init__(self, height: int, prev_hash: bytes, payload: bytes):
        self.height = height
        self.prev_hash = prev_hash
        self.payload = payload

    def digest(self) -> bytes:
        return hashlib.sha256(
            struct.pack(">Q", self.height) + self.prev_hash + self.payload
        ).digest()


class SimpleLedger(api.RequestConsumer):
    def __init__(self):
        genesis = Block(0, b"\x00" * 32, b"genesis")
        self._blocks: List[Block] = [genesis]

    async def deliver(self, operation: bytes) -> bytes:
        """Append one block per operation (reference simpleledger.go:168-187);
        the result returned to the client is the new block's digest."""
        prev = self._blocks[-1]
        block = Block(prev.height + 1, prev.digest(), operation)
        self._blocks.append(block)
        return block.digest()

    def state_digest(self) -> bytes:
        return self._blocks[-1].digest()

    async def query(self, operation: bytes) -> bytes:
        """Read-only operations (api.RequestConsumer.query contract:
        deterministic in committed state, since the client needs all n
        replies to match).  Supported ops:

        - ``b"head"`` (or anything unrecognized): chain height + head
          digest — "what is the current state?"
        - ``b"block:<height>"``: that block's digest, or empty bytes if
          out of range.
        """
        if operation.startswith(b"block:"):
            try:
                blk = self.block(int(operation[6:]))
            except ValueError:
                blk = None
            return blk.digest() if blk is not None else b""
        head = self._blocks[-1]
        return struct.pack(">Q", head.height) + head.digest()

    @property
    def length(self) -> int:
        """Number of blocks excluding genesis (reference ledger length
        assertions in core/integration_test.go:199-210)."""
        return len(self._blocks) - 1

    def block(self, height: int) -> Optional[Block]:
        return self._blocks[height] if 0 <= height < len(self._blocks) else None

    # -- checkpoint state transfer ------------------------------------------

    def snapshot(self) -> bytes:
        """Serialize the whole chain (sample-grade; a production state
        machine would snapshot compactly).  Round-trips through
        :meth:`install_snapshot` to an identical ``state_digest``."""
        out = [struct.pack(">I", len(self._blocks))]
        for b in self._blocks:
            out.append(
                struct.pack(">Q", b.height)
                + b.prev_hash
                + struct.pack(">I", len(b.payload))
                + b.payload
            )
        return b"".join(out)

    def snapshot_digest(self, data: bytes) -> bytes:
        """Digest the snapshot would produce once installed — parse and
        chain-verify without touching local state (api.RequestConsumer
        contract: lets state transfer check against a certified
        checkpoint digest before committing)."""
        return self._parse_and_verify(data)[-1].digest()

    def install_snapshot(self, data: bytes) -> None:
        """Parse and hash-chain-verify a snapshot, then swap atomically —
        the prior state survives any malformed/inconsistent payload."""
        self._blocks = self._parse_and_verify(data)

    def _parse_and_verify(self, data: bytes) -> List[Block]:
        try:
            (count,) = struct.unpack_from(">I", data, 0)
            off = 4
            blocks: List[Block] = []
            for _ in range(count):
                (height,) = struct.unpack_from(">Q", data, off)
                off += 8
                prev_hash = data[off : off + 32]
                if len(prev_hash) != 32:
                    raise ValueError("truncated prev_hash")
                off += 32
                (plen,) = struct.unpack_from(">I", data, off)
                off += 4
                payload = data[off : off + plen]
                if len(payload) != plen:
                    raise ValueError("truncated payload")
                off += plen
                blocks.append(Block(height, prev_hash, payload))
            if off != len(data):
                raise ValueError("trailing bytes")
        except struct.error as e:
            raise ValueError(f"malformed ledger snapshot: {e}") from e
        if not blocks:
            raise ValueError("empty ledger snapshot")
        for i, b in enumerate(blocks):
            if b.height != i:
                raise ValueError("non-sequential block heights")
            if i and b.prev_hash != blocks[i - 1].digest():
                raise ValueError("broken hash chain in snapshot")
        if blocks[0].prev_hash != b"\x00" * 32 or blocks[0].payload != b"genesis":
            raise ValueError("snapshot genesis mismatch")
        return blocks
