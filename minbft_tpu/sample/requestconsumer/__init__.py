"""Request consumers (replicated state machines)."""

from .simpleledger import SimpleLedger

__all__ = ["SimpleLedger"]
