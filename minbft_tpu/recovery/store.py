"""Durable stable-checkpoint store: atomic persistence, paranoid load.

One small binary file per replica (per group under group mode) holds the
latest *stable* position: the f+1 checkpoint certificate, the application
snapshot it certifies, the client retire watermarks, and the replica's own
USIG counter watermark at that point.  Two failure modes get opposite
treatment on load:

- **Torn write** (crash mid-save): impossible for the committed file by
  construction — saves go through write-to-temp + fsync + ``os.replace`` +
  directory fsync, so the committed path always holds either the previous
  complete file or the new complete file.  A leftover ``*.tmp`` is the torn
  artifact; it is discarded unread, never trusted.
- **Corrupted committed file** (digest trailer mismatch, bad magic, wrong
  owner, garbage fields): :class:`CorruptStoreError`.  This is a *hard
  startup failure* — a committed file never legitimately fails its digest,
  so silently starting fresh would mask disk corruption or tampering and
  forfeit the durability the operator asked for with ``--state-dir``.

The store is a cache of *certified* state, not an authority: the loader
re-validates the embedded certificate and recomputes the composite
checkpoint digest against the snapshot before anything is installed
(core/message_handling.py ``restore_from_store``), exactly as if the bytes
had arrived from an untrusted peer.

Saves never regress: :meth:`DurableStore.save` refuses a state whose count
is below what the file already holds, so the persisted stable bound — and
with it the USIG watermark — is monotonic across crashes by construction
(checked end-to-end by ``testing/invariants.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import struct
from typing import Optional, Tuple

from ..messages import Checkpoint, CodecError, marshal, unmarshal

MAGIC = b"MBFTSTR1"
STATE_DIR_ENV = "MINBFT_STATE_DIR"

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_DIGEST_LEN = 32


class CorruptStoreError(Exception):
    """The committed store file failed validation.  Deliberately fatal at
    startup: rc != 0 with a clear message, never a silent fresh start."""


def state_dir_from_env(default: str = "") -> str:
    """Resolve the durable state directory: explicit value wins, else
    ``MINBFT_STATE_DIR``, else ``default`` (empty = durability off)."""
    return os.environ.get(STATE_DIR_ENV, "") or default


def store_path(state_dir: str, replica_id: int, group: Optional[int] = None) -> str:
    """Store file path for one replica: ``<dir>/replica<i>.state``, with a
    ``group<g>/`` subdirectory under group mode so per-group cores sharing a
    process never collide."""
    if group is not None:
        state_dir = os.path.join(state_dir, f"group{group}")
    return os.path.join(state_dir, f"replica{replica_id}.state")


@dataclasses.dataclass
class StableState:
    """One durable stable position — everything a restart needs to resume
    from the last checkpoint instead of counter zero."""

    count: int
    view: int
    cv: int
    usig_counter: int
    app_state: bytes
    watermarks: Tuple[Tuple[int, int], ...]
    cert: Tuple[Checkpoint, ...]


def _encode(replica_id: int, state: StableState) -> bytes:
    parts = [
        MAGIC,
        _U32.pack(replica_id),
        _U64.pack(state.count),
        _U64.pack(state.view),
        _U64.pack(state.cv),
        _U64.pack(state.usig_counter),
        _U64.pack(len(state.app_state)),
        state.app_state,
        _U32.pack(len(state.watermarks)),
    ]
    for client, seq in state.watermarks:
        parts.append(_U32.pack(client) + _U64.pack(seq))
    parts.append(_U32.pack(len(state.cert)))
    for cp in state.cert:
        raw = marshal(cp)
        parts.append(_U32.pack(len(raw)))
        parts.append(raw)
    payload = b"".join(parts)
    return payload + hashlib.sha256(payload).digest()


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise CorruptStoreError("durable store file is truncated")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]

    def u64(self) -> int:
        return _U64.unpack(self.take(8))[0]


def _decode(replica_id: int, raw: bytes, path: str) -> StableState:
    if len(raw) < len(MAGIC) + _DIGEST_LEN:
        raise CorruptStoreError(f"durable store {path} is too short to be valid")
    payload, digest = raw[:-_DIGEST_LEN], raw[-_DIGEST_LEN:]
    if hashlib.sha256(payload).digest() != digest:
        raise CorruptStoreError(
            f"durable store {path} failed its integrity digest "
            "(disk corruption or tampering — refusing to start fresh)"
        )
    r = _Reader(payload)
    if r.take(len(MAGIC)) != MAGIC:
        raise CorruptStoreError(f"durable store {path} has wrong magic")
    owner = r.u32()
    if owner != replica_id:
        raise CorruptStoreError(
            f"durable store {path} belongs to replica {owner}, not {replica_id}"
        )
    count, view, cv, usig = r.u64(), r.u64(), r.u64(), r.u64()
    app_state = bytes(r.take(r.u64()))
    watermarks = tuple((r.u32(), r.u64()) for _ in range(r.u32()))
    cert = []
    for _ in range(r.u32()):
        try:
            msg = unmarshal(bytes(r.take(r.u32())))
        except CodecError as exc:
            raise CorruptStoreError(
                f"durable store {path} holds an undecodable certificate entry: {exc}"
            ) from exc
        if not isinstance(msg, Checkpoint):
            raise CorruptStoreError(
                f"durable store {path} certificate entry is not a CHECKPOINT"
            )
        cert.append(msg)
    if r.pos != len(payload):
        raise CorruptStoreError(f"durable store {path} has trailing garbage")
    return StableState(
        count=count,
        view=view,
        cv=cv,
        usig_counter=usig,
        app_state=app_state,
        watermarks=watermarks,
        cert=tuple(cert),
    )


class DurableStore:
    """Atomic, digest-sealed persistence for one replica's stable state.

    ``save``/``load`` do blocking file IO by design — callers on the event
    loop wrap them in ``asyncio.to_thread`` (saves are off-path at
    checkpoint cadence; the single startup load happens before serving).
    """

    def __init__(self, path: str, replica_id: int) -> None:
        self.path = path
        self.replica_id = replica_id
        self._last_count: Optional[int] = None

    def save(self, state: StableState) -> bool:
        """Persist ``state`` atomically.  Returns False (no write) when the
        file already holds an equal-or-newer stable count — the durable
        bound never regresses."""
        if self._last_count is None and os.path.exists(self.path):
            # First save of this process over an existing file: learn the
            # incumbent bound so a restarted replica that briefly lags its
            # own previous stable position cannot clobber it.
            try:
                incumbent = self.load()
                self._last_count = incumbent.count if incumbent else -1
            except CorruptStoreError:
                # Startup already vetted the file; mid-run corruption means
                # the disk is lying — overwriting with fresh certified
                # state is the best available move.
                self._last_count = -1
        if self._last_count is not None and state.count <= self._last_count:
            return False
        blob = _encode(self.replica_id, state)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, blob)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.path)
        dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._last_count = state.count
        return True

    def load(self) -> Optional[StableState]:
        """Load the committed stable state.  Returns None when no committed
        file exists (fresh start); discards a leftover torn temp file;
        raises :class:`CorruptStoreError` when the committed file fails any
        validation."""
        tmp = self.path + ".tmp"
        if os.path.exists(tmp):
            # Torn write from a crash mid-save: the committed file (if any)
            # is the authoritative previous state.
            os.unlink(tmp)
        try:
            with open(self.path, "rb") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return None
        state = _decode(self.replica_id, raw, self.path)
        self._last_count = state.count
        return state
