"""Deterministic chunking and digest chaining for resumable state transfer.

The wire protocol (``STATE_REQ``/``STATE_CHUNK``/``STATE_DONE`` in
:mod:`minbft_tpu.messages.message`) moves a stable application snapshot as a
sequence of fixed-size slices.  Two properties make the stream *resumable*
and *peer-switchable*:

- **Deterministic chunking.**  Every honest responder slices the same
  snapshot bytes into byte-identical chunks (fixed chunk size, offsets at
  multiples of it), so a requester that verified bytes ``[0, offset)`` from
  one peer can ask any other peer to continue from ``offset``.
- **Digest chaining.**  ``chain_k = sha256(chain_{k-1} || data_k)`` with an
  empty seed.  The responder recomputes the chain from byte zero even when
  serving a resume, so the carried chain commits to the *whole prefix*, not
  just the slice — a spliced or corrupted chunk is detected at the first
  bad slice instead of after the full download.  The chain is an early
  tripwire only; final authority is always the f+1 checkpoint certificate
  verified over the assembled snapshot before install.

The chunk size is a cluster-wide deployment constant (``chunk_bytes()``,
``MINBFT_RECOVERY_CHUNK_BYTES``): resume offsets are chunk-aligned by
construction, so mixing chunk sizes across peers degrades resume into
restart-from-zero via the normal failover path — safe, just wasteful.
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterator, Tuple

# Default 64 KiB: small enough that several chunks fit one 0xF0 multi-frame
# (MULTI_MAX_BYTES = 256 KiB) alongside its header, large enough that a
# megabyte-scale snapshot moves in tens of frames.
DEFAULT_CHUNK_BYTES = 64 * 1024
# Hard cap below MULTI_MAX_BYTES so one signed chunk always fits a frame.
MAX_CHUNK_BYTES = 128 * 1024
CHUNK_BYTES_ENV = "MINBFT_RECOVERY_CHUNK_BYTES"


def chunk_bytes() -> int:
    """State-transfer chunk size in bytes (``MINBFT_RECOVERY_CHUNK_BYTES``,
    default 64 KiB, clamped to [1, 128 KiB])."""
    raw = os.environ.get(CHUNK_BYTES_ENV, "")
    try:
        n = int(raw) if raw else DEFAULT_CHUNK_BYTES
    except ValueError:
        n = DEFAULT_CHUNK_BYTES
    return max(1, min(n, MAX_CHUNK_BYTES))


def chain_extend(chain: bytes, data: bytes) -> bytes:
    """One chain step: ``sha256(chain || data)``."""
    return hashlib.sha256(chain + data).digest()


def iter_chunks(data: bytes, size: int) -> Iterator[Tuple[int, bytes]]:
    """Yield ``(offset, slice)`` pairs covering ``data`` in ``size``-byte
    steps.  Empty data yields nothing (the stream is just its DONE frame)."""
    for off in range(0, len(data), size):
        yield off, data[off : off + size]


class ChainMismatch(Exception):
    """A chunk's carried chain digest does not extend the verified prefix —
    cross-stream splice, mid-stream tamper, or a responder whose snapshot
    diverges from the one the stream started with."""


class ChunkAssembler:
    """Reassembles one chunk stream, tolerating replayed prefixes.

    Reconnects replay unicast logs from their retained base (only
    *certified* entries honor ``Hello.resume_counter``), so after a
    connection reset the requester re-receives chunks it already verified.
    ``add`` ignores any chunk below the current offset (idempotent) and
    refuses gaps above it, so delivery order plus the chain digest force the
    buffer to grow monotonically and correctly.
    """

    def __init__(self, count: int) -> None:
        self.count = count
        self.total: int | None = None
        self.chain = b""
        self._buf = bytearray()

    @property
    def offset(self) -> int:
        """Verified byte count — the resume point for the next STATE-REQ."""
        return len(self._buf)

    @property
    def complete(self) -> bool:
        return self.total is not None and len(self._buf) == self.total

    def add(self, offset: int, total: int, data: bytes, chain: bytes) -> bool:
        """Append one chunk.  Returns True if it advanced the buffer, False
        for a stale replay (offset below the verified prefix) or a gap
        (offset ahead of it — wait for the in-order copy).  Raises
        :class:`ChainMismatch` when the carried chain does not extend the
        verified prefix, or when the claimed stream length shifts."""
        if self.total is None:
            self.total = total
        elif total != self.total:
            raise ChainMismatch(
                f"stream length changed mid-transfer: {self.total} -> {total}"
            )
        if offset != len(self._buf):
            return False
        expected = chain_extend(self.chain, data)
        if chain != expected:
            raise ChainMismatch(f"chain digest mismatch at offset {offset}")
        if len(self._buf) + len(data) > total:
            raise ChainMismatch(f"chunk at offset {offset} overruns total {total}")
        self._buf.extend(data)
        self.chain = expected
        return True

    def bytes(self) -> bytes:
        return bytes(self._buf)
