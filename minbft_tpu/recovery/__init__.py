"""Crash-recovery subsystem (ISSUE 20, ROADMAP item 5).

Three cooperating pieces, all optional (a replica without ``--state-dir``
behaves exactly as before):

- :mod:`minbft_tpu.recovery.store` — durable stable-checkpoint store:
  atomic write-rename persistence of the f+1 checkpoint certificate, the
  application snapshot, the retire watermarks, and the USIG counter
  watermark; crash-consistent load on restart (torn writes discarded by
  digest, corrupted committed files refused loudly — never silently
  restarted fresh).
- :mod:`minbft_tpu.recovery.transfer` — deterministic chunking + the
  digest-chained :class:`~minbft_tpu.recovery.transfer.ChunkAssembler`
  behind the ``STATE_REQ``/``STATE_CHUNK``/``STATE_DONE`` resumable
  state-transfer messages (the ``Hello.resume_counter`` pattern
  generalized to state).
- :mod:`minbft_tpu.recovery.manager` — per-replica recovery telemetry:
  phase machine, chunk/byte/resume counters, and the
  restart-to-first-executed-request ``recovery_time_ms`` clock exported
  as the ``minbft_recovery_*`` Prometheus families (obs/prom.py) and
  gated by benchgate (``chaos_recovery_time_ms``).
"""

from .manager import (
    PHASE_CATCHUP,
    PHASE_DONE,
    PHASE_FETCHING,
    PHASE_IDLE,
    PHASE_INSTALLING,
    PHASE_LOADING,
    PHASE_NAMES,
    RecoveryManager,
)
from .store import (
    STATE_DIR_ENV,
    CorruptStoreError,
    DurableStore,
    StableState,
    state_dir_from_env,
    store_path,
)
from .transfer import (
    CHUNK_BYTES_ENV,
    ChainMismatch,
    ChunkAssembler,
    chain_extend,
    chunk_bytes,
    iter_chunks,
)

__all__ = [
    "RecoveryManager",
    "PHASE_IDLE",
    "PHASE_LOADING",
    "PHASE_FETCHING",
    "PHASE_INSTALLING",
    "PHASE_CATCHUP",
    "PHASE_DONE",
    "PHASE_NAMES",
    "DurableStore",
    "StableState",
    "CorruptStoreError",
    "STATE_DIR_ENV",
    "state_dir_from_env",
    "store_path",
    "ChunkAssembler",
    "ChainMismatch",
    "chain_extend",
    "chunk_bytes",
    "iter_chunks",
    "CHUNK_BYTES_ENV",
]
