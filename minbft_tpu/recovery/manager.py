"""Per-replica recovery telemetry: phase machine, counters, and the
restart-to-first-executed-request clock.

The manager is deliberately passive — pure bookkeeping mutated from the
recovery paths in core/message_handling.py and core/replica.py, scraped by
``obs/prom.collect_recovery`` into the ``minbft_recovery_*`` families and
rendered as the RECOV column in ``peer top``.  It owns the
:class:`~minbft_tpu.recovery.store.DurableStore` handle so one object
threads through construction.

``recovery_time_ms`` is the SLO the chaos soak gates (benchgate key
``chaos_recovery_time_ms``): armed when a durable state is loaded at
startup, stopped at the first request *executed* after restart — i.e. the
full restart → restore → (re)transfer → catch-up → serving pipeline, not
just the file read.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .store import DurableStore

PHASE_IDLE = 0
PHASE_LOADING = 1
PHASE_FETCHING = 2
PHASE_INSTALLING = 3
PHASE_CATCHUP = 4
PHASE_DONE = 5

PHASE_NAMES = ("idle", "load", "fetch", "install", "catchup", "done")


class RecoveryManager:
    def __init__(
        self,
        store: Optional[DurableStore] = None,
        group: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.store = store
        self.group = group
        self._clock = clock
        self.phase = PHASE_IDLE
        # Chunked state transfer (either side).
        self.chunks_rx = 0
        self.bytes_rx = 0
        self.chunks_tx = 0
        self.bytes_tx = 0
        self.resumes = 0
        self.failovers = 0
        # Durable store.
        self.saves = 0
        self.save_errors = 0
        self.restored_count: Optional[int] = None
        # Recovery clock.
        self._armed_at: Optional[float] = None
        self.recovery_time_ms: Optional[float] = None

    # -- phase / clock ----------------------------------------------------

    def set_phase(self, phase: int) -> None:
        self.phase = phase

    def arm(self) -> None:
        """Start the recovery clock (startup restore found durable state)."""
        if self._armed_at is None:
            self._armed_at = self._clock()

    def disarm(self) -> None:
        self._armed_at = None

    @property
    def armed(self) -> bool:
        return self._armed_at is not None and self.recovery_time_ms is None

    def note_executed(self) -> None:
        """First executed request after an armed restart stops the clock and
        completes the phase machine.  Cheap no-op on every later call."""
        if self._armed_at is not None and self.recovery_time_ms is None:
            self.recovery_time_ms = (self._clock() - self._armed_at) * 1000.0
            self.phase = PHASE_DONE

    # -- counters ---------------------------------------------------------

    def note_chunk_rx(self, nbytes: int) -> None:
        self.chunks_rx += 1
        self.bytes_rx += nbytes

    def note_chunk_tx(self, nbytes: int) -> None:
        self.chunks_tx += 1
        self.bytes_tx += nbytes

    def note_resume(self) -> None:
        self.resumes += 1

    def note_failover(self) -> None:
        self.failovers += 1

    def note_saved(self, count: int) -> None:
        self.saves += 1

    def note_save_error(self) -> None:
        self.save_errors += 1

    def snapshot(self) -> dict:
        """Point-in-time view for /metrics and ``peer top``."""
        return {
            "phase": self.phase,
            "phase_name": PHASE_NAMES[self.phase],
            "chunks_rx": self.chunks_rx,
            "bytes_rx": self.bytes_rx,
            "chunks_tx": self.chunks_tx,
            "bytes_tx": self.bytes_tx,
            "resumes": self.resumes,
            "failovers": self.failovers,
            "saves": self.saves,
            "save_errors": self.save_errors,
            "restored_count": self.restored_count,
            "recovery_time_ms": self.recovery_time_ms,
        }
