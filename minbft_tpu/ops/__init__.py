"""TPU crypto kernels: the data-parallel compute path of the framework.

Every scheme operates on fixed-width inputs (32-byte digests, fixed-width
keys/signatures) so batch shapes stay static under ``jit``:

- :mod:`minbft_tpu.ops.sha256` — SHA-256 compression in uint32 jax.numpy.
- :mod:`minbft_tpu.ops.hmac_sha256` — batched HMAC-SHA256 (symmetric USIG
  certificates and MAC authenticator).
- :mod:`minbft_tpu.ops.limbs` — 256-bit modular arithmetic as 16×16-bit limb
  vectors (Montgomery), the substrate for the public-key schemes.
- :mod:`minbft_tpu.ops.p256` — batched ECDSA-P256 verification.
- :mod:`minbft_tpu.ops.ed25519` — batched Ed25519 verification.
"""
