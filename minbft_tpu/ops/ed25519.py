"""Batched Ed25519 verification as a JAX/XLA TPU kernel.

The Ed25519 authenticator path (BASELINE config[5]: n=31, batch=1024).
Same architecture as :mod:`minbft_tpu.ops.p256` — host does the cheap
irregular work, the device does the double-scalar multiplication over the
shared limb machinery (:mod:`minbft_tpu.ops.limbs`) — but the curve shape
is friendlier: twisted Edwards (a = -1) extended coordinates have
**complete** addition formulas (a is a square mod 2^255-19, d is not), so
the ladder needs *zero* exceptional-case handling: the identity is a
perfectly ordinary table entry and add(P, P) just works.

Strict cofactorless verification (OpenSSL's semantics, matching
:func:`minbft_tpu.utils.hostcrypto.ed25519_verify` — see the semantics
note there): accept iff ``compress(S*B - k*A) == R-bytes``.  Host computes
k = SHA-512(R||A||M) mod L (SHA-512 needs 64-bit ops — pointless to
emulate on device for 96-byte inputs) and decompresses A (one sqrt,
*cached per public key* — the key set is small and stable), and ships
``u1 = S``, ``u2 = k``, ``A' = -A``, and R's encoded y + sign bit.
Device computes ``P = u1*B + u2*A'`` (256 doublings + 256 *unconditional*
complete additions), normalizes it with one Fermat inversion, and accepts
iff ``(y(P), sign(x(P)))`` equals R's encoding.  R is never decompressed:
the per-signature host big-int sqrt that this replaces was the n=31
benchmark's dominant cost (~64 host pows per committed request).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs
from .limbs import (
    Fe,
    FieldSpec,
    add_mod,
    fe_const,
    fe_eq,
    fe_from_array,
    from_mont,
    mont_inv,
    mont_mul,
    mont_one,
    mont_sqr,
    sub_mod,
    to_limbs,
    to_mont,
)
from ..utils import hostcrypto as hc

P = hc.ED_P  # 2^255 - 19
L = hc.ED_L
D = hc.ED_D

FIELD = FieldSpec.make(P)

_BX_M = fe_const((hc.ED_BX << 256) % P)
_BY_M = fe_const((hc.ED_BY << 256) % P)
_BT_M = fe_const(((hc.ED_BX * hc.ED_BY % P) << 256) % P)
_D2_M = fe_const(((2 * D % P) << 256) % P)


class EdPoint(NamedTuple):
    """Extended twisted-Edwards point (X : Y : Z : T), Montgomery limbs."""

    x: Fe
    y: Fe
    z: Fe
    t: Fe


def _identity() -> EdPoint:
    one = mont_one(FIELD)
    zero = limbs.fe_zero()
    return EdPoint(zero, one, one, zero)


def _add(p: EdPoint, q: EdPoint) -> EdPoint:
    """Complete unified addition, a = -1 (add-2008-hwcd-3 with k = 2d).
    Handles identity and doubling inputs exactly — no special cases."""
    f = FIELD
    a = mont_mul(f, sub_mod(f, p.y, p.x), sub_mod(f, q.y, q.x))
    b = mont_mul(f, add_mod(f, p.y, p.x), add_mod(f, q.y, q.x))
    c = mont_mul(f, mont_mul(f, p.t, _D2_M), q.t)
    zz = mont_mul(f, p.z, q.z)
    d = add_mod(f, zz, zz)
    e = sub_mod(f, b, a)
    ff = sub_mod(f, d, c)
    g = add_mod(f, d, c)
    h = add_mod(f, b, a)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _dbl(p: EdPoint) -> EdPoint:
    """Dedicated doubling (dbl-2008-hwcd, a = -1): 4M + 4S."""
    f = FIELD
    a = mont_sqr(f, p.x)
    b = mont_sqr(f, p.y)
    zz = mont_sqr(f, p.z)
    c = add_mod(f, zz, zz)
    # a_curve = -1: D = -A
    e = sub_mod(f, sub_mod(f, mont_sqr(f, add_mod(f, p.x, p.y)), a), b)
    g = sub_mod(f, b, a)  # D + B
    ff = sub_mod(f, g, c)
    h = sub_mod(f, limbs.fe_zero(), add_mod(f, a, b))  # D - B = -(A+B)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _bits_of(scalar_arr: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.arange(limbs.LIMB_BITS, dtype=jnp.uint32)
    return ((scalar_arr[:, None] >> shifts[None, :]) & 1).reshape(256)


def _ladder(u1_arr: jnp.ndarray, u2_arr: jnp.ndarray, aq: EdPoint) -> EdPoint:
    """P = u1*B + u2*A' — interleaved ladder with *unconditional* complete
    additions: table index 0 is the identity, so every iteration is
    double-then-add with a 4-way table select and no branches at all."""
    one = mont_one(FIELD)
    bpt = EdPoint(_BX_M, _BY_M, one, _BT_M)
    ba = _add(bpt, aq)  # B + A'

    tab = [_identity(), aq, bpt, ba]  # index = 2*bit(u1) + bit(u2)
    bits1 = _bits_of(u1_arr)
    bits2 = _bits_of(u2_arr)

    def sel(d, coord):
        is1, is2 = d == 1, d == 2
        return tuple(
            jnp.where(
                is1, t1, jnp.where(is2, t2, jnp.where(d == 3, t3, t0))
            )
            for t0, t1, t2, t3 in zip(*(getattr(t, coord) for t in tab))
        )

    def body(i, acc):
        j = 255 - i
        acc = _dbl(acc)
        b1 = lax.dynamic_index_in_dim(bits1, j, keepdims=False)
        b2 = lax.dynamic_index_in_dim(bits2, j, keepdims=False)
        d = b1 * 2 + b2
        addend = EdPoint(sel(d, "x"), sel(d, "y"), sel(d, "z"), sel(d, "t"))
        return _add(acc, addend)

    return lax.fori_loop(0, 256, body, _identity())


def _verify_one(
    ax: jnp.ndarray,
    ay: jnp.ndarray,
    u1: jnp.ndarray,
    u2: jnp.ndarray,
    ry: jnp.ndarray,
    rsign: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scalar-shaped Ed25519 verify core; limb-array args [16] u32.

    Accepts iff compress(u1*B + u2*A') matches (ry, rsign) — the affine
    normalization (one Fermat inversion) runs on device; Z is never 0
    under complete formulas on curve points."""
    f = FIELD
    ax_m = to_mont(f, fe_from_array(ax))
    ay_m = to_mont(f, fe_from_array(ay))
    at_m = mont_mul(f, ax_m, ay_m)
    aq = EdPoint(ax_m, ay_m, mont_one(f), at_m)
    res = _ladder(u1, u2, aq)
    zi = mont_inv(f, res.z)
    x_aff = from_mont(f, mont_mul(f, res.x, zi))
    y_aff = from_mont(f, mont_mul(f, res.y, zi))
    ok_y = fe_eq(y_aff, fe_from_array(ry))
    ok_sign = (x_aff[0] & np.uint32(1)) == rsign
    return ok_y & ok_sign & valid


from .lowering import per_mode_jit

ed25519_verify_kernel = per_mode_jit(jax.vmap(_verify_one))


# ---------------------------------------------------------------------------
# Host-side batch preparation.


import functools


@functools.lru_cache(maxsize=4096)
def _neg_pub_limbs(pub: bytes):
    """pub32 -> (limbs of -A.x, limbs of A.y), or None if not a curve
    point.  Decompression (a big-int sqrt) and limb packing both cached:
    the cluster's key set is small and every signature reuses it."""
    a_pt = hc.ed_decompress(pub)
    if a_pt is None:
        return None
    x, y = a_pt[0], a_pt[1]  # decompress returns Z = 1
    return to_limbs((P - x) % P if x else 0), to_limbs(y)


_ZERO64 = b"\x00" * 64
_L_WORDS = limbs.words_of(L)
_P_WORDS = limbs.words_of(P)


def prepare_batch_scalar(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> Tuple[np.ndarray, ...]:
    """Per-item reference prep — the differential ORACLE for the
    vectorized :func:`prepare_batch` (kept verbatim, selectable via
    MINBFT_SCALAR_PREP=1)."""
    import hashlib

    b = bucket
    ax = np.zeros((b, limbs.NLIMBS), np.uint32)
    ay = np.zeros((b, limbs.NLIMBS), np.uint32)
    u1 = np.zeros((b, limbs.NLIMBS), np.uint32)
    u2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    ry = np.zeros((b, limbs.NLIMBS), np.uint32)
    rsign = np.zeros((b,), np.uint32)
    valid = np.zeros((b,), np.bool_)
    for i, (pub, msg, sig) in enumerate(items):
        if len(sig) != 64:
            continue
        a_limbs = _neg_pub_limbs(pub)
        if a_limbs is None:
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            continue
        y_enc = int.from_bytes(sig[:32], "little")
        y_r = y_enc & ((1 << 255) - 1)
        if y_r >= P:
            continue  # non-canonical R encoding (strict semantics)
        k = (
            int.from_bytes(
                hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
            )
            % L
        )
        ax[i], ay[i] = a_limbs  # A' = -A
        u1[i] = to_limbs(s)
        u2[i] = to_limbs(k)
        ry[i] = to_limbs(y_r)
        rsign[i] = y_enc >> 255
        valid[i] = True
    return ax, ay, u1, u2, ry, rsign, valid


def prepare_batch(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> Tuple[np.ndarray, ...]:
    """[(pub32, msg, sig64)] -> device-ready limb arrays, padded to
    ``bucket`` lanes.  Malformed/non-canonical inputs get valid=False.

    Vectorized (round-6, same division of labor as
    :func:`minbft_tpu.ops.p256.prepare_batch`): the only remaining
    per-item host work is one SHA-512 (64-bit ops — pointless to batch on
    host or emulate on device) and the per-public-key decompression
    cache.  Everything else is whole-batch numpy: the signature's s and
    R-encoding halves are '<u2' views of the concatenated sig bytes (the
    16-bit limb layout IS the wire layout), the s < L / y_r < p
    canonicality checks are vectorized word compares, and the only
    inversion-bearing prep (A's decompression sqrt) stays cached per key
    — the sign path's compression already batch-inverts
    (:func:`minbft_tpu.ops.limbs.batch_inv_host`).  Bit-identical to
    :func:`prepare_batch_scalar`.
    """
    if limbs.SCALAR_PREP:
        return prepare_batch_scalar(items, bucket)
    import hashlib

    b = bucket
    n = len(items)
    nl = limbs.NLIMBS
    ax = np.zeros((b, nl), np.uint32)
    ay = np.zeros((b, nl), np.uint32)
    u1 = np.zeros((b, nl), np.uint32)
    u2 = np.zeros((b, nl), np.uint32)
    ry = np.zeros((b, nl), np.uint32)
    rsign = np.zeros((b,), np.uint32)
    valid = np.zeros((b,), np.bool_)
    if n == 0:
        return ax, ay, u1, u2, ry, rsign, valid

    # Pass 1 (per item): structural sig check + cached decompression.
    sigbuf = bytearray()
    a_rows: list = []
    ok = np.zeros((n,), np.bool_)
    for i, (pub, _msg, sig) in enumerate(items):
        a_limbs = _neg_pub_limbs(pub) if len(sig) == 64 else None
        if a_limbs is None:
            sigbuf += _ZERO64
            a_rows.append(None)
            continue
        sigbuf += sig
        a_rows.append(a_limbs)
        ok[i] = True

    raw = bytes(sigbuf)
    srows = np.frombuffer(raw, dtype="<u2").reshape(n, 2, nl)
    swords = np.frombuffer(raw, dtype="<u8").reshape(n, 2, 4)
    ry16 = srows[:, 0].copy()
    rsign_n = (ry16[:, nl - 1] >> 15).astype(np.uint32)
    ry16[:, nl - 1] &= 0x7FFF  # y_r = y_enc & (2^255 - 1)

    # Vectorized canonicality: s < L, y_r < p (strict semantics).
    ok &= limbs.words_lt(swords[:, 1], _L_WORDS)
    ok &= limbs.words_lt(limbs.limb_words(ry16), _P_WORDS)

    # Pass 2 (valid lanes only): one SHA-512 per lane for the challenge k.
    vidx = np.flatnonzero(ok)
    idx = vidx.tolist()
    if idx:
        sha = hashlib.sha512
        k_ints = []
        for i in idx:
            pub, msg, sig = items[i]
            k_ints.append(
                int.from_bytes(sha(sig[:32] + pub + msg).digest(), "little")
                % L
            )
        ax[vidx] = np.stack([a_rows[i][0] for i in idx])
        ay[vidx] = np.stack([a_rows[i][1] for i in idx])
        u1[vidx] = srows[vidx, 1]
        u2[vidx] = limbs.to_limbs_batch(k_ints)
        ry[vidx] = ry16[vidx]
        rsign[vidx] = rsign_n[vidx]
        valid[vidx] = True
    return ax, ay, u1, u2, ry, rsign, valid


# Packed I/O (see ops/p256.py PACKED_COLS note): one u16 upload per
# dispatch instead of seven array RPCs — limb values are 16-bit by
# construction, rsign/valid are 0/1.

PACKED_COLS = 5 * limbs.NLIMBS + 2  # ax ay u1 u2 ry | rsign valid


def pack_arrays(arrays) -> np.ndarray:
    ax, ay, u1, u2, ry, rsign, valid = arrays
    return np.concatenate(
        [
            ax, ay, u1, u2, ry,
            rsign[:, None].astype(np.uint32),
            valid[:, None].astype(np.uint32),
        ],
        axis=1,
    ).astype(np.uint16)


def prepare_packed(
    items: Sequence[Tuple[bytes, bytes, bytes]],
    bucket: int,
    out: "np.ndarray | None" = None,
) -> np.ndarray:
    """prepare_batch + pack_arrays fused into one [bucket, PACKED_COLS]
    u16 staging write (see :func:`minbft_tpu.ops.p256.prepare_packed`);
    ``out`` is an engine-owned recycled staging buffer."""
    n = len(items)
    out = limbs.staging_out(out, bucket, PACKED_COLS, n)
    ax, ay, u1, u2, ry, rsign, valid = prepare_batch(items, bucket)
    L_ = limbs.NLIMBS
    out[:, 0:L_] = ax
    out[:, L_ : 2 * L_] = ay
    out[:, 2 * L_ : 3 * L_] = u1
    out[:, 3 * L_ : 4 * L_] = u2
    out[:, 4 * L_ : 5 * L_] = ry
    out[:, 5 * L_] = rsign
    out[:, 5 * L_ + 1] = valid
    return out


def _verify_one_packed(row: jnp.ndarray) -> jnp.ndarray:
    r32 = row.astype(jnp.uint32)
    L_ = limbs.NLIMBS
    return _verify_one(
        r32[0:L_],
        r32[L_ : 2 * L_],
        r32[2 * L_ : 3 * L_],
        r32[3 * L_ : 4 * L_],
        r32[4 * L_ : 5 * L_],
        r32[5 * L_],
        r32[5 * L_ + 1] != 0,
    )


ed25519_verify_kernel_packed = per_mode_jit(jax.vmap(_verify_one_packed))


def verify_batch_padded(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> np.ndarray:
    """Engine dispatch hook: prepare on host, verify on device -> [bucket]
    bool (lanes past len(items) are padding).  Packed single-upload path."""
    packed = prepare_packed(items, bucket)
    return np.asarray(ed25519_verify_kernel_packed(jnp.asarray(packed)))


def verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]]) -> np.ndarray:
    return verify_batch_padded(items, len(items))[: len(items)]


# ---------------------------------------------------------------------------
# Batched signing: the expensive half of RFC 8032 signing is the fixed-base
# scalar multiplication r*B — the same comb that carried ECDSA signing
# (ops/p256.py, see the note there), and simpler here because the Edwards
# addition is COMPLETE: the v = 0 table rows are literally the identity
# point and flow through _add with no flags or exceptional cases at all.
# Host does the SHA-512 scalar derivations and the final compression
# (one Montgomery batch inversion for the whole batch).

_COMB_WINDOWS = 64
_COMB_TABLE_NP: np.ndarray | None = None


def _comb_table_np() -> np.ndarray:
    """[64, 16, 3, NLIMBS] u32: (x, y, t=xy) affine Montgomery rows of
    v * 16^j * B; v = 0 rows are the identity (0, 1, 0)."""
    global _COMB_TABLE_NP
    if _COMB_TABLE_NP is not None:
        return _COMB_TABLE_NP
    tab = np.zeros((_COMB_WINDOWS, 16, 3, limbs.NLIMBS), np.uint32)
    one_m = to_limbs((1 << 256) % P)
    for j in range(_COMB_WINDOWS):
        tab[j, 0, 1] = one_m  # identity: (0 : 1 : 1 : 0)
    base = hc.ED_BASE  # extended affine-ish host tuple (x, y, z=1, t)
    for j in range(_COMB_WINDOWS):
        acc = None
        for v in range(1, 16):
            acc = base if acc is None else hc.ed_add(acc, base)
            x, y, z, _t = acc
            zi = pow(z, -1, P)
            xa, ya = x * zi % P, y * zi % P
            tab[j, v, 0] = to_limbs((xa << 256) % P)
            tab[j, v, 1] = to_limbs((ya << 256) % P)
            tab[j, v, 2] = to_limbs((xa * ya % P << 256) % P)
        base = hc.ed_scalar_mult(16, base)
    _COMB_TABLE_NP = tab
    return tab


def _rb_comb_one(r: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """Scalar-shaped r*B via the fixed-base comb -> [3, NLIMBS] u16
    (X, Y, Z extended coords, Montgomery domain; narrow transfer)."""
    one = mont_one(FIELD)
    shifts = (4 * jnp.arange(4, dtype=jnp.uint32))[None, :]
    nibs = ((r[:, None] >> shifts) & 0xF).reshape(_COMB_WINDOWS)

    def body(j, acc):
        tab_j = lax.dynamic_index_in_dim(table, j, keepdims=False)  # [16,3,L]
        v = lax.dynamic_index_in_dim(nibs, j, keepdims=False)
        mask = (jnp.arange(16, dtype=jnp.uint32) == v)[:, None, None]
        sel = jnp.sum(jnp.where(mask, tab_j, 0), axis=0)  # [3, L]
        q = EdPoint(
            fe_from_array(sel[0]), fe_from_array(sel[1]), one,
            fe_from_array(sel[2]),
        )
        return _add(acc, q)

    res = lax.fori_loop(0, _COMB_WINDOWS, body, _identity())
    out = jnp.stack(
        [
            limbs.fe_to_array(res.x),
            limbs.fe_to_array(res.y),
            limbs.fe_to_array(res.z),
        ]
    )
    return out.astype(jnp.uint16)


_rb_comb_batch = None


def ed25519_rb_kernel(r_arr) -> jnp.ndarray:
    """Batched r*B — [B, 16] limb rows in (uploaded u16), [B, 3, 16] u16
    out.  Table closed over as a jit constant (never a per-call upload)."""
    global _rb_comb_batch
    if _rb_comb_batch is None:
        table = jnp.asarray(_comb_table_np())

        def widen(r16):
            return jax.vmap(_rb_comb_one, in_axes=(0, None))(
                r16.astype(jnp.uint32), table
            )

        from .lowering import per_mode_jit as _pmj

        _rb_comb_batch = _pmj(widen)
    return _rb_comb_batch(jnp.asarray(np.asarray(r_arr).astype(np.uint16)))


_batch_inv = limbs.batch_inv_host

# Staging layout for the sign path (see ops/p256.py SIGN_COLS): one [16]
# u16 nonce-limb row per lane, recyclable through the engine's pool.
SIGN_COLS = limbs.NLIMBS


def sign_prepare(
    items: Sequence[Tuple[bytes, bytes]],
    bucket: int,
    out: "np.ndarray | None" = None,
) -> Tuple[np.ndarray, tuple]:
    """Host half 1 of batched Ed25519 signing: the RFC 8032 SHA-512
    scalar derivations, with the whole batch's nonce limbs packed into
    ``out`` (engine staging buffer when given) via one bulk conversion.
    Pad lanes get r = 1 (valid, discarded).  Returns ``(staging, meta)``
    for :func:`sign_finish`."""
    import hashlib

    n = len(items)
    out = limbs.staging_out(out, bucket, SIGN_COLS, n)
    # Per-seed derivation cache: the production shape is ONE signer, many
    # messages — the SHA-512 seed expansion, clamp, and public key are
    # computed once per distinct seed, not per item.
    per_seed: dict = {}
    rs = []
    lanes = []
    for seed, msg in items:
        entry = per_seed.get(seed)
        if entry is None:
            h = hashlib.sha512(seed).digest()
            a = int.from_bytes(h[:32], "little")
            a = (a & ((1 << 254) - 8)) | (1 << 254)
            entry = (a, h[32:], hc.ed25519_keygen(seed)[1])
            per_seed[seed] = entry
        a, prefix, pub = entry
        r = (
            int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little")
            % L
        )
        rs.append(r)
        lanes.append((a, pub, msg))
    if n:
        out[:n] = limbs.to_limbs_batch(rs)
    out[n:] = 0
    out[n:, 0] = 1  # r = 1: a valid lane, result discarded
    return out, (rs, lanes)


def sign_finish(meta: tuple, xyz) -> list:
    """Host half 2: batch-invert the device Zs (ONE Montgomery sweep),
    compress R, and finish s = r + k*a per lane (RFC 8032)."""
    import hashlib

    rs, lanes = meta
    b = len(lanes)
    xyz = np.concatenate([np.asarray(o) for o in xyz]) if isinstance(
        xyz, (list, tuple)
    ) else np.asarray(xyz)
    xyz = xyz[:b]  # [B,3,16] u16

    # No Montgomery undo needed: the R factor cancels in the X/Z and Y/Z
    # ratios ((X*R) * (Z*R)^-1 == X/Z), so the raw device limbs feed the
    # batch inversion directly.
    ints = [
        [int.from_bytes(row.astype("<u2").tobytes(), "little") for row in lane]
        for lane in xyz
    ]
    z_invs = _batch_inv([lane[2] for lane in ints], P)
    out = []
    for i, (a, pub, msg) in enumerate(lanes):
        x, y, _z = ints[i]
        zi = z_invs[i]
        xa, ya = x * zi % P, y * zi % P
        rp = (ya | ((xa & 1) << 255)).to_bytes(32, "little")
        k = (
            int.from_bytes(hashlib.sha512(rp + pub + msg).digest(), "little")
            % L
        )
        s = (rs[i] + k * a) % L
        out.append(rp + s.to_bytes(32, "little"))
    return out


def sign_batch(
    items: Sequence[Tuple[bytes, bytes]],
    bucket: int = 0,
    chunk: int = 4096,
    rb_kernel=None,
) -> list:
    """[(seed32, msg)] -> [signature64] — RFC 8032 deterministic,
    byte-identical to :func:`minbft_tpu.utils.hostcrypto.ed25519_sign`.
    Device computes r*B (the comb); host derives the scalars (SHA-512),
    batch-inverts the Zs for compression, and finishes s = r + k*a —
    :func:`sign_prepare` → r*B kernel → :func:`sign_finish`, the same
    three stages the engine's sign queue drives with recycled staging.

    Shape discipline matches :func:`minbft_tpu.ops.p256.sign_batch`:
    ``bucket`` pads to a fixed size, and anything larger is padded up to a
    multiple of ``chunk`` (pad lanes compute 1*B and are discarded) so
    varying batch sizes share compiled kernels — a fresh shape costs a
    ~15s compile — while chunked launches pipeline the transfers."""
    b = len(items)
    if b == 0 and bucket == 0:
        return []
    total = max(bucket, b)
    if total > chunk:
        total = -(-total // chunk) * chunk
    r_arr, meta = sign_prepare(items, total)
    kernel = rb_kernel if rb_kernel is not None else ed25519_rb_kernel
    step = chunk if total > chunk else total
    outs = [kernel(r_arr[c0 : c0 + step]) for c0 in range(0, total, step)]
    return sign_finish(meta, outs)
