"""Batched Ed25519 verification as a JAX/XLA TPU kernel.

The Ed25519 authenticator path (BASELINE config[5]: n=31, batch=1024).
Same architecture as :mod:`minbft_tpu.ops.p256` — host does the cheap
irregular work, the device does the double-scalar multiplication over the
shared limb machinery (:mod:`minbft_tpu.ops.limbs`) — but the curve shape
is friendlier: twisted Edwards (a = -1) extended coordinates have
**complete** addition formulas (a is a square mod 2^255-19, d is not), so
the ladder needs *zero* exceptional-case handling: the identity is a
perfectly ordinary table entry and add(P, P) just works.

Strict cofactorless verification (OpenSSL's semantics, matching
:func:`minbft_tpu.utils.hostcrypto.ed25519_verify` — see the semantics
note there): accept iff ``compress(S*B - k*A) == R-bytes``.  Host computes
k = SHA-512(R||A||M) mod L (SHA-512 needs 64-bit ops — pointless to
emulate on device for 96-byte inputs) and decompresses A (one sqrt,
*cached per public key* — the key set is small and stable), and ships
``u1 = S``, ``u2 = k``, ``A' = -A``, and R's encoded y + sign bit.
Device computes ``P = u1*B + u2*A'`` (256 doublings + 256 *unconditional*
complete additions), normalizes it with one Fermat inversion, and accepts
iff ``(y(P), sign(x(P)))`` equals R's encoding.  R is never decompressed:
the per-signature host big-int sqrt that this replaces was the n=31
benchmark's dominant cost (~64 host pows per committed request).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs
from .limbs import (
    Fe,
    FieldSpec,
    add_mod,
    fe_const,
    fe_eq,
    fe_from_array,
    fe_select,
    from_mont,
    mont_inv,
    mont_mul,
    mont_one,
    mont_sqr,
    sub_mod,
    to_limbs,
    to_mont,
)
from ..utils import hostcrypto as hc

P = hc.ED_P  # 2^255 - 19
L = hc.ED_L
D = hc.ED_D

FIELD = FieldSpec.make(P)

_BX_M = fe_const((hc.ED_BX << 256) % P)
_BY_M = fe_const((hc.ED_BY << 256) % P)
_BT_M = fe_const(((hc.ED_BX * hc.ED_BY % P) << 256) % P)
_D2_M = fe_const(((2 * D % P) << 256) % P)


class EdPoint(NamedTuple):
    """Extended twisted-Edwards point (X : Y : Z : T), Montgomery limbs."""

    x: Fe
    y: Fe
    z: Fe
    t: Fe


def _identity() -> EdPoint:
    one = mont_one(FIELD)
    zero = limbs.fe_zero()
    return EdPoint(zero, one, one, zero)


def _add(p: EdPoint, q: EdPoint) -> EdPoint:
    """Complete unified addition, a = -1 (add-2008-hwcd-3 with k = 2d).
    Handles identity and doubling inputs exactly — no special cases."""
    f = FIELD
    a = mont_mul(f, sub_mod(f, p.y, p.x), sub_mod(f, q.y, q.x))
    b = mont_mul(f, add_mod(f, p.y, p.x), add_mod(f, q.y, q.x))
    c = mont_mul(f, mont_mul(f, p.t, _D2_M), q.t)
    zz = mont_mul(f, p.z, q.z)
    d = add_mod(f, zz, zz)
    e = sub_mod(f, b, a)
    ff = sub_mod(f, d, c)
    g = add_mod(f, d, c)
    h = add_mod(f, b, a)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _dbl(p: EdPoint) -> EdPoint:
    """Dedicated doubling (dbl-2008-hwcd, a = -1): 4M + 4S."""
    f = FIELD
    a = mont_sqr(f, p.x)
    b = mont_sqr(f, p.y)
    zz = mont_sqr(f, p.z)
    c = add_mod(f, zz, zz)
    # a_curve = -1: D = -A
    e = sub_mod(f, sub_mod(f, mont_sqr(f, add_mod(f, p.x, p.y)), a), b)
    g = sub_mod(f, b, a)  # D + B
    ff = sub_mod(f, g, c)
    h = sub_mod(f, limbs.fe_zero(), add_mod(f, a, b))  # D - B = -(A+B)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _bits_of(scalar_arr: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.arange(limbs.LIMB_BITS, dtype=jnp.uint32)
    return ((scalar_arr[:, None] >> shifts[None, :]) & 1).reshape(256)


def _ladder(u1_arr: jnp.ndarray, u2_arr: jnp.ndarray, aq: EdPoint) -> EdPoint:
    """P = u1*B + u2*A' — interleaved ladder with *unconditional* complete
    additions: table index 0 is the identity, so every iteration is
    double-then-add with a 4-way table select and no branches at all."""
    one = mont_one(FIELD)
    zero = limbs.fe_zero()
    bpt = EdPoint(_BX_M, _BY_M, one, _BT_M)
    ba = _add(bpt, aq)  # B + A'

    tab = [_identity(), aq, bpt, ba]  # index = 2*bit(u1) + bit(u2)
    bits1 = _bits_of(u1_arr)
    bits2 = _bits_of(u2_arr)

    def sel(d, coord):
        is1, is2 = d == 1, d == 2
        return tuple(
            jnp.where(
                is1, t1, jnp.where(is2, t2, jnp.where(d == 3, t3, t0))
            )
            for t0, t1, t2, t3 in zip(*(getattr(t, coord) for t in tab))
        )

    def body(i, acc):
        j = 255 - i
        acc = _dbl(acc)
        b1 = lax.dynamic_index_in_dim(bits1, j, keepdims=False)
        b2 = lax.dynamic_index_in_dim(bits2, j, keepdims=False)
        d = b1 * 2 + b2
        addend = EdPoint(sel(d, "x"), sel(d, "y"), sel(d, "z"), sel(d, "t"))
        return _add(acc, addend)

    return lax.fori_loop(0, 256, body, _identity())


def _verify_one(
    ax: jnp.ndarray,
    ay: jnp.ndarray,
    u1: jnp.ndarray,
    u2: jnp.ndarray,
    ry: jnp.ndarray,
    rsign: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scalar-shaped Ed25519 verify core; limb-array args [16] u32.

    Accepts iff compress(u1*B + u2*A') matches (ry, rsign) — the affine
    normalization (one Fermat inversion) runs on device; Z is never 0
    under complete formulas on curve points."""
    f = FIELD
    ax_m = to_mont(f, fe_from_array(ax))
    ay_m = to_mont(f, fe_from_array(ay))
    at_m = mont_mul(f, ax_m, ay_m)
    aq = EdPoint(ax_m, ay_m, mont_one(f), at_m)
    res = _ladder(u1, u2, aq)
    zi = mont_inv(f, res.z)
    x_aff = from_mont(f, mont_mul(f, res.x, zi))
    y_aff = from_mont(f, mont_mul(f, res.y, zi))
    ok_y = fe_eq(y_aff, fe_from_array(ry))
    ok_sign = (x_aff[0] & np.uint32(1)) == rsign
    return ok_y & ok_sign & valid


from .lowering import per_mode_jit

ed25519_verify_kernel = per_mode_jit(jax.vmap(_verify_one))


# ---------------------------------------------------------------------------
# Host-side batch preparation.


import functools


@functools.lru_cache(maxsize=4096)
def _neg_pub_limbs(pub: bytes):
    """pub32 -> (limbs of -A.x, limbs of A.y), or None if not a curve
    point.  Decompression (a big-int sqrt) and limb packing both cached:
    the cluster's key set is small and every signature reuses it."""
    a_pt = hc.ed_decompress(pub)
    if a_pt is None:
        return None
    x, y = a_pt[0], a_pt[1]  # decompress returns Z = 1
    return to_limbs((P - x) % P if x else 0), to_limbs(y)


def prepare_batch(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> Tuple[np.ndarray, ...]:
    """[(pub32, msg, sig64)] -> device-ready limb arrays, padded to
    ``bucket`` lanes.  Malformed/non-canonical inputs get valid=False.

    Per-item host work is one SHA-512 and limb packing; the only big-int
    sqrt (A's decompression) is cached per public key, and R is shipped
    in its encoded form (see module docstring)."""
    import hashlib

    b = bucket
    ax = np.zeros((b, limbs.NLIMBS), np.uint32)
    ay = np.zeros((b, limbs.NLIMBS), np.uint32)
    u1 = np.zeros((b, limbs.NLIMBS), np.uint32)
    u2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    ry = np.zeros((b, limbs.NLIMBS), np.uint32)
    rsign = np.zeros((b,), np.uint32)
    valid = np.zeros((b,), np.bool_)
    for i, (pub, msg, sig) in enumerate(items):
        if len(sig) != 64:
            continue
        a_limbs = _neg_pub_limbs(pub)
        if a_limbs is None:
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            continue
        y_enc = int.from_bytes(sig[:32], "little")
        y_r = y_enc & ((1 << 255) - 1)
        if y_r >= P:
            continue  # non-canonical R encoding (strict semantics)
        k = (
            int.from_bytes(
                hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
            )
            % L
        )
        ax[i], ay[i] = a_limbs  # A' = -A
        u1[i] = to_limbs(s)
        u2[i] = to_limbs(k)
        ry[i] = to_limbs(y_r)
        rsign[i] = y_enc >> 255
        valid[i] = True
    return ax, ay, u1, u2, ry, rsign, valid


# Packed I/O (see ops/p256.py PACKED_COLS note): one u16 upload per
# dispatch instead of seven array RPCs — limb values are 16-bit by
# construction, rsign/valid are 0/1.

PACKED_COLS = 5 * limbs.NLIMBS + 2  # ax ay u1 u2 ry | rsign valid


def pack_arrays(arrays) -> np.ndarray:
    ax, ay, u1, u2, ry, rsign, valid = arrays
    return np.concatenate(
        [
            ax, ay, u1, u2, ry,
            rsign[:, None].astype(np.uint32),
            valid[:, None].astype(np.uint32),
        ],
        axis=1,
    ).astype(np.uint16)


def _verify_one_packed(row: jnp.ndarray) -> jnp.ndarray:
    r32 = row.astype(jnp.uint32)
    L_ = limbs.NLIMBS
    return _verify_one(
        r32[0:L_],
        r32[L_ : 2 * L_],
        r32[2 * L_ : 3 * L_],
        r32[3 * L_ : 4 * L_],
        r32[4 * L_ : 5 * L_],
        r32[5 * L_],
        r32[5 * L_ + 1] != 0,
    )


ed25519_verify_kernel_packed = per_mode_jit(jax.vmap(_verify_one_packed))


def verify_batch_padded(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> np.ndarray:
    """Engine dispatch hook: prepare on host, verify on device -> [bucket]
    bool (lanes past len(items) are padding).  Packed single-upload path."""
    packed = pack_arrays(prepare_batch(items, bucket))
    return np.asarray(ed25519_verify_kernel_packed(jnp.asarray(packed)))


def verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]]) -> np.ndarray:
    return verify_batch_padded(items, len(items))[: len(items)]
