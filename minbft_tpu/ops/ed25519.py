"""Batched Ed25519 verification as a JAX/XLA TPU kernel.

The Ed25519 authenticator path (BASELINE config[5]: n=31, batch=1024).
Same architecture as :mod:`minbft_tpu.ops.p256` — host does the cheap
irregular work, the device does the double-scalar multiplication over the
shared limb machinery (:mod:`minbft_tpu.ops.limbs`) — but the curve shape
is friendlier: twisted Edwards (a = -1) extended coordinates have
**complete** addition formulas (a is a square mod 2^255-19, d is not), so
the ladder needs *zero* exceptional-case handling: the identity is a
perfectly ordinary table entry and add(P, P) just works.

Cofactored verification (RFC 8032's recommended interpretation, matching
:func:`minbft_tpu.utils.hostcrypto.ed25519_verify`): accept iff
``8*S*B == 8*R + 8*k*A``.  Host computes k = SHA-512(R||A||M) mod L (SHA-512
needs 64-bit ops — pointless to emulate on device for 96-byte inputs),
decompresses A and R (one sqrt each, host big ints), negates A, and ships
``u1 = 8S mod L``, ``u2 = 8k mod L``, ``A' = -A``, and ``R8 = 8R`` (affine).
Device computes ``P = u1*B + u2*A'`` (256 doublings + 256 *unconditional*
complete additions) and accepts iff ``P == R8`` projectively.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs
from .limbs import (
    Fe,
    FieldSpec,
    add_mod,
    fe_const,
    fe_eq,
    fe_from_array,
    fe_select,
    mont_mul,
    mont_one,
    mont_sqr,
    sub_mod,
    to_limbs,
    to_mont,
)
from ..utils import hostcrypto as hc

P = hc.ED_P  # 2^255 - 19
L = hc.ED_L
D = hc.ED_D

FIELD = FieldSpec.make(P)

_BX_M = fe_const((hc.ED_BX << 256) % P)
_BY_M = fe_const((hc.ED_BY << 256) % P)
_BT_M = fe_const(((hc.ED_BX * hc.ED_BY % P) << 256) % P)
_D2_M = fe_const(((2 * D % P) << 256) % P)


class EdPoint(NamedTuple):
    """Extended twisted-Edwards point (X : Y : Z : T), Montgomery limbs."""

    x: Fe
    y: Fe
    z: Fe
    t: Fe


def _identity() -> EdPoint:
    one = mont_one(FIELD)
    zero = limbs.fe_zero()
    return EdPoint(zero, one, one, zero)


def _add(p: EdPoint, q: EdPoint) -> EdPoint:
    """Complete unified addition, a = -1 (add-2008-hwcd-3 with k = 2d).
    Handles identity and doubling inputs exactly — no special cases."""
    f = FIELD
    a = mont_mul(f, sub_mod(f, p.y, p.x), sub_mod(f, q.y, q.x))
    b = mont_mul(f, add_mod(f, p.y, p.x), add_mod(f, q.y, q.x))
    c = mont_mul(f, mont_mul(f, p.t, _D2_M), q.t)
    zz = mont_mul(f, p.z, q.z)
    d = add_mod(f, zz, zz)
    e = sub_mod(f, b, a)
    ff = sub_mod(f, d, c)
    g = add_mod(f, d, c)
    h = add_mod(f, b, a)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _dbl(p: EdPoint) -> EdPoint:
    """Dedicated doubling (dbl-2008-hwcd, a = -1): 4M + 4S."""
    f = FIELD
    a = mont_sqr(f, p.x)
    b = mont_sqr(f, p.y)
    zz = mont_sqr(f, p.z)
    c = add_mod(f, zz, zz)
    # a_curve = -1: D = -A
    e = sub_mod(f, sub_mod(f, mont_sqr(f, add_mod(f, p.x, p.y)), a), b)
    g = sub_mod(f, b, a)  # D + B
    ff = sub_mod(f, g, c)
    h = sub_mod(f, limbs.fe_zero(), add_mod(f, a, b))  # D - B = -(A+B)
    return EdPoint(
        mont_mul(f, e, ff),
        mont_mul(f, g, h),
        mont_mul(f, ff, g),
        mont_mul(f, e, h),
    )


def _bits_of(scalar_arr: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.arange(limbs.LIMB_BITS, dtype=jnp.uint32)
    return ((scalar_arr[:, None] >> shifts[None, :]) & 1).reshape(256)


def _ladder(u1_arr: jnp.ndarray, u2_arr: jnp.ndarray, aq: EdPoint) -> EdPoint:
    """P = u1*B + u2*A' — interleaved ladder with *unconditional* complete
    additions: table index 0 is the identity, so every iteration is
    double-then-add with a 4-way table select and no branches at all."""
    one = mont_one(FIELD)
    zero = limbs.fe_zero()
    bpt = EdPoint(_BX_M, _BY_M, one, _BT_M)
    ba = _add(bpt, aq)  # B + A'

    tab = [_identity(), aq, bpt, ba]  # index = 2*bit(u1) + bit(u2)
    bits1 = _bits_of(u1_arr)
    bits2 = _bits_of(u2_arr)

    def sel(d, coord):
        is1, is2 = d == 1, d == 2
        return tuple(
            jnp.where(
                is1, t1, jnp.where(is2, t2, jnp.where(d == 3, t3, t0))
            )
            for t0, t1, t2, t3 in zip(*(getattr(t, coord) for t in tab))
        )

    def body(i, acc):
        j = 255 - i
        acc = _dbl(acc)
        b1 = lax.dynamic_index_in_dim(bits1, j, keepdims=False)
        b2 = lax.dynamic_index_in_dim(bits2, j, keepdims=False)
        d = b1 * 2 + b2
        addend = EdPoint(sel(d, "x"), sel(d, "y"), sel(d, "z"), sel(d, "t"))
        return _add(acc, addend)

    return lax.fori_loop(0, 256, body, _identity())


def _verify_one(
    ax: jnp.ndarray,
    ay: jnp.ndarray,
    u1: jnp.ndarray,
    u2: jnp.ndarray,
    r8x: jnp.ndarray,
    r8y: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scalar-shaped Ed25519 verify core; limb-array args [16] u32.

    Accepts iff u1*B + u2*A' == R8 (projective compare: X == x*Z and
    Y == y*Z; Z is never 0 under complete formulas on curve points)."""
    f = FIELD
    ax_m = to_mont(f, fe_from_array(ax))
    ay_m = to_mont(f, fe_from_array(ay))
    at_m = mont_mul(f, ax_m, ay_m)
    aq = EdPoint(ax_m, ay_m, mont_one(f), at_m)
    res = _ladder(u1, u2, aq)
    x8 = to_mont(f, fe_from_array(r8x))
    y8 = to_mont(f, fe_from_array(r8y))
    ok_x = fe_eq(res.x, mont_mul(f, x8, res.z))
    ok_y = fe_eq(res.y, mont_mul(f, y8, res.z))
    return ok_x & ok_y & valid


from .lowering import per_mode_jit

ed25519_verify_kernel = per_mode_jit(jax.vmap(_verify_one))


# ---------------------------------------------------------------------------
# Host-side batch preparation.


def _to_affine_host(p) -> Tuple[int, int]:
    x, y, z, _ = p
    zi = pow(z, -1, P)
    return x * zi % P, y * zi % P


def prepare_batch(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> Tuple[np.ndarray, ...]:
    """[(pub32, msg, sig64)] -> device-ready limb arrays, padded to
    ``bucket`` lanes.  Malformed/non-canonical inputs get valid=False."""
    import hashlib

    b = bucket
    ax = np.zeros((b, limbs.NLIMBS), np.uint32)
    ay = np.zeros((b, limbs.NLIMBS), np.uint32)
    u1 = np.zeros((b, limbs.NLIMBS), np.uint32)
    u2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    r8x = np.zeros((b, limbs.NLIMBS), np.uint32)
    r8y = np.zeros((b, limbs.NLIMBS), np.uint32)
    valid = np.zeros((b,), np.bool_)
    for i, (pub, msg, sig) in enumerate(items):
        if len(sig) != 64:
            continue
        a_pt = hc.ed_decompress(pub)
        r_pt = hc.ed_decompress(sig[:32])
        if a_pt is None or r_pt is None:
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            continue
        k = (
            int.from_bytes(
                hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
            )
            % L
        )
        a_aff = _to_affine_host(a_pt)
        r8 = _to_affine_host(hc.ed_scalar_mult(8, r_pt))
        ax[i] = to_limbs((P - a_aff[0]) % P)  # A' = -A
        ay[i] = to_limbs(a_aff[1])
        u1[i] = to_limbs(8 * s % L)
        u2[i] = to_limbs(8 * k % L)
        r8x[i] = to_limbs(r8[0])
        r8y[i] = to_limbs(r8[1])
        valid[i] = True
    return ax, ay, u1, u2, r8x, r8y, valid


def verify_batch_padded(
    items: Sequence[Tuple[bytes, bytes, bytes]], bucket: int
) -> np.ndarray:
    """Engine dispatch hook: prepare on host, verify on device -> [bucket]
    bool (lanes past len(items) are padding)."""
    arrays = prepare_batch(items, bucket)
    return np.asarray(ed25519_verify_kernel(*[jnp.asarray(a) for a in arrays]))


def verify_batch(items: Sequence[Tuple[bytes, bytes, bytes]]) -> np.ndarray:
    return verify_batch_padded(items, len(items))[: len(items)]
