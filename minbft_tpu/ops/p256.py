"""Batched ECDSA-P256 verification as a JAX/XLA TPU kernel.

This is the north-star hot path: the reference verifies every PREPARE/COMMIT
UI certificate and client signature serially on CPU (Go crypto/ecdsa at
sample/authentication/crypto.go:79-89; enclave-side create at
usig/sgx/enclave/usig.c:36-76, verification in pure Go at
usig/sgx/sgx-usig.go:81-97).  Here a whole batch of verifications runs as one
data-parallel XLA program: ``jax.vmap`` over a scalar-shaped verifier whose
field arithmetic is the limb machinery of :mod:`minbft_tpu.ops.limbs`.

Division of labor (TPU-first):

- **Host** hashes variable-length bytes to the fixed 32-byte digest ``z``
  (:func:`minbft_tpu.messages.authen_digest`) and computes the two scalars
  ``u1 = z*s^-1 mod n`` and ``u2 = r*s^-1 mod n`` with native big-int ops —
  cheap, and it keeps mod-n arithmetic off the device entirely.
- **Device** does everything expensive: the 256-bit double-scalar
  multiplication ``u1*G + u2*Q`` (interleaved Shamir ladder, Jacobian
  coordinates, a = -3 doubling), one Fermat inversion to build the G+Q table
  entry, final affine conversion, and the ``x(R) ≡ r (mod n)`` check — all
  constant-shape, batched, jit-compiled once per batch bucket.

Exceptional cases (identity operands, P == ±Q mid-ladder) are handled with
constant-shape selects, never data-dependent branches, so adversarial
signatures cannot force a recompile or a trace divergence.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs
from .limbs import (
    FieldSpec,
    add_mod,
    from_limbs,
    limbs_eq,
    mont_inv,
    mont_mul,
    mont_one,
    mont_sqr,
    sub_mod,
    to_limbs,
    to_mont,
)

# ---------------------------------------------------------------------------
# Curve constants (NIST P-256 / secp256r1, FIPS 186-4 D.1.2.3).

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

FIELD = FieldSpec.make(P)
ORDER = FieldSpec.make(N)


def _const_mont(x: int) -> np.ndarray:
    """Host-side constant -> Montgomery-domain limbs (numpy, trace-time)."""
    return to_limbs((x << 256) % P)


_GX_M = _const_mont(GX)
_GY_M = _const_mont(GY)
_B_M = _const_mont(B)

Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # Jacobian (X, Y, Z), Montgomery


def _dbl(p: Point) -> Point:
    """Jacobian doubling, a = -3 (dbl-2001-b).  Maps identity to identity."""
    x, y, z = p
    f = FIELD
    delta = mont_sqr(f, z)
    gamma = mont_sqr(f, y)
    beta = mont_mul(f, x, gamma)
    t0 = sub_mod(f, x, delta)
    t1 = add_mod(f, x, delta)
    alpha = mont_mul(f, add_mod(f, add_mod(f, t0, t0), t0), t1)  # 3(x-d)(x+d)
    beta4 = add_mod(f, add_mod(f, beta, beta), add_mod(f, beta, beta))
    beta8 = add_mod(f, beta4, beta4)
    x3 = sub_mod(f, mont_sqr(f, alpha), beta8)
    yz = add_mod(f, y, z)
    z3 = sub_mod(f, sub_mod(f, mont_sqr(f, yz), gamma), delta)
    g2 = mont_sqr(f, gamma)
    g8 = add_mod(f, add_mod(f, g2, g2), add_mod(f, g2, g2))
    g8 = add_mod(f, g8, g8)
    y3 = sub_mod(f, mont_mul(f, alpha, sub_mod(f, beta4, x3)), g8)
    return x3, y3, z3


def _madd(p: Point, qx: jnp.ndarray, qy: jnp.ndarray, q_inf: jnp.ndarray) -> Point:
    """Mixed Jacobian + affine addition with full exceptional-case handling.

    q_inf: bool — the affine operand is the identity (then result = p).
    If p is the identity -> (qx, qy, 1).  If p == q -> doubling.  If
    p == -q -> identity (falls out of the formula with H = 0, r != 0).
    All cases resolved via constant-shape selects.
    """
    x1, y1, z1 = p
    f = FIELD
    z1z1 = mont_sqr(f, z1)
    u2 = mont_mul(f, qx, z1z1)
    s2 = mont_mul(f, qy, mont_mul(f, z1, z1z1))
    h = sub_mod(f, u2, x1)
    r = sub_mod(f, s2, y1)
    hh = mont_sqr(f, h)
    hhh = mont_mul(f, h, hh)
    v = mont_mul(f, x1, hh)
    x3 = sub_mod(f, sub_mod(f, mont_sqr(f, r), hhh), add_mod(f, v, v))
    y3 = sub_mod(f, mont_mul(f, r, sub_mod(f, v, x3)), mont_mul(f, y1, hhh))
    z3 = mont_mul(f, z1, h)

    p_inf = limbs.is_zero(z1)
    same_x = limbs.is_zero(h)
    same_y = limbs.is_zero(r)
    dblx, dbly, dblz = _dbl(p)

    one = mont_one(f)

    def sel(c, a, b):
        return jnp.where(c, a, b)

    # doubling case (p == q)
    use_dbl = jnp.logical_and(same_x, same_y) & ~p_inf & ~q_inf
    x3 = sel(use_dbl, dblx, x3)
    y3 = sel(use_dbl, dbly, y3)
    z3 = sel(use_dbl, dblz, z3)
    # p is identity -> q
    x3 = sel(p_inf, qx, x3)
    y3 = sel(p_inf, qy, y3)
    z3 = sel(p_inf, sel(q_inf, jnp.zeros_like(one), one), z3)
    # q is identity -> p
    x3 = sel(q_inf & ~p_inf, x1, x3)
    y3 = sel(q_inf & ~p_inf, y1, y3)
    z3 = sel(q_inf & ~p_inf, z1, z3)
    return x3, y3, z3


def _to_affine(p: Point) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Jacobian Montgomery -> affine *normal-domain* (x, y), plus inf flag."""
    x, y, z = p
    f = FIELD
    inf = limbs.is_zero(z)
    zsafe = jnp.where(inf, mont_one(f), z)
    zi = mont_inv(f, zsafe)
    zi2 = mont_sqr(f, zi)
    ax = mont_mul(f, x, zi2)
    ay = mont_mul(f, y, mont_mul(f, zi, zi2))
    return limbs.from_mont(f, ax), limbs.from_mont(f, ay), inf


def _bit_at(scalar: jnp.ndarray, j) -> jnp.ndarray:
    """Bit j (0 = LSB) of a [16]-limb scalar, traced index."""
    word = lax.dynamic_index_in_dim(scalar, j >> 4, keepdims=False)
    return (word >> (j & 15).astype(jnp.uint32)) & jnp.uint32(1)


def _shamir(u1: jnp.ndarray, u2: jnp.ndarray, qx_m: jnp.ndarray, qy_m: jnp.ndarray) -> Point:
    """Interleaved double-scalar multiplication u1*G + u2*Q.

    256 iterations of double-then-select-add against the 3-entry affine
    table {G, Q, G+Q}; the G+Q entry is built on device with one Fermat
    inversion.  Everything is one ``fori_loop``: the compiled program is a
    handful of loop nodes regardless of batch size.
    """
    f = FIELD
    one = mont_one(f)
    gx = jnp.asarray(_GX_M)
    gy = jnp.asarray(_GY_M)

    # Table entry G+Q (affine). Exceptional Q == ±G handled by _madd/_to_affine.
    gq = _madd((gx, gy, one), qx_m, qy_m, jnp.bool_(False))
    gq_xm, gq_ym, gq_z = gq
    gq_inf = limbs.is_zero(gq_z)
    zsafe = jnp.where(gq_inf, one, gq_z)
    zi = mont_inv(f, zsafe)
    zi2 = mont_sqr(f, zi)
    gqx = mont_mul(f, gq_xm, zi2)
    gqy = mont_mul(f, gq_ym, mont_mul(f, zi, zi2))

    # Affine table stacked on a leading index axis, indexed by
    # d = 2*bit(u1) + bit(u2): [none, Q, G, G+Q].
    zeros = jnp.zeros_like(one)
    tab_x = jnp.stack([zeros, qx_m, gx, gqx])
    tab_y = jnp.stack([zeros, qy_m, gy, gqy])
    tab_inf = jnp.stack(
        [jnp.bool_(True), jnp.bool_(False), jnp.bool_(False), gq_inf]
    )

    def body(i, acc):
        j = (255 - i).astype(jnp.int32)
        acc = _dbl(acc)
        d = (_bit_at(u1, j) * 2 + _bit_at(u2, j)).astype(jnp.int32)
        ax = lax.dynamic_index_in_dim(tab_x, d, keepdims=False)
        ay = lax.dynamic_index_in_dim(tab_y, d, keepdims=False)
        ainf = lax.dynamic_index_in_dim(tab_inf, d, keepdims=False)
        return _madd(acc, ax, ay, ainf)

    start: Point = (one, one, jnp.zeros_like(one))  # identity
    return lax.fori_loop(0, 256, body, start)


def _verify_one(
    qx: jnp.ndarray,
    qy: jnp.ndarray,
    u1: jnp.ndarray,
    u2: jnp.ndarray,
    r: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scalar-shaped ECDSA verify core; all limb args [16] u32, normal domain.

    ``valid`` carries host-side range checks (r, s in [1, n-1]); the kernel
    AND-folds it so invalid inputs burn the same cycles as valid ones
    (constant shape) but always return False.
    """
    f = FIELD
    qx_m = to_mont(f, qx)
    qy_m = to_mont(f, qy)
    rx, _, inf = _to_affine(_shamir(u1, u2, qx_m, qy_m))
    # x(R) mod n == r, given x(R) < p < 2n: true iff rx == r or rx - n == r.
    n_limbs = jnp.asarray(ORDER.modulus)
    rx_red = jnp.where(
        limbs._geq(rx, n_limbs), limbs._sub_limbs(rx, n_limbs), rx
    )
    ok = limbs_eq(rx_red, r) | limbs_eq(rx, r)
    return ok & ~inf & valid


_verify_batch = jax.jit(jax.vmap(_verify_one))


@functools.lru_cache(maxsize=None)
def _jitted_for_bucket(_: int):
    # One cached jitted callable per bucket size (jit caches by shape anyway;
    # the lru_cache just makes the bucketing explicit and introspectable).
    return _verify_batch


# ---------------------------------------------------------------------------
# Host-side batch preparation.


def prepare_batch(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
) -> Tuple[np.ndarray, ...]:
    """[(pubkey (x, y), digest32, (r, s))] -> device-ready limb arrays.

    Host computes w = s^-1 mod n, u1 = z*w, u2 = r*w (mod n) with Python
    big ints; out-of-range signatures get valid=False and dummy scalars so
    the batch shape never changes.
    """
    b = len(items)
    qx = np.zeros((b, limbs.NLIMBS), np.uint32)
    qy = np.zeros((b, limbs.NLIMBS), np.uint32)
    u1 = np.zeros((b, limbs.NLIMBS), np.uint32)
    u2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    rr = np.zeros((b, limbs.NLIMBS), np.uint32)
    valid = np.zeros((b,), np.bool_)
    for i, ((x, y), digest, (r, s)) in enumerate(items):
        if not (0 < r < N and 0 < s < N and 0 <= x < P and 0 <= y < P):
            continue
        z = int.from_bytes(digest[:32], "big") % N
        w = pow(s, -1, N)
        qx[i] = to_limbs(x)
        qy[i] = to_limbs(y)
        u1[i] = to_limbs((z * w) % N)
        u2[i] = to_limbs((r * w) % N)
        rr[i] = to_limbs(r)
        valid[i] = True
    return qx, qy, u1, u2, rr, valid


def verify_batch(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
) -> np.ndarray:
    """Convenience wrapper: prepare on host, verify on device -> [B] bool."""
    arrays = prepare_batch(items)
    return np.asarray(_verify_batch(*[jnp.asarray(a) for a in arrays]))


ecdsa_verify_kernel = _verify_batch  # the raw jitted batch entry point


def is_on_curve(x: int, y: int) -> bool:
    """Host-side curve membership check for keystore loading (not hot path)."""
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x - 3 * x + B)) % P == 0
