"""Batched ECDSA-P256 verification as a JAX/XLA TPU kernel.

This is the north-star hot path: the reference verifies every PREPARE/COMMIT
UI certificate and client signature serially on CPU (Go crypto/ecdsa at
sample/authentication/crypto.go:79-89; enclave-side create at
usig/sgx/enclave/usig.c:36-76, verification in pure Go at
usig/sgx/sgx-usig.go:81-97).  Here a whole batch of verifications runs as one
data-parallel XLA program: ``jax.vmap`` over a scalar-shaped verifier whose
field arithmetic is the fused limb machinery of :mod:`minbft_tpu.ops.limbs`.

Division of labor (TPU-first):

- **Host** hashes variable-length bytes to the fixed 32-byte digest ``z``
  (:func:`minbft_tpu.messages.authen_digest`) and computes the two scalars
  ``u1 = z*s^-1 mod n`` and ``u2 = r*s^-1 mod n`` with native big-int ops —
  cheap, and it keeps mod-n arithmetic off the device entirely.  The
  per-batch cost is bounded by Montgomery batch inversion (ONE ``pow``
  per batch — 3 big-int multiplies per lane) and whole-batch numpy limb
  packing/range checks; see the "Host-side batch preparation" section.
- **Device** does everything expensive: the 256-bit double-scalar
  multiplication ``u1*G + u2*Q`` (interleaved Shamir ladder, Jacobian
  coordinates, a = -3 doubling), one Fermat inversion to build the G+Q
  table entry, and the affine-free final check ``X == r * Z^2`` — all
  constant-shape, batched, jit-compiled once per batch bucket.

Adversarial-input policy: the mixed-addition formula is incomplete (it
cannot add a point to itself).  Instead of paying a full doubling inside
every ladder add, the kernel *detects* the exceptional case and marks the
lane rejected (``exc`` flag).  Honest signatures hit it with probability
~2^-250; crafted signatures that steer the ladder into a collision are
simply rejected, which is always sound — the kernel only ever errs toward
rejection.  Identity operands (ladder start, Q == -G table entry) are
handled exactly with constant-shape selects.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import limbs
from .limbs import (
    Fe,
    FieldSpec,
    add_mod,
    fe_const,
    fe_eq,
    fe_from_array,
    fe_is_zero,
    fe_select,
    mont_inv,
    mont_mul,
    mont_one,
    mont_sqr,
    sub_mod,
    to_limbs,
    to_mont,
)

# ---------------------------------------------------------------------------
# Curve constants (NIST P-256 / secp256r1, FIPS 186-4 D.1.2.3).

P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5

FIELD = FieldSpec.make(P)
ORDER = FieldSpec.make(N)

_GX_M = fe_const((GX << 256) % P)  # Montgomery-domain constants
_GY_M = fe_const((GY << 256) % P)


class Point(NamedTuple):
    """Jacobian point, coordinates in Montgomery domain. Z == 0 <=> identity."""

    x: Fe
    y: Fe
    z: Fe


def _dbl(p: Point) -> Point:
    """Jacobian doubling, a = -3 (dbl-2001-b).  Maps identity to identity."""
    f = FIELD
    delta = mont_sqr(f, p.z)
    gamma = mont_sqr(f, p.y)
    beta = mont_mul(f, p.x, gamma)
    t0 = sub_mod(f, p.x, delta)
    t1 = add_mod(f, p.x, delta)
    alpha = mont_mul(f, add_mod(f, add_mod(f, t0, t0), t0), t1)  # 3(x-d)(x+d)
    beta4 = add_mod(f, add_mod(f, beta, beta), add_mod(f, beta, beta))
    beta8 = add_mod(f, beta4, beta4)
    x3 = sub_mod(f, mont_sqr(f, alpha), beta8)
    yz = add_mod(f, p.y, p.z)
    z3 = sub_mod(f, sub_mod(f, mont_sqr(f, yz), gamma), delta)
    g2 = mont_sqr(f, gamma)
    g8 = add_mod(f, add_mod(f, g2, g2), add_mod(f, g2, g2))
    g8 = add_mod(f, g8, g8)
    y3 = sub_mod(f, mont_mul(f, alpha, sub_mod(f, beta4, x3)), g8)
    return Point(x3, y3, z3)


def _madd(
    p: Point, qx: Fe, qy: Fe, q_inf: jnp.ndarray
) -> Tuple[Point, jnp.ndarray]:
    """Mixed Jacobian + affine addition (madd, 8M+3S).

    Returns (result, exc) where ``exc`` flags the formula's undefined case
    p == q (same x, same y, both finite) — callers must reject the lane.
    p == -q falls out correctly as the identity (Z3 = Z1*H = 0); identity
    operands are resolved by selects.
    """
    x1, y1, z1 = p
    f = FIELD
    z1z1 = mont_sqr(f, z1)
    u2 = mont_mul(f, qx, z1z1)
    s2 = mont_mul(f, qy, mont_mul(f, z1, z1z1))
    h = sub_mod(f, u2, x1)
    r = sub_mod(f, s2, y1)
    hh = mont_sqr(f, h)
    hhh = mont_mul(f, h, hh)
    v = mont_mul(f, x1, hh)
    x3 = sub_mod(f, sub_mod(f, mont_sqr(f, r), hhh), add_mod(f, v, v))
    y3 = sub_mod(f, mont_mul(f, r, sub_mod(f, v, x3)), mont_mul(f, y1, hhh))
    z3 = mont_mul(f, z1, h)

    p_inf = fe_is_zero(z1)
    exc = fe_is_zero(h) & fe_is_zero(r) & ~p_inf & ~q_inf

    one = mont_one(f)
    zero = limbs.fe_zero()
    # p identity -> q (affine lift); q identity -> p; both -> identity.
    x3 = fe_select(p_inf, qx, fe_select(q_inf, x1, x3))
    y3 = fe_select(p_inf, qy, fe_select(q_inf, y1, y3))
    z3 = fe_select(
        p_inf, fe_select(q_inf, zero, one), fe_select(q_inf, z1, z3)
    )
    return Point(x3, y3, z3), exc


def _madd_complete_table(p: Point, qx: Fe, qy: Fe, q_inf: jnp.ndarray) -> Point:
    """madd with the doubling case handled exactly (one extra _dbl) — used
    once per verify to build the G+Q table entry, where Q == G must yield 2G
    (a legitimate, if weird, public key)."""
    res, exc = _madd(p, qx, qy, q_inf)
    d = _dbl(p)
    return Point(
        fe_select(exc, d.x, res.x),
        fe_select(exc, d.y, res.y),
        fe_select(exc, d.z, res.z),
    )


def _bits_of(scalar_arr: jnp.ndarray) -> jnp.ndarray:
    """[16] u32 limb array -> [256] bit array, bit j = bit j of the scalar."""
    shifts = jnp.arange(limbs.LIMB_BITS, dtype=jnp.uint32)
    return ((scalar_arr[:, None] >> shifts[None, :]) & 1).reshape(256)


def _shamir(
    u1_arr: jnp.ndarray, u2_arr: jnp.ndarray, qx_m: Fe, qy_m: Fe
) -> Tuple[Point, jnp.ndarray]:
    """Interleaved double-scalar multiplication u1*G + u2*Q.

    256 iterations of double-then-select-add against the affine table
    {-, Q, G, G+Q} (indexed by 2*bit(u1) + bit(u2)); the G+Q entry is built
    on device with one Fermat inversion.  One ``fori_loop``: the compiled
    program is a handful of loop nodes regardless of batch size.

    Measured dead end (round 3, v5e, batch 4096/16384): signed-window
    ladders (w=4 and w=5, host-precomputed G tables, device-built Jacobian
    Q tables, ~30% fewer field multiplies than this ladder) are *slower*
    here — 77-86k verifies/s vs 110-113k at 4096 — and compile 2-4x
    longer.  Mosaic schedules this tiny loop body (~19 mults) near peak
    VPU throughput, while the windowed bodies (~60 mults + 9-17-entry
    per-lane tables live across the loop) lose more to scheduling and
    vector-memory pressure than the multiply count saves; per-lane
    dynamic gathers for table lookups are 6x worse still.  The batch
    size, not the ladder, is the remaining lever: per-dispatch overhead
    on a tunnel-attached chip is ~13ms, so 16384-lane batches reach 150k
    verifies/s where 4096 reaches 113k.

    Returns (result, exc) — exc set if any ladder add hit the incomplete
    case (lane must be rejected; see module docstring).
    """
    f = FIELD
    one = mont_one(f)
    gx: Fe = _GX_M
    gy: Fe = _GY_M

    # Table entry G+Q (affine).  Q == ±G handled exactly.
    gq = _madd_complete_table(Point(gx, gy, one), qx_m, qy_m, jnp.bool_(False))
    gq_inf = fe_is_zero(gq.z)
    zsafe = fe_select(gq_inf, one, gq.z)
    zi = mont_inv(f, zsafe)
    zi2 = mont_sqr(f, zi)
    gqx = mont_mul(f, gq.x, zi2)
    gqy = mont_mul(f, gq.y, mont_mul(f, zi, zi2))

    bits1 = _bits_of(u1_arr)
    bits2 = _bits_of(u2_arr)

    def body(i, carry):
        acc, exc = carry
        j = 255 - i
        acc = _dbl(acc)
        b1 = lax.dynamic_index_in_dim(bits1, j, keepdims=False)
        b2 = lax.dynamic_index_in_dim(bits2, j, keepdims=False)
        d = b1 * 2 + b2
        # Select the table entry with elementwise masks (no gathers).
        is1, is2, is3 = d == 1, d == 2, d == 3
        ax = fe_select(is1, qx_m, fe_select(is2, gx, gqx))
        ay = fe_select(is1, qy_m, fe_select(is2, gy, gqy))
        ainf = jnp.where(d == 0, jnp.bool_(True), is3 & gq_inf)
        res, e = _madd(acc, ax, ay, ainf)
        return res, exc | e

    start = Point(one, one, limbs.fe_zero())  # identity
    return lax.fori_loop(0, 256, body, (start, jnp.bool_(False)))


def _verify_one(
    qx: jnp.ndarray,
    qy: jnp.ndarray,
    u1: jnp.ndarray,
    u2: jnp.ndarray,
    r: jnp.ndarray,
    r2: jnp.ndarray,
    r2_ok: jnp.ndarray,
    valid: jnp.ndarray,
) -> jnp.ndarray:
    """Scalar-shaped ECDSA verify core; limb-array args [16] u32.

    Checks x(R) ≡ r (mod n) without an affine conversion: with R = (X:Y:Z)
    Jacobian, x(R) = X/Z^2, so x(R) == c  <=>  X == c*Z^2 (all Montgomery).
    Host supplies both candidates c ∈ {r, r+n} (the second only when
    r+n < p, flagged by ``r2_ok``).

    ``valid`` carries host-side range checks (r, s in [1, n-1]); the kernel
    AND-folds it so invalid inputs burn the same cycles as valid ones
    (constant shape) but always return False.
    """
    f = FIELD
    qx_m = to_mont(f, fe_from_array(qx))
    qy_m = to_mont(f, fe_from_array(qy))
    res, exc = _shamir(u1, u2, qx_m, qy_m)
    inf = fe_is_zero(res.z)
    z2 = mont_sqr(f, res.z)
    c1 = mont_mul(f, to_mont(f, fe_from_array(r)), z2)
    c2 = mont_mul(f, to_mont(f, fe_from_array(r2)), z2)
    ok = fe_eq(res.x, c1) | (r2_ok & fe_eq(res.x, c2))
    return ok & ~inf & ~exc & valid


from .lowering import per_mode_jit

_verify_batch = per_mode_jit(jax.vmap(_verify_one))


# ---------------------------------------------------------------------------
# Host-side batch preparation.
#
# Division of labor for the batch-inversion prep (round-6): the device
# kernels were already fast enough that a 16384-lane batch was fed by a
# SERIAL host loop doing one ~25us ``pow(s, -1, N)`` and six per-item
# ``to_limbs`` list comprehensions per lane — the classic host-bound input
# pipeline.  The vectorized ``prepare_batch`` below replaces that with
#
# - ONE modular inversion per batch: Montgomery batch inversion
#   (:func:`minbft_tpu.ops.limbs.batch_inv_host` prefix-product sweep) —
#   3 cheap big-int multiplies per item instead of a pow each;
# - whole-batch limb packing: ints -> 32-byte little-endian -> one
#   ``np.frombuffer`` as [B, 16] '<u2' (:func:`limbs.to_limbs_batch`);
# - range validity (r, s in [1, n-1], coordinates < p, the r + n < p
#   second-candidate window) as vectorized limb comparisons
#   (:func:`limbs.limbs_lt`) feeding the kernel's ``valid`` lanes.
#
# ``prepare_batch_scalar`` keeps the original per-item path bit-for-bit as
# the differential oracle (tests assert packed-array identity) and as a
# runtime escape hatch (MINBFT_SCALAR_PREP=1).

_ZERO128 = b"\x00" * 128  # one all-zero packed record (r | s | x | y)
_N_WORDS = limbs.words_of(N)
_P_WORDS = limbs.words_of(P)
_PN_WORDS = limbs.words_of(P - N)  # r + n < p  <=>  r < p - n


def prepare_batch_scalar(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
) -> Tuple[np.ndarray, ...]:
    """Per-item reference prep: one ``pow(s, -1, N)`` and six ``to_limbs``
    per lane.  The differential ORACLE for the vectorized
    :func:`prepare_batch` — kept verbatim, selectable via
    MINBFT_SCALAR_PREP=1."""
    b = len(items)
    qx = np.zeros((b, limbs.NLIMBS), np.uint32)
    qy = np.zeros((b, limbs.NLIMBS), np.uint32)
    u1 = np.zeros((b, limbs.NLIMBS), np.uint32)
    u2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    rr = np.zeros((b, limbs.NLIMBS), np.uint32)
    r2 = np.zeros((b, limbs.NLIMBS), np.uint32)
    r2_ok = np.zeros((b,), np.bool_)
    valid = np.zeros((b,), np.bool_)
    for i, ((x, y), digest, (r, s)) in enumerate(items):
        if not (0 < r < N and 0 < s < N and 0 <= x < P and 0 <= y < P):
            continue
        z = int.from_bytes(digest[:32], "big") % N
        w = pow(s, -1, N)
        qx[i] = to_limbs(x)
        qy[i] = to_limbs(y)
        u1[i] = to_limbs((z * w) % N)
        u2[i] = to_limbs((r * w) % N)
        rr[i] = to_limbs(r)
        if r + N < P:
            r2[i] = to_limbs(r + N)
            r2_ok[i] = True
        valid[i] = True
    return qx, qy, u1, u2, rr, r2, r2_ok, valid


def prepare_batch(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
) -> Tuple[np.ndarray, ...]:
    """[(pubkey (x, y), digest32, (r, s))] -> device-ready limb arrays.

    Host computes w = s^-1 mod n (ONE batch inversion for the whole
    batch), u1 = z*w, u2 = r*w (mod n) with Python big ints, and packs /
    range-checks the batch with vectorized numpy (see the section note
    above).  Out-of-range signatures get valid=False and all-zero lanes so
    the batch shape never changes.  Bit-identical to
    :func:`prepare_batch_scalar`.
    """
    if limbs.SCALAR_PREP:
        return prepare_batch_scalar(items)
    b = len(items)
    nl = limbs.NLIMBS
    if b == 0:
        z16 = np.zeros((0, nl), np.uint32)
        zb = np.zeros((0,), np.bool_)
        return z16, z16, z16, z16, z16, z16, zb, zb

    # Pass 1 (per item, C-level): ints -> little-endian bytes.  Values
    # outside [0, 2^256) cannot pack (to_bytes raises) — their lane is
    # invalid regardless of the curve-order checks below, so pack zeros
    # and mark unfit.
    buf = bytearray()
    unfit = []
    for i, ((x, y), _digest, (r, s)) in enumerate(items):
        try:
            rec = (
                r.to_bytes(32, "little")
                + s.to_bytes(32, "little")
                + x.to_bytes(32, "little")
                + y.to_bytes(32, "little")
            )
        except (OverflowError, TypeError, AttributeError):
            rec = _ZERO128
            unfit.append(i)
        buf += rec
    raw = bytes(buf)
    rows = np.frombuffer(raw, dtype="<u2").reshape(b, 4, nl)
    words = np.frombuffer(raw, dtype="<u8").reshape(b, 4, 4)
    rw, sw = words[:, 0], words[:, 1]

    # Vectorized range validity: r, s in [1, n-1]; coordinates < p.
    valid = (
        rw.any(axis=1)
        & limbs.words_lt(rw, _N_WORDS)
        & sw.any(axis=1)
        & limbs.words_lt(sw, _N_WORDS)
        & limbs.words_lt(words[:, 2], _P_WORDS)
        & limbs.words_lt(words[:, 3], _P_WORDS)
    )
    if unfit:
        valid[unfit] = False

    # Pass 2 (valid lanes only): ONE inversion for the batch, then 2
    # multiplies per lane for the scalars.
    all_valid = bool(valid.all())
    idx = range(b) if all_valid else np.flatnonzero(valid).tolist()
    ws = limbs.batch_inv_host([items[i][2][1] for i in idx], N)
    u1_ints, u2_ints = [], []
    for i, w in zip(idx, ws):
        (_xy, digest, (r, _s)) = items[i]
        z = int.from_bytes(digest[:32], "big") % N
        u1_ints.append(z * w % N)
        u2_ints.append(r * w % N)
    if all_valid:
        u1 = limbs.to_limbs_batch(u1_ints)
        u2 = limbs.to_limbs_batch(u2_ints)
    else:
        u1 = np.zeros((b, nl), np.uint32)
        u2 = np.zeros((b, nl), np.uint32)
        if idx:
            u1[idx] = limbs.to_limbs_batch(u1_ints)
            u2[idx] = limbs.to_limbs_batch(u2_ints)

    # Second x-candidate: r + n < p  <=>  r < p - n, so the window check
    # needs no addition; the candidate itself is a vectorized limb add
    # computed only over the (rare: r < ~2^224) lanes inside the window —
    # no overflow there since r + n < p < 2^256.
    r2_ok = valid & limbs.words_lt(rw, _PN_WORDS)
    r2 = np.zeros((b, nl), np.uint32)
    i2 = np.flatnonzero(r2_ok)
    if len(i2):
        r2[i2] = limbs.limbs_add_const(rows[i2, 0], N)

    # Invalid lanes are all-zero in the oracle (its loop skips them
    # before writing) — mask for bit-identical output.
    if all_valid:
        qx = rows[:, 2].astype(np.uint32)
        qy = rows[:, 3].astype(np.uint32)
        rr = rows[:, 0].astype(np.uint32)
    else:
        lane = valid[:, None]
        z16 = np.uint16(0)
        qx = np.where(lane, rows[:, 2], z16).astype(np.uint32)
        qy = np.where(lane, rows[:, 3], z16).astype(np.uint32)
        rr = np.where(lane, rows[:, 0], z16).astype(np.uint32)
    return qx, qy, u1, u2, rr, r2, r2_ok, valid


def verify_batch(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
) -> np.ndarray:
    """Convenience wrapper: prepare on host, verify on device -> [B] bool."""
    arrays = prepare_batch(items)
    return np.asarray(_verify_batch(*[jnp.asarray(a) for a in arrays]))


ecdsa_verify_kernel = _verify_batch  # the raw jitted batch entry point


# Packed I/O: on tunnel-attached hosts each host->device array is its own
# RPC (~15-20ms); the 8-argument form pays 8 of them per dispatch, which
# dominated the e2e dispatch round trip (round-4 profile).  One u16 row per
# lane — limb values are 16-bit by construction, flags are 0/1 — makes the
# upload a single transfer at half the bytes.

PACKED_COLS = 6 * limbs.NLIMBS + 2  # qx qy u1 u2 r r2 | r2_ok valid


def pack_arrays(arrays) -> np.ndarray:
    """prepare_batch output -> [B, PACKED_COLS] u16 (one upload)."""
    qx, qy, u1, u2, rr, r2, r2_ok, valid = arrays
    return np.concatenate(
        [
            qx, qy, u1, u2, rr, r2,
            r2_ok[:, None].astype(np.uint32),
            valid[:, None].astype(np.uint32),
        ],
        axis=1,
    ).astype(np.uint16)


def prepare_packed(
    items: Sequence[Tuple[Tuple[int, int], bytes, Tuple[int, int]]],
    bucket: int,
    out: "np.ndarray | None" = None,
) -> np.ndarray:
    """prepare_batch + pack_arrays fused into one [bucket, PACKED_COLS]
    u16 staging write.  ``out`` (engine-owned staging buffer, recycled
    across dispatches) is written in place when given; padding the batch
    to ``bucket`` is a tail slice-zero instead of materializing
    ``list(items) + [PAD] * k`` and prepping the pad lanes."""
    n = len(items)
    out = limbs.staging_out(out, bucket, PACKED_COLS, n)
    qx, qy, u1, u2, rr, r2, r2_ok, valid = prepare_batch(items)
    L = limbs.NLIMBS
    out[:n, 0:L] = qx
    out[:n, L : 2 * L] = qy
    out[:n, 2 * L : 3 * L] = u1
    out[:n, 3 * L : 4 * L] = u2
    out[:n, 4 * L : 5 * L] = rr
    out[:n, 5 * L : 6 * L] = r2
    out[:n, 6 * L] = r2_ok
    out[:n, 6 * L + 1] = valid
    out[n:] = 0
    return out


def _verify_one_packed(row: jnp.ndarray) -> jnp.ndarray:
    r32 = row.astype(jnp.uint32)
    L = limbs.NLIMBS
    return _verify_one(
        r32[0:L],
        r32[L : 2 * L],
        r32[2 * L : 3 * L],
        r32[3 * L : 4 * L],
        r32[4 * L : 5 * L],
        r32[5 * L : 6 * L],
        r32[6 * L] != 0,
        r32[6 * L + 1] != 0,
    )


ecdsa_verify_kernel_packed = per_mode_jit(jax.vmap(_verify_one_packed))


# ---------------------------------------------------------------------------
# Batched signing.
#
# The reference signs serially inside the enclave (usig.c:36-76) and on the
# host for replies (crypto.go:66-77).  Here the expensive part of ECDSA
# signing — the fixed-base scalar multiplication k*G — runs as a batched
# device kernel, with the cheap big-int scalar work (RFC 6979 nonce, k^-1,
# s = k^-1(z + r*d) mod n) on the host.  Signatures are byte-identical to
# the host signer (deterministic k), which doubles as the differential
# test.  Useful on PCIe-attached chips (REPLY signing at high throughput);
# on tunnel-attached devices the per-dispatch latency usually favors the
# host signer.


def _kg_one(k: jnp.ndarray) -> jnp.ndarray:
    """Scalar-shaped k*G via a dedicated G-only bit ladder: 256 iterations
    of double-then-conditionally-add-G — no Q half, so none of the verify
    ladder's G+Q table build or its Fermat inversion (~10% of the verify's
    multiplies) and a 2-way instead of 4-way addend select.  Returns X and
    Z (Jacobian, Montgomery form) stacked as one [2, 16] array — a single
    device→host transfer per batch; Y is not needed for signing.

    Kept as the differential reference for the comb kernel below (and the
    fallback if a backend dislikes the comb's table selects)."""
    bits = _bits_of(k)

    def body(i, carry):
        acc, exc = carry
        j = 255 - i
        acc = _dbl(acc)
        b = lax.dynamic_index_in_dim(bits, j, keepdims=False)
        res, e = _madd(acc, _GX_M, _GY_M, b == 0)
        return res, exc | e

    start = Point(mont_one(FIELD), mont_one(FIELD), limbs.fe_zero())
    res, exc = lax.fori_loop(0, 256, body, (start, jnp.bool_(False)))
    # exc (acc == G mid-ladder) cannot fire for scalars < n (partial sums
    # are distinct G-multiples), but fold it into Z so a hypothetical hit
    # degrades to "infinity" — sign_batch falls back to the host signer.
    z = fe_select(exc, limbs.fe_zero(), res.z)
    return jnp.stack([limbs.fe_to_array(res.x), limbs.fe_to_array(z)])


ecdsa_kg_ladder_kernel = per_mode_jit(jax.vmap(_kg_one))


# --- fixed-base comb --------------------------------------------------------
#
# k*G with G fixed admits a precomputed-table comb that the general ladder
# cannot use: write k = sum_j k_j * 16^j over 64 nibble windows and
# precompute T[j][v] = v * 16^j * G (affine, Montgomery domain) ON THE HOST
# — then k*G = sum_j T[j][k_j] is just 64 mixed additions with NO doublings
# (~7x fewer field multiplies than the 256 double+add ladder).  The
# windowed approach measured as a dead end for the VERIFY ladder (see
# _shamir's note) fails on per-lane runtime tables; here the table is one
# global compile-time constant shared by every lane, and each window's
# lookup is an elementwise masked sum over 16 rows — no gathers, nothing
# per-lane resident across the loop.

_COMB_WINDOWS = 64
_COMB_TABLE_NP: np.ndarray | None = None


def _comb_table_np() -> np.ndarray:
    """[64, 16, 2, NLIMBS] u32: T[j][v] = affine(v * 16^j * G), Montgomery
    domain; the v=0 rows are zeros (skipped via the q_inf flag).  Built
    once with host big-int affine arithmetic (~1k cheap ops)."""
    global _COMB_TABLE_NP
    if _COMB_TABLE_NP is not None:
        return _COMB_TABLE_NP

    def aff_add(p1, p2):
        if p1 is None:
            return p2
        (x1, y1), (x2, y2) = p1, p2
        if x1 == x2:
            if (y1 + y2) % P == 0:
                return None
            lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, P) % P
        else:
            lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
        x3 = (lam * lam - x1 - x2) % P
        return x3, (lam * (x1 - x3) - y1) % P

    tab = np.zeros((_COMB_WINDOWS, 16, 2, limbs.NLIMBS), np.uint32)
    base = (GX, GY)  # 16^j * G for the current window
    for j in range(_COMB_WINDOWS):
        acc = None
        for v in range(1, 16):
            acc = aff_add(acc, base)
            x, y = acc
            tab[j, v, 0] = to_limbs((x << 256) % P)
            tab[j, v, 1] = to_limbs((y << 256) % P)
        for _ in range(4):  # base <- 16 * base
            base = aff_add(base, base)
    _COMB_TABLE_NP = tab
    return tab


def _kg_comb_one(k: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """Scalar-shaped k*G via the fixed-base comb (see the note above).
    Returns the same [2, 16] (X, Z) stack as _kg_one, narrowed to uint16
    (limbs are 16-bit; on tunnel-attached hosts the device→host transfer
    is a first-order cost and this halves it).

    Exceptional-case note: partial sums after window j are m*G with
    m < 16^(j+1), while window j+1 adds k_{j+1} * 16^(j+1) * G — the
    incomplete madd's p == ±q cases would need m == ±k_{j+1}*16^(j+1)
    (mod n), impossible for honest scalars < n; exc is still folded to
    Z = 0 (host-signer fallback) as defense in depth."""
    # limb i (16 bits) holds nibble windows 4i..4i+3
    shifts = (4 * jnp.arange(4, dtype=jnp.uint32))[None, :]
    nibs = ((k[:, None] >> shifts) & 0xF).reshape(_COMB_WINDOWS)

    def body(j, carry):
        acc, exc = carry
        tab_j = lax.dynamic_index_in_dim(table, j, keepdims=False)  # [16,2,L]
        v = lax.dynamic_index_in_dim(nibs, j, keepdims=False)
        mask = (jnp.arange(16, dtype=jnp.uint32) == v)[:, None, None]
        sel = jnp.sum(jnp.where(mask, tab_j, 0), axis=0)  # [2, L]
        ax = fe_from_array(sel[0])
        ay = fe_from_array(sel[1])
        res, e = _madd(acc, ax, ay, v == 0)
        return res, exc | e

    start = Point(mont_one(FIELD), mont_one(FIELD), limbs.fe_zero())
    res, exc = lax.fori_loop(
        0, _COMB_WINDOWS, body, (start, jnp.bool_(False))
    )
    z = fe_select(exc, limbs.fe_zero(), res.z)
    out = jnp.stack([limbs.fe_to_array(res.x), limbs.fe_to_array(z)])
    return out.astype(jnp.uint16)


_kg_comb_batch = None


def ecdsa_kg_kernel(k_arr) -> jnp.ndarray:
    """Batched k*G — fixed-base comb kernel (the sign hot path).  Takes
    [B, 16] limb rows (any integer dtype; values < 2^16), uploads them as
    uint16, and returns [B, 2, 16] uint16 (X, Z) Jacobian Montgomery.
    The comb table is closed over as a jit constant — baked into the
    executable, never a per-call transfer."""
    global _kg_comb_batch
    if _kg_comb_batch is None:
        table = jnp.asarray(_comb_table_np())

        def _kg_comb_widen(k16: jnp.ndarray) -> jnp.ndarray:
            # Widen the u16 upload on device; the wire carries half the
            # bytes of u32 limb rows.
            return jax.vmap(_kg_comb_one, in_axes=(0, None))(
                k16.astype(jnp.uint32), table
            )

        _kg_comb_batch = per_mode_jit(_kg_comb_widen)
    return _kg_comb_batch(jnp.asarray(np.asarray(k_arr).astype(np.uint16)))


_batch_inv = limbs.batch_inv_host

# Staging layout for the sign path: one [16] u16 nonce-limb row per lane
# (the k*G kernels upload u16 and widen on device).  The engine's sign
# queue recycles [bucket, SIGN_COLS] buffers through its _StagingPool
# exactly like the verify path's packed uploads.
SIGN_COLS = limbs.NLIMBS


def sign_prepare(
    items: Sequence[Tuple[int, bytes]],
    bucket: int,
    out: "np.ndarray | None" = None,
) -> Tuple[np.ndarray, list]:
    """Host half 1 of batched signing: derive the RFC 6979 nonce per item
    (an HMAC-SHA256 chain — inherently per-item, but cheap host hashing)
    and pack the whole batch's nonce limbs with one bulk '<u2' view
    (:func:`minbft_tpu.ops.limbs.to_limbs_batch`) into ``out`` (an
    engine-owned recycled staging buffer when given).  Pad lanes get
    k = 1 — a valid scalar whose result is discarded — as a tail write,
    never a re-derivation.  Returns ``(staging, meta)``; ``meta`` is the
    per-lane ``(d, z, k)`` list :func:`sign_finish` consumes."""
    from ..utils import hostcrypto as hc

    n = len(items)
    out = limbs.staging_out(out, bucket, SIGN_COLS, n)
    meta = []
    ks = []
    for d, digest in items:
        z = int.from_bytes(digest[:32], "big") % N
        k = hc._rfc6979_k(d, z)
        meta.append((d, z, k))
        ks.append(k)
    if n:
        out[:n] = limbs.to_limbs_batch(ks)
    out[n:] = 0
    out[n:, 0] = 1  # k = 1: a valid lane, result discarded
    return out, meta


def sign_finish(
    items: Sequence[Tuple[int, bytes]], meta: list, xz
) -> list:
    """Host half 2: turn the device's [B, 2, 16] X/Z limbs into (r, s).

    ONE Montgomery batch inversion each for the Z^2 chain (mod p) and the
    nonces (mod n) — 3 big-int multiplies per lane instead of a ~25us
    ``pow`` each (the PR-2 ``batch_inv_host`` machinery).  Exceptional
    lanes (Z == 0) and the vanishing-probability r == 0 / s == 0 RFC 6979
    retries fall back to the serial host signer per lane."""
    from ..utils import hostcrypto as hc

    b = len(meta)
    xz = np.concatenate([np.asarray(o) for o in xz]) if isinstance(
        xz, (list, tuple)
    ) else np.asarray(xz)
    xz = xz.astype("<u2")[:b]  # [B,2,16]
    # Vectorized limb→int: uint16 rows → little-endian bytes → one
    # int.from_bytes per row (a per-limb shift-sum costs ~250us/row).
    x_ints = [int.from_bytes(row.tobytes(), "little") for row in xz[:, 0]]
    z_ints = [int.from_bytes(row.tobytes(), "little") for row in xz[:, 1]]

    r_inv = pow(1 << 256, -1, P)  # undo the Montgomery factor on host
    valid = [i for i in range(b) if z_ints[i] != 0]
    zj = {i: z_ints[i] * r_inv % P for i in valid}
    zz_invs = dict(
        zip(valid, _batch_inv([zj[i] * zj[i] % P for i in valid], P))
    )
    k_invs = dict(zip(valid, _batch_inv([meta[i][2] for i in valid], N)))

    out = []
    for i, (d, z, k) in enumerate(meta):
        if i not in zz_invs:  # infinity / exceptional lane: serial fallback
            out.append(hc.ecdsa_sign_py(d, items[i][1]))
            continue
        x_aff = (x_ints[i] * r_inv % P) * zz_invs[i] % P
        r = x_aff % N
        s = k_invs[i] * (z + r * d) % N
        if r == 0 or s == 0:  # vanishing-probability RFC 6979 retry path
            out.append(hc.ecdsa_sign_py(d, items[i][1]))
            continue
        out.append((r, s))
    return out


def sign_batch(
    items: Sequence[Tuple[int, bytes]],
    bucket: int = 0,
    kg_kernel=None,
    chunk: int = 4096,
) -> list:
    """[(private scalar d, digest32)] -> [(r, s)] — RFC 6979 deterministic,
    byte-identical to :func:`minbft_tpu.utils.hostcrypto.ecdsa_sign_py`.

    ``bucket`` pads the device batch to a fixed size (pad lanes compute
    1*G and are discarded) so varying batch sizes share one compiled
    kernel — hot-path callers must pass their bucket ladder's size, like
    the verify path's engine buckets.  ``kg_kernel`` overrides the k*G
    kernel — pass :func:`minbft_tpu.parallel.mesh.sharded_ecdsa_sign_kernel`'s
    result to shard signing across a device mesh (bucket must then be a
    multiple of the mesh size).

    Composition of :func:`sign_prepare` → k*G kernel → :func:`sign_finish`
    — the engine's sign queue (:mod:`minbft_tpu.parallel.engine`) drives
    the same three stages with recycled staging buffers and a separately
    timed host/device split."""
    b = len(items)
    if b == 0 and bucket == 0:
        return []
    total = max(bucket, b)
    # Pipeline large batches through the device in fixed-size chunks: jax
    # dispatch is asynchronous, so launching every chunk before collecting
    # any overlaps chunk i's compute + device->host transfer with chunk
    # i+1's upload — on tunnel-attached chips the transfers are a
    # first-order cost and a monolithic batch serializes them.  Equal
    # chunk shapes share one compiled kernel.
    if total > chunk:
        total = -(-total // chunk) * chunk  # round up to a chunk multiple
    k_arr, meta = sign_prepare(items, total)
    kernel = kg_kernel if kg_kernel is not None else ecdsa_kg_kernel
    step = chunk if total > chunk else total
    outs = [kernel(k_arr[c0 : c0 + step]) for c0 in range(0, total, step)]
    return sign_finish(items, meta, outs)


def is_on_curve(x: int, y: int) -> bool:
    """Host-side curve membership check for keystore loading (not hot path)."""
    if not (0 <= x < P and 0 <= y < P):
        return False
    return (y * y - (x * x * x - 3 * x + B)) % P == 0
