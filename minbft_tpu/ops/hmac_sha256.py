"""Batched HMAC-SHA256 over fixed 32-byte inputs.

This is the symmetric authentication scheme of the build (the SGX-less USIG
certificate scheme of BASELINE config[0] and the MAC scheme the reference
lists as future work, reference README.md:499-500).  Everything is fixed
shape: key = 32 bytes, message = a 32-byte authen digest
(:func:`minbft_tpu.messages.authen_digest`), so one HMAC is exactly four
SHA-256 compressions and a batch of B HMACs is one ``vmap``-ped kernel.

Layout (RFC 2104 with B=64-byte block):
    inner = H( (key ⊕ ipad) ‖ msg32 ‖ pad )   — 2 compressions
    mac   = H( (key ⊕ opad) ‖ inner ‖ pad )   — 2 compressions
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .sha256 import IV, compress

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)

# Padding tail for a 64+32-byte message: 0x80 then zeros then bitlen=768.
_TAIL = np.array([0x80000000, 0, 0, 0, 0, 0, 0, 768], dtype=np.uint32)


def hmac32(key: jnp.ndarray, msg: jnp.ndarray) -> jnp.ndarray:
    """HMAC-SHA256(key32, msg32): key [8] u32, msg [8] u32 → mac [8] u32."""
    key = key.astype(jnp.uint32)
    msg = msg.astype(jnp.uint32)
    tail = jnp.asarray(_TAIL)
    zeros8 = jnp.zeros(8, dtype=jnp.uint32)

    ipad_block = jnp.concatenate([key ^ _IPAD, zeros8 ^ _IPAD])
    opad_block = jnp.concatenate([key ^ _OPAD, zeros8 ^ _OPAD])

    inner_state = compress(jnp.asarray(IV), ipad_block)
    inner = compress(inner_state, jnp.concatenate([msg, tail]))

    outer_state = compress(jnp.asarray(IV), opad_block)
    return compress(outer_state, jnp.concatenate([inner, tail]))


def hmac32_verify(key: jnp.ndarray, msg: jnp.ndarray, mac: jnp.ndarray) -> jnp.ndarray:
    """→ bool scalar: does HMAC(key, msg) equal ``mac``?"""
    return jnp.all(hmac32(key, msg) == mac.astype(jnp.uint32))


# Batched: keys [B,8], msgs [B,8], macs [B,8] → [B] bool.
hmac32_batch = jax.vmap(hmac32)
hmac32_verify_batch = jax.vmap(hmac32_verify)


from .lowering import per_mode_jit


@per_mode_jit
def hmac_verify_kernel(keys, msgs, macs):
    """The jitted batch-verify entry point used by the verification engine."""
    return hmac32_verify_batch(keys, msgs, macs)


@per_mode_jit
def hmac_verify_kernel_packed(packed):
    """Packed single-upload form: [B, 24] u32 rows (key | msg | mac) —
    one host->device RPC per dispatch instead of three (see the packed
    note in ops/p256.py)."""
    return hmac32_verify_batch(
        packed[:, 0:8], packed[:, 8:16], packed[:, 16:24]
    )


@per_mode_jit
def hmac_sign_kernel(keys, msgs):
    """Batched MAC generation (used by the software USIG and tests)."""
    return hmac32_batch(keys, msgs)
