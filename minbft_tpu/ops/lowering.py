"""Lowering-mode dispatch shared by the crypto kernels.

Every kernel in :mod:`minbft_tpu.ops` has two lowerings of the same
arithmetic: a fully **unrolled** straight-line form (what TPUs want — Mosaic
compiles it fast and fuses it completely) and a compact **loop** form
(``lax.scan``/``fori_loop``) for the CPU "SIM mode" backend, where XLA's
LLVM codegen is superlinear in basic-block size and chokes on big unrolled
graphs.  Dispatch is by backend at trace time; ``set_mode`` forces one for
equivalence tests.
"""

from __future__ import annotations

_FORCE_MODE = None  # None = auto by backend | "unrolled" | "loop"


def set_mode(mode) -> None:
    """Force 'unrolled' or 'loop' lowering (None = auto: unrolled off-CPU)."""
    global _FORCE_MODE
    if mode not in (None, "unrolled", "loop"):
        raise ValueError(mode)
    _FORCE_MODE = mode


def use_unrolled() -> bool:
    if _FORCE_MODE is not None:
        return _FORCE_MODE == "unrolled"
    import jax

    return jax.default_backend() != "cpu"
