"""Lowering-mode dispatch shared by the crypto kernels.

Every kernel in :mod:`minbft_tpu.ops` has two lowerings of the same
arithmetic: a fully **unrolled** straight-line form (what TPUs want — Mosaic
compiles it fast and fuses it completely) and a compact **loop** form
(``lax.scan``/``fori_loop``) for the CPU "SIM mode" backend, where XLA's
LLVM codegen is superlinear in basic-block size and chokes on big unrolled
graphs.  Dispatch is by backend at trace time; ``set_mode`` forces one for
equivalence tests.
"""

from __future__ import annotations

_FORCE_MODE = None  # None = auto by backend | "unrolled" | "loop" | "block"


def set_mode(mode) -> None:
    """Force a lowering (None = auto: block off-CPU, loop on CPU).

    - ``block``: scan over blocks of 4 unrolled CIOS iterations — the TPU
      default.  Measured on v5e at batch 4096: 122.8k ECDSA verifies/s
      with a 42s cold compile.
    - ``unrolled``: full straight-line trace-time expansion.  Measured
      102.8k verifies/s with a ~7 min cold compile — the giant basic block
      compiles 10x slower AND schedules worse than the blocked form, so
      this survives only as a differential-test reference and for
      experiments on other TPU generations.
    - ``loop``: outer loops as ``lax.scan`` — compiles in seconds
      everywhere; used by the CPU "SIM mode" backend and the protocol e2e
      paths (which need a sliver of kernel throughput)."""
    global _FORCE_MODE
    if mode not in (None, "unrolled", "loop", "block"):
        raise ValueError(mode)
    _FORCE_MODE = mode


def mode() -> str:
    if _FORCE_MODE is not None:
        return _FORCE_MODE
    import jax

    return "block" if jax.default_backend() != "cpu" else "loop"


def use_unrolled() -> bool:
    return mode() == "unrolled"


def per_mode_jit(fn):
    """``jax.jit`` keyed by the active lowering mode.

    The mode is read from a Python global at *trace* time, which a plain
    module-level ``jax.jit`` would bake into its first compilation and then
    silently reuse for every mode (the jit cache keys on shapes only).  One
    jitted instance per mode keeps the caches — in-process and persistent —
    honest."""
    import jax

    cache = {}

    def wrapper(*args, **kwargs):
        m = mode()
        jitted = cache.get(m)
        if jitted is None:
            jitted = jax.jit(fn)
            cache[m] = jitted
        return jitted(*args, **kwargs)

    wrapper.__name__ = getattr(fn, "__name__", "kernel")
    return wrapper
