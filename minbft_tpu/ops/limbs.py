"""256-bit modular arithmetic as fixed-width limb vectors for TPU.

XLA on TPU has no big-int and no native 64-bit integer multiply, so field
elements are represented as **16 little-endian limbs of 16 bits each, stored
in uint32 lanes**.  A 16x16-bit product is exact in uint32, which makes every
step below overflow-free by construction:

- ``mont_mul``: word-by-word Montgomery multiplication (CIOS) expressed as a
  ``lax.fori_loop`` so the HLO stays small; a verify compiles to a few loop
  nodes instead of a million-op unrolled graph.
- ``add_mod`` / ``sub_mod``: carry-propagated limb add/sub with a
  constant-shape conditional reduction (``jnp.where``, no data-dependent
  branching — everything is jit/vmap-safe).
- ``mont_pow``: square-and-multiply over a *static* exponent bit array with
  select-based multiply, used for Fermat inversion (the only inversion
  primitive needed on device).

This replaces the serial host big-int arithmetic of the reference (Go
``crypto/ecdsa`` under sample/authentication/crypto.go:79-89 and the SGX
enclave's sgx_ecc256 calls in usig/sgx/enclave/usig.c:36-76) with a batchable
data-parallel substrate: ``jax.vmap`` over any of these maps the batch onto
VPU lanes.

All functions take a :class:`FieldSpec` (modulus-specific constants built
host-side with Python big ints) and [16] uint32 arrays; none of them
allocates dynamically or branches on data.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp
from jax import lax

NLIMBS = 16
LIMB_BITS = 16
MASK = np.uint32(0xFFFF)
BITS = NLIMBS * LIMB_BITS  # 256


# ---------------------------------------------------------------------------
# Host-side conversions (Python int <-> limb vectors).


def to_limbs(x: int) -> np.ndarray:
    """Python int (< 2^256) -> [16] uint32 little-endian 16-bit limbs."""
    if not 0 <= x < (1 << BITS):
        raise ValueError("value out of 256-bit range")
    return np.array(
        [(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMBS)], dtype=np.uint32
    )


def from_limbs(limbs) -> int:
    """[16] uint32 limb vector -> Python int."""
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(NLIMBS))


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """Constants for Montgomery arithmetic mod a fixed 256-bit modulus.

    Built host-side once per field (P-256 coordinate field, P-256 group
    order, curve25519 field, ...) and closed over by the jitted kernels.
    """

    modulus_int: int
    modulus: np.ndarray  # [16] u32
    m_prime: np.uint32  # -modulus^-1 mod 2^16
    r_mod: np.ndarray  # R mod m      (Montgomery one)
    r2_mod: np.ndarray  # R^2 mod m    (to-Montgomery factor)

    @staticmethod
    def make(modulus: int) -> "FieldSpec":
        r = 1 << BITS
        m_inv = pow(modulus, -1, 1 << LIMB_BITS)
        return FieldSpec(
            modulus_int=modulus,
            modulus=to_limbs(modulus),
            m_prime=np.uint32((-m_inv) % (1 << LIMB_BITS)),
            r_mod=to_limbs(r % modulus),
            r2_mod=to_limbs((r * r) % modulus),
        )


# ---------------------------------------------------------------------------
# Carry handling helpers (device side).


def _carry_pass(t: jnp.ndarray) -> jnp.ndarray:
    """One full sequential carry propagation; limbs must be < 2^32 - 2^16 so
    ``limb + carry_in`` cannot overflow uint32.  [k] u32 -> [k] u32 with all
    but the last limb < 2^16."""

    def body(i, t):
        c = t[i] >> LIMB_BITS
        t = t.at[i].set(t[i] & MASK)
        return t.at[i + 1].add(c)

    return lax.fori_loop(0, t.shape[0] - 1, body, t)


def _geq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a >= b for fully-carried limb vectors, compared big-endian."""
    # Find the most significant differing limb via lexicographic trick:
    # scan from the top; equivalent closed form below avoids a loop.
    gt = a > b
    lt = a < b
    # Highest index where they differ decides; compute with cumulative logic.
    # diff_rank[i] = 1 if limbs differ at i. We want gt at the highest
    # differing index. Use weights: compare as integers via subtract chain
    # is simpler:
    borrow = jnp.uint32(0)
    n = a.shape[0]

    def body(i, borrow):
        d = a[i] - b[i] - borrow
        return (d >> jnp.uint32(31)) & jnp.uint32(1)  # 1 if underflow

    borrow = lax.fori_loop(0, n, body, borrow)
    del gt, lt
    return borrow == 0


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b (assumes a >= b), fully carried limbs -> fully carried limbs."""
    n = a.shape[0]

    def body(i, carry):
        out, borrow = carry
        d = a[i] - b[i] - borrow
        borrow = (d >> jnp.uint32(31)) & jnp.uint32(1)
        return out.at[i].set(d & MASK), borrow

    out, _ = lax.fori_loop(0, n, body, (jnp.zeros_like(a), jnp.uint32(0)))
    return out


def cond_sub_mod(spec: FieldSpec, a: jnp.ndarray) -> jnp.ndarray:
    """If a >= m, return a - m, else a (constant shape select)."""
    m = jnp.asarray(spec.modulus)
    return jnp.where(_geq(a, m), _sub_limbs(a, m), a)


# ---------------------------------------------------------------------------
# Modular add/sub.


def add_mod(spec: FieldSpec, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a + b) mod m; a, b fully-carried [16] u32."""
    t = jnp.concatenate([a + b, jnp.zeros(1, jnp.uint32)])
    t = _carry_pass(t)
    # t < 2m < 2^257: top limb is 0 or 1. Subtract m if t >= m.
    m17 = jnp.concatenate([jnp.asarray(spec.modulus), jnp.zeros(1, jnp.uint32)])
    t = jnp.where(_geq(t, m17), _sub_limbs(t, m17), t)
    return t[:NLIMBS]


def sub_mod(spec: FieldSpec, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod m; adds m first so the subtraction never underflows."""
    m = jnp.asarray(spec.modulus)
    t = jnp.concatenate([a + m, jnp.zeros(1, jnp.uint32)])
    t = _carry_pass(t)
    b17 = jnp.concatenate([b, jnp.zeros(1, jnp.uint32)])
    t = _sub_limbs(t, b17)
    m17 = jnp.concatenate([m, jnp.zeros(1, jnp.uint32)])
    t = jnp.where(_geq(t, m17), _sub_limbs(t, m17), t)
    return t[:NLIMBS]


# ---------------------------------------------------------------------------
# Montgomery multiplication (CIOS, word-by-word).


def mont_mul(spec: FieldSpec, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod m (R = 2^256).

    CIOS: for each 16-bit word of ``a``, accumulate a_i*b and a reduction
    multiple of m, then shift one word.  Accumulator limbs stay < 2^19
    (sum of fully-carried residue + two exact 16x16 product halves), so a
    single carry pass per iteration suffices — no uint32 overflow anywhere.
    """
    m = jnp.asarray(spec.modulus)
    mp = jnp.uint32(spec.m_prime)
    b = b.astype(jnp.uint32)

    def body(i, t):
        ai = lax.dynamic_index_in_dim(a, i, keepdims=False)
        p = ai * b  # [16] exact 32-bit products
        t = t.at[:NLIMBS].add(p & MASK)
        t = t.at[1 : NLIMBS + 1].add(p >> LIMB_BITS)
        u = ((t[0] & MASK) * mp) & MASK
        q = u * m
        t = t.at[:NLIMBS].add(q & MASK)
        t = t.at[1 : NLIMBS + 1].add(q >> LIMB_BITS)
        # Low word is now divisible by 2^16: shift down one word.
        c0 = t[0] >> LIMB_BITS
        t = jnp.concatenate([t[1:], jnp.zeros(1, jnp.uint32)])
        t = t.at[0].add(c0)
        return _carry_pass(t)

    t = jnp.zeros(NLIMBS + 2, dtype=jnp.uint32)
    t = lax.fori_loop(0, NLIMBS, body, t)
    # t < 2m here (standard CIOS bound); top limbs carry at most 1.
    m18 = jnp.concatenate([m, jnp.zeros(2, jnp.uint32)])
    t = jnp.where(_geq(t, m18), _sub_limbs(t, m18), t)
    return t[:NLIMBS]


def mont_sqr(spec: FieldSpec, a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(spec, a, a)


def to_mont(spec: FieldSpec, a: jnp.ndarray) -> jnp.ndarray:
    """a -> a*R mod m."""
    return mont_mul(spec, a, jnp.asarray(spec.r2_mod))


def from_mont(spec: FieldSpec, a: jnp.ndarray) -> jnp.ndarray:
    """a*R -> a mod m (multiply by 1)."""
    one = jnp.zeros(NLIMBS, jnp.uint32).at[0].set(1)
    return mont_mul(spec, a, one)


def mont_one(spec: FieldSpec) -> jnp.ndarray:
    return jnp.asarray(spec.r_mod)


# ---------------------------------------------------------------------------
# Exponentiation / inversion.


def mont_pow_static(spec: FieldSpec, a: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """a^exponent (Montgomery domain) for a *host-static* exponent.

    Left-to-right square-and-select-multiply driven by a precomputed bit
    array; a single ``fori_loop`` over 256 iterations keeps the HLO to two
    ``mont_mul`` call sites.
    """
    bits = np.array(
        [(exponent >> (BITS - 1 - i)) & 1 for i in range(BITS)], dtype=np.uint32
    )
    bits_d = jnp.asarray(bits)
    one = mont_one(spec)

    def body(i, acc):
        acc = mont_sqr(spec, acc)
        mul = mont_mul(spec, acc, a)
        return jnp.where(bits_d[i] == 1, mul, acc)

    return lax.fori_loop(0, BITS, body, one)


def mont_inv(spec: FieldSpec, a: jnp.ndarray) -> jnp.ndarray:
    """Fermat inversion a^(m-2) — modulus must be prime."""
    return mont_pow_static(spec, a, spec.modulus_int - 2)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0)


def limbs_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b)
