"""256-bit modular arithmetic as fixed-width limb tuples for TPU.

XLA on TPU has no big-int and no native 64-bit integer multiply, so field
elements are represented as **16 little-endian limbs of 16 bits each**, one
uint32 *scalar* per limb (a tuple of 16 tracers).  Under ``jax.vmap`` each
limb becomes a dense [B] lane vector — every operation below is pure
elementwise dataflow with zero gathers/slices, which is exactly what XLA's
fusion wants: a whole Montgomery multiply compiles to straight-line fused
vector code.

Design points, measured on a real TPU chip (v5e) against alternatives:

- **Lazy-carry CIOS Montgomery multiply** (:func:`mont_mul`): the classic
  word-by-word CIOS loop, but with *no* per-iteration carry propagation.
  Column accumulators receive at most four 16-bit addends per iteration, so
  over 16 iterations they stay < 2^22 — far from uint32 overflow — and a
  single carry pass at the end suffices.  The low word needed for the
  reduction quotient is exact at every step because column 0 never has
  un-received carries.  This cut the sequential dependency depth ~10x vs
  an eager-carry loop version.
- **Statically indexed**: no ``dynamic_slice``; the product schedule is a
  Python loop at trace time.  The default "block" lowering runs the outer
  CIOS loop as a 4-step ``lax.scan`` of 4 unrolled iterations each —
  measured faster than the fully unrolled straight-line form on v5e
  (122.8k vs 102.8k verifies/s at batch 4096) at ~10x less compile time;
  the fully-unrolled and per-iteration-scan forms remain as selectable
  lowerings (see :mod:`minbft_tpu.ops.lowering`).
- Long-running control flow (the 256-bit scalar ladder, Fermat powering)
  stays in ``lax.fori_loop`` *outside* this module so the HLO stays small.

This replaces the serial host big-int arithmetic of the reference (Go
crypto/ecdsa under sample/authentication/crypto.go:79-89 and the SGX
enclave's ECDSA in usig/sgx/enclave/usig.c:36-76) with a batchable
data-parallel substrate.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Tuple

import numpy as np

import jax.numpy as jnp
from jax import lax

NLIMBS = 16
LIMB_BITS = 16
MASK = np.uint32(0xFFFF)
BITS = NLIMBS * LIMB_BITS  # 256

# A field element: 16 uint32 "scalars" (|| [B] vectors under vmap).
Fe = Tuple[jnp.ndarray, ...]


# ---------------------------------------------------------------------------
# Host-side conversions (Python int <-> limbs).


def to_limbs(x: int) -> np.ndarray:
    """Python int (< 2^256) -> [16] uint32 little-endian 16-bit limbs."""
    if not 0 <= x < (1 << BITS):
        raise ValueError("value out of 256-bit range")
    return np.array(
        [(x >> (LIMB_BITS * i)) & 0xFFFF for i in range(NLIMBS)], dtype=np.uint32
    )


def from_limbs(limbs) -> int:
    """[16] uint32 limb vector (or Fe tuple) -> Python int."""
    if isinstance(limbs, tuple):
        limbs = np.stack([np.asarray(v) for v in limbs], axis=-1)
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(arr[..., i]) << (LIMB_BITS * i) for i in range(NLIMBS))


# --- whole-batch conversions (the vectorized host-prep substrate) ----------

# Escape hatch shared by the p256/ed25519 prep paths: route prepare_batch
# to the per-item scalar oracle (checked at call time, so tests can flip
# it without re-importing).
SCALAR_PREP = os.environ.get("MINBFT_SCALAR_PREP", "") == "1"


def staging_out(out, bucket: int, cols: int, n: int) -> np.ndarray:
    """Validate (or allocate) a [bucket, cols] u16 staging buffer for a
    fused prepare_packed write — the one staging-buffer contract shared
    by the p256 and ed25519 packers."""
    if n > bucket:
        raise ValueError(f"batch {n} exceeds bucket {bucket}")
    if out is None:
        return np.empty((bucket, cols), np.uint16)
    if out.shape != (bucket, cols) or out.dtype != np.uint16:
        raise ValueError(
            f"staging buffer {out.shape}/{out.dtype} != "
            f"({bucket}, {cols})/uint16"
        )
    return out
#
# The 16-bit little-endian limb layout IS numpy's '<u2' byte layout, so a
# whole batch converts with one ``frombuffer`` over the concatenated
# little-endian int bytes — no per-limb Python.  The per-item
# ``to_limbs`` list comprehension costs ~2.5us/value; the batch form is
# ~50x cheaper per value at B=16384 and is what feeds the prepare_batch
# staging buffers (ops/p256.py, ops/ed25519.py).


def to_limbs_batch(vals) -> np.ndarray:
    """Iterable of B Python ints (each in [0, 2^256)) -> [B, 16] uint32."""
    vals = vals if isinstance(vals, (list, tuple)) else list(vals)
    if not vals:
        return np.zeros((0, NLIMBS), np.uint32)
    buf = b"".join([v.to_bytes(32, "little") for v in vals])
    return (
        np.frombuffer(buf, dtype="<u2")
        .reshape(len(vals), NLIMBS)
        .astype(np.uint32)
    )


def from_limbs_batch(rows) -> list:
    """[B, 16] limb rows (any int dtype, values < 2^16) -> list of B ints."""
    arr = np.ascontiguousarray(np.asarray(rows), dtype="<u2")
    return [int.from_bytes(row.tobytes(), "little") for row in arr]


def limb_words(rows: np.ndarray) -> np.ndarray:
    """[B, 16] limb rows (values < 2^16) -> [B, 4] '<u8' word view.

    The comparison helpers below scan words, not limbs — 4 column passes
    instead of 16.  Zero-copy when ``rows`` is already a contiguous u16
    array (e.g. a '<u2' view of prep staging bytes)."""
    rows = np.asarray(rows)
    if rows.dtype != np.dtype("<u2"):
        rows = rows.astype("<u2")
    return np.ascontiguousarray(rows).view("<u8")


def words_of(x: int) -> np.ndarray:
    """Host constant -> [4] '<u8' little-endian words (for words_lt)."""
    return np.frombuffer(x.to_bytes(32, "little"), dtype="<u8")


def words_lt(words: np.ndarray, bound_words: np.ndarray) -> np.ndarray:
    """Vectorized 256-bit compare over [B, 4] '<u8' words -> [B] bool.

    Lexicographic scan from the most-significant word down — 4 elementwise
    column passes, no per-item Python (this is how prepare_batch turns the
    r/s/coordinate range checks into array ops)."""
    lt = np.zeros(words.shape[0], np.bool_)
    decided = np.zeros(words.shape[0], np.bool_)
    for i in (3, 2, 1, 0):
        col = words[:, i]
        b = bound_words[i]
        lt |= ~decided & (col < b)
        decided |= col != b
    return lt


def limbs_lt(rows: np.ndarray, bound: int) -> np.ndarray:
    """Vectorized 256-bit compare: [B, 16] limb rows < bound -> [B] bool."""
    return words_lt(limb_words(rows), words_of(bound))


def limbs_is_zero(rows: np.ndarray) -> np.ndarray:
    """[B, 16] limb rows == 0 -> [B] bool (vectorized)."""
    return ~limb_words(rows).any(axis=1)


def limbs_add_const(rows: np.ndarray, c: int) -> np.ndarray:
    """(rows + c) mod 2^256 -> [B, 16] uint32, limbwise with vectorized
    carry propagation.

    Used for the ECDSA second x-candidate r2 = r + n: callers must gate on
    a no-overflow condition (e.g. r < p - n) — the mod-2^256 wrap is not
    meaningful arithmetic."""
    cl = to_limbs(c)
    rows = np.asarray(rows, dtype=np.uint32)
    out = np.empty_like(rows)
    carry = np.zeros(rows.shape[0], np.uint32)
    for i in range(NLIMBS):
        s = rows[:, i] + cl[i] + carry
        out[:, i] = s & MASK
        carry = s >> np.uint32(LIMB_BITS)
    return out


def fe_from_array(x: jnp.ndarray) -> Fe:
    """[..., 16] uint32 array -> limb tuple (unstack the trailing axis)."""
    return tuple(x[..., i] for i in range(NLIMBS))


def fe_to_array(a: Fe) -> jnp.ndarray:
    """Limb tuple -> [..., 16] uint32 array."""
    return jnp.stack(a, axis=-1)


def fe_const(x: int) -> Tuple[np.uint32, ...]:
    """Host constant as a tuple of uint32 scalars (broadcasts under vmap)."""
    return tuple(np.uint32(int(v)) for v in to_limbs(x))


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """Constants for Montgomery arithmetic mod a fixed 256-bit modulus.

    Built host-side once per field (P-256 coordinate field, P-256 group
    order, curve25519 field, ...) and closed over by the jitted kernels.
    """

    modulus_int: int
    modulus: Tuple[np.uint32, ...]
    m_prime: np.uint32  # -modulus^-1 mod 2^16
    r_mod: Tuple[np.uint32, ...]  # R mod m    (Montgomery one)
    r2_mod: Tuple[np.uint32, ...]  # R^2 mod m  (to-Montgomery factor)

    @staticmethod
    def make(modulus: int) -> "FieldSpec":
        r = 1 << BITS
        m_inv = pow(modulus, -1, 1 << LIMB_BITS)
        return FieldSpec(
            modulus_int=modulus,
            modulus=fe_const(modulus),
            m_prime=np.uint32((-m_inv) % (1 << LIMB_BITS)),
            r_mod=fe_const(r % modulus),
            r2_mod=fe_const((r * r) % modulus),
        )


# ---------------------------------------------------------------------------
# Elementwise helpers.


def fe_select(c: jnp.ndarray, a: Fe, b: Fe) -> Fe:
    """where(c, a, b) limbwise; c is a bool scalar ([B] under vmap)."""
    return tuple(jnp.where(c, x, y) for x, y in zip(a, b))


def fe_eq(a: Fe, b: Fe) -> jnp.ndarray:
    acc = a[0] == b[0]
    for i in range(1, NLIMBS):
        acc = acc & (a[i] == b[i])
    return acc


def fe_is_zero(a: Fe) -> jnp.ndarray:
    acc = a[0] == 0
    for i in range(1, NLIMBS):
        acc = acc & (a[i] == 0)
    return acc


def fe_zero() -> Fe:
    return tuple(jnp.uint32(0) for _ in range(NLIMBS))


def _cond_sub(m: Tuple[np.uint32, ...], t: list, t_hi: jnp.ndarray) -> Fe:
    """Given fully-carried t (16 limbs + small high part t_hi), return
    t - m if t >= m else t.  Branch-free."""
    borrow = jnp.uint32(0)
    d = []
    for j in range(NLIMBS):
        x = t[j] - m[j] - borrow
        borrow = (x >> np.uint32(31)) & np.uint32(1)
        d.append(x & MASK)
    ge = t_hi >= borrow  # high part absorbs the final borrow iff t >= m
    return tuple(jnp.where(ge, d[j], t[j]) for j in range(NLIMBS))


# ---------------------------------------------------------------------------
# Modular add/sub (inputs fully reduced < m, outputs fully reduced < m).


def add_mod(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    s = [a[j] + b[j] for j in range(NLIMBS)]
    carry = jnp.uint32(0)
    for j in range(NLIMBS):
        s[j] = s[j] + carry
        carry = s[j] >> LIMB_BITS
        s[j] = s[j] & MASK
    return _cond_sub(spec.modulus, s, carry)


def sub_mod(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    # a + m - b, then conditionally subtract m. a+m never underflows b.
    m = spec.modulus
    s = [a[j] + m[j] for j in range(NLIMBS)]
    carry = jnp.uint32(0)
    for j in range(NLIMBS):
        s[j] = s[j] + carry
        carry = s[j] >> LIMB_BITS
        s[j] = s[j] & MASK
    borrow = jnp.uint32(0)
    for j in range(NLIMBS):
        x = s[j] - b[j] - borrow
        borrow = (x >> np.uint32(31)) & np.uint32(1)
        s[j] = x & MASK
    return _cond_sub(spec.modulus, s, carry - borrow)


# ---------------------------------------------------------------------------
# Montgomery multiplication (lazy-carry CIOS).
#
# Three lowerings of the *same* arithmetic (measured trade-offs in
# ops/lowering.py):
#
# - ``block`` (TPU default): the outer CIOS loop as a 4-step ``lax.scan``
#   of 4 unrolled iterations each — fastest measured on v5e AND ~10x
#   cheaper to compile than full unrolling.
# - ``unrolled``: the 16-iteration loop fully unrolled at trace time into
#   one straight-line program.  XLA compile time explodes with basic-block
#   size (minutes for the full ladder graph), and on v5e the giant block
#   also schedules worse than ``block``.
# - ``scan``/``loop`` (CPU default): the outer loop as a 16-step
#   ``lax.scan`` (~70-op body).  Compiles instantly everywhere; the
#   per-step fusion barrier costs throughput on TPU.
#
# Dispatch is by backend at trace time, overridable with ``set_mode`` (the
# equivalence of the three lowerings is itself under test).


from .lowering import mode as _lowering_mode
from .lowering import set_mode as _set_lowering_mode


def set_mode(mode):
    """Force a lowering mode (None = auto: 'block' off-CPU, 'loop' on CPU).

    Deprecated alias for :func:`minbft_tpu.ops.lowering.set_mode` ('scan'
    maps to 'loop')."""
    _set_lowering_mode("loop" if mode == "scan" else mode)


def mont_mul(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    """Montgomery product a*b*R^-1 mod m (R = 2^256), result < m.

    Lazy carries: column accumulators grow by at most 4 * 2^16 per
    iteration (two product halves from a_i*b and two from u*m), so after 16
    iterations every accumulator is < 2^22 — uint32 never overflows and no
    intra-loop carry propagation is needed.  Column 0's low 16 bits are
    always exact (carries only flow upward), so the reduction quotient
    u = t0 * m' mod 2^16 is computed directly from the lazy accumulator.
    """
    m = _lowering_mode()
    if m == "unrolled":
        return _mont_mul_unrolled(spec, a, b)
    if m == "block":
        return _mont_mul_block(spec, a, b)
    return _mont_mul_scan(spec, a, b)


def _mont_mul_unrolled(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    m = spec.modulus
    mp = spec.m_prime
    t = [jnp.uint32(0)] * (NLIMBS + 2)
    for i in range(NLIMBS):
        ai = a[i]
        for j in range(NLIMBS):
            p = ai * b[j]  # exact: 16-bit x 16-bit in uint32
            t[j] = t[j] + (p & MASK)
            t[j + 1] = t[j + 1] + (p >> LIMB_BITS)
        u = ((t[0] & MASK) * mp) & MASK
        for j in range(NLIMBS):
            q = u * m[j]
            t[j] = t[j] + (q & MASK)
            t[j + 1] = t[j + 1] + (q >> LIMB_BITS)
        c0 = t[0] >> LIMB_BITS  # low 16 bits are zero by construction of u
        t = t[1:] + [jnp.uint32(0)]
        t[0] = t[0] + c0
    return _mont_finish(m, t)


def _mont_mul_scan(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    m = spec.modulus
    mp = spec.m_prime
    zero = jnp.zeros_like(b[0])

    def step(t, ai):
        t = list(t)
        for j in range(NLIMBS):
            p = ai * b[j]
            t[j] = t[j] + (p & MASK)
            t[j + 1] = t[j + 1] + (p >> LIMB_BITS)
        u = ((t[0] & MASK) * mp) & MASK
        for j in range(NLIMBS):
            q = u * m[j]
            t[j] = t[j] + (q & MASK)
            t[j + 1] = t[j + 1] + (q >> LIMB_BITS)
        c0 = t[0] >> LIMB_BITS
        t = t[1:] + [jnp.zeros_like(t[0])]
        t[0] = t[0] + c0
        return tuple(t), None

    t0 = (zero,) * (NLIMBS + 2)
    t, _ = lax.scan(step, t0, jnp.stack(a))
    return _mont_finish(m, list(t))


_BLOCK = 4


def _mont_mul_block(spec: FieldSpec, a: Fe, b: Fe) -> Fe:
    """CIOS with the outer loop as a 4-step ``lax.scan`` whose body unrolls
    4 iterations — same arithmetic as the other lowerings, ~4x smaller HLO
    than ``unrolled`` (faster compile) with 4x fewer fusion barriers than
    ``loop`` (better TPU throughput)."""
    m = spec.modulus
    mp = spec.m_prime
    zero = jnp.zeros_like(b[0] + jnp.uint32(0))

    # Stacking the limbs gives [16, ...] (scalar-shaped limbs under vmap,
    # or explicitly batched [B] limbs); the scan consumes rows of 4.
    a_arr = jnp.stack([jnp.asarray(x) + zero for x in a])
    a_blocks = a_arr.reshape((NLIMBS // _BLOCK, _BLOCK) + a_arr.shape[1:])

    def step(t, ablk):
        t = list(t)
        for k in range(_BLOCK):
            ai = ablk[k]
            for j in range(NLIMBS):
                p = ai * b[j]
                t[j] = t[j] + (p & MASK)
                t[j + 1] = t[j + 1] + (p >> LIMB_BITS)
            u = ((t[0] & MASK) * mp) & MASK
            for j in range(NLIMBS):
                q = u * m[j]
                t[j] = t[j] + (q & MASK)
                t[j + 1] = t[j + 1] + (q >> LIMB_BITS)
            c0 = t[0] >> LIMB_BITS
            t = t[1:] + [jnp.zeros_like(t[0])]
            t[0] = t[0] + c0
        return tuple(t), None

    t0 = (zero,) * (NLIMBS + 2)
    t, _ = lax.scan(step, t0, a_blocks)
    return _mont_finish(m, list(t))


def _mont_finish(m, t: list) -> Fe:
    # Single full carry pass, then one conditional subtract (result < 2m).
    for j in range(NLIMBS + 1):
        c = t[j] >> LIMB_BITS
        t[j] = t[j] & MASK
        t[j + 1] = t[j + 1] + c
    t_hi = t[NLIMBS] + (t[NLIMBS + 1] << LIMB_BITS)
    return _cond_sub(m, t[:NLIMBS], t_hi)


def mont_sqr(spec: FieldSpec, a: Fe) -> Fe:
    return mont_mul(spec, a, a)


def to_mont(spec: FieldSpec, a: Fe) -> Fe:
    """a -> a*R mod m."""
    return mont_mul(spec, a, spec.r2_mod)


def from_mont(spec: FieldSpec, a: Fe) -> Fe:
    """a*R -> a mod m (multiply by 1)."""
    one = fe_const(1)
    return mont_mul(spec, a, one)


def mont_one(spec: FieldSpec) -> Fe:
    return spec.r_mod


# ---------------------------------------------------------------------------
# Exponentiation / inversion.


def mont_pow_static(spec: FieldSpec, a: Fe, exponent: int) -> Fe:
    """a^exponent (Montgomery domain) for a *host-static* exponent.

    Square-and-select-multiply inside one ``fori_loop`` (256 iterations, two
    mont_mul call sites) — the ladder itself must stay a loop to keep the
    HLO small; only the field ops inside it are unrolled.
    """
    bits = np.array(
        [(exponent >> (BITS - 1 - i)) & 1 for i in range(BITS)], dtype=np.uint32
    )
    bits_d = jnp.asarray(bits)

    def body(i, acc):
        acc = mont_sqr(spec, acc)
        mul = mont_mul(spec, acc, a)
        return fe_select(bits_d[i] == 1, mul, acc)

    return lax.fori_loop(0, BITS, body, mont_one(spec))


def mont_inv(spec: FieldSpec, a: Fe) -> Fe:
    """Fermat inversion a^(m-2) — modulus must be prime."""
    return mont_pow_static(spec, a, spec.modulus_int - 2)




def batch_inv_host(vals, mod):
    """Host-side Montgomery batch inversion: one ``pow`` + 3(B-1) mults
    for B inverses (a host pow costs ~25us; a mult ~0.1us).  All vals
    must be nonzero.  Shared by the P-256/Ed25519 sign paths and the
    ECDSA verify prep (one s^-1 sweep per batch in p256.prepare_batch)."""
    n = len(vals)
    if n == 0:
        return []
    prefix = [1] * (n + 1)
    p = 1
    for i, v in enumerate(vals):
        p = p * v % mod
        prefix[i + 1] = p
    inv_total = pow(p, -1, mod)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_total % mod
        inv_total = inv_total * vals[i] % mod
    return out
