"""SHA-256 as a JAX/XLA kernel.

The reference performs all hashing/signing serially on the CPU (Go
crypto/sha256 inside usig/sgx/sgx-usig.go:52-62 and
sample/authentication/crypto.go:103-126).  Here the compression function is
expressed in pure ``uint32`` jax.numpy ops so it can be ``vmap``-ped over a
batch axis and fused by XLA onto the TPU VPU: thousands of independent
HMAC/UI-certificate checks become one data-parallel kernel launch
(see :mod:`minbft_tpu.ops.hmac_sha256` and
:mod:`minbft_tpu.parallel.engine`).

Design notes (TPU-first):
- All shapes are static.  The protocol layer hashes variable-length message
  bytes down to 32-byte digests on the host
  (:func:`minbft_tpu.messages.authen_digest`); every on-device hash input is
  a fixed number of 64-byte blocks, so there is exactly one compiled kernel
  per (batch-bucket, block-count) pair.
- The 64-round loop runs as ``lax.fori_loop`` with the message schedule
  computed on the fly from a rolling 16-word window — small XLA graph, no
  64×-unrolled HLO, and no dynamically indexed 64-entry buffer.
- Scalar-shaped core + ``jax.vmap`` = the batch dimension maps onto VPU
  lanes; nothing here prevents further sharding of the batch axis across a
  device mesh (see :mod:`minbft_tpu.parallel.mesh`).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# Round constants (FIPS 180-4 §4.2.2).
_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

IV = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression: ``state`` [8] uint32, ``block`` [16] uint32
    (big-endian words) → new state [8] uint32.

    Scalar-shaped; batch via ``jax.vmap``.  Two lowerings of the same round
    function (see :mod:`minbft_tpu.ops.lowering`): fully unrolled 64 rounds
    for TPU fusion, a ``fori_loop`` with a rolling schedule window for the
    CPU SIM-mode backend.
    """
    from .lowering import mode

    # SHA-256 has two lowerings; the CIOS-specific "block" mode maps to the
    # unrolled form here (64 rounds of cheap ops compile fast regardless).
    if mode() != "loop":
        return _compress_unrolled(state, block)
    return _compress_loop(state, block)


def _round(av, wt, kt):
    a, b, c, d, e, f, g, h = av
    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + s1 + ch + kt + wt
    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    t2 = s0 + maj
    return (t1 + t2, a, b, c, d + t1, e, f, g)


def _compress_unrolled(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    block = block.astype(jnp.uint32)
    w = [block[i] for i in range(16)]
    for t in range(16, 64):
        sig0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint32(3))
        sig1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint32(10))
        w.append(w[t - 16] + sig0 + w[t - 7] + sig1)
    av = tuple(state[i] for i in range(8))
    for t in range(64):
        av = _round(av, w[t], np.uint32(_K[t]))
    return state + jnp.stack(av)


def _compress_loop(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    k = jnp.asarray(_K)

    def round_body(t, carry):
        av = carry[:8]
        w = carry[8]
        # w is the rolling 16-word schedule window; w[0] == W[t].
        av = _round(av, w[0], k[t])
        # Extend the schedule: W[t+16] from the current window.
        sig0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ (w[1] >> np.uint32(3))
        sig1 = _rotr(w[14], 17) ^ _rotr(w[14], 19) ^ (w[14] >> np.uint32(10))
        w_next = w[0] + sig0 + w[9] + sig1
        w = jnp.concatenate([w[1:], w_next[None]])
        return av + (w,)

    init = tuple(state[i] for i in range(8)) + (block.astype(jnp.uint32),)
    out = lax.fori_loop(0, 64, round_body, init)
    return state + jnp.stack(out[:8])


def sha256_fixed(blocks: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 over a fixed number of pre-padded blocks.

    ``blocks``: [nblocks, 16] uint32 → digest [8] uint32.  ``nblocks`` is a
    static (trace-time) constant, so this unrolls to a short chain of
    compressions — ideal for the fixed-layout inputs used by the protocol.
    """
    state = jnp.asarray(IV)
    for i in range(blocks.shape[0]):
        state = compress(state, blocks[i])
    return state


# Batched variants.
compress_batch = jax.vmap(compress)
sha256_fixed_batch = jax.vmap(sha256_fixed)


# ---------------------------------------------------------------------------
# Host-side helpers (numpy) for padding and byte/word conversion.


def pad_message(data: bytes) -> np.ndarray:
    """FIPS 180-4 padding → [nblocks, 16] uint32 big-endian words."""
    bitlen = len(data) * 8
    data = data + b"\x80"
    data += b"\x00" * ((56 - len(data)) % 64)
    data += bitlen.to_bytes(8, "big")
    words = np.frombuffer(data, dtype=">u4").astype(np.uint32)
    return words.reshape(-1, 16)


def words_to_bytes(words: np.ndarray) -> bytes:
    """uint32 big-endian words → bytes."""
    return np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()


def bytes_to_words(data: bytes) -> np.ndarray:
    """bytes (multiple of 4) → uint32 big-endian words."""
    if len(data) % 4:
        raise ValueError("length must be a multiple of 4")
    return np.frombuffer(data, dtype=">u4").astype(np.uint32)


def sha256_host(data: bytes) -> bytes:
    """Full SHA-256 of arbitrary bytes through the JAX kernel (used for
    differential testing against hashlib)."""
    digest = sha256_fixed(jnp.asarray(pad_message(data)))
    return words_to_bytes(np.asarray(digest))
