"""Handlers-level unit tests with injected fakes — no cluster.

Covers the graph-level invariants the reference pins in
core/message-handling_test.go: the generated-message UI-ordering invariant
(TestMakeGeneratedMessageHandlerConcurrent, message-handling_test.go:604),
the HELLO handler's broadcast+unicast replay (makeHelloHandler,
core/message-handling.go:316-350), dispatch branch errors, and the
view-lease guarantee that a message captured in view v never applies in
view v+1.
"""

import asyncio
import contextlib

import pytest

from minbft_tpu import api
from minbft_tpu.core.internal.clientstate import ClientStates
from minbft_tpu.core.internal.messagelog import MessageLog
from minbft_tpu.core.message_handling import Handlers, PeerStreamHandler
from minbft_tpu.messages import (
    UI,
    Commit,
    Hello,
    Prepare,
    ReqViewChange,
    Request,
    marshal,
    split_multi,
    unmarshal,
)
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.usig import ui_to_bytes


class _Auth(api.Authenticator):
    """USIG role issues sequential counters; everything else is a fixed tag
    that always verifies."""

    def __init__(self):
        self.counter = 0

    def generate_message_authen_tag(self, role, data, audience=-1):
        if role is api.AuthenticationRole.USIG:
            self.counter += 1
            return ui_to_bytes(UI(counter=self.counter, cert=b"cert"))
        return b"sig"

    async def verify_message_authen_tag(self, role, peer_id, data, tag):
        return None


class _Consumer(api.RequestConsumer):
    async def deliver(self, operation: bytes) -> bytes:
        return b"ok:" + operation

    def state_digest(self) -> bytes:
        return b""


def _handlers(replica_id=0, n=4, f=1):
    unicast = {p: MessageLog() for p in range(n) if p != replica_id}
    h = Handlers(
        replica_id,
        n,
        f,
        SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=60.0),
        _Auth(),
        _Consumer(),
        MessageLog(),
        unicast,
        ClientStates(),
    )
    return h


def _req(client_id=1, seq=1):
    return Request(client_id=client_id, seq=seq, operation=b"op")


def _prepare(cv=1, view=0, primary=None):
    primary = view % 4 if primary is None else primary
    return Prepare(
        replica_id=primary, view=view, request=_req(seq=cv), ui=UI(counter=cv)
    )


def test_generated_ui_counters_match_log_order():
    """UI assignment is serialized under the UI lock, so certified own
    messages land in the broadcast log in counter order even when generated
    concurrently (reference TestMakeGeneratedMessageHandlerConcurrent)."""

    async def scenario():
        h = _handlers()
        msgs = [
            Prepare(replica_id=0, view=0, request=_req(seq=i + 1))
            for i in range(64)
        ]
        await asyncio.gather(*[h.handle_generated(m) for m in msgs])
        return [m.ui.counter for m in h.message_log.snapshot()]

    counters = asyncio.run(scenario())
    assert counters == list(range(1, 65))


def test_generated_uncertified_message_gets_no_ui():
    async def scenario():
        h = _handlers()
        rvc = ReqViewChange(replica_id=0, new_view=1)
        await h.handle_generated(rvc)
        return h.message_log.snapshot(), rvc

    log, rvc = asyncio.run(scenario())
    assert log == [rvc]
    assert getattr(rvc, "ui", None) is None


def test_validate_dispatch_rejects_unexpected_kind():
    async def scenario():
        h = _handlers()
        with pytest.raises(api.AuthenticationError):
            await h.validate_message(Hello(replica_id=1))
        with pytest.raises(ValueError):
            await h.process_message(Hello(replica_id=1))
        # ReqViewChange processing (beyond the reference's refusal):
        # fresh demands are tallied, stale ones dropped.
        rvc = ReqViewChange(replica_id=1, new_view=1)
        assert await h.process_message(rvc) is True
        assert h.view_change_state.req_votes[1] == {1}
        stale = ReqViewChange(replica_id=2, new_view=0)
        assert await h.process_message(stale) is False
        return True

    assert asyncio.run(scenario())


def test_client_stream_rejects_non_request():
    async def scenario():
        h = _handlers()
        with pytest.raises(api.AuthenticationError):
            await h.handle_client_message(Hello(replica_id=1))
        return True

    assert asyncio.run(scenario())


def test_peer_message_stale_view_dropped():
    async def scenario():
        h = _handlers(replica_id=2)
        # A PREPARE from view 1's primary while this replica is in view 0:
        # UI capture succeeds (it is a well-formed new message) but the
        # view check under the lease refuses to apply it.
        stale = _prepare(cv=1, view=1, primary=1)
        return await h._process_peer_message(stale)

    assert asyncio.run(scenario()) is False


def test_view_advance_between_capture_and_apply_drops_message():
    """The VERDICT-flagged race: processing suspends between UI capture and
    apply; if the view advances in that window the message must be dropped,
    not applied in the new view."""

    async def scenario():
        h = _handlers(replica_id=2)
        applied = []

        async def record_apply(prepare):
            applied.append(prepare)

        h.apply_prepare = record_apply

        gate = asyncio.Event()
        real_capture = h.capture_ui

        async def blocking_capture(msg):
            ok = await real_capture(msg)
            await gate.wait()  # suspend between capture and the view lease
            return ok

        h.capture_ui = blocking_capture

        msg = _prepare(cv=1, view=0, primary=0)
        task = asyncio.ensure_future(h._process_peer_message(msg))
        await asyncio.sleep(0)  # let it capture and park on the gate

        assert await h.view_state.advance_expected_view(1)
        assert await h.view_state.advance_current_view(1)
        gate.set()
        result = await task
        return result, applied

    result, applied = asyncio.run(scenario())
    assert result is False and applied == []


def test_view_advance_waits_for_inflight_apply():
    """The inverse guarantee: a message already holding the view lease
    finishes applying in its view before the advance completes."""

    async def scenario():
        h = _handlers(replica_id=2)
        release = asyncio.Event()
        applied = []

        async def slow_apply(prepare):
            await release.wait()
            applied.append(prepare)

        h.apply_prepare = slow_apply
        msg = _prepare(cv=1, view=0, primary=0)
        task = asyncio.ensure_future(h._process_peer_message(msg))
        await asyncio.sleep(0)  # in the lease, parked in slow_apply

        await h.view_state.advance_expected_view(1)
        adv = asyncio.ensure_future(h.view_state.advance_current_view(1))
        await asyncio.sleep(0)
        assert not adv.done()  # blocked on the read lease
        release.set()
        assert await adv is True
        return await task, applied

    result, applied = asyncio.run(scenario())
    assert result is True and len(applied) == 1


def test_hello_handler_replays_broadcast_and_unicast():
    """After HELLO from peer p the stream carries the broadcast log plus
    p's unicast log (reference makeHelloHandler,
    core/message-handling.go:316-350)."""

    async def scenario():
        h = _handlers(replica_id=0)
        p = _prepare(cv=1)
        h.message_log.append(p)
        forwarded = _req(client_id=5, seq=9)
        h.unicast_logs[1].append(forwarded)

        async def incoming():
            yield marshal(Hello(replica_id=1))
            await asyncio.sleep(30)  # keep the stream open

        handler = PeerStreamHandler(h)
        out = handler.handle_message_stream(incoming())
        got = []
        while len(got) < 2:
            # frames may arrive coalesced (pack_multi) — split first
            data = await asyncio.wait_for(out.__anext__(), 5)
            got.extend(unmarshal(fr) for fr in split_multi(data))
        await out.aclose()
        return p, forwarded, got

    p, forwarded, got = asyncio.run(scenario())
    # two concurrent log pumps: order across logs is unspecified
    kinds = {type(m) for m in got}
    assert kinds == {Prepare, Request}
    for m in got:
        if isinstance(m, Prepare):
            assert m.ui.counter == p.ui.counter
        else:
            assert (m.client_id, m.seq) == (5, 9)


def test_hello_resume_counter_skips_captured_prefix():
    """A HELLO carrying ``resume_counter`` resumes the broadcast replay
    at that UI counter: certified entries below it are skipped (the
    subscriber already captured them), while non-certified kinds
    (REQ-VIEW-CHANGE here) always replay.  This is what makes a redial
    through a lossy link heal a gap with one short tail replay instead
    of re-traversing the whole log."""

    async def scenario():
        h = _handlers(replica_id=0)
        for cv in (1, 2, 3, 4):
            h.message_log.append(_prepare(cv=cv))
        rvc = ReqViewChange(replica_id=0, new_view=1)
        h.message_log.append(rvc)

        async def incoming():
            yield marshal(Hello(replica_id=1, resume_counter=4))
            await asyncio.sleep(30)  # keep the stream open

        handler = PeerStreamHandler(h)
        out = handler.handle_message_stream(incoming())
        got = []
        while sum(isinstance(m, Prepare) for m in got) < 1 or not any(
            isinstance(m, ReqViewChange) for m in got
        ):
            data = await asyncio.wait_for(out.__anext__(), 5)
            got.extend(unmarshal(fr) for fr in split_multi(data))
        # give the pump a tick to deliver anything else it wrongly kept
        with contextlib.suppress(asyncio.TimeoutError):
            data = await asyncio.wait_for(out.__anext__(), 0.2)
            got.extend(unmarshal(fr) for fr in split_multi(data))
        await out.aclose()
        return got

    got = asyncio.run(scenario())
    prepares = [m for m in got if isinstance(m, Prepare)]
    assert [p.ui.counter for p in prepares] == [4]  # 1..3 skipped
    assert any(isinstance(m, ReqViewChange) for m in got)


def test_hello_resume_counter_is_signed():
    """resume_counter rides the HELLO's signed bytes: an in-path attacker
    must not be able to inflate it (starving the subscriber of entries it
    still needs) without breaking the signature."""
    from minbft_tpu.messages.authen import authen_bytes

    a = authen_bytes(Hello(replica_id=1, resume_counter=0))
    b = authen_bytes(Hello(replica_id=1, resume_counter=7))
    assert a != b
    m = unmarshal(marshal(Hello(replica_id=2, resume_counter=123, signature=b"s")))
    assert m.resume_counter == 123 and m.replica_id == 2


def test_deviating_reproposal_refused_and_view_change_demanded():
    """A new primary whose first PREPARE does not match the agreed
    re-proposal set S is refused, and the replica broadcasts a demand for
    the next view (the Byzantine-new-primary defense, wired end to end
    through _process_peer_message)."""

    async def scenario():
        h = _handlers(replica_id=2)
        # replica 2 entered view 1 with one expected re-proposal batch
        await h.view_state.advance_expected_view(1)
        await h.view_state.advance_current_view(1)
        expected = Prepare(replica_id=1, view=1, request=_req(client_id=9, seq=1))
        from minbft_tpu.core.viewchange import batch_key

        h.view_change_state.arm_reproposals(1, [batch_key(expected)])

        applied = []

        async def record_apply(prepare):
            applied.append(prepare)

        h.apply_prepare = record_apply

        # the (faulty) new primary proposes a different request first
        deviating = Prepare(
            replica_id=1, view=1, request=_req(client_id=5, seq=7),
            ui=UI(counter=1),
        )
        assert await h._process_peer_message(deviating) is False
        assert applied == []
        demands = [
            m for m in h.message_log.snapshot() if isinstance(m, ReqViewChange)
        ]
        assert [d.new_view for d in demands] == [2]

        # the honest re-proposal (next counter) is accepted
        honest = Prepare(
            replica_id=1, view=1, requests=expected.requests, ui=UI(counter=2)
        )
        assert await h._process_peer_message(honest) is True
        assert applied == [honest]
        return True

    assert asyncio.run(scenario())


def test_peer_stream_requires_hello_first():
    async def scenario():
        h = _handlers(replica_id=0)

        async def incoming():
            yield marshal(_req())

        handler = PeerStreamHandler(h)
        out = handler.handle_message_stream(incoming())
        with pytest.raises(api.AuthenticationError):
            await out.__anext__()
        return True

    assert asyncio.run(scenario())


def test_certified_message_after_peers_view_change_not_applied():
    """The round-3 advisor's safety hole: a peer that voted (sent a
    VIEW-CHANGE for v' > v) froze its log evidence in that vote, but its
    USIG counters stay gap-free — it can certify a view-v COMMIT *after*
    voting.  A straggler still in view v must not count that commitment
    toward f+1: no NEW-VIEW quorum log contains it, so the re-proposal set
    S could omit a request the straggler executed (ledger fork at f >= 2).
    The per-peer view-change bar refuses exactly these messages."""

    async def scenario():
        from minbft_tpu.messages import ViewChange

        h = _handlers(replica_id=3)
        delivered = []

        async def record_execute(req):
            delivered.append(req)

        h.commitment_collector._execute = record_execute

        # Peer 1 votes for view 1 (its USIG counter 1)...
        vc = ViewChange(replica_id=1, new_view=1, log=(), ui=UI(counter=1))
        assert await h._process_peer_message(vc) is True

        # ...then certifies a COMMIT for a view-0 prepare at counter 2.
        # The primary's PREPARE itself (peer 0, no vote) still applies —
        # only peer 1's post-vote commitment must be refused.
        prep = _prepare(cv=1, view=0, primary=0)
        late_commit = Commit(replica_id=1, prepare=prep, ui=UI(counter=2))
        assert await h._process_peer_message(late_commit) is False

        # The commitment was not counted: with f=1 the primary's prepare
        # plus one commit would have completed the quorum and executed.
        assert delivered == []

        # A commitment from a peer that has NOT voted completes the
        # quorum as usual (non-regression).
        ok_commit = Commit(replica_id=2, prepare=prep, ui=UI(counter=1))
        assert await h._process_peer_message(ok_commit) is True
        assert [r.seq for r in delivered] == [1]
        return True

    assert asyncio.run(scenario())


def test_live_stub_for_uncovered_batch_refused_without_capture():
    """The stub-blinding defense: a Byzantine primary could send one
    replica the STUB encoding of a live PREPARE (same authen bytes, same
    UI) to consume its capture slot and blind it to the batch.  A stub
    whose batch the local stable checkpoint does not cover must be
    refused WITHOUT capturing — the full version still processes."""

    async def scenario():
        from minbft_tpu.messages.authen import collection_digest

        h = _handlers(replica_id=2)
        h._viewchange_timeout = 0.0  # don't wait around in the test

        full = _prepare(cv=1, view=0, primary=0)
        stub = Prepare(
            replica_id=0,
            view=0,
            requests=(),
            ui=UI(counter=1),
            requests_digest=collection_digest(full.requests, b""),
        )
        with pytest.raises(api.AuthenticationError):
            await h._process_peer_message(stub)

        # the capture slot was NOT consumed: the full PREPARE applies
        assert await h._process_peer_message(full) is True
        return True

    assert asyncio.run(scenario())


def test_log_base_validation_rejects_unprovable_base():
    """A LOG-BASE announcement is exactly its certificate: f+1 matching
    signed checkpoints each attesting a coverage bound for the sender at
    or above the announced base.  A Byzantine peer announcing a base its
    certificate cannot prove (hiding live history from its replayed log)
    is refused."""

    async def scenario():
        from minbft_tpu.messages import Checkpoint, LogBase

        h = _handlers(replica_id=2)

        def cp(replica, bound):
            return Checkpoint(
                replica_id=replica, count=100, view=0, cv=50,
                digest=b"D" * 32, bounds=((1, bound),), signature=b"s",
            )

        good = LogBase(replica_id=1, base=10, cert=(cp(0, 10), cp(3, 12)))
        await h.validate_message(good)  # bounds 10,12 >= base 10: ok

        over = LogBase(replica_id=1, base=20, cert=(cp(0, 10), cp(3, 12)))
        with pytest.raises(api.AuthenticationError, match="coverage bounds"):
            await h.validate_message(over)

        short = LogBase(replica_id=1, base=5, cert=(cp(0, 10),))
        with pytest.raises(api.AuthenticationError, match="f\\+1"):
            await h.validate_message(short)
        return True

    assert asyncio.run(scenario())


def _pending_nv_fixture(h, anchor_count=10):
    """Stage a NEW-VIEW deferred behind a state transfer: the quorum
    anchor sits at ``anchor_count`` and the handler's transfer target is
    below it.  Returns (nv, applied) where ``applied`` records calls to
    the monkeypatched ``_apply_new_view``."""
    from minbft_tpu.messages import Checkpoint, NewView, ViewChange

    cert = (
        Checkpoint(
            replica_id=1, count=anchor_count, view=0, cv=anchor_count,
            digest=b"D" * 32, signature=b"s",
        ),
        Checkpoint(
            replica_id=2, count=anchor_count, view=0, cv=anchor_count,
            digest=b"D" * 32, signature=b"s",
        ),
    )
    vc = ViewChange(
        replica_id=1, new_view=1, log=(), log_base=anchor_count,
        checkpoint_cert=cert,
    )
    nv = NewView(replica_id=1, new_view=1, view_changes=(vc,))
    h._pending_new_view = nv
    applied = []

    async def record_apply(got):
        applied.append(got)
        return True

    h._apply_new_view = record_apply
    return nv, applied


def test_snapshot_catchup_reapplies_pending_new_view():
    """Round-4 advisor (medium): a NEW-VIEW deferred behind a state
    transfer, followed by catching up past the transfer target via
    ordinary log replay, must not strand the pending NEW-VIEW when the
    stale snapshot response is dropped — the catch-up branch re-checks
    and applies it."""

    async def scenario():
        from minbft_tpu.messages import Checkpoint, SnapshotResp

        h = _handlers(replica_id=0)
        nv, applied = _pending_nv_fixture(h, anchor_count=10)
        # transfer in flight targeting count 5; local replay has already
        # executed past BOTH the target and the NEW-VIEW anchor
        h._snapshot_expect = Checkpoint(
            replica_id=1, count=5, view=0, cv=5, digest=b"E" * 32,
        )
        h.checkpoint_emitter.count = 12
        resp = SnapshotResp(
            replica_id=2, count=5, view=0, cv=5, app_state=b"",
        )
        assert await h._process_snapshot_resp(resp) is False
        assert h._snapshot_expect is None, "stale transfer not dropped"
        assert applied == [nv], "pending NEW-VIEW stranded after catch-up"
        assert h._pending_new_view is None
        return True

    assert asyncio.run(scenario())


def test_dropped_transfer_below_anchor_retries_new_view_entry():
    """If the transfer is dropped while the replica is still BELOW the
    NEW-VIEW anchor, the pending NEW-VIEW is re-driven through
    _apply_new_view (which re-defers and re-requests the anchor state)
    rather than silently stranded with no transfer in flight."""

    async def scenario():
        from minbft_tpu.messages import Checkpoint, SnapshotResp

        h = _handlers(replica_id=0)
        nv, applied = _pending_nv_fixture(h, anchor_count=50)
        h._snapshot_expect = Checkpoint(
            replica_id=1, count=5, view=0, cv=5, digest=b"E" * 32,
        )
        h.checkpoint_emitter.count = 7  # past the target, below the anchor
        resp = SnapshotResp(
            replica_id=2, count=5, view=0, cv=5, app_state=b"",
        )
        assert await h._process_snapshot_resp(resp) is False
        assert applied == [nv], "entry not re-driven after dropped transfer"
        return True

    assert asyncio.run(scenario())


def test_batch_end_past_anchor_applies_pending_new_view():
    """Ordinary execution advancing the checkpoint count past a deferred
    NEW-VIEW's anchor applies it (as a task outside the view lease) even
    if no snapshot response ever arrives."""

    async def scenario():
        h = _handlers(replica_id=0)
        nv, applied = _pending_nv_fixture(h, anchor_count=10)
        h.checkpoint_emitter.count = 10
        # drive the collector's batch-end hook the way execution does
        await h.commitment_collector._on_batch_end(0, 10)
        # the re-check runs as its own task; let it drain
        for _ in range(10):
            if applied:
                break
            await asyncio.sleep(0.01)
        assert applied == [nv], "batch-end past anchor left NEW-VIEW pending"
        return True

    assert asyncio.run(scenario())


def test_state_transfer_rotation_includes_non_claimants():
    """ADVICE r4: the snapshot-request rotation is claimants-first but
    widens to every peer — a certificate guarantees a correct attester,
    not a live one, and any caught-up replica can serve the state."""

    async def scenario():
        from minbft_tpu.messages import Checkpoint

        h = _handlers(replica_id=0)  # peers 1, 2, 3
        cert = (
            Checkpoint(replica_id=1, count=10, view=0, cv=10, digest=b"D" * 32),
            Checkpoint(replica_id=2, count=10, view=0, cv=10, digest=b"D" * 32),
        )
        await h._request_state(cert)
        try:
            # the initial send already popped claimant 1 and cycled it to
            # the back: claimants led the rotation, and every peer —
            # claimant or not — is in it
            assert h._snapshot_sources == [2, 3, 1], (
                "rotation should be claimants-first then all other peers"
            )
        finally:
            if h._snapshot_timer is not None:
                h._snapshot_timer.cancel()
        return True

    assert asyncio.run(scenario())


def test_id_spoofing_hello_is_refused():
    """Round-4 verdict weak #6 (beats the reference, which trusts the
    HELLO id unauthenticated): a peer claiming another replica's id with
    a forged signature is refused before any unicast-log subscription;
    the genuine signed HELLO is accepted."""

    async def scenario():
        from minbft_tpu.messages.authen import authen_bytes

        h = _handlers(replica_id=0)

        # per-replica keyed auth: only the true owner can sign its id
        def gen(role, data, audience=-1):
            return b"key-of-0:" + data

        async def verify(role, peer_id, data, tag):
            if tag != b"key-of-%d:" % peer_id + data:
                raise api.AuthenticationError("bad replica signature")

        h.authenticator.generate_message_authen_tag = gen
        h.authenticator.verify_message_authen_tag = verify

        def stream_for(hello):
            async def incoming():
                yield marshal(hello)

            return PeerStreamHandler(h).handle_message_stream(incoming())

        # replica 2's key signing a HELLO that claims id 1
        spoof = Hello(replica_id=1)
        spoof.signature = b"key-of-2:" + authen_bytes(spoof)
        with pytest.raises(api.AuthenticationError):
            await stream_for(spoof).__anext__()

        # out-of-range and self ids are refused outright
        for bad_id in (7, 0):
            bad = Hello(replica_id=bad_id)
            bad.signature = b"key-of-%d:" % bad_id + authen_bytes(bad)
            with pytest.raises(api.AuthenticationError):
                await stream_for(bad).__anext__()

        # the genuine peer's HELLO passes and the log stream starts
        genuine = Hello(replica_id=1)
        genuine.signature = b"key-of-1:" + authen_bytes(genuine)
        h.message_log.append(_req())
        out = stream_for(genuine)
        got = await asyncio.wait_for(out.__anext__(), 5)
        assert unmarshal(got) == _req()
        await out.aclose()
        return True

    assert asyncio.run(scenario())


def test_malformed_multi_frame_is_dropped_not_fatal():
    """A byzantine peer sending a corrupt coalesced container must cost
    only that frame: it counts as a drop and the stream keeps processing
    later (well-formed) frames."""

    async def scenario():
        import struct

        from minbft_tpu.messages import pack_multi

        h = _handlers(replica_id=0)
        good_req = _req(client_id=1, seq=1)

        hello = Hello(replica_id=1)
        hello.signature = b"sig"

        # A container corrupt at the CONTAINER level: the first subframe's
        # length prefix claims far more bytes than exist, so the drop can
        # only come from split_multi's truncation check — an intact first
        # subframe would let per-message unmarshal failures satisfy the
        # assert vacuously.
        packed_garbage = (
            b"\xf0" + struct.pack(">I", 2) + struct.pack(">I", 10**8)
        )

        async def incoming():
            yield marshal(hello)
            yield packed_garbage
            # a proper coalesced frame still lands after the bad one
            yield pack_multi([marshal(good_req), marshal(good_req)])
            await asyncio.sleep(30)

        handler = PeerStreamHandler(h)
        out = handler.handle_message_stream(incoming())
        stream_task = asyncio.ensure_future(out.__anext__())
        for _ in range(100):
            if h.metrics.counters.get("messages_dropped", 0) >= 1 and (
                h.metrics.counters.get("prepares_sent", 0) >= 1
            ):
                break
            await asyncio.sleep(0.02)
        # deliver the cancellation BEFORE aclose — closing a generator
        # whose __anext__ is still suspended raises RuntimeError and would
        # mask the diagnostic asserts below on exactly the failure path
        stream_task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await stream_task
        await out.aclose()
        assert h.metrics.counters.get("messages_dropped", 0) >= 1, (
            "malformed container not counted as a drop"
        )
        # the later well-formed frame was processed: this replica (the
        # view-0 primary) proposed the embedded request
        assert h.metrics.counters.get("prepares_sent", 0) >= 1, (
            "stream did not survive the malformed container"
        )
        return True

    assert asyncio.run(scenario())


def test_peer_connection_reconnects_after_stream_ends():
    """A dropped peer stream (blip, peer restart) is redialed with
    backoff: peer A's messages reach B only over B's dial to A, so a
    one-shot dial would silently halve the link forever.  Each attempt
    re-sends HELLO; processing resumes on the new stream."""

    async def scenario():
        from minbft_tpu.core.message_handling import run_peer_connection

        h = _handlers(replica_id=0)
        handled = []

        async def record(msg):
            handled.append(msg)
            return True

        h.handle_peer_message = record

        hellos = []

        class FlakyHandler(api.MessageStreamHandler):
            """First two streams die after their replay; the third lives.
            Each attempt REPLAYS the peer's whole log so far (the real
            HELLO replay-then-follow semantics — which is what makes a
            mid-processing cancellation on a dying stream harmless)."""

            def __init__(self):
                self.calls = 0

            async def handle_message_stream(self, in_stream):
                self.calls += 1
                hellos.append(await in_stream.__anext__())
                for cv in range(1, self.calls + 1):
                    yield marshal(_prepare(cv=cv, view=0, primary=1))
                if self.calls >= 3:
                    await asyncio.sleep(30)  # a healthy, open stream

        done = asyncio.Event()
        fh = FlakyHandler()
        task = asyncio.ensure_future(run_peer_connection(h, 1, fh, done))
        for _ in range(200):
            if len({m.ui.counter for m in handled}) >= 3:
                break
            await asyncio.sleep(0.02)
        done.set()
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task
        assert fh.calls >= 3, f"no reconnects: {fh.calls} dials"
        assert len({m.ui.counter for m in handled}) >= 3, (
            "replayed messages after reconnect not processed"
        )
        assert h.metrics.counters.get("peer_reconnects", 0) >= 2
        # every attempt opened with a fresh signed HELLO
        for raw in hellos[:3]:
            m = unmarshal(raw)
            assert isinstance(m, Hello) and m.signature
        return True

    assert asyncio.run(scenario())


def test_peer_connection_internal_error_teardown_is_permanent():
    """A wedged local handler (non-codec, non-auth exceptions on EVERY
    message) must close the peer connection loudly and PERMANENTLY — a
    deterministic local bug redialing forever would loop without end."""

    async def scenario():
        from minbft_tpu.core.message_handling import (
            _MAX_CONSECUTIVE_INTERNAL_ERRORS,
            run_peer_connection,
        )

        h = _handlers(replica_id=0)

        async def broken(msg):
            raise RuntimeError("wedged handler")

        h.handle_peer_message = broken

        class Stream(api.MessageStreamHandler):
            def __init__(self):
                self.calls = 0

            async def handle_message_stream(self, in_stream):
                self.calls += 1
                await in_stream.__anext__()
                for cv in range(1, _MAX_CONSECUTIVE_INTERNAL_ERRORS + 9):
                    yield marshal(_prepare(cv=cv, view=0, primary=1))
                    await asyncio.sleep(0)  # let the error counter advance
                await asyncio.sleep(30)  # stream stays open: only the
                # teardown check can end the connection

        done = asyncio.Event()
        st = Stream()
        task = asyncio.ensure_future(run_peer_connection(h, 1, st, done))
        await asyncio.wait_for(task, 20)  # returns on its own: permanent
        assert st.calls == 1, f"redialed a wedged-handler teardown: {st.calls}"
        return True

    assert asyncio.run(scenario())


def test_peer_connection_internal_errors_reset_per_stream():
    """Internal-error counts must NOT accumulate across redials: two
    streams each below the teardown threshold (but above it combined)
    followed by a healthy stream must still reconnect and process — a
    transient outage spanning a redial is not a wedged handler."""

    async def scenario():
        from minbft_tpu.core.message_handling import (
            _MAX_CONSECUTIVE_INTERNAL_ERRORS,
            run_peer_connection,
        )

        h = _handlers(replica_id=0)
        handled = []
        flaky = {"on": True}

        async def sometimes_broken(msg):
            if flaky["on"]:
                raise RuntimeError("transient backend outage")
            handled.append(msg)
            return True

        h.handle_peer_message = sometimes_broken
        per_stream = _MAX_CONSECUTIVE_INTERNAL_ERRORS - 8
        # keep the guard honest if the constant is ever retuned
        assert per_stream > 0 and 2 * per_stream > _MAX_CONSECUTIVE_INTERNAL_ERRORS

        class Stream(api.MessageStreamHandler):
            def __init__(self):
                self.calls = 0

            async def handle_message_stream(self, in_stream):
                self.calls += 1
                await in_stream.__anext__()
                if self.calls <= 2:
                    for cv in range(1, per_stream + 1):
                        yield marshal(_prepare(cv=cv, view=0, primary=1))
                        await asyncio.sleep(0)
                    return  # stream dies; errors so far < threshold
                flaky["on"] = False  # outage over
                yield marshal(_prepare(cv=1, view=0, primary=1))
                await asyncio.sleep(30)

        done = asyncio.Event()
        st = Stream()
        task = asyncio.ensure_future(run_peer_connection(h, 1, st, done))
        for _ in range(300):
            if handled:
                break
            await asyncio.sleep(0.02)
        done.set()
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task
        assert st.calls >= 3, f"connection closed before the outage cleared: {st.calls}"
        assert handled, "healthy stream after the outage was never processed"
        return True

    assert asyncio.run(scenario())
