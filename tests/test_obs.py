"""Flight-recorder subsystem tests (minbft_tpu/obs, ISSUE 4): ring
semantics under concurrency, histogram correctness against the reservoir
oracle, recorder pairing, and the dump→ingest stage table."""

import asyncio
import json
import os
import random
import threading

import pytest

from minbft_tpu.obs.hist import Log2Histogram
from minbft_tpu.obs.trace import (
    CLIENT_STAGES,
    REPLICA_STAGES,
    FlightRecorder,
    MTStageRing,
    StageRing,
    dump_recorder,
    load_dumps,
    stage_table,
)
from minbft_tpu.utils.metrics import LatencyReservoir


# ---------------------------------------------------------------------------
# rings


def test_stage_ring_orders_and_wraps():
    r = StageRing(capacity=8)
    assert r.capacity == 8
    for k in range(5):
        r.push(1, k, 2, 100 + k)
    assert len(r) == 5
    assert [e[1] for e in r.snapshot()] == [0, 1, 2, 3, 4]
    for k in range(5, 20):
        r.push(1, k, 2, 100 + k)
    # wrapped: only the newest `capacity` events remain, still in order
    assert len(r) == 8
    assert [e[1] for e in r.snapshot()] == list(range(12, 20))
    assert [e[1] for e in r.snapshot(limit=3)] == [17, 18, 19]


def test_stage_ring_capacity_rounds_to_power_of_two():
    assert StageRing(capacity=100).capacity == 128
    assert MTStageRing(capacity=100).capacity == 128


def test_mt_ring_multi_producer_hammer():
    """Engine-worker-shaped hammer: several OS threads push concurrently;
    every surviving row must be internally consistent (a torn row — one
    thread's column interleaved into another's — would break the a+b==c
    invariant each producer maintains)."""
    ring = MTStageRing(capacity=1024)
    n_threads, per_thread = 8, 3000

    def producer(tid: int) -> None:
        for k in range(per_thread):
            ring.push(tid, k, tid + k, tid * 1_000_000 + k)

    threads = [
        threading.Thread(target=producer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = ring.snapshot()
    assert len(snap) == 1024  # saturated
    per_tid_last = {}
    for a, b, c, t in snap:
        assert 0 <= a < n_threads
        assert c == a + b, "torn row: columns from different producers"
        assert t == a * 1_000_000 + b
        # per-producer order is preserved (the lock serializes pushes)
        assert per_tid_last.get(a, -1) < b
        per_tid_last[a] = b


def test_mt_ring_event_loop_plus_worker_threads():
    """The deployment shape: the event loop and asyncio.to_thread
    workers (engine dispatcher stand-ins) produce into one ring while
    the loop also drains snapshots mid-flight."""

    async def run():
        ring = MTStageRing(capacity=4096)

        def worker(tid: int) -> None:
            for k in range(500):
                ring.push(tid, k, tid + k, k)

        async def loop_producer() -> None:
            for k in range(500):
                ring.push(99, k, 99 + k, k)
                if k % 50 == 0:
                    for a, b, c, _ in ring.snapshot(limit=64):
                        assert c == a + b
                    await asyncio.sleep(0)

        await asyncio.gather(
            loop_producer(),
            *[asyncio.to_thread(worker, t) for t in range(4)],
        )
        snap = ring.snapshot()
        assert len(snap) == 4 * 500 + 500  # nothing lost below capacity
        for a, b, c, _ in snap:
            assert c == a + b

    asyncio.run(run())


def test_engine_worker_ring_records_dispatch_spans():
    """The engine's _note_prep pushes dispatcher span events from worker
    threads into its MTStageRing; drain decodes queue names."""
    from minbft_tpu.parallel import BatchVerifier

    async def run():
        eng = BatchVerifier(max_batch=8, buckets=(8,))
        eng.enable_obs_ring(capacity=256)
        key, msg, mac = b"\x11" * 32, b"\x22" * 32, b"\x33" * 32
        import hashlib
        import hmac as hmac_mod

        good = hmac_mod.new(key, msg, hashlib.sha256).digest()
        oks = await asyncio.gather(
            *[eng.verify_hmac_sha256(key, msg, good) for _ in range(8)]
        )
        assert all(oks)
        events = eng.drain_obs_events()
        assert events, "no dispatcher span events recorded"
        names = {e[0] for e in events}
        assert names == {"hmac_sha256"}
        for _name, pad, prep_ns, t_ns in events:
            assert pad >= 0 and prep_ns >= 0 and t_ns > 0
        # disabled engines pay one attribute check and record nothing
        eng2 = BatchVerifier(max_batch=8, buckets=(8,))
        assert eng2.drain_obs_events() == []

    asyncio.run(run())


def test_engine_queue_wait_and_service_histograms():
    """Queue-wait attribution (ISSUE 8): every successfully dispatched
    item records one enqueue→dispatch wait and one dispatch→complete
    service span — count == items — and both surface in the Prometheus
    exposition."""
    import hashlib
    import hmac as hmac_mod

    from minbft_tpu.obs.prom import collect_replica, render_families
    from minbft_tpu.parallel import BatchVerifier

    async def run():
        eng = BatchVerifier(max_batch=4, buckets=(4,))
        key, msg = b"\x01" * 32, b"\x02" * 32
        good = hmac_mod.new(key, msg, hashlib.sha256).digest()
        items = [(key, msg, good[:-1] + bytes([i])) for i in range(9)]
        await asyncio.gather(*[eng.verify_hmac_sha256(*it) for it in items])
        st = eng.stats["hmac_sha256"]
        assert st.queue_wait.count == st.items == 9
        assert st.queue_service.count == st.items
        assert st.queue_wait.negatives == 0
        assert st.queue_service.total_s > 0
        # sign side mirrors it (host fallback on the CPU backend still
        # flows through the queue — the spans are queue properties)
        from minbft_tpu.utils import hostcrypto as hc

        d, _ = hc.keygen()
        await eng.sign_ecdsa_p256(d, hashlib.sha256(b"qw").digest())
        sst = eng.sign_stats["ecdsa_p256"]
        assert sst.queue_wait.count == sst.items == 1
        assert sst.queue_service.count == 1
        text = render_families(collect_replica(engine=eng))
        assert "minbft_verify_queue_wait_seconds_bucket" in text
        assert "minbft_verify_queue_service_seconds_count" in text
        assert "minbft_sign_queue_wait_seconds_bucket" in text

    asyncio.run(run())


def test_loop_lag_sampler_records_blocking(monkeypatch):
    """The event-loop lag sampler sees a deliberate loop block: the max
    observed lag must be at least the blocked interval (minus one tick),
    and stop() tears the task down."""
    import time as time_mod

    from minbft_tpu.obs.looplag import LoopLagSampler, maybe_sampler

    async def run():
        hist = Log2Histogram()
        sampler = LoopLagSampler(hist, interval=0.01)
        sampler.start()
        await asyncio.sleep(0.05)  # healthy ticks
        time_mod.sleep(0.08)  # block the loop (the GIL-saturation shape)
        await asyncio.sleep(0.03)
        sampler.stop()
        await asyncio.sleep(0)  # let the cancellation land
        assert hist.count >= 3
        assert hist.negatives == 0
        # one sample must carry the ~80ms block: p100 >= 32ms bucket
        assert hist.percentile(100) >= 0.032
        # and most ticks are healthy: p50 well under the block
        assert hist.percentile(50) < 0.032

    asyncio.run(run())
    # env knob: 0 disables, garbage falls back to the default
    monkeypatch.setenv("MINBFT_LOOPLAG_INTERVAL", "0")
    assert maybe_sampler(Log2Histogram()) is None
    monkeypatch.setenv("MINBFT_LOOPLAG_INTERVAL", "not-a-number")
    assert maybe_sampler(Log2Histogram()) is not None
    monkeypatch.delenv("MINBFT_LOOPLAG_INTERVAL")
    s = maybe_sampler(Log2Histogram())
    assert s is not None and s.interval == 0.05


def test_replica_dump_carries_loop_lag_and_nf(tmp_path, monkeypatch):
    """A replica's shutdown dump carries n/f and the sampled loop-lag
    histogram — the critpath merge's quorum rank and loop_lag inputs."""
    from conftest import make_cluster
    from minbft_tpu.obs import trace as trace_mod
    from minbft_tpu.sample.config import SimpleConfiger

    async def run():
        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=60.0, timeout_prepare=30.0
        )
        cfg.trace = True
        replicas, _c_auths, _stubs, _ledgers = await make_cluster(4, 1, cfg=cfg)
        await asyncio.sleep(0.12)  # let the lag samplers tick
        monkeypatch.setenv(
            trace_mod.TRACE_DUMP_ENV, str(tmp_path / "dump")
        )
        for r in replicas:
            await r.stop()

    asyncio.run(run())
    docs = load_dumps(str(tmp_path / "dump"))
    assert len(docs) == 4
    for doc in docs:
        assert doc["n"] == 4 and doc["f"] == 1
        assert doc["clock_domain"]
        lag = Log2Histogram.from_dict(doc["loop_lag"])
        assert lag.count > 0


def test_trace_dump_fires_on_fatal_task_crash(tmp_path, monkeypatch):
    """A replica task dying with an exception dumps the trace at the
    moment of death — a crashed soak must not lose its forensics (the
    dump used to fire only on clean stop)."""
    from conftest import make_cluster
    from minbft_tpu.obs import trace as trace_mod
    from minbft_tpu.sample.config import SimpleConfiger

    monkeypatch.setenv(trace_mod.TRACE_DUMP_ENV, str(tmp_path / "crash"))

    async def run():
        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=60.0, timeout_prepare=30.0
        )
        cfg.trace = True
        replicas, _c, _stubs, _ledgers = await make_cluster(4, 1, cfg=cfg)
        try:
            replicas[0].handlers.trace.note(1, 9, 9)  # something to dump
            # Kill one protocol task the way a real bug would: make it
            # raise, then let the done-callback observe the corpse.
            victim = replicas[0]._tasks[0]
            victim.cancel()  # unwind it...
            await asyncio.sleep(0)

            async def boom():
                raise RuntimeError("injected fatal task error")

            t = asyncio.get_running_loop().create_task(boom())
            t.add_done_callback(replicas[0]._on_task_done)
            await asyncio.sleep(0.05)
            assert os.path.exists(str(tmp_path / "crash") + ".r0.json")
        finally:
            for r in replicas:
                await r.stop()

    asyncio.run(run())
    docs = load_dumps(str(tmp_path / "crash"))
    assert any(d["kind"] == "replica" and d["id"] == 0 for d in docs)


def test_engine_flush_reasons_and_occupancy_sum_to_batches():
    from minbft_tpu.parallel import BatchVerifier

    async def run():
        eng = BatchVerifier(max_batch=4, buckets=(4,))
        import hashlib
        import hmac as hmac_mod

        key, msg = b"\x01" * 32, b"\x02" * 32
        good = hmac_mod.new(key, msg, hashlib.sha256).digest()
        # distinct MACs so nothing dedups away
        items = [
            (key, msg, good[:-1] + bytes([i])) for i in range(16)
        ] + [(key, msg, good)]
        await asyncio.gather(
            *[eng.verify_hmac_sha256(*it) for it in items]
        )
        st = eng.stats["hmac_sha256"]
        assert st.batches >= 1
        assert sum(st.flush_reasons.values()) == st.batches
        assert sum(st.occupancy.values()) == st.batches
        assert set(st.flush_reasons) <= {
            "full", "idle", "timer", "completion", "direct"
        }
        assert eng.queue_depths()["hmac_sha256"] == 0  # drained
        assert eng.sign_queue_depths() == {}

    asyncio.run(run())


# ---------------------------------------------------------------------------
# histograms


def test_log2_histogram_bucket_edges():
    h = Log2Histogram()
    h.observe(0.5e-6)   # <= 1us -> bucket 0
    h.observe(1e-6)     # == 1us -> bucket 0
    h.observe(2e-6)     # bucket 1
    h.observe(3e-6)     # bucket 2 (2 < 3 <= 4)
    assert h.buckets[0] == 2 and h.buckets[1] == 1 and h.buckets[2] == 1
    assert h.count == 4


def test_log2_histogram_counts_negative_durations():
    """Clock weirdness is COUNTED, never silently clamped (ISSUE 8): a
    negative duration lands in ``negatives`` only — buckets, count, and
    total stay unpolluted — and the counter rides merge, the dump round
    trip, and the Prometheus exposition."""
    h = Log2Histogram()
    h.observe(1e-6)
    h.observe(-1.0)
    h.observe_ns(-5)
    assert h.negatives == 2
    assert h.count == 1 and h.buckets[0] == 1
    assert h.total_s == pytest.approx(1e-6)

    other = Log2Histogram()
    other.observe(-2.0)
    h.merge(other)
    assert h.negatives == 3

    d = json.loads(json.dumps(h.to_dict()))
    assert Log2Histogram.from_dict(d).negatives == 3
    clean = Log2Histogram()
    clean.observe(1e-3)
    assert "negatives" not in clean.to_dict()  # sparse: only when nonzero

    from minbft_tpu.obs.prom import render_families

    text = render_families(
        [("lat_seconds", "histogram", "x", [({"stage": "s"}, h)])]
    )
    assert 'lat_seconds_negatives_total{stage="s"} 3' in text
    assert "# TYPE lat_seconds_negatives_total counter" in text
    clean_text = render_families(
        [("lat_seconds", "histogram", "x", [({"stage": "s"}, clean)])]
    )
    assert "negatives" not in clean_text


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_histogram_percentile_vs_reservoir_oracle(seed):
    """Property: on identical samples the histogram's percentile is the
    nearest-rank value rounded UP to its bucket edge — within a factor
    of 2 above the reservoir oracle's exact answer, never below it."""
    rng = random.Random(seed)
    hist = Log2Histogram()
    oracle = LatencyReservoir(capacity=10_000)  # holds every sample
    samples = []
    for _ in range(3000):
        # log-uniform over ~1us..10s — the range of real stage spans
        v = 10 ** rng.uniform(-6, 1)
        samples.append(v)
        hist.observe(v)
        oracle.observe(v)
    assert hist.count == oracle.count == 3000
    assert abs(hist.total_s - sum(samples)) < 1e-6 * hist.count
    for q in (1, 25, 50, 90, 99):
        exact = oracle.percentile(q)
        approx = hist.percentile(q)
        assert exact * (1 - 1e-9) <= approx <= exact * 2 + 2e-6, (
            q, exact, approx,
        )


def test_histogram_merge_equals_concatenation():
    rng = random.Random(7)
    a, b, both = Log2Histogram(), Log2Histogram(), Log2Histogram()
    for i in range(2000):
        v = 10 ** rng.uniform(-6, 0)
        (a if i % 2 else b).observe(v)
        both.observe(v)
    merged = Log2Histogram.merged([a, b])
    assert merged.buckets == both.buckets
    assert merged.count == both.count
    assert abs(merged.total_s - both.total_s) < 1e-9
    for q in (50, 99):
        assert merged.percentile(q) == both.percentile(q)


def test_histogram_dict_round_trip():
    h = Log2Histogram()
    for v in (1e-6, 5e-4, 0.25, 3.0):
        h.observe(v)
    d = json.loads(json.dumps(h.to_dict()))  # survives JSON
    h2 = Log2Histogram.from_dict(d)
    assert h2.buckets == h.buckets and h2.count == h.count
    assert abs(h2.total_s - h.total_s) < 1e-12


# ---------------------------------------------------------------------------
# recorder pairing + stage table


def test_recorder_pairs_consecutive_points_and_retires_keys():
    rec = FlightRecorder.for_replica(0)
    assert rec.stages == REPLICA_STAGES
    for stage in range(len(REPLICA_STAGES)):
        rec.note(stage, 5, 42)
    hists = rec.stage_hists()
    # BOTH replica entry stages (ingest and recv) open spans without
    # closing one, so N points yield N-2 spans
    assert set(hists) == set(REPLICA_STAGES[2:])
    assert all(h.count == 1 for h in hists.values())
    assert rec._last == {}, "final stage must retire the pairing key"
    assert len(rec.ring) == len(REPLICA_STAGES)


def test_recorder_inflight_keys_are_bounded():
    from minbft_tpu.obs import trace as trace_mod

    rec = FlightRecorder.for_replica(0)
    cap = trace_mod._MAX_INFLIGHT_KEYS
    for k in range(cap + 10):  # never-completing requests
        rec.note(0, 0, k)
    assert len(rec._last) <= cap


def test_stage_table_from_dumped_recorders(tmp_path):
    base = str(tmp_path / "trace")
    for rid in (0, 1):
        rec = FlightRecorder.for_replica(rid)
        for seq in range(10):
            for stage in range(len(REPLICA_STAGES)):
                rec.note(stage, 1, seq)
        assert dump_recorder(rec, base=base) is not None
    crec = FlightRecorder.for_client(1)
    for seq in range(10):
        for stage in range(len(CLIENT_STAGES)):
            crec.note(stage, 1, seq)
    dump_recorder(crec, base=base)

    docs = load_dumps(base)
    assert len(docs) == 3
    table = stage_table(docs, "t")
    # entry stages (ingest, recv) never record spans — no table keys
    for name in REPLICA_STAGES[2:]:
        assert f"t_stage_{name}_p50_ms" in table
        assert f"t_stage_{name}_share" in table
    for name in CLIENT_STAGES[1:]:
        assert f"t_stage_client_{name}_p50_ms" in table
        # client spans overlap the replica pipeline: no share key
        assert f"t_stage_client_{name}_share" not in table
    shares = [v for k, v in table.items() if k.endswith("_share")]
    assert abs(sum(shares) - 1.0) < 0.01

    # empty dumps (tracing off) produce NO keys — the bench's
    # byte-identical-keys contract
    assert stage_table([], "t") == {}
    assert stage_table([{"kind": "replica", "hists": {}}], "t") == {}


def test_tracing_enabled_env_parsing(monkeypatch):
    """MINBFT_TRACE follows the repo's env-flag convention: the usual
    falsy spellings DISABLE; MINBFT_TRACE_DUMP is a path (any non-empty
    value enables)."""
    from minbft_tpu.obs.trace import tracing_enabled

    monkeypatch.delenv("MINBFT_TRACE", raising=False)
    monkeypatch.delenv("MINBFT_TRACE_DUMP", raising=False)
    assert not tracing_enabled()
    for off in ("0", "false", "no", ""):
        monkeypatch.setenv("MINBFT_TRACE", off)
        assert not tracing_enabled(), off
    monkeypatch.setenv("MINBFT_TRACE", "1")
    assert tracing_enabled()
    monkeypatch.setenv("MINBFT_TRACE", "0")
    monkeypatch.setenv("MINBFT_TRACE_DUMP", "/tmp/somewhere")
    assert tracing_enabled()


def test_flush_reasons_skip_failed_dispatches():
    """The 'flush_reasons and occupancy both sum to batches' invariant
    must hold on error paths: a batch whose dispatch raises is counted
    in none of the three."""
    import asyncio as aio

    from minbft_tpu.parallel import BatchVerifier
    from minbft_tpu.parallel.engine import _SchemeQueue

    async def run():
        eng = BatchVerifier(max_batch=4, buckets=(4,), dispatch_timeout=0)

        def boom(items):
            raise RuntimeError("dispatch exploded")

        q = _SchemeQueue(eng, "boom", boom)
        eng._queues["boom"] = q
        with pytest.raises(RuntimeError):
            await q.submit((b"x",))
        assert q.stats.batches == 0
        assert sum(q.stats.flush_reasons.values()) == 0
        assert sum(q.stats.occupancy.values()) == 0

    aio.run(run())


def test_dump_respects_env_and_noop_when_unset(tmp_path, monkeypatch):
    from minbft_tpu.obs import trace as trace_mod

    rec = FlightRecorder.for_replica(3)
    rec.note(0, 1, 1)
    monkeypatch.delenv(trace_mod.TRACE_DUMP_ENV, raising=False)
    assert dump_recorder(rec) is None  # env unset, explicit base absent
    monkeypatch.setenv(trace_mod.TRACE_DUMP_ENV, str(tmp_path / "envtrace"))
    path = dump_recorder(rec)
    assert path is not None and path.endswith(".r3.json")
    assert os.path.exists(path)
    doc = load_dumps(str(tmp_path / "envtrace"))[0]
    assert doc["kind"] == "replica" and doc["id"] == 3
    assert doc["events"], "ring events must land in the dump"
