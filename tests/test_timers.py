"""Timer / failure-detection tests driven by FakeTimerProvider.

Mirrors the reference's timer tests (core/internal/clientstate/
timeout_test.go:46-80 against a mock timer provider) and the timeout
behaviors of core/timeout.go:45-72 (request timeout → signed
REQ-VIEW-CHANGE, deduplicated via expectedView) and core/request.go:315-324
(prepare timeout → forward the starved request to the primary's unicast
log).  No real time elapses: timers are fired explicitly.
"""

import asyncio

from minbft_tpu import api
from minbft_tpu.core import new_replica
from minbft_tpu.core.internal.timer import FakeTimerProvider
from minbft_tpu.messages import ReqViewChange, Request, authen_bytes
from minbft_tpu.sample.authentication import new_test_authenticators
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.sample.conn.inprocess import (
    InProcessPeerConnector,
    make_testnet_stubs,
)
from minbft_tpu.sample.requestconsumer import SimpleLedger


def _make_backup(n=3, f=1, replica_id=1):
    """A single backup replica (view 0 primary is replica 0) with fake
    timers and no network started — we poke handlers directly."""
    timers = FakeTimerProvider()
    configer = SimpleConfiger(n=n, f=f, timeout_request=5.0, timeout_prepare=2.0)
    replica_auths, client_auths = new_test_authenticators(
        n, n_clients=1, usig_kind="hmac", engine=None
    )
    stubs = make_testnet_stubs(n)
    r = new_replica(
        replica_id,
        configer,
        replica_auths[replica_id],
        InProcessPeerConnector(stubs),
        SimpleLedger(),
        timer_provider=timers,
    )
    return r, timers, replica_auths, client_auths


def _signed_request(client_auth, seq=1, op=b"op"):
    req = Request(client_id=0, seq=seq, operation=op)
    req.signature = client_auth.generate_message_authen_tag(
        api.AuthenticationRole.CLIENT, authen_bytes(req)
    )
    return req


def test_request_timeout_emits_signed_req_view_change_once():
    """Request timer expiry demands view v+1 exactly once: a signed
    REQ-VIEW-CHANGE hits the broadcast log, and a second expiry for the
    same view is deduplicated via expectedView (reference
    core/timeout.go:45-72)."""

    async def run():
        r, timers, replica_auths, client_auths = _make_backup()
        h = r.handlers
        req = _signed_request(client_auths[0])
        await h.handle_peer_message(req)  # backup accepts a forwarded request

        assert len(timers.timers) >= 1  # request + prepare timers armed
        timers.fire_all()
        # Timer callbacks schedule a task; let it run.
        await asyncio.sleep(0)
        await asyncio.sleep(0)

        log = list(h.message_log.snapshot())
        rvcs = [m for m in log if isinstance(m, ReqViewChange)]
        assert len(rvcs) == 1
        rvc = rvcs[0]
        assert rvc.new_view == 1
        assert rvc.replica_id == r.id
        # The emitted message is properly signed (replica role).
        await replica_auths[0].verify_message_authen_tag(
            api.AuthenticationRole.REPLICA,
            r.id,
            authen_bytes(rvc),
            rvc.signature,
        )

        # A second expiry for the same view is a no-op (dedup).
        await h.handle_request_timeout(0)
        await asyncio.sleep(0)
        rvcs = [m for m in h.message_log.snapshot() if isinstance(m, ReqViewChange)]
        assert len(rvcs) == 1

    asyncio.run(run())


def test_prepare_timeout_forwards_request_to_primary():
    """A backup whose request is never prepared forwards it to the primary's
    unicast log on prepare-timer expiry (reference core/request.go:315-324)."""

    async def run():
        r, timers, _, client_auths = _make_backup()
        h = r.handlers
        req = _signed_request(client_auths[0], seq=7)
        await h.handle_peer_message(req)

        primary_log_before = list(h.unicast_logs[0].snapshot())
        assert req not in primary_log_before

        timers.fire_all()
        await asyncio.sleep(0)

        forwarded = list(h.unicast_logs[0].snapshot())
        assert any(
            isinstance(m, Request) and m.seq == 7 and m.client_id == 0
            for m in forwarded
        )

    asyncio.run(run())


def test_timers_stop_on_commit():
    """Committing a request cancels its client's request+prepare timers: a
    later fire_all must not emit a view-change demand."""

    async def run():
        n, f = 3, 1
        timers_by_replica = [FakeTimerProvider() for _ in range(n)]
        configer = SimpleConfiger(
            n=n, f=f, timeout_request=5.0, timeout_prepare=2.0
        )
        replica_auths, client_auths = new_test_authenticators(
            n, n_clients=1, usig_kind="hmac", engine=None
        )
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i,
                configer,
                replica_auths[i],
                InProcessPeerConnector(stubs),
                ledgers[i],
                timer_provider=timers_by_replica[i],
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()

        from minbft_tpu.client import new_client
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        client = new_client(
            0, n, f, client_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"x"), 30)

        # Let commit propagation finish on all replicas.
        for _ in range(100):
            if all(lg.length >= 1 for lg in ledgers):
                break
            await asyncio.sleep(0.01)

        for i, (r, timers) in enumerate(zip(replicas, timers_by_replica)):
            timers.fire_all()
        await asyncio.sleep(0.05)

        for r in replicas:
            rvcs = [
                m
                for m in r.handlers.message_log.snapshot()
                if isinstance(m, ReqViewChange)
            ]
            assert not rvcs, f"replica {r.id} demanded a view change after commit"

        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())
