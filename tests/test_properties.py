"""Randomized property tests — the reference seeds its unit fixtures from
random protocol parameters (core/testutils_test.go:24-70 randN 3..255,
randView, randOtherReplicaID); these mirror that style over the invariants
the arithmetic must hold for EVERY (n, f, view), not just the bench
configs.
"""

import asyncio
import random

from minbft_tpu.core.commit import make_commitment_collector
from minbft_tpu.core.utils import is_primary
from minbft_tpu.messages import (
    UI,
    Prepare,
    Reply,
    Request,
    authen_bytes,
    marshal,
    unmarshal,
)
from minbft_tpu.utils.backoff import ReconnectBackoff


def _rand_nf(rng):
    f = rng.randrange(1, 16)
    n = 2 * f + 1 + rng.randrange(0, 4)  # n >= 2f+1
    return n, f


def test_exactly_one_primary_per_view():
    rng = random.Random(0xB5)
    for _ in range(200):
        n, _ = _rand_nf(rng)
        view = rng.randrange(0, 10_000)
        primaries = [i for i in range(n) if is_primary(view, i, n)]
        assert len(primaries) == 1
        assert primaries[0] == view % n


def test_primary_rotation_covers_every_replica():
    rng = random.Random(0xB6)
    for _ in range(50):
        n, _ = _rand_nf(rng)
        start = rng.randrange(0, 1000)
        seen = set()
        for view in range(start, start + n):
            seen.update(i for i in range(n) if is_primary(view, i, n))
        assert seen == set(range(n)), (n, start)


def test_commit_quorum_fires_exactly_at_f_plus_1_for_random_f():
    """The collector's quorum threshold, behaviorally, over random f
    (the reference randomizes its fixtures the same way,
    core/testutils_test.go:24-70): execution fires at the (f+1)-th
    DISTINCT committer — never earlier, never again on extras or
    duplicates."""

    async def run():
        rng = random.Random(0xB7)
        for _ in range(25):
            n, f = _rand_nf(rng)
            executed = []

            async def execute(request):
                executed.append(request.seq)

            collect = make_commitment_collector(f, execute)
            req = Request(client_id=0, seq=1, operation=b"op")
            p = Prepare(replica_id=0, view=0, request=req, ui=UI(counter=1))
            committers = list(range(n))
            rng.shuffle(committers)
            # the primary's own PREPARE must be among the first f+1 votes
            # (the collector counts it as a committer)
            distinct = 0
            for rid in committers:
                dup = rng.random() < 0.3
                await collect(rid, p)
                distinct += 1
                if dup:
                    await collect(rid, p)  # duplicate vote: no effect
                if distinct < f + 1:
                    assert executed == [], (n, f, distinct)
                else:
                    assert executed == [1], (n, f, distinct)

    asyncio.run(run())


def test_request_authen_bytes_distinct_over_random_fields():
    """Distinct (client, seq, op, read_mode) must never collide in authen
    bytes — a collision would let one signature authorize another request."""
    rng = random.Random(0xB8)
    seen = {}
    for _ in range(500):
        r = Request(
            client_id=rng.randrange(0, 1 << 16),
            seq=rng.randrange(1, 1 << 48),
            operation=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 16))),
            read_mode=rng.randrange(0, 3),
        )
        key = (r.client_id, r.seq, r.operation, r.read_mode)
        ab = authen_bytes(r)
        if key in seen:
            assert seen[key] == ab
        else:
            for other_key, other_ab in seen.items():
                assert other_ab != ab or other_key == key, (key, other_key)
            seen[key] = ab


def test_codec_roundtrip_random_request_reply():
    rng = random.Random(0xB9)
    for _ in range(300):
        r = Request(
            client_id=rng.randrange(0, 1 << 16),
            seq=rng.randrange(1, 1 << 48),
            operation=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 32))),
            signature=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 8))),
            read_mode=rng.randrange(0, 3),
        )
        assert unmarshal(marshal(r)) == r
        p = Reply(
            replica_id=rng.randrange(0, 64),
            client_id=rng.randrange(0, 1 << 16),
            seq=rng.randrange(1, 1 << 48),
            result=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 32))),
            read_only=bool(rng.randrange(2)),
            error=bool(rng.randrange(2)),
        )
        assert unmarshal(marshal(p)) == p


def test_backoff_ladder_properties():
    """For random parameters: delays are monotone non-decreasing up to the
    cap while attempts die young, never exceed the cap, and reset to the
    start after any lived connection."""
    rng = random.Random(0xBA)
    for _ in range(100):
        start = rng.uniform(0.05, 1.0)
        cap = start * rng.uniform(2.0, 50.0)
        lived = rng.uniform(1.0, 10.0)
        b = ReconnectBackoff(
            start_s=start, cap_s=cap, lived_reset_s=lived, jitter_frac=0.0
        )
        prev = 0.0
        for _ in range(20):
            d = b.next_delay(0.0)
            assert prev <= d <= cap + 1e-9, (prev, d, cap)
            prev = d
        assert b.next_delay(lived + 0.1) == start
        # With jitter on, every delay stays inside the +-frac envelope of
        # the deterministic ladder (and under the cap) — the spread that
        # de-synchronizes a partition heal's redial herd.
        j = rng.uniform(0.05, 0.5)
        jb = ReconnectBackoff(
            start_s=start, cap_s=cap, lived_reset_s=lived, jitter_frac=j,
            rng=random.Random(1),
        )
        ladder = start
        for _ in range(20):
            d = jb.next_delay(0.0)
            lo, hi = ladder * (1 - j), min(ladder * (1 + j), cap)
            assert lo - 1e-9 <= d <= hi + 1e-9, (d, lo, hi)
            ladder = min(ladder * 2.0, cap)
