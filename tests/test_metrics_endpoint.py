"""Prometheus exposition tests: text-format rendering, the stdlib
``/metrics`` endpoint scraped off a live in-process cluster, the
``peer metrics`` one-shot subcommand, and the bench-keys regression pin
(tracing disabled must be key-identical to tracing absent)."""

import asyncio
import os
import sys
import urllib.request

import pytest

from minbft_tpu.obs.hist import Log2Histogram
from minbft_tpu.obs.prom import (
    CONTENT_TYPE,
    MetricsServer,
    collect_replica,
    render_families,
    scrape,
)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from conftest import make_cluster  # noqa: E402


# ---------------------------------------------------------------------------
# rendering


def test_render_counters_and_gauges():
    text = render_families(
        [
            ("m_total", "counter", "help text", [({"replica": "0"}, 3)]),
            ("g", "gauge", "a gauge", [({}, 1.5)]),
            ("empty", "counter", "skipped entirely", []),
        ]
    )
    assert "# HELP m_total help text" in text
    assert "# TYPE m_total counter" in text
    assert 'm_total{replica="0"} 3' in text
    assert "g 1.5" in text
    assert "empty" not in text


def test_render_histogram_is_cumulative_with_inf():
    h = Log2Histogram()
    for v in (1e-6, 1e-6, 3e-6, 1e-3):
        h.observe(v)
    text = render_families(
        [("lat_seconds", "histogram", "latency", [({"stage": "s"}, h)])]
    )
    lines = [ln for ln in text.splitlines() if ln.startswith("lat_seconds")]
    buckets = [ln for ln in lines if "_bucket" in ln]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert counts[-1] == 4
    assert 'le="+Inf"' in buckets[-1]
    assert 'lat_seconds_count{stage="s"} 4' in text
    assert any(ln.startswith("lat_seconds_sum") for ln in lines)


def test_collect_replica_families_from_live_objects():
    from minbft_tpu.obs.trace import FlightRecorder
    from minbft_tpu.utils.metrics import ReplicaMetrics

    m = ReplicaMetrics()
    m.inc("requests_executed", 2)
    m.observe_execute(0.01)
    from minbft_tpu.obs.trace import R_INGEST, R_VERIFY_ENQUEUE

    rec = FlightRecorder.for_replica(1)
    rec.note(R_INGEST, 0, 1)
    rec.note(R_VERIFY_ENQUEUE, 0, 1)
    text = render_families(collect_replica(metrics=m, recorder=rec, replica_id=1))
    assert 'minbft_requests_executed_total{replica="1"} 2' in text
    assert "minbft_uptime_seconds" in text
    assert "minbft_execute_latency_seconds_count" in text
    assert 'minbft_stage_latency_seconds_count{replica="1",stage="verify_enqueue"} 1' in text


# ---------------------------------------------------------------------------
# live endpoint


def test_metrics_endpoint_scrapes_a_committing_cluster():
    """Acceptance smoke: a 4-replica in-process cluster commits requests
    with the flight recorder on; the stdlib endpoint serves Prometheus
    text that carries the protocol counters, the stage histograms, AND
    the engine queue gauges — scraped over real HTTP while the loop is
    live, by raw urllib and by the `peer metrics` subcommand."""

    async def run():
        from minbft_tpu.client import new_client
        from minbft_tpu.parallel import BatchVerifier
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=60.0, timeout_prepare=30.0
        )
        cfg.trace = True  # flight recorder on for every replica
        engine = BatchVerifier(max_batch=8, buckets=(8,))
        # batch_signatures=False: message signatures stay on the host
        # queue, so the only device kernel this test compiles is the
        # cheap HMAC USIG one (the CPU-backend ECDSA verify kernel takes
        # minutes to build — not a price a smoke test pays).
        replicas, c_auths, stubs, _ledgers = await make_cluster(
            4, 1, cfg=cfg, engines=[engine] * 4, batch_signatures=False
        )
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        try:
            for i in range(3):
                await asyncio.wait_for(client.request(b"scrape-%d" % i), 30)
            # f+1 matching replies resolve the client before the LAST
            # replica executes; replica 0 may be one of the stragglers —
            # wait for its counter before scraping (the pre-existing
            # flake this poll fixes fired under PYTHONDEVMODE's slower
            # loop).
            for _ in range(400):
                if replicas[0].metrics.counters.get(
                    "requests_executed", 0
                ) >= 3:
                    break
                await asyncio.sleep(0.02)

            server = MetricsServer(
                lambda: render_families(
                    collect_replica(
                        metrics=replicas[0].metrics,
                        recorder=replicas[0].trace,
                        engine=engine,
                        replica_id=0,
                    )
                ),
                host="127.0.0.1",
            )
            port = server.start()
            try:
                url = f"http://127.0.0.1:{port}/metrics"
                with urllib.request.urlopen(url, timeout=10) as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"] == CONTENT_TYPE
                    body = resp.read().decode()
                assert 'minbft_requests_executed_total{replica="0"} 3' in body
                assert "minbft_stage_latency_seconds_bucket" in body
                assert 'stage="commit_quorum"' in body
                assert "minbft_verify_queue_items_total" in body
                assert "minbft_verify_queue_flushes_total" in body
                assert "minbft_verify_queue_depth" in body

                # the one-shot scrape helper (what `peer metrics` calls)
                scraped = scrape(f"127.0.0.1:{port}")
                assert 'minbft_requests_executed_total{replica="0"} 3' in scraped
                assert "minbft_stage_latency_seconds_bucket" in scraped

                # unknown paths 404 instead of leaking anything
                with pytest.raises(urllib.error.HTTPError):
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/secrets", timeout=10
                    )
                return port, body
            finally:
                server.stop()
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()

    asyncio.run(run())


def test_parse_and_merge_expositions():
    """The scrape→parse→merge round trip (the `peer metrics` cluster
    aggregate): histograms merge EXACTLY (per-le bucket counts add,
    sparse grids union), counters sum, and the per-process replica
    label is stripped so the same logical series folds together."""
    from minbft_tpu.obs.prom import merge_expositions, parse_exposition

    def exposition(replica, counter, samples):
        h = Log2Histogram()
        for v in samples:
            h.observe(v)
        return render_families([
            ("minbft_requests_executed_total", "counter", "c",
             [({"replica": str(replica)}, counter)]),
            ("minbft_stage_latency_seconds", "histogram", "h",
             [({"replica": str(replica), "stage": "execute"}, h)]),
        ])

    a_samples = [1e-6, 3e-6, 1e-3]
    b_samples = [2e-6, 0.25]
    merged = merge_expositions(
        [exposition(0, 3, a_samples), exposition(1, 4, b_samples)]
    )
    fams = parse_exposition(merged)
    assert fams["minbft_requests_executed_total"]["samples"][()] == 7
    hist_fam = fams["minbft_stage_latency_seconds"]
    (key, sample), = hist_fam["samples"].items()
    assert dict(key) == {"stage": "execute"}  # replica label stripped
    assert sample["count"] == 5
    assert sample["sum"] == pytest.approx(sum(a_samples) + sum(b_samples))
    # the merged cumulative counts equal a direct merge of the hists
    both = Log2Histogram()
    for v in a_samples + b_samples:
        both.observe(v)
    cum = 0
    expected = {}
    for i, c in enumerate(both.buckets):
        cum += c
        if c:
            expected[both.bucket_upper_bounds_s()[i]] = cum
    finite = {
        le: c for le, c in sample["buckets"].items() if le != float("inf")
    }
    assert finite == expected


def test_peer_metrics_multi_target_merges(capsys):
    """`peer metrics a b` prints per-target sections plus one merged
    cluster aggregate; --merged-only prints just the aggregate; a dead
    target costs rc=1 but not the live targets' output."""
    from minbft_tpu.sample.peer import cli

    def server_for(replica, count):
        return MetricsServer(
            lambda: render_families([
                ("minbft_requests_executed_total", "counter", "c",
                 [({"replica": str(replica)}, count)]),
            ]),
            host="127.0.0.1",
        )

    s0, s1 = server_for(0, 3), server_for(1, 4)
    p0, p1 = s0.start(), s1.start()
    try:
        rc = cli.main(["metrics", f"127.0.0.1:{p0}", f"127.0.0.1:{p1}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert f"# ==== target 127.0.0.1:{p0} ====" in out
        assert "merged cluster aggregate (2 targets)" in out
        assert 'minbft_requests_executed_total{replica="0"} 3' in out
        assert "\nminbft_requests_executed_total 7" in out

        rc = cli.main([
            "metrics", f"127.0.0.1:{p0}", f"127.0.0.1:{p1}", "--merged-only",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "==== target" not in out
        assert "\nminbft_requests_executed_total 7" in out
    finally:
        s0.stop()
        s1.stop()
    # one target dead: the live one still prints, rc flags the failure
    s2 = server_for(0, 5)
    p2 = s2.start()
    try:
        rc = cli.main(
            ["metrics", f"127.0.0.1:{p2}", f"127.0.0.1:{p1}",
             "--timeout", "0.5"]
        )
        assert rc == 1
        out = capsys.readouterr().out
        assert 'minbft_requests_executed_total{replica="0"} 5' in out
    finally:
        s2.stop()


def test_peer_metrics_subcommand_scrapes(capsys):
    """`peer metrics host:port` prints the exposition text (the scrape
    path an operator uses without any Prometheus server)."""
    from minbft_tpu.sample.peer import cli

    server = MetricsServer(
        lambda: render_families(
            [("minbft_up", "gauge", "smoke", [({}, 1)])]
        ),
        host="127.0.0.1",
    )
    port = server.start()
    try:
        rc = cli.main(["metrics", f"127.0.0.1:{port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "minbft_up 1" in out
    finally:
        server.stop()
    # a dead endpoint is a clean error, not a traceback
    rc = cli.main(["metrics", f"127.0.0.1:{port}", "--timeout", "0.5"])
    assert rc == 1


# ---------------------------------------------------------------------------
# bench-keys regression: tracing disabled == tracing absent


def _bench_cluster_keys(trace: bool):
    os.environ.setdefault("MINBFT_BENCH_SKIP_PREFLIGHT", "1")
    import bench

    out = asyncio.run(
        bench._bench_cluster(
            4, 1, 24,
            n_clients=4,
            usig_kind="hmac",
            max_batch=8,
            depth=4,
            prefix="pin",
            trace=trace,
        )
    )
    return set(out)


# The exact key set _bench_cluster emitted BEFORE the flight recorder
# existed: a tracing-DISABLED run must reproduce it byte-identically —
# the recorder must be invisible unless asked for.
_PINNED_BENCH_KEYS = {
    "pin_request_latency_p50_ms",
    "pin_request_latency_p99_ms",
    "pin_exec_latency_p50_ms",
    "pin_exec_latency_p99_ms",
    "pin_messages_handled",
    "pin_messages_dropped",
    "pin_n",
    "pin_f",
    "pin_clients",
    "pin_requests",
    "pin_committed_req_per_sec",
    # Bundle-ingest fill gauges (ISSUE 6): ALWAYS present — 0-valued when
    # MINBFT_BUNDLE_INGEST=0 — so the key set cannot depend on a runtime
    # toggle (the byte-identical contract this pin enforces).
    "pin_ingest_batch_mean",
    "pin_ingest_ticks_per_sec",
    "pin_batched_verifies",
    "pin_batches",
    "pin_mean_batch",
    "pin_device_verifies_per_sec",
    "pin_logical_verifies",
    "pin_memo_hits",
    "pin_hmac_sha256_prep_share",
    # REPLY signing rides the engine sign queue even on the CPU backend
    # (host fallback, recorded) — these four predate the recorder.
    "pin_device_signs_per_sec",
    "pin_queue_signs",
    "pin_sign_fallback_items",
    "pin_sign_share",
}


@pytest.mark.slow
def test_bench_keys_trace_disabled_is_byte_identical():
    keys = _bench_cluster_keys(trace=False)
    assert keys == _PINNED_BENCH_KEYS
    assert not any("_stage_" in k for k in keys)


@pytest.mark.slow
def test_bench_keys_trace_enabled_adds_only_stage_keys():
    keys = _bench_cluster_keys(trace=True)
    extra = keys - _PINNED_BENCH_KEYS
    assert extra, "traced run must add stage keys"
    # a traced run adds exactly the per-stage attribution AND the
    # cluster critical-path keys (ISSUE 8) — nothing else
    assert all(
        "pin_stage_" in k or "pin_critpath_" in k for k in extra
    ), sorted(extra)
    # and the replica pipeline is fully attributed
    for name in ("verify_done", "commit_quorum", "execute", "reply_sent"):
        assert f"pin_stage_{name}_p50_ms" in keys
        assert f"pin_stage_{name}_share" in keys
    # the critical path carries its full stable segment set
    from minbft_tpu.obs.critpath import SEGMENTS

    for seg in SEGMENTS:
        assert f"pin_critpath_{seg}_share" in keys, seg
