"""Commitment collector unit tests (reference core/commit_test.go:112-320):
quorum counting, sequential-CV enforcement, replay handling, and in-order
execution under reordered/concurrent quorum completion (the race batched
validation makes possible)."""

import asyncio

import pytest

from minbft_tpu import api
from minbft_tpu.core.commit import make_commitment_collector
from minbft_tpu.messages import UI, Prepare, Request


def _prepare(cv: int, view: int = 0, primary: int = 0) -> Prepare:
    req = Request(client_id=0, seq=cv, operation=b"op-%d" % cv)
    return Prepare(replica_id=primary, view=view, request=req, ui=UI(counter=cv))


def test_quorum_at_f_plus_1():
    async def run():
        executed = []

        async def execute(request):
            executed.append(request.seq)

        collect = make_commitment_collector(1, execute)  # f=1 -> quorum 2
        p = _prepare(1)
        await collect(0, p)  # primary's own PREPARE
        assert executed == []
        await collect(1, p)  # one backup commit -> quorum
        assert executed == [1]
        await collect(2, p)  # extra commit: no re-execution
        assert executed == [1]

    asyncio.run(run())


def test_non_sequential_cv_rejected():
    async def run():
        collect = make_commitment_collector(1, lambda r: None)
        await collect(0, _prepare(1))
        with pytest.raises(api.AuthenticationError):
            await collect(0, _prepare(3))  # skips CV 2

    asyncio.run(run())


def test_replayed_commitment_ignored():
    async def run():
        executed = []

        async def execute(request):
            executed.append(request.seq)

        collect = make_commitment_collector(1, execute)
        p = _prepare(1)
        await collect(0, p)
        await collect(0, p)  # replay from same replica: no double count
        assert executed == []
        await collect(1, p)
        assert executed == [1]

    asyncio.run(run())


def test_execution_stays_in_cv_order_with_slow_consumer():
    """A suspended execution (consumer that actually awaits) must not be
    overtaken by a later CV whose quorum completes meanwhile."""

    async def run():
        executed = []
        gate = asyncio.Event()

        async def execute(request):
            if request.seq == 1:
                await gate.wait()  # CV 1 execution suspends mid-deliver
            executed.append(request.seq)

        collect = make_commitment_collector(1, execute)
        p1, p2 = _prepare(1), _prepare(2)
        await collect(0, p1)
        await collect(0, p2)
        # Complete CV1's quorum in a background task; it blocks on the gate.
        t1 = asyncio.create_task(collect(1, p1))
        await asyncio.sleep(0.01)
        # CV2's quorum completes while CV1 is still executing.
        t2 = asyncio.create_task(collect(1, p2))
        await asyncio.sleep(0.01)
        assert executed == []  # CV2 must not run ahead of CV1
        gate.set()
        await asyncio.gather(t1, t2)
        assert executed == [1, 2]

    asyncio.run(run())


def test_out_of_order_quorum_completion_releases_in_order():
    async def run():
        executed = []

        async def execute(request):
            executed.append(request.seq)

        collect = make_commitment_collector(1, execute)
        p1, p2 = _prepare(1), _prepare(2)
        # Replica 0 (primary) commits both in order.
        await collect(0, p1)
        await collect(0, p2)
        # Replica 1's commitments arrive; CV2's quorum completes *after*
        # CV1's, but execution is released 1 then 2 regardless.
        await collect(1, p1)
        await collect(1, p2)
        assert executed == [1, 2]

    asyncio.run(run())
