"""Embedded-request auth asymmetry: a UI-valid proposal carrying a request
the local replica cannot authenticate must demand a view change instead of
silently wedging the primary's counter stream (the MAC-scheme liveness
hazard documented in sample/authentication/mac.py; see
api.EmbeddedRequestAuthError)."""

import asyncio

import pytest

from conftest import make_cluster
from minbft_tpu import api
from minbft_tpu.core.prepare import make_prepare_validator
from minbft_tpu.messages import Hello, ReqViewChange, marshal
from minbft_tpu.messages.message import Prepare, Request


def test_prepare_validator_distinguishes_embedded_request_failure():
    """UI valid + embedded request invalid -> EmbeddedRequestAuthError;
    UI invalid -> plain AuthenticationError (no view-change escalation
    for a forgeable message)."""

    async def ok(_msg):
        return None

    async def bad(_msg):
        raise api.AuthenticationError("nope")

    req = Request(client_id=0, seq=1, operation=b"x", signature=b"s")
    prep = Prepare(replica_id=0, view=0, requests=[req], ui=None)

    async def run():
        v = make_prepare_validator(4, validate_request=bad, verify_ui=ok)
        with pytest.raises(api.EmbeddedRequestAuthError):
            await v(prep)
        v = make_prepare_validator(4, validate_request=ok, verify_ui=bad)
        with pytest.raises(api.AuthenticationError) as ei:
            await v(prep)
        assert not isinstance(ei.value, api.EmbeddedRequestAuthError)
        v = make_prepare_validator(4, validate_request=bad, verify_ui=bad)
        with pytest.raises(api.AuthenticationError) as ei:
            await v(prep)
        assert not isinstance(ei.value, api.EmbeddedRequestAuthError)

    asyncio.run(run())


def test_backup_demands_view_change_on_ui_valid_bad_request():
    """End-to-end: a PREPARE certified by the real primary USIG but
    embedding a badly-signed request makes the backup demand view 1 and
    broadcast REQ-VIEW-CHANGE (reference-parity: processing of the demand
    itself stays unimplemented)."""

    async def run():
        replicas, _c_auths, stubs, _ledgers = await make_cluster()
        try:
            primary = replicas[0].handlers
            backup = replicas[1].handlers

            forged_req = Request(
                client_id=0, seq=7, operation=b"evil", signature=b"bad" * 8
            )
            prep = Prepare(replica_id=0, view=0, requests=[forged_req], ui=None)
            primary.assign_ui(prep)  # genuine primary UI over the proposal

            done = asyncio.Event()

            async def outgoing():
                # the handshake is authenticated now: sign as the real
                # primary whose stream this impersonates
                hello = Hello(replica_id=0)
                primary.sign_message(hello)
                yield marshal(hello)
                yield marshal(prep)
                try:
                    await asyncio.wait_for(done.wait(), 1.0)
                except asyncio.TimeoutError:
                    return

            handler = stubs[1].peer_message_stream_handler()

            async def drain():
                async for _ in handler.handle_message_stream(outgoing()):
                    pass

            t = asyncio.ensure_future(drain())
            for _ in range(100):
                _, expected = await backup.view_state.hold_view()
                if expected >= 1:
                    break
                await asyncio.sleep(0.02)
            done.set()
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

            _, expected = await backup.view_state.hold_view()
            assert expected == 1, "backup did not demand a view change"
            # the demand was broadcast as a signed REQ-VIEW-CHANGE
            assert any(
                isinstance(m, ReqViewChange) and m.new_view == 1
                for m in backup.message_log.snapshot()
            )
        finally:
            for r in replicas:
                await r.stop()

    asyncio.run(run())
