"""Differential tests for the vectorized host batch prep (round-6).

The numpy/batch-inversion ``prepare_batch`` paths must produce
BIT-IDENTICAL packed arrays to the per-item scalar oracles
(``prepare_batch_scalar``) on random AND adversarial inputs — out-of-range
r/s, the ``r + n < p`` second-candidate edge, zero/garbage pubkeys,
non-int garbage — and the engine's recycled staging buffers must survive
``max_inflight`` concurrent dispatchers without cross-talk.
"""

import hashlib
import random
import threading

import numpy as np
import pytest

from minbft_tpu.ops import ed25519 as ed
from minbft_tpu.ops import limbs, p256
from minbft_tpu.utils import hostcrypto as hc

# ---------------------------------------------------------------------------
# limb batch helpers


def test_to_limbs_batch_matches_scalar():
    rng = random.Random(1)
    vals = [0, 1, (1 << 256) - 1, p256.P, p256.N] + [
        rng.randrange(1 << 256) for _ in range(50)
    ]
    rows = limbs.to_limbs_batch(vals)
    assert rows.dtype == np.uint32 and rows.shape == (len(vals), 16)
    for v, row in zip(vals, rows):
        assert np.array_equal(row, limbs.to_limbs(v))
    assert limbs.from_limbs_batch(rows) == vals
    assert limbs.to_limbs_batch([]).shape == (0, 16)


def test_limbs_lt_and_add_const():
    rng = random.Random(2)
    bound = p256.N
    vals = [0, 1, bound - 1, bound, bound + 1, (1 << 256) - 1] + [
        rng.randrange(1 << 256) for _ in range(100)
    ]
    rows = limbs.to_limbs_batch(vals)
    got = limbs.limbs_lt(rows, bound)
    assert list(got) == [v < bound for v in vals]
    assert list(limbs.limbs_is_zero(rows)) == [v == 0 for v in vals]
    # add_const on the no-overflow subset
    small = [v for v in vals if v + bound < (1 << 256)]
    srows = limbs.to_limbs_batch(small)
    added = limbs.limbs_add_const(srows, bound)
    assert limbs.from_limbs_batch(added) == [v + bound for v in small]


# ---------------------------------------------------------------------------
# ECDSA-P256 prep parity


def _assert_p256_parity(items):
    a = p256.prepare_batch_scalar(items)
    b = p256.prepare_batch(items)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.dtype == y.dtype, f"array {i} dtype"
        assert np.array_equal(x, y), f"array {i} diverged"
    bucket = len(items) + 3
    packed = p256.prepare_packed(items, bucket)
    assert np.array_equal(p256.pack_arrays(a), packed[: len(items)])
    assert not packed[len(items) :].any(), "pad lanes not zeroed"


def _fuzz_p256_items(rng, n):
    """Mix of plausible lanes, boundary values, and garbage."""
    boundary = [
        0, 1, 2,
        p256.N - 1, p256.N, p256.N + 1,
        p256.P - 1, p256.P, p256.P + 1,
        p256.P - p256.N - 1, p256.P - p256.N, p256.P - p256.N + 1,
        (1 << 256) - 1, 1 << 256, -1, -p256.N, 1 << 300,
    ]

    def field(kind):
        if kind == 0:
            return rng.choice(boundary)
        return rng.randrange(1 << 256)

    items = []
    for _ in range(n):
        shape = rng.randrange(4)
        if shape == 0:  # plausible in-range lane
            items.append(
                (
                    (rng.randrange(p256.P), rng.randrange(p256.P)),
                    rng.randbytes(32),
                    (rng.randrange(1, p256.N), rng.randrange(1, p256.N)),
                )
            )
        elif shape == 1:  # second-candidate window: r < p - n
            items.append(
                (
                    (rng.randrange(p256.P), rng.randrange(p256.P)),
                    rng.randbytes(32),
                    (rng.randrange(1, p256.P - p256.N), rng.randrange(1, p256.N)),
                )
            )
        else:  # boundary/garbage components in random positions
            items.append(
                (
                    (field(rng.randrange(2)), field(rng.randrange(2))),
                    rng.randbytes(rng.choice((0, 31, 32, 33))),
                    (field(rng.randrange(2)), field(rng.randrange(2))),
                )
            )
    return items


def test_p256_prep_parity_fuzz_1000():
    """Acceptance pin: bit-identical packed arrays on >=1000 fuzzed
    inputs (random + adversarial mix, deterministic seed)."""
    rng = random.Random(0xF00D)
    items = _fuzz_p256_items(rng, 1000)
    _assert_p256_parity(items)
    # the fuzz exercises all three verdict populations
    arrays = p256.prepare_batch(items)
    valid, r2_ok = arrays[7], arrays[6]
    assert valid.any() and (~valid).any() and r2_ok.any()


def test_p256_prep_adversarial_edges():
    d, q = hc.keygen()
    digest = hashlib.sha256(b"edge").digest()
    sig = hc.ecdsa_sign(d, digest)
    items = [
        (q, digest, sig),                          # genuine
        (q, digest, (0, sig[1])),                  # r = 0
        (q, digest, (sig[0], 0)),                  # s = 0
        (q, digest, (p256.N, sig[1])),             # r = n
        (q, digest, (sig[0], p256.N)),             # s = n
        (q, digest, (p256.N - 1, p256.N - 1)),     # max in-range scalars
        (q, digest, (-1, sig[1])),                 # negative r
        (q, digest, (sig[0], 1 << 257)),           # oversized s
        ((0, 0), b"\x00" * 32, (0, 0)),            # the engine pad shape
        ((0, 0), digest, sig),                     # zero pubkey, real sig
        ((p256.P, p256.P), digest, sig),           # coords = p
        ((q[0], p256.P - 1), digest, sig),         # garbage-but-in-range y
        (q, b"", sig),                             # empty digest
        (q, digest, (7, 9)),                       # r < p - n: 2nd candidate
    ]
    _assert_p256_parity(items)
    arrays = p256.prepare_batch(items)
    valid, r2_ok = arrays[7], arrays[6]
    assert valid[0] and not valid[1] and not valid[2]
    assert not valid[3] and not valid[4] and valid[5]
    assert not valid[6] and not valid[7]
    assert r2_ok[13] and valid[13]


def test_p256_prep_scalar_flag_roundtrip(monkeypatch):
    """MINBFT_SCALAR_PREP=1 (limbs.SCALAR_PREP, shared by both schemes)
    routes prepare_batch to the oracle."""
    monkeypatch.setattr(limbs, "SCALAR_PREP", True)
    d, q = hc.keygen()
    digest = hashlib.sha256(b"flag").digest()
    items = [(q, digest, hc.ecdsa_sign(d, digest))]
    a = p256.prepare_batch(items)
    monkeypatch.setattr(limbs, "SCALAR_PREP", False)
    b = p256.prepare_batch(items)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


def test_p256_prep_empty_and_all_invalid():
    empty = p256.prepare_batch([])
    for arr, ref in zip(empty, p256.prepare_batch_scalar([])):
        assert arr.shape == ref.shape and arr.dtype == ref.dtype
    bad = [((0, 0), b"\x00" * 32, (0, 0))] * 5
    _assert_p256_parity(bad)
    assert not p256.prepare_batch(bad)[7].any()


def test_p256_prep_hypothesis_fuzz():
    """Property fuzz over prep when hypothesis is available (the bare
    jax_graft image does not ship it — the seeded fuzz above is the
    always-on floor)."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    component = st.one_of(
        st.integers(min_value=-4, max_value=1 << 257),
        st.sampled_from(
            [p256.N, p256.N - 1, p256.P, p256.P - p256.N, (1 << 256) - 1]
        ),
    )
    item = st.tuples(
        st.tuples(component, component),
        st.binary(min_size=0, max_size=40),
        st.tuples(component, component),
    )

    @settings(max_examples=50, deadline=None)
    @given(st.lists(item, min_size=1, max_size=20))
    def check(items):
        _assert_p256_parity(items)

    check()


# ---------------------------------------------------------------------------
# Ed25519 prep parity


def _assert_ed_parity(items, bucket):
    a = ed.prepare_batch_scalar(items, bucket)
    b = ed.prepare_batch(items, bucket)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x.dtype == y.dtype, f"array {i} dtype"
        assert np.array_equal(x, y), f"array {i} diverged"
    assert np.array_equal(ed.pack_arrays(a), ed.prepare_packed(items, bucket))


def test_ed25519_prep_parity_fuzz():
    rng = random.Random(0xED)
    seed, pub = hc.ed25519_keygen(rng.randbytes(32))
    msgs = [rng.randbytes(rng.randrange(0, 64)) for _ in range(24)]
    items = [(pub, m, hc.ed25519_sign(seed, m)) for m in msgs]
    sig0 = items[0][2]
    items += [
        (pub, b"x", b"\x00" * 63),                                  # bad length
        (pub, b"x", b""),                                           # empty sig
        (pub, b"x", sig0[:32] + ed.L.to_bytes(32, "little")),       # s = L
        (pub, b"x", sig0[:32] + (ed.L - 1).to_bytes(32, "little")), # s = L-1
        (pub, b"x", ed.P.to_bytes(32, "little") + sig0[32:]),       # y_r = p
        (pub, b"x", (ed.P - 1).to_bytes(32, "little") + sig0[32:]), # y_r = p-1
        (pub, b"x", b"\xff" * 64),                                  # all-ones
        (b"\x00" * 32, b"y", sig0),                                 # zero pub
        (rng.randbytes(32), b"z", sig0),                            # random pub
        (pub, b"", sig0),                                           # empty msg
    ]
    # high-bit R encodings exercise the rsign split
    items += [
        (pub, b"hb", (1 << 255 | 5).to_bytes(32, "little") + sig0[32:]),
    ]
    for bucket in (len(items), len(items) + 7):
        _assert_ed_parity(items, bucket)
    valid = ed.prepare_batch(items, len(items))[6]
    assert valid[:24].all() and not valid[24] and not valid[25]


# ---------------------------------------------------------------------------
# staging-buffer reuse under concurrency


def test_staging_pool_concurrent_checkout():
    """A buffer checked out by one thread must never be handed to another
    before release — hammer acquire/hold/release from 8 threads and track
    simultaneous holders by buffer identity."""
    from minbft_tpu.parallel.engine import _StagingPool

    pool = _StagingPool()
    held: set = set()
    held_lock = threading.Lock()
    errors: list = []
    barrier = threading.Barrier(8)

    def hammer(tid):
        barrier.wait()
        for i in range(200):
            buf = pool.acquire((16, 4), np.uint16)
            with held_lock:
                if id(buf) in held:
                    errors.append(f"t{tid}: double checkout at iter {i}")
                held.add(id(buf))
            buf.fill(tid)  # scribble: a shared buffer would tear
            if not (buf == tid).all():
                errors.append(f"t{tid}: torn buffer at iter {i}")
            with held_lock:
                held.discard(id(buf))
            pool.release(buf)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]
    # the free list is bounded by the cap, not the hammer volume
    assert sum(len(v) for v in pool._free.values()) <= pool._cap


def test_engine_staging_reuse_thread_hammer():
    """Regression for staging-buffer reuse under max_inflight concurrent
    dispatchers: distinct items through recycled buffers must produce
    their OWN verdicts (a cross-dispatch buffer share would leak lanes),
    with exact padded-lane accounting and host_prep_time_s populated."""
    import hmac as hmac_mod

    from minbft_tpu.parallel import BatchVerifier

    def item(i, valid=True):
        key = hashlib.sha256(b"key-%d" % i).digest()
        msg = hashlib.sha256(b"msg-%d" % i).digest()
        mac = hmac_mod.new(key, msg, hashlib.sha256).digest()
        if not valid:
            mac = bytes([mac[0] ^ 1]) + mac[1:]
        return key, msg, mac

    eng = BatchVerifier(max_batch=8, buckets=(8,))
    eng._queue("hmac_sha256", eng._dispatch_hmac)
    eng._dispatch_hmac([item(0)])  # warm the kernel off the clock
    base = eng.stats["hmac_sha256"].padded_lanes
    n_threads, per_thread = 8, 6
    barrier = threading.Barrier(n_threads)
    errors: list = []

    def hammer(tid):
        barrier.wait()
        for j in range(per_thread):
            i = 1000 + tid * per_thread + j
            valid = (i % 3) != 0
            batch = [item(i, valid=valid), item(i + 100000)]
            res = eng._dispatch_hmac(batch)
            if list(res) != [valid, True]:
                errors.append(f"t{tid}/{j}: {list(res)} != [{valid}, True]")

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:5]
    st = eng.stats["hmac_sha256"]
    assert st.padded_lanes - base == n_threads * per_thread * 6  # bucket 8, n=2
    assert st.host_prep_time_s > 0.0


# ---------------------------------------------------------------------------
# throughput acceptance (slow: excluded from the tier-1 run)


@pytest.mark.slow
def test_prep_speedup_at_least_5x():
    """Acceptance: >=5x host-prep throughput for prepare_batch at B=16384
    vs the scalar oracle on the same host (and bit-identical output on
    the same items).  bench.py's bench_prep reports the same measurement
    as extras."""
    import time

    rng = random.Random(0x5EED)
    B = 16384
    items = [
        (
            (rng.randrange(p256.P), rng.randrange(p256.P)),
            rng.randbytes(32),
            (rng.randrange(1, p256.N), rng.randrange(1, p256.N)),
        )
        for _ in range(B)
    ]
    assert np.array_equal(
        p256.pack_arrays(p256.prepare_batch(items)),
        p256.pack_arrays(p256.prepare_batch_scalar(items)),
    )

    def best_of(fn, n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    tv = best_of(lambda: p256.prepare_batch(items))
    ts = best_of(lambda: p256.prepare_batch_scalar(items))
    assert ts / tv >= 5.0, f"speedup {ts / tv:.2f}x < 5x ({tv:.3f}s vs {ts:.3f}s)"
