"""Closure-level unit tests with injected fakes.

The reference tests every ``makeX`` closure constructor in isolation by
injecting mock closures and enumerating each branch (reference
core/message-handling_test.go:41-120, core/prepare_test.go,
core/commit_test.go:112-320, core/request_test.go, core/usig-ui_test.go);
integration tests alone don't pin the per-branch contracts.  This file is
that per-closure matrix for the asyncio closure graph: every branch of
core/{prepare,commit,request,usig_ui}.py is reachable from here without
spinning up a cluster.
"""

import asyncio

import pytest

from minbft_tpu import api
from minbft_tpu.core import commit as commit_mod
from minbft_tpu.core import prepare as prepare_mod
from minbft_tpu.core import request as request_mod
from minbft_tpu.core import usig_ui
from minbft_tpu.core.internal.clientstate import ClientStates
from minbft_tpu.messages import UI, Commit, Prepare, Reply, Request
from minbft_tpu.usig import ui_to_bytes


def run(coro):
    return asyncio.run(coro)


def _req(client_id=7, seq=1, op=b"op"):
    return Request(client_id=client_id, seq=seq, operation=op)


def _prepare(cv=1, view=0, primary=None, requests=None):
    primary = view % 4 if primary is None else primary
    return Prepare(
        replica_id=primary,
        view=view,
        requests=requests or [_req(seq=cv)],
        ui=UI(counter=cv),
    )


class Calls:
    """Recording fake: each named closure appends (name, args) and returns /
    raises what the test configured."""

    def __init__(self):
        self.log = []
        self.raises = {}
        self.returns = {}

    def sync(self, name):
        def fn(*args):
            self.log.append((name, args))
            exc = self.raises.get(name)
            if exc is not None:
                raise exc
            return self.returns.get(name)

        return fn

    def coro(self, name):
        async def fn(*args):
            self.log.append((name, args))
            exc = self.raises.get(name)
            if exc is not None:
                raise exc
            return self.returns.get(name)

        return fn

    def names(self):
        return [name for name, _ in self.log]


# ---------------------------------------------------------------------------
# prepare.py — make_prepare_validator (reference core/prepare.go:46-65)


def test_prepare_validator_rejects_non_primary():
    c = Calls()
    validate = prepare_mod.make_prepare_validator(
        4, c.coro("validate_request"), c.coro("verify_ui")
    )
    # view 0 primary is replica 0; a PREPARE claiming replica 2 is refused
    # before any signature work (reference prepare.go:51-53).
    bad = _prepare(view=0, primary=2)
    with pytest.raises(api.AuthenticationError):
        run(validate(bad))
    assert c.log == []


def test_prepare_validator_checks_requests_and_ui():
    c = Calls()
    validate = prepare_mod.make_prepare_validator(
        4, c.coro("validate_request"), c.coro("verify_ui")
    )
    reqs = [_req(seq=1), _req(seq=2)]
    run(validate(_prepare(requests=reqs)))
    assert sorted(c.names()) == [
        "validate_request",
        "validate_request",
        "verify_ui",
    ]
    checked = [a[0] for n, a in c.log if n == "validate_request"]
    assert checked == reqs


def test_prepare_validator_embedded_request_failure_is_typed():
    # A UI-valid PREPARE embedding an unverifiable request raises the
    # *typed* error so the handler can demand a view change instead of
    # wedging on the primary's counter gap (see
    # message_handling.handle_peer_message).
    c = Calls()
    c.raises["validate_request"] = api.AuthenticationError("bad client sig")
    validate = prepare_mod.make_prepare_validator(
        4, c.coro("validate_request"), c.coro("verify_ui")
    )
    with pytest.raises(api.EmbeddedRequestAuthError):
        run(validate(_prepare()))


def test_prepare_validator_ui_failure_wins_over_request_failure():
    # If the UI itself is bad the message is simply unauthenticated —
    # plain AuthenticationError, not the embedded-request escalation.
    c = Calls()
    c.raises["validate_request"] = api.AuthenticationError("bad client sig")
    c.raises["verify_ui"] = api.AuthenticationError("bad UI")
    validate = prepare_mod.make_prepare_validator(
        4, c.coro("validate_request"), c.coro("verify_ui")
    )
    with pytest.raises(api.AuthenticationError) as ei:
        run(validate(_prepare()))
    assert not isinstance(ei.value, api.EmbeddedRequestAuthError)
    assert "bad UI" in str(ei.value)


def test_prepare_validator_internal_error_passes_through():
    c = Calls()
    c.raises["validate_request"] = RuntimeError("boom")
    validate = prepare_mod.make_prepare_validator(
        4, c.coro("validate_request"), c.coro("verify_ui")
    )
    with pytest.raises(RuntimeError):
        run(validate(_prepare()))


# ---------------------------------------------------------------------------
# prepare.py — make_prepare_applier (reference core/prepare.go:69-94)


def _applier(c, replica_id):
    return prepare_mod.make_prepare_applier(
        replica_id,
        c.sync("prepare_seq"),
        c.coro("collect_commitment"),
        c.coro("handle_generated"),
        c.sync("stop_prepare_timer"),
    )


def test_prepare_applier_backup_emits_commit():
    c = Calls()
    apply = _applier(c, replica_id=1)  # backup
    p = _prepare(requests=[_req(seq=1), _req(seq=2)])
    run(apply(p))
    # every embedded request marked prepared + timer stopped, commitment
    # collected for the primary, then an own COMMIT
    assert c.names() == [
        "prepare_seq",
        "stop_prepare_timer",
        "prepare_seq",
        "stop_prepare_timer",
        "collect_commitment",
        "handle_generated",
    ]
    (gen,) = c.log[-1][1]
    assert isinstance(gen, Commit) and gen.replica_id == 1 and gen.prepare is p
    assert c.log[-2][1] == (p.replica_id, p)


def test_prepare_applier_own_prepare_no_commit():
    # The primary processes its own PREPARE from the log replay — it counts
    # the commitment but must not commit to itself (reference
    # prepare.go:86-90 guards on ownership).
    c = Calls()
    apply = _applier(c, replica_id=0)  # == prepare.replica_id
    run(apply(_prepare()))
    assert "handle_generated" not in c.names()
    assert "collect_commitment" in c.names()


# ---------------------------------------------------------------------------
# commit.py — make_commit_validator (reference core/commit.go:74-92)


def test_commit_validator_rejects_primary_committer():
    c = Calls()
    validate = commit_mod.make_commit_validator(
        4, c.coro("validate_prepare"), c.coro("verify_ui")
    )
    commit = Commit(replica_id=0, prepare=_prepare(view=0, primary=0))
    with pytest.raises(api.AuthenticationError):
        run(validate(commit))
    assert c.log == []


def test_commit_validator_validates_prepare_then_ui():
    c = Calls()
    validate = commit_mod.make_commit_validator(
        4, c.coro("validate_prepare"), c.coro("verify_ui")
    )
    p = _prepare()
    commit = Commit(replica_id=2, prepare=p)
    run(validate(commit))
    assert c.log == [("validate_prepare", (p,)), ("verify_ui", (commit,))]


def test_commit_validator_prepare_failure_short_circuits():
    c = Calls()
    c.raises["validate_prepare"] = api.AuthenticationError("bad prepare")
    validate = commit_mod.make_commit_validator(
        4, c.coro("validate_prepare"), c.coro("verify_ui")
    )
    with pytest.raises(api.AuthenticationError):
        run(validate(Commit(replica_id=2, prepare=_prepare())))
    assert "verify_ui" not in c.names()


def test_commit_applier_delegates():
    c = Calls()
    apply = commit_mod.make_commit_applier(c.coro("collect"))
    p = _prepare()
    run(apply(Commit(replica_id=3, prepare=p)))
    assert c.log == [("collect", (3, p))]


# ---------------------------------------------------------------------------
# commit.py — CommitmentCollector branches not covered by test_commit.py
# (reference core/commit_test.go:112-320)


def test_collector_view_transitions():
    async def scenario():
        executed = []

        async def execute(request):
            executed.append((request.seq))

        col = commit_mod.CommitmentCollector(1, execute)
        # view 1 commitment accepted (CV numbering starts at 1 per view)
        await col.collect(1, _prepare(cv=1, view=1, primary=1))
        # stale view-0 commitment from the same replica is ignored, even
        # with a CV that would otherwise be a skip
        await col.collect(1, _prepare(cv=9, view=0, primary=0))
        # view 2: CV numbering restarts at 1; a later view resets `last`
        await col.collect(1, _prepare(cv=1, view=2, primary=2))
        return executed

    assert run(scenario()) == []


def test_collector_counter_view_reset_and_straggler():
    async def scenario():
        executed = []

        async def execute(request):
            executed.append(request.seq)

        col = commit_mod.CommitmentCollector(1, execute)  # quorum = 2
        # full quorum in view 1
        await col.collect(1, _prepare(cv=1, view=1, primary=1))
        await col.collect(2, _prepare(cv=1, view=1, primary=1))
        assert executed == [1]
        # straggler for the released CV must not re-execute
        await col.collect(3, _prepare(cv=1, view=1, primary=1))
        assert executed == [1]
        # view 2 resets the counter: a fresh quorum at CV 1 executes again
        await col.collect(1, _prepare(cv=1, view=2, primary=2))
        await col.collect(2, _prepare(cv=1, view=2, primary=2))
        return executed

    assert run(scenario()) == [1, 1]


def test_collector_batched_prepare_executes_in_batch_order():
    async def scenario():
        executed = []

        async def execute(request):
            executed.append(request.seq)

        col = commit_mod.CommitmentCollector(1, execute)
        reqs = [_req(client_id=1, seq=4), _req(client_id=2, seq=9)]
        p = Prepare(replica_id=0, view=0, requests=reqs, ui=UI(counter=1))
        await col.collect(0, p)
        await col.collect(1, p)
        return executed

    assert run(scenario()) == [4, 9]


# ---------------------------------------------------------------------------
# request.py closures (reference core/request.go:146-276)


def test_request_validator_delegates():
    c = Calls()
    validate = request_mod.make_request_validator(c.coro("verify"))
    r = _req()
    run(validate(r))
    assert c.log == [("verify", (r,))]


class _FakeViewState:
    def __init__(self, view=0):
        self.view = view

    def hold_view_lease(self):
        import contextlib

        @contextlib.asynccontextmanager
        async def lease():
            yield (self.view, self.view)

        return lease()


class _FakePending:
    def __init__(self):
        self.added = []
        self.removed = []

    def add(self, req):
        self.added.append(req)

    def remove(self, req):
        self.removed.append(req)


def test_request_processor_duplicate_seq_skips_apply():
    c = Calls()
    c.returns["capture_seq"] = False
    pending = _FakePending()
    process = request_mod.make_request_processor(
        c.coro("capture_seq"), pending, _FakeViewState(), c.coro("apply")
    )
    assert run(process(_req())) is False
    assert pending.added == [] and "apply" not in c.names()


def test_request_processor_new_seq_applies_under_view():
    c = Calls()
    c.returns["capture_seq"] = True
    pending = _FakePending()
    r = _req()
    process = request_mod.make_request_processor(
        c.coro("capture_seq"), pending, _FakeViewState(view=3), c.coro("apply")
    )
    assert run(process(r)) is True
    assert pending.added == [r]
    assert c.log[-1] == ("apply", (r, 3))


def test_request_applier_primary_proposes():
    c = Calls()
    apply = request_mod.make_request_applier(
        0, 4, c.coro("propose"), c.sync("prepare_timer"), c.sync("request_timer")
    )
    r = _req()
    run(apply(r, 0))  # view 0 -> replica 0 is primary
    assert "propose" in c.names() and "prepare_timer" not in c.names()
    assert ("request_timer", (r, 0)) in c.log


def test_request_applier_backup_starts_prepare_timer():
    c = Calls()
    apply = request_mod.make_request_applier(
        1, 4, c.coro("propose"), c.sync("prepare_timer"), c.sync("request_timer")
    )
    r = _req()
    run(apply(r, 0))  # view 0 -> replica 1 is a backup
    assert "propose" not in c.names()
    assert ("prepare_timer", (r, 0)) in c.log
    assert ("request_timer", (r, 0)) in c.log


def test_request_executor_full_path_and_dedup():
    async def scenario():
        c = Calls()
        pending = _FakePending()
        delivered = []
        replies = []

        class Consumer:
            async def deliver(self, op):
                delivered.append(op)
                return b"result:" + op

            def state_digest(self):
                return b""

        retired = {"n": 0}

        def retire(req):
            retired["n"] += 1
            return retired["n"] == 1  # second call = duplicate

        execute = request_mod.make_request_executor(
            5,
            retire,
            pending,
            c.sync("stop_timers"),
            Consumer(),
            c.coro("sign"),  # the executor awaits the batch-aware signer
            replies.append,
        )
        r = _req(client_id=9, seq=4)
        await execute(r)
        await execute(r)  # duplicate: retire_seq false -> no effects
        # REPLY signing runs off the execution chain (spawned task — see
        # make_request_executor): drain it before asserting.
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        return c, pending, delivered, replies, r

    c, pending, delivered, replies, r = run(scenario())
    assert delivered == [b"op"]
    assert pending.removed == [r]
    (reply,) = replies
    assert isinstance(reply, Reply)
    assert (reply.replica_id, reply.client_id, reply.seq) == (5, 9, 4)
    assert reply.result == b"result:op"
    assert c.names() == ["stop_timers", "sign"]


def test_request_replier_returns_buffered_reply():
    async def scenario():
        states = ClientStates()
        r = _req(client_id=3, seq=1)
        reply = Reply(replica_id=0, client_id=3, seq=1, result=b"ok")
        states.client(3).add_reply(1, reply)
        reply_req = request_mod.make_request_replier(states)
        return await reply_req(r)

    assert run(scenario()).result == b"ok"


def test_seq_closures_delegate_to_clientstate():
    async def scenario():
        states = ClientStates()
        capture = request_mod.make_seq_capturer(states)
        release = request_mod.make_seq_releaser(states)
        prep = request_mod.make_seq_preparer(states)
        retire = request_mod.make_seq_retirer(states)
        r = _req(client_id=2, seq=1)
        assert await capture(r) is True
        assert await capture(_req(client_id=2, seq=1)) is False  # dup
        await release(r)
        prep(r)
        assert retire(r) is True
        assert retire(r) is False  # already retired
        return True

    assert run(scenario())


# ---------------------------------------------------------------------------
# usig_ui.py (reference core/usig-ui.go:37-91)


class _FakeAuth(api.Authenticator):
    def __init__(self):
        self.verified = []
        self.fail = None
        self.counter = 0

    def generate_message_authen_tag(self, role, data, audience=-1):
        self.counter += 1
        return ui_to_bytes(UI(counter=self.counter, cert=b"cert"))

    async def verify_message_authen_tag(self, role, peer_id, data, tag):
        self.verified.append((role, peer_id, data, tag))
        if self.fail is not None:
            raise self.fail


def test_ui_verifier_branches():
    async def scenario():
        auth = _FakeAuth()
        verify = usig_ui.make_ui_verifier(auth)
        p = _prepare()

        # missing UI
        p_missing = _prepare()
        p_missing.ui = None
        with pytest.raises(api.AuthenticationError):
            await verify(p_missing)
        # zero counter (reference core/usig-ui.go:65-67)
        p_zero = _prepare()
        p_zero.ui = UI(counter=0, cert=b"c")
        with pytest.raises(api.AuthenticationError):
            await verify(p_zero)
        assert auth.verified == []  # rejected before any crypto

        ui = await verify(p)
        assert ui is p.ui
        role, peer, _, tag = auth.verified[0]
        assert role is api.AuthenticationRole.USIG
        assert peer == p.replica_id
        assert tag == ui_to_bytes(p.ui)

        auth.fail = api.AuthenticationError("bad")
        with pytest.raises(api.AuthenticationError):
            await verify(p)
        return True

    assert run(scenario())


def test_ui_assigner_attaches_ui():
    auth = _FakeAuth()
    assign = usig_ui.make_ui_assigner(auth)
    p = _prepare()
    p.ui = None
    assign(p)
    assert p.ui.counter == 1
    assign(p)
    assert p.ui.counter == 2  # fresh tag every call


def test_ui_capturer_in_order_once_only():
    async def scenario():
        from minbft_tpu.core.internal.peerstate import PeerStates

        capture = usig_ui.make_ui_capturer(PeerStates())
        first = _prepare(cv=1)
        assert await capture(first) is True
        assert await capture(first) is False  # replay
        # CV 3 must wait for CV 2: parks until 2 is captured
        waiter = asyncio.ensure_future(capture(_prepare(cv=3)))
        await asyncio.sleep(0)
        assert not waiter.done()
        assert await capture(_prepare(cv=2)) is True
        assert await waiter is True
        return True

    assert run(scenario())
