"""Client robustness: retransmission, per-request timeout, reply dedup
(reference clients rely on stream replay, core/message-handling.go:316-350;
this build's client retransmits explicitly — VERDICT r1 weak #8)."""

import asyncio

import pytest

from minbft_tpu import api
from minbft_tpu.client import new_client
from conftest import make_cluster as _cluster
from minbft_tpu.sample.conn.inprocess import InProcessClientConnector


class _LossyClientConnector(api.ReplicaConnector):
    """Drops the first ``drop`` messages of every stream — the fault the
    retransmitter exists for."""

    def __init__(self, inner: api.ReplicaConnector, drop: int):
        self._inner = inner
        self._drop = drop

    def replica_message_stream_handler(self, replica_id):
        inner_handler = self._inner.replica_message_stream_handler(replica_id)
        if inner_handler is None:
            return None
        drop = self._drop

        class _Lossy(api.MessageStreamHandler):
            async def handle_message_stream(self, in_stream):
                async def filtered():
                    seen = 0
                    async for data in in_stream:
                        seen += 1
                        if seen <= drop:
                            continue  # lost on the wire
                        yield data

                async for out in inner_handler.handle_message_stream(filtered()):
                    yield out

        return _Lossy()


def test_retransmit_recovers_lost_request():
    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        # every replica drops the client's first message: without
        # retransmission the request would hang forever
        conn = _LossyClientConnector(InProcessClientConnector(stubs), drop=1)
        client = new_client(
            0, 4, 1, c_auths[0], conn, seq_start=0, retransmit_interval=0.1
        )
        await client.start()
        result = await asyncio.wait_for(client.request(b"lossy-op"), 30)
        assert result
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_request_timeout_without_retransmit():
    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _LossyClientConnector(InProcessClientConnector(stubs), drop=10**9)
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0)
        await client.start()
        with pytest.raises(asyncio.TimeoutError):
            await client.request(b"never", timeout=0.3)
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


class _DuplicatingConnector(api.ReplicaConnector):
    """Delivers every outgoing message twice — guarantees the replicas'
    duplicate-REQUEST path executes (no timing luck involved)."""

    def __init__(self, inner: api.ReplicaConnector):
        self._inner = inner

    def replica_message_stream_handler(self, replica_id):
        inner_handler = self._inner.replica_message_stream_handler(replica_id)
        if inner_handler is None:
            return None

        class _Dup(api.MessageStreamHandler):
            async def handle_message_stream(self, in_stream):
                async def doubled():
                    async for data in in_stream:
                        yield data
                        yield data  # the duplicate

                async for out in inner_handler.handle_message_stream(doubled()):
                    yield out

        return _Dup()


def test_duplicate_request_replied_but_executed_once():
    """Replicas reply to a duplicate REQUEST (the client may be retrying a
    lost reply — reference message-handling.go:396-403) but execute it
    exactly once."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _DuplicatingConnector(InProcessClientConnector(stubs))
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0)
        await client.start()
        assert await asyncio.wait_for(client.request(b"once"), 30)
        assert await asyncio.wait_for(client.request(b"twice"), 30)
        # let the duplicates drain, then check exactly-once execution
        await asyncio.sleep(0.3)
        for _ in range(100):
            if all(lg.length == 2 for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        assert all(lg.length == 2 for lg in ledgers), [lg.length for lg in ledgers]
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_ed25519_scheme_cluster_commit():
    """Full commit with the Ed25519 signature scheme (BASELINE config 5's
    scheme) on the SIM backend."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster(scheme="ed25519")
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        assert await asyncio.wait_for(client.request(b"ed-op"), 60)
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


class _FlakyClientConnector(api.ReplicaConnector):
    """Every replica's FIRST stream swallows one frame and dies — the
    mid-flight connection drop the client's reconnect loop exists for.
    Later attempts delegate to the real connector."""

    def __init__(self, inner: api.ReplicaConnector):
        self._inner = inner
        self.attempts: dict = {}

    def replica_message_stream_handler(self, replica_id):
        inner_handler = self._inner.replica_message_stream_handler(replica_id)
        if inner_handler is None:
            return None
        outer = self

        class _Flaky(api.MessageStreamHandler):
            async def handle_message_stream(self, in_stream):
                n = outer.attempts.get(replica_id, 0) + 1
                outer.attempts[replica_id] = n
                if n == 1:
                    # consume the request, then the connection drops: the
                    # frame is gone — no retransmit timer is configured, so
                    # only the reconnect re-send can ever recover it
                    async for _ in in_stream:
                        return
                    yield b""  # pragma: no cover - async-generator marker
                    return
                async for out in inner_handler.handle_message_stream(in_stream):
                    yield out

        return _Flaky()


def test_client_reconnects_after_stream_drop():
    """A dropped replica stream is redialed with backoff and every pending
    request re-sent: losing >f streams permanently would wedge all future
    requests (f+1 matching replies needed) even with healthy replicas."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _FlakyClientConnector(InProcessClientConnector(stubs))
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0)
        await client.start()
        # no retransmit_interval: completion proves the reconnect re-send
        result = await asyncio.wait_for(client.request(b"flaky-op"), 30)
        assert result
        assert all(n >= 2 for n in conn.attempts.values()), conn.attempts
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_reconnect_backoff_ladder():
    """Shared redial policy: exponential growth to the cap, reset only
    after a lived connection (a crash-looping peer must not be rewarded)."""
    from minbft_tpu.utils.backoff import ReconnectBackoff

    b = ReconnectBackoff(start_s=0.2, cap_s=10.0, lived_reset_s=5.0,
                         jitter_frac=0.0)
    assert [b.next_delay(0.0) for _ in range(7)] == [
        0.2, 0.4, 0.8, 1.6, 3.2, 6.4, 10.0,
    ]
    assert b.next_delay(0.0) == 10.0  # pinned at the cap
    assert b.next_delay(6.0) == 0.2   # lived >5s: ladder restarts
    assert b.next_delay(0.1) == 0.4


def test_reconnect_backoff_default_jitter_desynchronizes():
    """Two ladders born in the same tick (a partition heal ends every
    stream at once) must NOT redial in lockstep: the default jitter makes
    their delay sequences diverge while staying in the +-25% envelope."""
    import random

    from minbft_tpu.utils.backoff import ReconnectBackoff

    a = ReconnectBackoff(rng=random.Random(1))
    b = ReconnectBackoff(rng=random.Random(2))
    da = [a.next_delay(0.0) for _ in range(6)]
    db = [b.next_delay(0.0) for _ in range(6)]
    assert da != db
    ladder = 0.2
    for x, y in zip(da, db):
        for d in (x, y):
            assert ladder * 0.75 - 1e-9 <= d <= min(ladder * 1.25, 10.0) + 1e-9
        ladder = min(ladder * 2.0, 10.0)


def test_retransmit_backoff_ladder():
    """Client retransmit policy: capped exponential with jitter — the
    un-jittered ladder doubles from start to the 8x default cap, jittered
    delays stay in the envelope, and start_s must be positive."""
    import random

    import pytest

    from minbft_tpu.utils.backoff import RetransmitBackoff

    b = RetransmitBackoff(0.1, jitter_frac=0.0)
    assert [round(b.next_delay(), 10) for _ in range(6)] == [
        0.1, 0.2, 0.4, 0.8, 0.8, 0.8,
    ]
    b2 = RetransmitBackoff(0.1, cap_s=0.3, jitter_frac=0.0)
    assert [round(b2.next_delay(), 10) for _ in range(4)] == [
        0.1, 0.2, 0.3, 0.3,
    ]
    jb = RetransmitBackoff(0.1, jitter_frac=0.25, rng=random.Random(7))
    ladder = 0.1
    seen_off_ladder = False
    for _ in range(8):
        d = jb.next_delay()
        assert ladder * 0.75 - 1e-9 <= d <= min(ladder * 1.25, 0.8) + 1e-9
        seen_off_ladder = seen_off_ladder or abs(d - ladder) > 1e-9
        ladder = min(ladder * 2.0, 0.8)
    assert seen_off_ladder  # jitter actually moved the delays
    with pytest.raises(ValueError):
        RetransmitBackoff(0.0)


class _ChaosClientConnector(api.ReplicaConnector):
    """Kills every stream after it has delivered ``frames_per_life`` reply
    frames — repeated mid-run drops under pipelined load, the worst case
    for the redial loop's queue swap + pending re-send."""

    def __init__(self, inner: api.ReplicaConnector, frames_per_life: int):
        self._inner = inner
        self._frames_per_life = frames_per_life
        self.drops = 0

    def replica_message_stream_handler(self, replica_id):
        inner_handler = self._inner.replica_message_stream_handler(replica_id)
        if inner_handler is None:
            return None
        outer = self

        class _Chaos(api.MessageStreamHandler):
            async def handle_message_stream(self, in_stream):
                served = 0
                async for out in inner_handler.handle_message_stream(in_stream):
                    yield out
                    served += 1
                    if served >= outer._frames_per_life:
                        outer.drops += 1
                        return  # the connection dies mid-conversation

        return _Chaos()


def test_client_pipelined_load_survives_repeated_stream_drops():
    """30 pipelined requests complete while every replica stream dies
    after each 3 delivered frames — the redial loop must keep swapping
    queues and re-sending without losing or double-counting any request."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _ChaosClientConnector(InProcessClientConnector(stubs), 3)
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0, max_inflight=10)
        await client.start()
        results = await asyncio.wait_for(
            asyncio.gather(
                *(client.request(b"chaos-%d" % i) for i in range(30))
            ),
            60,
        )
        assert all(results)
        assert conn.drops > 0, "chaos connector never dropped a stream"
        # exactly-once execution despite every re-send
        for _ in range(100):
            if all(lg.length == 30 for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        assert all(lg.length == 30 for lg in ledgers), [lg.length for lg in ledgers]
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


class _CountingConnector(api.ReplicaConnector):
    """Transparent passthrough that counts dials per replica."""

    def __init__(self, inner: api.ReplicaConnector):
        self._inner = inner
        self.dials: dict = {}

    def replica_message_stream_handler(self, replica_id):
        inner_handler = self._inner.replica_message_stream_handler(replica_id)
        if inner_handler is None:
            return None
        outer = self

        class _C(api.MessageStreamHandler):
            async def handle_message_stream(self, in_stream):
                outer.dials[replica_id] = outer.dials.get(replica_id, 0) + 1
                async for out in inner_handler.handle_message_stream(in_stream):
                    yield out

        return _C()


def test_client_reply_verifier_outage_poisons_stream_but_never_severs():
    """Non-auth exceptions in reply handling (e.g. a transient verifier
    backend outage) cost frames, then — after a consecutive run — the
    STREAM (backoff redial), but never the connection permanently: a
    transient outage severing >f streams forever would wedge every future
    request against healthy replicas."""

    async def run():
        from minbft_tpu.client.client import _MAX_CONSECUTIVE_REPLY_ERRORS

        replicas, c_auths, stubs, ledgers = await _cluster()
        auth = c_auths[0]
        real_verify = auth.verify_message_authen_tag
        state = {"fail": True, "raised": 0}
        # pigeonhole: this many raises across 4 streams forces at least
        # one stream past the per-stream guard, whatever its value
        outage = 4 * _MAX_CONSECUTIVE_REPLY_ERRORS + 4

        async def flaky_verify(role, rid, data, sig):
            if state["fail"] and role == api.AuthenticationRole.REPLICA:
                state["raised"] += 1
                if state["raised"] >= outage:
                    state["fail"] = False
                raise RuntimeError("verifier backend outage")
            return await real_verify(role, rid, data, sig)

        auth.verify_message_authen_tag = flaky_verify
        conn = _CountingConnector(InProcessClientConnector(stubs))
        client = new_client(
            0, 4, 1, auth, conn, seq_start=0, retransmit_interval=0.05
        )
        await client.start()
        result = await asyncio.wait_for(client.request(b"verifier-outage"), 30)
        assert result
        # at least one stream hit the consecutive-failure guard and was
        # redialed rather than severed
        assert max(conn.dials.values()) >= 2, conn.dials
        assert state["raised"] >= outage - 4, state
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_stop_fails_inflight_requests_instead_of_hanging():
    """stop() must resolve in-flight requests with an error: their reply
    streams are gone, so leaving the futures pending parks the callers
    forever."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _LossyClientConnector(InProcessClientConnector(stubs), drop=10**9)
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0)
        await client.start()
        task = asyncio.ensure_future(client.request(b"never-answered"))
        await asyncio.sleep(0.1)
        await client.stop()
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(task, 5)
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_stop_fails_requests_parked_on_the_inflight_semaphore():
    """A caller that passed the started check but was parked on the
    max_inflight semaphore when stop() swept the pending map must fail
    fast too — registering after the sweep would hang forever."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        conn = _LossyClientConnector(InProcessClientConnector(stubs), drop=10**9)
        client = new_client(0, 4, 1, c_auths[0], conn, seq_start=0, max_inflight=1)
        await client.start()
        t1 = asyncio.ensure_future(client.request(b"in-flight"))
        await asyncio.sleep(0.05)
        t2 = asyncio.ensure_future(client.request(b"parked-on-semaphore"))
        await asyncio.sleep(0.05)
        await client.stop()
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(t1, 5)
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(t2, 5)
        for r in replicas:
            await r.stop()

    asyncio.run(run())
