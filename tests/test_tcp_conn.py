"""Native TCP transport unit tests (sample/conn/tcp): frame round-trips,
chat-kind routing, the frame-length cap, late-binding dial retry, and
server stop with live connections (the 3.12 wait_closed regression)."""

import asyncio
import struct

import pytest

from minbft_tpu import api
from minbft_tpu.sample.conn.tcp import (
    CLIENT_KIND,
    MAX_FRAME,
    TcpReplicaConnector,
    TcpReplicaServer,
)


class _EchoHandler(api.MessageStreamHandler):
    def __init__(self, tag: bytes):
        self._tag = tag

    async def handle_message_stream(self, in_stream):
        async for data in in_stream:
            yield self._tag + data


class _EchoConn(api.ConnectionHandler):
    def peer_message_stream_handler(self):
        return _EchoHandler(b"P:")

    def client_message_stream_handler(self):
        return _EchoHandler(b"C:")


async def _drive(handler, frames, n_expect):
    sent = asyncio.Event()

    async def outgoing():
        for fr in frames:
            yield fr
        sent.set()
        await asyncio.sleep(30)  # keep the stream open

    out = handler.handle_message_stream(outgoing())
    got = []
    try:
        while len(got) < n_expect:
            got.append(await asyncio.wait_for(out.__anext__(), 10))
    finally:
        await out.aclose()
    return got


def test_round_trip_and_kind_routing():
    async def scenario():
        server = TcpReplicaServer(_EchoConn())
        addr = await server.start("127.0.0.1:0")
        try:
            for kind, tag in (("peer", b"P:"), ("client", b"C:")):
                conn = TcpReplicaConnector(kind)
                conn.connect_replica(0, addr)
                h = conn.replica_message_stream_handler(0)
                frames = [b"alpha", b"x" * 70_000, b""]
                got = await _drive(h, frames, len(frames))
                assert got == [tag + f for f in frames]
        finally:
            await server.stop()
        return True

    assert asyncio.run(scenario())


def test_oversized_frame_closes_connection_only():
    """A length prefix past MAX_FRAME is a hostile/corrupt stream: that
    connection dies; the server keeps serving others."""

    async def scenario():
        server = TcpReplicaServer(_EchoConn())
        addr = await server.start("127.0.0.1:0")
        host, port = addr.rsplit(":", 1)
        try:
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(CLIENT_KIND + struct.pack(">I", MAX_FRAME + 1))
            await writer.drain()
            # server closes on the bogus prefix: EOF on our side
            assert await asyncio.wait_for(reader.read(), 10) == b""
            writer.close()

            # a well-behaved connection still works afterwards
            conn = TcpReplicaConnector("client")
            conn.connect_replica(0, addr)
            got = await _drive(
                conn.replica_message_stream_handler(0), [b"ok"], 1
            )
            assert got == [b"C:ok"]
        finally:
            await server.stop()
        return True

    assert asyncio.run(scenario())


def test_dial_retries_until_server_binds():
    """wait_for_ready semantics: the dialer retries while the server is
    still coming up (clusters start in any order)."""

    async def scenario():
        from minbft_tpu.utils.netports import free_base_port

        port = free_base_port(1)
        conn = TcpReplicaConnector("peer", dial_timeout=30.0)
        conn.connect_replica(0, f"127.0.0.1:{port}")
        h = conn.replica_message_stream_handler(0)

        async def late_server():
            await asyncio.sleep(0.5)
            server = TcpReplicaServer(_EchoConn())
            await server.start(f"127.0.0.1:{port}")
            return server

        server_task = asyncio.ensure_future(late_server())
        got = await _drive(h, [b"late"], 1)
        assert got == [b"P:late"]
        await (await server_task).stop()
        return True

    assert asyncio.run(scenario())


def test_server_stop_with_live_connections_returns():
    """Regression: in 3.12 Server.wait_closed() waits for connection
    handlers to finish, and ours run until their stream ends — stop()
    must cancel them or it hangs forever."""

    async def scenario():
        server = TcpReplicaServer(_EchoConn())
        addr = await server.start("127.0.0.1:0")
        conn = TcpReplicaConnector("peer")
        conn.connect_replica(0, addr)
        h = conn.replica_message_stream_handler(0)
        got = await _drive(h, [b"live"], 1)  # stream opened and exercised
        assert got == [b"P:live"]
        # another stream left OPEN while the server stops
        open_stream = h.handle_message_stream(_forever())
        first = await asyncio.wait_for(open_stream.__anext__(), 10)
        assert first == b"P:first"  # the connection is live right now
        await asyncio.wait_for(server.stop(), 10)  # must not hang
        # the dropped connection ends the stream instead of wedging it
        with pytest.raises(StopAsyncIteration):
            await asyncio.wait_for(open_stream.__anext__(), 10)
        return True

    async def _forever():
        yield b"first"
        await asyncio.sleep(30)

    assert asyncio.run(scenario())


def test_server_stop_honors_grace_window():
    """ADVICE r5: stop(grace) gives live handlers the grace window to
    drain before cancellation (and still returns promptly after it), and
    stop with no live connections skips the wait entirely."""

    async def scenario():
        server = TcpReplicaServer(_EchoConn())
        addr = await server.start("127.0.0.1:0")
        conn = TcpReplicaConnector("peer")
        conn.connect_replica(0, addr)
        h = conn.replica_message_stream_handler(0)
        open_stream = h.handle_message_stream(_forever())
        first = await asyncio.wait_for(open_stream.__anext__(), 10)
        assert first == b"P:one"
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await asyncio.wait_for(server.stop(grace=0.3), 10)
        elapsed = loop.time() - t0
        # the never-ending stream forces the full grace wait, then cancel
        assert 0.25 <= elapsed < 5.0, elapsed
        with pytest.raises(StopAsyncIteration):
            await asyncio.wait_for(open_stream.__anext__(), 10)

        # no live connections: grace adds no delay
        server2 = TcpReplicaServer(_EchoConn())
        await server2.start("127.0.0.1:0")
        t0 = loop.time()
        await asyncio.wait_for(server2.stop(grace=5.0), 10)
        assert loop.time() - t0 < 1.0
        return True

    async def _forever():
        yield b"one"
        await asyncio.sleep(30)

    assert asyncio.run(scenario())
