"""Batch-ingest runtime tests (ISSUE 6).

- Differential fuzz: ``messages.codec.unmarshal_batch`` vs the scalar
  ``unmarshal`` oracle over 1000+ random well-formed AND corrupted
  frames — corrupt frames must fail ITEM-WISE, never poison the bundle
  (the ``prepare_batch_scalar`` oracle pattern from the prep-vectorization
  round, applied to the codec).
- Engine batch feed: ``submit_many`` lands a whole bundle in ONE flush.
- ``Handlers.preverify_requests``: the batch verification seed shares
  the per-message memo discipline and fails item-wise.
- The bundle-ingest cluster path commits end-to-end, and the
  MINBFT_BUNDLE_INGEST=0 lever really reverts to the per-task pumps.
- ``_ConcurrentStreamProcessor.cancel`` iterates a snapshot (a task
  finishing during cancel mutates the set via its done-callback).
"""

import asyncio
import os
import random
import sys

import pytest

from minbft_tpu import api
from minbft_tpu.messages import (
    Checkpoint,
    Hello,
    Prepare,
    Reply,
    Request,
    authen_bytes,
    marshal,
    unmarshal,
    unmarshal_batch,
)
from minbft_tpu.messages import codec as codec_mod
from minbft_tpu.messages.codec import CodecError

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from conftest import make_cluster  # noqa: E402


def _clear_intern():
    codec_mod._intern.clear()
    codec_mod._intern_bytes = 0


def _random_messages(rng, n):
    """Well-formed messages across kinds, REQUEST-heavy (the hot path)."""
    msgs = []
    for k in range(n):
        pick = rng.random()
        if pick < 0.55:
            msgs.append(
                Request(
                    client_id=rng.randrange(2**32),
                    seq=rng.randrange(2**64),
                    operation=rng.randbytes(rng.randrange(0, 96)),
                    signature=rng.randbytes(rng.randrange(0, 96)),
                    read_mode=rng.randrange(3),
                )
            )
        elif pick < 0.7:
            msgs.append(
                Reply(
                    replica_id=rng.randrange(2**32),
                    client_id=rng.randrange(2**32),
                    seq=rng.randrange(2**64),
                    result=rng.randbytes(rng.randrange(0, 64)),
                    signature=rng.randbytes(rng.randrange(0, 64)),
                    read_only=bool(rng.getrandbits(1)),
                    error=bool(rng.getrandbits(1)),
                )
            )
        elif pick < 0.8:
            msgs.append(
                Hello(
                    replica_id=rng.randrange(2**32),
                    resume_counter=rng.randrange(2**64),
                    signature=rng.randbytes(rng.randrange(0, 64)),
                )
            )
        elif pick < 0.9:
            msgs.append(
                Prepare(
                    replica_id=rng.randrange(2**32),
                    view=rng.randrange(2**32),
                    requests=tuple(
                        Request(
                            client_id=rng.randrange(2**32),
                            seq=rng.randrange(2**32),
                            operation=rng.randbytes(rng.randrange(0, 24)),
                            signature=rng.randbytes(8),
                        )
                        for _ in range(rng.randrange(1, 4))
                    ),
                )
            )
        else:
            msgs.append(
                Checkpoint(
                    replica_id=rng.randrange(2**32),
                    count=rng.randrange(2**32),
                    digest=rng.randbytes(32),
                    view=rng.randrange(2**16),
                    cv=rng.randrange(2**32),
                    bounds=((rng.randrange(4), rng.randrange(2**16)),),
                    signature=rng.randbytes(64),
                )
            )
    return msgs


def _corrupt(rng, frame: bytes) -> bytes:
    b = bytearray(frame)
    mode = rng.randrange(5)
    if mode == 0 and b:  # bit flip anywhere (tag, lengths, payload)
        b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        return bytes(b)
    if mode == 1:  # truncation
        return bytes(b[: rng.randrange(len(b) + 1)])
    if mode == 2:  # trailing junk (must be rejected: one encoding per msg)
        return bytes(b) + rng.randbytes(rng.randrange(1, 8))
    if mode == 3:  # pure garbage
        return rng.randbytes(rng.randrange(0, 40))
    return b""  # empty frame


def test_unmarshal_batch_differential_fuzz():
    """1200+ frames through unmarshal_batch == item-by-item unmarshal:
    same accept/reject per frame, equal decoded messages, and a corrupt
    frame never affects its neighbours."""
    rng = random.Random(0xB16B00)
    frames = [marshal(m) for m in _random_messages(rng, 800)]
    frames += [_corrupt(rng, rng.choice(frames)) for _ in range(400)]
    rng.shuffle(frames)
    assert len(frames) >= 1200

    _clear_intern()
    got = unmarshal_batch(frames)
    _clear_intern()
    n_err = 0
    for fr, out in zip(frames, got):
        try:
            want = unmarshal(fr)
        except CodecError:
            want = None
        if want is None:
            n_err += 1
            assert isinstance(out, CodecError), (fr[:32], out)
        else:
            assert not isinstance(out, CodecError), (fr[:32], out)
            assert out == want
    # the corruption really exercised the reject path
    assert n_err >= 100


def test_unmarshal_batch_small_bundles_use_scalar_path():
    """Below the numpy threshold the contract is identical (item-wise
    values, errors as values)."""
    good = marshal(Request(client_id=1, seq=2, operation=b"x"))
    bad = good[:-1]
    out = unmarshal_batch([good, bad])
    assert isinstance(out[0], Request) and out[0].seq == 2
    assert isinstance(out[1], CodecError)


def test_unmarshal_batch_corrupt_frames_fail_item_wise():
    """A bundle mixing valid and corrupt REQUEST frames decodes every
    valid frame (large enough to take the vectorized path)."""
    rng = random.Random(7)
    reqs = [
        Request(client_id=i, seq=i * 7, operation=b"op-%d" % i,
                signature=b"s" * (i % 11))
        for i in range(64)
    ]
    frames = [marshal(r) for r in reqs]
    # corrupt every 4th frame
    for i in range(0, len(frames), 4):
        frames[i] = _corrupt(rng, frames[i])
    _clear_intern()
    out = unmarshal_batch(frames)
    for i, (r, got) in enumerate(zip(reqs, out)):
        if i % 4 == 0:
            continue  # may or may not decode (corruption is random)
        assert got == r, i


def test_unmarshal_batch_interns_requests():
    """Identical REQUEST wire bytes collapse to ONE object (the same
    dedup the scalar decoder provides for the n-replica fan-in)."""
    fr = marshal(Request(client_id=9, seq=9, operation=b"same"))
    _clear_intern()
    out = unmarshal_batch([fr] * 16)
    assert all(m is out[0] for m in out)
    # and a scalar decode of the same bytes hits the shared intern
    assert unmarshal(fr) is out[0]


def test_engine_submit_many_is_one_flush():
    """A bundle fed through verify_*_many lands as ONE engine batch
    (mean batch == bundle size), with per-item verdicts in order."""
    from minbft_tpu.parallel import BatchVerifier
    from minbft_tpu.utils import hostcrypto as hc
    import hashlib

    async def run():
        eng = BatchVerifier(max_batch=64, buckets=(64,))
        priv, pub = hc.keygen()
        items = []
        want = []
        for i in range(24):
            digest = hashlib.sha256(b"m%d" % i).digest()
            sig = hc.ecdsa_sign(priv, digest)
            if i % 5 == 0:  # corrupt some signatures: item-wise False
                sig = (sig[0], sig[1] ^ 1)
            items.append((pub, digest, sig))
            want.append(i % 5 != 0)
        got = await eng.verify_ecdsa_p256_host_many(items)
        assert got == want
        st = eng.stats["ecdsa_p256_host"]
        assert st.items == 24
        assert st.batches == 1, (st.batches, st.items)
        return True

    assert asyncio.run(run())


def test_preverify_seeds_one_engine_batch_and_coalesces():
    """Handlers.preverify_requests: a decoded bundle's outstanding
    signature checks reach the engine as ONE batch; the per-message
    validations that follow coalesce onto the seeded lanes (no second
    dispatch); failures stay item-wise on the per-message path; a
    revisit of validated requests seeds nothing."""
    from minbft_tpu.parallel import BatchVerifier

    async def run():
        engine = BatchVerifier(max_batch=64, buckets=(64,))
        replicas, c_auths, stubs, ledgers = await make_cluster(
            4, 1, n_clients=2, engines=[engine] * 4, batch_signatures=False
        )
        try:
            h = replicas[0].handlers
            assert h.authenticator.supports_batch_verify

            def signed(cid, seq, op):
                r = Request(client_id=cid, seq=seq, operation=op)
                r.signature = c_auths[cid].generate_message_authen_tag(
                    api.AuthenticationRole.CLIENT, authen_bytes(r)
                )
                return r

            good = [signed(i % 2, i, b"op%d" % i) for i in range(8)]
            bad = signed(0, 99, b"evil")
            bad.signature = b"\x00" * len(bad.signature)
            msgs = good[:4] + [bad] + good[4:]
            # cluster start-up (HELLO verification) may already have used
            # this queue: assert on DELTAS, not absolutes
            st0 = engine.stats.get("ecdsa_p256_host")
            items0 = st0.items if st0 else 0
            batches0 = st0.batches if st0 else 0
            assert h.preverify_requests(msgs) == len(msgs)
            # let the fire-and-forget seed land and resolve
            for t in list(h._bg_tasks):
                await t
            st = engine.stats["ecdsa_p256_host"]
            assert st.items - items0 == len(msgs)
            assert st.batches - batches0 == 1, (st.batches, st.items)
            # per-message validation: coalesces (memo/in-flight), no new
            # device items; the bad signature fails ONLY its request
            for m in good:
                await h.validate_message(m)
                assert h._marked(m, "_validated_by")
            with pytest.raises(api.AuthenticationError):
                await h.validate_message(bad)
            assert not h._marked(bad, "_validated_by")
            st = engine.stats["ecdsa_p256_host"]
            assert st.items - items0 == len(msgs), "per-message path re-dispatched"
            # already-validated requests seed nothing
            assert h.preverify_requests(good) == 0
            return True
        finally:
            for r in replicas:
                await r.stop()

    assert asyncio.run(run())


@pytest.mark.parametrize("bundle", ["1", "0"])
def test_cluster_commits_on_both_ingest_paths(bundle, monkeypatch):
    """End-to-end: the same small cluster commits with bundle ingest on
    (default) and with the MINBFT_BUNDLE_INGEST=0 per-task lever — and
    the ingest tick metrics appear exactly on the bundle path."""
    if bundle == "0":
        monkeypatch.setenv("MINBFT_BUNDLE_INGEST", "0")
    else:
        monkeypatch.delenv("MINBFT_BUNDLE_INGEST", raising=False)

    async def run():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        replicas, c_auths, stubs, ledgers = await make_cluster(4, 1)
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        try:
            for i in range(5):
                await asyncio.wait_for(client.request(b"tick-%d" % i), 30)
            ticks = sum(
                r.metrics.counters.get("ingest_ticks", 0) for r in replicas
            )
            if bundle == "0":
                assert ticks == 0
            else:
                assert ticks > 0
                frames = sum(
                    r.metrics.counters.get("ingest_frames", 0)
                    for r in replicas
                )
                assert frames >= ticks
            assert all(lg.length >= 5 for lg in ledgers)
            return True
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()

    assert asyncio.run(run())


def test_stream_processor_cancel_iterates_snapshot():
    """cancel() must tolerate a task finishing DURING the cancel sweep:
    its done-callback discards it from the live set mid-iteration."""
    from minbft_tpu.core.message_handling import _ConcurrentStreamProcessor

    proc = _ConcurrentStreamProcessor(None, None)

    class FinishingTask:
        def __init__(self, tasks):
            self._tasks = tasks

        def cancel(self):
            # what add_done_callback(self._tasks.discard) does when the
            # task was already completing: the set shrinks under cancel()
            self._tasks.discard(self)

    proc._tasks.update({FinishingTask(proc._tasks) for _ in range(8)})
    proc.cancel()  # must not raise "Set changed size during iteration"
    assert not proc._tasks


def test_uvloop_knob_tri_state(monkeypatch):
    from minbft_tpu.utils.loop import maybe_enable_uvloop, uvloop_requested

    monkeypatch.setenv("MINBFT_UVLOOP", "0")
    assert uvloop_requested() is False
    assert maybe_enable_uvloop() is False
    monkeypatch.setenv("MINBFT_UVLOOP", "auto")
    assert uvloop_requested() is None
    monkeypatch.setenv("MINBFT_UVLOOP", "1")
    assert uvloop_requested() is True
    # uvloop may or may not be installed: the call must never raise, and
    # must only report True when the policy really switched.
    got = maybe_enable_uvloop()
    try:
        import uvloop  # noqa: F401,DC401 (availability probe)

        assert got is True
        import asyncio as aio

        aio.set_event_loop_policy(None)  # restore for later tests
    except ImportError:
        assert got is False
