"""Batched ECDSA signing kernel: deterministic (RFC 6979) signatures must
be byte-identical to the host signer, and verify on both host and device."""

import hashlib

from minbft_tpu.ops import p256
from minbft_tpu.utils import hostcrypto as hc


def test_sign_batch_matches_host_and_verifies():
    items, expected = [], []
    for i in range(6):
        d, q = hc.keygen()
        digest = hashlib.sha256(b"sign-%d" % i).digest()
        items.append((d, digest))
        # ecdsa_sign_py is the deterministic RFC 6979 signer (the OpenSSL
        # fast path uses a random nonce, so only _py is byte-comparable)
        expected.append((q, digest, hc.ecdsa_sign_py(d, digest)))

    got = p256.sign_batch(items)
    for (r, s), (q, digest, host_sig) in zip(got, expected):
        assert (r, s) == host_sig  # deterministic k -> identical bytes
        assert hc.ecdsa_verify(q, digest, (r, s))

    # and the device verifier accepts the device-signed batch
    verify_items = [
        (q, digest, sig) for (q, digest, _), sig in zip(expected, got)
    ]
    assert list(p256.verify_batch(verify_items)) == [True] * len(items)

    # bucketed call: pads to the same device shape (no extra compile),
    # pad lanes discarded, results identical
    got_padded = p256.sign_batch(items[:3], bucket=len(items))
    assert got_padded == got[:3]
