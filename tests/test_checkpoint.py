"""Checkpoint tests (beyond the reference, whose checkpointing is a
reserved config knob): claim matching on the full (count, view, cv,
digest) position, certificate growth for the truncation audit, coverage
bookkeeping, batch-boundary emission, and the in-process cluster reaching
a stable checkpoint with every replica (primary included) emitting."""

import asyncio

from conftest import make_cluster
from minbft_tpu.core.checkpoint import (
    CheckpointCollector,
    CheckpointEmitter,
    CoverageTracker,
    checkpoint_digest,
)
from minbft_tpu.messages import UI, Checkpoint, Commit, Prepare, Request


def _cp(replica, count, digest=b"d" * 32, view=0, cv=0, bounds=()):
    return Checkpoint(
        replica_id=replica, count=count, digest=digest, view=view, cv=cv,
        bounds=tuple(bounds), signature=b"sig",
    )


def test_collector_stability_at_f_plus_1():
    col = CheckpointCollector(f=1)
    assert col.record(_cp(0, 4)) is False
    assert col.stable_count == 0
    assert col.record(_cp(1, 4)) is True  # f+1 = 2 matching
    assert col.stable_count == 4
    assert {c.replica_id for c in col.stable_certificate} == {0, 1}
    # below the watermark: ignored
    assert col.record(_cp(2, 3)) is False
    # next period
    assert col.record(_cp(2, 8)) is False
    assert col.record(_cp(0, 8)) is True
    assert col.stable_count == 8


def test_collector_divergent_digests_never_combine():
    col = CheckpointCollector(f=1)
    assert col.record(_cp(0, 4, digest=b"a" * 32)) is False
    # a different certified digest at the same count must not stack onto
    # the first one's quorum
    assert col.record(_cp(1, 4, digest=b"b" * 32)) is False
    assert col.stable_count == 0
    # neither does a different (view, cv) claim for the same digest
    assert col.record(_cp(3, 4, digest=b"a" * 32, cv=9)) is False
    assert col.stable_count == 0
    # a genuine match still stabilizes
    assert col.record(_cp(2, 4, digest=b"a" * 32)) is True
    assert col.stable_digest == b"a" * 32


def test_collector_certificate_grows_and_bounds_audit():
    """Late matching claims at the stable count keep growing the
    certificate, and certificate_for_bound picks the f+1 subset proving
    the deepest truncation base for a given replica."""
    col = CheckpointCollector(f=1)
    col.record(_cp(0, 4, bounds=[(2, 10)]))
    col.record(_cp(1, 4, bounds=[(2, 3)]))
    assert col.stable_count == 4
    # replica 2's provable base: the 2nd-largest attested bound = 3
    beta, cert = col.certificate_for_bound(2, quorum=2)
    assert beta == 3 and len(cert) == 2
    # a straggler's matching claim with a higher bound arrives late
    col.record(_cp(3, 4, bounds=[(2, 8)]))
    beta, cert = col.certificate_for_bound(2, quorum=2)
    assert beta == 8
    assert all(c.bound_for(2) >= 8 for c in cert)


def test_coverage_tracker_bounds():
    """Bounds advance past covered entries and stop before the first
    uncovered one — the validator-checkable truncation audit."""
    t = CoverageTracker()
    req = Request(client_id=1, seq=1, operation=b"x")
    prep_cv1 = Prepare(replica_id=0, view=0, request=req, ui=UI(counter=1))
    prep_cv9 = Prepare(replica_id=0, view=0, request=req, ui=UI(counter=9))
    # peer 1: commits to batches cv=1 (counter 1) then cv=9 (counter 2),
    # then its view-change for view 1 (counter 3)
    t.track(1, 1, Commit(replica_id=1, prepare=prep_cv1, ui=UI(counter=1)))
    t.track(1, 2, Commit(replica_id=1, prepare=prep_cv9, ui=UI(counter=2)))
    from minbft_tpu.messages import ViewChange

    t.track(1, 3, ViewChange(replica_id=1, new_view=1, log=(), ui=UI(counter=3)))
    # checkpoint at (view 0, cv 5): covers counter 1 only — the commit to
    # cv=9 blocks, so the bound stops at 1
    assert t.bounds_at(0, 5) == ((1, 1),)
    # checkpoint at (view 0, cv 9): covers the second commit, but the
    # view-1 transition has not concluded at view 0
    assert t.bounds_at(0, 9) == ((1, 2),)
    # checkpoints running in view 1 cover the concluded transition too
    assert t.bounds_at(1, 9) == ((1, 3),)


def test_emitter_cadence_batch_boundaries_and_disable():
    async def scenario():
        emitted = []

        class Consumer:
            def state_digest(self):
                return b"digest"

            def snapshot(self):
                return b"snap"

        async def emit(cp):
            emitted.append(cp)

        em = CheckpointEmitter(
            0, 2, Consumer(), lambda: ((1, 5),), lambda v, c: (), emit
        )
        # three deliveries, then a batch boundary: ONE checkpoint at the
        # boundary count (3), never mid-batch
        for _ in range(3):
            em.on_delivered()
        await em.on_batch_end(0, 1)
        assert [m.count for m in emitted] == [3]
        assert emitted[0].digest == checkpoint_digest(b"digest", 3, 0, 1, ((1, 5),))
        # the snapshot at the emission position is retained for transfer
        assert em.snapshot_for(3) == (0, 1, b"snap", ((1, 5),))
        # count 4 crosses the next multiple of the period -> emits
        em.on_delivered()
        await em.on_batch_end(0, 2)
        assert [m.count for m in emitted] == [3, 4]
        # count 5 crosses none -> no emission
        em.on_delivered()
        await em.on_batch_end(0, 3)
        assert [m.count for m in emitted] == [3, 4]

        emitted.clear()
        off = CheckpointEmitter(
            0, 0, Consumer(), lambda: (), lambda v, c: (), emit
        )
        for _ in range(5):
            off.on_delivered()
        await off.on_batch_end(0, 5)
        assert emitted == []
        return True

    assert asyncio.run(scenario())


def test_cluster_reaches_stable_checkpoints():
    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, checkpoint_period=4,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(n=4, f=1, cfg=cfg)
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        try:
            for k in range(10):
                await asyncio.wait_for(client.request(b"op-%d" % k), 30)
            deadline = asyncio.get_running_loop().time() + 10
            while asyncio.get_running_loop().time() < deadline:
                counts = [
                    r.handlers.checkpoint_collector.stable_count for r in replicas
                ]
                if all(c >= 8 for c in counts):
                    break
                await asyncio.sleep(0.05)
            assert all(c >= 8 for c in counts), counts
            digests = {
                r.handlers.checkpoint_collector.stable_digest for r in replicas
            }
            assert len(digests) == 1  # everyone stabilized the same state
            # every replica emitted, the primary included (signed
            # checkpoints consume no USIG counter, so the prepare-CV
            # sequence is untouched)
            assert all(
                r.handlers.metrics.counters.get("checkpoints_sent", 0) > 0
                for r in replicas
            )
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(scenario())


def test_cert_validator_rejects_duplicate_claimants():
    """ISSUE 20 edge: f+1 claims must come from DISTINCT replicas — one
    replica signing twice is one claimant, and a Byzantine claimant
    padding a certificate with its own replays must not reach quorum."""
    from minbft_tpu import api
    from minbft_tpu.core.checkpoint import make_cert_validator

    async def scenario():
        async def verify(cp):
            return None

        validate = make_cert_validator(1, verify)
        good = (_cp(0, 4), _cp(1, 4))
        assert (await validate(good)).count == 4
        dup = (_cp(0, 4), _cp(0, 4))
        try:
            await validate(dup)
        except api.AuthenticationError as e:
            assert "duplicate claimants" in str(e)
        else:
            raise AssertionError("duplicate claimants accepted")
        # short certificate: f claims are never enough
        try:
            await validate((_cp(0, 4),))
        except api.AuthenticationError as e:
            assert "f+1" in str(e)
        else:
            raise AssertionError("f-sized certificate accepted")
        return True

    assert asyncio.run(scenario())


def test_cert_validator_rejects_one_mismatched_digest():
    """f matching claims + 1 claim diverging in ANY position field
    (digest, count, view, or cv) invalidate the whole certificate — a
    near-quorum must never round up."""
    from minbft_tpu import api
    from minbft_tpu.core.checkpoint import make_cert_validator

    async def scenario():
        async def verify(cp):
            return None

        validate = make_cert_validator(1, verify)
        for bad in (
            _cp(1, 4, digest=b"X" * 32),
            _cp(1, 8),
            _cp(1, 4, cv=9),
            _cp(1, 4, view=2),
        ):
            try:
                await validate((_cp(0, 4), bad))
            except api.AuthenticationError as e:
                assert "do not match" in str(e)
            else:
                raise AssertionError(f"mismatched claim accepted: {bad}")
        return True

    assert asyncio.run(scenario())


def test_cert_validator_surfaces_signature_failure():
    """Every member's signature is verified — one forged claim in an
    otherwise matching certificate kills it."""
    from minbft_tpu import api
    from minbft_tpu.core.checkpoint import make_cert_validator

    async def scenario():
        async def verify(cp):
            if cp.replica_id == 1:
                raise api.AuthenticationError("forged claim")
            return None

        validate = make_cert_validator(1, verify)
        try:
            await validate((_cp(0, 4), _cp(1, 4)))
        except api.AuthenticationError as e:
            assert "forged" in str(e)
        else:
            raise AssertionError("forged member signature accepted")
        return True

    assert asyncio.run(scenario())


def test_collector_install_refuses_non_dominating_cert():
    """CheckpointCollector.install adopts an external certificate only
    when it is AHEAD of the local stable watermark: an equal or older
    cert (e.g. replayed from a lagging peer's LOG-BASE) must not replace
    the richer certificate already collected, nor churn cert_version."""
    col = CheckpointCollector(f=1)
    col.record(_cp(0, 8))
    col.record(_cp(1, 8))
    col.record(_cp(2, 8))  # late claim grows the certificate to 3
    assert col.stable_count == 8 and len(col.stable_certificate) == 3
    v = col.cert_version
    # same count: refused even though the incoming cert is valid
    col.install([_cp(1, 8), _cp(3, 8)])
    assert len(col.stable_certificate) == 3 and col.cert_version == v
    # older count: refused outright
    col.install([_cp(1, 4), _cp(3, 4)])
    assert col.stable_count == 8 and col.cert_version == v
    # empty cert: no-op, never a crash
    col.install([])
    assert col.stable_count == 8
    # genuinely newer: adopted wholesale
    col.install([_cp(1, 12), _cp(3, 12)])
    assert col.stable_count == 12
    assert {c.replica_id for c in col.stable_certificate} == {1, 3}
    assert col.cert_version == v + 1
