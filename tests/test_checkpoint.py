"""Checkpoint-certificate tests (beyond the reference, whose checkpointing
is a reserved config knob): emission cadence, f+1 stability, divergence
surfacing, and the in-process cluster reaching a stable checkpoint."""

import asyncio

from conftest import make_cluster
from minbft_tpu.core.checkpoint import CheckpointCollector, make_checkpoint_emitter
from minbft_tpu.messages import UI, Checkpoint


def _cp(replica, count, digest=b"d" * 32, cv=1):
    return Checkpoint(
        replica_id=replica, count=count, digest=digest, ui=UI(counter=cv)
    )


def test_collector_stability_at_f_plus_1():
    col = CheckpointCollector(f=1)
    assert col.record(_cp(0, 4)) is False
    assert col.stable_count == 0
    assert col.record(_cp(1, 4)) is True  # f+1 = 2 matching
    assert col.stable_count == 4
    assert {c.replica_id for c in col.stable_certificate} == {0, 1}
    # at/below the watermark: ignored
    assert col.record(_cp(2, 4)) is False
    assert col.record(_cp(2, 3)) is False
    # next period
    assert col.record(_cp(2, 8)) is False
    assert col.record(_cp(0, 8)) is True
    assert col.stable_count == 8


def test_collector_divergent_digests_never_combine():
    col = CheckpointCollector(f=1)
    assert col.record(_cp(0, 4, digest=b"a" * 32)) is False
    # a different certified digest at the same count must not stack onto
    # the first one's quorum
    assert col.record(_cp(1, 4, digest=b"b" * 32)) is False
    assert col.stable_count == 0
    # a genuine match still stabilizes
    assert col.record(_cp(2, 4, digest=b"a" * 32)) is True
    assert col.stable_digest == b"a" * 32


def test_emitter_cadence_and_disable():
    async def scenario():
        emitted = []

        class Consumer:
            def state_digest(self):
                return b"digest-%d" % len(emitted)

        async def handle_generated(msg):
            emitted.append(msg)

        emit = make_checkpoint_emitter(0, 2, Consumer(), handle_generated)
        for _ in range(5):
            await emit()
        assert [m.count for m in emitted] == [2, 4]
        assert all(isinstance(m, Checkpoint) for m in emitted)

        emitted.clear()
        off = make_checkpoint_emitter(0, 0, Consumer(), handle_generated)
        for _ in range(5):
            await off()
        assert emitted == []
        return True

    assert asyncio.run(scenario())


def test_cluster_reaches_stable_checkpoints():
    # Also the primary-gate regression: if the view-0 primary emitted
    # checkpoints, its prepare-CV sequence would gap and the cluster
    # would stall after the first checkpoint period (seen live).
    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, checkpoint_period=4,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(n=4, f=1, cfg=cfg)
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        try:
            for k in range(10):
                await asyncio.wait_for(client.request(b"op-%d" % k), 30)
            deadline = asyncio.get_running_loop().time() + 10
            while asyncio.get_running_loop().time() < deadline:
                counts = [
                    r.handlers.checkpoint_collector.stable_count for r in replicas
                ]
                if all(c >= 8 for c in counts):
                    break
                await asyncio.sleep(0.05)
            assert all(c >= 8 for c in counts), counts
            digests = {
                r.handlers.checkpoint_collector.stable_digest for r in replicas
            }
            assert len(digests) == 1  # everyone stabilized the same state
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(scenario())
