"""Bench regression gate tests (tools/benchgate, ISSUE 8): pass /
regression / stddev-band / relative-floor behavior, the hard
tpu_unavailable refusal, missing-key handling, and the CLI exit codes
CI relies on (0 pass, 1 regression, 2 refusal)."""

import json

import pytest

from tools import benchgate
from tools.benchgate import BackendMismatch, __main__ as benchgate_cli


def _artifact(mean, stddev=0.0, prefix="e2e", backend="cpu",
              tpu_unavailable=True, **extra):
    art = {
        "backend": backend,
        f"{prefix}_req_per_sec_mean": mean,
        f"{prefix}_req_per_sec_stddev": stddev,
        f"{prefix}_req_per_sec_runs": [mean],
        f"{prefix}_committed_req_per_sec": mean,
    }
    if tpu_unavailable:
        art["tpu_unavailable"] = True
    art.update(extra)
    return art


def test_identical_artifacts_pass():
    base = _artifact(100.0, 5.0)
    report = benchgate.compare(base, dict(base))
    assert report.ok
    assert report.results[0].status == "ok"
    assert report.backend_kind == "cpu-fallback"


def test_regression_beyond_both_bands_fails():
    base = _artifact(100.0, 2.0)
    cand = _artifact(60.0, 2.0)  # -40%: outside 3σ AND the 30% floor
    report = benchgate.compare(base, cand)
    assert not report.ok
    r = report.results[0]
    assert r.status == "regression" and r.drop == pytest.approx(40.0)


def test_stddev_band_absorbs_noisy_drop():
    """A drop inside sigmas*sqrt(σb²+σc²) is noise, not a regression —
    the _runs/_mean/_stddev triples exist exactly for this judgment."""
    base = _artifact(100.0, 15.0)
    cand = _artifact(62.0, 15.0)  # drop 38 < 3*sqrt(450) ≈ 63.6
    assert benchgate.compare(base, cand).ok
    # tighten the band and the same drop regresses
    assert not benchgate.compare(base, cand, sigmas=1.0, rel_floor=0.1).ok


def test_relative_floor_covers_single_run_configs():
    """runs=1 ⇒ stddev 0.0: without the relative floor every wiggle
    would 'regress'.  A 20% drop passes at the default 30% floor; a
    40% drop does not."""
    base = _artifact(10.0, 0.0)
    assert benchgate.compare(base, _artifact(8.0, 0.0)).ok
    assert not benchgate.compare(base, _artifact(6.0, 0.0)).ok


def test_improvement_is_not_a_regression():
    report = benchgate.compare(_artifact(10.0), _artifact(30.0))
    assert report.ok
    assert report.results[0].status == "improved"


def test_tpu_unavailable_refuses_real_tpu_baseline():
    tpu_base = _artifact(1000.0, backend="tpu", tpu_unavailable=False)
    cpu_cand = _artifact(5.0)
    with pytest.raises(BackendMismatch):
        benchgate.compare(tpu_base, cpu_cand)
    # and symmetrically: a TPU candidate never gates against CPU numbers
    with pytest.raises(BackendMismatch):
        benchgate.compare(cpu_cand, tpu_base)


def test_last_tpu_carry_forward_block_is_never_read():
    """A CPU artifact embedding a last_tpu block stays a CPU artifact:
    the nested chip numbers must neither flip the backend kind nor leak
    into the gated key set."""
    base = _artifact(5.0, last_tpu={
        "extras": {"backend": "tpu", "e2e_req_per_sec_mean": 450.0},
    })
    cand = _artifact(5.0, last_tpu={
        "extras": {"backend": "tpu", "e2e_req_per_sec_mean": 1.0},
    })
    report = benchgate.compare(base, cand)
    assert report.ok
    assert [r.key for r in report.results] == ["e2e"]
    assert report.results[0].baseline == 5.0


def test_missing_candidate_key_warns_by_default():
    base = _artifact(100.0)
    base.update(_artifact(50.0, prefix="mp"))
    cand = _artifact(100.0)
    report = benchgate.compare(base, cand)
    assert report.ok
    assert report.missing == ["mp"]


def test_cli_exit_codes(tmp_path, capsys):
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(_artifact(100.0, 2.0)))

    cand_p.write_text(json.dumps(_artifact(99.0, 2.0)))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 0
    assert "benchgate: pass" in capsys.readouterr().out

    cand_p.write_text(json.dumps(_artifact(40.0, 2.0)))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 1
    assert "REGRESSION" in capsys.readouterr().out

    # refusal: backend-kind mismatch
    cand_p.write_text(json.dumps(
        _artifact(40.0, backend="tpu", tpu_unavailable=False)
    ))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 2

    # refusal: unreadable artifact
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(tmp_path / "nope")]
    ) == 2

    # refusal: nothing to gate
    cand_p.write_text(json.dumps({"backend": "cpu", "tpu_unavailable": True}))
    base2 = tmp_path / "empty.json"
    base2.write_text(json.dumps({"backend": "cpu", "tpu_unavailable": True}))
    assert benchgate_cli.main(
        ["--baseline", str(base2), "--candidate", str(cand_p)]
    ) == 2


def test_cli_fail_on_missing_and_json_report(tmp_path, capsys):
    base = _artifact(100.0)
    base.update(_artifact(50.0, prefix="mp"))
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(base))
    cand_p.write_text(json.dumps(_artifact(100.0)))
    args = ["--baseline", str(base_p), "--candidate", str(cand_p)]
    assert benchgate_cli.main(args) == 0
    capsys.readouterr()
    assert benchgate_cli.main(args + ["--fail-on-missing"]) == 1
    capsys.readouterr()
    assert benchgate_cli.main(args + ["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["missing"] == ["mp"]
    assert doc["results"][0]["key"] == "e2e"


def test_committed_artifacts_pass_the_default_gate():
    """The acceptance wiring: the repo's own committed candidate and
    baseline must gate green with the default thresholds (this is what
    `make check` runs)."""
    assert benchgate_cli.main([]) == 0


def test_load_goodput_is_gated_on_drop():
    """ISSUE 15: load_*_goodput_per_sec joins the gate as a throughput
    key — a collapse at the overload point regresses even when the
    classic configs hold."""
    base = _artifact(100.0, load_over_goodput_per_sec=400.0)
    cand = _artifact(100.0, load_over_goodput_per_sec=100.0)  # -75%
    report = benchgate.compare(base, cand)
    by_key = {r.key: r for r in report.results}
    assert by_key["load_over_goodput"].status == "regression"
    assert by_key["load_over_goodput"].direction == "drop"
    # inside the 30% floor: noise, not regression
    assert benchgate.compare(
        base, _artifact(100.0, load_over_goodput_per_sec=300.0)
    ).ok


def test_load_p99_is_gated_on_increase():
    """Latency gates the OTHER way: a p99 that climbs past the (wide)
    latency floor regresses; a p99 that falls is an improvement, and a
    2x climb sits inside the default 1.5x-increase floor."""
    base = _artifact(100.0, load_sat_p99_ms=2000.0)
    worse = _artifact(100.0, load_sat_p99_ms=9000.0)  # +350% > 150%
    report = benchgate.compare(base, worse)
    by_key = {r.key: r for r in report.results}
    assert by_key["load_sat_p99"].status == "regression"
    assert by_key["load_sat_p99"].direction == "increase"
    assert by_key["load_sat_p99"].drop == pytest.approx(7000.0)
    assert benchgate.compare(
        base, _artifact(100.0, load_sat_p99_ms=4000.0)  # 2x: tolerated
    ).ok
    better = benchgate.compare(
        base, _artifact(100.0, load_sat_p99_ms=500.0)
    )
    assert {r.key: r.status for r in better.results}[
        "load_sat_p99"
    ] == "improved"
    # the latency floor is independently tunable
    assert not benchgate.compare(
        base, _artifact(100.0, load_sat_p99_ms=4000.0), lat_rel_floor=0.5
    ).ok


def test_load_keys_do_not_leak_outside_their_namespace():
    """Only the load_ namespace's _goodput_per_sec/_p99_ms keys join the
    gate — e.g. an unrelated *_p99_ms diagnostic stays ungated."""
    base = _artifact(
        100.0, sched_p99_ms=5.0, other_goodput_per_sec=3.0
    )
    cand = _artifact(
        100.0, sched_p99_ms=500.0, other_goodput_per_sec=0.1
    )
    report = benchgate.compare(base, cand)
    assert [r.key for r in report.results] == ["e2e"]


def test_groups_sweep_headline_is_gated():
    """The multi-group sweep's aggregate headline (ISSUE 10:
    groups{G}_req_per_sec_mean triples from bench_groups) participates
    in the gate exactly like every other config — a 60% drop at one
    sweep point must regress even when the classic configs hold."""
    base = _artifact(100.0)
    for G in (1, 4):
        base.update(_artifact(40.0 * G, prefix=f"groups{G}"))
    cand = dict(base)
    cand["groups4_req_per_sec_mean"] = 40.0 * 4 * 0.4
    report = benchgate.compare(base, cand)
    assert [r.key for r in report.results] == ["e2e", "groups1", "groups4"]
    assert [r.status for r in report.results] == ["ok", "ok", "regression"]


def test_grid_load_goodput_and_p99_join_the_gate():
    """ISSUE 17: the (G, chips) grid's embedded per-point curves
    (groups{G}x{C}_load_*) gate exactly like the top-level load_* curve —
    goodput on drop, p99 on increase."""
    base = _artifact(
        100.0,
        groups4x2_load_sat_goodput_per_sec=500.0,
        groups4x2_load_sat_p99_ms=1000.0,
    )
    cand = dict(base)
    cand["groups4x2_load_sat_goodput_per_sec"] = 100.0  # -80%
    cand["groups4x2_load_sat_p99_ms"] = 9000.0  # +800% > the 150% floor
    report = benchgate.compare(base, cand)
    by_key = {r.key: r for r in report.results}
    assert by_key["groups4x2_load_sat_goodput"].status == "regression"
    assert by_key["groups4x2_load_sat_goodput"].direction == "drop"
    assert by_key["groups4x2_load_sat_p99"].status == "regression"
    assert by_key["groups4x2_load_sat_p99"].direction == "increase"
    # inside both floors: noise, not regression
    ok_cand = dict(base)
    ok_cand["groups4x2_load_sat_goodput_per_sec"] = 400.0  # -20% < 30%
    ok_cand["groups4x2_load_sat_p99_ms"] = 2000.0  # 2x < 1.5x-increase
    assert benchgate.compare(base, ok_cand).ok


def test_grid_pool_aggregate_util_is_gated():
    """The grid's pool-aggregate utilization headline
    (groups{G}x{C}_util_effective_per_sec) rides the utilization rule —
    a collapse regresses; per-chip attribution keys stay ungated."""
    base = _artifact(
        100.0,
        groups4x2_util_effective_per_sec=8000.0,
        groups4x2_chip0_util_busy=0.9,
    )
    cand = dict(base)
    cand["groups4x2_util_effective_per_sec"] = 2000.0  # -75%
    cand["groups4x2_chip0_util_busy"] = 0.01  # diagnostic only
    report = benchgate.compare(base, cand)
    by_key = {r.key: r for r in report.results}
    assert by_key["groups4x2_util"].status == "regression"
    assert "groups4x2_chip0_util_busy" not in {
        r.key for r in report.results
    }


def test_grid_load_namespace_is_anchored():
    """The grid pattern matches ONLY groups{G}x{C}_load_* — a plain
    groups{G}_* sweep key or a lookalike elsewhere in the name never
    joins the load gate."""
    assert benchgate._in_load_namespace("groups8x4_load_sat_p99_ms")
    assert benchgate._in_load_namespace("load_over_goodput_per_sec")
    assert not benchgate._in_load_namespace("groups8_load_sat_p99_ms")
    assert not benchgate._in_load_namespace("groups8x_load_sat_p99_ms")
    assert not benchgate._in_load_namespace("xgroups8x4_load_sat_p99_ms")
    base = _artifact(
        100.0,
        groups8_p99_ms=5.0,  # sweep diagnostic, not a grid curve
        groups8x4_extra_goodput_per_sec=9.0,  # not under _load_
    )
    cand = dict(base)
    cand["groups8_p99_ms"] = 500.0
    cand["groups8x4_extra_goodput_per_sec"] = 0.1
    report = benchgate.compare(base, cand)
    assert [r.key for r in report.results] == ["e2e"]


def test_grid_keys_respect_backend_refusal():
    """Cross-backend refusal covers grid keys: a CPU grid artifact never
    gates against a chip baseline, even when only grid keys differ."""
    tpu_base = _artifact(
        1000.0, backend="tpu", tpu_unavailable=False,
        groups4x8_load_sat_goodput_per_sec=90000.0,
        groups4x8_util_effective_per_sec=500000.0,
    )
    cpu_cand = _artifact(
        5.0,
        groups4x1_load_sat_goodput_per_sec=300.0,
        groups4x1_util_effective_per_sec=2000.0,
    )
    with pytest.raises(BackendMismatch):
        benchgate.compare(tpu_base, cpu_cand)


def test_load_finality_p99_is_gated_on_increase():
    """ISSUE 19: the SLO finality headline (load_*_finality_p99_ms —
    scheduled-origin, unresolved requests charged their age) gates on
    INCREASE with the same wide latency floor as the plain p99."""
    base = _artifact(100.0, load_sat_finality_p99_ms=2000.0)
    worse = _artifact(100.0, load_sat_finality_p99_ms=7000.0)  # 3.5x
    report = benchgate.compare(base, worse)
    by_key = {r.key: r for r in report.results}
    assert by_key["load_sat_finality_p99"].status == "regression"
    assert by_key["load_sat_finality_p99"].direction == "increase"
    assert by_key["load_sat_finality_p99"].drop == pytest.approx(5000.0)
    # 2x sits inside the default 1.5x-increase floor: tolerated
    assert benchgate.compare(
        base, _artifact(100.0, load_sat_finality_p99_ms=4000.0)
    ).ok
    assert {r.key: r.status for r in benchgate.compare(
        base, _artifact(100.0, load_sat_finality_p99_ms=500.0)
    ).results}["load_sat_finality_p99"] == "improved"


def test_cli_injected_finality_regression_exits_1(tmp_path, capsys):
    """Gate liveness for the new family: a 3x finality-p99 wedge at one
    curve point must flip the CLI to rc 1 even when every classic
    throughput key holds."""
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(_artifact(
        100.0, load_over_finality_p99_ms=3000.0
    )))
    cand_p.write_text(json.dumps(_artifact(
        100.0, load_over_finality_p99_ms=9000.0  # 3x > the 1.5x floor
    )))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 1
    assert "load_over_finality_p99" in capsys.readouterr().out


def test_grid_finality_joins_the_gate():
    """The (G, chips) grid's embedded finality keys
    (groups{G}x{C}_load_*_finality_p99_ms) ride the same increase rule
    as the top-level curve."""
    base = _artifact(100.0, groups4x2_load_over_finality_p99_ms=1000.0)
    cand = dict(base)
    cand["groups4x2_load_over_finality_p99_ms"] = 9000.0
    report = benchgate.compare(base, cand)
    by_key = {r.key: r for r in report.results}
    assert by_key["groups4x2_load_over_finality_p99"].status == "regression"
    assert by_key["groups4x2_load_over_finality_p99"].direction == "increase"


def test_slo_family_respects_load_namespace_and_fraction_stays_ungated():
    """Namespace pin for the slo family: a finality lookalike outside
    the load_/groups{G}x{C}_load_ namespaces never joins the gate, and
    the informational slo_good_fraction companion is not gated at all
    (the finality p99 is the gated half of the pair)."""
    base = _artifact(
        100.0,
        sched_finality_p99_ms=5.0,  # not in a load namespace
        groups8_finality_p99_ms=5.0,  # sweep key, not a grid curve
        load_sat_slo_good_fraction=0.999,
        load_sat_finality_p99_ms=800.0,
    )
    cand = dict(base)
    cand["sched_finality_p99_ms"] = 500.0
    cand["groups8_finality_p99_ms"] = 500.0
    cand["load_sat_slo_good_fraction"] = 0.1  # collapse: informational
    report = benchgate.compare(base, cand)
    assert [r.key for r in report.results] == [
        "e2e", "load_sat_finality_p99"
    ]
    assert report.ok


def test_finality_keys_respect_backend_refusal(tmp_path):
    """Cross-backend refusal covers the new family: a CPU candidate
    carrying finality keys never gates against a chip baseline — the
    CLI refuses with rc 2 before reading a number."""
    tpu_base = _artifact(
        1000.0, backend="tpu", tpu_unavailable=False,
        load_sat_finality_p99_ms=40.0,
    )
    cpu_cand = _artifact(5.0, load_sat_finality_p99_ms=4000.0)
    with pytest.raises(BackendMismatch):
        benchgate.compare(tpu_base, cpu_cand)
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(tpu_base))
    cand_p.write_text(json.dumps(cpu_cand))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 2


def test_recovery_time_is_gated_on_increase():
    """ISSUE 20: the recovery-time SLO (chaos_recovery_time_ms, kill-to-
    first-executed) gates on INCREASE with the wide latency floor — a
    recovery that takes 4x longer regresses; 2x is within the floor."""
    base = _artifact(100.0, chaos_recovery_time_ms=3000.0)
    worse = _artifact(100.0, chaos_recovery_time_ms=12000.0)  # 4x
    report = benchgate.compare(base, worse)
    by_key = {r.key: r for r in report.results}
    assert by_key["chaos_recovery_time"].status == "regression"
    assert by_key["chaos_recovery_time"].direction == "increase"
    assert by_key["chaos_recovery_time"].drop == pytest.approx(9000.0)
    # 2x sits inside the default 1.5x-increase floor: tolerated
    assert benchgate.compare(
        base, _artifact(100.0, chaos_recovery_time_ms=6000.0)
    ).ok
    assert {r.key: r.status for r in benchgate.compare(
        base, _artifact(100.0, chaos_recovery_time_ms=500.0)
    ).results}["chaos_recovery_time"] == "improved"
    # the latency floor stays independently tunable
    assert not benchgate.compare(
        base, _artifact(100.0, chaos_recovery_time_ms=6000.0),
        lat_rel_floor=0.5,
    ).ok


def test_recovery_goodput_is_gated_on_drop():
    """Under-recovery goodput (whole-run rate INCLUDING the outage
    window) gates on DROP like any throughput headline."""
    base = _artifact(100.0, chaos_recovery_goodput_per_sec=50.0)
    cand = _artifact(100.0, chaos_recovery_goodput_per_sec=10.0)  # -80%
    report = benchgate.compare(base, cand)
    by_key = {r.key: r for r in report.results}
    assert by_key["chaos_recovery_goodput"].status == "regression"
    assert by_key["chaos_recovery_goodput"].direction == "drop"
    # inside the 30% floor: noise
    assert benchgate.compare(
        base, _artifact(100.0, chaos_recovery_goodput_per_sec=40.0)
    ).ok


def test_recovery_keys_are_exact_matches_no_namespace_leak():
    """The recovery keys are EXACT matches: lookalike *_time_ms /
    *recovery* keys never join the gate."""
    base = _artifact(
        100.0,
        foo_recovery_time_ms=5.0,  # not the exact key
        chaos_recovery_time_total_ms=5.0,  # suffix lookalike
        recovery_goodput_per_sec=9.0,  # missing the chaos_ prefix
    )
    cand = dict(base)
    cand["foo_recovery_time_ms"] = 50000.0
    cand["chaos_recovery_time_total_ms"] = 50000.0
    cand["recovery_goodput_per_sec"] = 0.01
    report = benchgate.compare(base, cand)
    assert [r.key for r in report.results] == ["e2e"]
    assert report.ok


def test_recovery_keys_respect_backend_refusal(tmp_path):
    """Cross-backend refusal covers the recovery family: rc 2 before a
    single recovery number is read."""
    tpu_base = _artifact(
        1000.0, backend="tpu", tpu_unavailable=False,
        chaos_recovery_time_ms=200.0,
        chaos_recovery_goodput_per_sec=5000.0,
    )
    cpu_cand = _artifact(
        5.0, chaos_recovery_time_ms=9000.0,
        chaos_recovery_goodput_per_sec=5.0,
    )
    with pytest.raises(BackendMismatch):
        benchgate.compare(tpu_base, cpu_cand)
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(tpu_base))
    cand_p.write_text(json.dumps(cpu_cand))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 2


def test_cli_injected_recovery_regression_exits_1(tmp_path, capsys):
    """Gate liveness: a 4x recovery-time wedge flips the CLI to rc 1
    even when every throughput key holds."""
    base_p = tmp_path / "base.json"
    cand_p = tmp_path / "cand.json"
    base_p.write_text(json.dumps(_artifact(
        100.0, chaos_recovery_time_ms=3000.0
    )))
    cand_p.write_text(json.dumps(_artifact(
        100.0, chaos_recovery_time_ms=12000.0
    )))
    assert benchgate_cli.main(
        ["--baseline", str(base_p), "--candidate", str(cand_p)]
    ) == 1
    assert "chaos_recovery_time" in capsys.readouterr().out
