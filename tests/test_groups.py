"""Multi-group consensus sharding (minbft_tpu/groups): codec envelope,
shard router, GroupRuntime demux, cross-group engine coalescing, group
isolation, and the G=4 seeded chaos soak.

Seed discipline matches tests/test_chaos.py: MINBFT_CHAOS_SEED replays a
failure byte-identically; the soak's committed default seed is pinned in
CI (the multi-group step runs this file WITHOUT the `not slow` filter).
"""

import asyncio
import json
import logging
import os
import sys

import pytest

from minbft_tpu import api
from minbft_tpu.groups import (
    GroupAuthenticator,
    GroupRuntime,
    MultiGroupClient,
    ShardRouter,
    group_for_key,
)
from minbft_tpu.messages import (
    CodecError,
    marshal,
    pack_group,
    split_group,
    split_group_batch,
    Request,
)
from minbft_tpu.sample.authentication import new_test_authenticators
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.sample.conn.inprocess import (
    InProcessClientConnector,
    InProcessPeerConnector,
    make_testnet_stubs,
)
from minbft_tpu.sample.requestconsumer import SimpleLedger
from minbft_tpu.testing import FaultNet, FaultPlan, InvariantChecker, chaos_seed

# Dev-mode wall-clock stretch, exactly tests/test_chaos.py's contract:
# the seeded fault schedule is frame-indexed, so scaling every timeout
# keeps replay byte-identical while the asyncio-debug-slowed cluster
# gets proportionate patience.
TIME_SCALE = 5.0 if sys.flags.dev_mode else 1.0


def _t(seconds: float) -> float:
    return seconds * TIME_SCALE


_log = logging.getLogger("minbft.groups.test")


# ---------------------------------------------------------------------------
# codec: the group envelope.


def test_group_envelope_roundtrip():
    frame = marshal(Request(client_id=3, seq=9, operation=b"op"))
    for gid in (1, 7, 0xFFFF):
        wrapped = pack_group(gid, frame)
        assert wrapped != frame
        assert split_group(wrapped) == (gid, frame)
    # group 0 is BARE by definition: one canonical encoding per frame.
    assert pack_group(0, frame) == frame
    assert split_group(frame) == (0, frame)
    with pytest.raises(CodecError):
        pack_group(0x10000, frame)
    with pytest.raises(CodecError):
        split_group(bytes([0xF1, 0x00]))  # truncated envelope


def test_split_group_batch_matches_scalar():
    # Above the vectorized threshold (48): mixed bare/tagged/malformed
    # frames must classify identically to the scalar path, item-wise.
    frames = []
    expect = []
    for i in range(120):
        inner = marshal(Request(client_id=i, seq=i, operation=b"x" * (i % 7)))
        gid = i % 5
        frames.append(pack_group(gid, inner))
        expect.append((gid, inner))
    # malformed: truncated envelope (tag present, id cut off)
    frames.append(bytes([0xF1, 0x01]))
    expect.append(None)  # CodecError slot
    frames.append(b"")  # empty frame is bare group 0
    expect.append((0, b""))
    out = split_group_batch(frames)
    assert len(out) == len(frames)
    for got, want in zip(out, expect):
        if want is None:
            assert isinstance(got[0], CodecError)
        else:
            assert got == want
    # and the scalar path (below the threshold) agrees
    small = frames[:10] + frames[-2:]
    small_expect = expect[:10] + expect[-2:]
    for got, want in zip(split_group_batch(small), small_expect):
        if want is None:
            assert isinstance(got[0], CodecError)
        else:
            assert got == want


# ---------------------------------------------------------------------------
# shard router: same key -> same group, across restarts and processes.


def test_shard_router_is_deterministic_across_restarts():
    # group_for_key is a pure function of (key, G) — SHA-256, no state,
    # no seed.  Pin exact values so an accidental hash change (which
    # would silently re-shard every deployed key space) fails loudly.
    assert group_for_key(b"", 4) == group_for_key(b"", 4)
    vals = {k: group_for_key(k, 8) for k in (b"a", b"b", b"user:42", b"\x00")}
    # recompute "after a restart" (fresh router objects)
    for k, v in vals.items():
        assert ShardRouter(8).group_for(k) == v
    # the committed pins (sha256 first-8-bytes big-endian mod G):
    assert group_for_key(b"user:42", 8) == 2
    assert group_for_key(b"a", 8) == 2
    assert group_for_key(b"", 4) == 0
    # G=1 shortcut and input validation
    assert group_for_key(b"anything", 1) == 0
    with pytest.raises(ValueError):
        group_for_key(b"x", 0)
    # rough uniformity: 256 keys over 4 groups, no group starved
    counts = [0] * 4
    for i in range(256):
        counts[group_for_key(b"key-%d" % i, 4)] += 1
    assert min(counts) > 256 // 4 // 3, counts


def test_group_authenticator_domain_separation():
    async def run():
        (r_auths, _c), = [new_test_authenticators(1, n_clients=1)]
        base = r_auths[0]
        g1 = GroupAuthenticator(base, 1)
        g2 = GroupAuthenticator(base, 2)
        g0 = GroupAuthenticator(base, 0)
        msg = b"payload"
        tag = g1.generate_message_authen_tag(
            api.AuthenticationRole.REPLICA, msg
        )
        await g1.verify_message_authen_tag(
            api.AuthenticationRole.REPLICA, 0, msg, tag
        )
        # the same bytes+tag must NOT verify in another group
        with pytest.raises(api.AuthenticationError):
            await g2.verify_message_authen_tag(
                api.AuthenticationRole.REPLICA, 0, msg, tag
            )
        # group 0 is the empty prefix: byte-compatible with the base
        tag0 = g0.generate_message_authen_tag(
            api.AuthenticationRole.REPLICA, msg
        )
        await base.verify_message_authen_tag(
            api.AuthenticationRole.REPLICA, 0, msg, tag0
        )
        # batch surface applies the same prefix item-wise
        out = await g2.verify_message_authen_tags(
            api.AuthenticationRole.REPLICA, [(0, msg, tag), (0, msg, tag0)]
        )
        assert all(isinstance(e, api.AuthenticationError) for e in out)
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# cluster helper.


async def make_group_cluster(
    n=4,
    f=1,
    n_groups=2,
    n_clients=2,
    cfg=None,
    usig_kind="hmac",
    wrap_group_connector=None,
    **auth_kw,
):
    """In-process G-group cluster over the real shared-channel mux.
    Returns (runtimes, per_group_client_auths, stubs, ledgers) with
    ledgers[i][g] = replica i's group-g ledger."""
    if cfg is None:
        cfg = SimpleConfiger(
            n=n, f=f, timeout_request=60.0, timeout_prepare=30.0
        )
    per_group = [
        new_test_authenticators(
            n, n_clients=n_clients, usig_kind=usig_kind, **auth_kw
        )
        for _ in range(n_groups)
    ]
    stubs = make_testnet_stubs(n)
    ledgers = [
        [SimpleLedger() for _ in range(n_groups)] for _ in range(n)
    ]
    runtimes = []
    for i in range(n):
        rt = GroupRuntime(
            i,
            cfg,
            [per_group[g][0][i] for g in range(n_groups)],
            InProcessPeerConnector(stubs),
            ledgers[i],
            wrap_group_connector=(
                (lambda g, c, _i=i: wrap_group_connector(g, c, _i))
                if wrap_group_connector is not None
                else None
            ),
        )
        stubs[i].assign_replica(rt)
        runtimes.append(rt)
    for rt in runtimes:
        await rt.start()
    client_auths = [per_group[g][1] for g in range(n_groups)]
    return runtimes, client_auths, stubs, ledgers


def _mg_client(client_id, n, f, client_auths, stubs, **kw):
    return MultiGroupClient(
        client_id,
        n,
        f,
        len(client_auths),
        [client_auths[g][client_id] for g in range(len(client_auths))],
        InProcessClientConnector(stubs),
        retransmit_interval=kw.pop("retransmit_interval", 30.0),
        **kw,
    )


# ---------------------------------------------------------------------------
# runtime: commit across groups on shared transport, both ingest paths.


@pytest.mark.parametrize("ingest", ["1", "0"])
def test_group_runtime_commits_across_groups(ingest, monkeypatch):
    monkeypatch.setenv("MINBFT_BUNDLE_INGEST", ingest)

    async def run():
        runtimes, c_auths, stubs, ledgers = await make_group_cluster(
            n=4, f=1, n_groups=2
        )
        client = _mg_client(0, 4, 1, c_auths, stubs)
        await client.start()
        try:
            ops = [b"op-%d" % k for k in range(8)]
            results = await asyncio.wait_for(
                asyncio.gather(*[client.request(op) for op in ops]), _t(60)
            )
            assert all(results)
            per_g = [0, 0]
            for op in ops:
                per_g[client.group_for(op)] += 1
            assert all(per_g), f"hash routing starved a group: {per_g}"
            # every replica's per-group ledger holds exactly its shard
            for g in range(2):
                lens = [ledgers[i][g].length for i in range(4)]
                assert all(l == per_g[g] for l in lens), (g, lens, per_g)
            # per-group observability labels are threaded through
            for rt in runtimes:
                assert [c.group for c in rt.cores] == [0, 1]
                assert [c.metrics.group for c in rt.cores] == [0, 1]
            agg = runtimes[0].metrics_aggregate()
            assert agg.get("requests_executed", 0) == len(ops)
        finally:
            await client.stop()
            for rt in runtimes:
                await rt.stop()
        return True

    assert asyncio.run(run())


def test_pinned_group_and_unknown_group_frames():
    async def run():
        runtimes, c_auths, stubs, ledgers = await make_group_cluster(
            n=4, f=1, n_groups=2
        )
        client = _mg_client(0, 4, 1, c_auths, stubs)
        await client.start()
        try:
            # explicit pinning beats the hash route
            await asyncio.wait_for(
                client.request(b"pinned", group=1), _t(60)
            )
            assert [ledgers[i][1].length for i in range(4)] == [1] * 4
            assert all(ledgers[i][0].length == 0 for i in range(4))
            with pytest.raises(ValueError):
                await client.request(b"x", group=7)
            # frames for an unknown group are dropped, never detonate:
            # inject one straight into replica 0's client stream.
            handler = runtimes[0].client_message_stream_handler()

            async def one_shot():
                yield pack_group(
                    9, marshal(Request(client_id=0, seq=1, operation=b"z"))
                )

            out = handler.handle_message_stream(one_shot())
            with pytest.raises((asyncio.TimeoutError, StopAsyncIteration)):
                # no reply ever comes back for an unknown group — the
                # stream just drains and ends (or stays silent)
                await asyncio.wait_for(out.__anext__(), _t(0.6))
            await out.aclose()
            # and the cluster still works afterwards
            await asyncio.wait_for(client.request(b"after", group=0), _t(60))
            assert [ledgers[i][0].length for i in range(4)] == [1] * 4
        finally:
            await client.stop()
            for rt in runtimes:
                await rt.stop()
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# cross-group engine coalescing: the tentpole's measurable claim.


def _spy_host_sig_queue(engine):
    """Wrap the host ECDSA verify queue's dispatcher to record every
    dispatched batch (host queue: items are exactly the submitted
    (pub, digest, sig) lanes — no padding)."""
    q = engine._queue("ecdsa_p256_host", engine._dispatch_ecdsa_host)
    batches = []
    orig = q.dispatch

    def spy(items):
        batches.append(list(items))
        return orig(items)

    q.dispatch = spy
    return q, batches


async def _run_coalescing_cluster(n_groups, per_group_requests, clients=2):
    """Fixed per-group load through one shared engine; returns
    (recorded host-sig-queue batches, pub->group map, queue stats)."""
    from minbft_tpu.parallel import BatchVerifier

    engine = BatchVerifier(max_batch=64, buckets=(64,))
    # Keep the USIG off the device path on the CPU test backend: route
    # its MAC checks through the engine's host HMAC queue (same
    # coalescing semantics, no kernel compile).
    engine.verify_hmac_sha256 = engine.verify_hmac_sha256_host
    q, batches = _spy_host_sig_queue(engine)
    runtimes, c_auths, stubs, ledgers = await make_group_cluster(
        n=4,
        f=1,
        n_groups=n_groups,
        n_clients=clients,
        engine=engine,
        batch_signatures=False,  # client/replica sigs -> engine HOST queue
    )
    pub_to_group = {}
    for g in range(n_groups):
        for pub in c_auths[g][0]._client_pubs.values():
            pub_to_group[pub] = g
    mclients = [
        _mg_client(c, 4, 1, c_auths, stubs) for c in range(clients)
    ]
    for mc in mclients:
        await mc.start()
    try:
        # identical per-group wave structure at every G: each wave fires
        # one request per (client, group) concurrently.
        for wave in range(per_group_requests):
            await asyncio.wait_for(
                asyncio.gather(
                    *[
                        mc.request(b"w-%d-%d" % (mc.client_id, wave), group=g)
                        for mc in mclients
                        for g in range(n_groups)
                    ]
                ),
                _t(60),
            )
    finally:
        for mc in mclients:
            await mc.stop()
        for rt in runtimes:
            await rt.stop()
    return batches, pub_to_group, q.stats


@pytest.mark.slow
def test_one_engine_flush_spans_groups():
    """THE coalescing differential: with G=2 on one engine, at least one
    dispatched verify batch must contain client-signature lanes from BOTH
    groups (the grouped ingest seeds every group's checks in the same
    loop turn, ahead of one flush decision)."""
    batches, pub_to_group, _stats = asyncio.run(
        _run_coalescing_cluster(n_groups=2, per_group_requests=6)
    )
    assert batches, "no host-sig batches dispatched"
    spans = [
        {pub_to_group[pub] for pub, _d, _s in b if pub in pub_to_group}
        for b in batches
    ]
    assert any(len(s) >= 2 for s in spans), (
        f"no flush spanned groups: {[sorted(s) for s in spans]}"
    )


@pytest.mark.slow
def test_verify_mean_batch_rises_with_groups():
    """At FIXED per-group load, the shared queue's mean batch fill must
    rise with G — the 'device sees one big batch regardless of group
    count' claim, as a differential."""
    _b1, _m1, stats1 = asyncio.run(
        _run_coalescing_cluster(n_groups=1, per_group_requests=8)
    )
    _b2, _m2, stats2 = asyncio.run(
        _run_coalescing_cluster(n_groups=2, per_group_requests=8)
    )
    m1 = stats1.mean_batch
    m2 = stats2.mean_batch
    assert stats1.items and stats2.items
    # G=2 delivers ~2x the lanes into the same flush windows; demand a
    # clear rise with margin for scheduling noise.
    assert m2 >= m1 * 1.2, (m1, m2)


# ---------------------------------------------------------------------------
# group isolation: a wedged group never blocks another group's commits.


def test_wedged_group_does_not_block_others():
    async def run():
        # Black-hole EVERY peer link of group 1 (drop=1.0 via a
        # group-scoped faultnet between its cores and the shared mux);
        # group 0 shares the same physical channels and must keep
        # committing.  Long protocol timeouts: the wedged group parks,
        # it doesn't view-change-thrash.
        net = FaultNet(seed=0xB10C, default_plan=FaultPlan(drop=1.0))
        runtimes, c_auths, stubs, ledgers = await make_group_cluster(
            n=4,
            f=1,
            n_groups=2,
            wrap_group_connector=(
                lambda g, c, i: net.wrap(c, f"r{i}") if g == 1 else c
            ),
        )
        client = _mg_client(0, 4, 1, c_auths, stubs)
        await client.start()
        try:
            # the wedged group cannot commit (sanity: the wedge is real)
            with pytest.raises(asyncio.TimeoutError):
                await asyncio.wait_for(
                    client.request(b"wedged", group=1), _t(2.0)
                )
            # ...while the healthy group commits a full batch
            ops = [b"ok-%d" % k for k in range(6)]
            await asyncio.wait_for(
                asyncio.gather(
                    *[client.request(op, group=0) for op in ops]
                ),
                _t(60),
            )
            assert all(
                ledgers[i][0].length >= len(ops) for i in range(4)
            ), [ledgers[i][0].length for i in range(4)]
        finally:
            await client.stop()
            for rt in runtimes:
                await rt.stop()
        return True

    assert asyncio.run(run())


def test_saturated_group_processor_never_blocks_the_shared_drain(monkeypatch):
    """HOL differential at the HANDLER layer (the transport layer's
    drop-on-full is covered above): shrink the per-group processor bound,
    park more than that many requests in a wedged group, and require the
    healthy group to commit THROUGH the same shared stream.  Pre-fix the
    shared tick loop blocked in the wedged group's submit and this times
    out; post-fix the wedged group sheds (client retransmission heals)
    and the drain keeps moving."""
    from minbft_tpu.core import message_handling as mh

    monkeypatch.setattr(mh, "_STREAM_CONCURRENCY", 4)

    async def run():
        net = FaultNet(seed=0xB10C2, default_plan=FaultPlan(drop=1.0))
        runtimes, c_auths, stubs, ledgers = await make_group_cluster(
            n=4,
            f=1,
            n_groups=2,
            wrap_group_connector=(
                lambda g, c, i: net.wrap(c, f"r{i}") if g == 1 else c
            ),
        )
        client = _mg_client(0, 4, 1, c_auths, stubs)
        await client.start()
        floods = []
        try:
            # 3x the patched bound into the black-holed group: its
            # handlers park awaiting a quorum that can never form, so
            # the processor saturates and starts shedding.
            floods = [
                asyncio.ensure_future(
                    client.request(b"flood-%d" % k, group=1)
                )
                for k in range(12)
            ]
            await asyncio.sleep(_t(1.0))  # reach the replicas and park
            await asyncio.wait_for(client.request(b"ok", group=0), _t(60))
            assert all(
                ledgers[i][0].length >= 1 for i in range(4)
            ), [ledgers[i][0].length for i in range(4)]
        finally:
            for t in floods:
                t.cancel()
            await asyncio.gather(*floods, return_exceptions=True)
            await client.stop()
            for rt in runtimes:
                await rt.stop()
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# observability plumbing: labels, dumps, exposition.


def test_group_labels_in_trace_and_prom():
    from minbft_tpu.obs.prom import (
        collect_replica,
        merge_family_lists,
        render_families,
    )
    from minbft_tpu.obs.trace import FlightRecorder, dump_path_for, filter_group
    from minbft_tpu.utils.metrics import ReplicaMetrics

    rec = FlightRecorder.for_replica(2, group=3)
    assert rec.to_dict()["group"] == 3
    assert dump_path_for("replica", 2, base="/tmp/x", group=3) == (
        "/tmp/x.r2g3.json"
    )
    assert dump_path_for("replica", 2, base="/tmp/x") == "/tmp/x.r2.json"
    docs = [
        {"kind": "replica", "group": 0, "hists": {}},
        {"kind": "replica", "group": 1, "hists": {}},
        {"kind": "engine", "hists": {}},  # shared: survives any filter
    ]
    kept = filter_group(docs, 1)
    assert {d.get("group") for d in kept} == {1, None}
    m = ReplicaMetrics(group=2)
    m.inc("requests_executed", 5)
    text = render_families(
        merge_family_lists(
            [
                collect_replica(metrics=m, replica_id=0),
                collect_replica(
                    metrics=ReplicaMetrics(group=3), replica_id=0
                ),
            ]
        )
    )
    assert 'group="2"' in text
    # one family block even with two groups' samples
    assert text.count("# TYPE minbft_uptime_seconds gauge") == 1


# ---------------------------------------------------------------------------
# THE multi-group chaos soak (satellite): G=4 on shared transport,
# partition/heal + primary stall in ONE group only; per-group invariants
# hold, untouched groups keep committing, census replays from the seed.

GROUPS_CHAOS_PLAN = FaultPlan(
    drop=0.03,
    delay=0.08,
    delay_s=(0.0005, 0.005),
    duplicate=0.03,
    reorder=0.05,
    corrupt=0.02,
)

_CHAOS_GROUP = 2  # the group that takes the faults


@pytest.mark.slow
def test_groups_chaos_soak_one_group_faulted():
    seed = chaos_seed(default=0x64A05)
    G = 4

    async def run():
        net = FaultNet(seed=seed, default_plan=GROUPS_CHAOS_PLAN)
        # Patience scaled to the G=4 single-event-loop operating point:
        # four groups' pure-Python crypto share one loop, so loop
        # latency under load is ~4x the ungrouped soak's — sub-second
        # request timers would fire spuriously and spiral the chaos
        # group into view-change thrash whose (pure-Python-verified)
        # whole-log VIEW-CHANGE storms then starve every group.
        cfg = SimpleConfiger(
            n=4,
            f=1,
            timeout_request=_t(2.5),
            timeout_prepare=_t(1.2),
            timeout_viewchange=_t(2.5),
        )
        runtimes, c_auths, stubs, ledgers = await make_group_cluster(
            n=4,
            f=1,
            n_groups=G,
            cfg=cfg,
            wrap_group_connector=(
                lambda g, c, i: (
                    net.wrap(c, f"r{i}") if g == _CHAOS_GROUP else c
                )
            ),
        )
        client = _mg_client(0, 4, 1, c_auths, stubs,
                            retransmit_interval=_t(1.0), max_inflight=8)
        await client.start()
        accepted = {g: [] for g in range(G)}

        async def issue(g, tag, k, timeout=90):
            ops = [b"g%d-%s-%d" % (g, tag, i) for i in range(k)]
            results = await asyncio.gather(
                *[
                    client.request(op, group=g, timeout=_t(timeout))
                    for op in ops
                ]
            )
            accepted[g].extend(zip(ops, results))

        untouched = [g for g in range(G) if g != _CHAOS_GROUP]
        try:
            # Phase A: seeded chaos on the target group, traffic to ALL.
            _log.warning("groups chaos A: 2 req/group under seeded plan")
            await issue(_CHAOS_GROUP, b"a", 2)
            await asyncio.gather(*[issue(g, b"a", 2) for g in untouched])

            # Phase B: partition the TARGET group {r0,r1}|{r2,r3} (its
            # links only — the same physical channels keep carrying the
            # other groups).  Target requests resolve after heal;
            # untouched groups must commit DURING the partition.
            _log.warning("groups chaos B: partition group %d", _CHAOS_GROUP)
            net.partition({"r0", "r1"}, {"r2", "r3"})
            target_b = asyncio.ensure_future(issue(_CHAOS_GROUP, b"b", 3))
            # untouched groups must commit DURING the partition — the
            # isolation claim under live faults (with n=4/f=1 the
            # partitioned group itself may or may not commit, depending
            # on which side holds its current primary: f+1=2 commits
            # suffice, so no assertion either way until after heal).
            await asyncio.gather(*[issue(g, b"b", 2) for g in untouched])
            await asyncio.sleep(_t(0.5))
            net.heal_partition()
            _log.warning("groups chaos B: healed")
            await target_b

            # settle the target group's view before stalling its primary
            deadline = asyncio.get_running_loop().time() + _t(30)
            view = 0
            while asyncio.get_running_loop().time() < deadline:
                views = []
                for rt in runtimes:
                    cur, _ = await rt.group(
                        _CHAOS_GROUP
                    ).handlers.view_state.hold_view()
                    views.append(cur)
                if len(set(views)) == 1:
                    view = views[0]
                    break
                await asyncio.sleep(0.1)

            # Phase C: stall the target group's CURRENT primary (its
            # links only — the same replica's cores in other groups keep
            # running undisturbed).  The target group must depose it;
            # untouched groups commit throughout.
            primary = view % 4
            _log.warning(
                "groups chaos C: stalling group-%d primary r%d (view %d)",
                _CHAOS_GROUP, primary, view,
            )
            net.stall_replica(primary)
            target_c = asyncio.ensure_future(issue(_CHAOS_GROUP, b"c", 3))
            await asyncio.gather(*[issue(g, b"c", 2) for g in untouched])
            await target_c
            survivors = [rt for rt in runtimes if rt.id != primary]
            views = {}
            deadline = asyncio.get_running_loop().time() + _t(30)
            while asyncio.get_running_loop().time() < deadline:
                for rt in survivors:
                    cur, _ = await rt.group(
                        _CHAOS_GROUP
                    ).handlers.view_state.hold_view()
                    views[rt.id] = cur
                if all(v > view for v in views.values()):
                    break
                await asyncio.sleep(0.05)
            assert all(v > view for v in views.values()), (
                f"group-{_CHAOS_GROUP} survivors still at {views}"
            )
            # the UNTOUCHED groups never left view 0 (their primary —
            # the same OS-level replica — was never stalled for them)
            for g in untouched:
                for rt in runtimes:
                    cur, _ = await rt.group(g).handlers.view_state.hold_view()
                    assert cur == 0, (g, rt.id, cur)
            net.unstall_replica(primary)

            # freeze the seeded census before heal clears the plan
            frames_snapshot = dict(net.census.frames)
            live_seeded = dict(net.census.seeded_counts())

            # Phase D: heal + reset, clean tail on every group.
            _log.warning("groups chaos D: heal + tail")
            net.heal()
            net.reset_all()
            await asyncio.gather(*[issue(g, b"d", 1, 60) for g in range(G)])

            # every group's accepted set committed on every replica
            per_group_expected = {
                g: len(accepted[g]) for g in range(G)
            }
            assert per_group_expected[_CHAOS_GROUP] == 9
            deadline = asyncio.get_running_loop().time() + _t(60)
            while asyncio.get_running_loop().time() < deadline:
                if all(
                    ledgers[i][g].length >= per_group_expected[g]
                    for i in range(4)
                    for g in range(G)
                ):
                    break
                await asyncio.sleep(0.1)
            for g in range(G):
                lens = [ledgers[i][g].length for i in range(4)]
                assert all(
                    l >= per_group_expected[g] for l in lens
                ), (g, lens)

            # per-group safety invariants over per-group cores/ledgers
            summaries = {}
            for g in range(G):
                checker = InvariantChecker(
                    [rt.group(g) for rt in runtimes],
                    [ledgers[i][g] for i in range(4)],
                )
                summaries[g] = checker.check(accepted[g])
            # the injected faults really happened, in the target group's
            # world only, and replay the seed exactly
            assert net.census.counters.get("partition", 0) >= 1
            assert net.census.counters.get("stall", 0) >= 1
            replayed = net.replay_counts(
                frames_snapshot, plan=GROUPS_CHAOS_PLAN
            )
            assert replayed == live_seeded, (replayed, live_seeded)
            out = net.census.snapshot()
            out["seed"] = seed
            out["groups"] = G
            out["chaos_group"] = _CHAOS_GROUP
            out["time_scale"] = TIME_SCALE
            out["requests_committed"] = {
                str(g): per_group_expected[g] for g in range(G)
            }
            out["invariants"] = {str(g): summaries[g] for g in range(G)}
            return out
        finally:
            await client.stop()
            for rt in runtimes:
                await rt.stop()

    try:
        census = asyncio.run(run())
    except BaseException:
        print(f"replay with MINBFT_CHAOS_SEED={seed}")
        raise
    assert census["frames_total"] > 0
    census_path = os.environ.get("MINBFT_GROUPS_CHAOS_CENSUS")
    if census_path:
        with open(census_path, "w") as fh:
            json.dump(census, fh, indent=2, sort_keys=True)
            fh.write("\n")


# ---------------------------------------------------------------------------
# CLI + config plumbing: declare G once, every layer sees it.


def test_testnet_scaffold_declares_groups_and_config_layers(tmp_path, monkeypatch):
    from minbft_tpu.sample.config import load_config
    from minbft_tpu.sample.peer.cli import main

    d = str(tmp_path)
    rc = main(
        ["testnet", "-n", "4", "--clients", "1", "-d", d,
         "--usig", "HMAC_SHA256", "--base-port", "45300", "--groups", "8"]
    )
    assert rc == 0
    cfg = load_config(f"{d}/consensus.yaml")
    assert cfg.groups == 8
    # env layering (CONSENSUS_GROUPS, the test/bench override path)
    cfg2 = load_config(f"{d}/consensus.yaml", env={"CONSENSUS_GROUPS": "2"})
    assert cfg2.groups == 2
    # an ungrouped scaffold stays at the ungrouped default
    rc = main(
        ["testnet", "-n", "4", "--clients", "1", "-d", f"{d}/plain",
         "--usig", "HMAC_SHA256", "--base-port", "45310"]
    )
    assert rc == 0
    assert load_config(f"{d}/plain/consensus.yaml").groups == 1
