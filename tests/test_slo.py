"""Latency-SLO engine tests (minbft_tpu/obs/slo.py, ISSUE 19): policy
env layering (per-group comma lists), ledger classification semantics,
hand-computed multi-window burn rates and their exact cross-process
merge, the breach-attribution invariant (segments sum to the breached
requests' budget spend, under every classification origin), and the two
forensics defenses (token bucket + spool bound) under sustained breach.
"""

import asyncio
import json
import os

import pytest

from minbft_tpu.obs import critpath
from minbft_tpu.obs import slo
from minbft_tpu.obs.slo import (
    BreachSpool,
    BudgetLedger,
    SLOPolicy,
    TokenBucket,
    breach_report,
    burn_rates,
    register_slo_series,
    series_name,
)
from minbft_tpu.obs.timeseries import CounterSampler, TimeSeries

from test_critpath import MS, synth_docs


# ---------------------------------------------------------------------------
# policy / env layering


def test_policy_defaults():
    p = SLOPolicy()
    assert p.target_ms == 1000.0 and p.objective == 0.99
    assert p.budget_ns == 1e9
    assert p.error_budget == pytest.approx(0.01)
    assert p.fast_window_s == 5.0 and p.slow_window_s == 60.0
    assert p.burn_threshold == 8.0


def test_policy_objective_100_percent_never_divides_by_zero():
    assert SLOPolicy(objective=1.0).error_budget > 0


def test_policy_env_overrides(monkeypatch):
    monkeypatch.setenv(slo.TARGET_ENV, "250")
    monkeypatch.setenv(slo.OBJECTIVE_ENV, "0.999")
    monkeypatch.setenv(slo.FAST_WINDOW_ENV, "2")
    monkeypatch.setenv(slo.SLOW_WINDOW_ENV, "30")
    monkeypatch.setenv(slo.BURN_THRESHOLD_ENV, "4")
    p = SLOPolicy.from_env()
    assert p.target_ms == 250.0 and p.objective == 0.999
    assert p.fast_window_s == 2.0 and p.slow_window_s == 30.0
    assert p.burn_threshold == 4.0


def test_policy_per_group_comma_list(monkeypatch):
    """"1000,500" gives group 0 the first entry; a SHORT list extends
    its last entry to every later group (adding a group never silently
    drops SLO coverage), and a garbage entry falls back to the
    configer/default layer instead of erroring."""
    monkeypatch.setenv(slo.TARGET_ENV, "1000,500")
    assert SLOPolicy.from_env(group=0).target_ms == 1000.0
    assert SLOPolicy.from_env(group=1).target_ms == 500.0
    assert SLOPolicy.from_env(group=7).target_ms == 500.0  # extends last
    assert SLOPolicy.from_env().target_ms == 1000.0  # ungrouped: first
    monkeypatch.setenv(slo.TARGET_ENV, "bogus")
    assert SLOPolicy.from_env(group=0).target_ms == 1000.0


def test_policy_configer_layering(monkeypatch):
    """consensus.yaml fields arrive via the configer; env goes on top —
    the same layering every other protocol knob uses."""

    class Cfg:
        slo_target_ms = 750.0
        slo_objective = 0.95

    monkeypatch.delenv(slo.TARGET_ENV, raising=False)
    monkeypatch.delenv(slo.OBJECTIVE_ENV, raising=False)
    p = SLOPolicy.from_env(configer=Cfg())
    assert p.target_ms == 750.0 and p.objective == 0.95
    monkeypatch.setenv(slo.TARGET_ENV, "200")
    assert SLOPolicy.from_env(configer=Cfg()).target_ms == 200.0


def test_slo_enabled_gates(monkeypatch):
    for var in (slo.SLO_ENV, slo.DUMP_ENV, slo.TARGET_ENV):
        monkeypatch.delenv(var, raising=False)
    assert not slo.slo_enabled()
    monkeypatch.setenv(slo.SLO_ENV, "1")
    assert slo.slo_enabled()
    monkeypatch.setenv(slo.SLO_ENV, "0")  # explicit off, repo convention
    assert not slo.slo_enabled()
    monkeypatch.delenv(slo.SLO_ENV)
    monkeypatch.setenv(slo.DUMP_ENV, "/tmp/spool")
    assert slo.slo_enabled()
    monkeypatch.delenv(slo.DUMP_ENV)
    monkeypatch.setenv(slo.TARGET_ENV, "100")
    assert slo.slo_enabled()
    monkeypatch.delenv(slo.TARGET_ENV)

    class Cfg:
        slo_target_ms = 500.0

    assert slo.slo_enabled(Cfg())


# ---------------------------------------------------------------------------
# ledger classification


def test_ledger_classifies_good_and_breached():
    fast = BudgetLedger(SLOPolicy(target_ms=1e6))  # ~17 min budget
    fast.arrive(1, 1)
    assert fast.commit(1, 1) is True
    assert (fast.good, fast.breached) == (1, 0)
    assert fast.good_fraction() == 1.0

    tight = BudgetLedger(SLOPolicy(target_ms=0.0))  # nothing can meet it
    tight.arrive(1, 2)
    assert tight.commit(1, 2) is False
    assert (tight.good, tight.breached) == (0, 1)
    assert tight.breached_budget_ns > 0  # the spend attribution covers


def test_ledger_unknown_commit_is_none_and_retransmit_keeps_stamp():
    lg = BudgetLedger(SLOPolicy())
    assert lg.commit(9, 9) is None  # no arrival stamp: unclassifiable
    assert lg.total == 0
    lg.arrive(2, 5)
    t0 = lg._origin[(2, 5)]
    lg.arrive(2, 5)  # retransmission must NOT reset the clock
    assert lg._origin[(2, 5)] == t0


def test_ledger_inflight_map_is_bounded():
    lg = BudgetLedger(SLOPolicy())
    for i in range(slo._MAX_INFLIGHT_KEYS):
        lg._origin[(0, i)] = 1
    lg.arrive(1, 0)  # at the bound: wholesale reset, then stamp
    assert len(lg._origin) == 1 and (1, 0) in lg._origin


def test_budget_remaining_math():
    lg = BudgetLedger(SLOPolicy(objective=0.99))
    assert lg.budget_remaining() == 1.0  # no traffic: untouched
    lg.good, lg.breached = 99, 1  # breach rate == allowed rate
    assert lg.budget_remaining() == pytest.approx(0.0)
    lg.good, lg.breached = 98, 2  # 2x overspend: negative, unclamped
    assert lg.budget_remaining() == pytest.approx(-1.0)
    lg.good, lg.breached = 100, 0
    assert lg.budget_remaining() == 1.0


# ---------------------------------------------------------------------------
# burn rates: hand-computed windows, exact merge, group aggregation

# All ring math below uses explicit epoch stamps on a 1s grid; NOW sits
# at an exact slot boundary so the hand-computed windows are unambiguous
# (window() excludes the newest, still-filling slot).
NOW = 1_000_000.0


def _ring(events):
    """events: (series, value, seconds_before_now)."""
    ts = TimeSeries(interval_s=1.0)
    for name, value, ago in events:
        ts.record(name, value, "rate", t=NOW - ago)
    return ts


def test_burn_rates_hand_computed():
    """90 good + 10 breached inside the fast window at a 99% objective:
    breached fraction 0.1 against an allowed 0.01 = burn 10.0.  The slow
    window additionally holds older all-good traffic, diluting the
    fraction to 100/1000."""
    policy = SLOPolicy(objective=0.99, fast_window_s=5.0, slow_window_s=60.0)
    ts = _ring(
        [("slo_good", 18.0, a) for a in (0.5, 1.5, 2.5, 3.5, 4.5)]
        + [("slo_breached", 2.0, a) for a in (0.5, 1.5, 2.5, 3.5, 4.5)]
        + [("slo_good", 90.0, a) for a in range(6, 16)]
    )
    b = burn_rates(ts, policy, now=NOW)
    # fast: 90 good + 10 breached -> frac 0.1 -> burn 10x
    assert b["fast_burn"] == pytest.approx(10.0)
    assert b["fast_good_per_sec"] == pytest.approx(90 / 5)
    assert b["fast_breached_per_sec"] == pytest.approx(10 / 5)
    # slow: (90 + 900) good + 10 breached -> frac 0.01 -> burn 1x
    assert b["slow_burn"] == pytest.approx(1.0)
    assert b["burn_threshold"] == policy.burn_threshold


def test_idle_window_burns_zero_but_trickle_burns_full():
    """No traffic spends no budget; a stalled-but-trickling group where
    EVERY request breaches burns 1/error_budget regardless of rate."""
    policy = SLOPolicy(objective=0.99)
    assert burn_rates(_ring([]), policy, now=NOW)["fast_burn"] == 0.0
    trickle = _ring([("slo_breached", 1.0, 2.5)])
    assert burn_rates(trickle, policy, now=NOW)["fast_burn"] == (
        pytest.approx(100.0)
    )


def test_burn_merges_exactly_across_processes():
    """The cluster-burn claim: merging per-process rings slot-wise then
    computing burn equals computing burn over the hand-added totals —
    no approximation, any merge order."""
    policy = SLOPolicy(objective=0.99)
    a = _ring([("slo_good", 40.0, 1.5), ("slo_breached", 4.0, 2.5)])
    b = _ring([("slo_good", 50.0, 1.5), ("slo_breached", 6.0, 1.5)])
    merged_ab = TimeSeries.merged([a, b])
    merged_ba = TimeSeries.merged([b, a])
    expect = ((4 + 6) / (40 + 50 + 4 + 6)) / policy.error_budget
    for m in (merged_ab, merged_ba):
        assert burn_rates(m, policy, now=NOW)["fast_burn"] == (
            pytest.approx(round(expect, 3))
        )


def test_burn_group_selection_and_aggregation():
    """Per-group series (slo_good_g{G}) let one ring carry every
    group's counters: group=K reads one group, group=None sums all —
    the cluster-burn aggregation `peer slo` renders."""
    policy = SLOPolicy(objective=0.99)
    ts = _ring([
        ("slo_good_g0", 99.0, 1.5),
        ("slo_breached_g0", 1.0, 1.5),
        ("slo_good_g1", 50.0, 1.5),
        ("slo_breached_g1", 50.0, 1.5),
    ])
    g0 = burn_rates(ts, policy, now=NOW, group=0)
    g1 = burn_rates(ts, policy, now=NOW, group=1)
    both = burn_rates(ts, policy, now=NOW, group=None)
    assert g0["fast_burn"] == pytest.approx(1.0)
    assert g1["fast_burn"] == pytest.approx(50.0)
    assert both["fast_burn"] == pytest.approx(
        round((51 / 200) / 0.01, 3)
    )


def test_register_slo_series_feeds_counter_deltas():
    """register_slo_series rides the CounterSampler counter-delta
    discipline: the first tick only baselines, later ticks record the
    per-interval increments under the per-group series names."""
    ts = TimeSeries(interval_s=1.0)
    sampler = CounterSampler(ts)
    lg = BudgetLedger(SLOPolicy(), group=2)
    register_slo_series(sampler, lg)
    sampler.tick(t=NOW - 3.5)  # baseline only
    lg.good, lg.breached = 7, 3
    sampler.tick(t=NOW - 2.5)
    lg.good, lg.breached = 10, 3
    sampler.tick(t=NOW - 1.5)
    win = ts.window(5.0, now=NOW)
    assert win[series_name("slo_good", 2)] == pytest.approx(10 / 5)
    assert win[series_name("slo_breached", 2)] == pytest.approx(3 / 5)
    assert series_name("slo_good", None) == "slo_good"


# ---------------------------------------------------------------------------
# breach attribution: the sums-to-spend invariant


def _sum_attribution(rep):
    return sum(rep["attribution_ms"].values())


def test_breach_attribution_sums_to_spend_client_origin():
    """Every request in the synthetic cluster takes ~16.1ms client to
    quorum; a 10ms budget breaches all of them and the per-segment
    attribution must sum to the breached spend (per-request segments
    telescope to per-request totals by construction)."""
    docs, _ = synth_docs(n_req=6)
    rep = breach_report(docs, SLOPolicy(target_ms=10.0))
    assert rep["origin"] == "client"
    assert rep["requests"] == 6 and rep["breached"] == 6
    assert rep["good_fraction"] == 0.0
    assert _sum_attribution(rep) == pytest.approx(
        rep["breached_spend_ms"], abs=0.01
    )
    # a 20ms budget clears every request: no spend, no attribution
    ok = breach_report(docs, SLOPolicy(target_ms=20.0))
    assert ok["breached"] == 0 and ok["good_fraction"] == 1.0
    assert ok["breached_spend_ms"] == 0.0 and ok["attribution_ms"] == {}


def test_breach_attribution_replica_origin_fallback():
    """With no client dump (the loadgen harness keeps no client
    recorders) classification falls back to recv-origin paths built
    from the replica stages alone — the invariant holds there too."""
    # one host (the loadgen in-process shape): replicas share a clock
    # domain, so alignment is exact without a client hub
    all_docs, _ = synth_docs(n_req=4, domains=["host"] * 4)
    docs = [d for d in all_docs if d.get("kind") != "client"]
    rep = breach_report(docs, SLOPolicy(target_ms=5.0))
    assert rep["origin"] == "replica"
    assert rep["requests"] == 4 and rep["breached"] == 4
    assert _sum_attribution(rep) == pytest.approx(
        rep["breached_spend_ms"], abs=0.01
    )


def test_breach_attribution_scheduled_origin_adds_sched_wait():
    """A loadgen metadata doc switches classification to SCHEDULED
    origin (the coordinated-omission rule): each request's pre-entry
    wait lands in an explicit sched_wait segment, totals grow to the
    scheduled latency, and the invariant still holds exactly.  Requests
    that clear the budget client-origin can breach scheduled-origin —
    that asymmetry IS the point of the rule."""
    docs, _ = synth_docs(n_req=5, client_id=7)
    paths = critpath.cluster_paths(docs).paths
    assert len(paths) == 5
    sched = {
        f"7:{p.seq}": p.total_ns + 5 * MS  # waited 5ms before entry
        for p in paths
    }
    docs.append({"kind": "loadgen", "sched_lat_ns": sched})
    # 20ms clears client-origin (~16.1ms) but not scheduled (~21.1ms)
    rep = breach_report(docs, SLOPolicy(target_ms=20.0))
    assert rep["origin"] == "scheduled"
    assert rep["breached"] == 5
    assert rep["attribution_ms"].get(slo.SCHED_WAIT_SEGMENT, 0.0) == (
        pytest.approx(25.0, abs=0.01)
    )
    assert _sum_attribution(rep) == pytest.approx(
        rep["breached_spend_ms"], abs=0.01
    )


# ---------------------------------------------------------------------------
# forensics: token bucket + spool bound under sustained breach


def test_token_bucket_starts_full_and_refills():
    tb = TokenBucket(capacity=1.0, refill_s=100.0, now=0.0)
    assert tb.take(now=0.0)  # the first breach deserves its bundle
    assert not tb.take(now=50.0)  # half a refill: still dry
    assert tb.take(now=151.0)  # refilled
    assert not tb.take(now=152.0)


def test_spool_rate_limit_and_bound(tmp_path):
    """Sustained synthetic breach against both defenses: the bucket
    refuses dump 2 (rate), the spool bound refuses dump 4 (size), and
    the suppressed path never even BUILDS the lazy bundle."""
    import time as _time

    spool = BreachSpool(str(tmp_path), max_bundles=2, refill_s=100.0)
    base = _time.monotonic()  # the bucket's clock origin (starts full)
    built = []

    def bundle():
        built.append(1)
        return {"kind": "slo_breach", "n": len(built)}

    p1 = spool.maybe_dump(bundle, now=base)
    assert p1 is not None and os.path.exists(p1)
    assert json.load(open(p1))["kind"] == "slo_breach"
    assert spool.written == 1 and spool.bundle_count() == 1

    assert spool.maybe_dump(bundle, now=base + 1.0) is None  # bucket dry
    assert spool.suppressed == 1 and len(built) == 1  # not built

    p2 = spool.maybe_dump(bundle, now=base + 200.0)  # bucket refilled
    assert p2 is not None and spool.bundle_count() == 2

    # spool bound: at max_bundles the write is refused even with tokens
    assert spool.maybe_dump(bundle, now=base + 900.0) is None
    assert spool.suppressed == 2 and len(built) == 2
    assert spool.bundle_count() == 2  # bounded on disk, not per-process


def test_spool_bound_counts_files_not_this_process(tmp_path):
    """Restart honesty: the bound counts slo_breach.*.json FILES, so a
    restarted process shares the bound with its predecessor's spool."""
    (tmp_path / "slo_breach.old-run.0.json").write_text("{}")
    spool = BreachSpool(str(tmp_path), max_bundles=1, refill_s=1.0)
    assert spool.maybe_dump({"kind": "slo_breach"}, now=0.0) is None
    assert spool.suppressed == 1


def test_spool_from_env(monkeypatch, tmp_path):
    monkeypatch.delenv(slo.DUMP_ENV, raising=False)
    assert BreachSpool.from_env() is None
    monkeypatch.setenv(slo.DUMP_ENV, str(tmp_path))
    monkeypatch.setenv(slo.DUMP_MAX_ENV, "7")
    monkeypatch.setenv(slo.DUMP_REFILL_ENV, "42")
    spool = BreachSpool.from_env()
    assert spool.directory == str(tmp_path)
    assert spool.max_bundles == 7 and spool.bucket.refill_s == 42.0


def test_watch_dumps_once_on_threshold_crossing(tmp_path):
    """The auto-dump trigger loop: a ring whose fast window is pure
    breach crosses the threshold on the first poll, dumps exactly one
    bundle (the bucket holds the second), and the task cancels clean."""
    import time as _time

    policy = SLOPolicy(objective=0.99, burn_threshold=8.0)
    ts = TimeSeries(interval_s=1.0)
    now = _time.time()
    for ago in (1.5, 2.5):
        ts.record("slo_breached", 5.0, "rate", t=now - ago)
    spool = BreachSpool(str(tmp_path), max_bundles=4, refill_s=3600.0)
    lg = BudgetLedger(policy)
    lg.breached = 10

    def bundle_fn(burn):
        return slo.build_bundle(policy, burn, [lg], timeseries=ts)

    async def run():
        task = asyncio.get_running_loop().create_task(
            slo.watch(ts, policy, spool, bundle_fn, interval_s=0.02)
        )
        for _ in range(200):
            await asyncio.sleep(0.02)
            if spool.written and spool.suppressed:
                break
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    asyncio.run(run())
    assert spool.written == 1  # exactly one bundle; the bucket held
    assert spool.suppressed >= 1
    bundles = sorted(tmp_path.glob("slo_breach.*.json"))
    assert len(bundles) == 1
    doc = json.load(open(bundles[0]))
    assert doc["kind"] == "slo_breach"
    assert doc["burn"]["fast_burn"] >= policy.burn_threshold
    assert doc["ledgers"][0]["breached"] == 10
    assert doc["policy"]["target_ms"] == policy.target_ms


def test_build_bundle_embeds_breach_report_and_ring():
    docs, _ = synth_docs(n_req=3)
    policy = SLOPolicy(target_ms=10.0)
    ts = _ring([("slo_breached", 3.0, 1.5)])
    burn = burn_rates(ts, policy, now=NOW)
    lg = BudgetLedger(policy, group=0)
    lg.good, lg.breached, lg.breached_budget_ns = 1, 3, 50 * MS

    class FakeRecorder:
        def __init__(self, doc):
            self._doc = doc

        def to_dict(self):
            return self._doc

    bundle = slo.build_bundle(
        policy, burn, [lg],
        recorders=[FakeRecorder(d) for d in docs],
        timeseries=ts, util={"busy": 0.5},
    )
    assert bundle["kind"] == "slo_breach"
    assert bundle["breach"]["breached"] == 3
    assert _sum_attribution(bundle["breach"]) == pytest.approx(
        bundle["breach"]["breached_spend_ms"], abs=0.01
    )
    assert bundle["ledgers"][0] == {
        "group": 0, "good": 1, "breached": 3,
        "breached_budget_ms": 50.0, "budget_remaining": -74.0,
    }
    assert bundle["util"] == {"busy": 0.5}
    assert "slo_breached" in bundle["timeseries"]["series"]
    # the bundle is one self-contained JSON document
    json.dumps(bundle)
