"""Native C++ USIG module tests.

Builds the module in-tree (skips if the toolchain can't), runs the C++
test binary (the port of reference usig/sgx/test/usig_test.c:34-60), and
cross-checks the Python binding against the software USIG and the TPU
batch-verification path: a natively-created UI must verify everywhere.
"""

import os
import subprocess

import pytest

from minbft_tpu.usig import UsigError
from minbft_tpu.usig import native as native_mod

NATIVE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "minbft_tpu", "native"
)

# Gate on actual LOADABILITY, not just a successful `make`: a container
# without libcrypto.so.3 (or with a stale artifact from one) can have a
# libusig.so on disk that cannot link or load — that is "module
# unavailable" (skip), not a test failure.
pytestmark = pytest.mark.skipif(
    not native_mod.available(auto_build=True),
    reason="native USIG unavailable (toolchain or libcrypto.so.3 missing)",
)


def test_cxx_test_binary_passes():
    res = subprocess.run(
        ["make", "check"], cwd=os.path.abspath(NATIVE_DIR),
        capture_output=True, text=True, timeout=180,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "all checks passed" in res.stdout


def test_counter_monotonic_and_cert_format():
    u = native_mod.NativeEcdsaUSIG()
    uis = [u.create_ui(b"msg-%d" % i) for i in range(4)]
    assert [ui.counter for ui in uis] == [1, 2, 3, 4]
    for ui in uis:
        assert len(ui.cert) == 8 + 64  # epoch || r || s
    # verify via the native verifier
    for i, ui in enumerate(uis):
        u.verify_ui(b"msg-%d" % i, ui, u.id())
    with pytest.raises(UsigError):
        u.verify_ui(b"other", uis[0], u.id())


def test_native_ui_verifies_via_python_software_path():
    """The native cert format is byte-compatible with EcdsaUSIG: the pure
    Python verifier accepts natively-signed UIs."""
    from minbft_tpu.usig.software import EcdsaUSIG

    u = native_mod.NativeEcdsaUSIG()
    ui = u.create_ui(b"cross-check")
    # Any EcdsaUSIG instance can verify a foreign UI given the usig_id.
    verifier = EcdsaUSIG()
    verifier.verify_ui(b"cross-check", ui, u.id())
    with pytest.raises(UsigError):
        verifier.verify_ui(b"cross-check!", ui, u.id())


def test_native_ui_verifies_on_tpu_batch_path():
    """usig_verify_items decomposes a native UI into the (pubkey, digest,
    sig) triple and the batch kernel accepts it (SIM backend)."""
    from minbft_tpu.ops import lowering, p256
    from minbft_tpu.usig.software import usig_verify_items

    u = native_mod.NativeEcdsaUSIG()
    good = u.create_ui(b"batch-me")
    q, payload, sig = usig_verify_items(b"batch-me", good, u.id())

    bad_sig = (sig[0], sig[1] ^ 2)
    # Batch of 8: the same device shape as test_p256's differential batch,
    # so the two files share one compiled kernel per CI run.
    items = [(q, payload, sig), (q, payload, bad_sig)] + [(q, payload, sig)] * 6
    lowering.set_mode("loop")
    try:
        out = p256.verify_batch(items)
    finally:
        lowering.set_mode(None)
    assert out.tolist() == [True, False] + [True] * 6


def test_seal_restores_key_with_fresh_epoch():
    """Restore = same key, FRESH epoch, counter back at 1 (reference
    usig.c:168-186): the restored instance can never re-certify an
    (epoch, cv) pair the old instance already issued."""
    u = native_mod.NativeEcdsaUSIG()
    blob = u.seal()
    ui1 = u.create_ui(b"before")

    r = native_mod.NativeEcdsaUSIG.from_sealed(blob)
    assert r.public_key == u.public_key  # same key: anchors stable
    assert r.epoch != u.epoch  # fresh epoch per init
    ui2 = r.create_ui(b"after")
    assert ui2.counter == 1  # counter is volatile state
    # each instance's certs verify only under its own epoch-bearing ID
    r.verify_ui(b"after", ui2, r.id())
    u.verify_ui(b"before", ui1, u.id())
    with pytest.raises(UsigError):
        r.verify_ui(b"after", ui2, u.id())  # old-epoch ID rejects new cert
    with pytest.raises(UsigError):
        u.verify_ui(b"before", ui1, r.id())

    with pytest.raises(UsigError):
        native_mod.NativeEcdsaUSIG.from_sealed(b"\x00" * 20)


def test_python_fallback_when_library_missing(tmp_path, monkeypatch):
    """load() returns None for a missing library path and the authenticator
    stack still works through the software USIG (clean fallback)."""
    monkeypatch.setattr(native_mod, "_LIB_PATH", str(tmp_path / "nope.so"))
    monkeypatch.setattr(native_mod, "_lib", None)
    monkeypatch.setattr(native_mod, "_load_attempted", False)
    assert native_mod.load(auto_build=False) is None
    with pytest.raises(UsigError):
        native_mod.NativeEcdsaUSIG(_lib_override=None)
