"""Open-loop load harness tests (minbft_tpu/loadgen, ISSUE 15).

Covers the four contracts the harness stands on:

- Determinism: same seed ⇒ byte-identical schedule (digest equality) and
  a replayable census (``replay_census(spec)`` == live fired-census),
  mirroring the faultnet ``replay_counts`` discipline.
- Coordinated omission: latency is measured from the SCHEDULED arrival
  instant.  The stall-regression test injects an event-loop stall and
  pins that the reported percentiles reflect the full user-visible wait
  while the send-origin counterfactual under-reports it — if someone
  flips report() to the send-origin series, that test fails.
- Admission: a saturated stream processor sheds with a signed BUSY under
  a token-bucket sign budget, the generator honors the hold, and a
  cluster offered far beyond saturation keeps committing with bounded
  queues and zero lost requests.
- Hygiene: the repo carries no ``__pycache__``-only orphan directories
  (the pre-ISSUE-15 ``minbft_tpu/loadgen/`` ghost this package replaced).
"""

import asyncio
import os
import sys
import time

import pytest

from minbft_tpu.core.admission import AdmissionController
from minbft_tpu.groups.router import ShardRouter
from minbft_tpu.loadgen import (
    LoadSpec,
    OpenLoopGenerator,
    build_schedule,
    replay_census,
)
from minbft_tpu.loadgen.harness import _Pending
from minbft_tpu.messages import (
    Busy,
    Reply,
    Request,
    authen_bytes,
    marshal,
    split_multi,
    unmarshal,
)
from minbft_tpu.utils.metrics import ReplicaMetrics

# Same dev-mode wall-clock scaling the chaos suite uses: asyncio debug
# mode slows the protocol hot path ~10x, so deadlines stretch while the
# seeded schedules (frame- and spec-indexed, not time-based) stay pinned.
TIME_SCALE = 5.0 if sys.flags.dev_mode else 1.0


def _t(seconds: float) -> float:
    return seconds * TIME_SCALE


# ---------------------------------------------------------------------------
# Schedule determinism (the seed-replay contract).


def test_same_seed_same_schedule():
    spec = LoadSpec(
        seed=0xD15C, rate=500.0, duration_s=2.0, n_clients=200,
        read_fraction=0.2, large_fraction=0.1,
    )
    a, b = build_schedule(spec), build_schedule(spec)
    assert a.digest == b.digest
    assert a.arrivals == b.arrivals
    assert a.census() == b.census() == replay_census(spec)
    # a different seed is a different schedule
    other = build_schedule(
        LoadSpec(seed=0xD15D, rate=500.0, duration_s=2.0, n_clients=200,
                 read_fraction=0.2, large_fraction=0.1)
    )
    assert other.digest != a.digest
    # census structure: fixed keys always present, mix accounted
    c = a.census()
    assert c["arrivals"] == len(a.arrivals) > 0
    assert c["reads"] + c["writes"] == c["arrivals"]
    assert c["large"] + c["small"] == c["arrivals"]


def test_onoff_schedule_is_bursty_and_deterministic():
    spec = LoadSpec(
        seed=7, rate=400.0, duration_s=2.0, n_clients=50,
        process="onoff", on_s=0.2, off_s=0.3,
    )
    sched = build_schedule(spec)
    assert sched.digest == build_schedule(spec).digest
    ts = [a.t_ns for a in sched.arrivals]
    assert ts == sorted(ts)
    # OFF windows carry no arrivals: every arrival's position inside its
    # on/off cycle falls within the ON span.
    cycle_ns = int((spec.on_s + spec.off_s) * 1e9)
    on_ns = int(spec.on_s * 1e9)
    assert all(t % cycle_ns <= on_ns for t in ts)
    # time-averaged offered rate holds (loose band, it's a Poisson draw)
    assert 0.5 * 400 * 2.0 < len(ts) < 1.5 * 400 * 2.0


def test_grouped_schedule_routes_by_shard_router():
    spec = LoadSpec(
        seed=3, rate=300.0, duration_s=1.0, n_clients=64, n_groups=4,
    )
    sched = build_schedule(spec)
    router = ShardRouter(4)
    for a in sched.arrivals:
        assert a.group == router.group_for(b"loadgen-client-%d" % a.client_idx)
    c = sched.census()
    assert sum(c.get(f"group_{g}", 0) for g in range(4)) == c["arrivals"]


def test_spec_validation_rejects_nonsense():
    with pytest.raises(ValueError):
        LoadSpec(seed=1, rate=0.0, duration_s=1.0).validate()
    with pytest.raises(ValueError):
        LoadSpec(seed=1, rate=10.0, duration_s=1.0, process="lockstep").validate()
    with pytest.raises(ValueError):
        LoadSpec(seed=1, rate=10.0, duration_s=1.0, read_fraction=1.5).validate()
    with pytest.raises(ValueError):
        LoadSpec(
            seed=1, rate=10.0, duration_s=1.0, process="onoff", on_s=0.0
        ).validate()


# ---------------------------------------------------------------------------
# BUSY wire format + admission controller units.


def test_busy_codec_and_authen_roundtrip():
    busy = Busy(
        replica_id=2, client_id=41, seq=9000, retry_after_ms=250,
        signature=b"sig-bytes",
    )
    out = unmarshal(marshal(busy))
    assert isinstance(out, Busy)
    assert out == busy
    ab = authen_bytes(busy)
    assert ab.startswith(b"BUSY")
    # the hold hint is covered by the signature (a forged retry-after
    # must not verify)
    assert ab != authen_bytes(
        Busy(replica_id=2, client_id=41, seq=9000, retry_after_ms=999)
    )


class _SaturatedProc:
    async def try_submit_msg(self, msg):
        return False

    async def try_submit(self, data):
        return False


class _FakeHandlers:
    def __init__(self):
        import logging

        self.metrics = ReplicaMetrics()
        self.replica_id = 2
        self.log = logging.getLogger("test.admission")
        self.signed = 0

    async def sign_message_async(self, msg):
        self.signed += 1
        msg.signature = b"unit-sig"


def test_admission_controller_sheds_with_signed_busy():
    async def run():
        h = _FakeHandlers()
        h.metrics.note_admission_rx(128, 256)  # 50% rx saturation
        out: asyncio.Queue = asyncio.Queue()
        adm = AdmissionController(h, _SaturatedProc(), out)
        req = Request(client_id=7, seq=3, operation=b"x", signature=b"s")
        await adm.submit_msg(req)
        assert h.metrics.counters.get("admission_shed") == 1
        assert h.metrics.counters.get("admission_busy_sent") == 1
        busy = unmarshal(out.get_nowait())
        assert isinstance(busy, Busy)
        assert (busy.client_id, busy.seq) == (7, 3)
        assert busy.signature == b"unit-sig"
        # retry-after scales with rx saturation, inside the bounds
        assert 25 <= busy.retry_after_ms <= 1000
        assert busy.retry_after_ms > 300  # 50% saturation ⇒ mid-range
        # non-REQUEST sheds are counted but never signalled
        await adm.submit_msg(Reply(replica_id=0, client_id=7, seq=3, result=b""))
        assert h.metrics.counters.get("admission_shed") == 2
        assert h.metrics.counters.get("admission_busy_sent") == 1
        return True

    assert asyncio.run(run())


def test_admission_busy_token_bucket_bounds_sign_load():
    """A garbage flood cannot convert shed work into unbounded sign work:
    past the burst budget, sheds are counted but BUSY emission stops."""

    async def run():
        h = _FakeHandlers()
        out: asyncio.Queue = asyncio.Queue()
        adm = AdmissionController(h, _SaturatedProc(), out)
        for i in range(300):
            await adm.submit_msg(
                Request(client_id=1, seq=i, operation=b"", signature=b"s")
            )
        c = h.metrics.counters
        assert c["admission_shed"] == 300
        # burst 200 plus whatever trickled back in at 400/s during the
        # loop — well short of one-BUSY-per-shed
        assert c["admission_busy_sent"] <= 260
        assert c["admission_busy_suppressed"] >= 1
        assert (
            c["admission_busy_sent"] + c["admission_busy_suppressed"] == 300
        )
        assert h.signed == c["admission_busy_sent"]
        return True

    assert asyncio.run(run())


def _mac_fleet(n, n_clients):
    """MAC-authenticated cluster keys + per-identity client auths (the
    loadgen default scheme — see runner.run_local_load's docstring)."""
    from minbft_tpu.sample.authentication import generate_testnet_keys

    store = generate_testnet_keys(
        n, n_clients=n_clients, usig_spec="HMAC_SHA256", with_macs=True
    )
    return store, [store.mac_client_authenticator(c) for c in range(n_clients)]


def test_generator_honors_busy_hold():
    """A (counted) BUSY suppresses that request's retransmission until
    the hold expires; holds only ever extend; absurd hints are capped."""

    async def run():
        spec = LoadSpec(seed=5, rate=10.0, duration_s=0.5, n_clients=2)
        _store, auths = _mac_fleet(1, 2)

        class _Dead:
            def replica_message_stream_handler(self, rid):
                return None

        gen = OpenLoopGenerator(
            spec, 1, 0, [0, 1], auths, [_Dead()], retransmit_interval=0.2
        )
        p = _Pending(
            key=(0, 1), slot=0, group=0, read=False, threshold=1,
            sched_s=0.0, frame=b"fr", backoff=None,
        )
        gen._pending[p.key] = p
        await gen._handle_busy(
            0, Busy(replica_id=0, client_id=0, seq=1, retry_after_ms=400)
        )
        now = time.monotonic()
        assert gen._busy_received == 1
        assert now + 0.2 < p.busy_until <= now + 0.5
        # a shorter follow-up hint never shortens the hold
        held = p.busy_until
        await gen._handle_busy(
            0, Busy(replica_id=0, client_id=0, seq=1, retry_after_ms=1)
        )
        assert p.busy_until == held
        # absurd hints cap at the product client's 60s ceiling
        await gen._handle_busy(
            0, Busy(replica_id=0, client_id=0, seq=1, retry_after_ms=10**9)
        )
        assert p.busy_until <= time.monotonic() + 60.5
        # wrong attribution is ignored (count unchanged from the three
        # valid signals above)
        await gen._handle_busy(
            1, Busy(replica_id=0, client_id=0, seq=1, retry_after_ms=400)
        )
        assert gen._busy_received == 3
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# Coordinated omission: the stall regression.


class _InstantEcho:
    """A fake replica stream: every REQUEST gets an immediate matching
    Reply (unsigned — the generator runs verify_replies=False)."""

    def __init__(self, rid):
        self.rid = rid

    def handle_message_stream(self, in_stream):
        return self._gen(in_stream)

    async def _gen(self, in_stream):
        async for data in in_stream:
            for fr in split_multi(data):
                try:
                    msg = unmarshal(fr)
                except Exception:
                    continue
                if isinstance(msg, Request):
                    yield marshal(
                        Reply(
                            replica_id=self.rid,
                            client_id=msg.client_id,
                            seq=msg.seq,
                            result=b"ok",
                        )
                    )


class _InstantEchoConn:
    def replica_message_stream_handler(self, rid):
        return _InstantEcho(rid)


def test_latency_measured_from_scheduled_arrival_under_stall():
    """The coordinated-omission regression: block the event loop for
    0.5s mid-schedule against an instant-echo replica.  Every arrival
    scheduled inside the stall fires late and resolves immediately, so a
    send-origin (closed-loop-style) measurement reports near-zero
    latency — but the user's request was due DURING the stall and waited
    out its full length.  The reported percentiles must come from the
    scheduled-origin series and show the stall; the send-origin series
    is kept only as the explicit under-reporting witness."""

    async def run():
        spec = LoadSpec(seed=0x57A1, rate=150.0, duration_s=1.2, n_clients=30)
        _store, auths = _mac_fleet(1, 30)
        gen = OpenLoopGenerator(
            spec, 1, 0, list(range(30)), auths, [_InstantEchoConn()],
            retransmit_interval=None, drain_s=_t(10),
        )
        loop = asyncio.get_running_loop()
        loop.call_later(0.3, time.sleep, 0.5)  # the injected stall
        return await gen.run()

    rep = asyncio.run(run())
    assert rep["census_ok"], rep["census"]
    assert rep["timeouts"] == 0
    # The stall is charged to the user-facing (scheduled-origin) series…
    assert rep["p99_ms"] >= 300.0, rep
    assert rep["late_fire_max_ms"] >= 300.0, rep
    # …while the send-origin counterfactual under-reports it.  THIS gap
    # is what coordinated omission would hide.
    assert rep["send_p99_ms"] < rep["p99_ms"] * 0.5, rep


# ---------------------------------------------------------------------------
# End-to-end: real cluster over real loopback TCP.


def test_open_loop_end_to_end_census_faithful():
    from minbft_tpu.loadgen.runner import run_local_load

    spec = LoadSpec(
        seed=0xE2E, rate=150.0, duration_s=1.0, n_clients=100,
        read_fraction=0.1, large_fraction=0.05,
    )
    rep = asyncio.run(
        run_local_load(spec, drain_s=_t(15), expect_goodput=20.0)
    )
    assert rep["census_ok"], (rep["census"], replay_census(spec))
    assert rep["timeouts"] == 0
    assert rep["resolved"] == rep["fired"] == rep["arrivals"]
    assert rep["goodput_ok"], rep["goodput_per_sec"]
    assert rep["pool_connections"] == 16  # 4 slots x 4 replicas
    assert rep["cluster"]["committed_entries_all_replicas"] > 0
    assert rep["p50_ms"] > 0 and rep["p99_ms"] >= rep["p50_ms"]


def test_open_loop_grouped_cluster():
    from minbft_tpu.loadgen.runner import run_local_load

    spec = LoadSpec(
        seed=0x6B0, rate=100.0, duration_s=1.0, n_clients=60, n_groups=2,
    )
    rep = asyncio.run(run_local_load(spec, drain_s=_t(15)))
    assert rep["census_ok"]
    assert rep["timeouts"] == 0
    assert rep["census"].get("group_0", 0) > 0
    assert rep["census"].get("group_1", 0) > 0


def test_overload_sheds_and_keeps_committing():
    """2x+-saturation contract: offered far beyond the per-stream
    in-flight bound (one pool slot concentrates it), the replica sheds
    with client-visible signed BUSY, queue growth stays bounded by the
    rx high-water mark, and every request still resolves — overload
    drains into backoff, not into a wedge."""
    from minbft_tpu.loadgen.runner import run_local_load

    # 2000 arrivals in 0.5s on ONE stream: even a fast commit pace
    # leaves the in-flight backlog well past the 1024-per-stream
    # concurrency bound, so shed onset doesn't ride on pace jitter.
    spec = LoadSpec(seed=0x0BAD, rate=4000.0, duration_s=0.5, n_clients=400)
    rep = asyncio.run(run_local_load(spec, pool_slots=1, drain_s=_t(45)))
    cl = rep["cluster"]
    assert rep["census_ok"]
    assert rep["timeouts"] == 0, rep  # shed ≠ lost: all resolved
    assert cl["admission_shed"] > 0
    assert cl["admission_busy_sent"] > 0
    assert rep["busy_received"] > 0  # the signal reached the clients
    assert cl["committed_entries_all_replicas"] > 0
    assert 0 < cl["admission_rx_peak"] <= cl["admission_rx_bound"]
    assert rep["sustained_per_sec"] > 0


# ---------------------------------------------------------------------------
# Thundering herd under seeded chaos: a primary-isolating partition stalls
# commits while the open-loop generator keeps firing; on heal every
# pending request's retransmit ladder re-broadcasts near-simultaneously.
# The cluster must absorb the herd: zero lost requests, live census ==
# seed-replayed census on BOTH layers (loadgen schedule and faultnet),
# safety invariants green.


def test_thundering_herd_after_partition_heal():
    from minbft_tpu.core import new_replica
    from minbft_tpu.sample.config import SimpleConfiger
    from minbft_tpu.sample.conn.inprocess import (
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from minbft_tpu.sample.conn.tcp import (
        TcpReplicaServer,
        connect_many_replicas_tcp,
    )
    from minbft_tpu.sample.requestconsumer import SimpleLedger
    from minbft_tpu.testing import FaultNet, FaultPlan, InvariantChecker, chaos_seed

    seed = chaos_seed(default=0xF100D)
    n, f, n_clients = 4, 1, 80
    spec = LoadSpec(
        seed=0x4E4D, rate=120.0, duration_s=1.5, n_clients=n_clients,
    )

    async def run():
        net = FaultNet(
            seed=seed,
            default_plan=FaultPlan(
                drop=0.02, delay=0.08, delay_s=(0.0005, 0.004),
                duplicate=0.02, reorder=0.04,
            ),
        )
        store, auths = _mac_fleet(n, n_clients)
        cfg = SimpleConfiger(
            n=n, f=f, timeout_request=_t(60.0), timeout_prepare=_t(30.0),
        )
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i, cfg, store.mac_replica_authenticator(i),
                net.wrap(InProcessPeerConnector(stubs), f"r{i}"),
                ledgers[i],
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        servers, addrs = [], {}
        connectors = []
        try:
            for r in replicas:
                await r.start()
            for i in range(n):
                srv = TcpReplicaServer(stubs[i])
                servers.append(srv)
                addrs[i] = await srv.start("127.0.0.1:0")
            connectors = [
                connect_many_replicas_tcp(addrs, kind="client")
                for _ in range(2)
            ]
            gen = OpenLoopGenerator(
                spec, n, f, list(range(n_clients)), auths, connectors,
                retransmit_interval=_t(0.4), drain_s=_t(30),
            )

            async def herd():
                # Isolate the primary mid-schedule: client traffic keeps
                # arriving over TCP, PREPAREs go nowhere, the pending
                # backlog builds…  Timings are REAL seconds, not
                # _t-scaled: the open-loop firing clock is wall-pinned,
                # so the schedule occupies the same window in every mode.
                await asyncio.sleep(0.4)
                net.partition({"r0"}, {"r1", "r2", "r3"})
                await asyncio.sleep(0.6)
                # …heal and reset every peer stream (redials replay the
                # full message logs — soak phase-D convergence), landing
                # the backlog's retransmit herd on a recovering cluster.
                net.heal_partition()
                net.reset_all()

            herd_task = asyncio.ensure_future(herd())
            rep = await gen.run()
            await herd_task

            assert rep["census_ok"], rep["census"]
            assert rep["timeouts"] == 0, rep
            assert rep["resolved"] == rep["arrivals"]
            # the partition really bit (peer frames were dropped across it)
            assert net.census.counters.get("partition", 0) >= 1
            assert net.census.counters.get("reset_all", 0) >= 1
            # faultnet layer: live seeded census == seed-replayed census
            assert net.replay_counts() == net.census.seeded_counts()

            # every replica converges on the committed prefix
            writes = rep["census"]["writes"]
            deadline = asyncio.get_running_loop().time() + _t(30)
            while asyncio.get_running_loop().time() < deadline:
                if all(lg.length >= writes for lg in ledgers):
                    break
                await asyncio.sleep(0.05)
            lengths = [lg.length for lg in ledgers]
            assert all(l >= writes for l in lengths), (lengths, writes)
            InvariantChecker(replicas, ledgers).check()
            return True
        finally:
            for conn in connectors:
                try:
                    await conn.close()
                except Exception:
                    pass
            for srv in servers:
                await srv.stop()
            for r in replicas:
                await r.stop()

    try:
        assert asyncio.run(run())
    except BaseException:
        print(f"replay with MINBFT_CHAOS_SEED={seed}")
        raise


# ---------------------------------------------------------------------------
# Repo hygiene (satellite): no __pycache__-only orphan directories.


def test_no_pycache_only_orphan_dirs():
    """A directory whose ONLY content is __pycache__ is a ghost of a
    deleted (or never-committed) package: imports resolve against stale
    bytecode with no source behind it.  minbft_tpu/loadgen/ spent PRs
    9-14 in exactly that state; keep the repo free of the pattern."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offenders = []
    for root, dirs, _files in os.walk(repo):
        dirs[:] = [
            d for d in dirs
            if d not in (".git", ".venv", "node_modules", ".pytest_cache")
        ]
        if os.path.basename(root) == "__pycache__":
            dirs[:] = []
            continue
        entries = os.listdir(root)
        if entries and all(e == "__pycache__" for e in entries):
            offenders.append(os.path.relpath(root, repo))
    assert not offenders, (
        f"__pycache__-only orphan dirs: {offenders} — delete them or "
        "restore their packages"
    )


# ---------------------------------------------------------------------------
# SLO surface (ISSUE 19): finality percentiles, good fraction, and the
# breach-forensics hook on the runner.


def test_report_carries_slo_surface():
    """Every report carries the SLO pair: scheduled-origin finality p99
    (unresolved requests charged their age-so-far) and the fraction of
    FIRED requests inside the budget.  A generous budget clears
    everything; a sub-microsecond budget clears nothing — same run,
    same latencies, only the policy line moves."""

    async def run(target_ms):
        spec = LoadSpec(seed=0x510, rate=100.0, duration_s=0.8, n_clients=20)
        _store, auths = _mac_fleet(1, 20)
        gen = OpenLoopGenerator(
            spec, 1, 0, list(range(20)), auths, [_InstantEchoConn()],
            retransmit_interval=None, drain_s=_t(10),
            slo_target_ms=target_ms,
        )
        rep = await gen.run()
        return rep, gen

    rep, gen = asyncio.run(run(60_000.0))
    assert rep["census_ok"] and rep["timeouts"] == 0
    assert rep["slo_target_ms"] == 60_000.0
    assert rep["slo_good_fraction"] == 1.0
    assert rep["finality_p99_ms"] > 0
    # all resolved: finality p99 IS the scheduled-origin p99
    assert rep["finality_p99_ms"] == pytest.approx(rep["p99_ms"], rel=1e-6)

    # the same harness under an unmeetable budget: zero good
    rep2, gen2 = asyncio.run(run(1e-6))
    assert rep2["census_ok"]
    assert rep2["slo_good_fraction"] == 0.0

    # sched_doc feeds breach attribution: one scheduled-origin latency
    # per RESOLVED request, keyed cid:seq
    doc = gen.sched_doc()
    assert doc["kind"] == "loadgen"
    assert len(doc["sched_lat_ns"]) == rep["resolved"]
    assert all(ns > 0 for ns in doc["sched_lat_ns"].values())

    # slo_ring replays the classifications into a mergeable wall-clock
    # ring: totals match the report's counts
    from minbft_tpu.obs.slo import SLOPolicy, burn_rates

    ring = gen2.slo_ring()
    b = burn_rates(
        ring, SLOPolicy(target_ms=1e-6), now=time.time() + 2.0,
        group=None,
    )
    # every request breached: the slow window must show pure breach
    assert b["slow_breached_per_sec"] > 0
    assert b["slow_good_per_sec"] == 0.0


def test_run_local_load_slo_contract_and_breach_forensics(
    tmp_path, monkeypatch
):
    """The runner's rc contract surface: slo_ok = good_fraction >=
    objective.  With a breach-forensics spool configured and an
    unmeetable budget, exactly ONE bounded bundle lands in the spool
    (token bucket + spool bound), stamped kind=slo_breach, with its
    attribution summing to the breached spend when trace docs exist."""
    from minbft_tpu.loadgen.runner import run_local_load

    monkeypatch.setenv("MINBFT_TRACE", "1")
    monkeypatch.setenv("MINBFT_SLO_DUMP", str(tmp_path))
    spec = LoadSpec(seed=0x510E, rate=120.0, duration_s=1.0, n_clients=60)
    rep = asyncio.run(
        run_local_load(spec, drain_s=_t(15), slo_target_ms=1e-6)
    )
    assert rep["census_ok"]
    assert rep["slo_good_fraction"] == 0.0
    assert rep["slo_ok"] is False
    assert 0 < rep["slo_objective"] <= 1.0

    bundles = sorted(tmp_path.glob("slo_breach.*.json"))
    assert len(bundles) == 1, bundles  # rate-limited: exactly one
    assert rep["slo_breach_bundle"] == str(bundles[0])
    import json

    doc = json.load(open(bundles[0]))
    assert doc["kind"] == "slo_breach"
    assert doc["policy"]["target_ms"] == 1e-6
    breach = doc["breach"]
    assert breach["origin"] == "scheduled"
    assert breach["breached"] > 0
    assert sum(breach["attribution_ms"].values()) == pytest.approx(
        breach["breached_spend_ms"], abs=0.01
    )
    assert "sched_wait" in breach["attribution_ms"]
    assert doc["ledgers"], doc.keys()  # per-core counters rode along

    # a meetable budget on the same harness reports slo_ok True and
    # never touches the spool again (good_fraction >= objective)
    spec2 = LoadSpec(seed=0x510F, rate=80.0, duration_s=0.8, n_clients=40)
    rep2 = asyncio.run(
        run_local_load(spec2, drain_s=_t(15), slo_target_ms=60_000.0)
    )
    assert rep2["slo_ok"] is True and rep2["slo_good_fraction"] == 1.0
    assert "slo_breach_bundle" not in rep2
    assert len(sorted(tmp_path.glob("slo_breach.*.json"))) == 1
