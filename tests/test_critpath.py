"""Cluster critical-path tests (minbft_tpu/obs/critpath.py, ISSUE 8):
synthetic multi-node dump fixtures with KNOWN per-process clock offsets
and a fully hand-computed stage timeline — clockalign must recover the
offsets within its own uncertainty (the Cristian RTT bound), and the
critpath segment shares must match the ground truth the generator built
the events from."""

import json

import pytest

from minbft_tpu.obs import clockalign, critpath
from minbft_tpu.obs.hist import Log2Histogram
from minbft_tpu.obs.trace import CLIENT_STAGES, REPLICA_STAGES, load_dumps

MS = 1_000_000  # ns

# Ground-truth stage constants (ns).  The generator telescopes these
# into event timestamps; the tests recompute expected segments from the
# SAME names longhand, so a critpath formula drift shows as a diff
# against constants, not against a reimplementation of itself.
SIGN = 2 * MS
GATE = MS // 10
NET = [MS, MS * 11 // 10, MS * 12 // 10, MS * 13 // 10]  # per-replica one-way
PREVERIFY = MS // 2
VERIFY_SPAN = 3 * MS
PREPARE_WAIT = 1 * MS
CQ_WAIT = 2 * MS
EXECUTE = MS // 10
SIGN_SPAN = 4 * MS
REPLY_SEND = MS // 5
LAG_S = 0.0002  # 0.2 ms mean loop lag
VR = 0.75  # verify queue wait ratio
SR = 0.5  # sign queue wait ratio

_R = {name: i for i, name in enumerate(REPLICA_STAGES)}
_C = {name: i for i, name in enumerate(CLIENT_STAGES)}


def synth_docs(n=4, f=1, n_req=12, client_id=7,
               offsets=None, client_offset=0, domains=None,
               client_domain="hostC"):
    """Synthetic dump docs for an n-replica cluster and one client.

    True-timeline construction (per request k, all on one ideal clock):
    client start → +SIGN sign → +GATE broadcast; replica i receives at
    +NET[i], verifies (+PREVERIFY, +VERIFY_SPAN); the primary (replica
    0) applies the PREPARE +PREPARE_WAIT later, backups +NET[i] after
    that; every replica's commit quorum lands +CQ_WAIT after its own
    prepare, then +EXECUTE/+SIGN_SPAN/+REPLY_SEND; replies travel back
    +NET[i]; the client's quorum note is the (f+1)-th reply arrival.
    ``offsets[i]``/``client_offset`` shift each dump into its own local
    clock; ``domains`` control whether alignment may assume a shared
    clock (same string) or must estimate (distinct strings)."""
    offsets = offsets or [0] * n
    domains = domains or [f"host{i}" for i in range(n)]
    lag = Log2Histogram()
    lag.observe(LAG_S)

    client_rows = []
    replica_rows = {i: [] for i in range(n)}
    truth = {}
    for k in range(n_req):
        t0 = 50 * MS * (k + 1)
        sign = t0 + SIGN
        bcast = sign + GATE
        client_rows += [
            [client_id, k, _C["start"], t0 + client_offset],
            [client_id, k, _C["sign"], sign + client_offset],
            [client_id, k, _C["broadcast"], bcast + client_offset],
        ]
        prep0 = bcast + NET[0] + PREVERIFY + VERIFY_SPAN + PREPARE_WAIT
        arrivals = []
        for i in range(n):
            recv = bcast + NET[i]
            venq = recv + PREVERIFY
            vdone = venq + VERIFY_SPAN
            prep = prep0 if i == 0 else prep0 + NET[i]
            cq = prep + CQ_WAIT
            exe = cq + EXECUTE
            rsign = exe + SIGN_SPAN
            rsent = rsign + REPLY_SEND
            arrivals.append(rsent + NET[i])
            off = offsets[i]
            replica_rows[i] += [
                [client_id, k, _R["recv"], recv + off],
                [client_id, k, _R["verify_enqueue"], venq + off],
                [client_id, k, _R["verify_done"], vdone + off],
                [client_id, k, _R["prepare"], prep + off],
                [client_id, k, _R["commit_quorum"], cq + off],
                [client_id, k, _R["execute"], exe + off],
                [client_id, k, _R["reply_sign"], rsign + off],
                [client_id, k, _R["reply_sent"], rsent + off],
            ]
        quorum = sorted(arrivals)[f]  # (f+1)-th reply arrival
        client_rows.append(
            [client_id, k, _C["quorum"], quorum + client_offset]
        )
        truth[k] = {"t0": t0, "quorum": quorum}

    docs = []
    for i in range(n):
        docs.append({
            "kind": "replica", "id": i, "stages": list(REPLICA_STAGES),
            "clock_domain": domains[i], "n": n, "f": f,
            "loop_lag": lag.to_dict(), "events": replica_rows[i],
        })
    docs.append({
        "kind": "client", "id": client_id, "stages": list(CLIENT_STAGES),
        "clock_domain": client_domain, "events": client_rows,
    })
    # Engine doc with exact wait/service ratios (ratio = total_s based,
    # so single observations pin it exactly).
    vwait, vservice = Log2Histogram(), Log2Histogram()
    vwait.observe(VR)
    vservice.observe(1 - VR)
    swait, sservice = Log2Histogram(), Log2Histogram()
    swait.observe(SR)
    sservice.observe(1 - SR)
    docs.append({
        "kind": "engine", "id": 0,
        "verify_queue_wait": {"q": vwait.to_dict()},
        "verify_queue_service": {"q": vservice.to_dict()},
        "sign_queue_wait": {"s": swait.to_dict()},
        "sign_queue_service": {"s": sservice.to_dict()},
    })
    return docs, truth


def expected_segments():
    """The hand-computed ground truth, longhand from the constants (the
    rank-(f+1) tail with f=1 runs through replica 1 — NET is strictly
    increasing, so replica i's whole tail chain is the i-th smallest)."""
    lag_ns = LAG_S * 1e9
    return {
        "client_sign": SIGN,
        "client_gate": GATE,
        "ingress": NET[0] - lag_ns,
        "loop_lag": lag_ns,
        "preverify": PREVERIFY,
        "queue_wait": VERIFY_SPAN * VR + SIGN_SPAN * SR,
        "verify": VERIFY_SPAN * (1 - VR),
        "prepare_wait": PREPARE_WAIT,
        "commit": NET[1] + CQ_WAIT,
        "execute": EXECUTE,
        "reply_sign": SIGN_SPAN * (1 - SR),
        "reply_send": REPLY_SEND,
        "reply_net": NET[1],
        "unattributed": 0.0,
    }


# ---------------------------------------------------------------------------
# clockalign


def test_same_domain_docs_align_exactly():
    docs, _ = synth_docs(domains=["sharedhost"] * 4,
                         client_domain="sharedhost")
    al = clockalign.align(docs)
    for i in range(4):
        assert al[("replica", i)].offset_ns == 0.0
        assert al[("replica", i)].err_ns == 0.0
    assert al[("client", 7)].offset_ns == 0.0


def test_alignment_recovers_injected_offsets_within_rtt_bound():
    """Distinct clock domains with known injected offsets: the Cristian
    estimate must land within its OWN reported uncertainty of the true
    offset, and that uncertainty must stay within the round-trip bound
    (one-way latencies here are ~1ms, so RTT-derived error can never
    legitimately exceed a few ms)."""
    offsets = [0, 250 * MS, -40 * MS, 7 * MS]
    client_offset = 1000 * MS
    docs, _ = synth_docs(offsets=offsets, client_offset=client_offset)
    al = clockalign.align(docs)
    # Reference timeline = replica 0's local clock (true + offsets[0]).
    exact_client = offsets[0] - client_offset
    got = al[("client", 7)]
    assert abs(got.offset_ns - exact_client) <= got.err_ns + 1
    assert 0 < got.err_ns <= 3 * MS  # the RTT bound
    for i in range(1, 4):
        exact = offsets[0] - offsets[i]
        got = al[("replica", i)]
        assert abs(got.offset_ns - exact) <= got.err_ns + 1, (i, got)
        assert got.err_ns <= 2 * 3 * MS  # two estimated hops via the hub


def test_pair_estimate_reports_inconsistent_bounds():
    """Contaminated bounds (L > U) must surface as consistent=False with
    an |U-L|/2 uncertainty, not crash or report false precision."""
    cdoc = {
        "kind": "client", "id": 0, "stages": list(CLIENT_STAGES),
        "events": [
            [0, 1, _C["broadcast"], 1000],
            # quorum noted long BEFORE this replica's reply went out —
            # the late-replier contamination shape.
            [0, 1, _C["quorum"], 1500],
        ],
    }
    rdoc = {
        "kind": "replica", "id": 0, "stages": list(REPLICA_STAGES),
        "events": [
            [0, 1, _R["recv"], 1100],
            [0, 1, _R["reply_sent"], 9000],
        ],
    }
    est = clockalign.estimate_pair(cdoc, rdoc)
    assert est is not None
    assert not est.consistent
    assert est.err_ns > 0


# ---------------------------------------------------------------------------
# critpath ground truth


def test_critpath_shares_match_hand_computed_ground_truth(tmp_path):
    """Same-clock cluster (one domain): the per-segment shares must
    reproduce the generator's constants through the REAL dump→ingest
    path (files on disk, load_dumps)."""
    docs, truth = synth_docs(domains=["h"] * 4, client_domain="h")
    base = str(tmp_path / "trace")
    for d in docs:
        tag = {"replica": "r", "client": "c", "engine": "engine"}[d["kind"]]
        with open(f"{base}.{tag}{d['id']}.json", "w") as fh:
            json.dump(d, fh)
    loaded = load_dumps(base)
    assert len(loaded) == 6

    table = critpath.critpath_table(loaded, "t")
    exp = expected_segments()
    total = sum(v for k, v in exp.items())
    # Telescoping check on the generator itself: the segment constants
    # must reconstruct the client-observed total exactly.
    k0 = next(iter(truth))
    assert total == pytest.approx(
        truth[k0]["quorum"] - truth[k0]["t0"], abs=1
    )
    for seg in critpath.SEGMENTS:
        assert f"t_critpath_{seg}_share" in table, seg
        assert table[f"t_critpath_{seg}_share"] == pytest.approx(
            exp[seg] / total, abs=2e-3
        ), seg
    assert sum(
        v for k, v in table.items() if k.endswith("_share")
    ) == pytest.approx(1.0, abs=0.02)
    assert table["t_critpath_requests"] == 12
    assert table["t_critpath_skipped"] == 0
    assert table["t_critpath_clock_err_ms"] == 0.0
    assert table["t_critpath_total_p50_ms"] == pytest.approx(
        total / 1e6, rel=0.01
    )
    assert "t_critpath_negative_spans" not in table


def test_critpath_survives_injected_offsets():
    """Cross-domain dumps with large injected offsets: shares must
    still telescope to 1.0 and stay close to ground truth — the
    alignment error is bounded by the (reported) RTT uncertainty."""
    docs, _ = synth_docs(offsets=[0, 500 * MS, -300 * MS, 60 * MS],
                         client_offset=-2000 * MS)
    table = critpath.critpath_table(docs, "t")
    assert table, "offsets must not make the merge give up"
    assert sum(
        v for k, v in table.items() if k.endswith("_share")
    ) == pytest.approx(1.0, abs=0.02)
    assert table["t_critpath_clock_err_ms"] > 0
    exp = expected_segments()
    total = sum(exp.values())
    # Cross-node segments can shift by up to the alignment error; the
    # error itself is ~1ms on a ~17ms path, so shares stay within a few
    # points of truth.
    err_share = table["t_critpath_clock_err_ms"] * 1e6 * 2 / total
    for seg in ("commit", "reply_net", "queue_wait", "verify"):
        assert table[f"t_critpath_{seg}_share"] == pytest.approx(
            exp[seg] / total, abs=max(0.05, err_share)
        ), seg


def test_critpath_negative_spans_clock_sanity_flag():
    docs, _ = synth_docs(domains=["h"] * 4, client_domain="h")
    bad = Log2Histogram()
    bad.observe(-0.5)
    bad.observe(0.001)
    docs[0]["hists"] = {"execute": bad.to_dict()}
    table = critpath.critpath_table(docs, "t")
    assert table["t_critpath_negative_spans"] == 1


def test_critpath_empty_and_partial_dumps():
    assert critpath.critpath_table([], "t") == {}
    # replica-only dumps (no client anchor): no path, no keys
    docs, _ = synth_docs()
    replicas_only = [d for d in docs if d["kind"] == "replica"]
    assert critpath.critpath_table(replicas_only, "t") == {}
    # a request with a missing head is SKIPPED, not misattributed
    docs, _ = synth_docs(domains=["h"] * 4, client_domain="h", n_req=4)
    for d in docs:
        if d["kind"] == "replica":
            d["events"] = [
                row for row in d["events"]
                if not (row[1] == 0 and row[2] == _R["prepare"])
            ]
    res = critpath.cluster_paths(docs)
    assert res.skipped == 1
    assert len(res.paths) == 3


def test_engine_queue_doc_round_trip():
    """The live engine's queue histograms survive the doc round trip
    and drive the wait-ratio split."""
    import asyncio

    from minbft_tpu.parallel import BatchVerifier

    async def run():
        eng = BatchVerifier(max_batch=8, buckets=(8,))
        import hashlib
        import hmac as hmac_mod

        key, msg = b"\x01" * 32, b"\x02" * 32
        good = hmac_mod.new(key, msg, hashlib.sha256).digest()
        oks = await asyncio.gather(
            *[eng.verify_hmac_sha256(key, msg, good) for _ in range(8)]
        )
        assert all(oks)
        doc = critpath.engine_queue_doc(eng, ident=3)
        assert doc["kind"] == "engine" and doc["id"] == 3
        wait = doc["verify_queue_wait"]["hmac_sha256"]
        service = doc["verify_queue_service"]["hmac_sha256"]
        st = eng.stats["hmac_sha256"]
        assert wait["count"] == st.items
        assert service["count"] == st.items
        ratio = critpath._wait_ratio([doc], "verify")
        assert ratio is not None and 0.0 <= ratio <= 1.0

    asyncio.run(run())


def test_grouped_dumps_pair_within_groups_only():
    """Multi-group dump sets (ISSUE 10): the G inner clients share one
    client id and their seq spaces can overlap, so (cid, seq) pairing
    must happen WITHIN a group.  Two groups with IDENTICAL (cid, seq)
    event keys but different timelines: the stitcher must yield each
    group's requests separately (2x the paths, correct totals), never a
    cross-group chimera — and the group= filter must reproduce each
    group's table alone."""
    docs_a, truth_a = synth_docs(domains=["h"] * 4, client_domain="h")
    # group 1: same cid/seq keys, every event shifted by a constant so a
    # cross-group stitch would produce wildly different (even negative)
    # spans; a pure shift leaves within-group spans identical.
    shift = 3_600 * 10**9
    docs_b, _ = synth_docs(
        domains=["h"] * 4, client_domain="h",
        offsets=[shift] * 4, client_offset=shift,
    )
    for d in docs_a:
        if d["kind"] != "engine":
            d["group"] = 0
    for d in docs_b:
        if d["kind"] != "engine":
            d["group"] = 1
    merged = docs_a + [d for d in docs_b if d["kind"] != "engine"]
    res = critpath.cluster_paths(merged)
    assert len(res.paths) == 2 * len(truth_a)
    assert res.skipped == 0
    table_all = critpath.critpath_table(merged, "t")
    assert table_all["t_critpath_requests"] == 2 * len(truth_a)
    # per-group filter: exactly one group's requests, ground-truth total
    exp_total = sum(expected_segments().values())
    for g in (0, 1):
        tg = critpath.critpath_table(merged, "t", group=g)
        assert tg["t_critpath_requests"] == len(truth_a)
        assert tg["t_critpath_total_p50_ms"] == pytest.approx(
            exp_total / 1e6, rel=0.01
        )
        assert "t_critpath_negative_spans" not in tg
    # the unpartitioned merge must agree with the per-group totals (no
    # cross-group spans contaminated the timeline)
    assert table_all["t_critpath_total_p50_ms"] == pytest.approx(
        exp_total / 1e6, rel=0.01
    )
    assert "t_critpath_negative_spans" not in table_all


def test_incarnation_refusal_drops_restarted_identities():
    """ISSUE 14 satellite: a restarted replica keeps its id but is a new
    process (fresh run_id) whose (cid, seq) keys can collide with its
    predecessor's — the merge must drop BOTH incarnations of that
    identity (it cannot know which events belong to whom), count them in
    ``refused_docs``, and still stitch the surviving replicas."""
    import copy

    docs, truth = synth_docs(domains=["h"] * 4, client_domain="h")
    for d in docs:
        if d["kind"] != "engine":
            d["run_id"] = "1000-1"
    ghost = copy.deepcopy(
        next(d for d in docs if d["kind"] == "replica" and d["id"] == 3)
    )
    ghost["run_id"] = "2000-2"  # the restart
    merged = docs + [ghost]
    res = critpath.cluster_paths(merged)
    assert res.refused_docs == 2  # both incarnations of replica 3
    assert len(res.paths) == len(truth)  # 3 replicas still quorate
    table = critpath.critpath_table(merged, "t")
    assert table["t_critpath_refused_docs"] == 2
    assert table["t_critpath_requests"] == len(truth)
    # no conflict -> the key is ABSENT, not 0 (the stage_table contract:
    # only-when-nonzero sanity counters)
    clean = critpath.critpath_table(docs, "t")
    assert "t_critpath_refused_docs" not in clean
    # a stamped doc meeting an unstamped doc of the same identity is
    # indistinguishable from a restart: refused too
    unstamped = copy.deepcopy(ghost)
    del unstamped["run_id"]
    res2 = critpath.cluster_paths(docs + [unstamped])
    assert res2.refused_docs == 2
