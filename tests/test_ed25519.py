"""Differential tests: batched Ed25519 TPU kernel vs the host RFC 8032
reference verifier (valid, tampered, wrong-key, malformed, non-canonical)."""

import hashlib

from minbft_tpu.ops import ed25519 as ed
from minbft_tpu.utils import hostcrypto as hc


def test_host_reference_roundtrip():
    seed, pub = hc.ed25519_keygen(b"\x01" * 32)
    msg = b"hello ed25519"
    sig = hc.ed25519_sign(seed, msg)
    assert hc.ed25519_verify(pub, msg, sig)
    assert not hc.ed25519_verify(pub, msg + b"x", sig)


def test_kernel_matches_host():
    items, expected = [], []
    for i in range(3):
        seed, pub = hc.ed25519_keygen(bytes([i]) * 32)
        msg = hashlib.sha256(b"msg-%d" % i).digest()
        sig = hc.ed25519_sign(seed, msg)
        items.append((pub, msg, sig))
        expected.append(True)

    seed0, pub0 = hc.ed25519_keygen(b"\x09" * 32)
    msg = hashlib.sha256(b"orig").digest()
    sig = hc.ed25519_sign(seed0, msg)
    # tampered message
    items.append((pub0, hashlib.sha256(b"tampered").digest(), sig))
    expected.append(False)
    # wrong key
    items.append((items[0][0], msg, sig))
    expected.append(False)
    # bit-flipped R
    items.append((pub0, msg, bytes([sig[0] ^ 1]) + sig[1:]))
    expected.append(False)
    # S out of range (S + L)
    s_big = (int.from_bytes(sig[32:], "little") + hc.ED_L).to_bytes(32, "little")
    items.append((pub0, msg, sig[:32] + s_big))
    expected.append(False)
    # truncated signature
    items.append((pub0, msg, sig[:63]))
    expected.append(False)

    got = list(ed.verify_batch(items))
    assert got == expected


def test_sign_batch_matches_host_signer():
    """Batched Ed25519 signing (device r*B comb + host scalar finish) is
    byte-identical to the RFC 8032 host signer, across distinct seeds and
    message lengths, and the signatures verify."""
    import secrets

    from minbft_tpu.ops import ed25519 as ed
    from minbft_tpu.utils import hostcrypto as hc

    items = []
    for i in range(7):
        seed, _pub = hc.ed25519_keygen(secrets.token_bytes(32))
        items.append((seed, b"m" * (i * 13 + 1)))
    # edge scalars: same seed twice (pub cache), empty-ish message
    items.append((items[0][0], b"x"))

    sigs = ed.sign_batch(items)
    for (seed, msg), sig in zip(items, sigs):
        assert sig == hc.ed25519_sign(seed, msg)
        assert hc.ed25519_verify(hc.ed25519_keygen(seed)[1], msg, sig)
    assert ed.sign_batch([]) == []
