"""Batch/serial verification parity for the USIG schemes.

The TPU batch path must accept exactly the certificates the serial
verifier accepts (reference behavior: one verifier, usig/sgx/sgx-usig.go:81-97).
These are regression tests for two divergences found in review:

- an over-long ECDSA cert (epoch || r || s || padding) must be rejected by
  ``usig_verify_items`` just as the serial verifier rejects it;
- the HMAC batch path must enforce the usig_id key-fingerprint check and
  the exact cert length that ``HmacUSIG._verify`` enforces.
"""

import asyncio
import hashlib

import pytest

from minbft_tpu import api
from minbft_tpu.messages import UI
from minbft_tpu.parallel import BatchVerifier
from minbft_tpu.sample.authentication import SampleAuthenticator
from minbft_tpu.usig.software import (
    EcdsaUSIG,
    HmacUSIG,
    UsigError,
    usig_verify_items,
)


def test_overlong_ecdsa_cert_rejected():
    u = EcdsaUSIG()
    ui = u.create_ui(b"msg")
    padded = UI(counter=ui.counter, cert=ui.cert + b"\x00")
    with pytest.raises(UsigError):
        usig_verify_items(b"msg", padded, u.id())
    short = UI(counter=ui.counter, cert=ui.cert[:-1])
    with pytest.raises(UsigError):
        usig_verify_items(b"msg", short, u.id())
    # the canonical cert still decomposes fine
    usig_verify_items(b"msg", ui, u.id())


def _hmac_authenticator(key: bytes, engine) -> SampleAuthenticator:
    usig = HmacUSIG(key)
    return SampleAuthenticator(usig=usig, usig_ids={0: usig.id()}, engine=engine), usig


def test_hmac_batch_matches_serial():
    async def run():
        engine = BatchVerifier(max_batch=8, buckets=(8,))
        key = hashlib.sha256(b"k").digest()
        auth, usig = _hmac_authenticator(key, engine)
        ui = usig.create_ui(b"msg")

        # canonical tag verifies
        await auth.verify_message_authen_tag(
            api.AuthenticationRole.USIG, 0, b"msg", ui.to_bytes()
        )

        # trailing bytes after the MAC: serial rejects, batch must too
        padded = UI(counter=ui.counter, cert=ui.cert + b"\x00")
        with pytest.raises(UsigError):
            usig.verify_ui(b"msg", padded, usig.id())
        with pytest.raises(api.AuthenticationError):
            await auth.verify_message_authen_tag(
                api.AuthenticationRole.USIG, 0, b"msg", padded.to_bytes()
            )

        # a usig_id claiming a different key fingerprint must fail in batch
        # mode exactly as it does serially
        other = HmacUSIG(hashlib.sha256(b"other").digest(), epoch=usig.epoch)
        auth2 = SampleAuthenticator(
            usig=usig, usig_ids={0: other.id()}, engine=engine
        )
        with pytest.raises(api.AuthenticationError):
            await auth2.verify_message_authen_tag(
                api.AuthenticationRole.USIG, 0, b"msg", ui.to_bytes()
            )

    asyncio.run(run())
