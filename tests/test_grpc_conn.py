"""gRPC transport tests (mirror of reference sample/conn/grpc/grpc_test.go:42-219):
loopback echo streams against mock connection handlers on 127.0.0.1:0, then
a full n=3 cluster committing requests over real sockets.
"""

import asyncio

from minbft_tpu import api
from minbft_tpu.client import new_client
from minbft_tpu.core import new_replica
from minbft_tpu.sample.authentication import new_test_authenticators
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.sample.conn.grpc import (
    GrpcReplicaConnector,
    ReplicaServer,
    connect_many_replicas,
)
from minbft_tpu.sample.requestconsumer import SimpleLedger


class _EchoHandler(api.MessageStreamHandler):
    def __init__(self, tag: bytes):
        self._tag = tag

    async def handle_message_stream(self, in_stream):
        async for data in in_stream:
            yield self._tag + data


class _EchoConnHandler(api.ConnectionHandler):
    def peer_message_stream_handler(self):
        return _EchoHandler(b"peer:")

    def client_message_stream_handler(self):
        return _EchoHandler(b"client:")


def test_loopback_streams():
    """Both chat kinds round-trip messages over a real socket."""

    async def run():
        server = ReplicaServer(_EchoConnHandler())
        addr = await server.start("127.0.0.1:0")
        try:
            for kind, tag in (("peer", b"peer:"), ("client", b"client:")):
                conn = GrpcReplicaConnector(kind)
                conn.connect_replica(0, addr)
                handler = conn.replica_message_stream_handler(0)
                assert handler is not None
                assert conn.replica_message_stream_handler(9) is None

                async def outgoing():
                    for i in range(5):
                        yield b"msg-%d" % i

                got = []
                async for resp in handler.handle_message_stream(outgoing()):
                    got.append(resp)
                    if len(got) == 5:
                        break
                assert got == [tag + b"msg-%d" % i for i in range(5)]
                await conn.close()
        finally:
            await server.stop()

    asyncio.run(run())


def test_cluster_over_sockets():
    """n=3/f=1: replicas connected over real gRPC sockets commit requests
    end-to-end (the reference's integration test layout with the dummy
    connector swapped for the gRPC backend)."""

    async def run():
        n, f = 3, 1
        configer = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
        replica_auths, client_auths = new_test_authenticators(
            n, n_clients=1, usig_kind="hmac", engine=None
        )
        ledgers = [SimpleLedger() for _ in range(n)]

        # Start servers first (ephemeral ports), then dial the mesh.
        replicas = []
        servers = []
        addrs = {}
        peer_conns = []
        for i in range(n):
            # Peer connector is filled in below once all addresses exist;
            # the replica needs it only at start().
            conn = GrpcReplicaConnector("peer")
            peer_conns.append(conn)
            r = new_replica(i, configer, replica_auths[i], conn, ledgers[i])
            replicas.append(r)
            server = ReplicaServer(r)
            addrs[i] = await server.start("127.0.0.1:0")
            servers.append(server)
        for i, conn in enumerate(peer_conns):
            for j, addr in addrs.items():
                if j != i:
                    conn.connect_replica(j, addr)
        for r in replicas:
            await r.start()

        client_conn = connect_many_replicas(addrs, kind="client")
        client = new_client(0, n, f, client_auths[0], client_conn, seq_start=0)
        await client.start()

        for k in range(3):
            result = await asyncio.wait_for(client.request(b"sock-%d" % k), 30)
            assert result  # SimpleLedger returns the block digest

        # Every replica's ledger reached length 3
        # (reference core/integration_test.go:199-210).
        for _ in range(100):
            if all(lg.length >= 3 for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        assert all(lg.length >= 3 for lg in ledgers)

        await client.stop()
        await client_conn.close()
        for r in replicas:
            await r.stop()
        for conn in peer_conns:
            await conn.close()
        for s in servers:
            await s.stop()

    asyncio.run(run())
