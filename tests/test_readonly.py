"""Read-only requests (the reference's roadmap item, README.md:503-504).

Two modes, both covered by the client's signature (a flipped mode breaks
authentication): FAST reads (read_mode=1) answered from committed state
without ordering, accepted only on ALL n matching replies — with n=2f+1 a
smaller read quorum cannot be guaranteed to intersect a write quorum in a
correct replica — and ORDERED reads (read_mode=2), the fallback, which
ride consensus for linearization but execute via consumer.query without
mutating state."""

import asyncio
import struct

import pytest

from minbft_tpu import api
from minbft_tpu.client import new_client
from minbft_tpu.messages import Request, authen_bytes, marshal, unmarshal
from minbft_tpu.messages.codec import CodecError
from minbft_tpu.sample.conn.inprocess import InProcessClientConnector
from conftest import make_cluster as _cluster


def test_read_mode_codec_roundtrip_and_strictness():
    for mode in (0, 1, 2):
        r = Request(client_id=1, seq=7, operation=b"head", read_mode=mode)
        out = unmarshal(marshal(r))
        assert out.read_mode == mode
        assert out.is_read == (mode != 0)
        assert out.is_fast_read == (mode == 1)
    # byte 3 (and anything above 2) has no meaning: one canonical encoding
    data = bytearray(marshal(Request(client_id=1, seq=7, operation=b"x")))
    data[1 + 4 + 8] = 3  # tag + client_id + seq -> the mode byte
    with pytest.raises(CodecError, match="read_mode"):
        unmarshal(bytes(data))


def test_read_mode_is_signature_covered():
    """Flipping the mode in flight must break the client's signature:
    write→fast read would bypass ordering; read→write would mutate state
    with an operation the client signed as a read."""
    base = dict(client_id=1, seq=7, operation=b"op")
    abytes = {
        m: authen_bytes(Request(read_mode=m, **base)) for m in (0, 1, 2)
    }
    assert len(set(abytes.values())) == 3


def test_fast_read_answers_without_ordering():
    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        # a committed write, so the read has state to see; f+1 replies
        # resolve before the slowest replica executes, so poll for all 4
        await asyncio.wait_for(client.request(b"write-1"), 30)
        for _ in range(100):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        assert all(lg.length == 1 for lg in ledgers)
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True), 30
        )
        height = struct.unpack(">Q", head[:8])[0]
        assert height == 1
        assert head[8:] == ledgers[0].state_digest()
        # the read ordered NOTHING and mutated NOTHING
        await asyncio.sleep(0.2)
        assert all(lg.length == 1 for lg in ledgers)
        # fast-path metrics: every replica answered from query
        assert all(
            r.handlers.metrics.counters.get("readonly_served", 0) >= 1
            for r in replicas
        )
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_supports_query_feature_probe():
    """api.consumer_supports_query (ADVICE low-#3): explicit
    ``supports_query`` wins; the structural did-you-override probe is
    only the fallback, and duck-typed consumers don't crash it."""
    from minbft_tpu.sample.requestconsumer import SimpleLedger

    class NoQuery(api.RequestConsumer):
        async def deliver(self, op):
            return b""

        def state_digest(self):
            return b""

    class OptOut(SimpleLedger):
        supports_query = False

    class DuckDelegator:
        """Never subclasses RequestConsumer; forwards everything."""

        supports_query = True

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

    assert api.consumer_supports_query(SimpleLedger())
    assert not api.consumer_supports_query(NoQuery())
    assert not api.consumer_supports_query(OptOut())  # opt-out wins
    assert api.consumer_supports_query(DuckDelegator(SimpleLedger()))


def test_fast_read_survives_delegating_consumer_wrapper():
    """A delegating wrapper consumer (metrics shim / access decorator)
    must keep the fast-read path: the identity-based probe this replaces
    either crashed on duck-typed wrappers or silently demoted every fast
    read to the ordered fallback."""

    class Delegator:
        supports_query = True

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        for r in replicas:
            r.handlers.consumer = Delegator(r.handlers.consumer)
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"write-1"), 30)
        for _ in range(100):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True), 30
        )
        assert struct.unpack(">Q", head[:8])[0] == 1
        # every replica served the FAST path through the wrapper
        assert all(
            r.handlers.metrics.counters.get("readonly_served", 0) >= 1
            for r in replicas
        )
        assert all(
            r.handlers.metrics.counters.get("readonly_unsupported", 0) == 0
            for r in replicas
        )
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_fast_read_falls_back_to_ordered_read_when_a_replica_is_down():
    """With one replica stopped the all-n fast quorum cannot form; the
    client falls back to an ORDERED read: linearized by consensus,
    executed via query — the ledger must not grow."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"write-1"), 30)
        await replicas[3].stop()  # a backup; 3/4 still orders
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True, read_timeout=0.3), 30
        )
        height = struct.unpack(">Q", head[:8])[0]
        assert height == 1
        assert head[8:] == ledgers[0].state_digest()
        # the ordered read linearized WITHOUT mutating: length still 1 on
        # the live replicas
        await asyncio.sleep(0.2)
        assert all(lg.length == 1 for lg in ledgers[:3]), [
            lg.length for lg in ledgers
        ]
        await client.stop()
        for r in replicas[:3]:
            await r.stop()

    asyncio.run(run())


def test_fast_read_requires_all_n_matching():
    """A single diverging replica must defeat the fast read (the all-n
    quorum is the correctness bound, not an implementation detail)."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"write-1"), 30)
        # replica 3's application state diverges (Byzantine or buggy)
        await ledgers[3].deliver(b"phantom-write")
        with pytest.raises(asyncio.TimeoutError):
            await client.request(
                b"head", read_only=True, read_timeout=0.3, read_fallback=False
            )
        # with fallback, the ordered read still answers — f+1 matching
        # CORRECT replies outvote the diverged replica
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True, read_timeout=0.3), 30
        )
        assert head[8:] == ledgers[0].state_digest()
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_prepare_embedding_fast_read_is_rejected():
    """A Byzantine primary batching a FAST read orders what the client
    signed as unordered — backups must refuse the PREPARE."""

    async def run():
        from minbft_tpu.core import prepare as prepare_mod
        from minbft_tpu.messages import UI, Prepare

        async def ok_request(r):
            return None

        async def ok_ui(m):
            return m.ui

        validate = prepare_mod.make_prepare_validator(4, ok_request, ok_ui)
        fast = Request(client_id=0, seq=1, operation=b"head", read_mode=1)
        p = Prepare(replica_id=0, view=0, requests=(fast,), ui=UI(counter=1))
        with pytest.raises(api.AuthenticationError, match="fast-read"):
            await validate(p)
        # an ORDERED read (the fallback) batches fine
        ordered = Request(client_id=0, seq=2, operation=b"head", read_mode=2)
        p2 = Prepare(
            replica_id=0, view=0, requests=(ordered,), ui=UI(counter=2)
        )
        await validate(p2)
        return True

    assert asyncio.run(run())


def test_reads_see_own_completed_writes():
    """Session causality under interleaving: after a client's write
    resolves, its next read must reflect that write — either the fast
    path proves all n executed it, or the fallback linearizes the read
    after it.  Exactly the committed count: this is the only writer, so
    any other height is a lost or duplicated execution."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        for i in range(1, 6):
            await asyncio.wait_for(client.request(b"write-%d" % i), 30)
            head = await asyncio.wait_for(
                client.request(b"head", read_only=True, read_timeout=0.5), 30
            )
            height = struct.unpack(">Q", head[:8])[0]
            # exactly i: the sole client wrote i blocks, so >= would mask
            # a duplicate-execution regression
            assert height == i, (i, height)
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_query_crash_costs_the_read_not_the_replica():
    """consumer.query raising on crafted client input must neither crash
    the fast path nor detonate the ordered execution chain: replicas
    answer SIGNED error replies (silence would park reply waiters on the
    bounded stream slots until the client's stream wedges), the client
    raises the typed error fast, and writes keep committing."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster()
        for lg in ledgers:
            orig = lg.query

            async def bomb(op, _orig=orig):
                if op.startswith(b"crash"):
                    raise ValueError("consumer bug on crafted input")
                return await _orig(op)

            lg.query = bomb
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"write-1"), 30)
        # fast path errors everywhere -> error quorum -> fallback ordered
        # read errors everywhere -> typed error, well before any timeout
        with pytest.raises(api.ReadOnlyQueryError):
            await asyncio.wait_for(
                client.request(b"crash-op", read_only=True, read_timeout=5.0),
                20,
            )
        # error replies are distinguishable from REAL empty results: a
        # query legitimately returning b"" still resolves
        for lg in ledgers:
            orig2 = lg.query

            async def empty(op, _orig=orig2):
                if op.startswith(b"empty"):
                    return b""
                return await _orig(op)

            lg.query = empty
        assert (
            await asyncio.wait_for(
                client.request(b"empty-op", read_only=True), 30
            )
            == b""
        )
        # the cluster survived: ordinary reads and writes still work
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True), 30
        )
        assert struct.unpack(">Q", head[:8])[0] == 1
        assert await asyncio.wait_for(client.request(b"write-2"), 30)
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_ordered_read_commits_through_a_view_change():
    """A read issued while the primary is crashed: the fast all-n quorum
    cannot form (the primary is dead), so the fallback ORDERED read rides
    the view-change machinery like any request — timeout demands, NEW-VIEW,
    commit in view 1 — and still mutates nothing."""

    async def run():
        from minbft_tpu.sample.config import SimpleConfiger

        cfg = SimpleConfiger(
            n=4, f=1,
            timeout_request=0.8, timeout_prepare=0.4, timeout_viewchange=3.0,
        )
        replicas, c_auths, stubs, ledgers = await _cluster(cfg=cfg)
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        try:
            assert await asyncio.wait_for(client.request(b"write-1"), 30)

            stubs[0].crash()  # the view-0 primary
            await replicas[0].stop()

            head = await asyncio.wait_for(
                client.request(b"head", read_only=True, read_timeout=0.3), 30
            )
            height = struct.unpack(">Q", head[:8])[0]
            assert height == 1, height
            # survivors moved to view >= 1 and the read mutated nothing
            for r in replicas[1:]:
                cur, _ = await r.handlers.view_state.hold_view()
                assert cur >= 1, cur
            # poll: the slowest survivor may still be executing write-1
            # (quorums resolve at f+1 of 3)
            for _ in range(100):
                if all(lg.length == 1 for lg in ledgers[1:]):
                    break
                await asyncio.sleep(0.05)
            assert all(lg.length == 1 for lg in ledgers[1:]), [
                lg.length for lg in ledgers[1:]
            ]
            # ordinary writes still work in the new view
            assert await asyncio.wait_for(client.request(b"write-2"), 30)
        finally:
            await client.stop()
            for r in replicas[1:]:
                await r.stop()

    asyncio.run(run())


def test_fast_read_under_ed25519_scheme():
    """Scheme-independence: the read path signs/verifies replies like any
    REPLY, so it must work under the Ed25519 scheme (cfg5's) too."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster(scheme="ed25519")
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"write-1"), 60)
        for _ in range(200):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        assert all(lg.length == 1 for lg in ledgers)
        # read_fallback=False: this pins the FAST path under the scheme —
        # a silent ordered fallback would pass every assertion
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True, read_fallback=False,
                           read_timeout=30.0),
            60,
        )
        assert struct.unpack(">Q", head[:8])[0] == 1
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_concurrent_reads_and_writes_storm():
    """20 writers and 20 readers concurrently: writes execute exactly
    once, and EVERY read result is a (height, digest) the chain really
    passed through — a fabricated or torn read would name a digest that
    never existed at that height."""

    async def run():
        replicas, c_auths, stubs, ledgers = await _cluster(n_clients=2)
        writer = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        reader = new_client(
            1, 4, 1, c_auths[1], InProcessClientConnector(stubs), seq_start=0
        )
        await writer.start()
        await reader.start()
        reads: list = []

        async def write(i):
            await writer.request(b"w-%d" % i)

        async def read(i):
            # fallback allowed: under concurrent writes the all-n quorum
            # legitimately fails whenever a write is mid-execution
            reads.append(
                await reader.request(b"head", read_only=True, read_timeout=0.5)
            )

        await asyncio.wait_for(
            asyncio.gather(
                *(write(i) for i in range(20)), *(read(i) for i in range(20))
            ),
            60,
        )
        for _ in range(100):
            if all(lg.length == 20 for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        assert all(lg.length == 20 for lg in ledgers), [
            lg.length for lg in ledgers
        ]
        assert len(reads) == 20
        for res in reads:
            height = struct.unpack(">Q", res[:8])[0]
            assert 0 <= height <= 20, height
            blk = ledgers[0].block(height)
            assert blk is not None and blk.digest() == res[8:], (
                "read named a digest the chain never had at that height"
            )
        await writer.stop()
        await reader.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())
