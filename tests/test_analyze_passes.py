"""Per-pass fixture tests for tools.analyze: for each of the four project
passes, a snippet it MUST flag and a near-identical snippet it must NOT
flag (the calibration contract — precision regressions show up here)."""

import textwrap
from pathlib import Path

from tools.analyze import Project, run_passes
from tools.analyze.project import (
    AnalyzeConfig,
    DeadCodeConfig,
    ExhaustivenessConfig,
    LockClassSpec,
    SecretHygieneConfig,
    TracePurityConfig,
)

REPO = Path(__file__).resolve().parent.parent


def make_config(**kw):
    defaults = dict(
        source_roots=("src",),
        lock_classes=(),
        trace=TracePurityConfig(roots=()),
        exhaustiveness=None,
        secrets=SecretHygieneConfig(roots=()),
        dead=DeadCodeConfig(roots=()),
    )
    defaults.update(kw)
    return AnalyzeConfig(**defaults)


def analyze(tmp_path, files, config, select):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return run_passes(Project(tmp_path, config=config), select=select)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# lock discipline


LOCK_SPEC = (
    LockClassSpec(
        path="src/state.py", cls="State", locks=("_lock",), guarded=("auto",)
    ),
)

THREAD_SPEC = (
    LockClassSpec(
        path="src/eng.py",
        cls="Eng",
        locks=("_lock",),
        guarded=("_memo",),
        mode="threads",
    ),
)


def test_lock_discipline_flags_unlocked_write_across_await(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._seq = 0

                async def locked(self):
                    async with self._lock:
                        self._seq += 1

                async def racy(self, v):
                    await asyncio.sleep(0)
                    self._seq = v  # write after a suspension, no lock
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]
    assert "racy" in findings[0].message and "_seq" in findings[0].message


def test_lock_discipline_allows_sync_and_init_writes(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._seq = 0

                def sync_write(self, v):
                    self._seq = v  # loop-atomic: fine in "loop" mode

                async def no_suspension(self, v):
                    self._seq = v  # async but cannot interleave

                async def locked(self, v):
                    async with self._lock:
                        self._seq = v
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert findings == []


def test_lock_discipline_threads_mode_flags_sync_writes_and_mutators(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def ok(self, k, v):
                    with self._lock:
                        self._memo[k] = v

                def bad_assign(self, k, v):
                    self._memo[k] = v

                def bad_mutator(self):
                    self._memo.clear()
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001", "LD001"]


def test_lock_discipline_flags_lock_rebind(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def reset(self):
                    self._lock = threading.Lock()
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD002"]


def test_lock_discipline_auto_infers_guarded_attrs(tmp_path):
    """An attribute locked ONCE is guarded EVERYWHERE (lock affinity)."""
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._a = 0
                    self._free = 0

                async def locked(self):
                    async with self._lock:
                        self._a += 1

                async def racy(self):
                    await asyncio.sleep(0)
                    self._a = 9      # inferred-guarded: flagged
                    self._free = 9   # never locked anywhere: not guarded
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]
    assert "_a" in findings[0].message


def test_lock_discipline_condvar_wait_counts_as_suspension(tmp_path):
    """`await self._cond.wait()` inside `async with self._cond` both
    suspends AND releases the lock — an unlocked write elsewhere in the
    same method races it and must be flagged (the ClientState/PeerState
    pattern this pass exists for)."""
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Condition()
                    self._seq = 0

                async def bump(self):
                    async with self._lock:
                        while self._seq == 0:
                            await self._lock.wait()
                    self._seq += 1  # unlocked, after a real suspension
            """
        },
        make_config(
            lock_classes=(
                LockClassSpec(
                    path="src/state.py",
                    cls="State",
                    locks=("_lock",),
                    guarded=("_seq",),
                ),
            )
        ),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]


def test_lock_discipline_noqa(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def justified(self, k, v):
                    self._memo[k] = v  # noqa: LD001
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# trace purity


TRACE_CFG = TracePurityConfig(roots=("src",))


def test_trace_purity_flags_reachable_impurity(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax
            import numpy as np

            def _helper(x):
                print("tracing", x)       # TP101
                return np.asarray(x) + 1  # TP102: np on a traced value

            def _verify_one(x):
                if x > 0:                 # TP105: branch on a tracer
                    return _helper(x)
                return x

            verify_kernel = jax.jit(jax.vmap(_verify_one))
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert sorted(codes(findings)) == ["TP101", "TP102", "TP105"]


def test_trace_purity_ignores_host_side_and_static(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax
            import numpy as np

            def to_limbs(x: int):
                # host-static param: np here is trace-time constant building
                if not 0 <= x < 2**256:
                    raise ValueError("range")
                return np.array([x & 0xFFFF], dtype=np.uint32)

            def _verify_one(x):
                k = np.uint32(7)          # np on a literal: constant
                if x.shape[0] > 4:        # shape is static under trace
                    return x * k
                return x

            verify_kernel = jax.jit(jax.vmap(_verify_one))

            def host_driver(items):
                # NOT reachable from any jit root: impurity is fine here
                print(len(items))
                return np.asarray(items)
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert findings == []


def test_trace_purity_cross_module_reachability(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/limbs.py": """
            import time

            def slow_add(a, b):
                time.sleep(0.1)  # TP103, reachable from kernel.py's root
                return a + b
            """,
            "src/kernel.py": """
            import jax
            from limbs import slow_add

            def _one(x):
                return slow_add(x, x)

            k = jax.jit(_one)
            """,
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert codes(findings) == ["TP103"]
    assert findings[0].path == "src/limbs.py"


def test_trace_purity_flags_global_statement(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax

            _COUNT = 0

            def _one(x):
                global _COUNT   # TP104
                _COUNT += 1
                return x

            k = jax.jit(_one)
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert codes(findings) == ["TP104"]


# ---------------------------------------------------------------------------
# exhaustiveness


def _msg_tree(
    *,
    drop_codec_marshal=False,
    drop_codec_unmarshal=False,
    drop_authen=False,
    drop_handler=False,
):
    codec_marshal = "" if drop_codec_marshal else """
    if isinstance(m, Ping):
        return b"\\x01"
"""
    codec_unmarshal = "" if drop_codec_unmarshal else """
    if data[0] == 1:
        return Ping(replica_id=0)
"""
    authen = "" if drop_authen else """
    if isinstance(m, Ping):
        return b"PING"
"""
    handler = "" if drop_handler else """
        if isinstance(msg, Ping):
            return True
"""
    return {
        "src/message.py": """
class Message:
    KIND = "?"

class Ping(Message):
    KIND = "PING"
    replica_id: int
    signature: bytes = b""

SIGNED_MESSAGES = (Ping,)
""",
        "src/codec.py": f"""
from message import Message, Ping

def marshal(m):{codec_marshal}
    raise ValueError(m)

def _unmarshal_at(data, off):{codec_unmarshal}
    raise ValueError(data)
""",
        "src/authen.py": f"""
from message import Ping

def _authen_bytes(m):{authen}
    raise TypeError(m)
""",
        "src/handlers.py": f"""
from message import Ping

class H:
    async def validate_message(self, msg):{handler or "        pass"}
    async def process_message(self, msg):{handler or "        pass"}
""",
    }


EX_CFG = ExhaustivenessConfig(
    message_module="src/message.py",
    codec_module="src/codec.py",
    authen_module="src/authen.py",
    handler_module="src/handlers.py",
)


def test_exhaustiveness_clean_when_fully_wired(tmp_path):
    findings = analyze(
        tmp_path, _msg_tree(), make_config(exhaustiveness=EX_CFG), ["exhaustiveness"]
    )
    assert findings == []


def test_exhaustiveness_flags_each_missing_layer(tmp_path):
    for kw, expect in (
        ({"drop_codec_marshal": True}, "EX201"),
        ({"drop_codec_unmarshal": True}, "EX202"),
        ({"drop_authen": True}, "EX203"),
        ({"drop_handler": True}, "EX204"),
    ):
        tree = tmp_path / expect
        tree.mkdir()
        findings = analyze(
            tree, _msg_tree(**kw), make_config(exhaustiveness=EX_CFG), ["exhaustiveness"]
        )
        assert expect in codes(findings), (kw, findings)


def test_exhaustiveness_handler_alternative_verified(tmp_path):
    cfg = ExhaustivenessConfig(
        message_module="src/message.py",
        codec_module="src/codec.py",
        authen_module="src/authen.py",
        handler_module="src/handlers.py",
        handler_alternatives={"Ping": ("src/client.py", "client-side kind")},
    )
    # alternative module really handles it -> clean even though the
    # dispatch functions don't mention Ping
    files = _msg_tree(drop_handler=True)
    files["src/client.py"] = "from message import Ping\n\ndef on(msg):\n    return isinstance(msg, Ping)\n"
    findings = analyze(
        tmp_path / "ok", files, make_config(exhaustiveness=cfg), ["exhaustiveness"]
    )
    assert findings == []

    # alternative module does NOT handle it -> stale exemption (EX205)
    files2 = _msg_tree(drop_handler=True)
    files2["src/client.py"] = "def on(msg):\n    return False\n"
    findings = analyze(
        tmp_path / "stale", files2, make_config(exhaustiveness=cfg), ["exhaustiveness"]
    )
    assert "EX205" in codes(findings)


def test_exhaustiveness_on_this_repo_is_clean():
    findings = run_passes(Project(REPO), select=["exhaustiveness"])
    assert findings == []


# ---------------------------------------------------------------------------
# secret hygiene


SH_CFG = SecretHygieneConfig(roots=("src",))


def test_secret_hygiene_flags_interpolation_and_logging(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            import logging

            log = logging.getLogger("x")

            def leak(private_key, seed):
                msg = f"loaded key {private_key!r}"     # SH301
                log.info("seed is %s", seed)            # SH302
                print(repr(private_key))                # SH302 (print arg)
                return msg
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    got = codes(findings)
    assert "SH301" in got and got.count("SH302") == 2


def test_secret_hygiene_allows_public_names_and_truthiness(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            def fine(pub_key, keyspec, env_key, mac_keys, kid):
                a = f"public key {pub_key.hex()} spec {keyspec}"
                b = f"id {kid}, CONSENSUS_{env_key}"
                c = "with MACs" if mac_keys is not None else "no MACs"
                d = f"have {len(mac_keys)} macs"
                return a, b, c, d
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    assert findings == []


def test_secret_hygiene_flags_hex_and_format_sinks(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            def leak(sealed_blob, priv):
                a = "blob: " + sealed_blob.hex()        # SH303
                b = "{}".format(priv)                    # SH303
                c = "p=%s" % priv                        # SH303
                return a, b, c
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    assert codes(findings) == ["SH303", "SH303", "SH303"]


def test_secret_hygiene_on_this_repo_is_clean():
    findings = run_passes(Project(REPO), select=["secret-hygiene"])
    assert findings == []


# ---------------------------------------------------------------------------
# dead code


DC_CFG = DeadCodeConfig(roots=("src",))


def test_dead_code_flags_unused_import_and_local(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/m.py": """
            import os
            import sys
            from typing import Dict, List

            def f():
                unused = sys.platform     # DC402
                d: Dict = {}
                return d
            """
        },
        make_config(dead=DC_CFG),
        ["dead-code"],
    )
    assert sorted(codes(findings)) == ["DC401", "DC401", "DC402"]
    msgs = " ".join(f.message for f in findings)
    assert "os" in msgs and "List" in msgs and "unused" in msgs


def test_dead_code_ignores_class_attributes_in_function_scope(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/m.py": """
            def make():
                class Cfg:
                    retries = 3
                return Cfg
            """
        },
        make_config(dead=DC_CFG),
        ["dead-code"],
    )
    assert findings == []


def test_dead_code_respects_reexports_and_annotations(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/pkg/__init__.py": "from .m import helper\n",
            "src/pkg/m.py": """
            from typing import Optional

            def helper(x: "Optional[int]"):
                return x
            """,
            "src/closure.py": """
            def outer():
                captured = 1
                def inner():
                    return captured
                return inner
            """,
        },
        make_config(dead=DeadCodeConfig(roots=("src",))),
        ["dead-code"],
    )
    assert findings == []

# ---------------------------------------------------------------------------
# async hygiene


def ah_config(**kw):
    from tools.analyze.project import AsyncHygieneConfig

    return make_config(async_hygiene=AsyncHygieneConfig(roots=("src",), **kw))


def test_async_hygiene_flags_blocking_sink_through_sync_helper(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import time

            def helper():
                time.sleep(0.5)

            async def handler():
                helper()
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert codes(findings) == ["AH101"]
    assert "handler" in findings[0].message


def test_async_hygiene_cross_module_chain_and_witness_path(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/util.py": """
            import subprocess

            def probe():
                subprocess.run(["true"])
            """,
            "src/app.py": """
            from src.util import probe

            def shim():
                probe()

            async def serve():
                shim()
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert codes(findings) == ["AH101"]
    assert "serve" in findings[0].message and "probe" in findings[0].message


def test_async_hygiene_executor_handoff_is_whitelisted(tmp_path):
    # The SAME blocking helper is fine when it only runs behind
    # asyncio.to_thread / run_in_executor: the hand-off suspends.
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio
            import time

            def helper():
                time.sleep(0.5)

            async def handler():
                await asyncio.to_thread(helper)

            async def handler2():
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, helper)
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert findings == []


def test_async_hygiene_boundary_config_excludes_function(tmp_path):
    files = {
        "src/app.py": """
        import time

        def engine_step():
            time.sleep(0.001)

        async def run():
            engine_step()
        """,
    }
    flagged = analyze(tmp_path / "a", files, ah_config(), ["async-hygiene"])
    assert codes(flagged) == ["AH101"]
    excused = analyze(
        tmp_path / "b",
        files,
        ah_config(
            boundary={"src/app.py::engine_step": "micro-bounded by design"}
        ),
        ["async-hygiene"],
    )
    assert excused == []


def test_async_hygiene_sync_io_and_lock_and_pow(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                async def dump(self, path, doc):
                    with open(path, "w") as fh:
                        fh.write(doc)

                async def bump(self):
                    with self._lock:
                        pass

            async def modexp(x):
                return pow(x, 65537, 2**255 - 19)
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert sorted(codes(findings)) == ["AH102", "AH103", "AH104"]


def test_async_hygiene_sync_context_not_flagged(tmp_path):
    # The same sinks OUTSIDE the loop-reachable graph are fine.
    findings = analyze(
        tmp_path,
        {
            "src/tool.py": """
            import time

            def main():
                time.sleep(1)
                with open("x") as fh:
                    return fh.read()
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert findings == []


def test_async_hygiene_loop_scheduled_reference_is_a_root(tmp_path):
    # A SYNC function handed to call_soon runs on the loop: its sinks count.
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio
            import time

            def tick():
                time.sleep(0.1)

            def arm(loop):
                loop.call_soon(tick)
            """,
        },
        ah_config(),
        ["async-hygiene"],
    )
    assert codes(findings) == ["AH101"]


def test_async_hygiene_on_this_repo_is_clean():
    from tools.analyze.project import default_config

    project = Project(REPO, config=default_config())
    assert run_passes(project, select=["async-hygiene"]) == []


# ---------------------------------------------------------------------------
# task lifecycle


def tl_config():
    from tools.analyze.project import TaskLifecycleConfig

    return make_config(tasks=TaskLifecycleConfig(roots=("src",)))


def test_task_lifecycle_flags_dropped_and_unretained_tasks(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio

            async def work():
                pass

            async def bare():
                asyncio.create_task(work())

            async def named_but_dropped():
                t = asyncio.create_task(work())
                print("unrelated", 1)

            async def conditional_dropped(flag):
                t = (asyncio.create_task(work()) if flag else None)
            """,
        },
        tl_config(),
        ["task-lifecycle"],
    )
    assert codes(findings) == ["TL601", "TL601", "TL601"]


def test_task_lifecycle_retention_evidence_not_flagged(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio

            async def work():
                pass

            class H:
                def __init__(self):
                    self._bg_tasks = set()

                def spawn(self):
                    t = asyncio.get_running_loop().create_task(work())
                    self._bg_tasks.add(t)
                    t.add_done_callback(self._bg_tasks.discard)
                    return t

                def attr_store(self):
                    self._task = asyncio.create_task(work())

            async def awaited():
                await asyncio.create_task(work())

            async def cancelled_then_awaited():
                t = asyncio.create_task(work())
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass

            async def callback_only():
                t = asyncio.create_task(work())
                t.add_done_callback(lambda _t: None)

            async def gathered():
                t = asyncio.create_task(work())
                await asyncio.gather(t)
            """,
        },
        tl_config(),
        ["task-lifecycle"],
    )
    assert findings == []


def test_task_lifecycle_flags_unsnapshotted_tracked_set_iteration(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio

            class H:
                def __init__(self):
                    self._bg_tasks = set()

                def spawn(self, coro):
                    t = asyncio.create_task(coro)
                    self._bg_tasks.add(t)
                    t.add_done_callback(self._bg_tasks.discard)
                    return t

                def cancel_all(self):
                    for t in self._bg_tasks:
                        t.cancel()
            """,
        },
        tl_config(),
        ["task-lifecycle"],
    )
    assert codes(findings) == ["TL602"]
    assert "list(" in findings[0].message


def test_task_lifecycle_snapshotted_iteration_not_flagged(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": """
            import asyncio

            class H:
                def __init__(self):
                    self._bg_tasks = set()

                def spawn(self, coro):
                    t = asyncio.create_task(coro)
                    self._bg_tasks.add(t)
                    t.add_done_callback(self._bg_tasks.discard)
                    return t

                def cancel_all(self):
                    for t in list(self._bg_tasks):
                        t.cancel()
            """,
        },
        tl_config(),
        ["task-lifecycle"],
    )
    assert findings == []


def test_task_lifecycle_on_this_repo_is_clean():
    from tools.analyze.project import default_config

    project = Project(REPO, config=default_config())
    assert run_passes(project, select=["task-lifecycle"]) == []


# ---------------------------------------------------------------------------
# schema drift


def sd_files(bench_doc, bench_body, gate_body, prom_body="", test_body=""):
    return {
        "bench.py": f'"""{bench_doc}"""\n{bench_body}',
        "gate/__init__.py": gate_body,
        "obs/prom.py": prom_body,
        "tests/test_pins.py": test_body,
    }


def sd_config(**kw):
    from tools.analyze.project import SchemaDriftConfig

    return make_config(
        schema=SchemaDriftConfig(
            bench_module="bench.py",
            benchgate_module="gate/__init__.py",
            prom_module="obs/prom.py",
            pinned_tests=("tests/test_pins.py",),
            **kw,
        )
    )


SD_DOC = """Bench.

Extras schema:
  cfg_req_per_sec_mean   committed throughput
  ro_reads_per_sec       read-only phase rate

Environment knobs:
  NONE
"""


def test_schema_drift_clean_when_aligned(tmp_path):
    findings = analyze(
        tmp_path,
        sd_files(
            SD_DOC,
            'out = {"cfg_req_per_sec_mean": 1.0, "ro_reads_per_sec": 2.0}\n',
            '_MEAN_SUFFIX = "_req_per_sec_mean"\n',
        ),
        sd_config(),
        ["schema-drift"],
    )
    assert findings == []


def test_schema_drift_flags_each_direction(tmp_path):
    findings = analyze(
        tmp_path,
        sd_files(
            SD_DOC.replace(
                "\nEnvironment knobs:",
                "  ghost_req_per_sec_mean   never emitted\n"
                "\nEnvironment knobs:",
            ),
            'out = {"cfg_req_per_sec_mean": 1.0,'
            ' "new_goodput_per_sec": 3.0}\n',
            '_MEAN_SUFFIX = "_req_per_sec_meanX"\n',
        ),
        sd_config(),
        ["schema-drift"],
    )
    got = sorted(codes(findings))
    # cfg_req_per_sec_mean headline but ungated (701); the suffix gate
    # matches nothing (702); ghost_* documented but dead (703);
    # new_goodput_per_sec emitted+headline-suffixed but ungated AND
    # undocumented (701, 704); ro_reads_per_sec doc'd but dead (703).
    assert got == ["SD701", "SD701", "SD702", "SD703", "SD703", "SD704"]


def test_schema_drift_exempt_families_skip_gating(tmp_path):
    findings = analyze(
        tmp_path,
        sd_files(
            SD_DOC,
            'out = {"cfg_req_per_sec_mean": 1.0, "ro_reads_per_sec": 2.0,'
            ' "probe_goodput_per_sec": 3.0}\n',
            '_MEAN_SUFFIX = "_req_per_sec_mean"\n',
        ),
        sd_config(
            exempt={"probe_goodput_per_sec": "diagnostic, not a headline"}
        ),
        ["schema-drift"],
    )
    assert findings == []


def test_schema_drift_pinned_prom_names(tmp_path):
    findings = analyze(
        tmp_path,
        sd_files(
            SD_DOC,
            'out = {"cfg_req_per_sec_mean": 1.0, "ro_reads_per_sec": 2.0}\n',
            '_MEAN_SUFFIX = "_req_per_sec_mean"\n',
            prom_body='FAM = "minbft_committed_total"\n',
            test_body=(
                'OK = "minbft_committed_total"\n'
                'BAD = "minbft_never_registered_total"\n'
            ),
        ),
        sd_config(),
        ["schema-drift"],
    )
    assert codes(findings) == ["SD705"]
    assert "minbft_never_registered_total" in findings[0].message


def test_schema_drift_fstring_families_intersect(tmp_path):
    # f-string keys become * families on BOTH sides of the cross-check.
    findings = analyze(
        tmp_path,
        sd_files(
            SD_DOC + "  load_{half,sat,over}_p99_ms   sweep latency\n",
            "out = {\"cfg_req_per_sec_mean\": 1.0,"
            " \"ro_reads_per_sec\": 2.0}\n"
            "for point in ('half', 'sat', 'over'):\n"
            "    out[f'load_{point}_p99_ms'] = 1.0\n",
            '_MEAN_SUFFIX = "_req_per_sec_mean"\n',
        ),
        sd_config(),
        ["schema-drift"],
    )
    assert findings == []


def test_schema_drift_on_this_repo_is_clean():
    from tools.analyze.project import default_config

    project = Project(REPO, config=default_config())
    assert run_passes(project, select=["schema-drift"]) == []


# ---------------------------------------------------------------------------
# env registry


def er_config():
    from tools.analyze.project import EnvRegistryConfig

    return make_config(
        env=EnvRegistryConfig(roots=("src",), registry="ENV.md")
    )


ER_HEADER = "# Registry\n\n| Variable | Description |\n|---|---|\n"


def test_env_registry_clean_when_registered(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": 'import os\nV = os.environ.get("MINBFT_KNOB")\n',
            "ENV.md": ER_HEADER + "| `MINBFT_KNOB` | turns the knob |\n",
        },
        er_config(),
        ["env-registry"],
    )
    assert findings == []


def test_env_registry_flags_unregistered_dead_and_undescribed(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": (
                "import os\n"
                'A = os.environ.get("MINBFT_LIVE")\n'
                'B = os.environ.get("MINBFT_NEW_KNOB")\n'
            ),
            "ENV.md": ER_HEADER
            + "| `MINBFT_LIVE` | TODO: describe |\n"
            + "| `MINBFT_GONE` | removed long ago |\n",
        },
        er_config(),
        ["env-registry"],
    )
    got = sorted(codes(findings))
    assert got == ["ER501", "ER502", "ER503"]


def test_env_registry_prefix_pattern_covers_fstring_sites(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/app.py": (
                "import os\n"
                "def get(i):\n"
                '    return os.environ.get(f"MINBFT_CFG{i}_REQUESTS")\n'
            ),
            "ENV.md": ER_HEADER + "| `MINBFT_CFG*` | per-config knobs |\n",
        },
        er_config(),
        ["env-registry"],
    )
    assert findings == []


def test_env_registry_missing_registry_is_one_finding(tmp_path):
    findings = analyze(
        tmp_path,
        {"src/app.py": 'import os\nV = os.environ.get("MINBFT_KNOB")\n'},
        er_config(),
        ["env-registry"],
    )
    assert codes(findings) == ["ER501"]
    assert "registry missing" in findings[0].message


def test_env_registry_write_then_clean(tmp_path):
    from tools.analyze.passes.env_registry import write_registry

    files = {
        "src/app.py": 'import os\nV = os.environ.get("MINBFT_KNOB")\n',
    }
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    project = Project(tmp_path, config=er_config())
    relpath, count = write_registry(project)
    assert count == 1
    # freshly generated: every description is a TODO -> ER503 only
    project = Project(tmp_path, config=er_config())
    findings = run_passes(project, select=["env-registry"])
    assert codes(findings) == ["ER503"]
    # describe it -> clean
    reg = tmp_path / relpath
    reg.write_text(reg.read_text().replace("TODO: describe", "the knob"))
    project = Project(tmp_path, config=er_config())
    assert run_passes(project, select=["env-registry"]) == []


def test_env_registry_on_this_repo_is_clean():
    from tools.analyze.project import default_config

    project = Project(REPO, config=default_config())
    assert run_passes(project, select=["env-registry"]) == []
