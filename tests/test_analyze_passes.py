"""Per-pass fixture tests for tools.analyze: for each of the four project
passes, a snippet it MUST flag and a near-identical snippet it must NOT
flag (the calibration contract — precision regressions show up here)."""

import textwrap
from pathlib import Path

from tools.analyze import Project, run_passes
from tools.analyze.project import (
    AnalyzeConfig,
    DeadCodeConfig,
    ExhaustivenessConfig,
    LockClassSpec,
    SecretHygieneConfig,
    TracePurityConfig,
)

REPO = Path(__file__).resolve().parent.parent


def make_config(**kw):
    defaults = dict(
        source_roots=("src",),
        lock_classes=(),
        trace=TracePurityConfig(roots=()),
        exhaustiveness=None,
        secrets=SecretHygieneConfig(roots=()),
        dead=DeadCodeConfig(roots=()),
    )
    defaults.update(kw)
    return AnalyzeConfig(**defaults)


def analyze(tmp_path, files, config, select):
    for rel, content in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return run_passes(Project(tmp_path, config=config), select=select)


def codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# lock discipline


LOCK_SPEC = (
    LockClassSpec(
        path="src/state.py", cls="State", locks=("_lock",), guarded=("auto",)
    ),
)

THREAD_SPEC = (
    LockClassSpec(
        path="src/eng.py",
        cls="Eng",
        locks=("_lock",),
        guarded=("_memo",),
        mode="threads",
    ),
)


def test_lock_discipline_flags_unlocked_write_across_await(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._seq = 0

                async def locked(self):
                    async with self._lock:
                        self._seq += 1

                async def racy(self, v):
                    await asyncio.sleep(0)
                    self._seq = v  # write after a suspension, no lock
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]
    assert "racy" in findings[0].message and "_seq" in findings[0].message


def test_lock_discipline_allows_sync_and_init_writes(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._seq = 0

                def sync_write(self, v):
                    self._seq = v  # loop-atomic: fine in "loop" mode

                async def no_suspension(self, v):
                    self._seq = v  # async but cannot interleave

                async def locked(self, v):
                    async with self._lock:
                        self._seq = v
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert findings == []


def test_lock_discipline_threads_mode_flags_sync_writes_and_mutators(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def ok(self, k, v):
                    with self._lock:
                        self._memo[k] = v

                def bad_assign(self, k, v):
                    self._memo[k] = v

                def bad_mutator(self):
                    self._memo.clear()
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001", "LD001"]


def test_lock_discipline_flags_lock_rebind(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def reset(self):
                    self._lock = threading.Lock()
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD002"]


def test_lock_discipline_auto_infers_guarded_attrs(tmp_path):
    """An attribute locked ONCE is guarded EVERYWHERE (lock affinity)."""
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Lock()
                    self._a = 0
                    self._free = 0

                async def locked(self):
                    async with self._lock:
                        self._a += 1

                async def racy(self):
                    await asyncio.sleep(0)
                    self._a = 9      # inferred-guarded: flagged
                    self._free = 9   # never locked anywhere: not guarded
            """
        },
        make_config(lock_classes=LOCK_SPEC),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]
    assert "_a" in findings[0].message


def test_lock_discipline_condvar_wait_counts_as_suspension(tmp_path):
    """`await self._cond.wait()` inside `async with self._cond` both
    suspends AND releases the lock — an unlocked write elsewhere in the
    same method races it and must be flagged (the ClientState/PeerState
    pattern this pass exists for)."""
    findings = analyze(
        tmp_path,
        {
            "src/state.py": """
            import asyncio

            class State:
                def __init__(self):
                    self._lock = asyncio.Condition()
                    self._seq = 0

                async def bump(self):
                    async with self._lock:
                        while self._seq == 0:
                            await self._lock.wait()
                    self._seq += 1  # unlocked, after a real suspension
            """
        },
        make_config(
            lock_classes=(
                LockClassSpec(
                    path="src/state.py",
                    cls="State",
                    locks=("_lock",),
                    guarded=("_seq",),
                ),
            )
        ),
        ["lock-discipline"],
    )
    assert codes(findings) == ["LD001"]


def test_lock_discipline_noqa(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/eng.py": """
            import threading

            class Eng:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._memo = {}

                def justified(self, k, v):
                    self._memo[k] = v  # noqa: LD001
            """
        },
        make_config(lock_classes=THREAD_SPEC),
        ["lock-discipline"],
    )
    assert findings == []


# ---------------------------------------------------------------------------
# trace purity


TRACE_CFG = TracePurityConfig(roots=("src",))


def test_trace_purity_flags_reachable_impurity(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax
            import numpy as np

            def _helper(x):
                print("tracing", x)       # TP101
                return np.asarray(x) + 1  # TP102: np on a traced value

            def _verify_one(x):
                if x > 0:                 # TP105: branch on a tracer
                    return _helper(x)
                return x

            verify_kernel = jax.jit(jax.vmap(_verify_one))
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert sorted(codes(findings)) == ["TP101", "TP102", "TP105"]


def test_trace_purity_ignores_host_side_and_static(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax
            import numpy as np

            def to_limbs(x: int):
                # host-static param: np here is trace-time constant building
                if not 0 <= x < 2**256:
                    raise ValueError("range")
                return np.array([x & 0xFFFF], dtype=np.uint32)

            def _verify_one(x):
                k = np.uint32(7)          # np on a literal: constant
                if x.shape[0] > 4:        # shape is static under trace
                    return x * k
                return x

            verify_kernel = jax.jit(jax.vmap(_verify_one))

            def host_driver(items):
                # NOT reachable from any jit root: impurity is fine here
                print(len(items))
                return np.asarray(items)
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert findings == []


def test_trace_purity_cross_module_reachability(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/limbs.py": """
            import time

            def slow_add(a, b):
                time.sleep(0.1)  # TP103, reachable from kernel.py's root
                return a + b
            """,
            "src/kernel.py": """
            import jax
            from limbs import slow_add

            def _one(x):
                return slow_add(x, x)

            k = jax.jit(_one)
            """,
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert codes(findings) == ["TP103"]
    assert findings[0].path == "src/limbs.py"


def test_trace_purity_flags_global_statement(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/kernel.py": """
            import jax

            _COUNT = 0

            def _one(x):
                global _COUNT   # TP104
                _COUNT += 1
                return x

            k = jax.jit(_one)
            """
        },
        make_config(trace=TRACE_CFG),
        ["trace-purity"],
    )
    assert codes(findings) == ["TP104"]


# ---------------------------------------------------------------------------
# exhaustiveness


def _msg_tree(
    *,
    drop_codec_marshal=False,
    drop_codec_unmarshal=False,
    drop_authen=False,
    drop_handler=False,
):
    codec_marshal = "" if drop_codec_marshal else """
    if isinstance(m, Ping):
        return b"\\x01"
"""
    codec_unmarshal = "" if drop_codec_unmarshal else """
    if data[0] == 1:
        return Ping(replica_id=0)
"""
    authen = "" if drop_authen else """
    if isinstance(m, Ping):
        return b"PING"
"""
    handler = "" if drop_handler else """
        if isinstance(msg, Ping):
            return True
"""
    return {
        "src/message.py": """
class Message:
    KIND = "?"

class Ping(Message):
    KIND = "PING"
    replica_id: int
    signature: bytes = b""

SIGNED_MESSAGES = (Ping,)
""",
        "src/codec.py": f"""
from message import Message, Ping

def marshal(m):{codec_marshal}
    raise ValueError(m)

def _unmarshal_at(data, off):{codec_unmarshal}
    raise ValueError(data)
""",
        "src/authen.py": f"""
from message import Ping

def _authen_bytes(m):{authen}
    raise TypeError(m)
""",
        "src/handlers.py": f"""
from message import Ping

class H:
    async def validate_message(self, msg):{handler or "        pass"}
    async def process_message(self, msg):{handler or "        pass"}
""",
    }


EX_CFG = ExhaustivenessConfig(
    message_module="src/message.py",
    codec_module="src/codec.py",
    authen_module="src/authen.py",
    handler_module="src/handlers.py",
)


def test_exhaustiveness_clean_when_fully_wired(tmp_path):
    findings = analyze(
        tmp_path, _msg_tree(), make_config(exhaustiveness=EX_CFG), ["exhaustiveness"]
    )
    assert findings == []


def test_exhaustiveness_flags_each_missing_layer(tmp_path):
    for kw, expect in (
        ({"drop_codec_marshal": True}, "EX201"),
        ({"drop_codec_unmarshal": True}, "EX202"),
        ({"drop_authen": True}, "EX203"),
        ({"drop_handler": True}, "EX204"),
    ):
        tree = tmp_path / expect
        tree.mkdir()
        findings = analyze(
            tree, _msg_tree(**kw), make_config(exhaustiveness=EX_CFG), ["exhaustiveness"]
        )
        assert expect in codes(findings), (kw, findings)


def test_exhaustiveness_handler_alternative_verified(tmp_path):
    cfg = ExhaustivenessConfig(
        message_module="src/message.py",
        codec_module="src/codec.py",
        authen_module="src/authen.py",
        handler_module="src/handlers.py",
        handler_alternatives={"Ping": ("src/client.py", "client-side kind")},
    )
    # alternative module really handles it -> clean even though the
    # dispatch functions don't mention Ping
    files = _msg_tree(drop_handler=True)
    files["src/client.py"] = "from message import Ping\n\ndef on(msg):\n    return isinstance(msg, Ping)\n"
    findings = analyze(
        tmp_path / "ok", files, make_config(exhaustiveness=cfg), ["exhaustiveness"]
    )
    assert findings == []

    # alternative module does NOT handle it -> stale exemption (EX205)
    files2 = _msg_tree(drop_handler=True)
    files2["src/client.py"] = "def on(msg):\n    return False\n"
    findings = analyze(
        tmp_path / "stale", files2, make_config(exhaustiveness=cfg), ["exhaustiveness"]
    )
    assert "EX205" in codes(findings)


def test_exhaustiveness_on_this_repo_is_clean():
    findings = run_passes(Project(REPO), select=["exhaustiveness"])
    assert findings == []


# ---------------------------------------------------------------------------
# secret hygiene


SH_CFG = SecretHygieneConfig(roots=("src",))


def test_secret_hygiene_flags_interpolation_and_logging(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            import logging

            log = logging.getLogger("x")

            def leak(private_key, seed):
                msg = f"loaded key {private_key!r}"     # SH301
                log.info("seed is %s", seed)            # SH302
                print(repr(private_key))                # SH302 (print arg)
                return msg
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    got = codes(findings)
    assert "SH301" in got and got.count("SH302") == 2


def test_secret_hygiene_allows_public_names_and_truthiness(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            def fine(pub_key, keyspec, env_key, mac_keys, kid):
                a = f"public key {pub_key.hex()} spec {keyspec}"
                b = f"id {kid}, CONSENSUS_{env_key}"
                c = "with MACs" if mac_keys is not None else "no MACs"
                d = f"have {len(mac_keys)} macs"
                return a, b, c, d
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    assert findings == []


def test_secret_hygiene_flags_hex_and_format_sinks(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/ks.py": """
            def leak(sealed_blob, priv):
                a = "blob: " + sealed_blob.hex()        # SH303
                b = "{}".format(priv)                    # SH303
                c = "p=%s" % priv                        # SH303
                return a, b, c
            """
        },
        make_config(secrets=SH_CFG),
        ["secret-hygiene"],
    )
    assert codes(findings) == ["SH303", "SH303", "SH303"]


def test_secret_hygiene_on_this_repo_is_clean():
    findings = run_passes(Project(REPO), select=["secret-hygiene"])
    assert findings == []


# ---------------------------------------------------------------------------
# dead code


DC_CFG = DeadCodeConfig(roots=("src",))


def test_dead_code_flags_unused_import_and_local(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/m.py": """
            import os
            import sys
            from typing import Dict, List

            def f():
                unused = sys.platform     # DC402
                d: Dict = {}
                return d
            """
        },
        make_config(dead=DC_CFG),
        ["dead-code"],
    )
    assert sorted(codes(findings)) == ["DC401", "DC401", "DC402"]
    msgs = " ".join(f.message for f in findings)
    assert "os" in msgs and "List" in msgs and "unused" in msgs


def test_dead_code_ignores_class_attributes_in_function_scope(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/m.py": """
            def make():
                class Cfg:
                    retries = 3
                return Cfg
            """
        },
        make_config(dead=DC_CFG),
        ["dead-code"],
    )
    assert findings == []


def test_dead_code_respects_reexports_and_annotations(tmp_path):
    findings = analyze(
        tmp_path,
        {
            "src/pkg/__init__.py": "from .m import helper\n",
            "src/pkg/m.py": """
            from typing import Optional

            def helper(x: "Optional[int]"):
                return x
            """,
            "src/closure.py": """
            def outer():
                captured = 1
                def inner():
                    return captured
                return inner
            """,
        },
        make_config(dead=DeadCodeConfig(roots=("src",))),
        ["dead-code"],
    )
    assert findings == []
