"""Whole-system test: real `peer` CLI replica *processes* over gRPC
sockets commit a request submitted by the `peer request` CLI — the
scripted-deployment flow (deploy/local_testnet.sh) as a pytest.

The reference demonstrates this flow manually (README.md:411-458, killing
processes to show fault tolerance); here it runs under CI on the CPU
backend with --no-batch (serial host crypto: no kernel compiles in the
replica processes)."""

import json
import os
import subprocess
import sys
import time


REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


from minbft_tpu.utils.netports import free_base_port as _free_base_port
from minbft_tpu.utils.netports import wait_ports as _wait_ports


def _wait_for_log(paths, needle: bytes, timeout: float) -> bool:
    """Poll until ``needle`` appears in any of the log files."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if any(needle in open(p, "rb").read() for p in paths):
            return True
        time.sleep(0.5)
    return False


def test_three_process_cluster_commits(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    d = str(tmp_path)
    base_port = _free_base_port(3)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port), "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            # a log file, not PIPE: an unread pipe fills and blocks the
            # replica; closed in the finally block
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch"],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "process-cluster-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr
        assert len(req.stdout.strip()) == 64  # hex block digest

        # read-only FAST path over the same sockets (no ordered
        # fallback, or a fast-quorum regression would pass silently):
        # height 1 + head digest matching the write's result above
        ro = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "head", "--read-only", "--no-read-fallback",
             "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert ro.returncode == 0, ro.stderr
        head = ro.stdout.strip()
        assert head[:16] == "0000000000000001", head
        assert head[16:] == req.stdout.strip(), (head, req.stdout)

        # f=1: kill one backup, the cluster still commits
        replicas[2].terminate()
        replicas[2].wait(timeout=10)
        req2 = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "after-backup-kill", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req2.returncode == 0, req2.stderr
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_primary_crash_recovers_over_real_processes(tmp_path):
    """The README's manual primary-crash demo as a test: kill the view-0
    primary PROCESS of a real gRPC testnet, and a subsequent request
    commits in view >= 1 through the full view-change protocol (demand ->
    VIEW-CHANGE -> NEW-VIEW -> re-proposal) — the reference can only
    demonstrate backup crashes (its view change is 'Not implemented')."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # short protocol timeouts so the view change fires quickly
        CONSENSUS_TIMEOUT_REQUEST="2s",
        CONSENSUS_TIMEOUT_PREPARE="1s",
        CONSENSUS_TIMEOUT_VIEWCHANGE="5s",
    )
    d = str(tmp_path)
    base_port = _free_base_port(3)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port), "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch"],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "before-primary-crash", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        # kill the view-0 PRIMARY (replica 0)
        replicas[0].kill()
        replicas[0].wait(timeout=10)

        req2 = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "after-primary-crash", "--timeout", "150"],
            env=env, capture_output=True, text=True, timeout=200,
        )
        assert req2.returncode == 0, (
            req2.stderr
            + "".join(
                open(f"{d}/replica{i}.log", "rb")
                .read()
                .decode(errors="replace")[-2000:]
                for i in (1, 2)
            )
        )
        assert len(req2.stdout.strip()) == 64

        # the survivors entered a view >= 1 (their logs record it)
        recovered = any(
            b"entered view" in open(f"{d}/replica{i}.log", "rb").read()
            for i in (1, 2)
        )
        assert recovered, "no survivor logged a completed view change"
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_tcp_transport_cluster_commits(tmp_path):
    """The native TCP transport (sample/conn/tcp — length-prefixed frames
    over asyncio streams, the low-per-frame-cost alternative to gRPC)
    carries the same authenticated protocol: a 3-process cluster commits,
    and survives a backup kill."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    d = str(tmp_path)
    base_port = _free_base_port(3)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port), "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "--transport", "tcp", "run", str(i), "--no-batch"],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", "tcp-cluster-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr
        assert len(req.stdout.strip()) == 64

        replicas[2].terminate()
        replicas[2].wait(timeout=10)
        req2 = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", "after-backup-kill", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req2.returncode == 0, req2.stderr
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_tcp_primary_crash_recovers(tmp_path):
    """View change over the native TCP transport: kill the view-0 primary
    process and a later request commits in view >= 1 (the transport's
    reconnect/stream semantics must carry the full transition)."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        CONSENSUS_TIMEOUT_REQUEST="2s",
        CONSENSUS_TIMEOUT_PREPARE="1s",
        CONSENSUS_TIMEOUT_VIEWCHANGE="5s",
    )
    d = str(tmp_path)
    base_port = _free_base_port(3)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port), "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "--transport", "tcp", "run", str(i), "--no-batch"],
                    env=env, stdout=subprocess.DEVNULL, stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", "before-crash", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        replicas[0].kill()  # the view-0 primary
        replicas[0].wait(timeout=10)

        req2 = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", "after-crash", "--timeout", "150"],
            env=env, capture_output=True, text=True, timeout=200,
        )
        assert req2.returncode == 0, (
            req2.stderr
            + "".join(
                open(f"{d}/replica{i}.log", "rb").read().decode(errors="replace")[-1500:]
                for i in (1, 2)
            )
        )
        assert any(
            b"entered view" in open(f"{d}/replica{i}.log", "rb").read()
            for i in (1, 2)
        ), "no survivor logged a completed view change"
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_late_replica_joins_via_state_transfer_over_sockets(tmp_path):
    """Certified state transfer over REAL sockets: 3 of 4 replicas commit
    past the checkpoint window (peers truncate the history the absent
    replica would need), then replica 3 starts from nothing, fetches the
    certified snapshot over its peer connections, and follows live
    traffic.  (The in-process variant is
    test_checkpoint_gc.test_wiped_replica_joins_via_state_transfer; this
    pins the same flow through the wire transport's HELLO replay +
    LOG-BASE + SNAPSHOT-REQ/RESP unicast path.)"""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # small checkpoint window so 150 requests force truncation
        CONSENSUS_CHECKPOINT_PERIOD="20",
        CONSENSUS_TIMEOUT_REQUEST="60s",
        CONSENSUS_TIMEOUT_PREPARE="30s",
    )
    d = str(tmp_path)
    base_port = _free_base_port(4)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "4", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA", "--clients", "4"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = {}
    logs = []

    def start_replica(i):
        log = open(f"{d}/replica{i}.log", "wb")
        logs.append(log)
        replicas[i] = subprocess.Popen(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "run", str(i), "--no-batch",
             "--metrics-interval", "1"],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )

    try:
        for i in range(3):  # replica 3 stays offline
            start_replica(i)
        assert _wait_ports([base_port + i for i in range(3)]), "never bound"

        bench = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "bench", "--clients", "4",
             "--requests", "150", "--depth", "8", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert bench.returncode == 0, bench.stderr[-500:]

        # peers truncated the prefix replica 3 would need
        peer_logs = [f"{d}/replica{i}.log" for i in range(3)]
        assert _wait_for_log(peer_logs, b"log truncated", 30), (
            "no replica truncated; the join below would not need transfer"
        )

        start_replica(3)
        assert _wait_ports([base_port + 3]), "late replica never bound"

        assert _wait_for_log([f"{d}/replica3.log"], b"state transfer complete", 90), (
            "late replica never completed state transfer: "
            + open(f"{d}/replica3.log", "rb").read().decode(errors="replace")[-1500:]
        )

        # and it follows live traffic — REPLICA 3 itself must execute the
        # post-join request (the quorum of 0-2 would answer the client
        # even with 3 wedged, so check its own metrics, not the reply)
        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", "post-join", "--timeout", "60"],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert req.returncode == 0, req.stderr

        def replica3_executed() -> int:
            best = 0
            for line in open(f"{d}/replica3.log", errors="replace").read().splitlines():
                if "metrics:" in line:
                    snap = json.loads(line[line.index("metrics:") + 8 :])
                    best = max(best, snap.get("requests_executed", 0))
            return best

        deadline = time.time() + 30
        while time.time() < deadline and replica3_executed() < 1:
            time.sleep(0.5)
        assert replica3_executed() >= 1, (
            "replica 3 installed the snapshot but never executed live "
            "traffic"
        )
    finally:
        for p in replicas.values():
            if p.poll() is None:
                p.terminate()
        for p in replicas.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_killed_replica_rejoins_after_restart(tmp_path):
    """Survivors REDIAL a killed-and-restarted replica (reconnect with
    backoff): before stream self-healing, an established peer connection
    that died was never redialed, so a restarted replica received no
    broadcasts and was silently lost to the cluster.  Proven load-bearing
    by killing a DIFFERENT replica afterwards — the final request can only
    reach its n-f=3 quorum if the restarted replica participates.
    (The manual variant is the kill/restart drive in the verify recipe;
    the late-joiner test above covers the never-connected case, which
    worked even pre-reconnect via the initial dial window.)"""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        CONSENSUS_TIMEOUT_REQUEST="60s",
        CONSENSUS_TIMEOUT_PREPARE="30s",
    )
    d = str(tmp_path)
    base_port = _free_base_port(4)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "4", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    # ProcessChaos (testing/faultnet.py): the SIGKILL + respawn chaos
    # helper — kills/restarts are censused like any other injected fault.
    from minbft_tpu.testing import ProcessChaos

    chaos = ProcessChaos()
    logs = []

    def start_replica(i):
        log = open(f"{d}/replica{i}.log", "ab")
        logs.append(log)
        return subprocess.Popen(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "run", str(i), "--no-batch"],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )

    def req(op, timeout=120):
        r = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--transport", "tcp", "request", op, "--timeout", str(timeout)],
            env=env, capture_output=True, text=True, timeout=timeout + 60,
        )
        assert r.returncode == 0, f"{op}: {r.stderr[-800:]}"

    try:
        for i in range(4):
            chaos.manage(f"r{i}", lambda i=i: start_replica(i))
        assert _wait_ports([base_port + i for i in range(4)]), "never bound"

        req("before-kill")

        # snapshot log sizes so the redial assertion below cannot be
        # satisfied by cluster-formation dial noise from before the kill
        survivor_logs = [f"{d}/replica{i}.log" for i in range(3)]
        pre_kill = [os.path.getsize(p) for p in survivor_logs]

        chaos.kill("r3")  # SIGKILL: no graceful close on any stream
        req("while-down")  # 3/4 still commits

        chaos.restart("r3")
        assert _wait_ports([base_port + 3]), "restarted replica never bound"

        # every survivor's ESTABLISHED stream to 3 died at the kill and
        # must have entered the redial ladder (post-kill bytes only)
        def redialed_peer3() -> bool:
            for p, off in zip(survivor_logs, pre_kill):
                with open(p, "rb") as fh:
                    fh.seek(off)
                    if b"peer 3 stream ended: reconnecting" in fh.read():
                        return True
            return False

        deadline = time.time() + 30
        while time.time() < deadline and not redialed_peer3():
            time.sleep(0.5)
        assert redialed_peer3(), "no survivor ever redialed the killed peer"

        # ladder caps at 10s: give every survivor time to re-establish,
        # then make the restarted replica LOAD-BEARING for the quorum
        time.sleep(12)
        chaos.kill("r2")
        req("rejoined-load-bearing", timeout=150)

        # the chaos helper censused every scripted fault
        counts = chaos.census.snapshot()["counters"]
        assert counts == {"crash": 2, "restart": 1}, counts
    finally:
        chaos.terminate_all()
        for log in logs:
            log.close()

def test_metrics_port_served_and_scraped_by_peer_metrics(tmp_path):
    """Acceptance (ISSUE 4): `peer run --metrics-port` serves Prometheus
    text from a REAL replica process, the `peer metrics` subcommand
    scrapes it, and a SIGTERM shutdown writes the MINBFT_TRACE_DUMP
    JSON the flight recorder promised."""
    import re
    import urllib.request

    d = str(tmp_path)
    trace_base = f"{d}/trace"
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        MINBFT_TRACE_DUMP=trace_base,  # recorder on + dump at shutdown
    )
    base_port = _free_base_port(3)
    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            cmd = [sys.executable, "-m", "minbft_tpu.sample.peer",
                   "--keys", f"{d}/keys.yaml",
                   "--config", f"{d}/consensus.yaml",
                   "run", str(i), "--no-batch"]
            if i == 0:
                cmd += ["--metrics-port", "0"]  # 0 = pick a free port
            replicas.append(
                subprocess.Popen(env=env, args=cmd,
                                 stdout=subprocess.DEVNULL, stderr=log)
            )
        assert _wait_ports([base_port + i for i in range(3)]), "never bound"
        assert _wait_for_log([f"{d}/replica0.log"], b"/metrics", 30), (
            "replica 0 never announced its metrics endpoint"
        )
        mport = int(
            re.search(
                rb"metrics on http://[^:]+:(\d+)/metrics",
                open(f"{d}/replica0.log", "rb").read(),
            ).group(1)
        )

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "metrics-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        # the `peer metrics` subcommand scrapes the live endpoint
        scrape = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "metrics", f"127.0.0.1:{mport}"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert scrape.returncode == 0, scrape.stderr
        assert 'minbft_requests_executed_total{replica="0"} 1' in scrape.stdout
        assert "minbft_stage_latency_seconds_bucket" in scrape.stdout
        assert 'stage="commit_quorum"' in scrape.stdout
        # raw HTTP agrees on the content type (Prometheus text 0.0.4)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=10
        ) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )

        # graceful SIGTERM shutdown writes the per-replica trace dump
        replicas[0].terminate()
        replicas[0].wait(timeout=30)
        dump = f"{trace_base}.r0.json"
        assert os.path.exists(dump), os.listdir(d)
        doc = json.load(open(dump))
        assert doc["kind"] == "replica" and doc["id"] == 0
        assert doc["hists"], "stage histograms must land in the dump"
        assert doc["events"], "ring events must land in the dump"
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_grouped_cluster_commits_over_real_processes(tmp_path):
    """`peer run` hosting G=2 consensus groups per process (README
    §Sharding): the config declares protocol.groups, every replica
    process runs a GroupRuntime behind its one listener, and `peer
    request` routes by shard key / pins with --group over the shared
    gRPC sockets — the whole-system proof of the multi-group wire
    format (group envelopes + HELLO demux + domain-separated
    signatures)."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    d = str(tmp_path)
    base_port = _free_base_port(3)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "3", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA", "--groups", "2"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(3):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch"],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"

        # routed by the shard hash of the op bytes (whichever group that
        # is, the result must come back committed)
        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "grouped-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr
        assert len(req.stdout.strip()) == 64  # hex block digest

        # pinned to each group explicitly: BOTH group instances in every
        # process must be live behind the one listener
        for g in (0, 1):
            pinned = subprocess.run(
                [sys.executable, "-m", "minbft_tpu.sample.peer",
                 "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                 "request", f"pinned-g{g}", "--group", str(g),
                 "--timeout", "120"],
                env=env, capture_output=True, text=True, timeout=180,
            )
            assert pinned.returncode == 0, (g, pinned.stderr)
            assert len(pinned.stdout.strip()) == 64, (g, pinned.stdout)
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()

def _metrics_port(log_path: str) -> int:
    import re

    assert _wait_for_log([log_path], b"/metrics", 30), (
        f"{log_path} never announced its metrics endpoint"
    )
    return int(
        re.search(
            rb"metrics on http://[^:]+:(\d+)/metrics",
            open(log_path, "rb").read(),
        ).group(1)
    )


def test_peer_top_once_renders_live_ungrouped_cluster(tmp_path):
    """Acceptance (ISSUE 14): `peer top --once` against a real n=4
    `peer run --metrics-port` cluster renders the console header plus
    one healthy row and a build-attribution line per target, exit 0."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    d = str(tmp_path)
    base_port = _free_base_port(4)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "4", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(4):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch", "--metrics-port", "0"],
                    env=env, stdout=subprocess.DEVNULL, stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(4)]), "never bound"
        mports = [_metrics_port(f"{d}/replica{i}.log") for i in range(4)]

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "top-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        addrs = [f"127.0.0.1:{p}" for p in mports]
        top = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "top", "--once", *addrs],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert top.returncode == 0, top.stderr + top.stdout
        out = top.stdout
        assert "TARGET" in out and "REQ/S" in out and "HEALTH" in out
        for i, addr in enumerate(addrs):
            assert addr in out, out
            # the replica's identity row renders healthy in view 0
            row = next(ln for ln in out.splitlines() if ln.startswith(addr))
            assert row.rstrip().endswith("ok"), row
            rid, grp = row[24:30].split()
            assert rid == str(i) and grp == "-", row
        # one attribution line per target
        assert out.count("└ pid=") == 4, out
        assert "backend=" in out and "run=" in out

        # --stall-flag on a healthy cluster still exits 0; a dead target
        # renders DOWN and exits 1 (the CI contract)
        dead = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "top", "--once", "127.0.0.1:1"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert dead.returncode == 1, dead.stdout
        assert "DOWN" in dead.stdout
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_peer_top_once_renders_live_grouped_cluster(tmp_path):
    """Acceptance (ISSUE 14), grouped flavor: each `peer run` process
    hosts G=2 consensus groups, so every target renders one row PER
    GROUP (the stale-group and per-group committed gauges are per-core)
    — `peer top --once` must show both group identities per process."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    d = str(tmp_path)
    base_port = _free_base_port(4)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "4", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA", "--groups", "2"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(4):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch", "--metrics-port", "0"],
                    env=env, stdout=subprocess.DEVNULL, stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(4)]), "never bound"
        mports = [_metrics_port(f"{d}/replica{i}.log") for i in range(4)]

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "grouped-top-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        addrs = [f"127.0.0.1:{p}" for p in mports]
        top = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "top", "--once", *addrs],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert top.returncode == 0, top.stderr + top.stdout
        out = top.stdout
        for addr in addrs:
            rows = [ln for ln in out.splitlines() if ln.startswith(addr)]
            assert len(rows) == 2, (addr, rows, out)  # one row per group
            groups = set()
            for row in rows:
                assert row.rstrip().endswith("ok"), row
                groups.add(row[24:30].split()[-1])
            assert groups == {"0", "1"}, rows
        assert out.count("└ pid=") == 4, out
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()


def test_peer_slo_scrapes_live_cluster_with_slo_families(tmp_path):
    """Acceptance (ISSUE 19): with MINBFT_SLO_TARGET_MS set, a real
    `peer run --metrics-port` cluster exposes the minbft_slo_* families
    on /metrics, `peer top --once` renders the BURN/BUDG columns, and
    the one-shot `peer slo` report folds the scrape into per-group
    rows (rc 0; --breach-flag stays 0 on a healthy cluster)."""
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env["MINBFT_SLO_TARGET_MS"] = "60000"
    d = str(tmp_path)
    base_port = _free_base_port(4)

    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", "4", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr

    replicas = []
    logs = []
    try:
        for i in range(4):
            log = open(f"{d}/replica{i}.log", "wb")
            logs.append(log)
            replicas.append(
                subprocess.Popen(
                    [sys.executable, "-m", "minbft_tpu.sample.peer",
                     "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                     "run", str(i), "--no-batch", "--metrics-port", "0"],
                    env=env, stdout=subprocess.DEVNULL, stderr=log,
                )
            )
        assert _wait_ports([base_port + i for i in range(4)]), "never bound"
        mports = [_metrics_port(f"{d}/replica{i}.log") for i in range(4)]

        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "request", "slo-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr

        import urllib.request

        text = urllib.request.urlopen(
            f"http://127.0.0.1:{mports[0]}/metrics", timeout=10
        ).read().decode()
        for family in (
            "minbft_slo_good_total",
            "minbft_slo_breached_total",
            "minbft_slo_target_ms",
            "minbft_slo_objective",
            "minbft_slo_budget_remaining",
            "minbft_slo_burn_threshold",
            "minbft_slo_burn_rate",
        ):
            assert family in text, family
        assert 'window="fast"' in text and 'window="slow"' in text

        addrs = [f"127.0.0.1:{p}" for p in mports]
        top = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "top", "--once", "--stall-flag", *addrs],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert top.returncode == 0, top.stderr + top.stdout
        assert "BURN" in top.stdout and "BUDG" in top.stdout

        slo = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "slo", "--json", "--breach-flag", *addrs],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert slo.returncode == 0, slo.stderr + slo.stdout
        report = json.loads(slo.stdout)
        assert len(report["targets"]) == 4
        committed_somewhere = 0
        for tgt in report["targets"]:
            row = tgt["groups"]["-"]  # ungrouped: one identity row
            assert row["target_ms"] == 60000.0
            assert 0 < row["objective"] <= 1.0
            assert row["good_fraction"] == 1.0  # 60s budget: all good
            assert not row.get("breach")
            committed_somewhere += row.get("good", 0)
            assert tgt["spool"] == {"written": 0, "suppressed": 0}
        assert committed_somewhere >= 1  # the committed op was classed

        # the human rendering of the same report
        table = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "slo", addrs[0]],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert table.returncode == 0, table.stderr + table.stdout
        assert "GOODFRAC" in table.stdout and "TARGET_MS" in table.stdout
    finally:
        for p in replicas:
            if p.poll() is None:
                p.terminate()
        for p in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
