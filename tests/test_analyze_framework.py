"""Framework-level tests for tools.analyze: suppressions, baseline
round-trip (add finding -> baseline -> suppressed -> fix -> stale), CLI
exit codes, and pass registration."""

import json
import subprocess
import sys
from pathlib import Path

from tools.analyze import Baseline, Finding, Project, all_passes, run_passes
from tools.analyze.core import is_suppressed
from tools.analyze.project import (
    AnalyzeConfig,
    DeadCodeConfig,
    SecretHygieneConfig,
    TracePurityConfig,
)

REPO = Path(__file__).resolve().parent.parent


def make_config(**kw):
    """A minimal config: everything off unless a fixture opts in."""
    defaults = dict(
        source_roots=("src",),
        lock_classes=(),
        trace=TracePurityConfig(roots=()),
        exhaustiveness=None,
        secrets=SecretHygieneConfig(roots=()),
        dead=DeadCodeConfig(roots=()),
    )
    defaults.update(kw)
    return AnalyzeConfig(**defaults)


def write_tree(root: Path, files: dict) -> None:
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)


def test_all_four_project_passes_registered():
    passes = all_passes()
    prefixes = {cls.code_prefix for cls in passes.values()}
    assert {"LD", "TP", "EX", "SH", "DC"} <= prefixes


def test_noqa_suppresses_same_line_and_line_above(tmp_path):
    write_tree(
        tmp_path,
        {
            "src/m.py": (
                "import os  # noqa: DC401\n"
                "# noqa: DC401\n"
                "import sys\n"
                "import json\n"
            )
        },
    )
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    project = Project(tmp_path, config=cfg)
    findings = run_passes(project, select=["dead-code"])
    # os (inline noqa) and sys (standalone noqa above) suppressed; json not
    assert [f.message for f in findings] == ["unused import json"]


def test_bare_noqa_suppresses_all_codes(tmp_path):
    write_tree(tmp_path, {"src/m.py": "import os  # noqa\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    assert run_passes(Project(tmp_path, config=cfg), select=["dead-code"]) == []


def test_noqa_with_other_code_does_not_suppress(tmp_path):
    write_tree(tmp_path, {"src/m.py": "import os  # noqa: LD001\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    findings = run_passes(Project(tmp_path, config=cfg), select=["dead-code"])
    assert len(findings) == 1


def test_is_suppressed_out_of_range_line(tmp_path):
    write_tree(tmp_path, {"src/m.py": "x = 1\n"})
    project = Project(tmp_path, config=make_config())
    assert not is_suppressed(project, Finding("XX001", "src/m.py", 99, "m"))


def test_fingerprint_excludes_line_number():
    a = Finding("DC401", "src/m.py", 3, "unused import os")
    b = Finding("DC401", "src/m.py", 30, "unused import os")
    assert a.fingerprint == b.fingerprint


def test_baseline_round_trip(tmp_path):
    """The satellite-task contract: add finding -> write baseline ->
    suppressed -> fix the finding -> the baseline entry reports stale."""
    src = tmp_path / "src" / "m.py"
    write_tree(tmp_path, {"src/m.py": "import os\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))

    findings = run_passes(Project(tmp_path, config=cfg), select=["dead-code"])
    assert len(findings) == 1

    bl_path = tmp_path / "baseline.json"
    Baseline.from_findings(findings).save(bl_path)
    entries = json.loads(bl_path.read_text())["findings"]
    assert len(entries) == 1
    (entry,) = entries.values()
    assert entry["count"] == 1
    assert entry["justification"]  # never silently empty

    # baselined -> suppressed
    reported, suppressed, stale = Baseline.load(bl_path).apply(findings)
    assert reported == [] and len(suppressed) == 1 and stale == []

    # a SECOND instance of the same fingerprint exceeds the budget
    dup = findings + findings
    reported, suppressed, stale = Baseline.load(bl_path).apply(dup)
    assert len(reported) == 1 and len(suppressed) == 1

    # surplus budget is stale too: fixing one of N baselined instances
    # must be detected, or the leftover budget would silently absorb the
    # next regression of the same pattern
    surplus = Baseline(
        {findings[0].fingerprint: {"count": 3, "justification": "x"}}
    )
    reported, suppressed, stale = surplus.apply(findings)
    assert reported == [] and len(suppressed) == 1
    assert stale == [findings[0].fingerprint]

    # fix the finding -> entry is stale
    src.write_text("import os\nprint(os.sep)\n")
    project = Project(tmp_path, config=cfg)  # fresh AST cache
    findings = run_passes(project, select=["dead-code"])
    assert findings == []
    reported, suppressed, stale = Baseline.load(bl_path).apply(findings)
    assert reported == [] and suppressed == []
    assert len(stale) == 1 and "DC401" in stale[0]


def test_baseline_keeps_justification_on_regeneration(tmp_path):
    f = Finding("DC401", "src/m.py", 1, "unused import os")
    bl = Baseline.from_findings([f])
    bl.entries[f.fingerprint]["justification"] = "kept for the demo"
    bl2 = Baseline.from_findings([f, f], old=bl)
    assert bl2.entries[f.fingerprint]["justification"] == "kept for the demo"
    assert bl2.entries[f.fingerprint]["count"] == 2


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.analyze", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
    )


def test_cli_on_this_repo_is_clean_and_fails_on_seeded_violation(tmp_path):
    """Acceptance pin: `make lint`'s analyzer step exits 0 on the repo as
    committed, and non-zero once a violation of each pass is seeded."""
    clean = _run_cli([], REPO)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    # Seed one violation per pass in a scratch tree via --root + the test
    # config is not reachable from the CLI, so seed into a COPY of the
    # default layout: cheapest is a dead import in a new file under
    # tests/ … but that would dirty the repo.  Instead: a fixture root
    # exercising the dead-code pass end-to-end through the CLI.
    write_tree(
        tmp_path,
        {
            "tools/analyze/placeholder.txt": "",
            "minbft_tpu/bad.py": "import os\n",
        },
    )
    seeded = _run_cli(["--root", str(tmp_path)], REPO)
    assert seeded.returncode == 1
    assert "DC401" in seeded.stdout


def test_cli_write_baseline_refuses_partial_select(tmp_path):
    # A partial run writing the baseline would destroy the other passes'
    # grandfathered entries.
    res = _run_cli(
        ["--select", "dead-code", "--write-baseline", "--baseline",
         str(tmp_path / "bl.json")],
        REPO,
    )
    assert res.returncode == 2
    assert "full run" in res.stderr


def test_cli_list_passes():
    out = _run_cli(["--list-passes"], REPO)
    assert out.returncode == 0
    for name in (
        "lock-discipline",
        "trace-purity",
        "exhaustiveness",
        "secret-hygiene",
        "dead-code",
    ):
        assert name in out.stdout


def test_cli_stale_baseline_fails_and_allow_stale_passes(tmp_path):
    # The repo itself is clean, so a baseline naming a long-gone finding
    # is pure staleness: an error by default, tolerated with --allow-stale.
    bl = tmp_path / "baseline.json"
    bl.write_text(
        json.dumps(
            {
                "version": 1,
                "findings": {
                    "DC401:minbft_tpu/gone.py:unused import os": {
                        "count": 1,
                        "justification": "was grandfathered",
                    }
                },
            }
        )
    )
    res = _run_cli(["--baseline", str(bl)], REPO)
    assert res.returncode == 1 and "STALE" in res.stdout
    res = _run_cli(["--baseline", str(bl), "--allow-stale"], REPO)
    assert res.returncode == 0


# ---------------------------------------------------------------------------
# machine-readable output (the CI contract)


def test_json_report_and_annotation_round_trip():
    from tools.analyze.core import (
        finding_to_dict,
        findings_to_json,
        github_annotation,
    )

    f = Finding(
        "AH101", "src/app.py", 12,
        "blocking call time.sleep() on the event loop, 50% slower",
        severity="error", pass_name="async-hygiene",
    )
    w = Finding(
        "DC402", "src/m.py", 3, "unused local x",
        severity="warning", pass_name="dead-code",
    )
    doc = json.loads(findings_to_json([f, w], stale=[], passes=["async-hygiene", "dead-code"], timings={"dead-code": 0.51}))
    assert doc["version"] == 1 and doc["ok"] is False
    assert doc["passes"] == ["async-hygiene", "dead-code"]
    assert doc["findings"][0] == finding_to_dict(f)
    assert doc["findings"][0]["pass"] == "async-hygiene"
    assert doc["findings"][0]["fingerprint"] == f.fingerprint
    assert doc["timings_s"] == {"dead-code": 0.51}

    # warnings alone keep ok true; stale entries flip it
    assert json.loads(findings_to_json([w]))["ok"] is True
    assert json.loads(findings_to_json([], stale=["DC401:x:y"]))["ok"] is False

    ann = github_annotation(f)
    assert ann.startswith("::error ")
    assert "file=src/app.py" in ann and "line=12" in ann
    # the % in the message must be escaped per the Actions grammar
    assert "50%25 slower" in ann
    assert github_annotation(w).startswith("::warning ")


def test_cli_json_flags_and_annotations(tmp_path):
    write_tree(
        tmp_path,
        {
            "tools/analyze/placeholder.txt": "",
            "minbft_tpu/bad.py": "import os\n",
        },
    )
    out_file = tmp_path / "report.json"
    res = _run_cli(
        ["--root", str(tmp_path), "--json", "--json-out", str(out_file),
         "--github-annotations"],
        REPO,
    )
    assert res.returncode == 1
    doc = json.loads(res.stdout[: res.stdout.index("\n::")] if "\n::" in res.stdout else res.stdout)
    assert doc["ok"] is False
    assert any(f["code"] == "DC401" for f in doc["findings"])
    on_disk = json.loads(out_file.read_text())
    assert on_disk["findings"] == doc["findings"]
    assert any(
        line.startswith(("::error", "::warning"))
        for line in res.stdout.splitlines()
    )


# ---------------------------------------------------------------------------
# per-pass baselines


def test_baseline_set_partitions_and_detects_stale(tmp_path):
    from tools.analyze.core import BaselineSet

    bs = BaselineSet(tmp_path / "baselines")
    dc = Finding("DC401", "src/m.py", 1, "unused import os",
                 pass_name="dead-code")
    ah = Finding("AH101", "src/a.py", 2, "blocking call",
                 pass_name="async-hygiene")
    n = bs.write([dc, ah], ran=["dead-code", "async-hygiene"])
    assert n == 2
    assert (tmp_path / "baselines" / "dead-code.json").exists()
    assert (tmp_path / "baselines" / "async-hygiene.json").exists()

    bs = BaselineSet(tmp_path / "baselines")
    reported, suppressed, stale = bs.apply(
        [dc, ah], ran=["dead-code", "async-hygiene"]
    )
    assert reported == [] and len(suppressed) == 2 and stale == []

    # fix the AH finding -> only ITS per-pass file reports stale
    reported, suppressed, stale = bs.apply([dc], ran=["dead-code", "async-hygiene"])
    assert reported == [] and len(suppressed) == 1
    assert len(stale) == 1 and "AH101" in stale[0]

    # a pass that did not run must NOT stale its baseline
    reported, suppressed, stale = bs.apply([dc], ran=["dead-code"])
    assert stale == []


def test_baseline_set_orphan_files(tmp_path):
    from tools.analyze.core import BaselineSet

    d = tmp_path / "baselines"
    d.mkdir()
    (d / "dead-code.json").write_text('{"version": 1, "findings": {}}')
    (d / "retired-pass.json").write_text('{"version": 1, "findings": {}}')
    bs = BaselineSet(d)
    assert bs.orphan_files(["dead-code", "async-hygiene"]) == [
        "retired-pass.json"
    ]


def test_cli_stale_per_pass_baseline_fails(tmp_path):
    write_tree(
        tmp_path,
        {
            "tools/analyze/placeholder.txt": "",
            "minbft_tpu/ok.py": "",
        },
    )
    d = tmp_path / "bl"
    d.mkdir()
    (d / "dead-code.json").write_text(
        json.dumps(
            {
                "version": 1,
                "findings": {
                    "DC401:minbft_tpu/gone.py:unused import os": {
                        "count": 1,
                        "justification": "was grandfathered",
                    }
                },
            }
        )
    )
    res = _run_cli(
        ["--root", str(tmp_path), "--baseline-dir", str(d),
         "--select", "dead-code"],
        REPO,
    )
    assert res.returncode == 1 and "STALE" in res.stdout
    res = _run_cli(
        ["--root", str(tmp_path), "--baseline-dir", str(d),
         "--select", "dead-code", "--allow-stale"],
        REPO,
    )
    assert res.returncode == 0

    # a pass that is not selected must not stale its per-pass file
    res = _run_cli(
        ["--root", str(tmp_path), "--baseline-dir", str(d),
         "--select", "task-lifecycle"],
        REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr


# ---------------------------------------------------------------------------
# pass inventory, selftest liveness


ALL_PASS_NAMES = (
    "lock-discipline",
    "trace-purity",
    "exhaustiveness",
    "secret-hygiene",
    "dead-code",
    "async-hygiene",
    "task-lifecycle",
    "schema-drift",
    "env-registry",
)


def test_all_nine_passes_registered():
    passes = all_passes()
    prefixes = {cls.code_prefix for cls in passes.values()}
    assert {"LD", "TP", "EX", "SH", "DC", "AH", "TL", "SD", "ER"} <= prefixes
    for name in ALL_PASS_NAMES:
        assert name in passes


def test_cli_list_documents_scope_for_every_pass():
    out = _run_cli(["--list"], REPO)
    assert out.returncode == 0
    for name in ALL_PASS_NAMES:
        assert name in out.stdout
    # every registered pass prints a scope line
    assert out.stdout.count("scope:") == len(ALL_PASS_NAMES)


def test_cli_selftest_every_pass_flags_its_fixture():
    out = _run_cli(["--selftest"], REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    for name in ALL_PASS_NAMES:
        assert f"selftest: {name} OK" in out.stdout


def test_repo_baselines_are_empty():
    """The acceptance pin: the committed per-pass baselines carry ZERO
    grandfathered findings — real findings were fixed, not suppressed."""
    d = REPO / "tools" / "analyze" / "baselines"
    files = sorted(d.glob("*.json"))
    assert len(files) == len(ALL_PASS_NAMES)
    for p in files:
        assert json.loads(p.read_text())["findings"] == {}
