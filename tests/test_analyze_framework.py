"""Framework-level tests for tools.analyze: suppressions, baseline
round-trip (add finding -> baseline -> suppressed -> fix -> stale), CLI
exit codes, and pass registration."""

import json
import subprocess
import sys
from pathlib import Path

from tools.analyze import Baseline, Finding, Project, all_passes, run_passes
from tools.analyze.core import is_suppressed
from tools.analyze.project import (
    AnalyzeConfig,
    DeadCodeConfig,
    SecretHygieneConfig,
    TracePurityConfig,
)

REPO = Path(__file__).resolve().parent.parent


def make_config(**kw):
    """A minimal config: everything off unless a fixture opts in."""
    defaults = dict(
        source_roots=("src",),
        lock_classes=(),
        trace=TracePurityConfig(roots=()),
        exhaustiveness=None,
        secrets=SecretHygieneConfig(roots=()),
        dead=DeadCodeConfig(roots=()),
    )
    defaults.update(kw)
    return AnalyzeConfig(**defaults)


def write_tree(root: Path, files: dict) -> None:
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)


def test_all_four_project_passes_registered():
    passes = all_passes()
    prefixes = {cls.code_prefix for cls in passes.values()}
    assert {"LD", "TP", "EX", "SH", "DC"} <= prefixes


def test_noqa_suppresses_same_line_and_line_above(tmp_path):
    write_tree(
        tmp_path,
        {
            "src/m.py": (
                "import os  # noqa: DC401\n"
                "# noqa: DC401\n"
                "import sys\n"
                "import json\n"
            )
        },
    )
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    project = Project(tmp_path, config=cfg)
    findings = run_passes(project, select=["dead-code"])
    # os (inline noqa) and sys (standalone noqa above) suppressed; json not
    assert [f.message for f in findings] == ["unused import json"]


def test_bare_noqa_suppresses_all_codes(tmp_path):
    write_tree(tmp_path, {"src/m.py": "import os  # noqa\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    assert run_passes(Project(tmp_path, config=cfg), select=["dead-code"]) == []


def test_noqa_with_other_code_does_not_suppress(tmp_path):
    write_tree(tmp_path, {"src/m.py": "import os  # noqa: LD001\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))
    findings = run_passes(Project(tmp_path, config=cfg), select=["dead-code"])
    assert len(findings) == 1


def test_is_suppressed_out_of_range_line(tmp_path):
    write_tree(tmp_path, {"src/m.py": "x = 1\n"})
    project = Project(tmp_path, config=make_config())
    assert not is_suppressed(project, Finding("XX001", "src/m.py", 99, "m"))


def test_fingerprint_excludes_line_number():
    a = Finding("DC401", "src/m.py", 3, "unused import os")
    b = Finding("DC401", "src/m.py", 30, "unused import os")
    assert a.fingerprint == b.fingerprint


def test_baseline_round_trip(tmp_path):
    """The satellite-task contract: add finding -> write baseline ->
    suppressed -> fix the finding -> the baseline entry reports stale."""
    src = tmp_path / "src" / "m.py"
    write_tree(tmp_path, {"src/m.py": "import os\n"})
    cfg = make_config(dead=DeadCodeConfig(roots=("src",)))

    findings = run_passes(Project(tmp_path, config=cfg), select=["dead-code"])
    assert len(findings) == 1

    bl_path = tmp_path / "baseline.json"
    Baseline.from_findings(findings).save(bl_path)
    entries = json.loads(bl_path.read_text())["findings"]
    assert len(entries) == 1
    (entry,) = entries.values()
    assert entry["count"] == 1
    assert entry["justification"]  # never silently empty

    # baselined -> suppressed
    reported, suppressed, stale = Baseline.load(bl_path).apply(findings)
    assert reported == [] and len(suppressed) == 1 and stale == []

    # a SECOND instance of the same fingerprint exceeds the budget
    dup = findings + findings
    reported, suppressed, stale = Baseline.load(bl_path).apply(dup)
    assert len(reported) == 1 and len(suppressed) == 1

    # surplus budget is stale too: fixing one of N baselined instances
    # must be detected, or the leftover budget would silently absorb the
    # next regression of the same pattern
    surplus = Baseline(
        {findings[0].fingerprint: {"count": 3, "justification": "x"}}
    )
    reported, suppressed, stale = surplus.apply(findings)
    assert reported == [] and len(suppressed) == 1
    assert stale == [findings[0].fingerprint]

    # fix the finding -> entry is stale
    src.write_text("import os\nprint(os.sep)\n")
    project = Project(tmp_path, config=cfg)  # fresh AST cache
    findings = run_passes(project, select=["dead-code"])
    assert findings == []
    reported, suppressed, stale = Baseline.load(bl_path).apply(findings)
    assert reported == [] and suppressed == []
    assert len(stale) == 1 and "DC401" in stale[0]


def test_baseline_keeps_justification_on_regeneration(tmp_path):
    f = Finding("DC401", "src/m.py", 1, "unused import os")
    bl = Baseline.from_findings([f])
    bl.entries[f.fingerprint]["justification"] = "kept for the demo"
    bl2 = Baseline.from_findings([f, f], old=bl)
    assert bl2.entries[f.fingerprint]["justification"] == "kept for the demo"
    assert bl2.entries[f.fingerprint]["count"] == 2


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.analyze", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
    )


def test_cli_on_this_repo_is_clean_and_fails_on_seeded_violation(tmp_path):
    """Acceptance pin: `make lint`'s analyzer step exits 0 on the repo as
    committed, and non-zero once a violation of each pass is seeded."""
    clean = _run_cli([], REPO)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    # Seed one violation per pass in a scratch tree via --root + the test
    # config is not reachable from the CLI, so seed into a COPY of the
    # default layout: cheapest is a dead import in a new file under
    # tests/ … but that would dirty the repo.  Instead: a fixture root
    # exercising the dead-code pass end-to-end through the CLI.
    write_tree(
        tmp_path,
        {
            "tools/analyze/placeholder.txt": "",
            "minbft_tpu/bad.py": "import os\n",
        },
    )
    seeded = _run_cli(["--root", str(tmp_path)], REPO)
    assert seeded.returncode == 1
    assert "DC401" in seeded.stdout


def test_cli_write_baseline_refuses_partial_select(tmp_path):
    # A partial run writing the baseline would destroy the other passes'
    # grandfathered entries.
    res = _run_cli(
        ["--select", "dead-code", "--write-baseline", "--baseline",
         str(tmp_path / "bl.json")],
        REPO,
    )
    assert res.returncode == 2
    assert "full run" in res.stderr


def test_cli_list_passes():
    out = _run_cli(["--list-passes"], REPO)
    assert out.returncode == 0
    for name in (
        "lock-discipline",
        "trace-purity",
        "exhaustiveness",
        "secret-hygiene",
        "dead-code",
    ):
        assert name in out.stdout


def test_cli_stale_baseline_fails_and_allow_stale_passes(tmp_path):
    # The repo itself is clean, so a baseline naming a long-gone finding
    # is pure staleness: an error by default, tolerated with --allow-stale.
    bl = tmp_path / "baseline.json"
    bl.write_text(
        json.dumps(
            {
                "version": 1,
                "findings": {
                    "DC401:minbft_tpu/gone.py:unused import os": {
                        "count": 1,
                        "justification": "was grandfathered",
                    }
                },
            }
        )
    )
    res = _run_cli(["--baseline", str(bl)], REPO)
    assert res.returncode == 1 and "STALE" in res.stdout
    res = _run_cli(["--baseline", str(bl), "--allow-stale"], REPO)
    assert res.returncode == 0
