"""MAC-vector authentication (the reference's roadmap item,
README.md:500-505): tag formats, slot verification, forgery rejection,
and a full cluster commit under the MAC scheme."""

import asyncio

import pytest

from minbft_tpu import api
from minbft_tpu.sample.authentication.mac import (
    generate_testnet_mac_keys,
    new_test_mac_authenticators,
)


def test_request_vector_and_slots():
    async def run():
        n, n_clients = 4, 2
        r_auths, c_auths = new_test_mac_authenticators(n, n_clients)
        tag = c_auths[1].generate_message_authen_tag(
            api.AuthenticationRole.CLIENT, b"req"
        )
        assert len(tag) == n * 32
        # every replica accepts its slot
        for r in range(n):
            await r_auths[r].verify_message_authen_tag(
                api.AuthenticationRole.CLIENT, 1, b"req", tag
            )
        # corrupt replica 2's slot: only replica 2 rejects
        bad = tag[: 2 * 32] + bytes([tag[2 * 32] ^ 1]) + tag[2 * 32 + 1 :]
        await r_auths[1].verify_message_authen_tag(
            api.AuthenticationRole.CLIENT, 1, b"req", bad
        )
        with pytest.raises(api.AuthenticationError):
            await r_auths[2].verify_message_authen_tag(
                api.AuthenticationRole.CLIENT, 1, b"req", bad
            )

    asyncio.run(run())


def test_reply_mac_is_recipient_specific():
    async def run():
        n = 3
        r_auths, c_auths = new_test_mac_authenticators(n, 2)
        tag = r_auths[2].generate_message_authen_tag(
            api.AuthenticationRole.REPLICA, b"reply", audience=0
        )
        assert len(tag) == 32
        await c_auths[0].verify_message_authen_tag(
            api.AuthenticationRole.REPLICA, 2, b"reply", tag
        )
        # the other client's key rejects it
        with pytest.raises(api.AuthenticationError):
            await c_auths[1].verify_message_authen_tag(
                api.AuthenticationRole.REPLICA, 2, b"reply", tag
            )

    asyncio.run(run())


def test_replica_vector_for_view_change():
    async def run():
        n = 4
        r_auths, _ = new_test_mac_authenticators(n, 1)
        tag = r_auths[1].generate_message_authen_tag(
            api.AuthenticationRole.REPLICA, b"rvc"
        )
        assert len(tag) == n * 32
        for r in (0, 2, 3):
            await r_auths[r].verify_message_authen_tag(
                api.AuthenticationRole.REPLICA, 1, b"rvc", tag
            )

    asyncio.run(run())


def test_key_views_are_minimal():
    keys = generate_testnet_mac_keys(3, 2)
    view = keys.view_for_replica(1)
    assert all(k[1] == 1 for k in view.client_replica)
    assert all(1 in k for k in view.replica_pair)
    cview = keys.view_for_client(0)
    assert all(k[0] == 0 for k in cview.client_replica)
    assert not cview.replica_pair


async def _mac_cluster(n=4, f=1):
    """In-process cluster under pairwise-MAC authentication (the MAC-scheme
    twin of conftest.make_cluster).  Returns (replicas, client, stubs,
    ledgers); caller stops the client and replicas."""
    from minbft_tpu.client import new_client
    from minbft_tpu.core import new_replica
    from minbft_tpu.sample.config import SimpleConfiger
    from minbft_tpu.sample.conn.inprocess import (
        InProcessClientConnector,
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from minbft_tpu.sample.requestconsumer import SimpleLedger

    cfg = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
    r_auths, c_auths = new_test_mac_authenticators(n, 1, usig_kind="hmac")
    stubs = make_testnet_stubs(n)
    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        r = new_replica(
            i, cfg, r_auths[i], InProcessPeerConnector(stubs), ledgers[i]
        )
        stubs[i].assign_replica(r)
        replicas.append(r)
    for r in replicas:
        await r.start()
    client = new_client(0, n, f, c_auths[0], InProcessClientConnector(stubs))
    await client.start()
    return replicas, client, stubs, ledgers


def test_cluster_commit_under_mac_scheme():
    """Full n=4 commit where REQUEST/REPLY authentication is MACs and the
    USIG path is unchanged."""

    async def run():
        replicas, client, stubs, ledgers = await _mac_cluster()
        assert await asyncio.wait_for(client.request(b"mac-op"), 60)
        for _ in range(200):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        assert all(lg.length == 1 for lg in ledgers)
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_mac_verification_through_engine_queues():
    """The engine-backed MAC paths: the host queue (default placement) and
    the device HMAC kernel (device_macs=True) both accept valid slots and
    reject corrupted ones."""

    async def run():
        from minbft_tpu.parallel import BatchVerifier

        for device_macs in (False, True):
            engine = BatchVerifier(max_batch=8, buckets=(8,))
            r_auths, c_auths = new_test_mac_authenticators(
                4, 1, engines=[engine] * 4, device_macs=device_macs,
                client_engine=engine,
            )
            tag = c_auths[0].generate_message_authen_tag(
                api.AuthenticationRole.CLIENT, b"via-engine"
            )
            await r_auths[1].verify_message_authen_tag(
                api.AuthenticationRole.CLIENT, 0, b"via-engine", tag
            )
            bad = bytes([tag[32] ^ 1]) + tag[1:]
            with pytest.raises(api.AuthenticationError):
                await r_auths[0].verify_message_authen_tag(
                    api.AuthenticationRole.CLIENT, 0, b"via-engine", bad
                )
            queue = "hmac_sha256" if device_macs else "hmac_sha256_host"
            assert engine.stats[queue].items >= 2

    asyncio.run(run())


def test_unknown_principal_raises_auth_error():
    async def run():
        r_auths, c_auths = new_test_mac_authenticators(3, 1)
        tag = c_auths[0].generate_message_authen_tag(
            api.AuthenticationRole.CLIENT, b"m"
        )
        with pytest.raises(api.AuthenticationError):
            await r_auths[0].verify_message_authen_tag(
                api.AuthenticationRole.CLIENT, 9999, b"m", tag
            )

    asyncio.run(run())


def test_fast_read_under_mac_scheme():
    """Read-only fast path under pairwise-MAC authentication: reply MACs
    are recipient-keyed, and the all-n quorum counts them like signatures."""

    async def run():
        import struct

        replicas, client, stubs, ledgers = await _mac_cluster()
        assert await asyncio.wait_for(client.request(b"mac-write"), 60)
        for _ in range(200):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        assert all(lg.length == 1 for lg in ledgers)
        # read_fallback=False: a silent ordered fallback would pass every
        # assertion without exercising the fast MAC reply path
        head = await asyncio.wait_for(
            client.request(b"head", read_only=True, read_fallback=False,
                           read_timeout=30.0),
            60,
        )
        assert struct.unpack(">Q", head[:8])[0] == 1
        assert all(lg.length == 1 for lg in ledgers)  # read mutated nothing
        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())
