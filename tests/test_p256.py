"""Differential tests: batched ECDSA-P256 TPU kernel vs the host big-int
reference verifier (mirrors the reference's crypto tests,
reference sample/authentication/crypto_test.go:100 — sign/verify round trip
plus forged-input rejection)."""

import hashlib

import jax
import jax.numpy as jnp
import pytest

from minbft_tpu.ops import p256
from minbft_tpu.ops.limbs import from_limbs
from minbft_tpu.utils import hostcrypto as hc


@pytest.fixture(scope="module")
def keys():
    return [hc.keygen() for _ in range(3)]


def test_point_ops_match_host():
    f = p256.FIELD
    one = f.r_mod
    gx, gy = p256._GX_M, p256._GY_M

    def to_affine_host(pt):
        from minbft_tpu.ops.limbs import from_mont

        xi, yi, zi = (from_limbs(from_mont(f, v)) for v in pt)
        if zi == 0:
            return None
        z_inv = pow(zi, -1, hc.P)
        return (xi * z_inv**2 % hc.P, yi * z_inv**3 % hc.P)

    d2 = jax.jit(p256._dbl)(p256.Point(gx, gy, one))
    assert to_affine_host(d2) == hc.point_double((hc.GX, hc.GY))

    madd = jax.jit(lambda p, qx, qy: p256._madd(p, qx, qy, jnp.bool_(False)))
    p3, exc = madd(d2, gx, gy)
    assert to_affine_host(p3) == hc.scalar_mult(3, (hc.GX, hc.GY))
    assert not bool(exc)
    # the incomplete case P == Q is flagged, and the table-building variant
    # resolves it through the doubling formula
    _, exc = madd(p256.Point(gx, gy, one), gx, gy)
    assert bool(exc)
    tbl = jax.jit(
        lambda p, qx, qy: p256._madd_complete_table(p, qx, qy, jnp.bool_(False))
    )(p256.Point(gx, gy, one), gx, gy)
    assert to_affine_host(tbl) == hc.point_double((hc.GX, hc.GY))


def test_verify_batch_valid_and_forged(keys):
    items, expected = [], []
    for i, (d, q) in enumerate(keys):
        digest = hashlib.sha256(f"msg{i}".encode()).digest()
        sig = hc.ecdsa_sign(d, digest)
        assert hc.ecdsa_verify(q, digest, sig)
        items.append((q, digest, sig))
        expected.append(True)

    d0, q0 = keys[0]
    digest = hashlib.sha256(b"orig").digest()
    sig = hc.ecdsa_sign(d0, digest)
    # tampered digest
    items.append((q0, hashlib.sha256(b"tampered").digest(), sig))
    expected.append(False)
    # wrong key
    items.append((keys[1][1], digest, sig))
    expected.append(False)
    # out-of-range signature components
    items.append((q0, digest, (0, sig[1])))
    expected.append(False)
    items.append((q0, digest, (sig[0], hc.N)))
    expected.append(False)
    # bit-flipped s
    items.append((q0, digest, (sig[0], sig[1] ^ 1)))
    expected.append(False)

    got = p256.verify_batch(items)
    assert list(got) == expected


def test_is_on_curve(keys):
    _, q = keys[0]
    assert p256.is_on_curve(*q)
    assert not p256.is_on_curve(q[0], (q[1] + 1) % hc.P)
    assert not p256.is_on_curve(hc.P, 0)
