"""Crash-recovery subsystem tests (ISSUE 20): the durable checkpoint
store's atomic-write/paranoid-load contract, deterministic chunking and
digest chaining for resumable state transfer, the handlers-level
resume/failover/install paths, startup restore through the real f+1
certificate check, process-level corrupted-store rejection (rc != 0,
never a silent fresh start), and the pinned-seed kill-9 soak (slow).
"""

import asyncio
import hashlib
import os
import subprocess
import sys

import pytest

from minbft_tpu import api
from minbft_tpu.core.checkpoint import checkpoint_digest
from minbft_tpu.core.internal.clientstate import ClientStates
from minbft_tpu.core.internal.messagelog import MessageLog
from minbft_tpu.core.message_handling import Handlers
from minbft_tpu.messages import (
    UI,
    Checkpoint,
    Request,
    StateChunk,
    StateDone,
    StateReq,
)
from minbft_tpu.recovery import (
    CorruptStoreError,
    DurableStore,
    RecoveryManager,
    StableState,
    store_path,
)
from minbft_tpu.recovery import manager as recovery_manager
from minbft_tpu.recovery import store as recovery_store
from minbft_tpu.recovery import transfer
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.usig import ui_to_bytes

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# Fixtures / helpers


def _cp(replica, count=8, view=0, cv=8, digest=b"d" * 32):
    return Checkpoint(
        replica_id=replica, count=count, view=view, cv=cv, digest=digest,
        signature=b"sig",
    )


def _state(count=8, view=0, cv=8, app=b"app-bytes", marks=((1, 2),),
           usig=5, digest=None):
    digest = digest if digest is not None else b"d" * 32
    cert = (_cp(1, count, view, cv, digest), _cp(2, count, view, cv, digest))
    return StableState(
        count=count, view=view, cv=cv, usig_counter=usig, app_state=app,
        watermarks=tuple(marks), cert=cert,
    )


class _Auth(api.Authenticator):
    def __init__(self):
        self.counter = 0

    def generate_message_authen_tag(self, role, data, audience=-1):
        if role is api.AuthenticationRole.USIG:
            self.counter += 1
            return ui_to_bytes(UI(counter=self.counter, cert=b"cert"))
        return b"sig"

    async def verify_message_authen_tag(self, role, peer_id, data, tag):
        return None


class _SnapConsumer(api.RequestConsumer):
    """A consumer with real snapshot support: digest = sha256(bytes)."""

    def __init__(self):
        self.installed = None

    async def deliver(self, operation: bytes) -> bytes:
        return b"ok:" + operation

    def state_digest(self) -> bytes:
        return b""

    def snapshot_digest(self, data: bytes) -> bytes:
        return hashlib.sha256(data).digest()

    def install_snapshot(self, data: bytes) -> None:
        self.installed = data


def _handlers(replica_id=0, n=4, f=1, consumer=None, recovery=None):
    unicast = {p: MessageLog() for p in range(n) if p != replica_id}
    return Handlers(
        replica_id,
        n,
        f,
        SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=60.0),
        _Auth(),
        consumer if consumer is not None else _SnapConsumer(),
        MessageLog(),
        unicast,
        ClientStates(),
        recovery=recovery,
    )


def _composite(app, count, view, cv, marks):
    return checkpoint_digest(
        hashlib.sha256(app).digest(), count, view, cv, marks
    )


def _chunks_for(app, count, size):
    """Honest responder's chunk stream for ``app`` (chain from byte 0)."""
    out = []
    chain = b""
    for off, piece in transfer.iter_chunks(app, size):
        chain = transfer.chain_extend(chain, piece)
        out.append(
            StateChunk(
                replica_id=1, count=count, offset=off, total=len(app),
                data=piece, chain=chain,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Durable store: atomic save, paranoid load


def test_store_round_trip(tmp_path):
    path = str(tmp_path / "replica0.state")
    st = _state(count=8, view=1, cv=8, usig=17, marks=((1, 2), (9, 44)))
    store = DurableStore(path, 0)
    assert store.save(st) is True
    got = DurableStore(path, 0).load()
    assert got == st
    assert not os.path.exists(path + ".tmp")


def test_store_missing_file_is_fresh_start(tmp_path):
    assert DurableStore(str(tmp_path / "none.state"), 0).load() is None


def test_store_save_never_regresses(tmp_path):
    path = str(tmp_path / "replica0.state")
    store = DurableStore(path, 0)
    assert store.save(_state(count=10)) is True
    # equal and lower counts are refused without touching the file
    assert store.save(_state(count=10)) is False
    assert store.save(_state(count=4)) is False
    assert DurableStore(path, 0).load().count == 10
    assert store.save(_state(count=11)) is True
    assert DurableStore(path, 0).load().count == 11


def test_store_learns_incumbent_bound_across_restart(tmp_path):
    """A NEW DurableStore over an existing file (the restart case) must
    not clobber a newer persisted bound with a lagging save."""
    path = str(tmp_path / "replica0.state")
    DurableStore(path, 0).save(_state(count=16))
    fresh = DurableStore(path, 0)
    assert fresh.save(_state(count=8)) is False
    assert DurableStore(path, 0).load().count == 16
    assert fresh.save(_state(count=24)) is True


def test_store_torn_tmp_is_discarded_not_trusted(tmp_path):
    path = str(tmp_path / "replica0.state")
    store = DurableStore(path, 0)
    store.save(_state(count=8))
    # crash mid-save leaves a torn temp file next to the committed one
    with open(path + ".tmp", "wb") as fh:
        fh.write(b"half-written garbage")
    got = DurableStore(path, 0).load()
    assert got is not None and got.count == 8
    assert not os.path.exists(path + ".tmp"), "torn temp not discarded"


def test_store_tmp_only_means_fresh_start(tmp_path):
    # crashed during the very first save: no committed file exists yet
    path = str(tmp_path / "replica0.state")
    with open(path + ".tmp", "wb") as fh:
        fh.write(b"half-written garbage")
    assert DurableStore(path, 0).load() is None
    assert not os.path.exists(path + ".tmp")


def test_store_corrupted_committed_file_is_fatal(tmp_path):
    path = str(tmp_path / "replica0.state")
    DurableStore(path, 0).save(_state(count=8))
    raw = open(path, "rb").read()
    # flip one payload byte: the integrity digest must trip
    bad = bytearray(raw)
    bad[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(bad))
    with pytest.raises(CorruptStoreError):
        DurableStore(path, 0).load()
    # truncation (too short to even hold the trailer)
    open(path, "wb").write(raw[:10])
    with pytest.raises(CorruptStoreError):
        DurableStore(path, 0).load()


def test_store_wrong_owner_and_bad_magic_are_fatal(tmp_path):
    path = str(tmp_path / "replica0.state")
    DurableStore(path, 0).save(_state(count=8))
    with pytest.raises(CorruptStoreError, match="belongs to replica 0"):
        DurableStore(path, 3).load()
    # re-seal a payload with wrong magic but a VALID digest trailer: only
    # the magic check can reject it
    raw = open(path, "rb").read()
    payload = bytearray(raw[:-32])
    payload[:4] = b"XXXX"
    open(path, "wb").write(
        bytes(payload) + hashlib.sha256(bytes(payload)).digest()
    )
    with pytest.raises(CorruptStoreError, match="magic"):
        DurableStore(path, 0).load()


def test_store_trailing_garbage_and_non_checkpoint_cert_fatal(tmp_path):
    path = str(tmp_path / "replica0.state")
    st = _state(count=8)
    # trailing garbage re-sealed with a valid digest
    payload = recovery_store._encode(0, st)[:-32] + b"extra"
    open(path, "wb").write(payload + hashlib.sha256(payload).digest())
    with pytest.raises(CorruptStoreError, match="trailing garbage"):
        DurableStore(path, 0).load()
    # a certificate entry that decodes but is not a CHECKPOINT
    req = Request(client_id=1, seq=1, operation=b"x")
    req.signature = b"sig"
    bad = StableState(
        count=8, view=0, cv=8, usig_counter=1, app_state=b"", watermarks=(),
        cert=(req,),  # type: ignore[arg-type]
    )
    open(path, "wb").write(recovery_store._encode(0, bad))
    with pytest.raises(CorruptStoreError, match="not a CHECKPOINT"):
        DurableStore(path, 0).load()


# ---------------------------------------------------------------------------
# Chunking + chain (transfer module)


def test_iter_chunks_and_assembler_round_trip():
    app = os.urandom(1000)
    asm = transfer.ChunkAssembler(count=8)
    chain = b""
    for off, piece in transfer.iter_chunks(app, 64):
        chain = transfer.chain_extend(chain, piece)
        assert asm.add(off, len(app), piece, chain) is True
    assert asm.complete and asm.bytes() == app
    assert list(transfer.iter_chunks(b"", 64)) == []


def test_assembler_stale_replay_and_gap_are_ignored():
    app = b"A" * 64 + b"B" * 64
    asm = transfer.ChunkAssembler(count=8)
    chunks = _chunks_for(app, 8, 64)
    assert asm.add(0, len(app), chunks[0].data, chunks[0].chain) is True
    # reconnect replay of the verified prefix: idempotent no-op
    assert asm.add(0, len(app), chunks[0].data, chunks[0].chain) is False
    assert asm.offset == 64
    # a gap above the verified prefix: wait for the in-order copy
    assert asm.add(128, len(app), b"C" * 64, b"x" * 32) is False
    assert asm.add(64, len(app), chunks[1].data, chunks[1].chain) is True
    assert asm.complete


def test_assembler_chain_mismatch_total_shift_and_overrun():
    app = b"A" * 64 + b"B" * 64
    chunks = _chunks_for(app, 8, 64)
    asm = transfer.ChunkAssembler(count=8)
    asm.add(0, len(app), chunks[0].data, chunks[0].chain)
    with pytest.raises(transfer.ChainMismatch, match="chain digest"):
        asm.add(64, len(app), b"EVIL" + chunks[1].data[4:], chunks[1].chain)
    with pytest.raises(transfer.ChainMismatch, match="length changed"):
        asm.add(64, len(app) + 1, chunks[1].data, chunks[1].chain)
    # overrun: a chunk whose bytes extend past the pinned total
    asm2 = transfer.ChunkAssembler(count=8)
    big = app + b"C" * 8
    chain = transfer.chain_extend(b"", big)
    with pytest.raises(transfer.ChainMismatch, match="overruns"):
        asm2.add(0, len(app), big, chain)


def test_chunk_bytes_env_knob_is_clamped(monkeypatch):
    monkeypatch.delenv(transfer.CHUNK_BYTES_ENV, raising=False)
    assert transfer.chunk_bytes() == transfer.DEFAULT_CHUNK_BYTES
    monkeypatch.setenv(transfer.CHUNK_BYTES_ENV, "4096")
    assert transfer.chunk_bytes() == 4096
    monkeypatch.setenv(transfer.CHUNK_BYTES_ENV, "0")
    assert transfer.chunk_bytes() == 1
    monkeypatch.setenv(transfer.CHUNK_BYTES_ENV, str(10**9))
    assert transfer.chunk_bytes() == transfer.MAX_CHUNK_BYTES
    monkeypatch.setenv(transfer.CHUNK_BYTES_ENV, "junk")
    assert transfer.chunk_bytes() == transfer.DEFAULT_CHUNK_BYTES


# ---------------------------------------------------------------------------
# Handlers: serving, assembling, resume, failover, install


def test_state_req_serves_chunk_aligned_resume(monkeypatch):
    """The responder recomputes the chain from byte 0 but transmits only
    the missing tail; a fresh STATE-REQ prunes the superseded stream from
    the requester's unicast log first."""
    monkeypatch.setenv(transfer.CHUNK_BYTES_ENV, "4")

    async def scenario():
        h = _handlers(replica_id=0)
        app = b"0123456789AB"  # 3 chunks of 4
        h.checkpoint_emitter._snapshots[8] = (0, 8, app, ((1, 2),))

        assert await h._process_state_req(
            StateReq(replica_id=1, count=8, offset=0)
        ) is True
        msgs = h.unicast_logs[1].snapshot()
        chunks, done = msgs[:-1], msgs[-1]
        assert [c.offset for c in chunks] == [0, 4, 8]
        assert isinstance(done, StateDone) and done.total == len(app)
        assert b"".join(c.data for c in chunks) == app
        # every chunk's chain extends the previous one from byte zero
        chain = b""
        for c in chunks:
            chain = transfer.chain_extend(chain, c.data)
            assert c.chain == chain

        # resume from offset 8: the superseded stream is pruned, only the
        # missing tail (plus DONE) is served, and its chain still commits
        # to the whole prefix
        assert await h._process_state_req(
            StateReq(replica_id=1, count=8, offset=8)
        ) is True
        msgs = h.unicast_logs[1].snapshot()
        assert [type(m).__name__ for m in msgs] == ["StateChunk", "StateDone"]
        assert msgs[0].offset == 8 and msgs[0].chain == chain
        assert h.metrics.counters["state_chunks_sent"] == 4
        return True

    assert asyncio.run(scenario())


def test_corrupt_chunk_fails_over_to_next_source():
    """A chain mismatch is Byzantine evidence: the stream is abandoned,
    the corrupt counter ticks, and a fresh STATE-REQ (offset 0) goes to
    the NEXT source in the rotation."""

    async def scenario():
        h = _handlers(replica_id=0)
        app = b"A" * 64 + b"B" * 64
        digest = _composite(app, 8, 0, 8, ())
        cert = (_cp(1, digest=digest), _cp(2, digest=digest))
        await h._request_state(cert, first_source=1)
        try:
            assert h._state_source == 1
            chunks = _chunks_for(app, 8, 64)
            assert await h._process_state_chunk(chunks[0]) is True
            evil = StateChunk(
                replica_id=1, count=8, offset=64, total=len(app),
                data=b"EVIL" + chunks[1].data[4:], chain=chunks[1].chain,
            )
            assert await h._process_state_chunk(evil) is False
            assert h.metrics.counters["state_transfer_corrupt"] == 1
            assert h.metrics.counters["state_transfer_failovers"] == 1
            assert h._state_asm is None
            assert h._state_source == 2, "did not rotate off the liar"
            req = h.unicast_logs[2].snapshot()[-1]
            assert isinstance(req, StateReq) and req.offset == 0
        finally:
            if h._snapshot_timer is not None:
                h._snapshot_timer.cancel()
        return True

    assert asyncio.run(scenario())


def test_resume_keeps_source_and_verified_offset():
    """The mid-transfer-reset path: resume re-asks the SAME source from
    the assembler's verified offset — nothing verified is re-downloaded."""

    async def scenario():
        h = _handlers(replica_id=0)
        app = b"A" * 64 + b"B" * 64
        digest = _composite(app, 8, 0, 8, ())
        cert = (_cp(1, digest=digest), _cp(2, digest=digest))
        await h._request_state(cert, first_source=1)
        try:
            chunks = _chunks_for(app, 8, 64)
            assert await h._process_state_chunk(chunks[0]) is True
            h._send_state_req(resume=True)
            assert h._state_source == 1, "resume must not rotate"
            req = h.unicast_logs[1].snapshot()[-1]
            assert isinstance(req, StateReq)
            assert req.offset == 64 and req.count == 8
            assert h.metrics.counters["state_transfer_resumes"] == 1
            assert "state_transfer_failovers" not in h.metrics.counters
            # a replayed chunk of the verified prefix stays idempotent
            assert await h._process_state_chunk(chunks[0]) is False
            assert h.metrics.counters["state_chunks_received"] == 1
        finally:
            if h._snapshot_timer is not None:
                h._snapshot_timer.cancel()
        return True

    assert asyncio.run(scenario())


def test_done_with_incomplete_assembly_waits_for_retry():
    """A DONE replayed ahead of its chunks (reconnect reorder) must not
    fail the transfer over — the retry timer resumes from the verified
    offset."""

    async def scenario():
        h = _handlers(replica_id=0)
        app = b"A" * 64 + b"B" * 64
        digest = _composite(app, 8, 0, 8, ())
        cert = (_cp(1, digest=digest), _cp(2, digest=digest))
        await h._request_state(cert, first_source=1)
        try:
            chunks = _chunks_for(app, 8, 64)
            await h._process_state_chunk(chunks[0])
            done = StateDone(
                replica_id=1, count=8, view=0, cv=8, total=len(app),
                watermarks=(),
            )
            assert await h._process_state_done(done) is False
            assert "state_transfer_corrupt" not in h.metrics.counters
            assert h._state_asm is not None and h._state_asm.offset == 64
            assert h._state_source == 1
        finally:
            if h._snapshot_timer is not None:
                h._snapshot_timer.cancel()
        return True

    assert asyncio.run(scenario())


def test_chunked_transfer_installs_certified_state():
    """End-to-end happy path: chunks assemble, DONE resolves the target,
    the composite digest verifies against the f+1 certificate, and the
    snapshot installs (state, watermarks, execution position)."""

    async def scenario():
        consumer = _SnapConsumer()
        h = _handlers(replica_id=0, consumer=consumer)
        app = b"A" * 64 + b"B" * 32
        marks = ((1, 2), (5, 7))
        digest = _composite(app, 8, 0, 8, marks)
        cert = (_cp(1, digest=digest), _cp(2, digest=digest))
        await h._request_state(cert, first_source=1)
        for ck in _chunks_for(app, 8, 64):
            await h._process_state_chunk(ck)
        done = StateDone(
            replica_id=1, count=8, view=0, cv=8, total=len(app),
            watermarks=marks,
        )
        assert await h._process_state_done(done) is True
        assert consumer.installed == app
        assert h._snapshot_expect is None and h._snapshot_timer is None
        assert h.checkpoint_emitter.count == 8
        assert h._exec_pos == (0, 8)
        assert h.metrics.counters["state_transfers"] == 1
        return True

    assert asyncio.run(scenario())


def test_self_consistent_garbage_fails_certificate_and_fails_over():
    """A stream whose chain verifies but whose content does not match the
    f+1-certified composite digest is Byzantine garbage: refused, counted
    corrupt, failed over."""

    async def scenario():
        h = _handlers(replica_id=0)
        app = b"A" * 64
        cert = (_cp(1, digest=b"X" * 32), _cp(2, digest=b"X" * 32))
        await h._request_state(cert, first_source=1)
        try:
            for ck in _chunks_for(app, 8, 64):
                await h._process_state_chunk(ck)
            done = StateDone(
                replica_id=1, count=8, view=0, cv=8, total=len(app),
                watermarks=(),
            )
            assert await h._process_state_done(done) is False
            assert h.metrics.counters["state_transfer_corrupt"] == 1
            assert h._state_source == 2, "no failover after certified refusal"
        finally:
            if h._snapshot_timer is not None:
                h._snapshot_timer.cancel()
        return True

    assert asyncio.run(scenario())


# ---------------------------------------------------------------------------
# Handlers: durable save + startup restore


def test_stable_checkpoint_persists_verified_state(tmp_path):
    """_spawn_durable_save re-verifies the snapshot against the stable
    composite digest before persisting — the store only ever holds state
    the certificate vouches for."""

    async def scenario():
        path = str(tmp_path / "replica0.state")
        rec = RecoveryManager(store=DurableStore(path, 0))
        h = _handlers(replica_id=0, recovery=rec)
        app, marks = b"ledger-bytes", ((1, 2),)
        digest = _composite(app, 8, 0, 8, marks)
        coll = h.checkpoint_collector
        coll.stable_count, coll.stable_view, coll.stable_cv = 8, 0, 8
        coll.stable_digest = digest
        coll._stable_cert = {
            1: _cp(1, digest=digest), 2: _cp(2, digest=digest),
        }
        h.checkpoint_emitter._snapshots[8] = (0, 8, app, marks)
        h._spawn_durable_save()
        for _ in range(100):
            if rec.saves:
                break
            await asyncio.sleep(0.01)
        assert rec.saves == 1
        assert h.metrics.counters["recovery_saves"] == 1
        got = DurableStore(path, 0).load()
        assert (got.count, got.view, got.cv) == (8, 0, 8)
        assert got.app_state == app and got.watermarks == marks
        assert len(got.cert) == h.f + 1

        # divergence guard: a snapshot that no longer matches the stable
        # digest is NEVER persisted
        coll.stable_digest = b"Z" * 32
        coll.stable_count = 16
        h.checkpoint_emitter._snapshots[16] = (0, 16, app, marks)
        h._spawn_durable_save()
        await asyncio.sleep(0.05)
        assert rec.saves == 1 and DurableStore(path, 0).load().count == 8
        return True

    assert asyncio.run(scenario())


def test_restore_from_store_round_trip(tmp_path):
    """Startup restore re-validates the f+1 certificate and recomputes
    the composite digest, then installs and arms the recovery clock."""

    async def scenario():
        path = str(tmp_path / "replica0.state")
        app, marks = b"ledger-bytes", ((7, 3),)
        digest = _composite(app, 8, 1, 8, marks)
        cert = (
            _cp(1, count=8, view=1, cv=8, digest=digest),
            _cp(2, count=8, view=1, cv=8, digest=digest),
        )
        DurableStore(path, 0).save(
            StableState(
                count=8, view=1, cv=8, usig_counter=5, app_state=app,
                watermarks=marks, cert=cert,
            )
        )
        consumer = _SnapConsumer()
        rec = RecoveryManager(store=DurableStore(path, 0))
        h = _handlers(replica_id=0, consumer=consumer, recovery=rec)
        await h.restore_from_store()
        assert consumer.installed == app
        assert rec.restored_count == 8
        assert rec.phase == recovery_manager.PHASE_CATCHUP
        assert rec.armed, "recovery clock not armed after restore"
        assert h._exec_pos == (1, 8)
        assert h.checkpoint_emitter.count == 8
        assert h.metrics.counters["recovery_restores"] == 1
        # first executed request stops the clock and completes the phases
        rec.note_executed()
        assert rec.recovery_time_ms is not None
        assert rec.phase == recovery_manager.PHASE_DONE
        return True

    assert asyncio.run(scenario())


def test_restore_empty_store_is_clean_fresh_start(tmp_path):
    async def scenario():
        rec = RecoveryManager(
            store=DurableStore(str(tmp_path / "none.state"), 0)
        )
        h = _handlers(replica_id=0, recovery=rec)
        await h.restore_from_store()
        assert rec.phase == recovery_manager.PHASE_IDLE
        assert not rec.armed and rec.restored_count is None
        return True

    assert asyncio.run(scenario())


def test_restore_rejects_digest_mismatch_as_corrupt(tmp_path):
    """A store whose snapshot fails the certified composite digest is
    CorruptStoreError — the file is a cache of certified state, never an
    authority."""

    async def scenario():
        path = str(tmp_path / "replica0.state")
        # structurally valid cert, but its digest does not match the
        # snapshot content
        DurableStore(path, 0).save(_state(count=8, digest=b"Z" * 32))
        rec = RecoveryManager(store=DurableStore(path, 0))
        h = _handlers(replica_id=0, recovery=rec)
        with pytest.raises(CorruptStoreError, match="certificate"):
            await h.restore_from_store()
        return True

    assert asyncio.run(scenario())


def test_restore_rejects_undersized_certificate(tmp_path):
    async def scenario():
        path = str(tmp_path / "replica0.state")
        app, marks = b"x", ()
        digest = _composite(app, 8, 0, 8, marks)
        DurableStore(path, 0).save(
            StableState(
                count=8, view=0, cv=8, usig_counter=1, app_state=app,
                watermarks=marks, cert=(_cp(1, digest=digest),),  # f claims
            )
        )
        rec = RecoveryManager(store=DurableStore(path, 0))
        h = _handlers(replica_id=0, recovery=rec)
        with pytest.raises(CorruptStoreError, match="certificate invalid"):
            await h.restore_from_store()
        return True

    assert asyncio.run(scenario())


# ---------------------------------------------------------------------------
# ProcessChaos + plan specs (satellite a)


def test_plan_from_spec_profiles_pairs_and_errors():
    from minbft_tpu.testing import PROFILES, plan_from_spec

    assert plan_from_spec("") is PROFILES["lossy"]
    assert plan_from_spec("slow") is PROFILES["slow"]
    p = plan_from_spec("drop=0.02, reset=0.01")
    assert (p.drop, p.reset, p.delay) == (0.02, 0.01, 0.0)
    with pytest.raises(ValueError, match="unknown chaos plan"):
        plan_from_spec("lossyy")
    with pytest.raises(ValueError, match="unknown chaos fault kind"):
        plan_from_spec("explode=0.5")
    with pytest.raises(ValueError, match="bad probability"):
        plan_from_spec("drop=often")


def test_process_chaos_kill_restart_census():
    from minbft_tpu.testing import ProcessChaos

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    chaos = ProcessChaos()
    try:
        chaos.manage("r0", spawn)
        assert chaos.alive("r0")
        pid = chaos.proc("r0").pid
        chaos.kill("r0")
        assert not chaos.alive("r0")
        chaos.restart("r0")
        assert chaos.alive("r0") and chaos.proc("r0").pid != pid
        chaos.kill_restart("r0")
        assert chaos.alive("r0")
        counters = chaos.census.snapshot()["counters"]
        assert counters == {"crash": 2, "restart": 2}
    finally:
        chaos.terminate_all()
    assert not chaos.alive("r0")


# ---------------------------------------------------------------------------
# Real processes: corrupted-store startup rejection + the pinned soak


def _scaffold(d, n, base_port, env):
    scaffold = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer", "testnet",
         "-n", str(n), "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA"],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert scaffold.returncode == 0, scaffold.stderr


def test_peer_run_refuses_corrupted_store(tmp_path):
    """Liveness of the refusal itself: a replica started over a corrupted
    committed store must exit non-zero with a clear message — promptly,
    with no peers running — never serve, never silently start fresh."""
    from minbft_tpu.utils.netports import free_base_port

    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    d = str(tmp_path)
    _scaffold(d, 3, free_base_port(3), env)
    state_dir = os.path.join(d, "state")
    os.makedirs(state_dir)
    with open(store_path(state_dir, 0), "wb") as fh:
        fh.write(b"this is not a valid durable store file" * 4)

    run = subprocess.run(
        [sys.executable, "-m", "minbft_tpu.sample.peer",
         "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
         "run", "0", "--no-batch", "--state-dir", state_dir],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert run.returncode == 4, (run.returncode, run.stderr[-2000:])
    assert "corrupt" in run.stderr, run.stderr[-2000:]
    assert "state-dir" in run.stderr, run.stderr[-2000:]


@pytest.mark.slow
def test_pinned_seed_recovery_soak(tmp_path):
    """The ISSUE 20 acceptance soak: kill -9 a real ``peer run`` replica
    mid-load under a pinned chaos seed, restart it, and require zero
    committed loss, a durable restore (finite recovery_time_ms), green
    store invariants, and live census == seed-replayed census."""
    from minbft_tpu.testing.recovery_soak import run_recovery_soak

    report = run_recovery_soak(
        str(tmp_path),
        replicas=4,
        # Load must OUTLIVE the outage: the recovery clock stops at the
        # restarted replica's first executed request, and a bench that
        # drains during the ~5s python+jax reboot leaves it running
        # forever.  198 requests is ~35s at the host's ~5.5 req/s.
        requests=198,
        clients=6,
        depth=4,
        checkpoint_period=4,
        chunk_bytes=2048,
        chaos_seed=0x2020C0FFEE,
        down_s=0.5,
    )
    assert report["committed"] == report["requested"] == 198
    assert report["chaos_recovery_time_ms"] > 0
    assert report["restored_count"] > 0
    assert report["stores"], "no store invariant summaries"
    assert report["census"], "census equality never checked"
