"""Byzantine-fault integration tests: a cluster fed forged, malformed, and
replayed peer messages must reject them (messages_dropped counts up) while
staying live and consistent — the BFT property the unit-level rejection
tests imply, demonstrated end-to-end.  (The reference demonstrates fault
tolerance only by killing processes, README.md:411-458; crafted-message
faults are this build's addition.)"""

import asyncio

from conftest import make_cluster
from minbft_tpu.client import new_client
from minbft_tpu.messages import Hello, UI, marshal
from minbft_tpu.messages.message import Commit, Prepare, Request
from minbft_tpu.sample.conn.inprocess import InProcessClientConnector


async def _inject_peer_messages(stub, attacker, payloads) -> None:
    """Open a peer stream to the stub's replica (as the reference's HELLO
    handshake does) and pump crafted payloads into it.  ``attacker`` is
    the byzantine INSIDER replica whose stream this impersonates — the
    HELLO must carry its genuine signature now that the handshake is
    authenticated (an outsider without any replica key is refused at
    HELLO; see test_handlers_unit.test_id_spoofing_hello_is_refused)."""
    handler = stub.peer_message_stream_handler()
    done = asyncio.Event()

    async def outgoing():
        hello = Hello(replica_id=attacker.id)
        attacker.handlers.sign_message(hello)
        yield marshal(hello)
        for p in payloads:
            yield p
        # keep the stream open briefly so the payloads are consumed
        try:
            await asyncio.wait_for(done.wait(), 1.0)
        except asyncio.TimeoutError:
            return

    consumed = asyncio.ensure_future(_drain(handler.handle_message_stream(outgoing())))
    await asyncio.sleep(0.3)
    done.set()
    consumed.cancel()
    try:
        await consumed
    except (asyncio.CancelledError, Exception):
        pass


async def _drain(aiter):
    async for _ in aiter:
        pass


def test_cluster_survives_forged_and_malformed_peer_messages():
    async def run():
        replicas, c_auths, stubs, ledgers = await make_cluster()
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()

        # a healthy commit first
        assert await asyncio.wait_for(client.request(b"before-attack"), 30)

        # craft garbage from "replica 2" aimed at replica 1:
        fake_req = Request(client_id=0, seq=999, operation=b"evil", signature=b"x" * 64)
        fake_prep = Prepare(
            replica_id=0, view=0, requests=[fake_req],
            ui=UI(counter=77, cert=b"\x01" * 40),
        )
        payloads = [
            b"\xff\x00garbage-not-a-message",          # malformed wire bytes
            marshal(fake_prep),                          # forged primary UI
            marshal(
                Commit(replica_id=2, prepare=fake_prep, ui=UI(counter=9, cert=b"z" * 40))
            ),                                           # forged commit
            marshal(fake_req),                           # forged client sig via peer stream
        ]
        dropped_before = replicas[1].metrics.counters.get("messages_dropped", 0)
        await _inject_peer_messages(stubs[1], replicas[2], payloads)

        # give the drops a moment to be accounted
        for _ in range(100):
            if replicas[1].metrics.counters.get("messages_dropped", 0) >= dropped_before + 3:
                break
            await asyncio.sleep(0.02)
        assert replicas[1].metrics.counters.get("messages_dropped", 0) >= dropped_before + 3

        # the cluster is still live and consistent
        assert await asyncio.wait_for(client.request(b"after-attack"), 30)
        for _ in range(200):
            if all(lg.length == 2 for lg in ledgers):
                break
            await asyncio.sleep(0.02)
        assert all(lg.length == 2 for lg in ledgers), [lg.length for lg in ledgers]
        # no forged operation ever executed
        for lg in ledgers:
            ops = [lg.block(h).payload for h in range(1, lg.length + 1)]
            assert all(b"evil" not in op for op in ops), ops

        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_replayed_commit_is_idempotent():
    """A replica re-delivering its COMMIT (network duplication) must not
    double-execute (in-order once-only UI capture)."""

    async def run():
        replicas, c_auths, stubs, ledgers = await make_cluster()
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        assert await asyncio.wait_for(client.request(b"op"), 30)
        for _ in range(100):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)

        # replay replica 2's genuine COMMIT at replica 1
        commits = [
            m for m in replicas[2].handlers.message_log.snapshot()
            if isinstance(m, Commit)
        ]
        assert commits
        handled_before = replicas[1].metrics.counters.get("messages_handled", 0)
        await _inject_peer_messages(stubs[1], replicas[2], [marshal(commits[0])] * 3)
        # positive delivery signal: the replays were actually handled
        # (validated, then deduplicated by in-order UI capture) — without
        # this the test could pass vacuously if injection silently failed
        for _ in range(100):
            if (
                replicas[1].metrics.counters.get("messages_handled", 0)
                >= handled_before + 3
            ):
                break
            await asyncio.sleep(0.02)
        assert (
            replicas[1].metrics.counters.get("messages_handled", 0)
            >= handled_before + 3
        )
        await asyncio.sleep(0.2)
        assert ledgers[1].length == 1  # no double execution

        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())
