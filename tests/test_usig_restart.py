"""USIG restart semantics: fresh epoch per init + TOFU anchor capture.

The reference enclave draws a new random epoch on EVERY init — including
restores from a sealed key (reference usig/sgx/enclave/usig.c:168-186,
comment at 177-182) — so a restarted instance whose counter restarts at 1
can never re-certify already-issued (epoch, cv) values.  Verifiers capture
each peer's epoch trust-on-first-use from its first valid counter-1 UI
(reference sample/authentication/crypto.go:204-239).

This file is the done-criterion matrix for that behavior:
- restore → same key, fresh epoch, counter 1 (soft, HMAC and native specs);
- a verifier that captured the old epoch REJECTS the restarted instance's
  UIs (no counter-reset equivocation), and accepts them again only after
  the operator re-bootstrap hook (reset_usig_epoch);
- a crafted counter-reuse attempt (old epoch spliced onto a new-epoch
  cert) is rejected;
- TOFU capture itself requires counter == 1.
"""

import asyncio

import pytest

from minbft_tpu import api
from minbft_tpu.sample.authentication import generate_testnet_keys
from minbft_tpu.sample.authentication.keystore import usig_key_anchor

ROLE = api.AuthenticationRole.USIG


def _verify(auth, peer, msg, tag):
    asyncio.run(auth.verify_message_authen_tag(ROLE, peer, msg, tag))


def _expect_reject(auth, peer, msg, tag):
    with pytest.raises(api.AuthenticationError):
        _verify(auth, peer, msg, tag)


@pytest.mark.parametrize("usig_spec", ["SOFT_ECDSA", "HMAC_SHA256"])
def test_restart_cannot_equivocate_and_rebootstrap(usig_spec):
    store = generate_testnet_keys(2, usig_spec=usig_spec)
    signer = store.replica_authenticator(0)
    verifier = store.replica_authenticator(1)

    # First UI (counter 1) captures replica 0's epoch at the verifier.
    t1 = signer.generate_message_authen_tag(ROLE, b"msg-1")
    _verify(verifier, 0, b"msg-1", t1)
    t2 = signer.generate_message_authen_tag(ROLE, b"msg-2")
    _verify(verifier, 0, b"msg-2", t2)

    # Replica 0 "restarts": same sealed key, fresh epoch, counter back
    # at 1.  Its new counter-1 UI certifies a DIFFERENT message than the
    # old counter-1 UI — the equivocation the epoch exists to prevent.
    restarted = store.replica_authenticator(0)
    t1b = restarted.generate_message_authen_tag(ROLE, b"msg-OTHER")
    _expect_reject(verifier, 0, b"msg-OTHER", t1b)  # old epoch pinned

    # A verifier that never saw the old instance captures the new epoch
    # (and will in turn reject the OLD instance's certs).
    fresh_verifier = store.replica_authenticator(1)
    _verify(fresh_verifier, 0, b"msg-OTHER", t1b)
    _expect_reject(fresh_verifier, 0, b"msg-1", t1)

    # Operator re-bootstrap: after resetting the anchor, the original
    # verifier accepts the restarted instance — but only from counter 1.
    verifier.reset_usig_epoch(0)
    _verify(verifier, 0, b"msg-OTHER", t1b)
    # ...and the old instance's certs are now rejected there too.
    _expect_reject(verifier, 0, b"msg-2", t2)


def test_crafted_counter_reuse_rejected():
    """Splicing the captured (old) epoch onto a restarted instance's
    signature must fail: the signature binds the epoch."""
    store = generate_testnet_keys(2, usig_spec="SOFT_ECDSA")
    signer = store.replica_authenticator(0)
    verifier = store.replica_authenticator(1)
    t1 = signer.generate_message_authen_tag(ROLE, b"honest")
    _verify(verifier, 0, b"honest", t1)
    old_epoch = t1[8:16]  # tag = counter_be8 || cert(epoch8 || sig)

    restarted = store.replica_authenticator(0)
    t1b = restarted.generate_message_authen_tag(ROLE, b"equivocation")
    forged = t1b[:8] + old_epoch + t1b[16:]
    _expect_reject(verifier, 0, b"equivocation", forged)


def test_tofu_first_capture_requires_counter_one():
    store = generate_testnet_keys(2, usig_spec="SOFT_ECDSA")
    signer = store.replica_authenticator(0)
    verifier = store.replica_authenticator(1)
    t1 = signer.generate_message_authen_tag(ROLE, b"a")  # counter 1
    t2 = signer.generate_message_authen_tag(ROLE, b"b")  # counter 2
    # Out-of-order first contact: counter-2 UI cannot establish the epoch
    # (reference crypto.go:220-226 takes the cert epoch only for cv==1).
    _expect_reject(verifier, 0, b"b", t2)
    _verify(verifier, 0, b"a", t1)
    _verify(verifier, 0, b"b", t2)


def test_concurrent_first_contact_waits_for_capture():
    """Startup race: a peer's counter-2 UI verified concurrently with its
    counter-1 UI (batch-engine co-batching) must wait for the in-flight
    first-contact epoch capture instead of spuriously failing."""
    from minbft_tpu.sample.authentication.authenticator import SampleAuthenticator
    from minbft_tpu.usig.software import EcdsaUSIG
    from minbft_tpu.utils import hostcrypto as hc

    class SlowEngine:
        async def verify_ecdsa_p256(self, q, payload, sig):
            await asyncio.sleep(0.02)  # models the device round trip
            return hc.ecdsa_verify(q, payload, sig)

    signer = EcdsaUSIG()
    anchor = signer.id()[8:]  # epoch-free key anchor → TOFU mode
    verifier = SampleAuthenticator(
        usig=EcdsaUSIG(), usig_ids={0: anchor}, engine=SlowEngine()
    )
    t1 = signer.create_ui(b"first").to_bytes()
    t2 = signer.create_ui(b"second").to_bytes()

    async def run():
        await asyncio.gather(
            verifier.verify_message_authen_tag(ROLE, 0, b"first", t1),
            verifier.verify_message_authen_tag(ROLE, 0, b"second", t2),
        )

    asyncio.run(run())


def test_counter2_first_waits_for_late_counter1():
    """Even when the counter-2 UI reaches the authenticator BEFORE the
    counter-1 UI does, it must wait (bounded) for the first-contact
    capture rather than reject."""
    from minbft_tpu.sample.authentication.authenticator import SampleAuthenticator
    from minbft_tpu.usig.software import EcdsaUSIG
    from minbft_tpu.utils import hostcrypto as hc

    class Engine:
        async def verify_ecdsa_p256(self, q, payload, sig):
            return hc.ecdsa_verify(q, payload, sig)

    signer = EcdsaUSIG()
    verifier = SampleAuthenticator(
        usig=EcdsaUSIG(), usig_ids={0: signer.id()[8:]}, engine=Engine()
    )
    t1 = signer.create_ui(b"a").to_bytes()
    t2 = signer.create_ui(b"b").to_bytes()

    async def run():
        task2 = asyncio.create_task(
            verifier.verify_message_authen_tag(ROLE, 0, b"b", t2)
        )
        await asyncio.sleep(0.01)  # t2 is now parked on the pending future
        await verifier.verify_message_authen_tag(ROLE, 0, b"a", t1)
        await asyncio.wait_for(task2, timeout=5)

    asyncio.run(run())


def test_counter2_rejected_when_counter1_never_arrives():
    from minbft_tpu.sample.authentication.authenticator import SampleAuthenticator
    from minbft_tpu.usig.software import EcdsaUSIG

    signer = EcdsaUSIG()
    verifier = SampleAuthenticator(usig=EcdsaUSIG(), usig_ids={0: signer.id()[8:]})
    verifier.tofu_capture_timeout = 0.05
    signer.create_ui(b"a")  # counter 1 never shown to the verifier
    t2 = signer.create_ui(b"b").to_bytes()
    _expect_reject(verifier, 0, b"b", t2)


def test_native_restart_fresh_epoch():
    from minbft_tpu.usig import native as native_mod

    if not native_mod.available(auto_build=True):
        pytest.skip("native USIG module unavailable")
    store = generate_testnet_keys(2, usig_spec="NATIVE_ECDSA")
    u1 = store.make_usig(0)
    u2 = store.make_usig(0)  # restart
    assert usig_key_anchor(u1) == usig_key_anchor(u2)
    assert u1.epoch != u2.epoch
    assert u2.create_ui(b"x").counter == 1


def test_state_transfer_tofu_floor_allows_capture_above_base():
    """A late joiner whose history is truncated never sees counter-1 UIs;
    after validating a peer's LOG-BASE the core installs an epoch-capture
    floor, and the first valid UI at/above it establishes the epoch —
    below the floor (and above counter 1) stays rejected."""
    store = generate_testnet_keys(2, usig_spec="SOFT_ECDSA")
    signer = store.replica_authenticator(0)
    verifier = store.replica_authenticator(1)
    tags = [
        signer.generate_message_authen_tag(ROLE, b"m%d" % c)
        for c in range(1, 8)
    ]  # counters 1..7

    # floor at counter 5 (base 4 truncated away)
    verifier.allow_epoch_capture_from(0, 5)
    verifier.tofu_capture_timeout = 0.05
    # counter 3 is neither 1 nor >= floor: no capture
    _expect_reject(verifier, 0, b"m3", tags[2])
    # counter 6 is above the floor: captures the epoch...
    _verify(verifier, 0, b"m6", tags[5])
    # ...after which everything verifies normally, below the floor too
    _verify(verifier, 0, b"m3", tags[2])
    _verify(verifier, 0, b"m7", tags[6])


def test_tofu_floor_keeps_rejecting_wrong_epoch():
    """The floor relaxes WHICH counter may establish first contact, not
    the anchor check: a UI signed under a different key (or a stale
    epoch after capture) still fails."""
    store = generate_testnet_keys(2, usig_spec="SOFT_ECDSA")
    old_signer = store.replica_authenticator(0)
    old_tag = old_signer.generate_message_authen_tag(ROLE, b"z")
    for _ in range(5):
        old_signer.generate_message_authen_tag(ROLE, b"pad")

    # the peer restarted: fresh epoch, same key
    new_signer_usig = store.make_usig(0)
    from minbft_tpu.sample.authentication.authenticator import (
        SampleAuthenticator,
    )
    from minbft_tpu.sample.authentication.keystore import usig_key_anchor

    new_signer = SampleAuthenticator(
        usig=new_signer_usig, usig_ids={0: usig_key_anchor(new_signer_usig)}
    )
    tags = [
        new_signer.generate_message_authen_tag(ROLE, b"n%d" % c)
        for c in range(1, 8)
    ]

    verifier = store.replica_authenticator(1)
    verifier.tofu_capture_timeout = 0.05
    verifier.allow_epoch_capture_from(0, 5)
    _verify(verifier, 0, b"n6", tags[5])  # captures the NEW epoch
    # the old epoch's counter-1 UI no longer passes
    _expect_reject(verifier, 0, b"z", old_tag)


def test_reset_usig_epoch_drops_capture_floor():
    """Operator re-bootstrap must also drop the state-transfer floor: a
    delayed PRE-restart message (counter >= floor) arriving after the
    reset must not re-pin the stale epoch — only the restarted peer's
    counter-1 UI re-captures."""
    store = generate_testnet_keys(2, usig_spec="SOFT_ECDSA")
    old_signer = store.replica_authenticator(0)
    old_tags = [
        old_signer.generate_message_authen_tag(ROLE, b"o%d" % c)
        for c in range(1, 8)
    ]
    verifier = store.replica_authenticator(1)
    verifier.tofu_capture_timeout = 0.05
    verifier.allow_epoch_capture_from(0, 5)
    _verify(verifier, 0, b"o6", old_tags[5])  # epoch captured via floor

    from minbft_tpu.sample.authentication.authenticator import (
        SampleAuthenticator,
    )
    from minbft_tpu.sample.authentication.keystore import usig_key_anchor

    # peer restarts; operator re-bootstraps the verifier
    verifier.reset_usig_epoch(0)
    # a delayed pre-restart message above the old floor must NOT re-pin
    _expect_reject(verifier, 0, b"o7", old_tags[6])

    u = store.make_usig(0)
    new_signer = SampleAuthenticator(usig=u, usig_ids={0: usig_key_anchor(u)})
    t1 = new_signer.generate_message_authen_tag(ROLE, b"n1")
    _verify(verifier, 0, b"n1", t1)  # fresh counter-1 re-captures
