"""Multi-device engine pool tests (ISSUE 17): placement invariants,
rebalance safety, the C=1 differential identity, striped-vs-home-chip
agreement, per-chip cross-group coalescing, the pool ledger's degenerate
aggregate, and the prom/peer-top read surfaces.

All on the conftest-forced 8-virtual-device CPU mesh — the same SIM mode
the sharding suite uses.
"""

import asyncio
import hashlib
import hmac as hmac_mod
import random
import threading

import jax
import numpy as np
import pytest

from minbft_tpu.obs.ledger import DeviceLedger, PoolLedger
from minbft_tpu.parallel import BatchVerifier, EnginePool


def _devs(k):
    devices = jax.devices("cpu")
    assert len(devices) >= k, "conftest must force 8 virtual CPU devices"
    return devices[:k]


def _hmac_item(i: int, valid: bool = True):
    key = hashlib.sha256(b"pool-key-%d" % i).digest()
    msg = hashlib.sha256(b"pool-msg-%d" % i).digest()
    mac = hmac_mod.new(key, msg, hashlib.sha256).digest()
    if not valid:
        mac = bytes([mac[0] ^ 1]) + mac[1:]
    return key, msg, mac


# -- placement invariants ----------------------------------------------------


def test_placement_is_round_robin_and_unique():
    pool = EnginePool(chips=4, devices=_devs(4), max_batch=8)
    for g in range(12):
        assert pool.home_chip(g) == g % 4
    placed = pool.placement()
    assert len(placed) == 12  # every touched group has EXACTLY one home
    # repeated lookups never re-place
    assert pool.home_chip(5) == 1
    # one facade identity per group
    assert pool.engine_for(3) is pool.engine_for(3)


def test_chips_clamp_to_visible_devices():
    pool = EnginePool(chips=64, max_batch=8)
    assert pool.requested_chips == 64
    assert pool.chips == len(jax.devices())
    with pytest.raises(ValueError):
        EnginePool(chips=0)
    with pytest.raises(ValueError):
        EnginePool(chips=2, mesh=object())


def test_rebalance_never_migrates_a_group_with_inflight_dispatches():
    """The migration-safety invariant: a group whose verify future is
    outstanding stays on the engine that owns its memo/staging state;
    only idle groups move off the hot chip."""

    async def scenario():
        pool = EnginePool(
            chips=2, devices=_devs(2), max_batch=8, max_delay=0.01
        )
        f0 = pool.engine_for(0)  # home chip 0
        pool.engine_for(1)  # home chip 1
        pool.engine_for(2)  # home chip 0 (the idle migration candidate)
        release = threading.Event()

        def slow_dispatch(items):
            release.wait(30)
            return np.ones(len(items), dtype=bool)

        pool.engines[0]._queue("hmac_sha256", slow_dispatch)
        task = asyncio.create_task(f0.verify_hmac_sha256(*_hmac_item(0)))
        await asyncio.sleep(0.05)  # let the dispatch actually launch
        assert pool.group_inflight(0) == 1

        moves = pool.rebalance(scores=[1.0, 0.0])
        assert moves == {2: (0, 1)}  # the idle group moved ...
        assert pool.home_chip(0) == 0  # ... the in-flight one did not
        # second pass: only the in-flight group remains on the hot chip
        assert pool.rebalance(scores=[1.0, 0.0]) == {}
        assert pool.home_chip(0) == 0

        release.set()
        assert await asyncio.wait_for(task, 10) is True
        # once drained, the group is movable again
        assert pool.group_inflight(0) == 0
        assert pool.rebalance(scores=[1.0, 0.0]) == {0: (0, 1)}
        return True

    assert asyncio.run(scenario())


def test_rebalance_noop_cases():
    pool = EnginePool(chips=2, devices=_devs(2), max_batch=8)
    pool.engine_for(0)
    # balanced scores -> no move; 1-chip pool -> never moves
    assert pool.rebalance(scores=[0.5, 0.5]) == {}
    assert EnginePool(chips=1).rebalance() == {}
    with pytest.raises(ValueError):
        pool.rebalance(scores=[1.0])


# -- C=1 differential identity -----------------------------------------------


def _drive_mixed(eng, seed: int):
    """A deterministic verify load, driven in awaited rounds: mixed
    verdicts, in-round duplicates (lane sharing), cross-round repeats
    (memo hits), and rounds wider than max_batch (a "full" flush plus a
    remainder).  Every submission of a round is already on the loop's
    ready queue before the dispatch task spawned by a full flush can
    run, and the round is gathered before the next starts — so flush
    decisions depend only on the submission pattern, never on how long
    a dispatch takes.  Requires ``max_delay=0`` (the idle flush path)."""

    async def run():
        rng = random.Random(seed)
        valid = {i: rng.random() < 0.7 for i in range(40)}
        results = []
        for _ in range(8):
            idxs = [rng.randrange(40) for _ in range(12)]
            tasks = [
                asyncio.create_task(
                    eng.verify_hmac_sha256(*_hmac_item(i, valid[i]))
                )
                for i in idxs
            ]
            results.extend(await asyncio.gather(*tasks))
        return results

    return asyncio.run(run())


def test_c1_pool_is_byte_identical_to_bare_engine():
    """The degenerate-honesty contract: results, stats accounting, and
    flush decisions of a 1-chip pool match the pre-pool engine exactly
    under the same seeded load."""
    kwargs = dict(max_batch=8, max_delay=0.0)
    bare = BatchVerifier(**kwargs)
    pool = EnginePool(chips=1, **kwargs)
    fac = pool.engine_for(0)

    res_bare = _drive_mixed(bare, seed=0xC1)
    res_pool = _drive_mixed(fac, seed=0xC1)
    assert res_bare == res_pool

    sb = bare.stats["hmac_sha256"]
    sp = pool.engines[0].stats["hmac_sha256"]
    for field in (
        "items",
        "batches",
        "max_batch_seen",
        "padded_lanes",
        "memo_hits",
        "flush_reasons",
    ):
        assert getattr(sb, field) == getattr(sp, field), field
    # the pool's merged read surface is the bare engine's (no prefixes)
    assert set(pool.stats) == set(bare.stats)
    assert set(pool.queue_depths()) == set(bare.queue_depths())
    # facade stats passthrough reads the same object
    assert fac.stats["hmac_sha256"] is sp


# -- per-chip cross-group coalescing ----------------------------------------


def test_two_groups_on_same_home_chip_coalesce_into_one_flush():
    """The PR-8 win replicated per chip: groups 0 and 2 (both homed on
    chip 0 of a 2-chip pool) fill ONE batch together — one flush, not
    one per group."""

    async def run():
        pool = EnginePool(
            chips=2, devices=_devs(2), max_batch=8, max_delay=10.0
        )
        f0, f2 = pool.engine_for(0), pool.engine_for(2)
        assert pool.home_chip(0) == pool.home_chip(2) == 0
        tasks = [
            asyncio.create_task(f0.verify_hmac_sha256(*_hmac_item(i)))
            for i in range(4)
        ] + [
            asyncio.create_task(f2.verify_hmac_sha256(*_hmac_item(4 + i)))
            for i in range(4)
        ]
        results = await asyncio.wait_for(asyncio.gather(*tasks), 10)
        assert all(results)
        st = pool.engines[0].stats["hmac_sha256"]
        assert st.items == 8 and st.batches == 1
        # the other chip saw nothing
        assert "hmac_sha256" not in pool.engines[1].stats
        # multi-chip merged surface attributes per chip
        assert "c0:hmac_sha256" in pool.stats
        return True

    assert asyncio.run(run())


# -- striping ---------------------------------------------------------------


@pytest.fixture(scope="module", autouse=True)
def _loop_lowering():
    from minbft_tpu.ops import lowering

    lowering.set_mode("loop")
    yield
    lowering.set_mode(None)


@pytest.mark.slow  # ~1 min of loop-mode sharded-ECDSA traces; CI's
# multichip tier runs it unfiltered
def test_striped_and_home_chip_agree_on_adversarial_batches():
    """An explicit batch above stripe_threshold routes through the
    mesh-striped engine; its verdicts must agree lane-for-lane with the
    home-chip path on the same adversarial (mixed valid/corrupt) items."""
    from minbft_tpu.utils import hostcrypto as hc

    pool = EnginePool(chips=2, devices=_devs(2), max_batch=8, buckets=(8,))
    assert pool.stripe_threshold == 8
    d, pub = hc.keygen()
    items, expected = [], []
    for i in range(17):  # 17 > 8: stripes
        digest = hashlib.sha256(b"adv-%d" % i).digest()
        sig = hc.ecdsa_sign(d, digest)
        if i % 5 == 0:
            sig = (sig[0], sig[1] ^ 2)
        items.append((pub, digest, sig))
        expected.append(i % 5 != 0)

    fac = pool.engine_for(0)
    res = asyncio.run(fac.verify_ecdsa_p256_many(items))
    assert res == expected
    st = pool.striped_engine.stats.get("ecdsa_p256")
    assert st is not None and st.items == 17  # the stripe carried it
    assert "ecdsa_p256" not in pool.engines[0].stats

    # at-threshold batches stay on the home chip, same verdicts
    res_home = asyncio.run(fac.verify_ecdsa_p256_many(items[:8]))
    assert res_home == expected[:8]
    assert pool.engines[0].stats["ecdsa_p256"].items == 8
    # striped traffic shows under its own attribution prefix
    assert "stripe:ecdsa_p256" in pool.stats


def test_host_many_never_stripes():
    async def run():
        pool = EnginePool(
            chips=2, devices=_devs(2), max_batch=4, max_delay=0.01
        )
        fac = pool.engine_for(1)
        items = [_hmac_item(i) for i in range(9)]  # > threshold

        # host _many goes to the home chip regardless of size
        key = hashlib.sha256(b"host-k").digest()
        msg = hashlib.sha256(b"host-m").digest()
        mac = hmac_mod.new(key, msg, hashlib.sha256).digest()
        del items  # the hmac host path is per-call; use ed25519 host many
        ok = await fac.verify_hmac_sha256_host(key, msg, mac)
        assert ok
        assert "hmac_sha256_host" in pool.engines[1].stats
        striped = pool.striped_engine.stats
        assert "hmac_sha256_host" not in striped
        return True

    assert asyncio.run(run())


# -- pool ledger -------------------------------------------------------------


def test_pool_ledger_c1_aggregate_reduces_to_device_ledger():
    """A 1-chip pool's aggregate util block must be EXACTLY what a bare
    DeviceLedger reports for the same engine over the same window — same
    keys, same values, ceiling source unscaled."""
    pool = EnginePool(chips=1, max_batch=8, max_delay=0.0)
    pl = PoolLedger(pool, now=0.0)
    dl = DeviceLedger(pool.engines[0], now=0.0)
    pl.set_ceiling("hmac_sha256", 1000.0, "test")
    dl.set_ceiling("hmac_sha256", 1000.0, "test")

    _drive_mixed(pool.engine_for(0), seed=0xD1)

    agg = pl.util_keys("p", "hmac_sha256", now=10.0)
    ref = dl.util_keys("p", "hmac_sha256", now=10.0)
    assert ref  # the window saw traffic
    assert {k: v for k, v in agg.items() if k in ref} == ref
    assert agg["p_util_ceiling_source"] == "test"  # no " x1" suffix
    # per-chip attribution rides alongside the aggregate
    assert "p_chip0_util_busy" in agg


def test_pool_ledger_multichip_identity_and_scores():
    async def run():
        pool = EnginePool(
            chips=2, devices=_devs(2), max_batch=8, max_delay=0.01
        )
        pl = PoolLedger(pool, now=None)
        pl.set_ceiling("hmac_sha256", 1000.0, "test")
        f0, f1 = pool.engine_for(0), pool.engine_for(1)
        await asyncio.gather(
            *[f0.verify_hmac_sha256(*_hmac_item(i)) for i in range(8)],
            *[f1.verify_hmac_sha256(*_hmac_item(8 + i)) for i in range(4)],
        )
        keys = pl.util_keys("gp", "hmac_sha256")
        # both chips attributed; the aggregate identity holds
        assert keys["gp_chip0_util_lanes_useful"] > 0
        assert keys["gp_chip1_util_lanes_useful"] > 0
        assert keys["gp_util_effective_per_sec"] > 0
        # the per-chip ceiling scales by the pool width, stamped as such
        assert keys["gp_util_ceiling_source"] == "test x2"
        assert keys["gp_util_ceiling_per_sec"] == 2000.0
        scores = pl.chip_scores("hmac_sha256")
        assert len(scores) == 2 and all(s >= 0 for s in scores)
        return True

    assert asyncio.run(run())


# -- liveness + prom surfaces ------------------------------------------------


def test_chip_up_tracks_write_off_and_prom_renders_down():
    from minbft_tpu.obs.prom import collect_engine_pool

    pool = EnginePool(chips=2, devices=_devs(2), max_batch=4)
    assert pool.chip_up(0) and pool.chip_up(1)  # no queues yet: up
    eng = pool.engines[1]
    q = eng._queue("hmac_sha256", eng._dispatch_hmac)
    q._device_written_off = True
    assert pool.chip_up(1) is False
    assert pool.chip_up(0) is True

    pool.engine_for(0)
    pool.engine_for(1)
    fams = collect_engine_pool(pool)
    by_name = {f[0]: f for f in fams}
    assert by_name["minbft_engine_pool_chips"][3][0][1] == 2.0
    ups = {
        labels["chip"]: value
        for labels, value in by_name["minbft_engine_pool_chip_up"][3]
    }
    assert ups == {"0": 1.0, "1": 0.0}
    homes = {
        labels["group"]: value
        for labels, value in by_name["minbft_engine_pool_home_chip"][3]
    }
    assert homes == {"0": 0.0, "1": 1.0}
    for fam in ("minbft_engine_pool_chip_busy", "minbft_engine_pool_chip_fill",
                "minbft_engine_pool_chip_depth"):
        assert len(by_name[fam][3]) == 2


def test_chip_utilization_rows_are_renderable_when_idle():
    pool = EnginePool(chips=2, devices=_devs(2), max_batch=4)
    rows = pool.chip_utilization()
    assert [r["chip"] for r in rows] == [0, 1]
    for r in rows:
        assert set(r) >= {"chip", "busy", "fill", "score", "depth", "groups"}
        assert r["busy"] == 0.0 and r["depth"] == 0
