"""In-process multi-replica integration tests.

Mirrors reference core/integration_test.go:212-226: {n=3, n=5} x 1 client,
real keys, replicas wired by the in-process connector + replica stubs (the
whole network is asyncio tasks in one process); asserts every replica's
ledger reaches the expected length after requests commit.

Uses the HMAC USIG + host-serial verification (no batching engine) so the
protocol path is exercised without TPU kernels; the batched path is covered
by test_engine.py and the benchmark.
"""

import asyncio

import pytest

from minbft_tpu.client import new_client
from minbft_tpu.core import new_replica
from minbft_tpu.sample.authentication import new_test_authenticators
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.sample.conn.inprocess import (
    InProcessClientConnector,
    InProcessPeerConnector,
    make_testnet_stubs,
)
from minbft_tpu.sample.requestconsumer import SimpleLedger


async def _run_cluster(n: int, f: int, n_requests: int, usig_kind: str = "hmac"):
    configer = SimpleConfiger(n=n, f=f, timeout_request=30.0, timeout_prepare=15.0)
    replica_auths, client_auths = new_test_authenticators(
        n, n_clients=1, usig_kind=usig_kind
    )
    stubs = make_testnet_stubs(n)
    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        replica = new_replica(
            i,
            configer,
            replica_auths[i],
            InProcessPeerConnector(stubs),
            ledgers[i],
        )
        stubs[i].assign_replica(replica)
        replicas.append(replica)
    for r in replicas:
        await r.start()

    client = new_client(
        0, n, f, client_auths[0], InProcessClientConnector(stubs), seq_start=0
    )
    await client.start()

    results = []
    for k in range(n_requests):
        res = await asyncio.wait_for(client.request(b"op-%d" % k), timeout=30)
        results.append(res)

    # Let the slower replicas finish executing (f+1 suffice for the reply).
    for _ in range(200):
        if all(lg.length == n_requests for lg in ledgers):
            break
        await asyncio.sleep(0.05)

    await client.stop()
    for r in replicas:
        await r.stop()
    return ledgers, results


@pytest.mark.parametrize("n,f", [(3, 1), (5, 2)])
def test_cluster_commits_requests(n, f):
    ledgers, results = asyncio.run(_run_cluster(n, f, n_requests=2))
    for lg in ledgers:
        assert lg.length == 2
    # All replicas converged on the same chain: results are block digests.
    assert len(set(results)) == 2


def test_cluster_with_ecdsa_usig():
    ledgers, results = asyncio.run(_run_cluster(3, 1, n_requests=1, usig_kind="ecdsa"))
    for lg in ledgers:
        assert lg.length == 1


def test_replica_rejects_bad_config():
    configer = SimpleConfiger(n=2, f=1)
    with pytest.raises(ValueError):
        new_replica(0, configer, None, None, None)
