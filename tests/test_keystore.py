"""Keystore persistence and restoration (reference keymanager_test.go:129).

Covers: generate → save → load round-trip for each keyspec, authenticator
construction from a loaded store (cross sign/verify between two replicas
and a client), sealed-USIG restoration (same key, fresh epoch — the
durable-state story), private-key stripping, and integrity failure on
tamper.
"""

import asyncio

import pytest

import importlib.util

_HAVE_CRYPTOGRAPHY = importlib.util.find_spec("cryptography") is not None

# Sealing and the wide NIST curves are OpenSSL-backed features of the
# `cryptography` package: CI installs it; the bare jax_graft image runs
# unsealed with the P-256/Ed25519 paths only.
needs_cryptography = pytest.mark.skipif(
    not _HAVE_CRYPTOGRAPHY,
    reason="feature under test requires the optional cryptography package",
)

from minbft_tpu import api
from minbft_tpu.sample.authentication import (
    KeyStore,
    KeyStoreError,
    generate_testnet_keys,
)
from minbft_tpu.sample.authentication.keytool import main as keytool_main


def _roundtrip(tmp_path, store: KeyStore) -> KeyStore:
    path = str(tmp_path / "keys.yaml")
    store.save(path)
    return KeyStore.load(path)


@pytest.mark.parametrize("usig_spec", ["SOFT_ECDSA", "HMAC_SHA256"])
def test_generate_save_load_verify(tmp_path, usig_spec):
    store = _roundtrip(
        tmp_path, generate_testnet_keys(3, n_clients=2, usig_spec=usig_spec)
    )
    assert store.usig_spec == usig_spec
    auth0 = store.replica_authenticator(0)
    auth1 = store.replica_authenticator(1)
    client = store.client_authenticator(1)

    async def run():
        # replica 0 signs; replica 1 verifies
        tag = auth0.generate_message_authen_tag(api.AuthenticationRole.REPLICA, b"m")
        await auth1.verify_message_authen_tag(
            api.AuthenticationRole.REPLICA, 0, b"m", tag
        )
        # client signs; replica verifies
        ctag = client.generate_message_authen_tag(api.AuthenticationRole.CLIENT, b"c")
        await auth0.verify_message_authen_tag(
            api.AuthenticationRole.CLIENT, 1, b"c", ctag
        )
        # USIG: replica 0 certifies; replica 1 verifies against the stored
        # trust anchor
        utag = auth0.generate_message_authen_tag(api.AuthenticationRole.USIG, b"u")
        await auth1.verify_message_authen_tag(
            api.AuthenticationRole.USIG, 0, b"u", utag
        )

    asyncio.run(run())


def test_sealed_usig_restores_same_key_fresh_epoch(tmp_path):
    from minbft_tpu.sample.authentication.keystore import usig_key_anchor

    store = _roundtrip(tmp_path, generate_testnet_keys(2, usig_spec="SOFT_ECDSA"))
    u_first = store.make_usig(0)
    u_again = store.make_usig(0)  # "replica restart"
    # same key material anchor, but a fresh epoch per restore (reference
    # usig.c:168-186) — so the two restored instances' counter-1 certs
    # can never equivocate under one (epoch, cv).
    assert (
        usig_key_anchor(u_first)
        == usig_key_anchor(u_again)
        == store.usig_anchors()[0]
    )
    assert u_first.epoch != u_again.epoch
    # counters are volatile: both restored instances start at 1
    assert u_first.create_ui(b"x").counter == 1
    assert u_again.create_ui(b"x").counter == 1


def test_native_sealed_usig_roundtrip(tmp_path):
    from minbft_tpu.sample.authentication.keystore import usig_key_anchor
    from minbft_tpu.usig import native as native_mod

    if not native_mod.available(auto_build=True):
        pytest.skip("native USIG module unavailable")
    store = _roundtrip(tmp_path, generate_testnet_keys(2, usig_spec="NATIVE_ECDSA"))
    u = store.make_usig(0)
    assert usig_key_anchor(u) == store.usig_anchors()[0]
    ui = u.create_ui(b"native")
    u.verify_ui(b"native", ui, u.id())


def test_tampered_soft_seal_rejected(tmp_path):
    store = generate_testnet_keys(1, usig_spec="SOFT_ECDSA")
    sealed, uid = store.usig_keys[0]
    bad = bytes([sealed[0] ^ 1]) + sealed[1:]
    store.usig_keys[0] = (bad, uid)
    with pytest.raises((KeyStoreError, ValueError)):
        store.make_usig(0)


def test_strip_private(tmp_path):
    store = generate_testnet_keys(3, n_clients=1)
    public = store.strip_private(keep_replica=1)
    # replica 1 keeps its material, others lose it
    public.replica_authenticator(1)
    with pytest.raises(KeyStoreError):
        public.replica_authenticator(0)
    with pytest.raises(KeyStoreError):
        public.client_authenticator(0)
    # trust anchors survive
    assert public.usig_anchors() == store.usig_anchors()


def test_keystore_file_mode_owner_only(tmp_path):
    """keys.yaml carries private keys/sealed blobs/MAC matrices — save()
    must create it 0600 (and rewrite any laxer pre-existing file)."""
    import os
    import stat

    path = str(tmp_path / "keys.yaml")
    store = generate_testnet_keys(2, with_macs=True)
    store.save(path)
    assert stat.S_IMODE(os.stat(path).st_mode) == 0o600
    KeyStore.load(path)  # still loadable
    # a pre-existing laxer file is tightened, not inherited
    os.chmod(path, 0o644)
    store.save(path)
    assert stat.S_IMODE(os.stat(path).st_mode) == 0o600


def test_testnet_scaffold_writes_stripped_per_replica_copies(tmp_path):
    """The peer testnet scaffold emits least-privilege keys.replicaN.yaml
    copies: replica i keeps only its own private material."""
    from minbft_tpu.sample.peer.cli import main as peer_main

    d = str(tmp_path / "net")
    rc = peer_main(["testnet", "-n", "3", "-d", d, "--usig", "SOFT_ECDSA"])
    assert rc in (0, None)
    full = KeyStore.load(f"{d}/keys.yaml")
    for i in range(3):
        stripped = KeyStore.load(f"{d}/keys.replica{i}.yaml")
        # own private material present, others' absent
        stripped.replica_authenticator(i)
        for j in range(3):
            if j != i:
                with pytest.raises(KeyStoreError):
                    stripped.replica_authenticator(j)
        # trust anchors match the full store
        assert stripped.usig_anchors() == full.usig_anchors()


def test_keytool_generate(tmp_path):
    out = str(tmp_path / "k.yaml")
    rc = keytool_main(
        ["generate", "-o", out, "-n", "4", "--clients", "2", "--usig", "SOFT_ECDSA"]
    )
    assert rc == 0
    store = KeyStore.load(out)
    assert len(store.replica_keys) == 4
    assert len(store.client_keys) == 2
    assert len(store.usig_keys) == 4


def test_mac_section_roundtrip_and_cluster(tmp_path):
    """MAC pairwise material persists in keys.yaml and restores working
    MAC authenticators (cross sign/verify of every role; the full cluster
    commit under MAC auth lives in tests/test_mac_auth.py and the CLI
    socket flow was driven via peer --auth mac)."""
    import asyncio

    store = _roundtrip(
        tmp_path,
        generate_testnet_keys(3, n_clients=2, usig_spec="SOFT_ECDSA", with_macs=True),
    )
    assert store.mac_keys is not None

    async def run():
        r_auths = [store.mac_replica_authenticator(i) for i in range(3)]
        c_auth = store.mac_client_authenticator(1)
        tag = c_auth.generate_message_authen_tag(api.AuthenticationRole.CLIENT, b"m")
        for r in range(3):
            await r_auths[r].verify_message_authen_tag(
                api.AuthenticationRole.CLIENT, 1, b"m", tag
            )
        # USIG path still works through the restored sealed key
        utag = r_auths[0].generate_message_authen_tag(
            api.AuthenticationRole.USIG, b"u"
        )
        await r_auths[1].verify_message_authen_tag(
            api.AuthenticationRole.USIG, 0, b"u", utag
        )

    asyncio.run(run())

    # stripping keeps only the kept replica's MAC rows
    stripped = store.strip_private(keep_replica=2)
    assert stripped.mac_keys is not None
    assert all(k[1] == 2 for k in stripped.mac_keys.client_replica)
    assert all(2 in k for k in stripped.mac_keys.replica_pair)


@needs_cryptography
def test_sealed_keystore_encrypts_all_private_material(tmp_path):
    """With an operator secret, keys.yaml holds no recoverable private
    material: signature private keys, sealed USIG blobs, and MAC keys are
    AES-256-GCM encrypted (the reference's sgx_seal_data property,
    usig/sgx/enclave/usig.c:107-116); loading without the secret (or with
    the wrong one) is refused."""
    import base64

    import yaml

    from minbft_tpu.sample.authentication.keystore import (
        KeyStore,
        KeyStoreError,
        generate_testnet_keys,
    )

    secret = b"correct horse battery staple"
    store = generate_testnet_keys(3, n_clients=2, usig_spec="SOFT_ECDSA",
                                  with_macs=True)
    path = str(tmp_path / "keys.yaml")
    store.save(path, secret=secret)

    raw = open(path, "rb").read()
    data = yaml.safe_load(raw)
    assert "seal" in data and data["seal"]["kdf"] == "pbkdf2-sha256"
    # no plaintext private scalar / sealed blob / MAC key appears in the file
    for kid, (priv, _pub) in store.replica_keys.items():
        assert base64.b64encode(priv) not in raw
    for kid, (sealed, _a) in store.usig_keys.items():
        assert base64.b64encode(sealed) not in raw
    for _pair, k in store.mac_keys.replica_pair.items():
        assert base64.b64encode(k) not in raw

    back = KeyStore.load(path, secret=secret)
    assert back.replica_keys == store.replica_keys
    assert back.usig_keys == store.usig_keys
    assert back.mac_keys.replica_pair == store.mac_keys.replica_pair
    # a sealed store usable end to end: restore a USIG from it
    assert back.make_usig(0) is not None

    import pytest as _pytest

    with _pytest.raises(KeyStoreError):
        KeyStore.load(path, secret=None)
    with _pytest.raises(KeyStoreError):
        KeyStore.load(path, secret=b"wrong")


@needs_cryptography
def test_seal_secret_from_env(tmp_path, monkeypatch):
    """save()/load() source the secret from MINBFT_SEAL_SECRET by default
    — the deployment flow needs no code changes to turn sealing on."""
    from minbft_tpu.sample.authentication.keystore import (
        KeyStore,
        KeyStoreError,
        generate_testnet_keys,
    )

    monkeypatch.setenv("MINBFT_SEAL_SECRET", "env-secret")
    store = generate_testnet_keys(3, n_clients=1, usig_spec="SOFT_ECDSA")
    path = str(tmp_path / "keys.yaml")
    store.save(path)

    import yaml

    assert "seal" in yaml.safe_load(open(path))
    assert KeyStore.load(path).make_usig(1) is not None

    monkeypatch.delenv("MINBFT_SEAL_SECRET")
    import pytest as _pytest

    with _pytest.raises(KeyStoreError):
        KeyStore.load(path)


def test_native_v3_encrypted_seal_roundtrip():
    """The native module's v3 sealing: encrypted blob restores the same
    key under the right secret and is refused otherwise."""
    import pytest as _pytest

    from minbft_tpu.usig import native

    if not native.available(auto_build=True):
        _pytest.skip("native USIG module unavailable")
    u = native.NativeEcdsaUSIG()
    blob = u.seal(secret=b"s3cret")
    assert blob[:4] == b"USG3"
    # plaintext layout differs: the v2 blob's DER must not appear
    assert u.seal()[4:] not in blob
    back = native.NativeEcdsaUSIG.from_sealed(blob, secret=b"s3cret")
    assert back.public_key == u.public_key
    assert back.epoch != u.epoch  # fresh epoch per init, as ever
    with _pytest.raises(Exception):
        native.NativeEcdsaUSIG.from_sealed(blob)
    with _pytest.raises(Exception):
        native.NativeEcdsaUSIG.from_sealed(blob, secret=b"nope")


@needs_cryptography
def test_wide_curve_keyspecs_roundtrip():
    """Round-4 verdict missing #2 (reference keymanager.go:169-241 keyspec
    breadth): P-384/P-521 keystores generate, save/load, and authenticate
    on the host path; the device path rejects them with a clear error."""
    import asyncio

    import pytest

    from minbft_tpu import api
    from minbft_tpu.sample.authentication.authenticator import SCHEMES
    from minbft_tpu.sample.authentication.keystore import (
        KeyStore,
        generate_testnet_keys,
    )

    for scheme, spec in (("ecdsa-p384", "ECDSA_P384"), ("ecdsa-p521", "ECDSA_P521")):
        store = generate_testnet_keys(2, n_clients=1, scheme=scheme, usig_spec="SOFT_ECDSA")
        loaded = KeyStore.from_dict(store.to_dict())
        assert loaded.scheme == scheme
        assert loaded.to_dict()["replica"]["keyspec"] == spec

        auth0 = loaded.replica_authenticator(0)
        auth1 = loaded.replica_authenticator(1)
        tag = auth0.generate_message_authen_tag(
            api.AuthenticationRole.REPLICA, b"payload"
        )

        async def check(a=auth1, t=tag):
            await a.verify_message_authen_tag(
                api.AuthenticationRole.REPLICA, 0, b"payload", t
            )
            bad = bytes([t[0] ^ 1]) + t[1:]
            with pytest.raises(api.AuthenticationError):
                await a.verify_message_authen_tag(
                    api.AuthenticationRole.REPLICA, 0, b"payload", bad
                )

        asyncio.run(check())

        # explicit device dispatch rejects loudly (no silent degradation)
        async def device_check(s=scheme):
            with pytest.raises(api.AuthenticationError, match="no TPU verify kernel"):
                await SCHEMES[s].verify(b"\x00", b"m", b"\x00", engine=object(), device=True)

        asyncio.run(device_check())


@needs_cryptography
def test_engine_wired_wide_curve_routes_to_host():
    """An engine-wired P-384 authenticator must route signatures to the
    host path (device_capable=False), not raise on every verification."""
    import asyncio

    from minbft_tpu import api
    from minbft_tpu.parallel import BatchVerifier
    from minbft_tpu.sample.authentication.keystore import generate_testnet_keys

    store = generate_testnet_keys(2, n_clients=1, scheme="ecdsa-p384", usig_spec="SOFT_ECDSA")
    eng = BatchVerifier(max_batch=8)
    auth0 = store.replica_authenticator(0, engine=eng, batch_signatures=True)
    auth1 = store.replica_authenticator(1, engine=eng, batch_signatures=True)
    tag = auth0.generate_message_authen_tag(api.AuthenticationRole.REPLICA, b"m")

    async def check():
        await auth1.verify_message_authen_tag(
            api.AuthenticationRole.REPLICA, 0, b"m", tag
        )

    asyncio.run(check())
