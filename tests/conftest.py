"""Test configuration.

Tests run on the CPU JAX backend with 8 virtual devices — the "SIM mode" of
this build (the reference's analogue is running the SGX enclave in simulation
mode, reference usig/sgx/Makefile SGX_MODE=SIM): CI needs no TPU, while the
sharding/collective code paths still execute against a real 8-device mesh.

The environment may pre-register a TPU plugin via sitecustomize and pin
``JAX_PLATFORMS``; env vars alone therefore don't stick.  XLA_FLAGS must be
in place before the CPU client is (lazily) created, and the platform is
forced through ``jax.config`` which wins over the env var.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Event-loop matrix: MINBFT_UVLOOP=1 runs the whole selected suite under
# uvloop (CI's uvloop step re-runs the chaos seeds + the metrics endpoint
# this way), so event-loop-policy-sensitive code — the bundle-ingest tick
# loops, the stream pumps, the metrics server — is exercised on both
# loops.  Tests require the EXPLICIT opt-in (no auto-detect): the default
# suite must measure the stdlib loop every run, even on hosts where the
# perf extra happens to be installed.
from minbft_tpu.utils.loop import maybe_enable_uvloop, uvloop_requested  # noqa: E402

if uvloop_requested():
    maybe_enable_uvloop()


async def make_cluster(
    n=4, f=1, n_clients=1, usig_kind="hmac", cfg=None, wrap_conn=None,
    **auth_kw
):
    """Start an in-process cluster (the reference integration-test layout,
    core/integration_test.go:212-226).  Returns (replicas, client_auths,
    stubs, ledgers); caller stops the replicas.  Pass ``cfg`` to override
    the default long-timeout SimpleConfiger (e.g. short timeouts for
    view-change tests).  ``wrap_conn(replica_id, connector)`` wraps each
    replica's peer connector — the chaos tests use it to route every peer
    link through a testing.faultnet.FaultNet."""
    from minbft_tpu.core import new_replica
    from minbft_tpu.sample.authentication import new_test_authenticators
    from minbft_tpu.sample.config import SimpleConfiger
    from minbft_tpu.sample.conn.inprocess import (
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from minbft_tpu.sample.requestconsumer import SimpleLedger

    if cfg is None:
        cfg = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
    r_auths, c_auths = new_test_authenticators(
        n, n_clients=n_clients, usig_kind=usig_kind, **auth_kw
    )
    stubs = make_testnet_stubs(n)
    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        conn = InProcessPeerConnector(stubs)
        if wrap_conn is not None:
            conn = wrap_conn(i, conn)
        r = new_replica(i, cfg, r_auths[i], conn, ledgers[i])
        stubs[i].assign_replica(r)
        replicas.append(r)
    for r in replicas:
        await r.start()
    return replicas, c_auths, stubs, ledgers

# Persistent compilation cache: the crypto kernels are compile-dominated on
# the CPU backend (a cold ECDSA ladder compile is ~2 min), so warm CI runs
# should pay zero compiles.  Keyed by HLO, so kernel changes re-compile
# automatically.  Opt out with MINBFT_TEST_CACHE=0.
if os.environ.get("MINBFT_TEST_CACHE", "1") != "0":
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "MINBFT_TEST_CACHE_DIR",
            os.path.expanduser("~/.cache/minbft_jax_cache_tests"),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
