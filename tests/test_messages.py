"""Messages layer tests.

Mirrors the reference's protobuf round-trip tests
(reference messages/protobuf/*_test.go) plus authen-bytes invariants
(reference messages/authen.go).
"""

import pytest

from minbft_tpu import messages as msgs


def _sample_request(sig=b"\x01\x02"):
    return msgs.Request(client_id=3, seq=42, operation=b"op-bytes", signature=sig)


def _sample_prepare():
    return msgs.Prepare(
        replica_id=0,
        view=7,
        request=_sample_request(),
        ui=msgs.UI(counter=5, cert=b"cert!"),
    )


def _sample_commit():
    return msgs.Commit(replica_id=2, prepare=_sample_prepare(), ui=msgs.UI(9, b"c2"))


@pytest.mark.parametrize(
    "m",
    [
        msgs.Hello(replica_id=4),
        _sample_request(),
        msgs.Request(client_id=0, seq=0, operation=b"", signature=b""),
        msgs.Reply(replica_id=1, client_id=3, seq=42, result=b"res", signature=b"s"),
        _sample_prepare(),
        msgs.Prepare(replica_id=1, view=0, request=_sample_request(b""), ui=None),
        _sample_commit(),
        msgs.ReqViewChange(replica_id=1, new_view=2, signature=b"sig"),
    ],
)
def test_roundtrip(m):
    data = msgs.marshal(m)
    out = msgs.unmarshal(data)
    assert out == m
    assert msgs.marshal(out) == data


def test_roundtrip_preserves_embedding():
    c = msgs.unmarshal(msgs.marshal(_sample_commit()))
    assert isinstance(c, msgs.Commit)
    assert isinstance(c.prepare, msgs.Prepare)
    assert isinstance(c.prepare.request, msgs.Request)
    assert c.prepare.request.operation == b"op-bytes"
    assert c.prepare.ui.counter == 5


@pytest.mark.parametrize(
    "data",
    [
        b"",
        b"\xff",
        b"\x02\x00\x00\x00\x03",  # truncated request
        msgs.marshal(msgs.Hello(1)) + b"junk",  # trailing bytes
    ],
)
def test_unmarshal_rejects_malformed(data):
    with pytest.raises(msgs.CodecError):
        msgs.unmarshal(data)


def test_commit_must_embed_prepare():
    # Hand-craft a COMMIT embedding a REQUEST instead of a PREPARE.
    import struct

    inner = msgs.marshal(_sample_request())
    data = bytes([0x05]) + struct.pack(">I", 1) + struct.pack(">I", len(inner)) + inner
    data += struct.pack(">I", 0)
    with pytest.raises(msgs.CodecError):
        msgs.unmarshal(data)


def test_authen_bytes_deterministic_and_distinct():
    seen = set()
    for m in [
        _sample_request(),
        msgs.Reply(replica_id=1, client_id=3, seq=42, result=b"res"),
        _sample_prepare(),
        _sample_commit(),
        msgs.ReqViewChange(replica_id=1, new_view=2),
    ]:
        ab = msgs.authen_bytes(m)
        assert ab == msgs.authen_bytes(m)  # deterministic
        assert ab not in seen  # distinct across kinds
        seen.add(ab)
        assert len(msgs.authen_digest(m)) == 32


def test_authen_bytes_excludes_own_signature():
    # A message's own signature must not be covered by its authen bytes
    # (the signature is computed over them).
    r1 = _sample_request(sig=b"aaa")
    r2 = _sample_request(sig=b"bbb")
    assert msgs.authen_bytes(r1) == msgs.authen_bytes(r2)


def test_prepare_authen_covers_request_signature():
    # But a PREPARE's authen bytes DO cover the embedded request's signature
    # (the primary certifies the exact bytes it ordered).
    p1 = msgs.Prepare(replica_id=0, view=1, request=_sample_request(b"aaa"))
    p2 = msgs.Prepare(replica_id=0, view=1, request=_sample_request(b"bbb"))
    assert msgs.authen_bytes(p1) != msgs.authen_bytes(p2)


def test_commit_authen_covers_primary_counter():
    # reference messages/authen.go:70 — commit binds the primary's counter.
    p = _sample_prepare()
    c1 = msgs.Commit(replica_id=2, prepare=p)
    import copy

    p2 = copy.deepcopy(p)
    p2.ui.counter += 1
    c2 = msgs.Commit(replica_id=2, prepare=p2)
    assert msgs.authen_bytes(c1) != msgs.authen_bytes(c2)


def test_commit_authen_requires_prepare_ui():
    p = msgs.Prepare(replica_id=0, view=1, request=_sample_request(), ui=None)
    with pytest.raises(ValueError):
        msgs.authen_bytes(msgs.Commit(replica_id=2, prepare=p))


def test_stringify_smoke():
    for m in [
        msgs.Hello(1),
        _sample_request(),
        _sample_prepare(),
        _sample_commit(),
        msgs.Reply(replica_id=1, client_id=3, seq=2, result=b"x"),
        msgs.ReqViewChange(replica_id=1, new_view=2),
    ]:
        s = msgs.stringify(m)
        assert s.startswith("<") and s.endswith(">")


def test_malformed_ui_raises_codec_error():
    # A 1-7 byte UI field must surface as CodecError, not bare ValueError
    # (error contract of unmarshal for attacker-crafted wire bytes).
    import struct

    req = msgs.marshal(_sample_request())
    data = (
        bytes([0x04])
        + struct.pack(">I", 0)
        + struct.pack(">Q", 1)
        + struct.pack(">I", len(req))
        + req
        + struct.pack(">I", 3)
        + b"abc"
    )
    with pytest.raises(msgs.CodecError):
        msgs.unmarshal(data)


def test_out_of_range_fields_raise_codec_error():
    with pytest.raises(msgs.CodecError):
        msgs.marshal(msgs.Request(client_id=-1, seq=0, operation=b""))
    with pytest.raises(msgs.CodecError):
        msgs.marshal(msgs.Request(client_id=0, seq=2**64, operation=b""))
