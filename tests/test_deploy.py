"""Deployment-artifact tests (VERDICT r2 #9): the deploy/ scripts are
executed, not just shipped.

- ``deploy/local_testnet.sh`` runs for real: replica processes over gRPC
  sockets, a request committed through them (the reference documents this
  flow manually, README.md:411-458).
- The docker-compose stack can't run inside CI (no dockerd), so its parts
  are checked for consistency and the entrypoint's shared-scaffold lock
  pattern (reference sample/docker/docker-entrypoint.sh) is executed
  directly with two racing instances.
"""

import os
import subprocess

import yaml

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DEPLOY = os.path.join(REPO, "deploy")


def _env():
    return dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )


def test_local_testnet_script_commits(tmp_path):
    res = subprocess.run(
        ["bash", os.path.join(DEPLOY, "local_testnet.sh"), "3", str(tmp_path)],
        env=_env(), capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "testnet OK" in res.stdout
    # the request subcommand printed the committed block digest
    digests = [l for l in res.stdout.splitlines() if len(l.strip()) == 64]
    assert digests, res.stdout


def test_docker_artifacts_consistent():
    """The compose stack's pieces agree with each other and with the
    entrypoint's hostname-rewrite convention."""
    for script in ("docker-entrypoint.sh", "local_testnet.sh"):
        path = os.path.join(DEPLOY, script)
        shell = "bash" if script == "local_testnet.sh" else "sh"
        res = subprocess.run([shell, "-n", path], capture_output=True, text=True)
        assert res.returncode == 0, f"{script}: {res.stderr}"

    compose = yaml.safe_load(open(os.path.join(DEPLOY, "docker-compose.yml")))
    services = compose["services"]
    # the entrypoint rewrites peers[] to replica%d hostnames — the compose
    # service names must match that convention
    replica_services = sorted(s for s in services if s.startswith("replica"))
    assert replica_services == ["replica0", "replica1", "replica2"]
    for name in replica_services:
        build = services[name].get("build", {})
        context = os.path.normpath(
            os.path.join(DEPLOY, build.get("context", "."))
        )
        dockerfile = build.get("dockerfile", "Dockerfile")
        assert os.path.exists(os.path.join(context, dockerfile))
    assert os.path.exists(os.path.join(DEPLOY, "docker-entrypoint.sh"))
    dockerfile_text = open(os.path.join(DEPLOY, "Dockerfile")).read()
    assert "docker-entrypoint.sh" in dockerfile_text


def test_entrypoint_scaffold_lock(tmp_path):
    """Execute the entrypoint's once-only scaffold under contention: two
    racing instances, one scaffolds, both proceed; the lock directory is
    gone afterwards and the peers are rewritten to service hostnames.

    The only modification to the script under test is the data directory
    (/data is the container volume; tests must stay inside the repo/tmp).
    """
    script = open(os.path.join(DEPLOY, "docker-entrypoint.sh")).read()
    assert "cd /data" in script
    ported = script.replace("cd /data", f'cd "{tmp_path}"')
    script_path = tmp_path / "entrypoint-under-test.sh"
    script_path.write_text(ported)

    procs = [
        subprocess.Popen(
            ["sh", str(script_path), "--help"],
            env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for _ in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()
        assert b"usage" in out.lower() or b"usage" in err.lower()

    cfg = yaml.safe_load(open(tmp_path / "consensus.yaml"))
    assert [p["addr"] for p in cfg["peers"]] == [
        f"replica{i}:{42610 + i}" for i in range(3)
    ]
    # per-replica stripped keystores written; shared lock released
    for i in range(3):
        assert (tmp_path / f"keys.replica{i}.yaml").exists()
    assert not (tmp_path / ".scaffold.lock").exists()
