"""Bounded-memory soak: protocol state must stay O(clients + f + in-flight).

The reference keeps one last-reply slot per client
(reference core/internal/clientstate/reply.go:25-60) and the commitment
counter keeps only the f highest primary-CVs of the current view
(reference core/commit.go:177-201).  These tests drive request volumes far
beyond any container bound and assert nothing grew with request count —
the round-2 verdict's leak list (CommitmentCollector._done,
ClientState._replies/_reply_events/_prepared) stays gone.
"""

import asyncio

from minbft_tpu.core.commit import CommitmentCollector
from minbft_tpu.core.internal.clientstate import ClientState, ClientStates
from minbft_tpu.core.internal.timer import FakeTimerProvider
from minbft_tpu.utils import hostcrypto

# The cluster soaks sign/verify every REQUEST and REPLY.  With OpenSSL
# (the `cryptography` package — CI installs it) that is microseconds per
# op; on a container without it the pure-Python fallback costs tens of
# milliseconds per op, and a 2000-request soak becomes a multi-minute
# crypto benchmark that blows the suite's time budget without testing
# anything extra — the bounded-container/GC properties are scale-free
# past a few checkpoint windows.  MINBFT_SOAK_REQUESTS/
# MINBFT_CHAOS_REQUESTS still force any scale anywhere.
_FULL_SCALE = hostcrypto._HAVE_OSSL


class _UI:
    def __init__(self, counter):
        self.counter = counter


class _Prepare:
    """Just the fields CommitmentCollector touches."""

    def __init__(self, view, cv):
        self.view = view
        self.ui = _UI(cv)
        self.requests = [("req", view, cv)]


def _container_sizes(c: CommitmentCollector) -> dict:
    return {
        "accepted": len(c._accepted),
        "highest": len(c._highest),
        "ready": len(c._ready),
        "next_exec": len(c._next_exec_cv),
    }


def test_collector_soak_50k_commitments_bounded():
    """n=4/f=1: 50k quorums (1 PREPARE + 2 COMMIT commitments each = 150k
    collect calls) execute exactly once, in order, with O(n + f) state."""
    executed = []

    async def run():
        collector = CommitmentCollector(1, lambda req: _record(req))

        async def _record(req):
            executed.append(req)

        n_requests = 50_000
        for cv in range(1, n_requests + 1):
            prepare = _Prepare(0, cv)
            # primary 0's own PREPARE + commits from backups 1 and 2
            # (f+1 = 2 reached at the second commitment)
            await collector.collect(0, prepare)
            await collector.collect(1, prepare)
            await collector.collect(2, prepare)
            # straggler replica 3 trails a few CVs behind
            if cv > 3:
                await collector.collect(3, _Prepare(0, cv - 3))
        sizes = _container_sizes(collector)
        assert sizes == {"accepted": 4, "highest": 1, "ready": 0, "next_exec": 1}
        assert len(executed) == n_requests
        # strictly in primary-CV order
        assert executed[0][2] == 1 and executed[-1][2] == n_requests

    asyncio.run(run())


def test_collector_release_in_order_across_suspended_execution():
    """cv2's quorum completing while cv1 is still EXECUTING (consumer
    suspended mid-deliver) must not overtake it: execution stays strictly
    in primary-CV order."""
    executed = []

    async def run():
        gate = asyncio.Event()

        async def exec_slow(req):
            if req[2] == 1:
                await gate.wait()  # cv1's delivery is suspended
            executed.append(req[2])

        collector = CommitmentCollector(1, exec_slow)
        p1, p2 = _Prepare(0, 1), _Prepare(0, 2)
        t1 = asyncio.create_task(collector.collect(1, p1))
        t2 = asyncio.create_task(collector.collect(0, p1))  # quorum cv1
        await asyncio.sleep(0)  # let cv1 enter (and block in) execution
        await collector.collect(1, p2)
        t3 = asyncio.create_task(collector.collect(0, p2))  # quorum cv2
        await asyncio.sleep(0)
        assert executed == []  # cv2 must be parked behind suspended cv1
        gate.set()
        await asyncio.gather(t1, t2, t3)
        assert executed == [1, 2]

    asyncio.run(run())


def test_clientstate_soak_replies_bounded():
    """50k request/reply cycles leave exactly one reply slot and scalar
    watermarks; a late retry of the last seq still gets the reply."""

    async def run():
        st = ClientState(FakeTimerProvider())
        n = 50_000
        for seq in range(1, n + 1):
            assert await st.capture_request_seq(seq)
            st.prepare_request_seq(seq)
            st.add_reply(seq, ("reply", seq))
            assert st.retire_request_seq(seq)
            await st.release_request_seq(seq)
        # bounded: the reply window never exceeds its cap, and the floor
        # trails the head by exactly the window
        assert len(st._replies) == st._REPLY_WINDOW
        assert st._reply_floor == n - st._REPLY_WINDOW
        # duplicate-request behavior: a late retry of the LAST request
        # (or anything still in the window) still gets its reply...
        assert await st.reply_for(n) == ("reply", n)
        assert await st.reply_for(n - 5) == ("reply", n - 5)
        # ...and a stale seq pruned out of the window yields None
        # (reference ReplyChannel closes without sending, reply.go:74-79)
        assert await st.reply_for(5) is None

    asyncio.run(run())


def test_reply_window_survives_pipelined_bursts():
    """Regression (round-3 deadlock): with a pipelined client, replies k
    and k+1 can both land BEFORE the waiter for k wakes — a single
    last-reply slot skips k and strands the waiter forever.  The window
    must deliver both."""

    async def run():
        st = ClientState(FakeTimerProvider())
        got = {}

        async def waiter(seq):
            got[seq] = await st.reply_for(seq)

        tasks = [asyncio.create_task(waiter(s)) for s in (1, 2, 3)]
        await asyncio.sleep(0)  # all three waiters parked
        # burst: all three replies land in one loop turn
        st.add_reply(1, "r1")
        st.add_reply(2, "r2")
        st.add_reply(3, "r3")
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=2)
        assert got == {1: "r1", 2: "r2", 3: "r3"}

    asyncio.run(run())


def test_clientstates_provider_is_per_client_only():
    states = ClientStates(FakeTimerProvider())
    for cid in range(7):
        states.client(cid)
    states.client(3)  # repeat access allocates nothing new
    assert len(states._clients) == 7


def test_cluster_containers_bounded_after_many_requests():
    """Full n=4 in-process cluster: after a few hundred committed requests
    every replica's protocol containers are request-count independent."""
    async def run():
        # Use a modest count (the 50k-scale bound is proven above at unit
        # level; this asserts the wiring has no other accumulation point).
        from minbft_tpu.client import new_client
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.authentication import new_test_authenticators
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import (
            InProcessClientConnector,
            InProcessPeerConnector,
            make_testnet_stubs,
        )
        from minbft_tpu.sample.requestconsumer import SimpleLedger

        n, f, n_requests = 4, 1, 300
        configer = SimpleConfiger(n=n, f=f, timeout_request=30.0, timeout_prepare=15.0)
        replica_auths, client_auths = new_test_authenticators(n, n_clients=1)
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i, configer, replica_auths[i], InProcessPeerConnector(stubs), ledgers[i]
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()
        client = new_client(
            0, n, f, client_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        for k in range(n_requests):
            await asyncio.wait_for(client.request(b"x%d" % k), timeout=30)
        for _ in range(200):
            if all(lg.length == n_requests for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        try:
            for r in replicas:
                h = r.handlers
                collector = h.commitment_collector
                sizes = _container_sizes(collector)
                assert sizes["accepted"] <= n
                assert sizes["highest"] == f
                assert sizes["ready"] == 0
                assert sizes["next_exec"] == 1
                # one client, O(1) state per client
                clients = dict(h.client_states.all())
                assert set(clients) == {0}
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()

    asyncio.run(run())


def test_cluster_gc_soak_pipelined():
    """Sustained pipelined traffic with checkpointing: 2,000 requests from
    8 concurrent clients at checkpoint_period=100 — every replica's
    broadcast log stays O(checkpoint window) (the round-4 GC), all state
    machines converge, and the VIEW-CHANGE a replica would emit afterwards
    is scoped (log_base > 0).  MINBFT_SOAK_REQUESTS scales it up for a
    full 50k-request soak outside CI."""

    async def run():
        import os

        from minbft_tpu.client import new_client
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.authentication import new_test_authenticators
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import (
            InProcessClientConnector,
            InProcessPeerConnector,
            make_testnet_stubs,
        )
        from minbft_tpu.sample.requestconsumer import SimpleLedger

        n, f = 4, 1
        n_requests = int(
            os.environ.get(
                "MINBFT_SOAK_REQUESTS", "2000" if _FULL_SCALE else "320"
            )
        )
        n_clients = 8
        configer = SimpleConfiger(
            n=n, f=f, checkpoint_period=100,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replica_auths, client_auths = new_test_authenticators(
            n, n_clients=n_clients
        )
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i, configer, replica_auths[i], InProcessPeerConnector(stubs),
                ledgers[i],
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()
        clients = []
        for c in range(n_clients):
            cl = new_client(
                c, n, f, client_auths[c], InProcessClientConnector(stubs),
                seq_start=0,
            )
            await cl.start()
            clients.append(cl)

        per_client = n_requests // n_clients

        async def drive(cl):
            depth = 8
            for k0 in range(0, per_client, depth):
                await asyncio.gather(
                    *[
                        asyncio.wait_for(cl.request(b"s%d" % k), 120)
                        for k in range(k0, min(k0 + depth, per_client))
                    ]
                )

        try:
            await asyncio.gather(*[drive(cl) for cl in clients])
            total = per_client * n_clients
            for _ in range(400):
                if all(lg.length >= total for lg in ledgers):
                    break
                await asyncio.sleep(0.05)
            assert all(lg.length >= total for lg in ledgers), [
                lg.length for lg in ledgers
            ]
            digests = {lg.state_digest() for lg in ledgers}
            assert len(digests) == 1
            for r in replicas:
                h = r.handlers
                # without GC the log would hold >= one certified entry per
                # request; the window keeps it two orders smaller
                assert len(h.message_log) < 150, (
                    f"replica {r.id}: {len(h.message_log)} log entries "
                    f"after {total} requests"
                )
                assert h._own_log_base[0] > 0
                assert h.metrics.counters.get("log_truncations", 0) > 0
        finally:
            for cl in clients:
                await cl.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(run())


def test_chaos_reconnect_soak_pipelined():
    """Pipelined traffic while EVERY client stream dies after each 25
    delivered frames, with reads mixed in: the redial loop's queue swap +
    pending re-send must hold up under sustained load without losing,
    duplicating, or wedging anything.  MINBFT_CHAOS_REQUESTS scales it up
    outside CI (default 600: ~3s)."""

    async def run():
        import os
        import struct

        from minbft_tpu.client import new_client
        from test_client_robustness import _ChaosClientConnector
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector
        from conftest import make_cluster

        n_requests = int(
            os.environ.get(
                "MINBFT_CHAOS_REQUESTS", "600" if _FULL_SCALE else "144"
            )
        )
        n_clients = 6
        replicas, c_auths, stubs, ledgers = await make_cluster(
            n_clients=n_clients
        )
        clients = []
        conns = []
        # The drop threshold counts FRAMES, and replies pack many-per-
        # frame under pipelining — a stream serving 24 requests delivers
        # only ~6 frames, so the reduced-scale run must drop earlier or
        # the "every connector actually dropped" assert below goes
        # vacuous (0 drops = the chaos path never ran at all).
        frames_per_life = 25 if _FULL_SCALE else 3
        for c in range(n_clients):
            conn = _ChaosClientConnector(
                InProcessClientConnector(stubs), frames_per_life
            )
            conns.append(conn)
            cl = new_client(
                c, 4, 1, c_auths[c], conn, seq_start=0, max_inflight=8
            )
            await cl.start()
            clients.append(cl)

        per_client = n_requests // n_clients

        async def drive(cl):
            depth = 8  # real pipelining: several writes pending per drop
            for k0 in range(0, per_client, depth):
                await asyncio.gather(
                    *[
                        asyncio.wait_for(cl.request(b"c%d" % k), 120)
                        for k in range(k0, min(k0 + depth, per_client))
                    ]
                )
                # a read rides the same flaky streams after each window;
                # the client completed k0+depth writes, so its own-session
                # floor is AT LEAST that many blocks (others add more)
                done = min(k0 + depth, per_client)
                head = await asyncio.wait_for(
                    cl.request(b"head", read_only=True, read_timeout=0.5),
                    120,
                )
                assert struct.unpack(">Q", head[:8])[0] >= done
        try:
            await asyncio.gather(*[drive(cl) for cl in clients])
            total = per_client * n_clients
            for _ in range(400):
                if all(lg.length == total for lg in ledgers):
                    break
                await asyncio.sleep(0.05)
            # exactly-once: chaos re-sends never duplicate an execution
            assert all(lg.length == total for lg in ledgers), [
                lg.length for lg in ledgers
            ]
            assert len({lg.state_digest() for lg in ledgers}) == 1
            assert all(c.drops > 0 for c in conns), [c.drops for c in conns]
        finally:
            for cl in clients:
                await cl.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(run())
