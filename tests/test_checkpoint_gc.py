"""Checkpoint phase 2 done-criteria (the reference's top roadmap item,
README.md:492-493): logs stay O(checkpoint window) under sustained
traffic, view changes after long histories ship bounded VIEW-CHANGE
messages (scoped by the checkpoint certificate instead of re-shipping
genesis), and a replica with no state joins the cluster through
certified state transfer."""

import asyncio

from conftest import make_cluster
from minbft_tpu.messages import ViewChange, marshal


async def _commit(client, count, tag=b"op"):
    for k in range(count):
        r = await asyncio.wait_for(client.request(tag + b"-%d" % k), 30)
        assert r


async def _joiner_cluster(cfg, n=4, f=1, offline=(3,)):
    """Cluster with some replicas held OFFLINE (their auths/stubs/ledgers
    exist so the test can start them later as late joiners).  TOFU
    anchors, not pinned IDs: a deployed keystore captures peer epochs
    trust-on-first-use — the capture-floor machinery both joiner tests
    exist to pin (pinned IDs masked the round-5 deadlock).  Returns
    (replicas, r_auths, c_auths, stubs, ledgers)."""
    from minbft_tpu.core import new_replica
    from minbft_tpu.sample.authentication import new_test_authenticators
    from minbft_tpu.sample.conn.inprocess import (
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from minbft_tpu.sample.requestconsumer import SimpleLedger

    r_auths, c_auths = new_test_authenticators(
        n, n_clients=1, usig_kind="hmac", tofu_anchors=True
    )
    stubs = make_testnet_stubs(n)
    ledgers = [SimpleLedger() for _ in range(n)]
    replicas = []
    for i in range(n):
        if i in offline:
            continue
        r = new_replica(
            i, cfg, r_auths[i], InProcessPeerConnector(stubs), ledgers[i]
        )
        stubs[i].assign_replica(r)
        replicas.append(r)
    for r in replicas:
        await r.start()
    return replicas, r_auths, c_auths, stubs, ledgers


def test_log_stays_bounded_under_checkpointed_traffic():
    """With checkpoint_period=10, 150 serial requests leave every
    replica's broadcast log at O(window) — the covered prefix is dropped
    behind the stable certificate (without GC each replica's own log
    would hold one certified entry per request)."""

    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, checkpoint_period=10,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(n=4, f=1, cfg=cfg)
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        try:
            await _commit(client, 150)
            # let the final checkpoint round settle
            await asyncio.sleep(0.3)
            for r in replicas:
                h = r.handlers
                assert h.metrics.counters.get("log_truncations", 0) > 0, (
                    f"replica {r.id} never truncated"
                )
                # own log held ~150 certified entries without GC; with a
                # 10-request window it must stay a small multiple of it
                assert len(h.message_log) < 60, (
                    f"replica {r.id} log has {len(h.message_log)} entries"
                )
                assert h._own_log_base[0] > 0
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(scenario())


def test_view_change_after_checkpointing_is_scoped():
    """After 60 checkpointed requests, a primary crash recovers through
    VIEW-CHANGEs that carry a truncation base + certificate and a log
    bounded by the checkpoint window — not the 60-request history — and
    the cluster commits in the new view."""

    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, checkpoint_period=10,
            timeout_request=0.8, timeout_prepare=0.4, timeout_viewchange=3.0,
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(n=4, f=1, cfg=cfg)
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        try:
            await _commit(client, 60)
            stubs[0].crash()
            await replicas[0].stop()
            r1 = await asyncio.wait_for(client.request(b"after-crash"), 30)
            assert r1

            vcs = [
                m
                for r in replicas[1:]
                for m in r.handlers.message_log.snapshot()
                if isinstance(m, ViewChange)
            ]
            assert vcs, "no VIEW-CHANGE found in any survivor log"
            for vc in vcs:
                assert vc.log_base > 0, "VIEW-CHANGE shipped from genesis"
                assert vc.checkpoint_cert, "truncated VIEW-CHANGE without cert"
                # the log covers the post-checkpoint window, not history:
                # ~60 committed requests would mean >120 entries untruncated
                assert len(vc.log) < 60, f"unscoped log: {len(vc.log)} entries"
                assert len(marshal(vc)) < 64 * 1024, "oversized VIEW-CHANGE"
            # steady state in the new view
            r2 = await asyncio.wait_for(client.request(b"steady"), 30)
            assert r2
        finally:
            await client.stop()
            for r in replicas[1:]:
                await r.stop()
        return True

    assert asyncio.run(scenario())


def test_wiped_replica_joins_via_state_transfer():
    """A replica with no state (never ran; peers have already truncated
    the history it would need) joins the cluster: LOG-BASE announcements
    fast-forward its per-peer capture, the certified snapshot installs
    the application state + watermarks, and it then executes live traffic
    to the same state digest as the rest."""

    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import (
            InProcessClientConnector,
            InProcessPeerConnector,
        )

        n, f = 4, 1
        cfg = SimpleConfiger(
            n=n, f=f, checkpoint_period=10,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replicas, r_auths, c_auths, stubs, ledgers = await _joiner_cluster(cfg)
        client = new_client(0, n, f, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        late = None
        try:
            await _commit(client, 40)
            await asyncio.sleep(0.3)
            # peers truncated the history replica 3 would need
            assert all(
                r.handlers._own_log_base[0] > 0 for r in replicas
            ), "peers never truncated; the join below would not need transfer"

            # replica 3 joins from nothing
            late = new_replica(
                3, cfg, r_auths[3], InProcessPeerConnector(stubs), ledgers[3]
            )
            stubs[3].assign_replica(late)
            await late.start()

            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if late.handlers.metrics.counters.get("state_transfers", 0):
                    break
                await asyncio.sleep(0.05)
            assert late.handlers.metrics.counters.get("state_transfers", 0), (
                "late replica never completed state transfer"
            )

            # it now follows live traffic to the same state
            await _commit(client, 10, tag=b"post-join")
            deadline = asyncio.get_running_loop().time() + 20
            target = None
            while asyncio.get_running_loop().time() < deadline:
                target = replicas[0].handlers.consumer.state_digest()
                if (
                    ledgers[3].length > 0
                    and ledgers[3].state_digest() == target
                    and all(
                        lg.state_digest() == target for lg in ledgers[1:3]
                    )
                ):
                    break
                await asyncio.sleep(0.05)
            assert ledgers[3].state_digest() == target, (
                f"late replica at {ledgers[3].length} blocks, "
                f"digest mismatch"
            )
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
            if late is not None:
                await late.stop()
        return True

    assert asyncio.run(scenario())


def test_checkpointing_stays_aligned_with_ordered_reads_interleaved():
    """Ordered reads (read_mode=2) count toward the checkpoint period like
    any delivered request — deterministically on every replica — but leave
    state untouched.  Interleaving them with writes across checkpoint
    boundaries must keep checkpoints stabilizing (digests agree: reads
    mutate nothing) and GC truncating."""

    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import InProcessClientConnector

        cfg = SimpleConfiger(
            n=4, f=1, checkpoint_period=8,
            timeout_request=60.0, timeout_prepare=30.0,
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(n=4, f=1, cfg=cfg)
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        try:
            for i in range(30):
                await asyncio.wait_for(client.request(b"w-%d" % i), 30)
                # read_timeout=0: wait_for(..., 0) times out before any
                # reply can arrive, so the ORDERED fallback fires
                # deterministically — every read crosses checkpoint
                # boundaries as an execution
                await asyncio.wait_for(
                    client.request(b"head", read_only=True, read_timeout=0),
                    30,
                )
            await asyncio.sleep(0.3)
            digests = {lg.state_digest() for lg in ledgers}
            assert len(digests) == 1, "replicas diverged"
            assert all(lg.length == 30 for lg in ledgers), [
                lg.length for lg in ledgers
            ]
            for r in replicas:
                h = r.handlers
                # exactly 60 executions (30 writes + 30 ordered reads) at
                # period 8: checkpoints fired and GC ran
                assert h.checkpoint_emitter.count == 60, h.checkpoint_emitter.count
                assert h.metrics.counters.get("log_truncations", 0) > 0, (
                    f"replica {r.id} never truncated"
                )
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(scenario())


def test_replay_joiner_reexecutes_ordered_reads():
    """With NO checkpointing (no truncation), a late joiner catches up by
    pure log replay — re-executing ordered reads at their slots via
    query(), which must reproduce the same state digest (reads at the same
    log position see the same state) and mutate nothing."""

    async def scenario():
        from minbft_tpu.client import new_client
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import (
            InProcessClientConnector,
            InProcessPeerConnector,
        )

        n, f = 4, 1
        cfg = SimpleConfiger(
            n=n, f=f, timeout_request=60.0, timeout_prepare=30.0
        )
        replicas, r_auths, c_auths, stubs, ledgers = await _joiner_cluster(cfg)
        client = new_client(0, n, f, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        late = None
        try:
            for i in range(10):
                await asyncio.wait_for(client.request(b"w-%d" % i), 30)
                # deterministic ORDERED read (see the interleaved test):
                # lands in the log the joiner will replay
                await asyncio.wait_for(
                    client.request(b"head", read_only=True, read_timeout=0),
                    30,
                )

            late = new_replica(
                3, cfg, r_auths[3], InProcessPeerConnector(stubs), ledgers[3]
            )
            stubs[3].assign_replica(late)
            await late.start()

            # poll the EXECUTION counter (the last replayed entry is a
            # read, which never bumps ledger length)
            deadline = asyncio.get_running_loop().time() + 20
            while asyncio.get_running_loop().time() < deadline:
                if late.handlers.metrics.counters.get("requests_executed") == 20:
                    break
                await asyncio.sleep(0.05)
            # replayed reads counted as executions on the joiner too
            # (checkpoint alignment if GC is ever enabled): 20 total
            assert late.handlers.metrics.counters.get("requests_executed") == 20
            assert ledgers[3].length == 10, ledgers[3].length
            assert ledgers[3].state_digest() == ledgers[0].state_digest()
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
            if late is not None:
                await late.stop()
        return True

    assert asyncio.run(scenario())
