"""Codec fuzzing: random and mutated wire bytes must either raise
CodecError or decode to a message that re-marshals canonically — never
crash with another exception, hang, or decode two distinct byte strings
ambiguously.  (The reference relies on protobuf's hardening; this build's
hand-rolled codec earns it here.)"""

import random

import pytest

from minbft_tpu.messages import CodecError, marshal, unmarshal
from minbft_tpu.messages.message import UI, Commit, Hello, Prepare, Reply, Request


def _sample_messages():
    req = Request(client_id=3, seq=9, operation=b"op-bytes", signature=b"sig")
    prep = Prepare(
        replica_id=0, view=0, requests=[req], ui=UI(counter=5, cert=b"cert")
    )
    return [
        Hello(replica_id=2),
        req,
        Reply(
            replica_id=1,
            client_id=3,
            seq=9,
            result=b"res",
            signature=b"s2",
            read_only=True,
        ),
        prep,
        Commit(replica_id=4, prepare=prep, ui=UI(counter=6, cert=b"c2")),
        Request(client_id=3, seq=10, operation=b"ro", read_mode=1),
    ]


def test_random_bytes_never_crash():
    rng = random.Random(1234)
    for _ in range(3000):
        data = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 80)))
        try:
            m = unmarshal(data)
        except CodecError:
            continue
        # decoded: must re-marshal canonically
        assert marshal(m) == data


@pytest.mark.parametrize("mi", range(6))
def test_mutated_wire_bytes_never_crash(mi):
    rng = random.Random(99 + mi)
    base = marshal(_sample_messages()[mi])
    for _ in range(800):
        data = bytearray(base)
        op = rng.randrange(3)
        if op == 0 and data:  # flip a byte
            i = rng.randrange(len(data))
            data[i] ^= rng.randrange(1, 256)
        elif op == 1:  # truncate
            data = data[: rng.randrange(len(data) + 1)]
        else:  # extend with junk
            data += bytes(rng.randrange(256) for _ in range(rng.randrange(1, 9)))
        try:
            m = unmarshal(bytes(data))
        except CodecError:
            continue
        assert marshal(m) == bytes(data)


def test_roundtrip_is_canonical_for_all_kinds():
    for m in _sample_messages():
        data = marshal(m)
        assert marshal(unmarshal(data)) == data


def test_multi_frame_roundtrip_and_malformed():
    """Transport frame coalescing: pack/split round-trips, bare frames
    pass through, and malformed containers raise CodecError instead of
    crashing the stream."""
    import pytest

    from minbft_tpu.messages import CodecError, pack_multi, split_multi

    frames = [b"\x02aaa", b"\x04b", b"\x05" + b"c" * 100]
    packed = pack_multi(frames)
    assert split_multi(packed) == frames
    # single frame stays bare (no container overhead)
    assert pack_multi([b"\x02xyz"]) == b"\x02xyz"
    assert split_multi(b"\x02xyz") == [b"\x02xyz"]

    for bad in (
        packed[:-2],                      # truncated payload
        packed[:5],                       # truncated length
        packed + b"!",                    # trailing bytes
        b"\xf0\xff\xff\xff\xff",          # absurd count
    ):
        with pytest.raises(CodecError):
            split_multi(bad)
