"""Device-utilization ledger tests (minbft_tpu/obs/ledger.py, ISSUE 14):
the factor-product identity pinned to fp tolerance, the lane-class sum
invariant, baseline/window semantics against a synthetic engine with
hand-computed numbers, the self-ceiling fallback, and a live
BatchVerifier pass to keep the synthetic stats shape honest."""

import asyncio
import time

import pytest

from minbft_tpu.obs.ledger import DeviceLedger


class _Stats:
    """Mutable stand-in for VerifyStats/SignStats: only the fields the
    ledger reads, so a field rename there breaks here loudly."""

    def __init__(self, items=0, batches=0, padded_lanes=0, memo_hits=0,
                 host_fallback_items=0, device_time_s=0.0):
        self.items = items
        self.batches = batches
        self.padded_lanes = padded_lanes
        self.memo_hits = memo_hits
        self.host_fallback_items = host_fallback_items
        self.device_time_s = device_time_s


class _Engine:
    def __init__(self):
        self.stats = {}
        self.sign_stats = {}


def _mk(verify=None, sign=None):
    eng = _Engine()
    for name, st in (verify or {}).items():
        eng.stats[name] = st
    for name, st in (sign or {}).items():
        eng.sign_stats[name] = st
    return eng


# ---------------------------------------------------------------------------
# window accounting


def test_window_fields_from_hand_computed_deltas():
    eng = _mk(verify={"hmac_sha256": _Stats(
        items=10, batches=2, padded_lanes=6, memo_hits=3,
        device_time_s=1.0,
    )})
    led = DeviceLedger(eng, now=100.0)
    st = eng.stats["hmac_sha256"]
    st.items += 90
    st.batches += 8
    st.padded_lanes += 30
    st.memo_hits += 20
    st.device_time_s += 4.0
    wins = led.snapshot(now=110.0)
    win = wins["verify:hmac_sha256"]
    assert win.wall_s == pytest.approx(10.0)
    assert win.busy_s == pytest.approx(4.0)
    assert win.idle_s == pytest.approx(6.0)
    assert win.useful_lanes == 90  # deltas, not totals: baseline excluded
    assert win.padded_lanes == 30
    assert win.memo_lanes == 20
    assert win.fallback_lanes == 0
    assert win.batches == 8
    assert win.dispatched_lanes == 120
    assert win.mean_batch == pytest.approx(90 / 8)


def test_lane_classes_sum_to_total_lanes():
    eng = _mk(
        verify={"v": _Stats(items=50, batches=5, padded_lanes=14,
                            memo_hits=9, device_time_s=0.5)},
        sign={"s": _Stats(items=40, batches=4, padded_lanes=8,
                          host_fallback_items=12, device_time_s=0.25)},
    )
    led = DeviceLedger(eng, now=0.0)
    eng.stats["v"].items += 100
    eng.stats["v"].padded_lanes += 28
    eng.stats["v"].memo_hits += 7
    eng.stats["v"].batches += 4
    eng.stats["v"].device_time_s += 1.0
    eng.sign_stats["s"].items += 60
    eng.sign_stats["s"].padded_lanes += 4
    eng.sign_stats["s"].host_fallback_items += 15
    eng.sign_stats["s"].batches += 3
    eng.sign_stats["s"].device_time_s += 0.5
    wins = led.snapshot(now=5.0)
    v = wins["verify:v"]
    assert (v.useful_lanes + v.padded_lanes + v.memo_lanes
            + v.fallback_lanes) == v.total_lanes == 100 + 28 + 7
    s = wins["sign:s"]
    # sign items count every accepted item; host-fallback lanes never
    # crossed the device, so useful excludes them
    assert s.useful_lanes == 60 - 15
    assert s.fallback_lanes == 15
    assert (s.useful_lanes + s.padded_lanes + s.memo_lanes
            + s.fallback_lanes) == s.total_lanes == 60 + 4


def test_busy_is_clamped_to_wall_but_raw_overlap_kept():
    """max_inflight overlap can stack dispatch spans past the clock; the
    busy fraction must stay <= 1 while the raw sum stays readable."""
    eng = _mk(verify={"v": _Stats()})
    led = DeviceLedger(eng, now=0.0)
    st = eng.stats["v"]
    st.items, st.batches, st.device_time_s = 64, 2, 7.5
    win = led.snapshot(now=5.0)["verify:v"]
    assert win.busy_s == pytest.approx(5.0)
    assert win.device_time_s == pytest.approx(7.5)
    assert win.idle_s == 0.0
    dec = led.decompose(win, ceiling=100.0, source="test")
    assert dec.busy_fraction <= 1.0


def test_idle_queues_are_skipped():
    eng = _mk(verify={"v": _Stats(items=5, batches=1, device_time_s=0.1),
                      "w": _Stats()})
    led = DeviceLedger(eng, now=0.0)
    assert led.snapshot(now=1.0) == {}  # no movement anywhere
    eng.stats["v"].items += 1
    eng.stats["v"].batches += 1
    wins = led.snapshot(now=2.0)
    assert set(wins) == {"verify:v"}  # "w" never moved


# ---------------------------------------------------------------------------
# the headroom identity


def test_factor_product_equals_effective_rate():
    """effective = ceiling x busy x fill x useful, EXACTLY (fp): the
    factors are defined so the identity telescopes, and this test is the
    tripwire against a future clamp breaking it."""
    eng = _mk(verify={"v": _Stats()})
    led = DeviceLedger(eng, now=0.0)
    st = eng.stats["v"]
    st.items, st.batches = 900, 30
    st.padded_lanes, st.memo_hits = 120, 55
    st.device_time_s = 3.2
    win = led.snapshot(now=12.0)["verify:v"]
    for ceiling in (500.0, 10_000.0, 123_456.0):
        dec = led.decompose(win, ceiling=ceiling, source="test")
        assert dec.product() == pytest.approx(
            dec.effective_per_sec, rel=1e-9
        )
        assert dec.effective_per_sec == pytest.approx(900 / 12.0)
    # fill may exceed 1.0 when the live run beats a noisy probe ceiling:
    # the identity holds BECAUSE it is unclamped
    dec_low = led.decompose(win, ceiling=10.0, source="test")
    assert dec_low.fill_efficiency > 1.0
    assert dec_low.product() == pytest.approx(dec_low.effective_per_sec)


def test_self_ceiling_fallback_reads_fill_one():
    """With no calibrated ceiling the window's own busy lane rate is the
    ceiling (source 'self'): fill == 1.0 by construction and the
    identity still holds."""
    eng = _mk(verify={"v": _Stats()})
    led = DeviceLedger(eng, now=0.0)
    st = eng.stats["v"]
    st.items, st.batches, st.padded_lanes = 80, 10, 20
    st.device_time_s = 2.0
    win = led.snapshot(now=8.0)["verify:v"]
    dec = led.decompose(win)
    assert dec.ceiling_source == "self"
    assert dec.fill_efficiency == pytest.approx(1.0)
    assert dec.product() == pytest.approx(dec.effective_per_sec)


def test_set_ceiling_is_used_and_stamped():
    eng = _mk(verify={"hmac_sha256": _Stats()})
    led = DeviceLedger(eng, now=0.0)
    led.set_ceiling("hmac_sha256", 50_000.0, "last_tpu:BENCH_r05.json")
    with pytest.raises(ValueError):
        led.set_ceiling("hmac_sha256", 0.0, "bad")
    st = eng.stats["hmac_sha256"]
    st.items, st.batches, st.device_time_s = 640, 10, 0.4
    keys = led.util_keys("e2e", "hmac_sha256", now=4.0)
    assert keys["e2e_util_ceiling_per_sec"] == 50_000.0
    assert keys["e2e_util_ceiling_source"] == "last_tpu:BENCH_r05.json"


def test_util_keys_schema_and_absent_queue():
    eng = _mk(
        verify={"hmac_sha256": _Stats()},
        sign={"ecdsa_p256": _Stats()},
    )
    led = DeviceLedger(eng, now=0.0)
    st = eng.stats["hmac_sha256"]
    st.items, st.batches, st.padded_lanes = 100, 5, 28
    st.memo_hits, st.device_time_s = 4, 1.5
    keys = led.util_keys("cfg", "hmac_sha256", now=10.0)
    assert set(keys) == {
        "cfg_util_busy", "cfg_util_fill", "cfg_util_useful",
        "cfg_util_effective_per_sec", "cfg_util_per_device_per_sec",
        "cfg_util_ceiling_per_sec", "cfg_util_ceiling_source",
        "cfg_util_idle_s", "cfg_util_lanes_useful",
        "cfg_util_lanes_padding", "cfg_util_lanes_memo",
        "cfg_util_lanes_fallback",
    }
    assert keys["cfg_util_lanes_useful"] == 100
    assert keys["cfg_util_lanes_padding"] == 28
    assert keys["cfg_util_lanes_memo"] == 4
    # a queue this window never touched yields NO keys — honest absence,
    # not zeros (benchgate only gates keys present in both artifacts)
    assert led.util_keys("cfg", "never_ran", now=10.0) == {}
    # sign-side lookup works through the same entry point
    sg = eng.sign_stats["ecdsa_p256"]
    sg.items, sg.batches, sg.host_fallback_items = 30, 3, 30
    sg.device_time_s = 0.0
    skeys = led.util_keys("cfg", "ecdsa_p256", now=10.0)
    assert skeys["cfg_util_lanes_fallback"] == 30
    assert skeys["cfg_util_lanes_useful"] == 0


def test_probe_ceiling_times_a_full_bucket():
    calls = []

    def dispatch(batch):
        calls.append(len(batch))
        time.sleep(0.002)

    rate = DeviceLedger.probe_ceiling(dispatch, ("k", "m", "s"), 64)
    assert calls == [64]  # exactly one full-bucket dispatch
    assert 0 < rate < 64 / 0.002  # bounded by the sleep floor


def test_per_device_rate_uses_mesh_width():
    eng = _mk(verify={"v": _Stats()})
    eng._mesh = type("M", (), {"size": 4})()
    led = DeviceLedger(eng, now=0.0)
    assert led.n_devices == 4
    st = eng.stats["v"]
    st.items, st.batches, st.device_time_s = 400, 10, 1.0
    win = led.snapshot(now=10.0)["verify:v"]
    dec = led.decompose(win, ceiling=1000.0, source="test")
    assert dec.per_device_effective_per_sec == pytest.approx(
        dec.effective_per_sec / 4
    )


# ---------------------------------------------------------------------------
# live engine: the synthetic stats shape must match reality


def test_ledger_on_a_live_batch_verifier():
    """Run real HMAC verifies through a BatchVerifier and check every
    invariant on the measured window — if VerifyStats renames a field,
    the synthetic tests above would silently test a fiction; this one
    cannot."""
    import hashlib
    import hmac as hmac_mod

    from minbft_tpu.parallel import BatchVerifier

    async def run():
        eng = BatchVerifier(max_batch=8, buckets=(8,))
        key = b"\x01" * 32

        def item(i: int):
            msg = i.to_bytes(32, "big")  # fixed-width: the codec packs
            return key, msg, hmac_mod.new(key, msg, hashlib.sha256).digest()

        # warm (outside the window): the ledger baseline must absorb it
        assert all(await asyncio.gather(
            *[eng.verify_hmac_sha256(*item(i)) for i in range(8)]
        ))
        led = DeviceLedger(eng)
        warm_items = eng.stats["hmac_sha256"].items
        oks = await asyncio.gather(
            *[eng.verify_hmac_sha256(*item(100 + i))
              for i in range(5)]  # sub-bucket: padding appears
        )
        assert all(oks)
        win = led.snapshot()["verify:hmac_sha256"]
        assert win.useful_lanes == eng.stats["hmac_sha256"].items - warm_items
        assert win.useful_lanes == 5
        assert win.busy_s <= win.wall_s
        assert (win.useful_lanes + win.padded_lanes + win.memo_lanes
                + win.fallback_lanes) == win.total_lanes
        dec = led.decompose(win, ceiling=100_000.0, source="test")
        assert dec.product() == pytest.approx(dec.effective_per_sec)
        # high-water-mark satellite: peaks read-and-reset on the engine
        peaks = eng.queue_depth_peaks(reset=True)
        assert peaks.get("hmac_sha256", 0) >= 1
        assert eng.queue_depth_peaks(reset=True)["hmac_sha256"] == 0

    asyncio.run(run())
