"""Multi-chip sharding tests on the 8-virtual-device CPU mesh.

Covers the path the driver's ``dryrun_multichip`` exercises (the batch axis
sharded over a 1-D ``jax.sharding.Mesh``) so sharding regressions are caught
in CI, not only by the driver.  The reference scales by adding gRPC-connected
replicas (reference sample/conn/grpc/); here the data-parallel scale axis is
a sharding annotation over the verification batch (SURVEY.md §2.8).
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minbft_tpu.ops import lowering, p256
from minbft_tpu.ops.hmac_sha256 import hmac_sign_kernel
from minbft_tpu.parallel import mesh as mesh_mod
from minbft_tpu.utils import hostcrypto as hc


@pytest.fixture(scope="module")
def mesh8():
    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must force 8 virtual CPU devices"
    return mesh_mod.make_mesh(devices[:8])


@pytest.fixture(scope="module", autouse=True)
def _loop_lowering():
    # Tiny shapes on virtual CPU devices: loop lowering compiles in seconds.
    lowering.set_mode("loop")
    yield
    lowering.set_mode(None)


@pytest.fixture(scope="module")
def ecdsa_kernel(mesh8):
    # One compiled kernel shared by all tests (one shape = one compile).
    return mesh_mod.sharded_ecdsa_kernel(mesh8)


def test_sharded_ecdsa_kernel(mesh8, ecdsa_kernel):
    batch = 16  # two lanes per device
    d, q = hc.keygen()
    digest = hashlib.sha256(b"mesh-test").digest()
    sig = hc.ecdsa_sign(d, digest)
    items = [(q, digest, sig)] * batch
    items[5] = (q, digest, (sig[0], sig[1] ^ 2))  # corrupted lane
    packed = jnp.asarray(p256.pack_arrays(p256.prepare_batch(items)))

    out = np.asarray(ecdsa_kernel(packed))

    expected = np.ones(batch, dtype=bool)
    expected[5] = False
    assert (out == expected).all()


def test_sharded_hmac_kernel(mesh8):
    batch = 16
    rng = np.random.default_rng(7)
    keys = jnp.asarray(rng.integers(0, 2**32, (batch, 8), dtype=np.uint32))
    msgs = jnp.asarray(rng.integers(0, 2**32, (batch, 8), dtype=np.uint32))
    macs = hmac_sign_kernel(keys, msgs)
    kernel = mesh_mod.sharded_hmac_kernel(mesh8)
    packed = jnp.concatenate([keys, msgs, jnp.asarray(macs)], axis=1)
    assert np.asarray(kernel(packed)).all()

    bad = np.asarray(macs).copy()
    bad[3, 0] ^= 1
    packed_bad = jnp.concatenate([keys, msgs, jnp.asarray(bad)], axis=1)
    out = np.asarray(kernel(packed_bad))
    expected = np.ones(batch, dtype=bool)
    expected[3] = False
    assert (out == expected).all()


def test_sharded_output_matches_host(mesh8, ecdsa_kernel):
    """Differential check: sharded kernel agrees with the host verifier."""
    batch = 16  # same shape as test_sharded_ecdsa_kernel: no extra compile
    rng_seed = 3
    items = []
    expected = []
    for i in range(batch):
        d, q = hc.keygen()
        digest = hashlib.sha256(b"lane-%d-%d" % (rng_seed, i)).digest()
        sig = hc.ecdsa_sign(d, digest)
        if i % 4 == 1:
            sig = (sig[0], sig[1] ^ 1)
        items.append((q, digest, sig))
        expected.append(hc.ecdsa_verify(q, digest, sig))
    packed = jnp.asarray(p256.pack_arrays(p256.prepare_batch(items)))
    out = np.asarray(ecdsa_kernel(packed))
    assert out.tolist() == expected


def test_engine_routes_through_mesh(mesh8):
    """BatchVerifier(mesh=...) serves verifications through the sharded
    kernels (VERDICT r2: the serving path, not just the raw kernels):
    buckets are rounded to mesh multiples and all three schemes verify
    correctly, including rejected lanes."""
    import asyncio

    from minbft_tpu.parallel import BatchVerifier

    engine = BatchVerifier(max_batch=16, buckets=(6, 16), mesh=mesh8)
    assert engine.buckets == (8, 16)  # rounded up to mesh multiples
    assert engine.mesh is mesh8

    d, q = hc.keygen()
    digest = hashlib.sha256(b"engine-mesh").digest()
    sig = hc.ecdsa_sign(d, digest)
    seed, pub = hc.ed25519_keygen()
    ed_sig = hc.ed25519_sign(seed, b"engine-mesh")
    key = b"k" * 32
    import hmac as hmac_mod

    mac = hmac_mod.new(key, digest, hashlib.sha256).digest()

    async def run():
        ok, bad = await asyncio.gather(
            engine.verify_ecdsa_p256(q, digest, sig),
            engine.verify_ecdsa_p256(q, digest, (sig[0], sig[1] ^ 2)),
        )
        assert ok and not bad
        ok, bad = await asyncio.gather(
            engine.verify_hmac_sha256(key, digest, mac),
            engine.verify_hmac_sha256(key, digest, b"\x00" * 32),
        )
        assert ok and not bad
        ok, bad = await asyncio.gather(
            engine.verify_ed25519(pub, b"engine-mesh", ed_sig),
            engine.verify_ed25519(pub, b"other", ed_sig),
        )
        assert ok and not bad

    asyncio.run(run())
    # the sharded kernels were actually used
    assert set(engine._sharded_kernels) >= {"ecdsa", "hmac", "ed25519"}


def test_sharded_sign_kernel(mesh8):
    """Sharded fixed-base k*G agrees with the host scalar multiplication."""
    from minbft_tpu.ops.limbs import from_limbs, to_limbs
    from minbft_tpu.parallel.mesh import sharded_ecdsa_sign_kernel

    kernel = sharded_ecdsa_sign_kernel(mesh8)
    batch = 16
    rng = np.random.default_rng(11)
    ks = [int(rng.integers(1, 2**62)) for _ in range(batch)]
    k_arr = np.stack([to_limbs(k) for k in ks]).astype(np.uint32)
    xz = np.asarray(kernel(jnp.asarray(k_arr)))  # [B, 2, 16]

    r_inv = pow(1 << 256, -1, hc.P)
    for i, k in enumerate(ks):
        xm, zm = from_limbs(xz[i, 0]), from_limbs(xz[i, 1])
        assert zm != 0
        xj, zj = xm * r_inv % hc.P, zm * r_inv % hc.P
        x_aff = xj * pow(zj * zj % hc.P, -1, hc.P) % hc.P
        assert x_aff == hc.scalar_mult(k, (hc.GX, hc.GY))[0]
