"""Protocol metrics (SURVEY.md §5: the reference has no observability; this
build's counters are load-bearing for the benchmark harness)."""

import asyncio

from minbft_tpu.utils.metrics import LatencyReservoir, ReplicaMetrics, aggregate


def test_latency_reservoir_stats():
    r = LatencyReservoir(capacity=8)
    for v in [0.01, 0.02, 0.03, 0.04]:
        r.observe(v)
    assert r.count == 4
    assert abs(r.mean_s - 0.025) < 1e-9
    assert r.percentile(0) == 0.01
    assert r.percentile(99) == 0.04
    # overflow decimates but keeps counting
    for v in [0.05] * 20:
        r.observe(v)
    assert r.count == 24
    assert r.percentile(99) == 0.05


def test_reservoir_percentile_caches_sorted_view():
    """percentile() used to re-sort the full reservoir on EVERY call
    (snapshot() asks for several percentiles back-to-back); the sorted
    view is now cached and invalidated by observe()."""
    r = LatencyReservoir(capacity=64)
    for v in [0.03, 0.01, 0.02]:
        r.observe(v)
    assert r._sorted is None  # built lazily, invalidated by observe
    assert r.percentile(50) == 0.02
    cached = r._sorted
    assert cached == [0.01, 0.02, 0.03]
    # a second percentile reuses the SAME list object — no re-sort
    assert r.percentile(99) == 0.03
    assert r._sorted is cached
    # observe invalidates; the next percentile reflects the new sample
    r.observe(0.005)
    assert r._sorted is None
    assert r.percentile(0) == 0.005
    # overflow path (reservoir replacement) invalidates too
    full = LatencyReservoir(capacity=4)
    for v in [0.1, 0.2, 0.3, 0.4]:
        full.observe(v)
    assert full.percentile(99) == 0.4
    for _ in range(64):
        full.observe(0.9)
    assert full._sorted is None
    assert full.percentile(99) == 0.9


def test_aggregate_sums_counters_and_averages_latency():
    a, b = ReplicaMetrics(), ReplicaMetrics()
    a.inc("requests_executed", 3)
    b.inc("requests_executed", 5)
    a.observe_execute(0.010)
    b.observe_execute(0.030)
    agg = aggregate([a.snapshot(), b.snapshot()])
    assert agg["requests_executed"] == 8
    assert abs(agg["execute_latency_mean_ms"] - 20.0) < 0.5


def test_execute_hist_mirrors_the_reservoir():
    """The mergeable log2 histogram (obs/hist.py, feeds the Prometheus
    exposition) observes every execution the reservoir does."""
    m = ReplicaMetrics()
    m.observe_execute(0.010)
    m.observe_execute(0.030)
    assert m.execute_hist.count == 2 == m.execute_latency.count
    assert abs(m.execute_hist.total_s - m.execute_latency.total_s) < 1e-12
    # log2 resolution: p99 within a factor of 2 above the exact value
    assert 0.03 <= m.execute_hist.percentile(99) <= 0.06


def test_cluster_populates_counters():
    """An in-process commit increments the protocol counters on every
    replica (requests_executed, prepares/commits sent, messages handled)."""

    async def run():
        from minbft_tpu.client import new_client
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.authentication import new_test_authenticators
        from minbft_tpu.sample.config import SimpleConfiger
        from minbft_tpu.sample.conn.inprocess import (
            InProcessClientConnector,
            InProcessPeerConnector,
            make_testnet_stubs,
        )
        from minbft_tpu.sample.requestconsumer import SimpleLedger

        n, f = 3, 1
        cfg = SimpleConfiger(n=n, f=f, timeout_request=60.0, timeout_prepare=30.0)
        r_auths, c_auths = new_test_authenticators(n, usig_kind="hmac")
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i, cfg, r_auths[i], InProcessPeerConnector(stubs), ledgers[i]
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()
        client = new_client(
            0, n, f, c_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        await asyncio.wait_for(client.request(b"count-me"), 30)
        for _ in range(200):
            if all(
                r.metrics.counters.get("requests_executed", 0) >= 1
                for r in replicas
            ):
                break
            await asyncio.sleep(0.02)

        for i, r in enumerate(replicas):
            snap = r.metrics.snapshot()
            assert snap.get("requests_executed", 0) >= 1, (i, snap)
            assert snap.get("messages_handled", 0) >= 1, (i, snap)
            assert snap.get("execute_latency_p50_ms", 0) >= 0
        # primary sent the PREPARE; backups sent COMMITs
        assert replicas[0].metrics.counters.get("prepares_sent", 0) >= 1
        assert all(
            r.metrics.counters.get("commits_sent", 0) >= 1 for r in replicas[1:]
        )
        # quorum accounting ran everywhere
        assert all(
            r.metrics.counters.get("commitments_counted", 0) >= 2 for r in replicas
        )

        await client.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_reservoir_is_uniform_over_the_whole_run():
    """Long-run percentiles must reflect the full stream, not the last
    `capacity` events (VERDICT r2: the old round-robin overwrite was
    recent-biased — a p99 after a slow warm-up read as the steady state)."""
    from minbft_tpu.utils.metrics import LatencyReservoir

    r = LatencyReservoir(capacity=1000)
    for _ in range(50_000):
        r.observe(0.001)
    for _ in range(50_000):
        r.observe(0.1)
    frac_slow = sum(1 for s in r._samples if s > 0.01) / len(r._samples)
    # uniform => ~0.5; the old recency-biased scheme gave 1.0
    assert 0.35 < frac_slow < 0.65, frac_slow
    assert r.count == 100_000 and abs(r.mean_s - 0.0505) < 0.001
