"""Batching engine tests: adaptive flush, bucket padding, correctness of
batched lane results, and the engine-wired cluster (the submit-batch-then-
resolve restructuring of the reference's serial verification)."""

import asyncio
import hashlib
import hmac as hmac_mod
import time

from minbft_tpu.parallel import BatchVerifier


def _hmac_item(i: int, valid: bool = True):
    key = hashlib.sha256(b"key-%d" % i).digest()
    msg = hashlib.sha256(b"msg-%d" % i).digest()
    mac = hmac_mod.new(key, msg, hashlib.sha256).digest()
    if not valid:
        mac = bytes([mac[0] ^ 1]) + mac[1:]
    return key, msg, mac


def test_single_item_flushes_on_timeout():
    async def run():
        eng = BatchVerifier(max_batch=64, max_delay=0.01)
        ok = await eng.verify_hmac_sha256(*_hmac_item(0))
        assert ok
        st = eng.stats["hmac_sha256"]
        assert st.batches == 1 and st.items == 1
        return eng

    asyncio.run(run())


def test_concurrent_items_coalesce_and_resolve_lanes():
    async def run():
        eng = BatchVerifier(max_batch=64, max_delay=0.01)
        tasks = [
            asyncio.create_task(eng.verify_hmac_sha256(*_hmac_item(i, valid=(i % 3 != 0))))
            for i in range(20)
        ]
        results = await asyncio.gather(*tasks)
        for i, ok in enumerate(results):
            assert ok == (i % 3 != 0), f"lane {i}"
        st = eng.stats["hmac_sha256"]
        assert st.items == 20
        # All 20 should coalesce into few batches (typically 1).
        assert st.batches <= 3

    asyncio.run(run())


def test_full_batch_flushes_immediately():
    async def run():
        eng = BatchVerifier(max_batch=8, max_delay=10.0)  # long delay: only
        # a full batch can flush it quickly
        tasks = [
            asyncio.create_task(eng.verify_hmac_sha256(*_hmac_item(i)))
            for i in range(8)
        ]
        done = await asyncio.wait_for(asyncio.gather(*tasks), timeout=5)
        assert all(done)

    asyncio.run(run())


def test_cluster_with_batching_engine():
    """n=3 cluster where every replica routes verification through its own
    BatchVerifier (HMAC USIG; CPU SIM mode)."""
    from minbft_tpu.client import new_client
    from minbft_tpu.core import new_replica
    from minbft_tpu.sample.authentication import new_test_authenticators
    from minbft_tpu.sample.config import SimpleConfiger
    from minbft_tpu.sample.conn.inprocess import (
        InProcessClientConnector,
        InProcessPeerConnector,
        make_testnet_stubs,
    )
    from minbft_tpu.sample.requestconsumer import SimpleLedger

    async def run():
        n, f = 3, 1
        engines = [BatchVerifier(max_batch=32, max_delay=0.005) for _ in range(n)]
        configer = SimpleConfiger(n=n, f=f, timeout_request=30.0, timeout_prepare=15.0)
        replica_auths, client_auths = new_test_authenticators(
            n, n_clients=1, usig_kind="hmac", engines=engines,
            batch_signatures=False,  # only the USIG path batches on CPU SIM
        )
        stubs = make_testnet_stubs(n)
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            r = new_replica(
                i, configer, replica_auths[i], InProcessPeerConnector(stubs), ledgers[i]
            )
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()
        client = new_client(
            0, n, f, client_auths[0], InProcessClientConnector(stubs), seq_start=0
        )
        await client.start()
        for k in range(3):
            await asyncio.wait_for(client.request(b"op-%d" % k), timeout=30)
        for _ in range(100):
            if all(lg.length == 3 for lg in ledgers):
                break
            await asyncio.sleep(0.05)
        await client.stop()
        for r in replicas:
            await r.stop()
        assert all(lg.length == 3 for lg in ledgers)
        # the engines actually batched something
        total = sum(
            st.items for e in engines for st in e.stats.values()
        )
        assert total > 0

    asyncio.run(run())


def test_hung_device_dispatch_falls_back_to_host():
    """A device dispatch that hangs (tunnel stall — observed live) must
    not wedge the verification queue: after dispatch_timeout the items
    are re-verified on host, and repeated hangs write the device off so
    later batches skip the wait entirely."""
    import asyncio
    import threading

    from minbft_tpu.parallel.engine import BatchVerifier

    async def scenario():
        engine = BatchVerifier(max_batch=8, dispatch_timeout=0.2)
        hang = threading.Event()

        def hanging_dispatch(items):
            hang.wait(30)  # simulates a stalled tunnel RPC
            raise AssertionError("unreachable in test")

        import numpy as np

        def host_fallback(items):
            return np.array([item == b"good" for item in items], dtype=bool)

        engine._host_fallback_for = lambda name: host_fallback
        q = engine._queue("ecdsa_p256", hanging_dispatch)

        good = asyncio.ensure_future(q.submit(b"good"))
        bad = asyncio.ensure_future(q.submit(b"bad"))
        ok, nok = await asyncio.wait_for(asyncio.gather(good, bad), 10)
        assert ok is True and nok is False
        assert q.stats.dispatch_timeouts == 1

        # two more hangs -> the device is written off; a later batch goes
        # straight to host (no 0.2s wait — assert by elapsed time)
        for _ in range(2):
            await asyncio.wait_for(q.submit(b"good-%d" % _), 10)
        assert q._device_written_off
        t0 = asyncio.get_running_loop().time()
        assert await asyncio.wait_for(q.submit(b"good"), 10) is True
        # memo hit or host path; either way well under the device timeout
        assert asyncio.get_running_loop().time() - t0 < 0.15
        hang.set()  # let the abandoned threads exit
        return True

    assert asyncio.run(scenario())


def test_garbage_flood_does_not_evict_good_verdicts():
    """Round-4 verdict weak #7: failed verdicts live in their own small
    LRU, so a flood of distinct garbage signatures cannot evict known-good
    verdicts from the memo and re-drive device traffic for them."""

    async def scenario():
        eng = BatchVerifier(max_batch=64, max_delay=0.0)
        good = _hmac_item(0)
        assert await eng.verify_hmac_sha256(*good) is True
        q = eng._queues["hmac_sha256"]
        flood = q._NEG_MEMO_CAP + 200
        bads = [_hmac_item(10_000 + i, valid=False) for i in range(flood)]
        results = await asyncio.gather(
            *[eng.verify_hmac_sha256(*b) for b in bads]
        )
        assert not any(results)
        # the flood stayed out of the positive memo and its own LRU is
        # bounded; the good verdict survived
        assert len(q._neg_memo) <= q._NEG_MEMO_CAP
        assert q._memo == {good: True}
        hits_before = q.stats.memo_hits
        assert await eng.verify_hmac_sha256(*good) is True
        assert q.stats.memo_hits == hits_before + 1, "good verdict re-verified"
        return True

    assert asyncio.run(scenario())


def test_written_off_device_reprobes_and_recovers():
    """ADVICE r4: the dispatch-hang write-off is not permanent — after the
    re-probe window one batch re-tries the device and restores the queue
    when it answers again."""
    import threading

    import numpy as np

    async def scenario():
        engine = BatchVerifier(max_batch=8, dispatch_timeout=0.05)
        healthy = threading.Event()

        def flaky_dispatch(items):
            if not healthy.is_set():
                healthy.wait(30)  # stalled tunnel until healed
            return np.array([True] * len(items), dtype=bool)

        engine._host_fallback_for = (
            lambda name: lambda items: np.array([True] * len(items), bool)
        )
        q = engine._queue("ecdsa_p256", flaky_dispatch)
        q._REPROBE_AFTER = 0.3

        for i in range(3):
            assert await asyncio.wait_for(q.submit(b"it-%d" % i), 10) is True
        assert q._device_written_off

        healthy.set()  # device heals while written off
        await asyncio.sleep(0.35)  # past the re-probe window
        # the live batch resolves immediately via the fallback; the probe
        # runs out-of-band and restores the device shortly after
        t0 = asyncio.get_running_loop().time()
        assert await asyncio.wait_for(q.submit(b"probe"), 10) is True
        assert asyncio.get_running_loop().time() - t0 < 2.0, (
            "live batch waited on the probe"
        )
        for _ in range(100):
            if not q._device_written_off:
                break
            await asyncio.sleep(0.05)
        assert not q._device_written_off, "re-probe did not restore device"
        assert q._device_ever_succeeded
        return True

    assert asyncio.run(scenario())


def test_first_dispatch_gets_cold_compile_headroom():
    """ADVICE r4: a slow-but-healthy FIRST dispatch (cold kernel compile)
    must not count as a hang — the first-dispatch timeout is stretched,
    and only post-success dispatches run on the base timeout."""
    import numpy as np

    async def scenario():
        engine = BatchVerifier(max_batch=8, dispatch_timeout=0.15)

        def slow_dispatch(items):
            time.sleep(0.3)  # longer than base, within 4x headroom
            return np.array([True] * len(items), dtype=bool)

        engine._host_fallback_for = (
            lambda name: lambda items: np.array([False] * len(items), bool)
        )
        q = engine._queue("ecdsa_p256", slow_dispatch)
        # device verdict (True), NOT the fallback (False): no timeout fired
        assert await asyncio.wait_for(q.submit(b"cold"), 10) is True
        assert q.stats.dispatch_timeouts == 0
        assert q._device_ever_succeeded
        return True

    assert asyncio.run(scenario())


def test_host_prep_time_populated_for_device_schemes():
    """Round-6 prep/device split: every device dispatch accounts its host
    prep (pack) time separately, so host_prep_time_s is non-zero whenever
    a batch went through a device queue — the measurement bench.py turns
    into *_prep_share."""

    async def run():
        eng = BatchVerifier(max_batch=8, buckets=(8,))
        assert await eng.verify_hmac_sha256(*_hmac_item(0))
        st = eng.stats["hmac_sha256"]
        assert st.host_prep_time_s > 0.0
        assert st.device_time_s > 0.0
        # prep is a sub-interval of the dispatch the device clock wraps
        assert st.host_prep_time_s <= st.device_time_s * 1.5 + 0.05

    asyncio.run(run())


def test_padded_lane_accounting_is_thread_safe():
    """Regression pin for the padded_lanes data race: dispatchers run on
    worker threads (up to max_inflight concurrently) and used to do a bare
    read-modify-write on the shared stats counter — two racing dispatches
    could lose an increment.  All padded-lane accounting now goes through
    BatchVerifier._stats_lock (enforced by the tools/analyze
    lock-discipline pass), so N concurrent single-item dispatches into
    bucket size B must count EXACTLY N*(B-1) padded lanes."""
    import threading

    eng = BatchVerifier(max_batch=8, buckets=(8,))
    # Materialize the queue (dispatchers update its stats slot directly)
    # and warm the kernel so the threads race on accounting, not compile.
    eng._queue("hmac_sha256", eng._dispatch_hmac)
    eng._dispatch_hmac([_hmac_item(0)])
    base = eng.stats["hmac_sha256"].padded_lanes
    n_threads, per_thread = 8, 4
    barrier = threading.Barrier(n_threads)

    def hammer(tid: int) -> None:
        barrier.wait()
        for j in range(per_thread):
            res = eng._dispatch_hmac([_hmac_item(100 + tid * per_thread + j)])
            assert bool(res[0])

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got = eng.stats["hmac_sha256"].padded_lanes - base
    assert got == n_threads * per_thread * 7  # bucket 8, batch 1 -> 7 pads
