"""Chaos suite: deterministic fault injection, Byzantine adversaries,
and the seeded n=4/f=1 chaos soak (ISSUE 5).

Seed discipline: every seeded test resolves its seed via
``testing.faultnet.chaos_seed`` — ``MINBFT_CHAOS_SEED`` in the
environment wins (CI pins one; export it to replay a failure), otherwise
the test's committed default.  Failures print the seed.  The fault
schedule is a pure function of (seed, link, frame index):
``test_same_seed_reproduces_fault_schedule`` pins byte-identical replay,
and the soak cross-checks its live census against
``FaultNet.replay_counts`` recomputed from the seed alone.
"""

import asyncio
import json
import logging
import os
import sys

import pytest

from conftest import make_cluster
from minbft_tpu.client import new_client
from minbft_tpu.messages import Commit, Request
from minbft_tpu.sample.config import SimpleConfiger
from minbft_tpu.sample.conn.inprocess import InProcessClientConnector
from minbft_tpu.testing import (
    FaultNet,
    FaultPlan,
    InvariantChecker,
    chaos_seed,
)
from minbft_tpu.testing.adversary import Adversary, ConflictingReplyReplica


# Dev mode (PYTHONDEVMODE — the CI chaos step) arms asyncio debug mode,
# which captures a source traceback on EVERY Task/Future creation and
# times every callback: the protocol hot path runs roughly an order of
# magnitude slower, so a cluster tuned to sub-second patience knobs
# livelocks in view-change thrash (each round outlives timeout_request,
# every request demands a new view, forever).  The seeded fault schedule
# is FRAME-indexed, not time-based, so stretching every wall-clock knob
# by one factor keeps replay byte-identical — same draws, same per-kind
# census — while giving the slowed cluster proportionate patience.
TIME_SCALE = 5.0 if sys.flags.dev_mode else 1.0


def _t(seconds: float) -> float:
    """A wall-clock knob (protocol timeout, retransmit interval, test
    deadline) scaled for the execution mode."""
    return seconds * TIME_SCALE


# Phase markers interleave with the replicas' own captured log lines on
# failure — without them a wedge's log reads as one undifferentiated
# stream of timeouts with no way to tell which phase wedged.
_log = logging.getLogger("minbft.chaos")


# ---------------------------------------------------------------------------
# faultnet unit layer: the determinism contract.


def _frames(n, tag=b"fr"):
    return [tag + b"-%06d" % i + bytes([i % 251]) * (i % 17) for i in range(n)]


async def _pump(net, src, dst, frames):
    async def gen():
        for fr in frames:
            yield fr

    out = []
    async for fr in net.pipe(src, dst, gen()):
        out.append(fr)
    return out


def test_same_seed_reproduces_fault_schedule():
    """Two independent FaultNets with the SAME seed apply byte-identical
    faults to the same frame sequence (the MINBFT_CHAOS_SEED replay
    contract); a different seed produces a different schedule."""
    plan = FaultPlan(
        drop=0.1, delay=0.2, delay_s=(0.0, 0.0005), duplicate=0.1,
        reorder=0.15, corrupt=0.1, reset=0.004,
    )
    frames = _frames(400)

    async def run(seed):
        net = FaultNet(seed=seed, default_plan=plan)
        out = await _pump(net, "a", "b", frames)
        return out, net.census.seeded_counts(), dict(net.census.frames)

    out1, census1, frames1 = asyncio.run(run(1234))
    out2, census2, frames2 = asyncio.run(run(1234))
    assert out1 == out2
    assert census1 == census2
    assert frames1 == frames2
    assert sum(census1.values()) > 0  # the schedule actually fired
    out3, census3, _ = asyncio.run(run(99))
    assert (out3, census3) != (out1, census1)


def test_replay_counts_matches_live_census():
    """replay_counts recomputes a live run's seeded injection counts from
    (seed, per-link frame counts) alone — fresh RNGs, no live state."""
    plan = FaultPlan(
        drop=0.08, delay=0.1, delay_s=(0.0, 0.0002), duplicate=0.06,
        reorder=0.1, corrupt=0.05, reset=0.01,
    )

    async def run():
        net = FaultNet(seed=77, default_plan=plan)
        for src, dst, n in (("a", "b", 300), ("b", "a", 200), ("c", "a", 120)):
            await _pump(net, src, dst, _frames(n))
        return net

    net = asyncio.run(run())
    live = net.census.seeded_counts()
    assert net.replay_counts() == live
    assert net.replay_counts(dict(net.census.frames), plan=plan) == live


def test_faultnet_stall_partition_and_census_exposition():
    """Scripted faults: a stalled link holds frames without ending the
    stream (and releases them on unstall); a partition drops cross-group
    frames until healed; the census renders through the Prometheus
    exposition (obs.collect_faultnet)."""

    async def run():
        net = FaultNet(seed=5)

        async def gen():
            for i in range(6):
                yield b"f%d" % i

        got = []

        async def consume():
            async for fr in net.pipe("r0", "r1", gen()):
                got.append(fr)

        net.stall(src="r0")
        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.15)
        assert got == []  # held, stream still open
        net.unstall(src="r0")
        await asyncio.wait_for(task, 5)
        assert got == [b"f%d" % i for i in range(6)]
        assert net.census.counters.get("stall", 0) >= 1

        net.partition({"r0", "r1"}, {"r2", "r3"})
        cross = await _pump(net, "r0", "r2", [b"x", b"y"])
        same = await _pump(net, "r0", "r1", [b"z"])
        assert cross == [] and same == [b"z"]
        assert net.census.counters.get("partition", 0) == 2
        net.heal_partition()
        assert await _pump(net, "r0", "r2", [b"x2"]) == [b"x2"]

        from minbft_tpu.obs import collect_faultnet, render_families

        text = render_families(collect_faultnet(net.census))
        assert 'minbft_faultnet_injected_total{kind="stall"}' in text
        assert 'minbft_faultnet_injected_total{kind="partition"} 2' in text
        assert "minbft_faultnet_frames_total" in text
        return True

    assert asyncio.run(run())


def test_reset_all_ends_live_streams():
    async def run():
        net = FaultNet(seed=3)
        started = asyncio.Event()

        async def endless():
            yield b"one"
            started.set()
            await asyncio.sleep(60)

        got = []

        async def consume():
            async for fr in net.pipe("a", "b", endless()):
                got.append(fr)

        task = asyncio.ensure_future(consume())
        await asyncio.wait_for(started.wait(), 5)
        net.reset_all()
        await asyncio.wait_for(task, 5)  # the idle stream ended promptly
        assert got == [b"one"]
        assert net.census.counters.get("reset_all", 0) == 1
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# Byzantine adversary suite: real keys, real codec, hostile content.
# Every behavior must be rejected with no safety-invariant violation AND
# the cluster must still commit the honest workload.


def _short_cfg(vc=3.0):
    return SimpleConfiger(
        n=4, f=1, timeout_request=_t(0.8), timeout_prepare=_t(0.4),
        timeout_viewchange=_t(vc),
    )


def test_adversary_equivocation_rejected():
    """A Byzantine PRIMARY certifies one PREPARE, then re-sends the same
    UI over different content.  USIG counter monotonicity is the paper's
    core defense: one counter certifies ONE message, so the copy's cert
    cannot verify — backups must drop it, and the cluster (having lost
    only its primary to the adversary, within f=1) must view-change and
    keep committing."""

    async def run():
        replicas, c_auths, stubs, ledgers = await make_cluster(cfg=_short_cfg())
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        accepted = []
        r0 = await asyncio.wait_for(client.request(b"equiv-seed"), 30)
        accepted.append((b"equiv-seed", r0))

        # A genuine client-signed request to re-batch (from replica 1's
        # own COMMIT, which embeds the primary's PREPARE).
        commits = [
            m for m in replicas[1].handlers.message_log.snapshot()
            if isinstance(m, Commit)
        ]
        req = commits[0].prepare.requests[0]

        # The primary turns adversarial: its honest process stops, its
        # keys keep signing.
        stubs[0].crash()
        await replicas[0].stop()
        adv = Adversary(0, replicas[0].handlers.authenticator, 4)
        evil = Request(
            client_id=req.client_id, seq=req.seq + 999,
            operation=b"equiv-evil", signature=b"\x00" * 64,
        )
        pa, pb = adv.equivocating_prepares(0, [req], [evil])
        assert pb.ui.counter == pa.ui.counter  # the equivocation attempt

        m1 = replicas[1].metrics
        dropped = m1.counters.get("messages_dropped", 0)
        applied = m1.counters.get("prepares_accepted", 0)
        await adv.inject(stubs[1].peer_message_stream_handler(), [pa, pb])
        for _ in range(100):
            if m1.counters.get("messages_dropped", 0) > dropped:
                break
            await asyncio.sleep(0.02)
        # the conflicting copy is DROPPED (cert forgery)...
        assert m1.counters.get("messages_dropped", 0) >= dropped + 1
        # ...while at most the first certification was accepted.
        assert m1.counters.get("prepares_accepted", 0) <= applied + 1
        # nothing executed twice, nothing evil executed
        assert all(lg.length == 1 for lg in ledgers[1:])

        # honest workload continues (view change deposes the adversary)
        r1 = await asyncio.wait_for(client.request(b"after-equiv"), 45)
        accepted.append((b"after-equiv", r1))
        InvariantChecker(replicas, ledgers, correct=(1, 2, 3)).check(accepted)

        await client.stop()
        for r in replicas[1:]:
            await r.stop()
        return True

    assert asyncio.run(run())


def test_adversary_stale_replay_wrong_view_and_counter_gap():
    """Three adversarial behaviors from a backup's genuine keys:

    - stale-UI replay → dedup'd by once-only in-order capture (handled,
      no re-execution);
    - wrong-view PREPARE (genuinely certified, view the cluster is not
      in) → captured then refused, never applied;
    - counter-gap COMMIT (genuine cert, one counter burned unsent) →
      parked at capture, never processed past the gap.

    Throughout: the cluster keeps committing the honest workload."""

    async def run():
        replicas, c_auths, stubs, ledgers = await make_cluster(
            cfg=_short_cfg(vc=0.5)
        )
        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        accepted = []
        r0 = await asyncio.wait_for(client.request(b"adv-seed"), 30)
        accepted.append((b"adv-seed", r0))
        for _ in range(200):
            if all(lg.length == 1 for lg in ledgers):
                break
            await asyncio.sleep(0.02)

        # Replica 2 turns adversarial (still within f=1).
        genuine_commit = next(
            m for m in replicas[2].handlers.message_log.snapshot()
            if isinstance(m, Commit)
        )
        stubs[2].crash()
        await replicas[2].stop()
        adv = Adversary(2, replicas[2].handlers.authenticator, 4)

        # -- stale-UI replay at replica 1
        m1 = replicas[1].metrics
        handled = m1.counters.get("messages_handled", 0)
        await adv.inject(
            stubs[1].peer_message_stream_handler(),
            [adv.replay(genuine_commit)] * 3,
        )
        for _ in range(100):
            if m1.counters.get("messages_handled", 0) >= handled + 3:
                break
            await asyncio.sleep(0.02)
        assert m1.counters.get("messages_handled", 0) >= handled + 3
        assert ledgers[1].length == 1  # no double execution

        # -- wrong-view PREPARE at replica 1 (adversary IS view 2's
        # primary, but the cluster is in view 0)
        applied = m1.counters.get("prepares_accepted", 0)
        wv = adv.wrong_view_prepare(2, [genuine_commit.prepare.requests[0]])
        # the future-view park expires after 2*max(vc_timeout, 1.0)
        # (2s at this cfg, 5s dev-mode-scaled), then the message must be
        # captured and REFUSED, not applied — hold past the expiry
        await adv.inject(
            stubs[1].peer_message_stream_handler(), [wv],
            hold_s=2.0 * max(_t(0.5), 1.0) + _t(1.5),
        )
        assert m1.counters.get("messages_dropped_future_view", 0) >= 1
        assert m1.counters.get("prepares_accepted", 0) == applied
        assert ledgers[1].length == 1

        # -- counter-gap COMMIT at replica 3
        gap_commit = adv.counter_gap_commit(genuine_commit.prepare)
        m3 = replicas[3].metrics
        counted = m3.counters.get("commitments_counted", 0)
        mark_before = replicas[3].handlers.peer_states.peer(2)._next_cv
        assert gap_commit.ui.counter > mark_before + 1  # a real gap
        await adv.inject(stubs[3].peer_message_stream_handler(), [gap_commit])
        # parked at capture: the watermark must NOT have advanced to (or
        # past) the gapped counter, and no commitment was counted for it
        assert replicas[3].handlers.peer_states.peer(2)._next_cv <= mark_before + 1
        assert m3.counters.get("commitments_counted", 0) == counted
        assert ledgers[3].length == 1

        # honest workload still commits (primary 0 is honest and alive)
        r1 = await asyncio.wait_for(client.request(b"adv-after"), 30)
        accepted.append((b"adv-after", r1))
        InvariantChecker(replicas, ledgers, correct=(0, 1, 3)).check(accepted)

        await client.stop()
        for i in (0, 1, 3):
            await replicas[i].stop()
        return True

    assert asyncio.run(run())


def test_adversary_conflicting_replies_stay_below_quorum():
    """A replica answering clients with correctly-SIGNED wrong results:
    one liar's vote must never complete the client's f+1 matching-reply
    quorum, and the accepted result must be the honest ledgers' digest."""

    async def run():
        replicas, c_auths, stubs, ledgers = await make_cluster()
        # replica 2's identity is taken over by the reply forger
        stubs[2].crash()
        await replicas[2].stop()
        adv = Adversary(2, replicas[2].handlers.authenticator, 4)
        forger = ConflictingReplyReplica(adv)
        stubs[2].revive()
        stubs[2].assign_replica(forger)

        client = new_client(0, 4, 1, c_auths[0], InProcessClientConnector(stubs))
        await client.start()
        res = await asyncio.wait_for(client.request(b"honest-op"), 30)
        assert res != forger.forged_result
        for _ in range(200):
            if forger.replies_sent >= 1:
                break
            await asyncio.sleep(0.02)
        assert forger.replies_sent >= 1  # the liar really voted
        for _ in range(200):
            if all(lg.length == 1 for lg in (ledgers[0], ledgers[1], ledgers[3])):
                break
            await asyncio.sleep(0.02)
        assert res == ledgers[0].block(1).digest()
        InvariantChecker(replicas, ledgers, correct=(0, 1, 3)).check(
            [(b"honest-op", res)]
        )

        await client.stop()
        for i in (0, 1, 3):
            await replicas[i].stop()
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# View change under message LOSS (satellite): the transition completes
# across lossy links, not just after clean crashes.


def test_view_change_completes_under_message_loss():
    seed = chaos_seed(default=0xA11CE)

    async def run():
        net = FaultNet(
            seed=seed,
            default_plan=FaultPlan(
                drop=0.05, delay=0.15, delay_s=(0.0005, 0.008),
                duplicate=0.05, reorder=0.08, reset=0.01,
            ),
        )
        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=_t(0.8), timeout_prepare=_t(0.4),
            timeout_viewchange=_t(1.5),
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(
            cfg=cfg, wrap_conn=lambda i, c: net.wrap(c, f"r{i}")
        )
        client = new_client(
            0, 4, 1, c_auths[0], InProcessClientConnector(stubs),
            retransmit_interval=_t(0.5),
        )
        await client.start()
        accepted = []
        r0 = await asyncio.wait_for(client.request(b"loss-seed"), _t(60))
        accepted.append((b"loss-seed", r0))

        stubs[0].crash()
        await replicas[0].stop()

        # REQ-VIEW-CHANGE / VIEW-CHANGE / NEW-VIEW now cross lossy links;
        # the timeout/escalation + redial-replay paths must still land a
        # completed transition.
        r1 = await asyncio.wait_for(client.request(b"loss-after-crash"), _t(90))
        accepted.append((b"loss-after-crash", r1))
        for r in replicas[1:]:
            cur, _ = await r.handlers.view_state.hold_view()
            assert cur >= 1, f"replica {r.id} still in view {cur}"
        deadline = asyncio.get_running_loop().time() + _t(30)
        while asyncio.get_running_loop().time() < deadline:
            if all(lg.length >= 2 for lg in ledgers[1:]):
                break
            await asyncio.sleep(0.05)
        InvariantChecker(replicas, ledgers, correct=(1, 2, 3)).check(accepted)
        assert net.census.counters.get("drop", 0) >= 1

        await client.stop()
        for r in replicas[1:]:
            await r.stop()
        return True

    try:
        assert asyncio.run(run())
    except BaseException:
        print(f"replay with MINBFT_CHAOS_SEED={seed}")
        raise


# ---------------------------------------------------------------------------
# Stalled (half-open) primary: frames stop, connections stay up — the
# request-timeout → view-change path must fire on BOTH transports (a
# closed connection is the easy case the old tests covered).


def test_stalled_primary_triggers_view_change_inprocess():
    async def run():
        net = FaultNet(seed=chaos_seed(default=0x57A11))
        replicas, c_auths, stubs, ledgers = await make_cluster(
            cfg=_short_cfg(), wrap_conn=lambda i, c: net.wrap(c, f"r{i}")
        )
        client = new_client(
            0, 4, 1, c_auths[0],
            net.wrap(InProcessClientConnector(stubs), "c0"),
            retransmit_interval=0.5,
        )
        await client.start()
        accepted = []
        r0 = await asyncio.wait_for(client.request(b"stall-seed"), 30)
        accepted.append((b"stall-seed", r0))

        net.stall_replica(0)  # half-open: streams stay up, frames stop
        r1 = await asyncio.wait_for(client.request(b"stall-after"), 60)
        accepted.append((b"stall-after", r1))
        for r in replicas[1:]:
            cur, _ = await r.handlers.view_state.hold_view()
            assert cur >= 1, f"replica {r.id} still in view {cur}"
        assert net.census.counters.get("stall", 0) >= 1
        net.unstall_replica(0)
        # committed-results is a convergence property (f+1 replies prove
        # only f+1 executions) — give laggards a bounded catch-up first.
        deadline = asyncio.get_running_loop().time() + _t(30)
        while asyncio.get_running_loop().time() < deadline:
            if all(lg.length >= len(accepted) for lg in ledgers[1:]):
                break
            await asyncio.sleep(0.05)
        InvariantChecker(replicas, ledgers, correct=(1, 2, 3)).check(accepted)

        await client.stop()
        for r in replicas:
            await r.stop()
        return True

    assert asyncio.run(run())


def test_stalled_primary_triggers_view_change_tcp():
    """Same half-open primary scenario over the native TCP transport:
    replica stubs behind TcpReplicaServer, dial-side TcpReplicaConnectors
    wrapped in the FaultNet, idle teardown armed."""

    async def run():
        from minbft_tpu.core import new_replica
        from minbft_tpu.sample.authentication import new_test_authenticators
        from minbft_tpu.sample.conn.inprocess import make_testnet_stubs
        from minbft_tpu.sample.conn.tcp import (
            TcpReplicaConnector,
            TcpReplicaServer,
            connect_many_replicas_tcp,
        )
        from minbft_tpu.sample.requestconsumer import SimpleLedger

        net = FaultNet(seed=chaos_seed(default=0x7C9))
        n, f = 4, 1
        cfg = _short_cfg()
        r_auths, c_auths = new_test_authenticators(n, usig_kind="hmac")
        stubs = make_testnet_stubs(n)
        servers = {}
        addrs = {}
        for i in range(n):
            srv = TcpReplicaServer(stubs[i])
            addrs[i] = await srv.start("127.0.0.1:0")
            servers[i] = srv
        ledgers = [SimpleLedger() for _ in range(n)]
        replicas = []
        for i in range(n):
            conn = TcpReplicaConnector("peer", idle_timeout=30.0)
            for j, addr in addrs.items():
                if j != i:
                    conn.connect_replica(j, addr)
            r = new_replica(i, cfg, r_auths[i], net.wrap(conn, f"r{i}"), ledgers[i])
            stubs[i].assign_replica(r)
            replicas.append(r)
        for r in replicas:
            await r.start()
        client_conn = connect_many_replicas_tcp(addrs, kind="client")
        client = new_client(
            0, n, f, c_auths[0], net.wrap(client_conn, "c0"),
            retransmit_interval=0.5,
        )
        await client.start()
        try:
            accepted = []
            r0 = await asyncio.wait_for(client.request(b"tcp-stall-seed"), 60)
            accepted.append((b"tcp-stall-seed", r0))

            net.stall_replica(0)
            r1 = await asyncio.wait_for(client.request(b"tcp-stall-after"), 90)
            accepted.append((b"tcp-stall-after", r1))
            for r in replicas[1:]:
                cur, _ = await r.handlers.view_state.hold_view()
                assert cur >= 1, f"replica {r.id} still in view {cur}"
            assert net.census.counters.get("stall", 0) >= 1
            net.unstall_replica(0)
            # committed-results is a convergence property — wait for the
            # correct laggards before holding every ledger to it.
            deadline = asyncio.get_running_loop().time() + _t(30)
            while asyncio.get_running_loop().time() < deadline:
                if all(lg.length >= len(accepted) for lg in ledgers[1:]):
                    break
                await asyncio.sleep(0.05)
            InvariantChecker(replicas, ledgers, correct=(1, 2, 3)).check(accepted)
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
            for srv in servers.values():
                await srv.stop()
            await client_conn.close()
        return True

    assert asyncio.run(run())


def test_tcp_idle_timeout_recovers_half_open_stream():
    """Satellite: the native TCP connector's per-stream read-idle timeout
    tears down a half-open connection (server alive, frames stalled by a
    faultnet stall BELOW the dialer's socket) so the redial loop can
    recover — without it the read parks forever."""
    from minbft_tpu import api
    from minbft_tpu.sample.conn.tcp import TcpReplicaConnector, TcpReplicaServer
    from minbft_tpu.testing import FaultyConnectionHandler

    class _Echo(api.MessageStreamHandler):
        async def handle_message_stream(self, in_stream):
            async for data in in_stream:
                yield b"E:" + data

    class _EchoConn(api.ConnectionHandler):
        def peer_message_stream_handler(self):
            return _Echo()

        def client_message_stream_handler(self):
            return _Echo()

    async def run():
        net = FaultNet(seed=1)
        server = TcpReplicaServer(FaultyConnectionHandler(_EchoConn(), net, "srv"))
        addr = await server.start("127.0.0.1:0")
        conn = TcpReplicaConnector("peer", idle_timeout=0.4)
        conn.connect_replica(0, addr)
        try:
            handler = conn.replica_message_stream_handler(0)
            sent = asyncio.Event()

            async def outgoing():
                yield b"one"
                await sent.wait()
                yield b"two"
                await asyncio.sleep(60)

            out = handler.handle_message_stream(outgoing())
            assert await asyncio.wait_for(out.__anext__(), 10) == b"E:one"
            # Stall the server side: the TCP connection stays up but no
            # frames flow — the dialer's idle deadline must END the
            # stream (the redial loop's recovery signal)...
            net.stall(dst="srv")
            sent.set()
            t0 = asyncio.get_running_loop().time()
            with pytest.raises(StopAsyncIteration):
                await asyncio.wait_for(out.__anext__(), 10)
            assert asyncio.get_running_loop().time() - t0 < 5.0
            await out.aclose()
            # ...and after the stall heals, a fresh dial works again.
            net.unstall(dst="srv")
            h2 = conn.replica_message_stream_handler(0)

            async def once():
                yield b"back"
                await asyncio.sleep(60)

            out2 = h2.handle_message_stream(once())
            assert await asyncio.wait_for(out2.__anext__(), 10) == b"E:back"
            await out2.aclose()
        finally:
            await server.stop()
            await conn.close()
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# Silent tail loss: the hardest liveness hole a lossy link can open.  A
# replica that misses a burst's TAIL (a partition swallowing commits, a
# dropped NEW-VIEW with no follow-on traffic) has NOTHING to react to:
# no counter gap parks (nothing later arrived), no stream ends, no
# timeout fires.  Recovery is the dial loop's idle-refresh — tear down a
# silent stream and redial with a resumable HELLO so the publisher
# replays just the missed tail.


def test_idle_refresh_heals_silent_tail_loss():
    async def run():
        net = FaultNet(seed=chaos_seed(default=0x1D7E))  # faithful plan
        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=_t(60.0), timeout_prepare=_t(30.0),
            timeout_viewchange=_t(1.0),
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(
            cfg=cfg, wrap_conn=lambda i, c: net.wrap(c, f"r{i}")
        )
        client = new_client(
            0, 4, 1, c_auths[0],
            net.wrap(InProcessClientConnector(stubs), "c0"),
        )
        await client.start()
        accepted = []
        try:
            r0 = await asyncio.wait_for(client.request(b"tail-seed"), _t(30))
            accepted.append((b"tail-seed", r0))
            deadline = asyncio.get_running_loop().time() + _t(15)
            while asyncio.get_running_loop().time() < deadline:
                if all(lg.length == 1 for lg in ledgers):
                    break
                await asyncio.sleep(0.02)

            # r3 alone on the wrong side; the client stays with the
            # majority so NOTHING reaches r3 from here on.
            net.partition({"r0", "r1", "r2", "c0"}, {"r3"})
            for i in range(3):
                op = b"tail-%d" % i
                res = await asyncio.wait_for(client.request(op), _t(30))
                accepted.append((op, res))
            assert ledgers[3].length == 1  # r3 really missed the burst

            # Heal — and issue NO further traffic.  Without the
            # idle-refresh this wedges forever: the partition dropped
            # frames on streams that stayed up, so r3 sees only silence.
            net.heal_partition()
            deadline = asyncio.get_running_loop().time() + _t(45)
            while asyncio.get_running_loop().time() < deadline:
                if ledgers[3].length >= len(accepted):
                    break
                await asyncio.sleep(0.05)
            assert ledgers[3].length >= len(accepted), (
                f"r3 ledger stuck at {ledgers[3].length}/{len(accepted)} "
                "after heal (idle-refresh did not deliver the tail)"
            )
            assert replicas[3].metrics.counters.get("idle_redials", 0) >= 1
            InvariantChecker(replicas, ledgers).check(accepted)
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()
        return True

    assert asyncio.run(run())


# ---------------------------------------------------------------------------
# THE chaos soak: n=4/f=1 under seeded drop+delay+duplicate+reorder+
# corrupt(+reset), one partition-and-heal, one primary stall — 100% of
# issued requests must commit, invariants must hold on every replica,
# and the live census must match the schedule recomputed from the seed.


# Per-frame fault probabilities.  Calibrated to the BUNDLE-ingest frame
# dynamics: the batch runtime coalesces harder (one transport frame now
# carries a whole drained bundle), so the soak sees roughly half the
# seeded frames the per-task runtime did — ~90-110 on this container.
# corrupt at the old 0.008 had E[corrupt] ~ 0.7 there and legitimately
# came up zero; the raised rates also exercise corrupt's bigger blast
# radius (one flipped byte now rejects a whole coalesced bundle at
# split_multi), which the retransmit/replay paths must — and do —
# absorb.  The per-kind `>= 1 injected` assertion additionally gates on
# expected count at the observed frame volume (see the soak), so
# run-to-run frame-count swings can never turn a fair zero into a flake.
CHAOS_PLAN = FaultPlan(
    drop=0.03,
    delay=0.10,
    delay_s=(0.0005, 0.008),
    duplicate=0.03,
    reorder=0.05,
    corrupt=0.025,
    reset=0.004,
)


def test_chaos_soak_commits_under_faults():
    seed = chaos_seed(default=0xC4A05)

    async def run():
        net = FaultNet(seed=seed, default_plan=CHAOS_PLAN)
        cfg = SimpleConfiger(
            n=4, f=1, timeout_request=_t(0.8), timeout_prepare=_t(0.4),
            timeout_viewchange=_t(1.0),
        )
        replicas, c_auths, stubs, ledgers = await make_cluster(
            cfg=cfg, wrap_conn=lambda i, c: net.wrap(c, f"r{i}")
        )
        checker = InvariantChecker(replicas, ledgers)
        client = new_client(
            0, 4, 1, c_auths[0],
            net.wrap(InProcessClientConnector(stubs), "c0"),
            retransmit_interval=_t(0.4), max_inflight=8,
        )
        await client.start()
        accepted = []

        async def issue(tag, k, timeout=90):
            ops = [b"chaos-%s-%d" % (tag, i) for i in range(k)]
            results = await asyncio.gather(
                *[client.request(op, timeout=_t(timeout)) for op in ops]
            )
            accepted.extend(zip(ops, results))

        try:
            # Phase A: seeded chaos only (drop/delay/dup/reorder/corrupt).
            _log.warning("chaos phase A: 8 requests under seeded plan")
            await issue(b"a", 8)
            # Invariants hold MID-run: prefix consistency and UI
            # integrity are instant properties.  Committed-results is a
            # CONVERGENCE property (f+1 replies prove only f+1 replicas
            # executed; the rest legitimately lag under chaos), so give
            # the laggards a bounded catch-up before holding every
            # ledger to the accepted set.
            checker.check()
            deadline = asyncio.get_running_loop().time() + 45
            while asyncio.get_running_loop().time() < deadline:
                if all(lg.length >= len(accepted) for lg in ledgers):
                    break
                await asyncio.sleep(0.05)
            checker.check(accepted)

            # Phase B: partition {r0,r1} | {r2,r3} while traffic flows
            # (the majority-side primary keeps committing), then heal.
            _log.warning("chaos phase B: partition {r0,r1}|{r2,r3} + 6 requests")
            net.partition({"r0", "r1"}, {"r2", "r3"})
            issue_b = asyncio.ensure_future(issue(b"b", 6))
            await asyncio.sleep(1.5)
            net.heal_partition()
            _log.warning("chaos phase B: partition healed")
            t_heal = asyncio.get_running_loop().time()
            await issue_b
            # Recovery latency: heal → every partition-spanning request
            # client-accepted (the perf/CHAOS.md census headline).
            recovery_after_heal_s = (
                asyncio.get_running_loop().time() - t_heal
            )

            # Let the post-partition view settle cluster-wide before
            # picking the primary to stall.
            deadline = asyncio.get_running_loop().time() + 30
            view = 0
            while asyncio.get_running_loop().time() < deadline:
                views = []
                for r in replicas:
                    cur, _ = await r.handlers.view_state.hold_view()
                    views.append(cur)
                if len(set(views)) == 1:
                    view = views[0]
                    break
                await asyncio.sleep(0.1)

            # Phase C: stall the CURRENT primary (half-open — streams
            # stay connected, frames stop) → request timeouts must
            # depose it and commits continue in the next view.
            primary = view % 4
            _log.warning(
                "chaos phase C: settled view %d, stalling primary r%d",
                view, primary,
            )
            net.stall_replica(primary)
            await issue(b"c", 6)
            # Commits resume with the new primary + one backup (f+1), so
            # the third survivor may legitimately still be applying the
            # NEW-VIEW when the batch resolves — poll, don't snapshot.
            survivors = [r for r in replicas if r.id != primary]
            deadline = asyncio.get_running_loop().time() + _t(30)
            views = {}
            while asyncio.get_running_loop().time() < deadline:
                for r in survivors:
                    cur, _ = await r.handlers.view_state.hold_view()
                    views[r.id] = cur
                if all(v > view for v in views.values()):
                    break
                await asyncio.sleep(0.05)
            assert all(v > view for v in views.values()), (
                f"survivors still at {views} (stalled primary {primary} "
                f"not deposed past view {view})"
            )
            net.unstall_replica(primary)

            # Freeze the seeded census NOW (heal clears the plan, and
            # post-heal frames draw from the zero plan).
            frames_snapshot = dict(net.census.frames)
            live_seeded = dict(net.census.seeded_counts())

            # Phase D: heal + reset every stream (redials replay full
            # logs — the convergence step), then a clean tail batch.
            _log.warning("chaos phase D: heal + reset_all + 4 requests")
            net.heal()
            net.reset_all()
            await issue(b"d", 4, timeout=60)

            # 100% of issued requests committed...
            assert len(accepted) == 24
            assert all(res for _, res in accepted)
            # ...on EVERY replica (the stalled ex-primary catches up).
            deadline = asyncio.get_running_loop().time() + 60
            while asyncio.get_running_loop().time() < deadline:
                if all(lg.length >= len(accepted) for lg in ledgers):
                    break
                await asyncio.sleep(0.1)
            lengths = [lg.length for lg in ledgers]
            assert all(l >= len(accepted) for l in lengths), lengths

            # Safety invariants across ALL replicas at teardown.
            summary = checker.check(accepted)
            assert summary["accepted_checked"] == 24

            # The faults really happened... asserted per kind only when
            # its EXPECTED count at the run's observed frame volume makes
            # a zero impossible-in-practice (E >= 5 -> P(zero) < 1%).
            # Frame volume is timing-dependent (bundle coalescing, host
            # load): a quiet run legitimately draws zero events of a
            # low-probability kind, and that is the seeded schedule
            # working, not a missing fault injector — the determinism
            # cross-check below (replayed == live) covers those kinds
            # exactly.  High-volume runs (CI's full-size soak) clear the
            # gate for every kind and keep the assertion's full strength.
            seeded_frames = sum(frames_snapshot.values())
            for kind, p in (
                ("drop", CHAOS_PLAN.drop),
                ("delay", CHAOS_PLAN.delay),
                ("duplicate", CHAOS_PLAN.duplicate),
                ("reorder", CHAOS_PLAN.reorder),
                ("corrupt", CHAOS_PLAN.corrupt),
            ):
                if seeded_frames * p >= 5.0:
                    assert net.census.counters.get(kind, 0) >= 1, (
                        kind, seeded_frames, net.census.counters)
            assert net.census.counters.get("stall", 0) >= 1
            assert net.census.counters.get("partition", 0) >= 1
            # ...and followed the seed's deterministic schedule exactly:
            # the same MINBFT_CHAOS_SEED + the same frame counts always
            # reproduce these per-kind injection counts.
            replayed = net.replay_counts(frames_snapshot, plan=CHAOS_PLAN)
            assert replayed == live_seeded, (replayed, live_seeded)
            out = net.census.snapshot()
            out["seed"] = seed
            out["time_scale"] = TIME_SCALE
            out["requests_committed"] = len(accepted)
            out["recovery_after_heal_s"] = round(recovery_after_heal_s, 3)
            return out
        finally:
            await client.stop()
            for r in replicas:
                await r.stop()

    try:
        census = asyncio.run(run())
    except BaseException:
        print(f"replay with MINBFT_CHAOS_SEED={seed}")
        raise
    assert census["frames_total"] > 0
    # perf/CHAOS.md records one committed census; regenerate it with
    # MINBFT_CHAOS_CENSUS=<path> pointing at a JSON dump target.
    census_path = os.environ.get("MINBFT_CHAOS_CENSUS")
    if census_path:
        with open(census_path, "w") as fh:
            json.dump(census, fh, indent=2, sort_keys=True)
            fh.write("\n")
