"""Config provider tests (reference sample/config/config_test.go:85):
YAML schema parsing, duration forms, and CONSENSUS_* env layering."""

import pytest

from minbft_tpu.sample.config import load_config

YAML = """\
protocol:
  n: 5
  f: 2
  checkpointPeriod: 10
  logsize: 20
  batchsizePrepare: 128
  timeout:
    request: 1500ms
    prepare: 2s
peers:
  - id: 0
    addr: 127.0.0.1:9000
  - id: 1
    addr: 127.0.0.1:9001
"""


@pytest.fixture
def cfg_path(tmp_path):
    p = tmp_path / "consensus.yaml"
    p.write_text(YAML)
    return str(p)


def test_file_values(cfg_path):
    cfg = load_config(cfg_path, env={})
    assert (cfg.n, cfg.f) == (5, 2)
    assert cfg.checkpoint_period == 10 and cfg.logsize == 20
    assert cfg.batchsize_prepare == 128
    assert cfg.timeout_request == 1.5
    assert cfg.timeout_prepare == 2.0
    assert [p.addr for p in cfg.peers] == ["127.0.0.1:9000", "127.0.0.1:9001"]


def test_env_layering(cfg_path):
    env = {
        "CONSENSUS_TIMEOUT_REQUEST": "5s",
        "CONSENSUS_CHECKPOINT_PERIOD": "99",
    }
    cfg = load_config(cfg_path, env=env)
    assert cfg.timeout_request == 5.0
    assert cfg.checkpoint_period == 99
    # untouched values come from the file
    assert cfg.timeout_prepare == 2.0
    assert (cfg.n, cfg.f) == (5, 2)
