"""Differential tests: JAX SHA-256 / HMAC-SHA256 vs hashlib/hmac."""

import hashlib
import hmac as py_hmac
import os

import numpy as np
import pytest

from minbft_tpu.ops import hmac_sha256, sha256


@pytest.mark.parametrize(
    "data",
    [
        b"",
        b"abc",
        b"a" * 55,
        b"a" * 56,  # padding boundary
        b"a" * 64,
        b"hello world" * 20,
        os.urandom(301),
    ],
)
def test_sha256_matches_hashlib(data):
    assert sha256.sha256_host(data) == hashlib.sha256(data).digest()


def test_sha256_batch():
    msgs = [os.urandom(32) for _ in range(17)]
    blocks = np.stack([sha256.pad_message(m) for m in msgs])  # [17, 1, 16]
    out = np.asarray(sha256.sha256_fixed_batch(blocks))
    for i, m in enumerate(msgs):
        assert sha256.words_to_bytes(out[i]) == hashlib.sha256(m).digest()


def test_hmac32_matches_hmac_module():
    rng = np.random.default_rng(0)
    B = 33
    keys = rng.integers(0, 2**32, size=(B, 8), dtype=np.uint32)
    msgs = rng.integers(0, 2**32, size=(B, 8), dtype=np.uint32)
    macs = np.asarray(hmac_sha256.hmac_sign_kernel(keys, msgs))
    for i in range(B):
        expect = py_hmac.new(
            sha256.words_to_bytes(keys[i]),
            sha256.words_to_bytes(msgs[i]),
            hashlib.sha256,
        ).digest()
        assert sha256.words_to_bytes(macs[i]) == expect


def test_hmac_verify_batch_accepts_and_rejects():
    rng = np.random.default_rng(1)
    B = 16
    keys = rng.integers(0, 2**32, size=(B, 8), dtype=np.uint32)
    msgs = rng.integers(0, 2**32, size=(B, 8), dtype=np.uint32)
    macs = np.asarray(hmac_sha256.hmac_sign_kernel(keys, msgs))
    ok = np.asarray(hmac_sha256.hmac_verify_kernel(keys, msgs, macs))
    assert ok.all()

    # Corrupt one word of half the macs.
    bad = macs.copy()
    bad[::2, 3] ^= 1
    ok2 = np.asarray(hmac_sha256.hmac_verify_kernel(keys, msgs, bad))
    assert (~ok2[::2]).all() and ok2[1::2].all()

    # Wrong key rejects.
    ok3 = np.asarray(hmac_sha256.hmac_verify_kernel(keys[::-1], msgs, macs))
    assert not ok3.any() or B == 1
