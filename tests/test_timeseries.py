"""Telemetry-ring tests (minbft_tpu/obs/timeseries.py, ISSUE 14): slot
semantics on the absolute epoch grid, exact/associative merge (the
Log2Histogram contract), counter-delta discipline under resets, the
multi-producer hammer the lock-discipline analyzer assumes, and the
dump→merge incarnation refusal."""

import json
import random
import threading
import time

import pytest

from minbft_tpu.obs.timeseries import (
    CounterSampler,
    IncarnationMismatch,
    TimeSeries,
    dump_timeseries,
    merge_timeseries_docs,
)

# A fixed epoch anchor far from "now" so tests never race the wall
# clock's interval boundary: every record passes an explicit t.
T0 = 1_000_000.0


# ---------------------------------------------------------------------------
# slot semantics


def test_rate_sums_and_gauge_means_within_a_slot():
    ts = TimeSeries(interval_s=1.0)
    for v in (1.0, 2.0, 3.0):
        ts.record("c", v, kind="rate", t=T0 + 0.2)
        ts.record("d", v, kind="gauge", t=T0 + 0.2)
    idx = ts.index_for(T0)
    assert ts.value("c", idx) == 6.0  # rate: deltas add
    assert ts.value("d", idx) == 2.0  # gauge: mean of samples
    assert ts.value("c", idx + 1) == 0.0  # empty slot reads 0
    assert ts.kind("c") == "rate" and ts.kind("d") == "gauge"


def test_kind_is_fixed_at_first_record():
    ts = TimeSeries()
    ts.record("c", 1.0, kind="rate", t=T0)
    with pytest.raises(ValueError, match="cannot record"):
        ts.record("c", 1.0, kind="gauge", t=T0)
    with pytest.raises(ValueError, match="kind must be"):
        ts.record("e", 1.0, kind="bogus", t=T0)


def test_constructor_rejects_degenerate_grids():
    with pytest.raises(ValueError):
        TimeSeries(interval_s=0.0)
    with pytest.raises(ValueError):
        TimeSeries(capacity=0)


def test_window_excludes_the_still_filling_interval():
    """window() must not read the newest slot: a half-elapsed interval
    would report a half rate."""
    ts = TimeSeries(interval_s=1.0)
    for k in range(5):
        ts.record("c", 10.0, kind="rate", t=T0 + k)
        ts.record("g", float(k), kind="gauge", t=T0 + k)
    now = T0 + 4.5  # slot T0+4 is still filling
    w = ts.window(3.0, now=now)
    # slots T0+1..T0+3 → 30 units over 3 s
    assert w["c"] == pytest.approx(10.0)
    assert w["g"] == pytest.approx((1 + 2 + 3) / 3)
    # empty window reads 0, not a crash
    w_empty = ts.window(3.0, now=T0 - 100)
    assert w_empty["c"] == 0.0 and w_empty["g"] == 0.0


def test_timeline_fills_gaps_with_zero_and_honors_last():
    ts = TimeSeries(interval_s=1.0)
    base = ts.index_for(T0)
    ts.record("c", 5.0, kind="rate", t=T0)
    ts.record("c", 7.0, kind="rate", t=T0 + 3)
    start, vals = ts.timeline("c")
    assert start == base
    assert vals == [5.0, 0.0, 0.0, 7.0]
    start2, vals2 = ts.timeline("c", last=2)
    assert start2 == base + 2
    assert vals2 == [0.0, 7.0]
    assert ts.timeline("missing") == (0, [])


def test_capacity_prunes_from_the_newest_index():
    ts = TimeSeries(interval_s=1.0, capacity=10)
    for k in range(30):
        ts.record("c", 1.0, kind="rate", t=T0 + k)
    start, vals = ts.timeline("c")
    assert len(vals) <= 10
    assert start >= ts.index_for(T0 + 29) - 10
    # a late straggler older than the floor cannot resurrect history
    ts.record("c", 1.0, kind="rate", t=T0)
    ts.record("c", 1.0, kind="rate", t=T0 + 30)
    start3, _ = ts.timeline("c")
    assert start3 > ts.index_for(T0)


# ---------------------------------------------------------------------------
# merge: exact, associative, refuses mismatched grids/kinds


def _random_ring(seed: int) -> TimeSeries:
    rng = random.Random(seed)
    ts = TimeSeries(interval_s=1.0)
    for _ in range(rng.randrange(5, 40)):
        name = rng.choice(["a", "b", "g"])
        kind = "gauge" if name == "g" else "rate"
        t = T0 + rng.randrange(0, 20)
        for _ in range(rng.randrange(1, 4)):
            ts.record(name, rng.uniform(0, 100), kind=kind, t=t)
    return ts


def _copy(ts: TimeSeries) -> TimeSeries:
    return TimeSeries.from_dict(ts.to_dict())


def test_merge_is_exact_pair_addition():
    a, b = _random_ring(1), _random_ring(2)
    merged = _copy(a).merge(_copy(b))
    da, db, dm = a.to_dict(), b.to_dict(), merged.to_dict()
    names = set(da["series"]) | set(db["series"])
    assert set(dm["series"]) == names
    for name in names:
        pa = (da["series"].get(name) or {"points": {}})["points"]
        pb = (db["series"].get(name) or {"points": {}})["points"]
        pm = dm["series"][name]["points"]
        assert set(pm) == set(pa) | set(pb)
        for i in pm:
            s = (pa.get(i, [0, 0])[0] + pb.get(i, [0, 0])[0])
            n = (pa.get(i, [0, 0])[1] + pb.get(i, [0, 0])[1])
            assert pm[i][0] == pytest.approx(s)
            assert pm[i][1] == n


def test_merge_is_associative_slot_for_slot():
    for seed in range(4):
        a = _random_ring(3 * seed)
        b = _random_ring(3 * seed + 1)
        c = _random_ring(3 * seed + 2)
        left = _copy(a).merge(_copy(b)).merge(_copy(c))
        right = _copy(a).merge(_copy(b).merge(_copy(c)))
        assert left.to_dict() == right.to_dict()


def test_merge_refuses_mismatched_grids_and_kinds():
    a = TimeSeries(interval_s=1.0)
    with pytest.raises(ValueError, match="interval mismatch"):
        a.merge(TimeSeries(interval_s=2.0))
    a.record("x", 1.0, kind="rate", t=T0)
    b = TimeSeries(interval_s=1.0)
    b.record("x", 1.0, kind="gauge", t=T0)
    with pytest.raises(ValueError, match="kind mismatch"):
        a.merge(b)


def test_dict_round_trip_preserves_readings():
    a = _random_ring(9)
    b = TimeSeries.from_dict(json.loads(json.dumps(a.to_dict())))
    assert b.to_dict() == a.to_dict()
    for name in a.names():
        assert a.timeline(name) == b.timeline(name)


# ---------------------------------------------------------------------------
# concurrency: the lock class tools/analyze pins


def test_mt_record_multi_producer_hammer():
    """Sampler-thread-shaped hammer: several OS threads record into the
    SAME series (and a few private ones) concurrently; no update may be
    lost — the final (sum, n) pairs must account for every record."""
    ts = TimeSeries(interval_s=1.0, capacity=600)
    n_threads, per_thread = 8, 3000

    def producer(tid: int) -> None:
        for k in range(per_thread):
            t = T0 + (k % 50)
            ts.record("shared", 1.0, kind="rate", t=t)
            ts.record(f"own{tid}", 2.0, kind="gauge", t=t)

    threads = [
        threading.Thread(target=producer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _, vals = ts.timeline("shared")
    assert sum(vals) == n_threads * per_thread  # no lost update
    for tid in range(n_threads):
        _, gv = ts.timeline(f"own{tid}")
        assert all(v == 2.0 for v in gv)  # gauge mean of identical samples


def test_record_overhead_is_bounded():
    """Disabled-path honesty (ISSUE 14 satellite): the observability
    hooks must stay cheap enough that leaving them wired costs nothing
    the bench can see.  A/B a monotonic-stamped counter inc against a
    bare dict increment, and a ring record against the same baseline —
    thresholds are catastrophic-only (orders of magnitude) so a slow CI
    runner cannot flake this."""
    from minbft_tpu.utils.metrics import ReplicaMetrics

    n = 20_000
    plain = {}
    t0 = time.perf_counter()
    for _ in range(n):
        plain["requests_executed"] = plain.get("requests_executed", 0) + 1
    base = time.perf_counter() - t0

    m = ReplicaMetrics()
    t0 = time.perf_counter()
    for _ in range(n):
        m.inc("requests_executed")
    stamped = time.perf_counter() - t0

    ts = TimeSeries()
    t0 = time.perf_counter()
    for _ in range(n):
        ts.record("c", 1.0, kind="rate", t=T0)
    ring = time.perf_counter() - t0

    floor = max(base, 1e-4)  # guard against a 0-resolution timer
    assert stamped < 200 * floor, (stamped, base)
    assert ring < 400 * floor, (ring, base)


# ---------------------------------------------------------------------------
# CounterSampler: delta discipline


class _Counter:
    def __init__(self):
        self.v = 0.0

    def __call__(self):
        return self.v


def test_sampler_first_tick_only_baselines():
    ts = TimeSeries(interval_s=1.0)
    s = CounterSampler(ts)
    c = _Counter()
    c.v = 500  # pre-existing total at sampler start
    s.add_rate("committed", c)
    s.tick(t=T0)
    assert ts.names() == []  # baseline only, no fabricated burst
    c.v = 530
    s.tick(t=T0 + 1)
    assert ts.value("committed", ts.index_for(T0 + 1)) == 30.0


def test_sampler_backwards_counter_rebaselines():
    """A warm-up stats reset swaps a fresh counter in; the sampler must
    read that as 'no data', never as a negative rate."""
    ts = TimeSeries(interval_s=1.0)
    s = CounterSampler(ts)
    c = _Counter()
    s.add_rate("committed", c)
    s.tick(t=T0)
    c.v = 100
    s.tick(t=T0 + 1)
    c.v = 5  # reset!
    s.tick(t=T0 + 2)
    c.v = 25
    s.tick(t=T0 + 3)
    assert ts.value("committed", ts.index_for(T0 + 1)) == 100.0
    assert ts.value("committed", ts.index_for(T0 + 2)) == 0.0  # gap, not -95
    assert ts.value("committed", ts.index_for(T0 + 3)) == 20.0
    _, vals = ts.timeline("committed")
    assert all(v >= 0 for v in vals)


def test_sampler_ratio_skips_idle_denominator():
    ts = TimeSeries(interval_s=1.0)
    s = CounterSampler(ts)
    num, den = _Counter(), _Counter()
    s.add_ratio("fill", num, den)
    s.tick(t=T0)
    num.v, den.v = 12, 2
    s.tick(t=T0 + 1)
    s.tick(t=T0 + 2)  # denominator unmoved: gap, not a fake 0
    num.v, den.v = 18, 4
    s.tick(t=T0 + 3)
    assert ts.value("fill", ts.index_for(T0 + 1)) == pytest.approx(6.0)
    assert ts._read("fill", ts.index_for(T0 + 2)) is None
    assert ts.value("fill", ts.index_for(T0 + 3)) == pytest.approx(3.0)


def test_sampler_gauge_records_instantaneous_value():
    ts = TimeSeries(interval_s=1.0)
    s = CounterSampler(ts)
    g = _Counter()
    g.v = 7.0
    s.add_gauge("depth", g)
    s.tick(t=T0)
    g.v = 9.0
    s.tick(t=T0 + 1)
    assert ts.value("depth", ts.index_for(T0)) == 7.0
    assert ts.value("depth", ts.index_for(T0 + 1)) == 9.0


# ---------------------------------------------------------------------------
# dump / merge docs: the incarnation refusal


def test_dump_and_merge_docs_round_trip(tmp_path):
    ts = TimeSeries(interval_s=1.0)
    ts.record("committed", 11.0, kind="rate", t=T0)
    path = dump_timeseries(ts, str(tmp_path / "run.r0"), extra={"id": 0})
    assert path.endswith(".ts.json")
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["kind"] == "timeseries"
    assert doc["id"] == 0
    assert doc["run_id"] and doc["build"]["run_id"] == doc["run_id"]
    merged = merge_timeseries_docs([doc, doc])  # same incarnation: fine
    idx = merged.index_for(T0)
    assert merged.value("committed", idx) == 22.0


def test_merge_docs_refuses_two_incarnations_of_one_id():
    mk = lambda run: {  # noqa: E731 - tiny local fixture
        "kind": "timeseries", "id": 3, "run_id": run,
        "ts": TimeSeries().to_dict(),
    }
    with pytest.raises(IncarnationMismatch, match="two incarnations"):
        merge_timeseries_docs([mk("111-1"), mk("111-2")])
    # distinct ids may come from distinct incarnations (normal cluster)
    a, b = mk("111-1"), mk("111-2")
    b["id"] = 4
    merge_timeseries_docs([a, b])
    # docs of other kinds are ignored, not confused for rings
    merge_timeseries_docs([a, {"kind": "replica", "id": 3, "run_id": "x"}])
