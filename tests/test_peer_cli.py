"""peer CLI tests (reference sample/peer): testnet scaffolding, the
selftest cluster, and run/request *behavior* over real replica processes —
the MAC authentication path, the --metrics-interval output shape, and the
--usig auto fallback (VERDICT r2 #10)."""

import json
import os
import subprocess
import sys
import time

import pytest

from minbft_tpu.sample.authentication import KeyStore
from minbft_tpu.sample.config import load_config
from minbft_tpu.sample.peer.cli import main

from test_process_cluster import REPO, _free_base_port, _wait_ports


def test_testnet_scaffold(tmp_path):
    d = str(tmp_path)
    rc = main(
        ["testnet", "-n", "5", "--clients", "2", "-d", d, "--usig", "SOFT_ECDSA",
         "--base-port", "45100"]
    )
    assert rc == 0
    store = KeyStore.load(f"{d}/keys.yaml")
    assert len(store.replica_keys) == 5 and len(store.client_keys) == 2
    cfg = load_config(f"{d}/consensus.yaml")
    assert cfg.n == 5 and cfg.f == 2
    assert [p.addr for p in cfg.peers] == [f"127.0.0.1:{45100+i}" for i in range(5)]


def test_testnet_rejects_bad_f(tmp_path):
    import pytest

    with pytest.raises(SystemExit):
        main(["testnet", "-n", "3", "-f", "2", "-d", str(tmp_path)])


def test_selftest_commits():
    assert main(["selftest"]) == 0


def test_testnet_usig_auto_falls_back_without_native(tmp_path, monkeypatch):
    """--usig auto must degrade to the software seal when the native
    module can't be built (e.g. no g++ on the host)."""
    from minbft_tpu.usig import native as native_mod

    monkeypatch.setattr(native_mod, "available", lambda auto_build=False: False)
    assert main(["testnet", "-n", "3", "-d", str(tmp_path), "--usig", "auto"]) == 0
    assert KeyStore.load(f"{tmp_path}/keys.yaml").usig_spec == "SOFT_ECDSA"


def _spawn_replicas(d, n, global_args=(), run_args=()):
    """Start n replica processes from the scaffold in ``d``; ``global_args``
    go before the ``run`` subcommand, ``run_args`` after ``run <id>``."""
    env = dict(
        os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    procs, logs = [], []
    for i in range(n):
        log = open(f"{d}/replica{i}.log", "wb")
        logs.append(log)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "minbft_tpu.sample.peer",
                 "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
                 *global_args, "run", str(i), "--no-batch", *run_args],
                env=env, stdout=subprocess.DEVNULL, stderr=log,
            )
        )
    return env, procs, logs


def _stop_all(procs, logs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()


def test_mac_auth_real_processes(tmp_path):
    """--auth mac end to end: scaffold with MAC material, run replicas and
    submit a request under the pairwise-MAC scheme over real sockets."""
    d = str(tmp_path)
    base_port = _free_base_port(3)
    assert main(
        ["testnet", "-n", "3", "-d", d, "--base-port", str(base_port),
         "--usig", "SOFT_ECDSA", "--macs"]
    ) == 0
    env, procs, logs = _spawn_replicas(d, 3, global_args=("--auth", "mac"))
    try:
        assert _wait_ports([base_port + i for i in range(3)]), "replicas never bound"
        req = subprocess.run(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "--auth", "mac", "request", "mac-op", "--timeout", "120"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert req.returncode == 0, req.stderr
        assert len(req.stdout.strip()) == 64
    finally:
        _stop_all(procs, logs)


def test_metrics_interval_output_shape(tmp_path):
    """--metrics-interval periodically logs one-line JSON snapshots with
    the protocol counters an operator needs."""
    d = str(tmp_path)
    base_port = _free_base_port(1)
    assert main(
        ["testnet", "-n", "1", "-f", "0", "-d", d, "--base-port",
         str(base_port), "--usig", "SOFT_ECDSA"]
    ) == 0
    env = dict(
        os.environ, PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    log_path = f"{d}/replica0.log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "minbft_tpu.sample.peer",
             "--keys", f"{d}/keys.yaml", "--config", f"{d}/consensus.yaml",
             "run", "0", "--no-batch", "--metrics-interval", "0.3"],
            env=env, stdout=subprocess.DEVNULL, stderr=log,
        )
        try:
            assert _wait_ports([base_port]), "replica never bound"
            deadline = time.time() + 30
            lines = []
            while time.time() < deadline:
                lines = [
                    l for l in open(log_path, errors="replace").read().splitlines()
                    if l.startswith("metrics: ")
                ]
                if len(lines) >= 2:
                    break
                time.sleep(0.3)
        finally:
            proc.terminate()
            proc.wait(timeout=10)
    assert len(lines) >= 2, open(log_path, errors="replace").read()
    snap = json.loads(lines[-1][len("metrics: "):])
    # counter keys appear once incremented; the rate/latency keys always do
    for key in ("executed_per_sec", "execute_latency_p50_ms",
                "execute_latency_p99_ms"):
        assert key in snap, snap


def test_peer_options_file_layering(tmp_path, monkeypatch):
    """Per-node peer.yaml (reference sample/peer/peer.yaml + root.go:54-82):
    file values replace built-in defaults, PEER_* env overrides the file,
    and flags override both."""
    from minbft_tpu.sample.peer.cli import build_parser, load_peer_options

    opt_file = tmp_path / "peer.yaml"
    opt_file.write_text(
        "keys: /etc/minbft/keys.yaml\n"
        "log_level: debug\n"
        "run:\n"
        "  batch: 128\n"
        "  metrics_interval: 5\n"
        "request:\n"
        "  timeout: 7.5\n"
    )
    opts = load_peer_options(str(opt_file), explicit=True)

    args = build_parser(opts).parse_args(["run", "0"])
    assert args.keys == "/etc/minbft/keys.yaml"
    assert args.log_level == "debug"
    assert args.batch == 128
    assert args.metrics_interval == 5.0  # coerced to the option's type

    # env overrides the file; flags override both
    monkeypatch.setenv("PEER_BATCH", "64")
    args = build_parser(opts).parse_args(["run", "0"])
    assert args.batch == 64
    args = build_parser(opts).parse_args(["--keys", "k2.yaml", "run", "0"])
    assert args.keys == "k2.yaml"

    args = build_parser(opts).parse_args(["request", "op"])
    assert args.timeout == 7.5


def test_peer_options_file_rejects_unknowns(tmp_path):
    from minbft_tpu.sample.peer.cli import load_peer_options

    bad = tmp_path / "peer.yaml"
    bad.write_text("batchsize: 10\n")  # typo'd key must fail loudly
    with pytest.raises(SystemExit, match="unknown option"):
        load_peer_options(str(bad), explicit=True)
    bad.write_text("run:\n  batsch: 10\n")
    with pytest.raises(SystemExit, match="unknown option"):
        load_peer_options(str(bad), explicit=True)
    # non-scalar values for scalar options fail loudly too (str() would
    # happily stringify a list into a bogus path)
    bad.write_text("keys: [a.yaml, b.yaml]\n")
    with pytest.raises(SystemExit, match="must be a scalar"):
        load_peer_options(str(bad), explicit=True)
    bad.write_text("run:\n  batch: {x: 1}\n")
    with pytest.raises(SystemExit, match="must be a scalar"):
        load_peer_options(str(bad), explicit=True)
    with pytest.raises(SystemExit, match="not found"):
        load_peer_options(str(tmp_path / "absent.yaml"), explicit=True)
    # a missing DEFAULT path is not an error — no file, no layering
    assert load_peer_options(str(tmp_path / "absent.yaml"), explicit=False) == {}


def test_peer_options_flag_end_to_end(tmp_path):
    """The --options flag reaches main(): a bad file fails loudly even
    though the subcommand is valid."""
    from minbft_tpu.sample.peer.cli import main as cli_main

    bad = tmp_path / "opts.yaml"
    bad.write_text("nonsense: 1\n")
    with pytest.raises(SystemExit, match="unknown option"):
        cli_main(["--options", str(bad), "selftest"])
