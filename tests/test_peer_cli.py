"""peer CLI tests (reference sample/peer; run.go/request.go are exercised
over real sockets by deploy/local_testnet.sh — here the in-process
surfaces: testnet scaffolding and the selftest cluster)."""

from minbft_tpu.sample.authentication import KeyStore
from minbft_tpu.sample.config import load_config
from minbft_tpu.sample.peer.cli import main


def test_testnet_scaffold(tmp_path):
    d = str(tmp_path)
    rc = main(
        ["testnet", "-n", "5", "--clients", "2", "-d", d, "--usig", "SOFT_ECDSA",
         "--base-port", "45100"]
    )
    assert rc == 0
    store = KeyStore.load(f"{d}/keys.yaml")
    assert len(store.replica_keys) == 5 and len(store.client_keys) == 2
    cfg = load_config(f"{d}/consensus.yaml")
    assert cfg.n == 5 and cfg.f == 2
    assert [p.addr for p in cfg.peers] == [f"127.0.0.1:{45100+i}" for i in range(5)]


def test_testnet_rejects_bad_f(tmp_path):
    import pytest

    with pytest.raises(SystemExit):
        main(["testnet", "-n", "3", "-f", "2", "-d", str(tmp_path)])


def test_selftest_commits():
    assert main(["selftest"]) == 0
