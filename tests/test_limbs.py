"""Differential tests for the 256-bit limb arithmetic (ops/limbs.py) against
Python big ints — the substrate every public-key TPU kernel rests on."""

import secrets

import jax
import jax.numpy as jnp
import pytest

from minbft_tpu.ops.limbs import (
    FieldSpec,
    add_mod,
    from_limbs,
    from_mont,
    mont_inv,
    mont_mul,
    sub_mod,
    to_limbs,
    to_mont,
)

P256_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
P256_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
ED_P = 2**255 - 19

MODULI = [P256_P, P256_N, ED_P]


def _ops(spec):
    @jax.jit
    def mulmod(a, b):
        return from_mont(spec, mont_mul(spec, to_mont(spec, a), to_mont(spec, b)))

    return (
        mulmod,
        jax.jit(lambda a, b: add_mod(spec, a, b)),
        jax.jit(lambda a, b: sub_mod(spec, a, b)),
        jax.jit(lambda a: from_mont(spec, mont_inv(spec, to_mont(spec, a)))),
    )


@pytest.mark.parametrize("modulus", MODULI)
def test_mul_add_sub_random(modulus):
    spec = FieldSpec.make(modulus)
    mulmod, addmod, submod, _ = _ops(spec)
    for _ in range(10):
        a, b = secrets.randbelow(modulus), secrets.randbelow(modulus)
        am, bm = jnp.asarray(to_limbs(a)), jnp.asarray(to_limbs(b))
        assert from_limbs(mulmod(am, bm)) == (a * b) % modulus
        assert from_limbs(addmod(am, bm)) == (a + b) % modulus
        assert from_limbs(submod(am, bm)) == (a - b) % modulus


@pytest.mark.parametrize("modulus", MODULI)
def test_edge_values(modulus):
    spec = FieldSpec.make(modulus)
    mulmod, addmod, submod, _ = _ops(spec)
    for a, b in [(0, 0), (modulus - 1, modulus - 1), (1, modulus - 1), (0, modulus - 1)]:
        am, bm = jnp.asarray(to_limbs(a)), jnp.asarray(to_limbs(b))
        assert from_limbs(mulmod(am, bm)) == (a * b) % modulus
        assert from_limbs(addmod(am, bm)) == (a + b) % modulus
        assert from_limbs(submod(am, bm)) == (a - b) % modulus


def test_fermat_inverse():
    spec = FieldSpec.make(P256_P)
    *_, invmod = _ops(spec)
    for _ in range(3):
        a = secrets.randbelow(P256_P - 1) + 1
        assert from_limbs(invmod(jnp.asarray(to_limbs(a)))) == pow(a, -1, P256_P)


def test_vmap_batch_matches_scalar():
    from minbft_tpu.ops.limbs import fe_from_array, fe_to_array

    spec = FieldSpec.make(P256_N)
    batched = jax.jit(
        jax.vmap(
            lambda a, b: fe_to_array(
                mont_mul(spec, fe_from_array(a), fe_from_array(b))
            )
        )
    )
    import numpy as np

    vals = [(secrets.randbelow(P256_N), secrets.randbelow(P256_N)) for _ in range(8)]
    a = jnp.asarray(np.stack([to_limbs(x) for x, _ in vals]))
    b = jnp.asarray(np.stack([to_limbs(y) for _, y in vals]))
    out = batched(a, b)
    r_inv = pow(1 << 256, -1, P256_N)
    for i, (x, y) in enumerate(vals):
        assert from_limbs(out[i]) == (x * y * r_inv) % P256_N


def test_lowering_modes_agree_unrolled_scan_block():
    """The three lowerings (unrolled / scan / block) are the same
    arithmetic — pin their equivalence."""
    from minbft_tpu.ops import limbs as L

    spec = FieldSpec.make(P256_P)
    a = jnp.asarray(to_limbs(secrets.randbelow(P256_P)))
    b = jnp.asarray(to_limbs(secrets.randbelow(P256_P)))
    at, bt = L.fe_from_array(a), L.fe_from_array(b)
    try:
        L.set_mode("scan")
        ref = from_limbs(jax.jit(lambda: L.fe_to_array(mont_mul(spec, at, bt)))())
        L.set_mode("unrolled")
        got = from_limbs(jax.jit(lambda: L.fe_to_array(mont_mul(spec, at, bt)))())
        L.set_mode("block")
        blk = from_limbs(jax.jit(lambda: L.fe_to_array(mont_mul(spec, at, bt)))())
    finally:
        L.set_mode(None)
    assert got == ref
    assert blk == ref
