"""Direct unit tests for the core internal state packages.

Mirrors the reference's state-package test tier (SURVEY.md §4 tier 2):
clientstate blocking capture/release/retire
(reference core/internal/clientstate/request-seq_test.go), peerstate
in-order capture including the waiting case (peerstate_test.go:28-105), and
messagelog concurrent append/stream (messagelog_test.go:29-117).
"""

import asyncio

from minbft_tpu.core.internal.clientstate import ClientState, ClientStates
from minbft_tpu.core.internal.messagelog import MessageLog
from minbft_tpu.core.internal.peerstate import PeerState, PeerStates
from minbft_tpu.core.internal.timer import FakeTimerProvider
from minbft_tpu.core.internal.viewstate import ViewState


# ---------------------------------------------------------------------------
# clientstate


def test_clientstate_capture_release_retire():
    async def run():
        st = ClientState(FakeTimerProvider())
        assert await st.capture_request_seq(1)
        assert not await st.capture_request_seq(1)  # duplicate while captured
        await st.release_request_seq(1)
        assert not await st.capture_request_seq(1)  # duplicate after release
        assert await st.capture_request_seq(5)  # holes allowed (client clock)
        await st.release_request_seq(5)
        assert st.retire_request_seq(5)
        assert not st.retire_request_seq(5)  # already retired

    asyncio.run(run())


def test_clientstate_capture_blocks_until_release():
    """A second capture for the same client parks until the first is
    released (reference request-seq.go:47-82 condvar)."""

    async def run():
        st = ClientState(FakeTimerProvider())
        assert await st.capture_request_seq(1)
        order = []

        async def second():
            order.append("start")
            got = await st.capture_request_seq(2)
            order.append(("captured", got))

        task = asyncio.create_task(second())
        await asyncio.sleep(0.01)
        assert order == ["start"]  # still parked
        await st.release_request_seq(1)
        await asyncio.wait_for(task, 1)
        assert order == ["start", ("captured", True)]

    asyncio.run(run())


def test_clientstate_blocked_duplicate_resolves_false():
    """A duplicate capture is detectable immediately even while the gate is
    held by the original — it must not park (reference
    request-seq.go:61-66)."""

    async def run():
        st = ClientState(FakeTimerProvider())
        assert await st.capture_request_seq(3)
        task = asyncio.create_task(st.capture_request_seq(3))
        await asyncio.sleep(0.01)
        assert task.done() and task.result() is False

    asyncio.run(run())


def test_clientstate_reply_subscription():
    async def run():
        st = ClientState(FakeTimerProvider())

        waiter = asyncio.create_task(st.reply_for(4))
        await asyncio.sleep(0)
        st.add_reply(4, "reply-4")
        assert await asyncio.wait_for(waiter, 1) == "reply-4"
        # Late subscription sees the buffered reply.
        assert await st.reply_for(4) == "reply-4"

    asyncio.run(run())


def test_clientstates_provider_lazy_map():
    states = ClientStates(FakeTimerProvider())
    a = states.client(1)
    assert states.client(1) is a
    assert states.client(2) is not a


# ---------------------------------------------------------------------------
# peerstate


def test_peerstate_in_order_capture_and_dedup():
    async def run():
        st = PeerState()
        assert await st.capture_ui(1)
        assert not await st.capture_ui(1)  # replay
        assert await st.capture_ui(2)
        assert not await st.capture_ui(1)  # old replay

    asyncio.run(run())


def test_peerstate_waits_for_gap():
    """capture_ui(3) parks until 2 is captured (reference
    peerstate_test.go:28-105 waiting case)."""

    async def run():
        st = PeerState()
        assert await st.capture_ui(1)
        results = {}

        async def capture(cv):
            results[cv] = await st.capture_ui(cv)

        ahead = asyncio.create_task(capture(3))
        await asyncio.sleep(0.01)
        assert 3 not in results  # parked on the gap
        assert await st.capture_ui(2)
        await asyncio.wait_for(ahead, 1)
        assert results[3] is True

    asyncio.run(run())


def test_peerstate_concurrent_out_of_order_capture():
    """Many concurrent captures in shuffled order all succeed exactly once
    and complete (the sequencing backbone under concurrency)."""

    async def run():
        st = PeerState()
        import random

        cvs = list(range(1, 40))
        rng = random.Random(7)
        shuffled = cvs * 2  # every cv twice: one True, one False
        rng.shuffle(shuffled)
        results = await asyncio.gather(*[st.capture_ui(cv) for cv in shuffled])
        assert sum(results) == len(cvs)  # each cv captured exactly once

    asyncio.run(run())


def test_peerstate_retreat_allows_retry():
    async def run():
        st = PeerState()
        assert await st.capture_ui(1)
        await st.retreat_ui(1)
        assert await st.capture_ui(1)  # retry after failed processing

    asyncio.run(run())


def test_peerstates_provider():
    states = PeerStates()
    assert states.peer(3) is states.peer(3)
    assert states.peer(3) is not states.peer(4)


# ---------------------------------------------------------------------------
# messagelog


def test_messagelog_replay_then_follow():
    async def run():
        log = MessageLog()
        log.append("a")
        log.append("b")
        done = asyncio.Event()
        got = []

        async def consume():
            async for m in log.stream(done):
                got.append(m)

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.01)
        assert got == ["a", "b"]  # replay
        log.append("c")
        await asyncio.sleep(0.01)
        assert got == ["a", "b", "c"]  # follow
        done.set()
        log.append("d")  # wake the stream so it can observe done
        await asyncio.wait_for(task, 1)

    asyncio.run(run())


def test_messagelog_multiple_subscribers_see_everything():
    """Every subscriber sees every append exactly once, in order, no matter
    when it subscribed (reference messagelog_test.go:29-117)."""

    async def run():
        log = MessageLog()
        done = asyncio.Event()
        seen = {0: [], 1: [], 2: []}

        async def consume(k, expect):
            async for m in log.stream(done):
                seen[k].append(m)
                if len(seen[k]) == expect:
                    return

        total = 50
        early = asyncio.create_task(consume(0, total))
        await asyncio.sleep(0)
        for i in range(total // 2):
            log.append(i)
        mid = asyncio.create_task(consume(1, total))
        # Concurrent appender + late subscriber.
        for i in range(total // 2, total):
            log.append(i)
        late = asyncio.create_task(consume(2, total))
        await asyncio.wait_for(asyncio.gather(early, mid, late), 5)
        for k in seen:
            assert seen[k] == list(range(total))

    asyncio.run(run())


def test_messagelog_concurrent_appenders():
    async def run():
        log = MessageLog()
        done = asyncio.Event()
        got = []

        async def consume():
            async for m in log.stream(done):
                got.append(m)
                if len(got) == 100:
                    return

        async def produce(base):
            for i in range(50):
                log.append(base + i)
                if i % 7 == 0:
                    await asyncio.sleep(0)

        await asyncio.wait_for(
            asyncio.gather(consume(), produce(0), produce(1000)), 5
        )
        assert sorted(got) == sorted(list(range(50)) + list(range(1000, 1050)))

    asyncio.run(run())


# ---------------------------------------------------------------------------
# viewstate


def test_viewstate_advance_expected_and_current():
    async def run():
        vs = ViewState()
        view, expected = await vs.hold_view()
        assert (view, expected) == (0, 0)
        assert await vs.advance_expected_view(1)
        assert not await vs.advance_expected_view(1)  # dedup
        assert await vs.advance_expected_view(2)
        assert await vs.advance_current_view(1)
        assert not await vs.advance_current_view(1)  # already entered
        assert not await vs.advance_current_view(5)  # beyond expected
        assert await vs.advance_current_view(2)

    asyncio.run(run())


def test_viewstate_lease_blocks_view_advancement():
    """A message mid-apply (holding the read lease across an await) cannot
    be overtaken by advance_current_view — the reference's read-lock
    semantics (view-state.go:50-74)."""

    async def run():
        vs = ViewState()
        await vs.advance_expected_view(1)
        gate = asyncio.Event()
        order = []

        async def processing():
            async with vs.hold_view_lease() as (view, _):
                assert view == 0
                order.append("apply-start")
                await gate.wait()  # suspended mid-apply
                # still view 0 from this lease's perspective: the writer
                # is parked until we release
                order.append("apply-end")

        async def advancer():
            order.append("advance-start")
            assert await vs.advance_current_view(1)
            order.append("advanced")

        t1 = asyncio.create_task(processing())
        await asyncio.sleep(0)
        t2 = asyncio.create_task(advancer())
        await asyncio.sleep(0.01)
        assert order == ["apply-start", "advance-start"]  # writer parked
        gate.set()
        await asyncio.gather(t1, t2)
        assert order == ["apply-start", "advance-start", "apply-end", "advanced"]
        # a message from view 0 now fails the in-lease view check
        async with vs.hold_view_lease() as (view, _):
            assert view == 1

    asyncio.run(run())


def test_viewstate_concurrent_leases_are_shared():
    async def run():
        vs = ViewState()
        active = {"n": 0, "max": 0}

        async def reader():
            async with vs.hold_view_lease():
                active["n"] += 1
                active["max"] = max(active["max"], active["n"])
                await asyncio.sleep(0.01)
                active["n"] -= 1

        await asyncio.gather(*[reader() for _ in range(8)])
        assert active["max"] > 1  # leases overlap (no reader serialization)

    asyncio.run(run())


def test_clientstate_out_of_order_capture_not_dropped():
    """The round-4 wedge: a pipelined client's requests are processed by
    concurrent per-message tasks, so a HIGHER seq can reach capture first.
    A scalar captured-watermark (the reference's serial-client semantics)
    would drop the lower seq as a 'duplicate' — never proposed, silently
    retired past, request wedged forever.  Captures must tolerate
    out-of-order arrival while keeping the one-at-a-time gate and full
    dedup."""

    async def run():
        st = ClientState(FakeTimerProvider())
        # seq 89 arrives and completes first...
        assert await st.capture_request_seq(89)
        await st.release_request_seq(89)
        # ...then seq 73 arrives late: it must still capture
        assert await st.capture_request_seq(73)
        await st.release_request_seq(73)
        # both are now duplicates
        assert not await st.capture_request_seq(89)
        assert not await st.capture_request_seq(73)
        # execution retires 89 EXACTLY — retirement must not jump the
        # watermark past 80: with pipelined clients a reordered higher
        # seq commits first, and a jump would silently supersede the
        # still-live lower request (never executed, never replied — the
        # chaos soak wedged on this).
        assert st.retire_request_seq(89)
        assert await st.capture_request_seq(80)  # still live, still captures
        await st.release_request_seq(80)
        assert st.retire_request_seq(80)  # executes later, retires exactly
        assert not st.retire_request_seq(80)  # then dedups
        assert 89 not in st._done and 80 not in st._done
        # a genuinely new seq still works
        assert await st.capture_request_seq(90)
        await st.release_request_seq(90)

    asyncio.run(run())


def test_clientstate_done_window_overflow_raises_floor():
    """Overflowing the done-window must not LOSE dedup (a retransmit of an
    evicted seq would re-execute): evicted seqs raise a duplicate floor —
    conservative refusal, never re-capture."""

    async def run():
        st = ClientState(FakeTimerProvider())
        st._DONE_WINDOW = 4
        for seq in (10, 20, 30, 40, 50):
            assert await st.capture_request_seq(seq)
            await st.release_request_seq(seq)
        # window 4: seq 10 was evicted, floor raised to it
        assert st._done_floor == 10
        assert not await st.capture_request_seq(10)  # still a duplicate
        assert not await st.capture_request_seq(7)   # below the floor: refused
        assert await st.capture_request_seq(60)      # fresh seqs unaffected
        await st.release_request_seq(60)

    asyncio.run(run())
